"""Scenario-sharded APH over the async Synchronizer (multi-process).

The missing half of the reference's APH runtime (ref. mpisppy/opt/aph.py:
818-921 + mpisppy/utils/listener_util/listener_util.py:277-327): ranks
hold scenario shards, a listener thread on each rank keeps reducing the
(x̄, x̄², ȳ) "FirstReduce" and (τ, φ, norms) "SecondReduce" concatenations
*while* the worker solves, and the worker proceeds once enough ranks have
fresh data (``async_frac_needed``) — wall-clock overlap of reduction
communication with subproblem compute, staleness tolerated by design.

Here a "rank" is an OS process owning a contiguous scenario shard
(ir/batch.py shard_batch — the analog of the reference's contiguous
rank map, ref. spbase.py:172) with its own engine and device stream; the
listener exchange rides the native seqlock shm windows through
utils/synchronizer.Synchronizer (the DCN analog; on a multi-host TPU pod
each shard process is a host). The in-process APH (core/aph.py) remains
the single-chip fast path where the reductions are membership matmuls
inside the jitted step; this module is the multi-host deployment shape.

Reduction layout (per-stage node summands, flattened and concatenated —
multistage-safe because membership columns are global, see shard_batch):

  First  = [Σp·x | Σp·x² | Σp·y  per (node, slot) | Σp per node
            | per-shard timestamps]                  (3·Σ N_t k_t + Σ N_t + n)
  Second = [τ, φ, pusq, pvsq, pwsq, pzsq | per-shard timestamps]   (6 + n)

Timestamps live in per-shard slots (each shard sums in only its own), so
the reduced vector carries every shard's iteration count — the
enough-fresh check of the reference's side gig (ref. aph.py:204-324).
Convergence norms ride the same iteration's SecondReduce computed from
the PRE-step (W, z): the conv metric is "one notch behind", exactly the
staleness the reference's worker accepts (ref. listener_util.py:164-182
keep_up).
"""

from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np

from .. import global_toc
from ..ir.batch import shard_batch
from ..utils.synchronizer import Synchronizer
from .aph import APH, aph_conv_metric, aph_theta_step


class APHShard(APH):
    """One shard's APH engine + worker loop. Construct via ``make_shard``;
    drive via ``run`` (which owns the Synchronizer listener)."""

    def __init__(self, batch, options, n_shards, my_shard, shm_prefix=None,
                 windows=None, **kw):
        opts = dict(options or {})
        opts["partial_probabilities"] = True
        super().__init__(batch, opts, **kw)
        self.n_shards = int(n_shards)
        self.my_shard = int(my_shard)
        self.async_frac_needed = float(
            self.options.get("async_frac_needed", 1.0))
        self.async_sleep_secs = float(
            self.options.get("async_sleep_secs", 0.002))
        # per-stage (N_t, k_t) summand shapes
        self._stage_shapes = self.stage_shapes(self.batch)
        nk = sum(N * k for N, k in self._stage_shapes)
        nden = sum(N for N, _ in self._stage_shapes)
        self._nk, self._nden = nk, nden
        # wheel mode: aph_wheel_S = GLOBAL scenario count enables the
        # WX gather; shard 0 additionally carries the hub communicator
        # (set by the wheel launcher). The gather is an ON-DEMAND
        # reduction (summed once per APH iteration in _wheel_sync) —
        # riding the listener beat would republish + re-sum 2·S·K
        # doubles every ~5 ms.
        self._wheel_S = self.options.get("aph_wheel_S")
        ondemand = None
        if self._wheel_S is not None:
            self._wheel_S = int(self._wheel_S)
            self._shard_lo = shard_range(self._wheel_S, self.my_shard,
                                         self.n_shards)[0]
            K = sum(k for _, k in self._stage_shapes)
            ondemand = {"WX": 2 * self._wheel_S * K}
            if int(self.options.get("aph_sync_every", 0)):
                # the wheel's termination break is asynchronous (shard 0
                # decides on gap); the periodic barrier's equal-call-
                # count contract cannot survive it
                raise ValueError("aph_sync_every cannot be combined "
                                 "with wheel mode (aph_wheel_S): the "
                                 "hub's gap termination breaks the "
                                 "barrier call-count alignment")
        lens = self.reduction_lens(self.batch, self.n_shards)
        self.sync = Synchronizer(
            lens, self.n_shards, self.my_shard, shm_prefix=shm_prefix,
            windows=windows, ondemand_lens=ondemand,
            sleep_secs=float(self.options.get("listener_sleep_secs", 0.005)))
        self._g = {r: np.zeros(l) for r, l in lens.items()}
        self._l = {r: np.zeros(l) for r, l in lens.items()}

    # ---- wire layout (the ONE definition thread-mode embedders need to
    # prebuild the shared window table from) ----
    @staticmethod
    def stage_shapes(batch):
        return [(batch.tree.nodes_per_stage[t], sl.stop - sl.start)
                for t, sl in enumerate(batch.stage_slot_slices)]

    @classmethod
    def reduction_lens(cls, batch, n_shards):
        shapes = cls.stage_shapes(batch)
        nk = sum(N * k for N, k in shapes)
        nden = sum(N for N, _ in shapes)
        return {"First": 3 * nk + nden + n_shards,
                "Second": 6 + n_shards}

    # ---- summand packing ----
    def _node_summands(self, arr):
        """Per-stage B_tᵀ(p⊙arr[:, sl]) flattened and concatenated."""
        p = self.prob[:, None]
        outs = []
        for B, sl in zip(self.memberships, self.batch.stage_slot_slices):
            outs.append(jnp.ravel(B.T @ (p * arr[:, sl])))
        return jnp.concatenate(outs)

    def _den_summands(self):
        return jnp.concatenate([B.T @ self.prob for B in self.memberships])

    def _broadcast_nodes(self, flat):
        """Inverse of _node_summands: (Σ N_t k_t,) node values -> (S, K)."""
        out, off = [], 0
        for B, (N, k) in zip(self.memberships, self._stage_shapes):
            blk = jnp.asarray(flat[off:off + N * k].reshape(N, k), self.dtype)
            out.append(B @ blk)
            off += N * k
        return jnp.concatenate(out, axis=1)

    def _expand_den(self, dens):
        """(Σ N_t,) per-node masses -> (Σ N_t k_t,) aligned with the
        flattened per-(node, slot) numerators. A node no published shard
        passes through has zero mass; its quotient must not NaN-poison
        the broadcast matmul (0-column · NaN = NaN) — this shard never
        consumes such nodes (its own summand keeps every node it owns
        positive), so any placeholder is safe; use 1."""
        out, off = [], 0
        for N, k in self._stage_shapes:
            d = dens[off:off + N]
            out.append(np.repeat(np.where(d > 0, d, 1.0), k))
            off += N
        return np.concatenate(out)

    def _wait_fresh(self, red, it, vec):
        """Stage my summand (timestamp = it) and spin until the reduced
        vector shows >= async_frac_needed shards at timestamp >= it (the
        reference worker's spin for the side gig, ref. aph.py:327-448).
        The listener keeps folding stragglers in underneath us. The spin
        polls only the timestamp tail; the full vector is copied once,
        when fresh. A hard-killed peer never publishes anything — the
        deadline turns that into an error instead of an infinite spin."""
        ts = np.zeros(self.n_shards)
        ts[self.my_shard] = it
        self._l[red][:] = np.concatenate([vec, ts])
        need = max(1, int(np.ceil(self.async_frac_needed * self.n_shards)))
        self.sync.compute_global_data(self._l, self._g, rednames=[red],
                                      keep_up=True)
        deadline = time.monotonic() + float(
            self.options.get("aph_wait_timeout", 600.0))
        while True:
            fresh = int((self._g[red][-self.n_shards:] >= it).sum())
            if fresh >= need or self.sync.global_quitting:
                self.sync.compute_global_data(self._l, self._g,
                                              rednames=[red], keep_up=True)
                # a COPY, not a view into self._g: the buffer is
                # overwritten in place by the next compute_global_data /
                # peek_tail, and a caller holding the result across the
                # next reduce would read silently corrupted data
                # (ADVICE r3). The per-iteration memcpy is negligible
                # next to the solves.
                return self._g[red][:-self.n_shards].copy()
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"shard {self.my_shard}: {red} never got "
                    f"{need}/{self.n_shards} fresh shards at iter {it} — "
                    "a peer process likely died without publishing quit")
            time.sleep(self.async_sleep_secs)
            self._g[red][-self.n_shards:] = self.sync.peek_tail(
                red, self.n_shards)

    # ---- wheel citizenship (spin_aph_shard_wheel) ----
    def _wheel_sync(self, xn):
        """Publish this shard's (W, x-nonant) rows into the WX gather;
        on the hub-carrying shard, stage the gathered FULL arrays and
        run the cylinder sync. Returns True when the wheel terminated
        (gap met / spokes satisfied) — a loop-exit for the caller."""
        if self._wheel_S is None:
            return False
        K = self.batch.K
        off = self._wheel_S * K
        lo = self._shard_lo * K
        S_loc = self.batch.S
        buf = np.zeros(2 * off)
        buf[lo:lo + S_loc * K] = \
            np.asarray(self.W, np.float64).reshape(-1)
        buf[off + lo:off + lo + S_loc * K] = \
            np.asarray(xn, np.float64).reshape(-1)
        if self.spcomm is None:
            # non-hub shards PUBLISH only — the read+sum of n_shards
            # 2*S*K vectors per iteration would be pure waste on their
            # hot loop (the hub shard does the one gather below)
            self.sync.publish_now("WX", buf)
            return False
        # on-demand gather (disjoint rows -> the sum is an exact
        # concat, stale for other shards by at most their publish lag)
        g, min_wid = self.sync.reduce_now("WX", buf, return_min_wid=True)
        if min_wid < 1:
            # a shard has not published its first WX summand yet: the
            # gather holds zero rows for it, and staging that would
            # hand spokes partially-zero (W, x) — W-projection keeps
            # outer bounds valid but xhat spokes would burn dive/oracle
            # passes on zero-row candidate blocks (ADVICE r4). Skip the
            # cylinder sync this round; the next gather retries.
            return False
        self.wheel_W = g[:off].reshape(self._wheel_S, K)
        self.wheel_X = g[off:].reshape(self._wheel_S, K)
        self.spcomm.sync()
        return bool(self.spcomm.is_converged())

    # ---- the worker loop (one shard's APH_iterk) ----
    def _work(self):
        warm = getattr(self, "_warm_started", False)
        self.solve_loop(w_on=warm, prox_on=False, update=False)
        # iter-0 feasibility + trivial bound are genuinely collective:
        # the reference runs Iter0 synchronously before the listener
        # starts (ref. aph.py:889); sync_allreduce is that barrier
        ok, _ = self.iter0_feasible_mask()
        feas, bound = self.sync.sync_allreduce(
            np.array([float(np.dot(np.asarray(self.prob), ok)),
                      self.Ebound()]))
        if self.options.get("iter0_infeasibility_abort", True) \
                and abs(feas - 1.0) > 1e-6:
            raise RuntimeError(f"iter 0: global feasible probability {feas} "
                               "!= 1 (ref. phbase.py:1415-1427 abort)")
        self.trivial_bound = self.best_bound = bound
        # global iter-0 xbar (Update_W reads self.xbar; a shard-local mean
        # would seed W inconsistently across shards)
        xn0 = self.nonants_of(self.x)
        nk, nden = self._nk, self._nden
        g0 = self.sync.sync_allreduce(np.concatenate([
            np.asarray(self._node_summands(xn0)),
            np.asarray(self._den_summands())]))
        self.xbar = self._broadcast_nodes(g0[:nk] / self._expand_den(g0[nk:]))
        if not warm:
            # a restored W checkpoint must not be double-updated
            # (same guard as APH_main, core/aph.py)
            self.Update_W()
        if self.use_lag:
            # lagged (W, z) for dispatched solves (ref. aph.py:188-190)
            self._W_lag = self.W
            self._z_lag = self.z
        global_toc(f"APHShard[{self.my_shard}] iter 0: trivial bound = "
                   f"{bound:.4f}", self.verbose and self.my_shard == 0)
        wheel_done = self._wheel_sync(xn0)
        if wheel_done:
            global_toc("APHShard wheel: iter-0 termination",
                       self.verbose and self.my_shard == 0)

        nu, gamma = self.nu, self.gamma
        self.conv = np.inf
        it = self._iter = 0
        while not wheel_done and it < self.max_iterations \
                and not self.sync.global_quitting:
            it += 1
            self._iter = it
            xn = self.nonants_of(self.x)
            if it > 1:
                W_y = self._W_lag if self.use_lag else self.W
                z_y = self._z_lag if self.use_lag else self.z
                y_new = W_y + self.rho * (xn - z_y)
                self.y_aph = jnp.where(
                    jnp.asarray(self._dispatched)[:, None], y_new, self.y_aph)
            first = np.asarray(jnp.concatenate([
                self._node_summands(xn), self._node_summands(xn * xn),
                self._node_summands(self.y_aph), self._den_summands()]))
            gfirst = self._wait_fresh("First", it, first)
            if self.sync.global_quitting:
                break
            den = self._expand_den(gfirst[3 * nk:3 * nk + nden])
            xbar = self._broadcast_nodes(gfirst[:nk] / den)
            xsqbar = self._broadcast_nodes(gfirst[nk:2 * nk] / den)
            ybar = self._broadcast_nodes(gfirst[2 * nk:3 * nk] / den)

            u = xn - xbar
            pusq = float(jnp.dot(self.prob, jnp.sum(u * u, axis=1)))
            pvsq = float(jnp.dot(self.prob, jnp.sum(ybar * ybar, axis=1)))
            phi = float(jnp.dot(self.prob, jnp.sum(
                (self.z - xn) * (self.W - self.y_aph), axis=1)))
            pwsq = float(jnp.dot(self.prob, jnp.sum(self.W * self.W, axis=1)))
            pzsq = float(jnp.dot(self.prob, jnp.sum(self.z * self.z, axis=1)))
            tau_sum = pusq + pvsq / gamma
            second = np.array([tau_sum, phi, pusq, pvsq, pwsq, pzsq])
            gsecond = self._wait_fresh("Second", it, second)
            if self.sync.global_quitting:
                break
            gtau, gphi, gpusq, gpvsq, gpwsq, gpzsq = gsecond

            # the SAME θ-step as the fused single-chip update, fed the
            # Synchronizer-reduced globals (see aph.aph_theta_step).
            # CONSISTENCY CAVEAT (deliberate deviation, ADVICE r3): with
            # async_frac_needed < 1 each shard computes θ from its OWN
            # staleness-dependent view of (τ, φ), so shards can apply
            # slightly different θ in the same iteration — whereas the
            # reference's MPI Allreduce guarantees rank-identical
            # reduced scalars and one θ per iteration (ref.
            # listener_util.py:193-199 asynch=False SecondReduce). This
            # is the price of the wait-free exchange; APH's convergence
            # theory tolerates bounded staleness in (W, z) exactly as it
            # tolerates the dispatch lag, and frac=1 (the default)
            # restores rank-identical scalars because every shard then
            # folds the same n_shards fresh summands. Deployments that
            # need strict reference parity at frac < 1 should
            # periodically barrier via sync_allreduce (aph_sync_every).
            sync_every = int(self.options.get("aph_sync_every", 0))
            synced = False
            if sync_every and it % sync_every == 0:
                # consistent snapshot: barrier-reduce the FULL
                # SecondReduce so every shard applies the SAME θ and
                # sees the SAME conv this iteration (drift cannot
                # accumulate unboundedly). The collective-call-count
                # contract of sync_allreduce demands every shard pass
                # the same barrier sequence — guaranteed because `it`
                # advances uniformly per shard and, below, the
                # convthresh exit is restricted to synced iterations
                # (where conv is rank-identical), so shards cannot
                # leave the loop at different barrier counts. A peer
                # quitting mid-barrier (crash or max-iter exit) is a
                # loop exit for us too, not an error.
                try:
                    # same patience as every other wait in this loop —
                    # the 300 s sync_allreduce default would kill a
                    # shard waiting on a healthy-but-slow peer several
                    # iterations behind (hospital-assisted solves run
                    # tens of seconds per iteration)
                    gsync = self.sync.sync_allreduce(
                        second, timeout=float(
                            self.options.get("aph_wait_timeout", 600.0)))
                except RuntimeError:
                    if self.sync.global_quitting:
                        break
                    raise
                (gtau, gphi, gpusq, gpvsq, gpwsq, gpzsq) = (
                    float(v) for v in gsync[:6])
                synced = True
            self.W, self.z, theta = aph_theta_step(
                u, ybar, self.W, self.z, xbar, gtau, gphi, nu, gamma,
                iter1=(it == 1))
            theta = float(theta)
            self.xbar, self.xsqbar, self.ybar = xbar, xsqbar, ybar
            self.tau, self.phi, self.theta = gtau, gphi, theta
            # conv from THIS SecondReduce's (W, z) norms — they are the
            # pre-step norms, i.e. the previous θ-step's result: the
            # "one notch behind" staleness the reference worker accepts
            self.conv = float(aph_conv_metric(gpusq, gpvsq, gpwsq, gpzsq))

            phis = np.asarray(self.prob * jnp.sum(
                (self.z - xn) * (self.W - self.y_aph), axis=1))
            self.phis = phis
            global_toc(f"APHShard iter {it}: conv={self.conv:.3e} "
                       f"theta={theta:.3e}",
                       self.verbose and self.my_shard == 0 and it % 10 == 0)
            # wheel sync: gather the full (W, x), push to spokes from
            # the hub shard, terminate the loop on gap/hub decision
            if self._wheel_sync(xn):
                global_toc(f"APHShard wheel: termination at iter {it}",
                           self.verbose and self.my_shard == 0)
                break
            # with the periodic barrier on, the convthresh exit is only
            # taken at SYNCED iterations: conv is then rank-identical,
            # so every shard leaves at the same iteration and the
            # barrier call counts stay aligned (see the consistency
            # note above). Without it (pure async), conv is advisory
            # per shard and the exit is wait-free as before — the only
            # remaining collective is the wrap-up reduce, which every
            # shard calls exactly once regardless of exit iteration.
            if self.conv < self.convthresh and (not sync_every or synced):
                break
            frac = 1.0 if it == 1 else self.dispatch_frac
            mask = self._dispatch_mask(it, frac)
            self._aph_solve(mask)

        self.sync.quitting = 1
        # final collective: global expected objective of the CURRENT local
        # solutions. Evaluated from self.x directly — _last_base_obj also
        # covers solves whose results were REJECTED for non-dispatched
        # scenarios (x reverted in _aph_solve), which would price a
        # solution no scenario actually holds when dispatch_frac < 1
        try:
            eobj = self.sync.sync_allreduce(
                np.array([float(self.Eobjective(
                    self.scenario_objectives(self.x)))]),
                abort_on_quit=False, timeout=60.0)[0]
        except TimeoutError:
            # a peer died without reaching the wrap-up collective; its
            # own exception is the root cause — don't mask it with a
            # stall, report "no global objective" instead
            eobj = np.nan
        return self.conv, float(eobj), self.trivial_bound

    def run(self):
        try:
            return self.sync.run(self._work)
        finally:
            self.sync.close()


def shard_range(S, my_shard, n_shards):
    """The contiguous [lo, hi) scenario range of a shard — the ONE
    definition both entry points (in-process make_shard, process worker)
    must agree on (ref. spbase.py:172 _calculate_scenario_ranks)."""
    if n_shards > S:
        raise ValueError(
            f"{n_shards} shards for {S} scenarios would leave empty "
            "shards (the reference requires scenarios >= ranks too, "
            "ref. spbase.py:172)")
    return (S * my_shard) // n_shards, (S * (my_shard + 1)) // n_shards


def make_shard(batch, options, n_shards, my_shard, shm_prefix=None,
               windows=None, **kw):
    """Build shard ``my_shard`` of ``n_shards`` from the FULL batch: slice
    the contiguous range, keep global probabilities."""
    lo, hi = shard_range(batch.S, my_shard, n_shards)
    return APHShard(shard_batch(batch, lo, hi), options, n_shards, my_shard,
                    shm_prefix=shm_prefix, windows=windows, **kw)


# ---- multi-process driver (the deployment shape: one shard per host
# process, shm/DCN exchange; ref. aph.py:818 APH_main under mpiexec) ----

def _shard_worker(model, num_scens, creator_kwargs, options, n_shards,
                  my_shard, prefix, q, wheel=None):
    """``wheel``: optional dict {run_id, spoke_kinds, hub_options} —
    shard 0 then opens the spoke windows the launcher created and
    carries an APHShardHub through the APH loop (every shard gets
    options["aph_wheel_S"] so the WX gather exists group-wide)."""
    import os

    try:
        # FORCE, not setdefault (matching utils/multiproc.py:81): under
        # the tunneled-TPU environment JAX_PLATFORMS=axon is exported
        # globally, and a child inheriting it would fight the parent for
        # the single-process device tunnel instead of running on cpu.
        # The env var alone is not enough — jax binds jax_platforms from
        # the environment at IMPORT time, and the spawn machinery has
        # already imported this module (and jax with it) before this
        # worker runs, so the config must be set explicitly too.
        platform = str((options or {}).get("jax_platform", "cpu"))
        os.environ["JAX_PLATFORMS"] = platform
        import jax

        jax.config.update("jax_platforms", platform)
        from ..utils.runtime import setup_jax_runtime

        setup_jax_runtime(f32=bool((options or {}).get("f32", False)))
        import importlib

        mod = importlib.import_module(f"mpisppy_tpu.models.{model}")
        from ..ir.batch import build_batch, subtree

        # lower ONLY this shard's scenarios (the reference builds per-rank
        # locals the same way, ref. spbase.py:242 _create_scenarios) — the
        # model-lowering step is the expensive part at large S
        tree = mod.make_tree(num_scens)
        lo, hi = shard_range(num_scens, my_shard, n_shards)
        batch = build_batch(mod.scenario_creator, subtree(tree, lo, hi),
                            creator_kwargs=creator_kwargs)
        eng = APHShard(batch, options, n_shards, my_shard, shm_prefix=prefix)
        hub = None
        if wheel is not None and my_shard == 0:
            from ..cylinders.hub import APHShardHub
            from ..utils.multiproc import open_spoke_proxies

            proxies = open_spoke_proxies(wheel["spoke_kinds"],
                                         wheel["run_id"], num_scens,
                                         batch.K)
            hub = APHShardHub(eng, spokes=proxies,
                              options=wheel.get("hub_options") or {})
            hub.classify_spokes()
            hub.windows_made = True
            hub.setup_hub()
            eng.spcomm = hub
        try:
            conv, eobj, triv = eng.run()
        finally:
            if hub is not None:
                # release the spoke processes whatever happened to the
                # APH loop (the launcher joins them afterwards)
                hub.send_terminate()
        if hub is not None:
            outer, inner = hub.hub_finalize()
            for proxy in hub.spokes:
                proxy.hub_window.close(unlink=False)
                proxy.my_window.close(unlink=False)
            q.put((my_shard, (conv, eobj, triv, eng._iter, outer, inner)))
        else:
            q.put((my_shard, (conv, eobj, triv, eng._iter)))
    except Exception as e:           # surface, don't hang the parent —
        # construction failures (shm open timeout, spbase validation)
        # must reach the queue too, not just run() failures
        q.put((my_shard, e))
        raise


def spin_aph_shards(model: str, num_scens: int, options, n_shards: int,
                    creator_kwargs=None, join_timeout=600.0, _wheel=None):
    """Spawn one OS process per scenario shard and run APHShard in each.
    Returns shard 0's (conv, Eobjective, trivial_bound, iters). The spawn
    context is used so children initialize JAX cleanly."""
    import multiprocessing as mp
    import os
    import secrets

    shard_range(num_scens, 0, n_shards)   # fail fast on empty shards
    ctx = mp.get_context("spawn")
    prefix = f"/aphs{os.getpid():x}{secrets.token_hex(3)}"
    q = ctx.Queue()
    procs = [ctx.Process(target=_shard_worker,
                         args=(model, num_scens, creator_kwargs,
                               dict(options or {}), n_shards, i, prefix, q,
                               _wheel if i == 0 else None),
                         daemon=True)
             for i in range(n_shards)]
    for p in procs:
        p.start()
    results = {}
    try:
        import queue as _queue

        for _ in range(n_shards):
            try:
                shard, res = q.get(timeout=join_timeout)
            except _queue.Empty:
                dead = [i for i, p in enumerate(procs) if not p.is_alive()]
                raise RuntimeError(
                    f"APH shards never reported within {join_timeout:.0f}s; "
                    f"dead shard processes: {dead or 'none (hung)'}")
            if isinstance(res, Exception):
                raise RuntimeError(f"APH shard {shard} failed: {res!r}")
            results[shard] = res
    finally:
        for p in procs:
            p.join(timeout=30.0)
            if p.is_alive():
                p.terminate()
        # terminated/crashed children never reach Synchronizer.close();
        # reap whatever segments the group left in /dev/shm
        from ..utils.synchronizer import cleanup_shm

        cleanup_shm(prefix)
    return results[0]


def spin_aph_shard_wheel(cfg, n_shards: int, join_timeout=600.0,
                         spoke_ready_timeout=300.0):
    """The reference's "APH hub + bound spokes under mpiexec" deployment
    shape (ref. mpisppy/cylinders/hub.py:606 APHHub over rank groups):
    one OS process per scenario shard running APHShard over the async
    Synchronizer, PLUS one OS process per spoke cylinder (the same
    worker utils/multiproc uses), with shard 0 carrying the wheel's hub
    communicator (cylinders/hub.APHShardHub). ``cfg`` is a RunConfig
    whose hub is "aph"; returns (conv, Eobjective, trivial_bound,
    iters, best_outer, best_inner)."""
    import multiprocessing as mp
    import os
    import secrets

    from ..utils.multiproc import spawn_spoke_processes, wait_spoke_hellos
    from ..ir.batch import build_batch, subtree
    import importlib

    cfg.validate()
    mod = importlib.import_module(f"mpisppy_tpu.models.{cfg.model}")
    # K without lowering the whole batch: lower scenario 0 only
    probe = build_batch(mod.scenario_creator,
                        subtree(mod.make_tree(cfg.num_scens), 0, 1),
                        creator_kwargs=cfg.model_kwargs)
    S, K = cfg.num_scens, probe.K

    run_id = f"/apw{os.getpid():x}{secrets.token_hex(3)}"
    ctx = mp.get_context("spawn")
    owned, spoke_procs = [], []
    try:
        proxies, spoke_procs, owned = spawn_spoke_processes(
            cfg, run_id, ctx, S, K)
        # wait for every spoke's startup hello so a fast APH run cannot
        # terminate before the spokes are wired (the parent-side
        # proxies are only used for this wait; shard 0 opens its own)
        wait_spoke_hellos(cfg, proxies, spoke_procs, spoke_ready_timeout)

        options = dict(cfg.algo.to_options())
        options.update(cfg.hub_options)
        options["aph_wheel_S"] = S
        hub_options = {}
        if cfg.rel_gap is not None:
            hub_options["rel_gap"] = cfg.rel_gap
        if cfg.abs_gap is not None:
            hub_options["abs_gap"] = cfg.abs_gap
        wheel = {"run_id": run_id,
                 "spoke_kinds": [sp.kind for sp in cfg.spokes],
                 "hub_options": hub_options}
        res = spin_aph_shards(cfg.model, S, options, n_shards,
                              creator_kwargs=cfg.model_kwargs,
                              join_timeout=join_timeout, _wheel=wheel)
        return res
    finally:
        for p in spoke_procs:
            p.join(timeout=30.0)
            if p.is_alive():
                p.terminate()
        for w in owned:
            w.close(unlink=True)
