"""ExtensiveForm: build and solve the monolithic deterministic equivalent.

The reference flattens the scenario dict into one Pyomo model with explicit
nonanticipativity constraints on reference variables and hands it to a
commercial solver (ref. mpisppy/utils/sputils.py:168 create_EF,
mpisppy/opt/ef.py:61 solve_extensive_form). The TPU version substitutes
shared columns instead of adding equality rows: every tree node owns one
copy of its nonant variables, scenario-local variables get their own
columns, and each scenario's constraint block maps through a column-index
gather. The result is a single (batch-of-one) QP for the batched ADMM
kernel — fewer rows, better conditioning than the equality-row EF.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ir.batch import ScenarioBatch
from ..ops.qp_solver import (QPData, qp_setup, qp_solve, qp_cold_state,
                             qp_solve_segmented)
from .spbase import SPBase


class ExtensiveForm(SPBase):
    def __init__(self, batch: ScenarioBatch, options=None, dtype=None):
        super().__init__(batch, options, dtype)
        self._build_columns()

    def _build_columns(self):
        b = self.batch
        S, n, K = b.S, b.n, b.K
        tree = b.tree
        nonant_set = set(b.nonant_idx.tolist())
        local_cols = [j for j in range(n) if j not in nonant_set]
        n_local = len(local_cols)

        # global node ids: stage-major offsets
        node_offsets = np.cumsum([0] + tree.nodes_per_stage)  # per non-leaf stage
        total_nodes = int(node_offsets[-1])

        # nonant column table: (node_global_id, slot_within_stage) -> EF col
        stage_slot_counts = [sl.stop - sl.start for sl in b.stage_slot_slices]
        nonant_col_offset = np.zeros(total_nodes + 1, dtype=np.int64)
        g = 0
        for t, N in enumerate(tree.nodes_per_stage):
            for _ in range(N):
                nonant_col_offset[g + 1] = nonant_col_offset[g] + stage_slot_counts[t]
                g += 1
        n_nonant_cols = int(nonant_col_offset[-1])

        # per-scenario column map: x_s[j] = x_EF[colmap[s, j]]
        colmap = np.zeros((S, n), dtype=np.int64)
        for s in range(S):
            for t in range(tree.num_stages - 1):
                node_g = int(node_offsets[t] + tree.node_path[s, t])
                sl = b.stage_slot_slices[t]
                for k_local, j in enumerate(b.nonant_idx[sl.start:sl.stop]):
                    colmap[s, j] = nonant_col_offset[node_g] + k_local
            for k_local, j in enumerate(local_cols):
                colmap[s, j] = n_nonant_cols + s * n_local + k_local

        self.n_ef = n_nonant_cols + S * n_local
        self.colmap = colmap
        self._n_local = n_local

        # EF tensors
        m = b.m
        A_ef = np.zeros((S * m, self.n_ef))
        for s in range(S):
            # colmap[s] is injective, so this is a pure column scatter
            A_ef[s * m:(s + 1) * m][:, colmap[s]] = np.asarray(b.A_of(s))
        l_ef = np.asarray(b.l).reshape(-1)
        u_ef = np.asarray(b.u).reshape(-1)

        c_ef = np.zeros(self.n_ef)
        P_ef = np.zeros(self.n_ef)
        lb_ef = np.full(self.n_ef, -np.inf)
        ub_ef = np.full(self.n_ef, np.inf)
        for s in range(S):
            p = float(b.prob[s])
            np.add.at(c_ef, colmap[s], p * np.asarray(b.c[s]))
            np.add.at(P_ef, colmap[s], p * np.asarray(b.P_diag[s]))
            lb_ef[colmap[s]] = np.maximum(lb_ef[colmap[s]], np.asarray(b.lb[s]))
            ub_ef[colmap[s]] = np.minimum(ub_ef[colmap[s]], np.asarray(b.ub[s]))
        self.c0_ef = float(np.dot(b.prob, b.c0))

        t = self.dtype
        self.ef_data: QPData = QPData(
            jnp.asarray(P_ef, t)[None], jnp.asarray(A_ef, t)[None],
            jnp.asarray(l_ef, t)[None], jnp.asarray(u_ef, t)[None],
            jnp.asarray(lb_ef, t)[None], jnp.asarray(ub_ef, t)[None])
        self.c_ef = jnp.asarray(c_ef, t)[None]

    def solve_extensive_form(self, max_iter=40000, eps_abs=1e-7, eps_rel=1e-7,
                             integer=False, integer_method="milp",
                             time_limit=120.0, mip_gap=None):
        """Solve the EF; mirrors opt/ef.py:61. Returns (objective, x_batch)
        where x_batch is the per-scenario (S, n) solution block.

        ``integer=True`` solves the EF as a MIP:
        - ``integer_method="milp"`` (default): the host HiGHS B&B
          (scipy.optimize.milp) — the direct analog of the reference
          handing the monolithic EF to a rented solver (ref. opt/ef.py:61,
          phbase.py:1307). The EF is ONE host-side problem; sequential
          B&B is the right tool for it, exactly as in the reference.
        - ``integer_method="dive"``: the batched on-device fix-and-dive
          (core/mip.py) — integer-FEASIBLE (an upper bound with a small
          gap, typically ~1-2%), fully on the accelerator."""
        factors = qp_setup(self.ef_data, q_ref=self.c_ef)
        st = qp_cold_state(factors, self.ef_data)
        # segmented: watchdog-bounded device executions AND host-side
        # rho adaptation on backends whose in-jit f64 adaptation is
        # disabled (see qp_solver._device_f64_linalg_trusted)
        st, x_ef, _, _ = qp_solve_segmented(
            factors, self.ef_data, self.c_ef, st, max_iter=max_iter,
            segment=min(500, max_iter), eps_abs=eps_abs, eps_rel=eps_rel)
        if integer and np.asarray(self.batch.integer).any():
            integer_ef = np.zeros(self.n_ef, bool)
            for s in range(self.batch.S):
                integer_ef[self.colmap[s]] = np.asarray(self.batch.integer)
            if integer_method == "milp" and float(np.abs(
                    np.asarray(self.ef_data.P_diag)).max()) > 0.0:
                # HiGHS milp is LP-only; quadratic EFs go through the dive
                integer_method = "dive"
            if integer_method == "milp":
                from .mip import milp_solve
                x_int, _, feasible = milp_solve(
                    self.ef_data, self.c_ef, self.c0_ef, integer_ef,
                    time_limit=time_limit, mip_gap=mip_gap)
                x_int = jnp.asarray(x_int, self.dtype)
            else:
                from .mip import dive_integers
                x_int, _, feasible, st = dive_integers(
                    factors, self.ef_data, self.c_ef, self.c0_ef, st,
                    integer_ef, max_iter=max_iter, eps=eps_abs)
            if not bool(np.asarray(feasible).all()):
                raise RuntimeError("EF integer solve failed to reach an "
                                   "integer-feasible point")
            x_ef = x_int
        self.solver_state = st
        x_ef = np.asarray(x_ef[0])
        x_batch = x_ef[self.colmap]  # (S, n)
        obj = float(self.Eobjective(self.scenario_objectives(jnp.asarray(x_batch, self.dtype))))
        self.ef_x = x_ef
        self.x_batch = x_batch
        return obj, x_batch

    def get_objective_value(self):
        """User-sense objective (ref. opt/ef.py:102 get_root_solution)."""
        if not hasattr(self, "x_batch"):
            raise RuntimeError("call solve_extensive_form first")
        obj = float(self.Eobjective(self.scenario_objectives(
            jnp.asarray(self.x_batch, self.dtype))))
        return obj if self.batch.template.sense == "min" else -obj

    def get_root_solution(self):
        """First-stage nonant values (shared across scenarios)."""
        sl = self.batch.stage_slot_slices[0]
        return self.x_batch[0, self.batch.nonant_idx[sl]]
