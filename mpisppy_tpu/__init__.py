"""mpisppy_tpu: a TPU-native framework for optimization under uncertainty.

A ground-up redesign of the capabilities of mpi-sppy (reference:
/root/reference, pure-Python + MPI + Pyomo + commercial MIP solvers) for the
JAX/XLA/TPU stack:

- scenarios are a stacked, HBM-resident batch of standard-form LP/QP tensors
  (instead of per-rank Pyomo ConcreteModels, ref. mpisppy/spbase.py:242),
- per-scenario subproblem solves are a vmapped batched ADMM QP solver
  (instead of Gurobi/CPLEX via SolverFactory, ref. mpisppy/phbase.py:1304),
- nonanticipativity reductions (x-bar, W) are mesh collectives / batched
  matmuls (instead of per-tree-node MPI Allreduce, ref. mpisppy/phbase.py:196),
- the hub-and-spoke "cylinders" architecture is recreated as host-coordinated
  asynchronous exchanges with the same write-id freshness protocol
  (ref. mpisppy/cylinders/spcommunicator.py:97-124).
"""

import time as _time

__version__ = "0.1.0"

_T0 = _time.monotonic()


def global_toc(msg: str, cond: bool = True) -> None:
    """Wall-clock trace line, mirroring the reference's global_toc
    (ref. mpisppy/__init__.py:22-28): stamps ``[ssss.ss] msg``."""
    if cond:
        print(f"[{_time.monotonic() - _T0:8.2f}] {msg}", flush=True)


def tictoc() -> float:
    """Seconds since process start of this framework."""
    return _time.monotonic() - _T0
