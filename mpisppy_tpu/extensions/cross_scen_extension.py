"""CrossScenarioExtension: paces the hub's EF-bound solves.

ref. mpisppy/extensions/cross_scen_extension.py:16-283. The structural work
(eta variables, EF objective, cut rows) lives in
``core.cross_scenario.CrossScenarioPH``; this extension reproduces the
reference's *pacing*: once any cuts exist, attempt a bound check when the
incumbent has sat unchanged for ``check_bound_improve_iterations``
iterations, when the outer bound moved, or periodically when fresh cuts
arrived (ref. :246-262 miditer logic).
"""

from __future__ import annotations

import math

from .extension import Extension


class CrossScenarioExtension(Extension):
    def __init__(self, options=None):
        super().__init__(options)
        cso = self.options.get("cross_scen_options", self.options)
        self.check_iters = int(cso.get("check_bound_improve_iterations", 10))
        self.cur_ib = None
        self.iter_at_cur_ib = 1
        self.cur_ob = None
        self.iter_since_last_check = 0

    def post_iter0(self, opt):
        # iter 0's prox/W-off solve just produced per-scenario wait-and-see
        # dual bounds: use them as valid eta lower bounds
        if hasattr(opt, "update_eta_bounds"):
            opt.update_eta_bounds()

    def miditer(self, opt):
        if not getattr(opt, "any_cuts", False):
            return
        spcomm = opt.spcomm
        self.iter_since_last_check += 1

        ib = getattr(spcomm, "BestInnerBound", None) if spcomm is not None else None
        if ib != self.cur_ib:
            self.cur_ib = ib
            self.iter_at_cur_ib = 1
        elif self.cur_ib is not None and math.isfinite(self.cur_ib):
            self.iter_at_cur_ib += 1

        ob = getattr(spcomm, "BestOuterBound", None) if spcomm is not None else None
        ob_new = not (self.cur_ob is not None and ob is not None
                      and math.isclose(ob, self.cur_ob))
        if ob_new:
            self.cur_ob = ob

        check = ((self.iter_at_cur_ib == self.check_iters)
                 or (self.iter_at_cur_ib > self.check_iters and ob_new)
                 or (self.iter_since_last_check % self.check_iters == 0
                     and opt.new_cuts))
        if not check:
            return
        bound = opt.solve_ef_bound()
        opt.new_cuts = False
        self.iter_since_last_check = 0
        if bound is not None and spcomm is not None \
                and hasattr(spcomm, "OuterBoundUpdate"):
            spcomm.OuterBoundUpdate(bound, char="C")
