"""NormRhoUpdater: adaptive rho from primal/dual residual balance.

ref. mpisppy/extensions/norm_rho_updater.py:33. Classic residual-balancing
(Boyd et al. §3.4.1 as the reference cites): per iteration compute the
primal residual ‖x − x̄‖ (prob-weighted, reduced over scenarios) and the
dual residual ρ‖x̄ − x̄_prev‖; multiply rho by ``rho_update_factor`` when
primal > mult·dual, divide when dual > mult·primal.

The residuals here are whole-vector norms computed from the already-device-
resident xbar/x tensors; updating rho invalidates the engine's cached KKT
factorization (rho sits on the prox diagonal).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .extension import Extension


class NormRhoUpdater(Extension):
    def __init__(self, options=None):
        super().__init__(options)
        o = self.options.get("norm_rho_options", self.options)
        self.mult = float(o.get("primal_dual_mult", 10.0))
        self.factor = float(o.get("rho_update_factor", 2.0))
        self.verbose = bool(o.get("verbose", False))
        self._prev_xbar = None
        self.prim_hist, self.dual_hist = [], []

    def miditer(self, opt):
        xn = opt._hub_nonants()
        xbar = opt.xbar
        prim = float(jnp.dot(opt.prob, jnp.sum(jnp.abs(xn - xbar), axis=1)))
        if self._prev_xbar is None:
            self._prev_xbar = np.asarray(xbar)
            return
        dual = float(np.mean(np.asarray(opt.rho)) *
                     np.abs(np.asarray(xbar) - self._prev_xbar).sum() /
                     max(opt.batch.S, 1))
        self._prev_xbar = np.asarray(xbar)
        self.prim_hist.append(prim)
        self.dual_hist.append(dual)
        if prim > self.mult * dual:
            opt.rho = opt.rho * self.factor
            opt.invalidate_factors()
            if self.verbose:
                print(f"NormRhoUpdater it {opt._iter}: rho *= {self.factor} "
                      f"(prim {prim:.3e} dual {dual:.3e})")
        elif dual > self.mult * prim:
            opt.rho = opt.rho / self.factor
            opt.invalidate_factors()
            if self.verbose:
                print(f"NormRhoUpdater it {opt._iter}: rho /= {self.factor} "
                      f"(prim {prim:.3e} dual {dual:.3e})")
