"""NormRhoUpdater: adaptive rho from primal/dual residual balance.

ref. mpisppy/extensions/norm_rho_updater.py:33. Classic residual-balancing
(Boyd et al. §3.4.1 as the reference cites): per iteration compute the
primal residual ‖x − x̄‖ (prob-weighted, reduced over scenarios) and the
dual residual ρ‖x̄ − x̄_prev‖; multiply rho by ``rho_update_factor`` when
primal > mult·dual, divide when dual > mult·primal.

Two spellings:

- :class:`NormRhoUpdater` — the reference-shaped WHOLE-VECTOR update
  (one scalar factor on the whole rho block).
- :class:`DeviceNormRhoUpdater` — the per-SLOT device-side update
  (ops/shrink.per_slot_rho_update, ROADMAP item 5): each nonant slot
  balances its own residual pair, producing a vector rho on the prox
  diagonal. rho stays uniform across scenarios, so the engine's
  single-factor prox path keeps serving it; every applied update
  invalidates the cached KKT factorization exactly like the scalar
  spelling (rho sits on the prox diagonal).

Residual histories are bounded deques (``history_cap``, default 512):
the old unbounded lists leaked host memory on serve-hosted and
rolling-horizon wheels that run for days.
"""

from __future__ import annotations

from collections import deque

import jax.numpy as jnp
import numpy as np

from .extension import Extension

HISTORY_CAP_DEFAULT = 512


class NormRhoUpdater(Extension):
    def __init__(self, options=None):
        super().__init__(options)
        o = self.options.get("norm_rho_options", self.options)
        self.mult = float(o.get("primal_dual_mult", 10.0))
        self.factor = float(o.get("rho_update_factor", 2.0))
        self.verbose = bool(o.get("verbose", False))
        cap = int(o.get("history_cap", HISTORY_CAP_DEFAULT))
        self._prev_xbar = None
        self.prim_hist = deque(maxlen=cap)
        self.dual_hist = deque(maxlen=cap)

    def reset(self):
        """Forget per-run state (serve install_batch calls this when a
        warm engine is re-leased to a new tenant)."""
        self._prev_xbar = None
        self.prim_hist.clear()
        self.dual_hist.clear()

    def miditer(self, opt):
        xn = opt._hub_nonants()
        xbar = opt.xbar
        prim = float(jnp.dot(opt.prob, jnp.sum(jnp.abs(xn - xbar), axis=1)))
        if self._prev_xbar is None:
            self._prev_xbar = np.asarray(xbar)
            return
        dual = float(np.mean(np.asarray(opt.rho)) *
                     np.abs(np.asarray(xbar) - self._prev_xbar).sum() /
                     max(opt.batch.S, 1))
        self._prev_xbar = np.asarray(xbar)
        self.prim_hist.append(prim)
        self.dual_hist.append(dual)
        if prim > self.mult * dual:
            opt.rho = opt.rho * self.factor
            opt.invalidate_factors()
            if self.verbose:
                print(f"NormRhoUpdater it {opt._iter}: rho *= {self.factor} "
                      f"(prim {prim:.3e} dual {dual:.3e})")
        elif dual > self.mult * prim:
            opt.rho = opt.rho / self.factor
            opt.invalidate_factors()
            if self.verbose:
                print(f"NormRhoUpdater it {opt._iter}: rho /= {self.factor} "
                      f"(prim {prim:.3e} dual {dual:.3e})")


class DeviceNormRhoUpdater(Extension):
    """Per-slot residual balancing as ONE jitted op. The host pays a
    single tiny (3,) D2H per update pass ([changed, prim_sum,
    dual_sum] — the history samples ride it, they are not separate
    reads) instead of the whole-vector spelling's three big-array
    pulls. ``shrink_rho_interval`` rate-limits update passes: every
    APPLIED update invalidates the factor cache, and a per-iteration
    refactorization can cost more than the stepsize win on small
    models.

    options: ``primal_dual_mult``, ``rho_update_factor``,
    ``shrink_rho_interval`` (or ``update_interval``), ``history_cap``.
    Compatible with the ``adaptive_rho=False`` incumbent-pool path by
    construction — that knob freezes the SOLVER's internal rho_scale
    trajectory, while this extension moves the engine-level prox rho
    between iterations (the two never meet inside one solve)."""

    def __init__(self, options=None):
        super().__init__(options)
        o = self.options.get("norm_rho_options", self.options)
        self.mult = float(o.get("primal_dual_mult", 10.0))
        self.factor = float(o.get("rho_update_factor", 2.0))
        self.interval = int(o.get("shrink_rho_interval",
                                  o.get("update_interval", 1)))
        self.verbose = bool(o.get("verbose", False))
        cap = int(o.get("history_cap", HISTORY_CAP_DEFAULT))
        self._prev_xbar = None
        self.prim_hist = deque(maxlen=cap)
        self.dual_hist = deque(maxlen=cap)
        self.updates = 0

    def reset(self):
        """Forget per-run state (serve install_batch calls this when a
        warm engine is re-leased to a new tenant)."""
        self._prev_xbar = None
        self.prim_hist.clear()
        self.dual_hist.clear()
        self.updates = 0

    def miditer(self, opt):
        # _prev_xbar refreshes EVERY miditer (a device reference, no
        # D2H), not only on update passes: the dual residual must span
        # one iteration like the primal one, or an interval > 1 would
        # compare an interval-accumulated dual against a single-step
        # primal and bias the balance toward shrinking rho
        prev, self._prev_xbar = self._prev_xbar, opt.xbar
        if prev is None:
            return
        if self.interval > 1 and opt._iter % self.interval:
            return
        from ..ops import shrink as shrink_ops
        new_rho, stats = shrink_ops.per_slot_rho_update(
            opt.rho, opt.prob, opt._hub_nonants(), opt.xbar,
            prev, self.mult, self.factor)
        st = np.asarray(stats)     # the ONE (3,) D2H of the pass
        self.prim_hist.append(float(st[1]))
        self.dual_hist.append(float(st[2]))
        if st[0] > 0:
            opt.rho = new_rho
            opt.invalidate_factors()
            self.updates += 1
            from .. import obs
            obs.counter_add("shrink.rho_updates")
            obs.event("shrink.rho", {"iter": opt._iter,
                                     "prim_sum": float(st[1]),
                                     "dual_sum": float(st[2])})
            if self.verbose:
                print(f"DeviceNormRhoUpdater it {opt._iter}: per-slot "
                      f"rho update (prim {st[1]:.3e} dual {st[2]:.3e})")
