"""Extension (plugin) protocol for algorithm engines.

Mirrors the reference's Extension/PHExtension/MultiPHExtension callback
protocol (ref. mpisppy/extensions/extension.py:14-121): engines call the
hooks ``pre_iter0 / post_iter0 / miditer / enditer / post_everything``
around the iteration loop (ref. phbase.py:1438,1516,1552,1604) and
``post_solve`` after each batched solve pass (ref. phbase.py:955).

Each hook receives the engine (``opt``) so extensions stay stateless with
respect to the batch; any mutable extension state lives on the extension
instance itself.
"""

from .extension import Extension, MultiExtension
from .fixer import DeviceFixer, Fixer, FixerTuple
from .mipgapper import Gapper
from .norm_rho_updater import DeviceNormRhoUpdater, NormRhoUpdater
from .xhatclosest import XhatClosest
from .diagnoser import Diagnoser
from .avgminmaxer import MinMaxAvg
from .wxbar_io import WXBarWriter, WXBarReader

__all__ = [
    "Extension", "MultiExtension", "Fixer", "FixerTuple", "DeviceFixer",
    "Gapper", "NormRhoUpdater", "DeviceNormRhoUpdater", "XhatClosest",
    "Diagnoser", "MinMaxAvg", "WXBarWriter", "WXBarReader",
]
