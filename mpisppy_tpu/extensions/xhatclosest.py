"""XhatClosest: try the scenario nearest to x̄ as the incumbent.

ref. mpisppy/extensions/xhatclosest.py:10. The reference picks the scenario
minimizing a truncated z-score distance to x̄ (Allreduce MIN + rank
tie-break) and evaluates it via the xhat machinery. Here the distance is a
single vectorized reduction over the (S, K) nonant block and evaluation is
``PHBase.calculate_incumbent`` (batched fixed-nonant solve).
"""

from __future__ import annotations

import numpy as np

from .extension import Extension


class XhatClosest(Extension):
    def __init__(self, options=None):
        super().__init__(options)
        o = self.options.get("xhat_closest_options", self.options)
        self.keep_solution = bool(o.get("keep_solution", True))
        self.best_bound = None     # inner (upper, for min) bound
        self.best_xhat = None

    def _distance(self, opt):
        xn = np.asarray(opt._hub_nonants())    # (S, K)
        xbar = np.asarray(opt.xbar)
        std = np.sqrt(np.maximum(np.asarray(opt.xsqbar) - xbar * xbar, 0.0))
        z = np.abs(xn - xbar) / np.maximum(std, 1e-6)
        z = np.minimum(z, 10.0)   # truncation, matching the reference's cap
        return z.sum(axis=1)      # (S,)

    def try_closest(self, opt):
        s = int(np.argmin(self._distance(opt)))
        xhat = np.asarray(opt._hub_nonants())[s]
        val = opt.calculate_incumbent(xhat)
        if val is not None and (self.best_bound is None or val < self.best_bound):
            self.best_bound = val
            self.best_xhat = opt.round_nonants(xhat)
            if opt.spcomm is not None and hasattr(opt.spcomm, "InnerBoundUpdate"):
                opt.spcomm.InnerBoundUpdate(val, char="C")
        return val

    def miditer(self, opt):
        self.try_closest(opt)

    def post_everything(self, opt):
        val = self.try_closest(opt)
        if opt.options.get("verbose"):
            print(f"XhatClosest: final inner bound {self.best_bound}")
