"""Diagnoser: per-scenario per-iteration objective dump.

ref. mpisppy/extensions/diagnoser.py:16-71 (writes one file per rank into
``diagnoser_options["diagnoser_outdir"]``). Here one process holds every
scenario, so a single CSV accumulates (iter, scenario, objective) rows.
"""

from __future__ import annotations

import os

import numpy as np

from .extension import Extension


class Diagnoser(Extension):
    def __init__(self, options=None):
        super().__init__(options)
        o = self.options.get("diagnoser_options", self.options)
        self.outdir = o.get("diagnoser_outdir", ".")
        self.rows = []

    def _record(self, opt):
        obj = np.asarray(opt._last_base_obj)
        it = opt._iter
        for s, v in enumerate(obj):
            self.rows.append((it, opt.batch.tree.scen_names[s], float(v)))

    def post_iter0(self, opt):
        self._record(opt)

    def enditer(self, opt):
        self._record(opt)

    def post_everything(self, opt):
        os.makedirs(self.outdir, exist_ok=True)
        path = os.path.join(self.outdir, "diagnoser.csv")
        with open(path, "w") as f:
            f.write("iter,scenario,objective\n")
            for it, name, v in self.rows:
                f.write(f"{it},{name},{v}\n")
