"""MinMaxAvg: print avg/min/max of a named variable across scenarios.

ref. mpisppy/extensions/avgminmaxer.py:10 (options key ``avgminmax_name``).
"""

from __future__ import annotations

import numpy as np

from .extension import Extension


class MinMaxAvg(Extension):
    def __init__(self, options=None):
        super().__init__(options)
        self.compstr = self.options.get("avgminmax_name", None)
        self.history = []

    def _show(self, opt, when):
        if self.compstr is None or opt.x is None:
            return
        vals = opt.gather_var_values(opt.x)
        if self.compstr not in vals:
            raise KeyError(f"avgminmax_name {self.compstr!r} is not a "
                           f"variable: {list(vals)}")
        arr = vals[self.compstr]
        per_scen = arr.sum(axis=1)   # scalar summary per scenario
        avg, mn, mx = float(per_scen.mean()), float(per_scen.min()), float(per_scen.max())
        self.history.append((when, avg, mn, mx))
        print(f"====> {when} {self.compstr}: avg={avg:.4f} min={mn:.4f} max={mx:.4f}")

    def post_iter0(self, opt):
        self._show(opt, f"iter {opt._iter}")

    def enditer(self, opt):
        self._show(opt, f"iter {opt._iter}")

    def post_everything(self, opt):
        self._show(opt, "final")
