"""W / x̄ save & load: warm start and algorithm-state checkpointing.

ref. mpisppy/utils/wxbarwriter.py:31, wxbarreader.py:32, wxbarutils.py:40-368.
The reference round-trips (W, x̄) through CSV files as its only warm-start /
checkpoint mechanism (SURVEY §5.4). The algorithm state here is a handful of
device tensors, so the native format is a single ``.npz`` holding
(W, xbar, xsqbar, rho, iter); a CSV mode matching the reference's
``(scenario, slot, value)`` / ``(slot, value)`` row shapes is kept for
interop and human inspection.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from .extension import Extension


def _real_S(opt):
    """Checkpoints carry REAL scenarios only: a sharded engine pads its
    batch with zero-probability copies (doc/sharding.md), and a file
    written with pad rows would refuse to load into an unsharded run
    of the same model (and vice versa)."""
    return getattr(opt, "_S_orig", opt.batch.S)


def _placer(opt):
    """Engine-matched device placement for a full (S, K) host block: a
    host-placed W/x̄ on a mesh engine would recompile every jitted step
    for the new input sharding."""
    t = opt.dtype
    if opt.mesh is not None:
        import jax
        from ..parallel.mesh import scenario_sharding

        def place(a):
            return jax.device_put(jnp.asarray(a, t),
                                  scenario_sharding(opt.mesh, 2))
        return place
    return lambda a: jnp.asarray(a, t)


def save_state(opt, path):
    """Checkpoint the PH algorithm state to ``path`` (npz). ATOMIC:
    written to a temp sibling and ``os.replace``'d (the live.json
    pattern) — ``np.savez`` straight onto the target could leave a
    torn npz under a mid-write SIGKILL, exactly the preemption this
    checkpoint exists to survive."""
    from ..ckpt.bundle import atomic_savez

    S = _real_S(opt)
    atomic_savez(path, W=np.asarray(opt.W)[:S],
                 xbar=np.asarray(opt.xbar)[:S],
                 xsqbar=np.asarray(opt.xsqbar)[:S],
                 rho=np.asarray(opt.rho)[:S], iter=np.asarray(opt._iter))


def install_state_arrays(opt, d):
    """Install validated (W, x̄, x̄², ρ, iter) host blocks onto an
    engine: shape-checked against the REAL scenario count, mesh pads
    re-filled by replicating the last real row (pads ARE copies of the
    last scenario, so its x̄/ρ rows are the consistent fill and pad W
    carries no objective weight), engine-matched placement, factor
    invalidation when rho moved. The ONE install body behind
    ``load_state`` and the ckpt bundle resume
    (mpisppy_tpu.ckpt.manager.resume_hub)."""
    S_real, K = _real_S(opt), opt.batch.K
    S = opt.batch.S
    for key in ("W", "xbar", "xsqbar", "rho"):
        if d[key].shape != (S_real, K):
            raise ValueError(f"{key} shape {d[key].shape} != "
                             f"({S_real}, {K})")

    def pad(a):
        if S == S_real:
            return a
        return np.concatenate([a, np.repeat(a[-1:], S - S_real, axis=0)])

    place = _placer(opt)
    opt.W = place(pad(d["W"]))
    opt.xbar = place(pad(d["xbar"]))
    opt.xsqbar = place(pad(d["xsqbar"]))
    old_rho = np.asarray(opt.rho)
    new_rho = pad(np.asarray(d["rho"]))
    opt.rho = place(new_rho)
    opt._iter = int(d["iter"])
    if not np.allclose(old_rho, new_rho):
        opt.invalidate_factors()


def load_state(opt, path):
    """Restore a checkpoint saved by ``save_state``. Payloads pass the
    SAME load-side validation as checkpoint bundles
    (ckpt.bundle.validate_state_arrays): non-finite blocks and absurd
    iteration counters are rejected with a reasoned error and a
    ``ckpt.rejected.<reason>`` counter instead of installing NaNs into
    the prox center."""
    from .. import obs
    from ..ckpt.bundle import CheckpointError, validate_state_arrays

    with np.load(path) as f:
        raw = {k: np.asarray(f[k]) for k in f.files}
    try:
        d = validate_state_arrays(raw)
    except CheckpointError as e:
        obs.counter_add(f"ckpt.rejected.{e.reason}")
        raise
    install_state_arrays(opt, d)


def _write_scen_csv(opt, path, arr):
    """(scenario, slot, value) rows of an (S, K) block — REAL scenarios
    only (mesh pad rows carry generated ``_pad*`` names an unsharded
    reader of the same model could never resolve)."""
    with open(path, "w") as f:
        f.write("scenario,slot,value\n")
        for s, name in enumerate(opt.batch.tree.scen_names[:_real_S(opt)]):
            for k in range(opt.batch.K):
                f.write(f"{name},{k},{arr[s, k]:.17g}\n")


def _read_scen_csv(opt, path, arr):
    """Fill an (S, K) array in place from _write_scen_csv output, or from
    the legacy 2-column (slot, value) format (broadcast to all rows)."""
    name_to_s = {n: i for i, n in enumerate(opt.batch.tree.scen_names)}
    with open(path) as f:
        header = next(f)
        per_scen = header.strip().startswith("scenario")
        for line in f:
            if per_scen:
                name, k, v = line.rsplit(",", 2)
                arr[name_to_s[name], int(k)] = float(v)
            else:
                k, v = line.split(",")
                arr[:, int(k)] = float(v)
    return arr


def write_w_csv(opt, path):
    """(scenario, slot, value) rows (ref. wxbarutils.py:40 w_writer)."""
    _write_scen_csv(opt, path, np.asarray(opt.W))


def _read_and_install(opt, path, cur):
    """Shared body of the CSV readers: fill real rows from the file,
    re-fill mesh pad rows from the last real row (same semantics as
    ``load_state``), and install with the engine's placement."""
    a = _read_scen_csv(opt, path, np.asarray(cur).copy())
    S_real = _real_S(opt)
    if opt.batch.S != S_real:
        a[S_real:] = a[S_real - 1]
    return _placer(opt)(a)


def read_w_csv(opt, path):
    opt.W = _read_and_install(opt, path, opt.W)


def write_xbar_csv(opt, path):
    """(scenario, slot, value) rows — the full (S, K) block. On multistage
    trees xbar rows differ per node path, so a root-row-only dump would
    lose every non-root node's mean (ref. wxbarutils.py xbar_writer writes
    per-node values)."""
    _write_scen_csv(opt, path, np.asarray(opt.xbar))


def read_xbar_csv(opt, path):
    opt.xbar = _read_and_install(opt, path, opt.xbar)


class WXBarWriter(Extension):
    """options: {"W_fname": path or None, "Xbar_fname": path or None,
    "ckpt_fname": path or None, "every": int}. CSV names mirror the
    reference's PHoptions keys (ref. wxbarwriter.py:52-66)."""

    def __init__(self, options=None):
        super().__init__(options)
        self.w_fname = self.options.get("W_fname")
        self.x_fname = self.options.get("Xbar_fname")
        self.ckpt_fname = self.options.get("ckpt_fname")
        self.every = int(self.options.get("every", 0))  # 0 = only at end

    def _dump(self, opt):
        if self.w_fname:
            write_w_csv(opt, self.w_fname)
        if self.x_fname:
            write_xbar_csv(opt, self.x_fname)
        if self.ckpt_fname:
            save_state(opt, self.ckpt_fname)

    def enditer(self, opt):
        if self.every and opt._iter % self.every == 0:
            self._dump(opt)

    def post_everything(self, opt):
        self._dump(opt)


class WXBarReader(Extension):
    """options: {"init_W_fname", "init_Xbar_fname", "init_ckpt_fname"}
    (ref. wxbarreader.py:40-55). Loads before iter 0 so PH resumes."""

    def __init__(self, options=None):
        super().__init__(options)
        self.w_fname = self.options.get("init_W_fname")
        self.x_fname = self.options.get("init_Xbar_fname")
        self.ckpt_fname = self.options.get("init_ckpt_fname")

    def pre_iter0(self, opt):
        if self.ckpt_fname and os.path.exists(self.ckpt_fname):
            load_state(opt, self.ckpt_fname)
            opt._warm_started = True
            opt._warm_started_xbar = True   # ckpt restores xbar too
            return
        if self.w_fname and os.path.exists(self.w_fname):
            read_w_csv(opt, self.w_fname)
            opt._warm_started = True
        if self.x_fname and os.path.exists(self.x_fname):
            read_xbar_csv(opt, self.x_fname)
            # an xbar-only load must keep iter 0 from overwriting the
            # loaded prox center, or the warm start is a silent no-op
            opt._warm_started_xbar = True
