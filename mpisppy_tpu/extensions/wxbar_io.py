"""W / x̄ save & load: warm start and algorithm-state checkpointing.

ref. mpisppy/utils/wxbarwriter.py:31, wxbarreader.py:32, wxbarutils.py:40-368.
The reference round-trips (W, x̄) through CSV files as its only warm-start /
checkpoint mechanism (SURVEY §5.4). The algorithm state here is a handful of
device tensors, so the native format is a single ``.npz`` holding
(W, xbar, xsqbar, rho, iter); a CSV mode matching the reference's
``(scenario, slot, value)`` / ``(slot, value)`` row shapes is kept for
interop and human inspection.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from .extension import Extension


def save_state(opt, path):
    """Checkpoint the PH algorithm state to ``path`` (npz)."""
    np.savez(path, W=np.asarray(opt.W), xbar=np.asarray(opt.xbar),
             xsqbar=np.asarray(opt.xsqbar), rho=np.asarray(opt.rho),
             iter=np.asarray(opt._iter))


def load_state(opt, path):
    """Restore a checkpoint saved by ``save_state`` (shape-checked)."""
    d = np.load(path)
    S, K = opt.batch.S, opt.batch.K
    for key in ("W", "xbar", "xsqbar", "rho"):
        if d[key].shape != (S, K):
            raise ValueError(f"{key} shape {d[key].shape} != ({S}, {K})")
    t = opt.dtype
    opt.W = jnp.asarray(d["W"], t)
    opt.xbar = jnp.asarray(d["xbar"], t)
    opt.xsqbar = jnp.asarray(d["xsqbar"], t)
    old_rho = np.asarray(opt.rho)
    opt.rho = jnp.asarray(d["rho"], t)
    opt._iter = int(d["iter"])
    if not np.allclose(old_rho, d["rho"]):
        opt.invalidate_factors()


def _write_scen_csv(opt, path, arr):
    """(scenario, slot, value) rows of an (S, K) block."""
    with open(path, "w") as f:
        f.write("scenario,slot,value\n")
        for s, name in enumerate(opt.batch.tree.scen_names):
            for k in range(opt.batch.K):
                f.write(f"{name},{k},{arr[s, k]:.17g}\n")


def _read_scen_csv(opt, path, arr):
    """Fill an (S, K) array in place from _write_scen_csv output, or from
    the legacy 2-column (slot, value) format (broadcast to all rows)."""
    name_to_s = {n: i for i, n in enumerate(opt.batch.tree.scen_names)}
    with open(path) as f:
        header = next(f)
        per_scen = header.strip().startswith("scenario")
        for line in f:
            if per_scen:
                name, k, v = line.rsplit(",", 2)
                arr[name_to_s[name], int(k)] = float(v)
            else:
                k, v = line.split(",")
                arr[:, int(k)] = float(v)
    return arr


def write_w_csv(opt, path):
    """(scenario, slot, value) rows (ref. wxbarutils.py:40 w_writer)."""
    _write_scen_csv(opt, path, np.asarray(opt.W))


def read_w_csv(opt, path):
    opt.W = jnp.asarray(_read_scen_csv(opt, path, np.asarray(opt.W).copy()),
                        opt.dtype)


def write_xbar_csv(opt, path):
    """(scenario, slot, value) rows — the full (S, K) block. On multistage
    trees xbar rows differ per node path, so a root-row-only dump would
    lose every non-root node's mean (ref. wxbarutils.py xbar_writer writes
    per-node values)."""
    _write_scen_csv(opt, path, np.asarray(opt.xbar))


def read_xbar_csv(opt, path):
    opt.xbar = jnp.asarray(
        _read_scen_csv(opt, path, np.asarray(opt.xbar).copy()), opt.dtype)


class WXBarWriter(Extension):
    """options: {"W_fname": path or None, "Xbar_fname": path or None,
    "ckpt_fname": path or None, "every": int}. CSV names mirror the
    reference's PHoptions keys (ref. wxbarwriter.py:52-66)."""

    def __init__(self, options=None):
        super().__init__(options)
        self.w_fname = self.options.get("W_fname")
        self.x_fname = self.options.get("Xbar_fname")
        self.ckpt_fname = self.options.get("ckpt_fname")
        self.every = int(self.options.get("every", 0))  # 0 = only at end

    def _dump(self, opt):
        if self.w_fname:
            write_w_csv(opt, self.w_fname)
        if self.x_fname:
            write_xbar_csv(opt, self.x_fname)
        if self.ckpt_fname:
            save_state(opt, self.ckpt_fname)

    def enditer(self, opt):
        if self.every and opt._iter % self.every == 0:
            self._dump(opt)

    def post_everything(self, opt):
        self._dump(opt)


class WXBarReader(Extension):
    """options: {"init_W_fname", "init_Xbar_fname", "init_ckpt_fname"}
    (ref. wxbarreader.py:40-55). Loads before iter 0 so PH resumes."""

    def __init__(self, options=None):
        super().__init__(options)
        self.w_fname = self.options.get("init_W_fname")
        self.x_fname = self.options.get("init_Xbar_fname")
        self.ckpt_fname = self.options.get("init_ckpt_fname")

    def pre_iter0(self, opt):
        if self.ckpt_fname and os.path.exists(self.ckpt_fname):
            load_state(opt, self.ckpt_fname)
            opt._warm_started = True
            opt._warm_started_xbar = True   # ckpt restores xbar too
            return
        if self.w_fname and os.path.exists(self.w_fname):
            read_w_csv(opt, self.w_fname)
            opt._warm_started = True
        if self.x_fname and os.path.exists(self.x_fname):
            read_xbar_csv(opt, self.x_fname)
            # an xbar-only load must keep iter 0 from overwriting the
            # loaded prox center, or the warm start is a silent no-op
            opt._warm_started_xbar = True
