"""W / x̄ save & load: warm start and algorithm-state checkpointing.

ref. mpisppy/utils/wxbarwriter.py:31, wxbarreader.py:32, wxbarutils.py:40-368.
The reference round-trips (W, x̄) through CSV files as its only warm-start /
checkpoint mechanism (SURVEY §5.4). The algorithm state here is a handful of
device tensors, so the native format is a single ``.npz`` holding
(W, xbar, xsqbar, rho, iter); a CSV mode matching the reference's
``(scenario, slot, value)`` / ``(slot, value)`` row shapes is kept for
interop and human inspection.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from .extension import Extension


def save_state(opt, path):
    """Checkpoint the PH algorithm state to ``path`` (npz)."""
    np.savez(path, W=np.asarray(opt.W), xbar=np.asarray(opt.xbar),
             xsqbar=np.asarray(opt.xsqbar), rho=np.asarray(opt.rho),
             iter=np.asarray(opt._iter))


def load_state(opt, path):
    """Restore a checkpoint saved by ``save_state`` (shape-checked)."""
    d = np.load(path)
    S, K = opt.batch.S, opt.batch.K
    for key in ("W", "xbar", "xsqbar", "rho"):
        if d[key].shape != (S, K):
            raise ValueError(f"{key} shape {d[key].shape} != ({S}, {K})")
    t = opt.dtype
    opt.W = jnp.asarray(d["W"], t)
    opt.xbar = jnp.asarray(d["xbar"], t)
    opt.xsqbar = jnp.asarray(d["xsqbar"], t)
    old_rho = np.asarray(opt.rho)
    opt.rho = jnp.asarray(d["rho"], t)
    opt._iter = int(d["iter"])
    if not np.allclose(old_rho, d["rho"]):
        opt.invalidate_factors()


def write_w_csv(opt, path):
    """(scenario, slot, value) rows (ref. wxbarutils.py:40 w_writer)."""
    W = np.asarray(opt.W)
    with open(path, "w") as f:
        f.write("scenario,slot,value\n")
        for s, name in enumerate(opt.batch.tree.scen_names):
            for k in range(opt.batch.K):
                f.write(f"{name},{k},{W[s, k]:.17g}\n")


def read_w_csv(opt, path):
    W = np.asarray(opt.W).copy()
    name_to_s = {n: i for i, n in enumerate(opt.batch.tree.scen_names)}
    with open(path) as f:
        next(f)
        for line in f:
            name, k, v = line.rsplit(",", 2)
            W[name_to_s[name], int(k)] = float(v)
    opt.W = jnp.asarray(W, opt.dtype)


def write_xbar_csv(opt, path):
    """(slot, value) rows from the root-stage view (ref. wxbarutils.py
    xbar_writer — xbar is per tree node; scenario 0's row carries them all)."""
    xbar = np.asarray(opt.xbar)
    with open(path, "w") as f:
        f.write("slot,value\n")
        for k in range(opt.batch.K):
            f.write(f"{k},{xbar[0, k]:.17g}\n")


def read_xbar_csv(opt, path):
    xbar = np.asarray(opt.xbar).copy()
    with open(path) as f:
        next(f)
        for line in f:
            k, v = line.split(",")
            xbar[:, int(k)] = float(v)
    opt.xbar = jnp.asarray(xbar, opt.dtype)


class WXBarWriter(Extension):
    """options: {"W_fname": path or None, "Xbar_fname": path or None,
    "ckpt_fname": path or None, "every": int}. CSV names mirror the
    reference's PHoptions keys (ref. wxbarwriter.py:52-66)."""

    def __init__(self, options=None):
        super().__init__(options)
        self.w_fname = self.options.get("W_fname")
        self.x_fname = self.options.get("Xbar_fname")
        self.ckpt_fname = self.options.get("ckpt_fname")
        self.every = int(self.options.get("every", 0))  # 0 = only at end

    def _dump(self, opt):
        if self.w_fname:
            write_w_csv(opt, self.w_fname)
        if self.x_fname:
            write_xbar_csv(opt, self.x_fname)
        if self.ckpt_fname:
            save_state(opt, self.ckpt_fname)

    def enditer(self, opt):
        if self.every and opt._iter % self.every == 0:
            self._dump(opt)

    def post_everything(self, opt):
        self._dump(opt)


class WXBarReader(Extension):
    """options: {"init_W_fname", "init_Xbar_fname", "init_ckpt_fname"}
    (ref. wxbarreader.py:40-55). Loads before iter 0 so PH resumes."""

    def __init__(self, options=None):
        super().__init__(options)
        self.w_fname = self.options.get("init_W_fname")
        self.x_fname = self.options.get("init_Xbar_fname")
        self.ckpt_fname = self.options.get("init_ckpt_fname")

    def pre_iter0(self, opt):
        if self.ckpt_fname and os.path.exists(self.ckpt_fname):
            load_state(opt, self.ckpt_fname)
            opt._warm_started = True
            return
        if self.w_fname and os.path.exists(self.w_fname):
            read_w_csv(opt, self.w_fname)
            opt._warm_started = True
        if self.x_fname and os.path.exists(self.x_fname):
            read_xbar_csv(opt, self.x_fname)
