"""Fixer: WW-style iterative fixing of converged integer nonants.

ref. mpisppy/extensions/fixer.py:50. The reference keeps a per-variable
conv counter driven by the x̄² ≈ x̄² ("xbar squared vs xsqbar") variance
test and fixes a variable after it has been converged for N consecutive
iterations — at its current common value (``nb``), or at its lower/upper
bound when parked there (``lb``/``ub``). Tuples ``(varid, th, nb, lb, ub)``
come from a user ``id_fix_list_fct``.

TPU redesign: the counters are a (K,) device-friendly integer array and the
whole test-and-fix is one vectorized pass per ``miditer`` — no per-variable
Python loop, no solver var objects; fixing feeds ``PHBase.fix_nonants``
(bound-pinning inside the jitted step) with an accumulated mask.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .extension import Extension


@dataclass
class FixerTuple:
    """Per-slot fixing thresholds (ref. fixer.py:20 Fixer_tuple). ``None``
    disables that mode. Counts are in consecutive converged iterations."""
    tol: float = 1e-4
    nb: int | None = None   # fix at value when converged this many iters
    lb: int | None = None   # fix at lower bound when parked there
    ub: int | None = None   # fix at upper bound when parked there


def uniform_fix_list(batch, tol=1e-4, nb=3, lb=3, ub=3, integer_only=True):
    """Convenience id_fix_list_fct: the same FixerTuple for every nonant slot
    (integer slots only by default, matching typical reference usage)."""
    K = batch.K
    integer_mask = np.asarray(batch.integer)[np.asarray(batch.nonant_idx)]
    active = integer_mask if integer_only else np.ones(K, bool)
    inf = np.iinfo(np.int64).max

    def to_arr(v):
        a = np.full(K, inf if v is None else int(v), dtype=np.int64)
        a[~active] = inf
        return a

    return {"tol": np.full(K, float(tol)),
            "nb": to_arr(nb), "lb": to_arr(lb), "ub": to_arr(ub)}


class Fixer(Extension):
    """options: {"id_fix_list_fct": batch -> dict(tol,nb,lb,ub arrays),
    "boundtol": float}. Counters update each ``miditer``; a slot fixed once
    stays fixed (the reference never unfixes, fixer.py docstring)."""

    def __init__(self, options=None):
        super().__init__(options)
        self._init_done = False

    def reset(self):
        """Forget per-run state (see DeviceFixer.reset)."""
        self._init_done = False
        self.nfixed = 0

    def _setup(self, opt):
        K = opt.batch.K
        fct = self.options.get("id_fix_list_fct", None)
        spec = fct(opt.batch) if fct is not None else uniform_fix_list(opt.batch)
        self.tol = np.asarray(spec["tol"], float)
        self.nb = np.asarray(spec["nb"], np.int64)
        self.lbc = np.asarray(spec["lb"], np.int64)
        self.ubc = np.asarray(spec["ub"], np.int64)
        self.boundtol = float(self.options.get("boundtol", 1e-6))
        self.conv_count = np.zeros(K, np.int64)   # value-converged streak
        self.lb_count = np.zeros(K, np.int64)     # parked-at-lb streak
        self.ub_count = np.zeros(K, np.int64)
        idx = np.asarray(opt.batch.nonant_idx)
        self.slot_lb = np.asarray(opt.batch.lb)[:, idx]   # (S,K)
        self.slot_ub = np.asarray(opt.batch.ub)[:, idx]
        self.fixed_mask = np.zeros((opt.batch.S, K), bool)
        self.fixed_vals = np.zeros((opt.batch.S, K))
        self._init_done = True
        self.nfixed = 0

    def post_iter0(self, opt):
        if not self._init_done:
            self._setup(opt)

    def miditer(self, opt):
        if not self._init_done:
            self._setup(opt)
        xbar = np.asarray(opt.xbar)          # (S,K)
        xsqbar = np.asarray(opt.xsqbar)
        xn = np.asarray(opt._hub_nonants())  # (S,K) current solutions
        # variance test per slot: all scenarios agree when E[x^2]-E[x]^2 ~ 0
        # (ref. fixer.py xbar/xsqbar test). Reduce over the scenario axis so
        # the counter is per-slot even with per-node xbars.
        var = np.max(np.abs(xsqbar - xbar * xbar), axis=0)
        agree = var <= self.tol * self.tol + 1e-15
        self.conv_count = np.where(agree, self.conv_count + 1, 0)
        at_lb = np.all(np.abs(xn - self.slot_lb) <= self.boundtol, axis=0)
        at_ub = np.all(np.abs(xn - self.slot_ub) <= self.boundtol, axis=0)
        self.lb_count = np.where(agree & at_lb, self.lb_count + 1, 0)
        self.ub_count = np.where(agree & at_ub, self.ub_count + 1, 0)

        fix_lb = self.lb_count >= self.lbc
        fix_ub = (self.ub_count >= self.ubc) & ~fix_lb
        fix_nb = (self.conv_count >= self.nb) & ~fix_lb & ~fix_ub
        newly = (fix_lb | fix_ub | fix_nb) & ~self.fixed_mask[0]
        if not newly.any():
            return
        # per-scenario values: on multistage trees each scenario's xbar row
        # carries its OWN node's mean (and bounds may differ per scenario),
        # so fixing must use the full (S, K) arrays — broadcasting row 0
        # would pin non-root nonants at another node's value, which the
        # reference never does (it fixes at each variable's node value)
        value = np.where(fix_lb[None, :], self.slot_lb,
                         np.where(fix_ub[None, :], self.slot_ub, xbar))
        # integer slots snap to the nearest integer before fixing
        imask = opt.nonant_integer_mask
        value = np.where(imask[None, :], np.round(value), value)
        self.fixed_vals[:, newly] = value[:, newly]
        self.fixed_mask[:, newly] = True
        self.nfixed = int(self.fixed_mask[0].sum())
        opt.fix_nonants(self.fixed_vals, mask=self.fixed_mask)
        if opt.options.get("verbose"):
            print(f"Fixer: {self.nfixed}/{opt.batch.K} nonants fixed "
                  f"at iter {opt._iter}")

    def post_everything(self, opt):
        if self._init_done and opt.options.get("verbose"):
            print(f"Fixer: final fixed count {self.nfixed}")


class DeviceFixer(Extension):
    """The Fixer's test-and-fix as ONE jitted op over the hub's (S, K)
    device state (ops/shrink.fixer_update, ROADMAP item 5): per-slot
    consecutive-converged counters, bound-parking votes, and the
    accumulated fix mask live ON DEVICE — no per-``miditer`` D2H of
    xbar/xsqbar/x (the host Fixer pulled all three every pass). The
    host reads ONE scalar (the fixed-slot count) per iteration, after
    the PH step's existing convergence sync has already materialized
    the arrays — a copy, not a pipeline stall.

    With ``shrink_compact`` enabled the fixed-count trajectory also
    drives :meth:`PHBase.maybe_compact` — active-set compaction at the
    bucketed thresholds (doc/extensions.md §shrinking).

    options (engine options or a dedicated dict): ``id_fix_list_fct``
    (same contract as Fixer), ``boundtol``, ``shrink_fix_iters``
    (default threshold when no fix-list fct is given),
    ``shrink_fix_tol``."""

    def __init__(self, options=None):
        super().__init__(options)
        self._init_done = False
        self.nfixed = 0

    def reset(self):
        """Forget per-run state (serve install_batch calls this when a
        warm engine is re-leased to a new tenant): counters, streaks,
        and the latched slot bounds all re-derive from the NEW batch
        on the next ``_setup``."""
        self._init_done = False
        self.nfixed = 0

    def _setup(self, opt):
        import jax.numpy as jnp
        K = opt.batch.K
        fct = self.options.get("id_fix_list_fct", None)
        if fct is not None:
            spec = fct(opt.batch)
        else:
            it = int(self.options.get("shrink_fix_iters", 3))
            spec = uniform_fix_list(
                opt.batch, tol=float(self.options.get("shrink_fix_tol",
                                                      1e-4)),
                nb=it, lb=it, ub=it)
        t = opt.dtype
        from ..ops import shrink as shrink_ops
        clip = lambda a: np.minimum(np.asarray(a, np.int64),
                                    shrink_ops.INT_NEVER)
        self._tol = jnp.asarray(spec["tol"], t)
        self._nbc = jnp.asarray(clip(spec["nb"]))
        self._lbc = jnp.asarray(clip(spec["lb"]))
        self._ubc = jnp.asarray(clip(spec["ub"]))
        self._boundtol = float(self.options.get("boundtol", 1e-6))
        z = jnp.zeros(K, jnp.int32)
        self._conv_count, self._lb_count, self._ub_count = z, z, z
        idx = np.asarray(opt.batch.nonant_idx)
        self._slot_lb = jnp.asarray(np.asarray(opt.batch.lb)[:, idx], t)
        self._slot_ub = jnp.asarray(np.asarray(opt.batch.ub)[:, idx], t)
        self._imask = jnp.asarray(opt.nonant_integer_mask)
        self._init_done = True

    def post_iter0(self, opt):
        if not self._init_done:
            self._setup(opt)

    def miditer(self, opt):
        from ..ops import shrink as shrink_ops
        if not self._init_done:
            self._setup(opt)
        (self._conv_count, self._lb_count, self._ub_count,
         fixed_mask, fixed_vals, n_fixed) = shrink_ops.fixer_update(
            self._conv_count, self._lb_count, self._ub_count,
            opt._fixed_mask, opt._fixed_vals, opt.xbar, opt.xsqbar,
            opt._hub_nonants(), self._slot_lb, self._slot_ub,
            self._tol, self._boundtol, self._nbc, self._lbc, self._ubc,
            self._imask)
        # the ONE host scalar of the pass: rides the iteration's conv
        # sync (the arrays are already materialized), drives the fix
        # event + the compaction trigger. The (S, K) mask/values stay
        # on device end to end — fix_nonants consumes device arrays.
        nf = int(n_fixed)
        if nf > self.nfixed:
            opt.fix_nonants(fixed_vals, mask=fixed_mask)
            from .. import obs
            obs.counter_add("shrink.fixed_new", nf - self.nfixed)
            obs.gauge_set("shrink.fixed_fraction", nf / opt.batch.K)
            obs.event("shrink.fix", {"iter": opt._iter, "fixed": nf,
                                     "free": opt.batch.K - nf})
            if opt.options.get("verbose"):
                print(f"DeviceFixer: {nf}/{opt.batch.K} nonants fixed "
                      f"at iter {opt._iter}")
        self.nfixed = nf
        st = getattr(opt, "_shrink_status", None)
        if st is not None:
            st["fixed"], st["free"] = nf, opt.batch.K - nf
        opt.maybe_compact(nf)

    def post_everything(self, opt):
        if self._init_done and opt.options.get("verbose"):
            print(f"DeviceFixer: final fixed count {self.nfixed}")
