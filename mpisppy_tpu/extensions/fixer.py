"""Fixer: WW-style iterative fixing of converged integer nonants.

ref. mpisppy/extensions/fixer.py:50. The reference keeps a per-variable
conv counter driven by the x̄² ≈ x̄² ("xbar squared vs xsqbar") variance
test and fixes a variable after it has been converged for N consecutive
iterations — at its current common value (``nb``), or at its lower/upper
bound when parked there (``lb``/``ub``). Tuples ``(varid, th, nb, lb, ub)``
come from a user ``id_fix_list_fct``.

TPU redesign: the counters are a (K,) device-friendly integer array and the
whole test-and-fix is one vectorized pass per ``miditer`` — no per-variable
Python loop, no solver var objects; fixing feeds ``PHBase.fix_nonants``
(bound-pinning inside the jitted step) with an accumulated mask.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .extension import Extension


@dataclass
class FixerTuple:
    """Per-slot fixing thresholds (ref. fixer.py:20 Fixer_tuple). ``None``
    disables that mode. Counts are in consecutive converged iterations."""
    tol: float = 1e-4
    nb: int | None = None   # fix at value when converged this many iters
    lb: int | None = None   # fix at lower bound when parked there
    ub: int | None = None   # fix at upper bound when parked there


def uniform_fix_list(batch, tol=1e-4, nb=3, lb=3, ub=3, integer_only=True):
    """Convenience id_fix_list_fct: the same FixerTuple for every nonant slot
    (integer slots only by default, matching typical reference usage)."""
    K = batch.K
    integer_mask = np.asarray(batch.integer)[np.asarray(batch.nonant_idx)]
    active = integer_mask if integer_only else np.ones(K, bool)
    inf = np.iinfo(np.int64).max

    def to_arr(v):
        a = np.full(K, inf if v is None else int(v), dtype=np.int64)
        a[~active] = inf
        return a

    return {"tol": np.full(K, float(tol)),
            "nb": to_arr(nb), "lb": to_arr(lb), "ub": to_arr(ub)}


class Fixer(Extension):
    """options: {"id_fix_list_fct": batch -> dict(tol,nb,lb,ub arrays),
    "boundtol": float}. Counters update each ``miditer``; a slot fixed once
    stays fixed (the reference never unfixes, fixer.py docstring)."""

    def __init__(self, options=None):
        super().__init__(options)
        self._init_done = False

    def _setup(self, opt):
        K = opt.batch.K
        fct = self.options.get("id_fix_list_fct", None)
        spec = fct(opt.batch) if fct is not None else uniform_fix_list(opt.batch)
        self.tol = np.asarray(spec["tol"], float)
        self.nb = np.asarray(spec["nb"], np.int64)
        self.lbc = np.asarray(spec["lb"], np.int64)
        self.ubc = np.asarray(spec["ub"], np.int64)
        self.boundtol = float(self.options.get("boundtol", 1e-6))
        self.conv_count = np.zeros(K, np.int64)   # value-converged streak
        self.lb_count = np.zeros(K, np.int64)     # parked-at-lb streak
        self.ub_count = np.zeros(K, np.int64)
        idx = np.asarray(opt.batch.nonant_idx)
        self.slot_lb = np.asarray(opt.batch.lb)[:, idx]   # (S,K)
        self.slot_ub = np.asarray(opt.batch.ub)[:, idx]
        self.fixed_mask = np.zeros((opt.batch.S, K), bool)
        self.fixed_vals = np.zeros((opt.batch.S, K))
        self._init_done = True
        self.nfixed = 0

    def post_iter0(self, opt):
        if not self._init_done:
            self._setup(opt)

    def miditer(self, opt):
        if not self._init_done:
            self._setup(opt)
        xbar = np.asarray(opt.xbar)          # (S,K)
        xsqbar = np.asarray(opt.xsqbar)
        xn = np.asarray(opt._hub_nonants())  # (S,K) current solutions
        # variance test per slot: all scenarios agree when E[x^2]-E[x]^2 ~ 0
        # (ref. fixer.py xbar/xsqbar test). Reduce over the scenario axis so
        # the counter is per-slot even with per-node xbars.
        var = np.max(np.abs(xsqbar - xbar * xbar), axis=0)
        agree = var <= self.tol * self.tol + 1e-15
        self.conv_count = np.where(agree, self.conv_count + 1, 0)
        at_lb = np.all(np.abs(xn - self.slot_lb) <= self.boundtol, axis=0)
        at_ub = np.all(np.abs(xn - self.slot_ub) <= self.boundtol, axis=0)
        self.lb_count = np.where(agree & at_lb, self.lb_count + 1, 0)
        self.ub_count = np.where(agree & at_ub, self.ub_count + 1, 0)

        fix_lb = self.lb_count >= self.lbc
        fix_ub = (self.ub_count >= self.ubc) & ~fix_lb
        fix_nb = (self.conv_count >= self.nb) & ~fix_lb & ~fix_ub
        newly = (fix_lb | fix_ub | fix_nb) & ~self.fixed_mask[0]
        if not newly.any():
            return
        # per-scenario values: on multistage trees each scenario's xbar row
        # carries its OWN node's mean (and bounds may differ per scenario),
        # so fixing must use the full (S, K) arrays — broadcasting row 0
        # would pin non-root nonants at another node's value, which the
        # reference never does (it fixes at each variable's node value)
        value = np.where(fix_lb[None, :], self.slot_lb,
                         np.where(fix_ub[None, :], self.slot_ub, xbar))
        # integer slots snap to the nearest integer before fixing
        imask = opt.nonant_integer_mask
        value = np.where(imask[None, :], np.round(value), value)
        self.fixed_vals[:, newly] = value[:, newly]
        self.fixed_mask[:, newly] = True
        self.nfixed = int(self.fixed_mask[0].sum())
        opt.fix_nonants(self.fixed_vals, mask=self.fixed_mask)
        if opt.options.get("verbose"):
            print(f"Fixer: {self.nfixed}/{opt.batch.K} nonants fixed "
                  f"at iter {opt._iter}")

    def post_everything(self, opt):
        if self._init_done and opt.options.get("verbose"):
            print(f"Fixer: final fixed count {self.nfixed}")
