"""Extension base class and composition.

ref. mpisppy/extensions/extension.py:14 (Extension), :90 (MultiPHExtension).
"""

from __future__ import annotations


class Extension:
    """Base extension: every hook is a no-op. Engines call hooks through
    ``PHBase._ext`` with themselves as the single argument."""

    def __init__(self, options=None):
        self.options = dict(options or {})

    def pre_iter0(self, opt):
        pass

    def post_iter0(self, opt):
        pass

    def miditer(self, opt):
        pass

    def enditer(self, opt):
        pass

    def post_everything(self, opt):
        pass

    def post_solve(self, opt):
        pass


class MultiExtension(Extension):
    """Compose a list of extension classes or instances in order
    (ref. extension.py:90 MultiPHExtension)."""

    def __init__(self, ext_classes, options=None):
        super().__init__(options)
        self.extensions = [e if isinstance(e, Extension) else e(options)
                           for e in ext_classes]

    def _all(self, hook, opt):
        for e in self.extensions:
            getattr(e, hook)(opt)

    def pre_iter0(self, opt):
        self._all("pre_iter0", opt)

    def post_iter0(self, opt):
        self._all("post_iter0", opt)

    def miditer(self, opt):
        self._all("miditer", opt)

    def enditer(self, opt):
        self._all("enditer", opt)

    def post_everything(self, opt):
        self._all("post_everything", opt)

    def post_solve(self, opt):
        self._all("post_solve", opt)
