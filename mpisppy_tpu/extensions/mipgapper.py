"""Gapper: subproblem-tolerance schedule keyed by PH iteration.

ref. mpisppy/extensions/mipgapper.py:11. The reference sets the MIP solver's
``mipgap`` option per a {iteration: gap} dict. In the TPU engine the
analogous knob is the batched ADMM solver's stopping tolerance
(``subproblem_eps``): loose early iterations converge PH faster per second,
tight late iterations certify bounds — the exact trade the reference's
gap schedule expresses.
"""

from __future__ import annotations

from .extension import Extension


class Gapper(Extension):
    """options: {"mipgapdict": {iter: tol}}. At each scheduled iteration the
    engine's subproblem tolerance is replaced and the cached jitted steps are
    rebuilt (the tolerance is a compile-time constant of the fused step)."""

    def __init__(self, options=None):
        super().__init__(options)
        self.schedule = {int(k): float(v)
                         for k, v in (self.options.get("mipgapdict") or {}).items()}

    def _apply(self, opt, it):
        if it in self.schedule:
            opt.sub_eps = self.schedule[it]   # static jit arg; next solve recompiles/reuses by eps
            if opt.options.get("verbose"):
                print(f"Gapper: subproblem_eps = {opt.sub_eps:g} at iter {it}")

    def pre_iter0(self, opt):
        self._apply(opt, 0)

    def miditer(self, opt):
        self._apply(opt, opt._iter)
