"""Lowering: Model -> standard-form tensors.

The canonical subproblem form is the two-sided (OSQP) form

    min  ½ xᵀ diag(P) x + cᵀx + c0
    s.t. l ≤ A x ≤ u,     lb ≤ x ≤ ub,     x_i ∈ ℤ for integer i

which uniformly captures equalities (l == u), one-sided inequalities, and
ranged constraints. This replaces the reference's L0/L1 path where Pyomo
expression trees are handed verbatim to a commercial solver
(ref. mpisppy/phbase.py:1307); here every scenario becomes a fixed-shape
tensor block so that scenarios stack into an HBM-resident batch.

Stage structure is preserved: ``c_stage[t]`` is the stage-(t+1) linear cost
row (they sum to ``c``), mirroring ScenarioNode.cost_expression
(ref. mpisppy/scenario_tree.py:41-103) and enabling Ebound/Eobjective-style
per-stage reporting.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class StandardForm:
    name: str
    n: int
    m: int
    c: np.ndarray          # (n,)
    c0: float
    P_diag: np.ndarray     # (n,) diagonal quadratic cost (0 for LPs)
    A: np.ndarray          # (m, n) dense constraint matrix
    l: np.ndarray          # (m,)
    u: np.ndarray          # (m,)
    lb: np.ndarray         # (n,)
    ub: np.ndarray         # (n,)
    integer: np.ndarray    # (n,) bool
    stage_of_var: np.ndarray  # (n,) int, 1-based stage of each variable
    c_stage: np.ndarray    # (num_stages, n) per-stage linear cost
    c0_stage: np.ndarray   # (num_stages,)
    var_names: list = field(default_factory=list)
    var_slices: dict = field(default_factory=dict)
    # row range of each NAMED constraint block in A/l/u — the address
    # space of the vector-patch fast path (ir/batch.py
    # build_batch(vector_patch=...)); unnamed constraints get "con{i}"
    con_slices: dict = field(default_factory=dict)
    sense: str = "min"     # lowered form is always minimization; this records
                           # the user sense so objective values can be reported
                           # in the user's convention

    def var_values(self, x, name):
        sl = self.var_slices[name]
        return x[..., sl]

    def objective(self, x):
        return 0.5 * np.dot(x * self.P_diag, x) + np.dot(self.c, x) + self.c0


def lower(model, num_stages=None) -> StandardForm:
    """Lower a Model to StandardForm (always minimization)."""
    n = model.n
    sign = 1.0 if model.sense == "min" else -1.0
    T = int(num_stages or model.num_stages)

    c_stage = np.zeros((T, n))
    c0_stage = np.zeros(T)
    for t, expr in model._stage_costs.items():
        row = np.zeros(n)
        for vname, M in expr.coeffs.items():
            row[model.var_slice(vname)] += M.reshape(-1)
        c_stage[t - 1] += sign * row
        c0_stage[t - 1] += sign * float(expr.const.sum())

    P = np.zeros(n)
    for vname, d in model._quad_diag.items():
        P[model.var_slice(vname)] += sign * d

    rows, los, his = [], [], []
    con_slices = {}
    r0 = 0
    for i, con in enumerate(model.constraints):
        M = np.zeros((con.expr.m, n))
        for vname, B in con.expr.coeffs.items():
            M[:, model.var_slice(vname)] += B
        rows.append(M)
        los.append(con.lo)
        his.append(con.hi)
        cname = con.name if con.name is not None else f"con{i}"
        if cname in con_slices:
            raise ValueError(
                f"duplicate constraint name {cname!r}: named constraints "
                "must be unique (they address rows in the vector-patch "
                "protocol, ir/batch.py build_batch)")
        con_slices[cname] = slice(r0, r0 + con.expr.m)
        r0 += con.expr.m
    if rows:
        A = np.concatenate(rows, axis=0)
        l = np.concatenate(los)
        u = np.concatenate(his)
    else:
        A = np.zeros((0, n))
        l = np.zeros(0)
        u = np.zeros(0)

    lb = np.zeros(n)
    ub = np.zeros(n)
    integer = np.zeros(n, dtype=bool)
    stage_of_var = np.zeros(n, dtype=np.int32)
    names, slices = [], {}
    for vname, v in model.vars.items():
        sl = model.var_slice(vname)
        lb[sl], ub[sl] = v.lb, v.ub
        integer[sl] = v.integer
        stage_of_var[sl] = v.stage
        names.append(vname)
        slices[vname] = sl

    return StandardForm(
        name=model.name, n=n, m=A.shape[0],
        c=c_stage.sum(axis=0), c0=float(c0_stage.sum()),
        P_diag=P, A=A, l=l, u=u, lb=lb, ub=ub,
        integer=integer, stage_of_var=stage_of_var,
        c_stage=c_stage, c0_stage=c0_stage,
        var_names=names, var_slices=slices, con_slices=con_slices,
        sense=model.sense,
    )
