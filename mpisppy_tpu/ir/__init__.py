from .model import Model, AffExpr, Constraint  # noqa: F401
from .standard_form import StandardForm  # noqa: F401
from .tree import ScenarioTree, two_stage_tree, balanced_tree  # noqa: F401
from .batch import ScenarioBatch, build_batch  # noqa: F401
