"""Declarative scenario-model DSL.

This replaces the reference's L0 substrate (user-written Pyomo ConcreteModels,
ref. examples/farmer/farmer.py:23-83) with a small affine modeling layer that
lowers directly to standard-form tensors (see standard_form.py). The user
contract mirrors the reference's ``scenario_creator`` protocol
(ref. mpisppy/spbase.py:477-492): a callback builds one Model per scenario and
declares which variables are nonanticipative at which stage.

Design notes (TPU-first):
- Expressions are *vectorized*: an ``AffExpr`` is a stack of affine rows
  ``M_v @ x_v + const`` held as dense numpy blocks per variable. Model build
  happens once on the host; the hot path consumes only the lowered tensors.
- Every scenario of a problem must produce the same structure (same variables,
  same constraint counts) so scenarios stack into one batch; only the numeric
  data may differ. This is what lets the scenario axis be a mesh axis.
"""

from __future__ import annotations

import numpy as np

_INF = np.inf


def _as2d(M, size):
    M = np.asarray(M, dtype=np.float64)
    if M.ndim == 0:
        return M.reshape(1, 1) * np.eye(size)[:1] if size == 1 else None
    return M


class Var:
    """A (flat) decision-variable block of a Model."""

    __slots__ = ("model", "name", "size", "lb", "ub", "integer", "stage", "offset")
    __array_ufunc__ = None  # make numpy defer to our reflected operators

    def __init__(self, model, name, size, lb, ub, integer, stage, offset):
        self.model = model
        self.name = name
        self.size = int(size)
        self.lb = np.broadcast_to(np.asarray(lb, dtype=np.float64), (self.size,)).copy()
        self.ub = np.broadcast_to(np.asarray(ub, dtype=np.float64), (self.size,)).copy()
        self.integer = bool(integer)
        self.stage = int(stage)
        self.offset = int(offset)  # start index in the flat x vector

    # ---- expression protocol: a Var acts as the identity AffExpr ----
    def _aff(self):
        return AffExpr({self.name: np.eye(self.size)}, np.zeros(self.size), self.model)

    def __getitem__(self, idx):
        rows = np.eye(self.size)[idx]
        if rows.ndim == 1:
            rows = rows[None, :]
        return AffExpr({self.name: rows}, np.zeros(rows.shape[0]), self.model)

    def sum(self):
        return AffExpr({self.name: np.ones((1, self.size))}, np.zeros(1), self.model)

    def dot(self, c):
        c = np.asarray(c, dtype=np.float64).reshape(1, self.size)
        return AffExpr({self.name: c}, np.zeros(1), self.model)

    def __add__(self, o):
        return self._aff() + o

    def __radd__(self, o):
        return self._aff() + o

    def __sub__(self, o):
        return self._aff() - o

    def __rsub__(self, o):
        return (-1.0) * self._aff() + o

    def __mul__(self, c):
        return self._aff() * c

    def __rmul__(self, c):
        return self._aff() * c

    def __neg__(self):
        return (-1.0) * self._aff()

    def __rmatmul__(self, M):
        M = np.atleast_2d(np.asarray(M, dtype=np.float64))
        return AffExpr({self.name: M}, np.zeros(M.shape[0]), self.model)

    def __le__(self, o):
        return self._aff() <= o

    def __ge__(self, o):
        return self._aff() >= o

    def __eq__(self, o):  # noqa: PLW3201 - intentional constraint builder
        return self._aff() == o

    def __hash__(self):
        return id(self)


class AffExpr:
    """A stack of m affine rows over the model's variables.

    Stored as ``coeffs[varname] -> (m, size_v) ndarray`` plus ``const (m,)``.
    """

    __slots__ = ("coeffs", "const", "model")
    __array_ufunc__ = None  # make numpy defer to our reflected operators

    def __init__(self, coeffs, const, model):
        self.coeffs = coeffs
        self.const = np.asarray(const, dtype=np.float64)
        self.model = model

    @property
    def m(self):
        return self.const.shape[0]

    @staticmethod
    def _coerce(o, model, m):
        """Coerce `o` to an AffExpr with m rows (broadcasting constants)."""
        if isinstance(o, Var):
            o = o._aff()
        if isinstance(o, AffExpr):
            return o
        arr = np.asarray(o, dtype=np.float64).reshape(-1)
        if arr.shape[0] == 1 and m > 1:
            arr = np.broadcast_to(arr, (m,))
        return AffExpr({}, arr.copy(), model)

    def _zip(self, o):
        o = AffExpr._coerce(o, self.model, self.m)
        m = max(self.m, o.m)
        return o, m

    def _bcast(self, m):
        if self.m == m:
            return self
        if self.m != 1:
            raise ValueError(f"row mismatch: {self.m} vs {m}")
        coeffs = {k: np.repeat(v, m, axis=0) for k, v in self.coeffs.items()}
        return AffExpr(coeffs, np.repeat(self.const, m), self.model)

    def __add__(self, o):
        o, m = self._zip(o)
        a, b = self._bcast(m), o._bcast(m)
        coeffs = dict(a.coeffs)
        for k, v in b.coeffs.items():
            coeffs[k] = coeffs[k] + v if k in coeffs else v
        return AffExpr(coeffs, a.const + b.const, self.model)

    def __radd__(self, o):
        return self + o

    def __sub__(self, o):
        o, m = self._zip(o)
        return self + (-1.0) * o

    def __rsub__(self, o):
        return (-1.0) * self + o

    def __mul__(self, c):
        c = np.asarray(c, dtype=np.float64)
        if c.ndim == 0:
            coeffs = {k: v * float(c) for k, v in self.coeffs.items()}
            return AffExpr(coeffs, self.const * float(c), self.model)
        c = c.reshape(-1)
        a = self._bcast(c.shape[0]) if self.m == 1 else self
        if a.m != c.shape[0]:
            raise ValueError("elementwise scale size mismatch")
        coeffs = {k: v * c[:, None] for k, v in a.coeffs.items()}
        return AffExpr(coeffs, a.const * c, self.model)

    def __rmul__(self, c):
        return self * c

    def __neg__(self):
        return self * -1.0

    def sum(self):
        coeffs = {k: v.sum(axis=0, keepdims=True) for k, v in self.coeffs.items()}
        return AffExpr(coeffs, np.array([self.const.sum()]), self.model)

    # ---- constraint builders ----
    def __le__(self, o):
        diff = self - o
        return Constraint(diff, lo=np.full(diff.m, -_INF), hi=-diff.const + 0.0)

    def __ge__(self, o):
        diff = self - o
        return Constraint(diff, lo=-diff.const + 0.0, hi=np.full(diff.m, _INF))

    def __eq__(self, o):  # noqa: PLW3201
        diff = self - o
        rhs = -diff.const + 0.0
        return Constraint(diff, lo=rhs, hi=rhs.copy())

    def __hash__(self):
        return id(self)


class Constraint:
    """``lo <= rows(expr) <= hi`` where the expr's constant has been folded
    into lo/hi (OSQP two-sided form; eq constraints have lo == hi)."""

    __slots__ = ("expr", "lo", "hi", "name")

    def __init__(self, expr, lo, hi, name=None):
        # strip the constant out of expr; bounds already account for it
        self.expr = AffExpr(expr.coeffs, np.zeros(expr.m), expr.model)
        self.lo = np.asarray(lo, dtype=np.float64)
        self.hi = np.asarray(hi, dtype=np.float64)
        self.name = name

    def ranged(self, lo, hi):
        """Explicit two-sided bounds (like Pyomo's (lb, expr, ub) tuples,
        ref. examples/farmer/farmer.py EnforceQuotas_rule)."""
        m = self.expr.m
        self.lo = np.broadcast_to(np.asarray(lo, dtype=np.float64), (m,)).copy()
        self.hi = np.broadcast_to(np.asarray(hi, dtype=np.float64), (m,)).copy()
        return self


class Model:
    """One scenario's optimization model (minimization canonical form).

    Replaces the Pyomo ConcreteModel + ``_mpisppy_node_list`` contract
    (ref. mpisppy/spbase.py:477-492). Stage costs are declared per stage;
    nonant declarations happen through the tree (ir/tree.py) by naming
    variables, mirroring ScenarioNode's nonant_list
    (ref. mpisppy/scenario_tree.py:41-103).
    """

    def __init__(self, name="model", sense="min"):
        assert sense in ("min", "max")
        self.name = name
        self.sense = sense
        self.vars: dict[str, Var] = {}
        self.constraints: list[Constraint] = []
        self._stage_costs: dict[int, AffExpr] = {}
        self._quad_diag: dict[str, np.ndarray] = {}  # optional ½ d_i x_i² terms
        self._n = 0

    # ---- declaration API ----
    def var(self, name, size=1, lb=0.0, ub=_INF, integer=False, stage=2):
        if name in self.vars:
            raise ValueError(f"duplicate var {name}")
        v = Var(self, name, size, lb, ub, integer, stage, self._n)
        self.vars[name] = v
        self._n += v.size
        return v

    def constr(self, con: Constraint, name=None):
        if not isinstance(con, Constraint):
            raise TypeError("expected a Constraint (use <=, >=, ==)")
        con.name = name
        self.constraints.append(con)
        return con

    def stage_cost(self, stage: int, expr):
        """Declare the cost expression for a stage (scalar AffExpr).
        Mirrors ScenarioNode.cost_expression (ref. scenario_tree.py:41)."""
        if isinstance(expr, Var):
            expr = expr._aff()
        if isinstance(expr, AffExpr):
            expr = expr.sum() if expr.m > 1 else expr
        else:
            expr = AffExpr({}, np.array([float(expr)]), self)
        self._stage_costs[int(stage)] = expr

    def quad_cost(self, var: Var, diag):
        """Add ½ Σ d_i x_i² to the objective (diagonal quadratic)."""
        d = np.broadcast_to(np.asarray(diag, dtype=np.float64), (var.size,))
        self._quad_diag[var.name] = self._quad_diag.get(var.name, 0.0) + d

    # ---- introspection ----
    @property
    def n(self):
        return self._n

    @property
    def num_stages(self):
        return max(self._stage_costs) if self._stage_costs else 1

    def var_slice(self, name):
        v = self.vars[name]
        return slice(v.offset, v.offset + v.size)
