"""ScenarioBatch: the stacked, device-ready scenario tensor block.

This is the TPU replacement for the reference's per-rank dict of Pyomo
models (ref. mpisppy/spbase.py:242 _create_scenarios): all S scenarios of a
problem are lowered to StandardForm and stacked along a leading scenario
axis. The scenario axis is the data-parallel mesh axis (ref. SURVEY §2.3
axis 1); everything the algorithms need per-iteration lives in these arrays.

Nonant bookkeeping mirrors _attach_nonant_indices (ref. spbase.py:272):
``nonant_idx`` maps the K nonanticipative slots (concatenated over non-leaf
stages) into columns of x, and ``nonant_stage`` records each slot's stage.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .standard_form import StandardForm, lower
from .tree import ScenarioTree


@dataclass
class ScenarioBatch:
    tree: ScenarioTree
    template: StandardForm          # scenario 0's form (shared structure)
    # stacked numeric data (numpy on host; engines move to device)
    c: np.ndarray                   # (S, n)
    c0: np.ndarray                  # (S,)
    P_diag: np.ndarray              # (S, n)
    A: np.ndarray                   # (S, m, n); or (m, n) when every
                                    # scenario shares one constraint
                                    # matrix (see shared_A) — the
                                    # representation that lets a
                                    # reference-scale UC batch (m·n ~
                                    # 3.4e8 entries) hold ONE matrix
                                    # instead of S copies
    l: np.ndarray                   # (S, m)
    u: np.ndarray                   # (S, m)
    lb: np.ndarray                  # (S, n)
    ub: np.ndarray                  # (S, n)
    c_stage: np.ndarray             # (S, T, n)
    c0_stage: np.ndarray            # (S, T)
    prob: np.ndarray                # (S,)
    # nonant structure (shared across scenarios)
    nonant_idx: np.ndarray          # (K,) int columns of x
    nonant_stage: np.ndarray        # (K,) int 1-based stage per slot
    stage_slot_slices: list = field(default_factory=list)  # per non-leaf stage: slice into K

    @property
    def S(self):
        return self.c.shape[0]

    @property
    def n(self):
        return self.c.shape[1]

    @property
    def m(self):
        return self.A.shape[-2]

    @property
    def K(self):
        return self.nonant_idx.shape[0]

    @property
    def integer(self):
        return self.template.integer

    @property
    def shared_A(self):
        """True when one (m, n) matrix serves every scenario."""
        return self.A.ndim == 2

    def A_of(self, s):
        """Scenario s's (m, n) constraint matrix under either layout."""
        return self.A if self.A.ndim == 2 else self.A[s]

    def nonants_of(self, x):
        """Extract the (.., K) nonant slots from a (.., n) x array."""
        return x[..., self.nonant_idx]


def _nonant_indexing(f0, tree):
    """Nonant slots, concatenated by stage (ref. spbase.py:272)."""
    nonant_idx, nonant_stage, slot_slices = [], [], []
    k = 0
    for t, names in enumerate(tree.nonant_names_per_stage, start=1):
        for vn in names:
            sl = f0.var_slices[vn]
            nonant_idx.extend(range(sl.start, sl.stop))
            nonant_stage.extend([t] * (sl.stop - sl.start))
        slot_slices.append(slice(k, len(nonant_idx)))
        k = len(nonant_idx)
    return (np.asarray(nonant_idx, dtype=np.int32),
            np.asarray(nonant_stage, dtype=np.int32), slot_slices)


# vector fields a vector_patch may address, with their (kind ->
# name-space) mapping: constraint-row fields address con_slices,
# variable-column fields address var_slices
_PATCH_ROW_FIELDS = ("l", "u")
_PATCH_COL_FIELDS = ("lb", "ub", "c")


def _apply_patch(vecs, f0, patch, scen_name):
    """Apply one scenario's {(field, block_name): values} patch to copies
    of the template vectors (see build_batch's vector_patch)."""
    for (fld, bname), val in patch.items():
        val = np.asarray(val, dtype=np.float64)
        if fld in _PATCH_ROW_FIELDS:
            sl = f0.con_slices.get(bname)
            if sl is None:
                raise KeyError(
                    f"{scen_name}: patch addresses unknown constraint "
                    f"{bname!r} (known: {list(f0.con_slices)})")
        elif fld in _PATCH_COL_FIELDS:
            sl = f0.var_slices.get(bname)
            if sl is None:
                raise KeyError(
                    f"{scen_name}: patch addresses unknown variable "
                    f"{bname!r} (known: {list(f0.var_slices)})")
        else:
            raise KeyError(
                f"{scen_name}: patch field {fld!r} not supported "
                f"(row fields: {_PATCH_ROW_FIELDS}, column fields: "
                f"{_PATCH_COL_FIELDS})")
        want = sl.stop - sl.start
        if val.shape != (want,):
            raise ValueError(
                f"{scen_name}: patch ({fld!r}, {bname!r}) has shape "
                f"{val.shape}, block needs ({want},)")
        if fld == "c":
            # keep the per-stage cost split consistent: a patched var's
            # cost lives in exactly its own stage's row (enforced), so
            # the total and that row move together
            t = int(f0.stage_of_var[sl.start]) - 1
            others = [tt for tt in range(vecs["c_stage"].shape[0])
                      if tt != t]
            if others and np.abs(vecs["c_stage"][others, sl]).max() > 0:
                raise ValueError(
                    f"{scen_name}: cannot patch c of {bname!r} — its "
                    "cost spans stages other than its own")
            vecs["c_stage"][t, sl] = val
            vecs["c"][sl] = val
        else:
            vecs[fld][sl] = val
    return vecs


def build_batch(scenario_creator, tree: ScenarioTree, creator_kwargs=None,
                num_stages=None, vector_patch=None) -> ScenarioBatch:
    """Call `scenario_creator(name, **kwargs) -> Model` for every scenario in
    the tree and stack the lowered forms. The creator contract mirrors the
    reference's (ref. spbase.py:477-492) minus the Pyomo attachments: the
    tree (not the model) declares the nonant variable names per stage.

    When every scenario lowers to the SAME constraint matrix and
    quadratic (randomness in the rhs/bounds/costs only — uc, sizes,
    sslp, hydro), the batch stores ``A`` once as (m, n) instead of
    (S, m, n): detected by comparison on the default path, declared by
    construction on the fast path below.

    ``vector_patch``: the structure-shared FAST path for large
    instances, where re-running the creator S times would rebuild an
    identical (m, n) matrix per scenario (minutes of host time and
    S × |A| transient memory at reference-UC scale, ref.
    examples/uc/2013-05-11: ~90 generators × 48 periods). The creator
    runs ONCE (scenario 0 → template); every scenario's vectors are the
    template's with ``vector_patch(scenario_name, **creator_kwargs) ->
    {(field, block): values}`` applied, addressing named constraint
    rows ("l"/"u" via Model.constr names) and variable columns
    ("lb"/"ub"/"c"). Scenario 0 is patched too — so a correct patch
    function reproduces the template's own vectors at scenario 0, which
    is asserted (cheap, and catches creator/patch drift)."""
    creator_kwargs = creator_kwargs or {}
    T = num_stages or tree.num_stages

    if vector_patch is not None:
        f0 = lower(scenario_creator(tree.scen_names[0], **creator_kwargs),
                   num_stages=T)
        fields = dict(c=f0.c, c0=np.float64(f0.c0), P_diag=f0.P_diag,
                      l=f0.l, u=f0.u, lb=f0.lb, ub=f0.ub,
                      c_stage=f0.c_stage, c0_stage=f0.c0_stage)
        stacks = {k: [] for k in fields}
        for s, name in enumerate(tree.scen_names):
            vecs = {k: np.array(v, dtype=np.float64)
                    for k, v in fields.items()}
            _apply_patch(vecs, f0, vector_patch(name, **creator_kwargs),
                         name)
            if s == 0:
                for k, v in vecs.items():
                    if not np.array_equal(v, np.asarray(fields[k],
                                                        dtype=np.float64)):
                        raise ValueError(
                            f"vector_patch({name}) changed template "
                            f"field {k!r} at scenario 0 — the patch "
                            "must reproduce the creator's own data "
                            "there (creator/patch drift)")
            for k, v in vecs.items():
                stacks[k].append(v)
        nonant_idx, nonant_stage, slot_slices = _nonant_indexing(f0, tree)
        return ScenarioBatch(
            tree=tree, template=f0,
            c=np.stack(stacks["c"]), c0=np.stack(stacks["c0"]),
            P_diag=np.stack(stacks["P_diag"]),
            A=f0.A,                         # ONE shared matrix
            l=np.stack(stacks["l"]), u=np.stack(stacks["u"]),
            lb=np.stack(stacks["lb"]), ub=np.stack(stacks["ub"]),
            c_stage=np.stack(stacks["c_stage"]),
            c0_stage=np.stack(stacks["c0_stage"]),
            prob=tree.probabilities.copy(),
            nonant_idx=nonant_idx, nonant_stage=nonant_stage,
            stage_slot_slices=slot_slices,
        )

    forms = [lower(scenario_creator(name, **creator_kwargs), num_stages=T)
             for name in tree.scen_names]
    f0 = forms[0]
    for f in forms[1:]:
        if f.n != f0.n or f.m != f0.m or f.var_names != f0.var_names:
            raise ValueError(
                f"scenario {f.name} has different structure from {f0.name}: "
                "all scenarios must share variables and constraint counts")

    nonant_idx, nonant_stage, slot_slices = _nonant_indexing(f0, tree)

    # shared-structure compaction: one (m, n) matrix when every scenario
    # carries the same A and P (the chunked/single-factor kernel path;
    # detection mirrors what core/spbase.py used to re-derive from the
    # stacked copies)
    shared = len(forms) > 1 and all(
        np.array_equal(f.A, f0.A) and np.array_equal(f.P_diag, f0.P_diag)
        for f in forms[1:])

    stack = lambda attr: np.stack([getattr(f, attr) for f in forms])
    return ScenarioBatch(
        tree=tree, template=f0,
        c=stack("c"), c0=stack("c0"), P_diag=stack("P_diag"),
        A=f0.A if shared else stack("A"), l=stack("l"), u=stack("u"),
        lb=stack("lb"), ub=stack("ub"),
        c_stage=stack("c_stage"), c0_stage=stack("c0_stage"),
        prob=tree.probabilities.copy(),
        nonant_idx=nonant_idx, nonant_stage=nonant_stage,
        stage_slot_slices=slot_slices,
    )


def subtree(t: ScenarioTree, lo: int, hi: int) -> ScenarioTree:
    """Scenarios [lo, hi) of a tree, keeping GLOBAL probabilities and the
    full per-stage node index space (membership columns stay global, so
    cross-shard node summands add)."""
    # COPIES, not views: np.asarray in ScenarioTree.__init__ keeps a
    # slice view alive, and a caller overwriting the subtree's
    # probabilities would silently corrupt the parent tree's
    return ScenarioTree(
        t.scen_names[lo:hi], t.node_path[lo:hi].copy(),
        t.nodes_per_stage, t.nonant_names_per_stage,
        probabilities=t.probabilities[lo:hi].copy())


def shard_batch(batch: ScenarioBatch, lo: int, hi: int) -> ScenarioBatch:
    """Slice scenarios [lo, hi) into a shard batch for a multi-process
    scenario-sharded engine (core/aph_shard.py) — the analog of a
    reference rank's local-scenario subset (ref. spbase.py:172
    _calculate_scenario_ranks contiguous shard map). Probabilities stay
    GLOBAL (the shard's prob sums to its mass, not 1; pass
    ``partial_probabilities`` to the engine), and membership matrices
    keep their full per-stage node columns so cross-shard reductions are
    plain sums of per-node summands."""
    from dataclasses import replace

    sub_tree = subtree(batch.tree, lo, hi)
    sl = slice(lo, hi)
    return replace(
        batch, tree=sub_tree,
        c=batch.c[sl], c0=batch.c0[sl], P_diag=batch.P_diag[sl],
        A=batch.A if batch.shared_A else batch.A[sl],
        l=batch.l[sl], u=batch.u[sl],
        lb=batch.lb[sl], ub=batch.ub[sl],
        c_stage=batch.c_stage[sl], c0_stage=batch.c0_stage[sl],
        prob=batch.prob[sl])
