"""ScenarioBatch: the stacked, device-ready scenario tensor block.

This is the TPU replacement for the reference's per-rank dict of Pyomo
models (ref. mpisppy/spbase.py:242 _create_scenarios): all S scenarios of a
problem are lowered to StandardForm and stacked along a leading scenario
axis. The scenario axis is the data-parallel mesh axis (ref. SURVEY §2.3
axis 1); everything the algorithms need per-iteration lives in these arrays.

Nonant bookkeeping mirrors _attach_nonant_indices (ref. spbase.py:272):
``nonant_idx`` maps the K nonanticipative slots (concatenated over non-leaf
stages) into columns of x, and ``nonant_stage`` records each slot's stage.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .standard_form import StandardForm, lower
from .tree import ScenarioTree


@dataclass
class ScenarioBatch:
    tree: ScenarioTree
    template: StandardForm          # scenario 0's form (shared structure)
    # stacked numeric data (numpy on host; engines move to device)
    c: np.ndarray                   # (S, n)
    c0: np.ndarray                  # (S,)
    P_diag: np.ndarray              # (S, n)
    A: np.ndarray                   # (S, m, n)
    l: np.ndarray                   # (S, m)
    u: np.ndarray                   # (S, m)
    lb: np.ndarray                  # (S, n)
    ub: np.ndarray                  # (S, n)
    c_stage: np.ndarray             # (S, T, n)
    c0_stage: np.ndarray            # (S, T)
    prob: np.ndarray                # (S,)
    # nonant structure (shared across scenarios)
    nonant_idx: np.ndarray          # (K,) int columns of x
    nonant_stage: np.ndarray        # (K,) int 1-based stage per slot
    stage_slot_slices: list = field(default_factory=list)  # per non-leaf stage: slice into K

    @property
    def S(self):
        return self.c.shape[0]

    @property
    def n(self):
        return self.c.shape[1]

    @property
    def m(self):
        return self.A.shape[1]

    @property
    def K(self):
        return self.nonant_idx.shape[0]

    @property
    def integer(self):
        return self.template.integer

    def nonants_of(self, x):
        """Extract the (.., K) nonant slots from a (.., n) x array."""
        return x[..., self.nonant_idx]


def build_batch(scenario_creator, tree: ScenarioTree, creator_kwargs=None,
                num_stages=None) -> ScenarioBatch:
    """Call `scenario_creator(name, **kwargs) -> Model` for every scenario in
    the tree and stack the lowered forms. The creator contract mirrors the
    reference's (ref. spbase.py:477-492) minus the Pyomo attachments: the
    tree (not the model) declares the nonant variable names per stage.
    """
    creator_kwargs = creator_kwargs or {}
    T = num_stages or tree.num_stages
    forms = [lower(scenario_creator(name, **creator_kwargs), num_stages=T)
             for name in tree.scen_names]
    f0 = forms[0]
    for f in forms[1:]:
        if f.n != f0.n or f.m != f0.m or f.var_names != f0.var_names:
            raise ValueError(
                f"scenario {f.name} has different structure from {f0.name}: "
                "all scenarios must share variables and constraint counts")

    # nonant slots, concatenated by stage
    nonant_idx, nonant_stage, slot_slices = [], [], []
    k = 0
    for t, names in enumerate(tree.nonant_names_per_stage, start=1):
        for vn in names:
            sl = f0.var_slices[vn]
            nonant_idx.extend(range(sl.start, sl.stop))
            nonant_stage.extend([t] * (sl.stop - sl.start))
        slot_slices.append(slice(k, len(nonant_idx)))
        k = len(nonant_idx)

    stack = lambda attr: np.stack([getattr(f, attr) for f in forms])
    return ScenarioBatch(
        tree=tree, template=f0,
        c=stack("c"), c0=stack("c0"), P_diag=stack("P_diag"),
        A=stack("A"), l=stack("l"), u=stack("u"),
        lb=stack("lb"), ub=stack("ub"),
        c_stage=stack("c_stage"), c0_stage=stack("c0_stage"),
        prob=tree.probabilities.copy(),
        nonant_idx=np.asarray(nonant_idx, dtype=np.int32),
        nonant_stage=np.asarray(nonant_stage, dtype=np.int32),
        stage_slot_slices=slot_slices,
    )


def subtree(t: ScenarioTree, lo: int, hi: int) -> ScenarioTree:
    """Scenarios [lo, hi) of a tree, keeping GLOBAL probabilities and the
    full per-stage node index space (membership columns stay global, so
    cross-shard node summands add)."""
    return ScenarioTree(
        t.scen_names[lo:hi], t.node_path[lo:hi],
        t.nodes_per_stage, t.nonant_names_per_stage,
        probabilities=t.probabilities[lo:hi])


def shard_batch(batch: ScenarioBatch, lo: int, hi: int) -> ScenarioBatch:
    """Slice scenarios [lo, hi) into a shard batch for a multi-process
    scenario-sharded engine (core/aph_shard.py) — the analog of a
    reference rank's local-scenario subset (ref. spbase.py:172
    _calculate_scenario_ranks contiguous shard map). Probabilities stay
    GLOBAL (the shard's prob sums to its mass, not 1; pass
    ``partial_probabilities`` to the engine), and membership matrices
    keep their full per-stage node columns so cross-shard reductions are
    plain sums of per-node summands."""
    from dataclasses import replace

    sub_tree = subtree(batch.tree, lo, hi)
    sl = slice(lo, hi)
    return replace(
        batch, tree=sub_tree,
        c=batch.c[sl], c0=batch.c0[sl], P_diag=batch.P_diag[sl],
        A=batch.A[sl], l=batch.l[sl], u=batch.u[sl],
        lb=batch.lb[sl], ub=batch.ub[sl],
        c_stage=batch.c_stage[sl], c0_stage=batch.c0_stage[sl],
        prob=batch.prob[sl])
