"""Scenario tree metadata.

Replaces the reference's ScenarioNode / _ScenTree machinery
(ref. mpisppy/scenario_tree.py:41-103, mpisppy/utils/sputils.py:543-661).
The reference attaches per-scenario node lists to Pyomo models and later
derives rank maps and per-node MPI communicators (ref. mpisppy/spbase.py:311).
Here the tree is a pure index structure consumed by the batched engines:

- every non-leaf node has an id; scenarios record their node path by stage,
- per-stage *membership matrices* B_t ∈ {0,1}^{S×N_t} ("scenario s passes
  through node j of stage t") turn nonanticipativity reductions into dense
  matmuls: xbar_t = B_t (B_tᵀ(p⊙x_t)) / (B_tᵀp).  On a sharded scenario axis
  the inner product B_tᵀ(p⊙x_t) is a local matmul followed by a psum — the
  TPU-native analog of the reference's per-node comm.Allreduce
  (ref. mpisppy/phbase.py:196-201, spbase.py:311-350).
- nonant variable names are declared per stage, mirroring nonant_list.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class TreeNode:
    name: str
    stage: int              # 1-based
    cond_prob: float
    parent: "TreeNode | None"
    idx_in_stage: int = -1  # assigned by ScenarioTree


class ScenarioTree:
    """Non-leaf tree structure for S scenarios over T decision stages.

    ``node_path[s][t]`` = index (within stage t+1's node list) of the node
    scenario s passes through. Stage 1 always has the single ROOT node.
    """

    def __init__(self, scen_names, node_paths, nodes_per_stage, nonant_names_per_stage,
                 probabilities=None):
        self.scen_names = list(scen_names)
        self.S = len(self.scen_names)
        self.num_stages = len(nodes_per_stage) + 1  # leaves are implicit
        self.nodes_per_stage = list(nodes_per_stage)  # N_t for t = 1..T-1
        self.node_path = np.asarray(node_paths, dtype=np.int32)  # (S, T-1)
        assert self.node_path.shape == (self.S, self.num_stages - 1)
        # nonant variable names owned by each non-leaf stage
        self.nonant_names_per_stage = [list(v) for v in nonant_names_per_stage]
        if probabilities is None:
            probabilities = np.full(self.S, 1.0 / self.S)
        self.probabilities = np.asarray(probabilities, dtype=np.float64)

    def membership(self, stage: int) -> np.ndarray:
        """B_t ∈ {0,1}^{S×N_t} for 1-based non-leaf stage `stage`."""
        N = self.nodes_per_stage[stage - 1]
        B = np.zeros((self.S, N))
        B[np.arange(self.S), self.node_path[:, stage - 1]] = 1.0
        return B

    def validate(self):
        assert abs(self.probabilities.sum() - 1.0) < 1e-9, "probabilities must sum to 1"
        for t in range(1, self.num_stages):
            B = self.membership(t)
            assert (B.sum(axis=1) == 1).all()
        # node-contiguity (analogous to the reference's rank-map guarantee,
        # ref. sputils.py:635-659): scenarios of one node occupy a contiguous
        # index range so a sharded scenario axis keeps nodes on contiguous
        # mesh slices.
        for t in range(1, self.num_stages):
            path = self.node_path[:, t - 1]
            changes = np.flatnonzero(np.diff(path) != 0)
            seen = path[np.concatenate([[0], changes + 1])]
            assert len(set(seen.tolist())) == len(seen), \
                f"stage {t} scenario order is not node-contiguous"


def two_stage_tree(scen_names, nonant_names, probabilities=None) -> ScenarioTree:
    """All scenarios share the single ROOT node (the common case,
    ref. sputils.py:665 attach_root_node)."""
    S = len(scen_names)
    return ScenarioTree(
        scen_names=scen_names,
        node_paths=np.zeros((S, 1), dtype=np.int32),
        nodes_per_stage=[1],
        nonant_names_per_stage=[list(nonant_names)],
        probabilities=probabilities,
    )


def balanced_tree(branching_factors, nonant_names_per_stage, scen_name_fmt="Scen{}",
                  probabilities=None) -> ScenarioTree:
    """Balanced multistage tree from branching factors (the reference's
    --BFs convention, ref. utils/baseparsers.py:134-168; hydro uses [3,3]).

    For BFs = [b1, ..., b_{T-1}] there are prod(BFs) scenarios; the stage-t
    node of scenario s is s // prod(BFs[t-1:]).
    """
    BFs = list(branching_factors)
    S = int(np.prod(BFs))
    T1 = len(BFs)  # number of non-root branching stages; total stages = T1+1
    nodes_per_stage = [1]
    for b in BFs[:-1]:
        nodes_per_stage.append(nodes_per_stage[-1] * b)
    node_paths = np.zeros((S, T1), dtype=np.int32)
    for t in range(T1):
        block = int(np.prod(BFs[t:]))
        node_paths[:, t] = np.arange(S) // block
    return ScenarioTree(
        scen_names=[scen_name_fmt.format(i + 1) for i in range(S)],
        node_paths=node_paths,
        nodes_per_stage=nodes_per_stage,
        nonant_names_per_stage=nonant_names_per_stage,
        probabilities=probabilities,
    )
