"""Command-line driver: ``python -m mpisppy_tpu <model> [options]``.

The baseparsers + driver-script analog (ref. mpisppy/utils/baseparsers.py
:11-451 and examples/*_cylinders.py): one entry point that builds the
validated RunConfig, wires the hub and the requested spokes through
utils.vanilla, and spins the wheel (or solves the EF directly). Flag
names mirror the reference's argparse surface where one exists.

Examples:
  python -m mpisppy_tpu farmer --num-scens 3 --default-rho 1 \\
      --max-iterations 50 --with-lagrangian --with-xhatshuffle
  python -m mpisppy_tpu uc --num-scens 10 --default-rho 100 \\
      --with-lagrangian --with-xhatshuffle --rel-gap 0.001
  python -m mpisppy_tpu sizes --num-scens 3 --EF --EF-integer

The ``analyze`` subcommand consumes a run's ``--telemetry-dir``
artifacts instead of launching one (obs/analyze.py; no jax needed):
  python -m mpisppy_tpu analyze runs/t1
  python -m mpisppy_tpu analyze --compare runs/base runs/candidate

The ``serve`` subcommand starts the persistent serving layer
(mpisppy_tpu/serve/, doc/serving.md) instead of a one-shot wheel:
  python -m mpisppy_tpu serve --port 8765 --state-dir runs/serve
"""

from __future__ import annotations

import argparse
import json
import sys

from .utils.config import (AlgoConfig, RunConfig, SpokeConfig, KNOWN_MODELS,
                           KNOWN_SPOKES, KNOWN_HUBS, KERNEL_MODES,
                           INCUMBENT_MODES, STREAM_SOURCES)


def make_parser() -> argparse.ArgumentParser:
    """ref. baseparsers.py:134-168 make_parser + per-spoke *_args packs."""
    p = argparse.ArgumentParser(prog="python -m mpisppy_tpu")
    p.add_argument("model", choices=KNOWN_MODELS)
    p.add_argument("--num-scens", type=int, default=3)
    p.add_argument("--model-kwargs", type=str, default="{}",
                   help="JSON dict forwarded to the scenario creator")
    p.add_argument("--num-bundles", type=int, default=0,
                   help="bundles_per_rank analog (0 = no bundling)")
    p.add_argument("--hub", choices=KNOWN_HUBS, default="ph")
    # algo options (ref. baseparsers.py:11-132)
    p.add_argument("--default-rho", type=float, default=1.0)
    p.add_argument("--max-iterations", type=int, default=100)
    p.add_argument("--convthresh", type=float, default=1e-4)
    p.add_argument("--subproblem-max-iter", type=int, default=5000)
    p.add_argument("--subproblem-eps", type=float, default=1e-8)
    p.add_argument("--subproblem-polish-chunk", type=int, default=0)
    p.add_argument("--subproblem-ir-sweeps", type=int, default=1,
                   help="df32 x-update iterative-refinement sweeps "
                        "(doc/roofline.md §2; fused kernel mode "
                        "supports 1-4)")
    p.add_argument("--subproblem-kernel-mode", choices=KERNEL_MODES,
                   default="auto",
                   help="subproblem kernel backend (doc/kernels.md): "
                        "'segmented' = host-segmented drivers "
                        "bit-for-bit, 'fused' = one device program per "
                        "solve, 'auto' = fused where eligible")
    # progressive problem shrinking (ops/shrink, doc/extensions.md
    # §shrinking): device fixer, active-set compaction, per-slot rho
    p.add_argument("--shrink-fix", action="store_true",
                   help="device-side WW fixing: jitted per-var "
                        "convergence counters pin converged nonants "
                        "(the host Fixer's test-and-fix, zero big-array "
                        "D2H per iteration)")
    p.add_argument("--shrink-fix-iters", type=int, default=3,
                   help="consecutive converged iterations before a "
                        "nonant slot fixes")
    p.add_argument("--shrink-fix-tol", type=float, default=1e-4,
                   help="variance-test tolerance of the device fixer")
    p.add_argument("--shrink-compact", action="store_true",
                   help="active-set compaction: gather unfixed "
                        "columns (and the rows they touch) into a "
                        "smaller system at bucketed fixed-fraction "
                        "thresholds (one recompile per bucket "
                        "transition); implies --shrink-fix semantics")
    p.add_argument("--shrink-buckets", type=str, default="0.25,0.5,0.75",
                   help="comma-separated fixed-fraction thresholds for "
                        "compaction bucket transitions")
    p.add_argument("--shrink-rho", action="store_true",
                   help="per-slot device-side adaptive rho "
                        "(residual-balancing vector rho on the prox "
                        "diagonal)")
    p.add_argument("--shrink-rho-interval", type=int, default=1,
                   help="iterations between per-slot rho update passes")
    p.add_argument("--no-shrink-transplant", action="store_true",
                   help="disable the warm-state transplant across "
                        "compaction bucket transitions (states rebuild "
                        "cold, the pre-transplant spelling; transplant "
                        "is on by default when --shrink-compact is)")
    # scenario streaming (mpisppy_tpu/stream, doc/streaming.md)
    p.add_argument("--scenario-source", choices=STREAM_SOURCES,
                   default="resident",
                   help="where the chunked hot loop's per-scenario "
                        "vector blocks come from (doc/streaming.md): "
                        "'resident' = full-width device arrays, "
                        "'streamed' = host store + double-buffered H2D "
                        "chunk pipeline, 'synthesized' = device-side "
                        "seeded generation (models exporting "
                        "scenario_synth_spec; zero steady-state "
                        "transfer). Non-resident sources need "
                        "--subproblem-chunk and run hub-only")
    p.add_argument("--stream-int8", action="store_true",
                   help="int8 delta-packed host storage for the "
                        "streamed source (explicit opt-in behind a "
                        "host-side quantization gate, like the bf16 "
                        "packed blocks — doc/streaming.md)")
    p.add_argument("--stream-int8-tol", type=float, default=1e-3,
                   help="int8 gate: max per-entry reconstruction error "
                        "relative to 1+|value| before a field falls "
                        "back to full-precision storage")
    p.add_argument("--stream-depth", type=int, default=2,
                   help="prefetch pipeline depth (staged chunks; 2 = "
                        "double buffering)")
    p.add_argument("--subproblem-chunk", type=int, default=None,
                   help="scenario microbatch rows per device solve "
                        "call (the chunked hot loop; required by "
                        "non-resident --scenario-source). Lands in "
                        "hub_options like the programmatic spelling")
    p.add_argument("--forensics-interval", type=int, default=None,
                   help="sample the per-slot/per-scenario forensic "
                        "reduction every N iterations when telemetry "
                        "is on (default 5; 0 disables — see "
                        "doc/forensics.md). Lands in hub_options like "
                        "the programmatic spelling")
    # APH φ-dispatch (--hub aph; core/aph.py + ops/dispatch.py,
    # doc/aph.md)
    p.add_argument("--dispatch-frac", type=float, default=1.0,
                   help="APH: fraction of scenarios solved per "
                        "iteration, most-negative-φ first with "
                        "least-recently-dispatched fill (doc/aph.md); "
                        "1.0 = full dispatch. Partial dispatch needs "
                        "--hub aph")
    p.add_argument("--aph-nu", type=float, default=1.0,
                   help="APH projective step scale ν (θ = ν·φ/τ; ref. "
                        "APHnu)")
    p.add_argument("--aph-gamma", type=float, default=1.0,
                   help="APH z-update damping γ (ref. APHgamma)")
    p.add_argument("--linearize-proximal-terms", action="store_true")
    p.add_argument("--verbose", action="store_true")
    # termination (ref. baseparsers.py:172 two_sided_args)
    p.add_argument("--rel-gap", type=float, default=None)
    p.add_argument("--abs-gap", type=float, default=None)
    # spokes (ref. baseparsers.py:224-451)
    for kind in KNOWN_SPOKES:
        p.add_argument(f"--with-{kind.replace('_', '-')}",
                       action="store_true", dest=f"with_{kind}")
    p.add_argument("--incumbent-mode", choices=INCUMBENT_MODES,
                   default=None,
                   help="incumbent source policy for the inner-bound "
                        "spokes (doc/incumbents.md): 'device' = batched "
                        "on-device candidate pools/dives only (zero "
                        "host solver subprocesses), 'oracle' = "
                        "host-oracle sources only, 'auto' = device "
                        "with the oracle as opt-in fallback/polish. "
                        "Default: each spoke's own default (--with-dive "
                        "defaults to device)")
    # EF path (ref. examples/farmer/farmer_ef.py)
    p.add_argument("--EF", action="store_true", dest="solve_ef")
    p.add_argument("--EF-integer", action="store_true", dest="ef_integer")
    p.add_argument("--trace-prefix", type=str, default=None)
    p.add_argument("--telemetry-dir", type=str, default=None,
                   help="enable unified telemetry (mpisppy_tpu.obs): "
                        "write events.jsonl, trace.json (Chrome "
                        "trace-event; load in Perfetto) and "
                        "metrics.json under this directory — see "
                        "doc/observability.md")
    p.add_argument("--status-port", type=int, default=None,
                   help="serve live run state from the hub process "
                        "while it iterates: /metrics (Prometheus text "
                        "exposition of the telemetry registry) and "
                        "/status (JSON: bounds, gap, per-spoke "
                        "supervisor state + bound flow). 0 binds an "
                        "ephemeral port. See doc/observability.md "
                        "(live plane); --telemetry-dir also gets a "
                        "tailable live.json without the port")
    p.add_argument("--status-host", type=str, default="127.0.0.1",
                   help="bind host for --status-port (default "
                        "loopback; the endpoints serve full run state "
                        "unauthenticated — pass 0.0.0.0 only to opt "
                        "into remote scraping)")
    p.add_argument("--checkpoint-dir", type=str, default=None,
                   help="durable run-state checkpoints: the hub "
                        "captures manifest'd bundles (W, x̄, ρ, "
                        "bounds, per-spoke warm state) here — "
                        "periodic, plus forced on watchdog fire and "
                        "SIGTERM (the preemption notice). See "
                        "doc/fault_tolerance.md")
    p.add_argument("--checkpoint-interval", type=float, default=30.0,
                   help="seconds between periodic checkpoint bundles "
                        "(default 30)")
    p.add_argument("--checkpoint-keep", type=int, default=3,
                   help="retain the newest N bundles (default 3); "
                        "LATEST always points at the newest")
    p.add_argument("--resume-from", type=str, default=None,
                   help="relaunch the wheel from a checkpoint bundle "
                        "(or a --checkpoint-dir, resolved through its "
                        "LATEST pointer): hub state + best-bound "
                        "ledger + spoke warm state restored; a "
                        "corrupt or config-mismatched bundle falls "
                        "back to cold start with a reasoned event")
    p.add_argument("--wheel-deadline", type=float, default=None,
                   help="watchdog: cleanly terminate the wheel after "
                        "this many seconds (kill signal to spokes, "
                        "telemetry flushed, partial bounds reported — "
                        "see doc/fault_tolerance.md)")
    p.add_argument("--f32", action="store_true",
                   help="run in float32 (faster on TPU; bounds and "
                        "objectives carry ~1e-3 relative noise). Default "
                        "is float64 for solver-grade accuracy.")
    # scenario-axis sharding + multi-host (doc/sharding.md)
    p.add_argument("--mesh-devices", type=int, default=None,
                   help="shard the hub engine's scenario axis over this "
                        "many devices (0 = all visible devices); the PH "
                        "step runs SPMD with psum reductions")
    p.add_argument("--coordinator-address", type=str, default=None,
                   help="host:port of process 0 for multi-process JAX "
                        "(jax.distributed.initialize) — the wheel then "
                        "spans hosts over DCN")
    p.add_argument("--num-processes", type=int, default=None,
                   help="process count for --coordinator-address "
                        "(omit on TPU pods: self-discovered)")
    p.add_argument("--process-id", type=int, default=None,
                   help="this process's id for --coordinator-address")
    return p


def config_from_args(args) -> RunConfig:
    algo = AlgoConfig(
        default_rho=args.default_rho,
        max_iterations=args.max_iterations,
        convthresh=args.convthresh,
        subproblem_max_iter=args.subproblem_max_iter,
        subproblem_eps=args.subproblem_eps,
        subproblem_polish_chunk=args.subproblem_polish_chunk,
        subproblem_ir_sweeps=args.subproblem_ir_sweeps,
        subproblem_kernel_mode=args.subproblem_kernel_mode,
        shrink_fix=args.shrink_fix or args.shrink_compact,
        shrink_fix_iters=args.shrink_fix_iters,
        shrink_fix_tol=args.shrink_fix_tol,
        shrink_compact=args.shrink_compact,
        shrink_buckets=args.shrink_buckets,
        shrink_rho=args.shrink_rho,
        shrink_rho_interval=args.shrink_rho_interval,
        shrink_transplant=not args.no_shrink_transplant,
        scenario_source=args.scenario_source,
        stream_int8=args.stream_int8,
        stream_int8_tol=args.stream_int8_tol,
        stream_depth=args.stream_depth,
        dispatch_frac=args.dispatch_frac,
        aph_nu=args.aph_nu,
        aph_gamma=args.aph_gamma,
        linearize_proximal_terms=args.linearize_proximal_terms,
        verbose=args.verbose,
    )
    hub_options = {}
    if args.subproblem_chunk is not None:
        hub_options["subproblem_chunk"] = args.subproblem_chunk
    if args.forensics_interval is not None:
        hub_options["forensics_interval"] = args.forensics_interval
    spokes = [SpokeConfig(kind=k) for k in KNOWN_SPOKES
              if getattr(args, f"with_{k}")]
    # build the dict whenever ANY coordinator flag is present, so
    # --num-processes without --coordinator-address hits validate()'s
    # "coordinator needs an 'address'" error instead of silently
    # running single-process
    coordinator = None
    if (args.coordinator_address or args.num_processes is not None
            or args.process_id is not None):
        coordinator = {"address": args.coordinator_address}
        if args.num_processes is not None:
            coordinator["num_processes"] = args.num_processes
        if args.process_id is not None:
            coordinator["process_id"] = args.process_id
    return RunConfig(
        model=args.model, num_scens=args.num_scens,
        model_kwargs=json.loads(args.model_kwargs),
        num_bundles=args.num_bundles, hub=args.hub, algo=algo,
        hub_options=hub_options,
        spokes=spokes, rel_gap=args.rel_gap, abs_gap=args.abs_gap,
        incumbent_mode=args.incumbent_mode,
        solve_ef=args.solve_ef, ef_integer=args.ef_integer,
        trace_prefix=args.trace_prefix, telemetry_dir=args.telemetry_dir,
        status_port=args.status_port, status_host=args.status_host,
        wheel_deadline=args.wheel_deadline,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_interval=args.checkpoint_interval,
        checkpoint_keep=args.checkpoint_keep,
        resume_from=args.resume_from,
        mesh_devices=args.mesh_devices, coordinator=coordinator,
    ).validate()


def run(cfg: RunConfig):
    from . import global_toc, obs
    from .utils.runtime import maybe_init_distributed

    # multi-process JAX must come up BEFORE the backend initializes
    # (engine construction below touches devices)
    maybe_init_distributed(cfg.coordinator)
    # telemetry session: --telemetry-dir wins; otherwise the
    # MPISPPY_TPU_TELEMETRY_DIR env var can enable it without flags
    if cfg.telemetry_dir:
        obs.configure(out_dir=cfg.telemetry_dir, config=cfg.to_dict())
    else:
        obs.maybe_configure_from_env()
    try:
        if cfg.solve_ef:
            from .core.ef import ExtensiveForm
            from .utils.vanilla import build_batch_for

            ef = ExtensiveForm(build_batch_for(cfg))
            obj, _ = ef.solve_extensive_form(integer=cfg.ef_integer)
            global_toc(f"EF objective: {obj:.4f}")
            result = {"ef_objective": obj}
        else:
            from .utils.vanilla import wheel_dicts
            from .utils.sputils import spin_the_wheel

            hub_d, spoke_ds = wheel_dicts(cfg)
            wheel = spin_the_wheel(hub_d, spoke_ds)
            # never-established bounds report as null, not
            # JSON-invalid Infinity
            result = {
                "outer_bound": obs.finite_or_none(wheel.hub.BestOuterBound),
                "inner_bound": obs.finite_or_none(wheel.best_inner_bound)}
        obs.event("run.result", result)
        return result
    finally:
        if cfg.telemetry_dir:
            # flush + close so the artifacts are complete the moment
            # run() returns (tests and scripts read them right after)
            obs.shutdown()
        else:
            obs.flush()


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "analyze":
        # diagnostics-only path: reads telemetry artifacts, never
        # touches jax or the device runtime
        from .obs.analyze import main as analyze_main
        return analyze_main(argv[1:])
    if argv and argv[0] == "serve":
        # the persistent serving layer (mpisppy_tpu/serve): compile
        # once, batch many instances, serve concurrent wheels — one
        # long-lived process instead of one wheel per invocation
        from .serve.manager import serve_main
        return serve_main(argv[1:])
    args = make_parser().parse_args(argv)
    from .utils.runtime import setup_jax_runtime

    # x64 + persistent compile cache (shared with process workers so
    # repeat invocations and spoke children skip the first-compile)
    setup_jax_runtime(args.f32)
    result = run(config_from_args(args))
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
