"""int8 packed-block storage for streamed scenario vectors.

The streamed scenario source (stream/source.py) holds the per-scenario
vector blocks (l/u/lb/ub/c) on HOST and ships one chunk at a time;
int8 packing quarters those bytes — host residency AND the H2D wire —
the "halve resident bytes again after bf16" rung of ROADMAP item 3.

Representation: per (scenario row, field) block, the stored value is
the int8-quantized DELTA from the field's template row with a
per-block scale/zero-point:

    value[s, j] = template[j] + scale[s] * q[s, j] + zero[s]

Scenario randomness perturbs a few entries of a shared template
(doc/scenario_models.md), so deltas are small and mostly zero —
delta quantization keeps the absolute error at (delta range)/254
instead of (value range)/254, and an unperturbed row stores scale = 0
exactly (bit-exact roundtrip).

Quantization CHANGES the problem data, so the same double guard as the
bf16 packed blocks applies (doc/kernels.md §4):

- the gate (``quantize_field``) measures the worst per-entry
  reconstruction error ON HOST, reproducing the device's f32
  dequantization arithmetic exactly — a too-coarse block falls back to
  full-precision host storage and books ``stream.int8_fallbacks``;
- int8 packing is EXPLICIT opt-in (``stream_int8`` — never engaged by
  ``scenario_source='streamed'`` alone): like bf16, a residual-level
  data perturbation can relocate a degenerate optimum no residual gate
  can see.

Non-finite entries (±inf constraint/box bounds) must come from the
TEMPLATE: a scenario whose non-finite pattern differs from the
template's is rejected by the gate (int8 deltas cannot encode ±inf).

Dequantization (``dequantize``) runs on device inside the chunk
staging jit: the scale/zero arithmetic is pinned to f32 (the storage
precision — widening q to f64 first would manufacture digits the
storage never had) and only the final template add runs in the engine
dtype.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class Int8Field(NamedTuple):
    """Host-side packed storage of one (S, w) field: template row +
    per-scenario-block int8 deltas over the VARYING columns. Columns
    no scenario ever perturbs are excluded from the block range (the
    ``varying`` mask) and reconstruct as the template exactly —
    without the mask, one zero-delta template column in a block whose
    perturbed columns span hundreds would eat the whole error budget
    at its own (small) magnitude."""
    tmpl: np.ndarray       # (w,) f64 template row (non-finites live here)
    varying: np.ndarray    # (w,) bool — columns with any nonzero delta
    q: np.ndarray          # (S, w) int8 quantized deltas
    scale: np.ndarray      # (S, 1) f32 per-block scale
    zero: np.ndarray       # (S, 1) f32 per-block zero-point

    @property
    def nbytes(self) -> int:
        return (self.q.nbytes + self.scale.nbytes + self.zero.nbytes
                + self.varying.nbytes)


def _reconstruct_f32(fld: Int8Field, rows) -> np.ndarray:
    """Host twin of the device dequantization — f32 scale/zero
    arithmetic over the varying columns, template add in f64 — so the
    gate measures exactly the values the solver will see."""
    delta = (fld.scale[rows] * fld.q[rows].astype(np.float32)
             + fld.zero[rows]).astype(np.float64)
    delta = np.where(fld.varying[None, :], delta, 0.0)
    with np.errstate(invalid="ignore"):   # ±inf template entries
        return fld.tmpl[None, :] + delta


def quantize_field(a, tmpl, tol: float):
    """Gate + pack one (S, w) host field against its template row.
    Returns an :class:`Int8Field`, or ``None`` when the block set fails
    the gate (worst per-entry reconstruction error above ``tol``
    relative to 1 + |value|, or a non-finite pattern differing from the
    template's) — the caller keeps full-precision storage and books the
    fallback."""
    a = np.asarray(a, np.float64)
    tmpl = np.asarray(tmpl, np.float64)
    finite_t = np.isfinite(tmpl)
    if (np.isfinite(a) != finite_t[None, :]).any():
        return None
    with np.errstate(invalid="ignore"):   # inf - inf at non-finite
        delta = np.where(finite_t[None, :], a - tmpl[None, :], 0.0)
    varying = (delta != 0.0).any(axis=0)
    if varying.any():
        dv = delta[:, varying]
        dmin = dv.min(axis=1, keepdims=True)
        dmax = dv.max(axis=1, keepdims=True)
    else:
        # fully template-shared field (callers' const detection should
        # have caught it) — an all-zero pack is exact anyway
        dmin = dmax = np.zeros((a.shape[0], 1))
    zero = ((dmax + dmin) / 2.0).astype(np.float32)
    scale = ((dmax - dmin) / 254.0).astype(np.float32)
    safe = np.where(scale > 0, scale, 1.0).astype(np.float64)
    q = np.clip(np.rint((delta - zero.astype(np.float64)) / safe),
                -127, 127).astype(np.int8)
    q = np.where(varying[None, :], q, 0).astype(np.int8)
    fld = Int8Field(tmpl=tmpl, varying=varying, q=q, scale=scale,
                    zero=zero)
    recon = _reconstruct_f32(fld, slice(None))
    with np.errstate(invalid="ignore"):   # inf - inf at non-finite
        err = np.abs(np.where(finite_t[None, :], recon - a, 0.0)) \
            / (1.0 + np.abs(np.where(finite_t[None, :], a, 0.0)))
    if float(err.max(initial=0.0)) > tol:
        return None
    return fld


def dequantize(tmpl_dev, varying_dev, q_dev, scale_dev, zero_dev,
               dtype):
    """Device dequantization of one shipped chunk: f32 scale/zero
    arithmetic (the storage precision) over the varying columns,
    template add in the engine dtype. Traced inside the chunk staging
    jit — no standalone dispatch."""
    delta = scale_dev * q_dev.astype(jnp.float32) + zero_dev
    delta = jnp.where(varying_dev[None, :], delta, 0.0)
    return tmpl_dev.astype(dtype)[None, :] + delta.astype(dtype)


def dequantize_cols(tmpl_dev, vidx_dev, qv_dev, scale_dev, zero_dev,
                    dtype):
    """Varying-columns-only dequantization: the wire carries q over
    the VARYING columns alone (``qv = q[:, varying]``) and the deltas
    scatter into a broadcast template row on device. Same arithmetic
    as :func:`dequantize` on the varying columns; non-varying columns
    are the template verbatim (instead of template + 0.0 — identical
    values). This is what keeps ``stream.bytes_shipped`` honest when
    few columns vary: the booked bytes ARE the staged buffer's."""
    delta = scale_dev * qv_dev.astype(jnp.float32) + zero_dev
    rows = qv_dev.shape[0]
    base = jnp.broadcast_to(tmpl_dev.astype(dtype)[None, :],
                            (rows, tmpl_dev.shape[0]))
    return base.at[:, vidx_dev].add(delta.astype(dtype))
