"""Scenario streaming engine — the S=100k–1M scale wall (ROADMAP item 3).

The chunked hot loop's per-scenario vector blocks stop being
HBM-resident: a :class:`~mpisppy_tpu.stream.source.ScenarioSource`
(``scenario_source`` engine option: ``resident`` | ``streamed`` |
``synthesized``) stages them per chunk instead —

- **streamed**: host store (optionally int8 delta-packed,
  :mod:`.quant`) + a double-buffered prefetch thread
  (:mod:`.pipeline`) overlapping chunk k+1's H2D under chunk k's
  solve;
- **synthesized**: a seeded jitted generator (:mod:`.synth`)
  manufactures rhs/bound perturbations in-kernel from
  ``(seed, scenario_id)`` — nothing ships at all.

Anatomy, source selection, the quantization gate, and the
observability catalog live in doc/streaming.md.
"""

from .pipeline import ChunkPipeline                      # noqa: F401
from .quant import (Int8Field, dequantize,                # noqa: F401
                    dequantize_cols, quantize_field)
from .source import (ScenarioSource, StreamedSource,      # noqa: F401
                     SynthesizedSource, make_source)
from .synth import (SOURCE_FIELDS, SYNTH_FIELDS,          # noqa: F401
                    SynthField, SynthSpec, materialize, synth_batch,
                    synth_values)
