"""ScenarioSource: where a chunked engine's per-scenario vectors come from.

The chunked hot loop (core/ph._solve_loop_chunked) consumes five
per-scenario vector fields — ``l``/``u`` (S, m) and ``lb``/``ub``/``c``
(S, n). The resident path ships all of them into HBM at engine build
(core/spbase) and slices per chunk; that full-width residency is the
S=100k–1M scale wall of ROADMAP item 3. A :class:`ScenarioSource`
replaces the resident arrays with per-chunk staging:

- :class:`StreamedSource` — the fields live on HOST (optionally int8
  delta-packed, stream/quant.py); a :class:`~.pipeline.ChunkPipeline`
  prefetch thread ships chunk k+1's blocks under chunk k's solve.
  Device staging residency is bounded by the pipeline depth, host
  residency by the (possibly packed) store.
- :class:`SynthesizedSource` — nothing is stored OR shipped: a seeded
  jitted generator (stream/synth.py) manufactures each chunk's
  rhs/bound perturbations in-kernel from ``(seed, scenario_id)``;
  chunk staging is pure device compute.

Both expose the same surface to the engine:

- ``setup_arrays(dtype)`` — EXACT 2-row surrogates of the full-width
  setup reductions (see below), so qp_setup builds factors
  bit-identical to the resident path's;
- ``bind(layout)`` / ``begin_pass()`` / ``chunk(ci)`` — the in-order
  chunk staging protocol (two passes per PH iteration: solve +
  objectives);
- ``fetch(ci)`` / ``rows(ids)`` — direct out-of-band staging for the
  exceptional paths (cold-state build, chunk retries, the scenario
  hospital);
- ``status()`` — a plain host dict for bench's signal-safe gap-row
  stamp; ``close()`` — idempotent pipeline shutdown (wired into
  Hub.handle_preemption and engine finalize).

The setup surrogate: for a SHARED-structure batch, qp_setup consumes
the full-width vectors only through three exact reductions —
``all_s(l==u)`` / ``all_s(lb==ub)`` row/column equality patterns and
``max_{s,j} |D_j c_{s,j}|`` (the cost scale, ops/qp_solver
._setup_vectors). A 2-row surrogate encoding those reductions
(row pattern: (0, 0) where eq, (0, 1) where not; c rows: the
per-column |c| max) therefore yields bit-identical factors — which is
what makes streamed/synthesized trajectories EQUAL to resident ones
rather than merely close (tests/test_stream.py pins it).
"""

from __future__ import annotations

import numpy as np

from .. import obs
from .pipeline import ChunkPipeline
from .quant import dequantize_cols, quantize_field
from .synth import SOURCE_FIELDS, synth_values

# is_eq tolerance must match ops/qp_solver._setup_vectors' predicate
_EQ_TOL = 1e-9


def _np_dtype(dtype):
    """The engine dtype as a numpy dtype (host casts must round
    exactly the way the device ship would)."""
    return np.dtype(dtype)


def _eq_pattern(l, u, dtype=None):
    """The qp_setup equality predicate. ``dtype``: evaluate on values
    CAST to the engine dtype first — the resident path computes the
    pattern on the shipped (possibly f32) arrays, and a borderline
    l/u pair that collapses to equality under f32 rounding must
    classify identically here or the surrogate factors silently drift
    from the resident ones."""
    if dtype is not None:
        t = _np_dtype(dtype)
        l = np.asarray(l, t)
        u = np.asarray(u, t)
    d = u - l
    return np.isfinite(d) & (np.abs(d) <= _EQ_TOL * (1.0 + np.abs(u)))


def _surrogate_pair(eq: np.ndarray):
    """(lo, hi) 2-row surrogates whose all-scenarios equality pattern
    is exactly ``eq``: surrogate scenario 0 is (0, 0) everywhere (an
    equality under the solver's relative tolerance), scenario 1 breaks
    the non-eq columns with (0, 1) — so the per-column AND over the
    two rows reproduces the true all-S pattern."""
    lo = np.zeros((2,) + eq.shape)
    hi = np.stack([np.zeros(eq.shape), np.where(eq, 0.0, 1.0)])
    return lo, hi


class ScenarioSource:
    """Shared plumbing: chunk layout binding, device staging helpers,
    status accounting. Subclasses implement ``_load(np_ids)`` (host
    block for arbitrary scenario rows; streamed) or override
    ``chunk``/``fetch``/``rows`` wholesale (synthesized)."""

    kind = "abstract"
    fields = SOURCE_FIELDS

    def __init__(self, dtype, depth: int = 2, sharding=None):
        self.dtype = dtype
        self.depth = int(depth)
        self.sharding = sharding     # ndim -> jax sharding, or None
        self._layout_key = None
        self._np_ids = None          # list[np.ndarray] per chunk
        self._pipeline = None
        # out-of-band booking flag: a compaction transition's one full
        # restage books its bytes on its own counter, not the
        # per-iteration bytes_shipped the flatness verdict reads
        self._oob_book = False
        # whether the bound layout stages COMPACTED blocks (streamed
        # sources under an active shrink plan; see install_compacted)
        self._bind_compacted = False
        self._status = {"source": self.kind, "chunks_shipped": 0,
                        "bytes_shipped": 0, "synth_chunks": 0,
                        "int8_fallbacks": 0, "direct_fetches": 0}

    # ---- layout ----
    @property
    def bound_key(self):
        """The currently bound chunk-layout key (None when unbound) —
        callers gate their id staging on it so bind() cost is paid
        once per layout change, never per iteration."""
        return self._layout_key

    def bind(self, key, np_ids, compacted=False):
        """(Re)bind the chunk layout: ``np_ids[ci]`` are chunk ci's
        global scenario rows in chunk-row order (tail chunks repeat
        their last row; sharded chunks are device-major strided —
        exactly core/ph's slice maps). A changed layout tears down the
        pipeline; an unchanged one is a no-op. ``compacted``: this
        layout stages the compacted store (streamed sources after
        ``install_compacted``) — the flag is part of the layout, so a
        fixed-mode full-width bind and a shrunk bind never share a
        key."""
        if key == self._layout_key:
            return
        self.close()
        self._layout_key = key
        self._bind_compacted = bool(compacted)
        self._np_ids = [np.asarray(ids) for ids in np_ids]
        self._pipeline = self._make_pipeline()

    def _make_pipeline(self):
        return ChunkPipeline(self._stage_chunk, len(self._np_ids),
                             depth=self.depth)

    def begin_pass(self):
        """Rewind staging to chunk 0 (called before the solve pass and
        again before the objective pass of each PH iteration)."""
        self._pipeline.start_pass()

    def chunk(self, ci: int) -> dict:
        """Chunk ci's staged device blocks (in-order, prefetched)."""
        return self._pipeline.get(ci)

    def fetch(self, ci: int) -> dict:
        """Direct (pipeline-bypassing) staging of chunk ci — the
        exceptional paths: cold-state build, chunk retries."""
        self._status["direct_fetches"] += 1
        obs.counter_add("stream.direct_fetches")
        return self._stage_chunk(ci)

    def rows(self, np_ids, compacted=None) -> dict:
        """Device blocks for arbitrary scenario rows (the hospital's
        per-scenario rescue assembly). ``compacted`` overrides the
        bound layout's store selection (None: follow the bind)."""
        self._status["direct_fetches"] += 1
        obs.counter_add("stream.direct_fetches")
        return self._stage_rows(np.asarray(np_ids), compacted=compacted)

    def _stage_chunk(self, ci: int) -> dict:
        return self._stage_rows(self._np_ids[ci])

    # ---- lifecycle / accounting ----
    def status(self) -> dict:
        """Plain host ints — signal-safe for bench's SIGTERM-flush
        gap-row stamp."""
        return dict(self._status)

    def close(self):
        if self._pipeline is not None:
            self._pipeline.close()
            self._pipeline = None
        self._layout_key = None

    @property
    def prefetch_alive(self) -> bool:
        return self._pipeline is not None and self._pipeline.alive

    def _put(self, a_np, repl=False):
        """Host block -> device, under the mesh chunk sharding when
        present (``repl=True`` replicates instead — template rows are
        shared operands, not chunk rows), with the placement bytes
        booked (the streamed path's deliberate, flat-per-iteration
        device_put)."""
        import jax
        import jax.numpy as jnp

        if self.sharding is None:
            out = jnp.asarray(a_np)
        elif repl:
            from jax.sharding import NamedSharding, PartitionSpec
            mesh = self.sharding(1).mesh
            out = jax.device_put(a_np, NamedSharding(
                mesh, PartitionSpec(*([None] * np.ndim(a_np)))))
        else:
            out = jax.device_put(a_np, self.sharding(np.ndim(a_np)))
        nb = int(np.asarray(a_np).nbytes)
        obs.counter_add("xfer.device_put_bytes", nb)
        if self._oob_book:
            # transition restage: its one-off full-width bytes must not
            # pollute the per-iteration bytes_shipped flatness signal
            self._status["compacted_restage_bytes"] = \
                self._status.get("compacted_restage_bytes", 0) + nb
            obs.counter_add("stream.compacted_restage_bytes", nb)
        else:
            self._status["bytes_shipped"] += nb
            obs.counter_add("stream.bytes_shipped", nb)
        return out


class StreamedSource(ScenarioSource):
    """Host-resident field store, double-buffered H2D chunk staging.
    With ``stream_int8`` the store packs each field's per-scenario
    deltas int8 behind the host-side gate (stream/quant.py): packed
    fields ship int8 + per-block scale/zero and dequantize inside the
    staging jit; gate-rejected fields keep f64 host storage and book
    ``stream.int8_fallbacks``. Fields whose rows are all identical
    (template-shared c of a rhs-randomness family) are detected at
    build and never shipped at all — the template row lives on device
    once and broadcasts per chunk."""

    kind = "streamed"

    def __init__(self, batch, dtype, depth=2, sharding=None,
                 int8=False, int8_tol=1e-3):
        super().__init__(dtype, depth=depth, sharding=sharding)
        self._store = {}       # field -> ("const", tmpl) | ("f64", arr)
        #                        | ("int8", Int8Field)
        self._cstore = None    # compacted-width twin (install_compacted)
        self._tmpl_dev = {}
        self._tmpl_dev_c = {}
        self._status["compacted_transitions"] = 0
        self._status["compacted_restage_bytes"] = 0
        self.install(batch, int8=int8, int8_tol=int8_tol)

    def install(self, batch, int8=None, int8_tol=None):
        """(Re)build the host store from a batch's stacked arrays —
        engine construction and serve's install_batch tenant swap both
        land here. Keeps the quantization policy unless overridden."""
        if int8 is not None:
            self._int8 = bool(int8)
        if int8_tol is not None:
            self._int8_tol = float(int8_tol)
        self.close()           # a new tenant's data invalidates staging
        self._store = {}
        self._cstore = None    # a new tenant's widths are full again
        self._tmpl_dev = {}
        self._tmpl_dev_c = {}
        self._S = int(getattr(batch, "S", np.asarray(batch.l).shape[0]))
        for f in self.fields:
            a = np.asarray(getattr(batch, f), np.float64)
            tmpl = a[0]
            if a.shape[0] > 1 and (a == tmpl[None, :]).all():
                self._store[f] = ("const", tmpl.copy())
                continue
            if self._int8:
                fld = quantize_field(a, tmpl, self._int8_tol)
                if fld is not None:
                    self._store[f] = ("int8", fld)
                    continue
                self._status["int8_fallbacks"] += 1
                obs.counter_add("stream.int8_fallbacks")
                obs.event("stream.int8_fallback", {"field": f})
            self._store[f] = ("f64", a.copy())

    def host_nbytes(self) -> int:
        """Host residency of the store (the int8 win is visible here:
        Int8Field.nbytes counts the packed representation)."""
        nb = sum(val.nbytes for _, val in self._store.values())
        if self._cstore is not None:
            nb += sum(val.nbytes for _, val in self._cstore.values())
        return nb

    def _stage_rows(self, ids, compacted=None) -> dict:
        import jax.numpy as jnp

        if compacted is None:
            compacted = self._bind_compacted
        if compacted and self._cstore is None:
            raise RuntimeError(
                "compacted staging requested before install_compacted")
        store = self._cstore if compacted else self._store
        cache = self._tmpl_dev_c if compacted else self._tmpl_dev
        out = {}
        rows = ids.shape[0]
        for f in self.fields:
            kind, val = store[f]
            if kind == "const":
                td = cache.get(f)
                if td is None:
                    # pre-cast on host: ship engine-dtype bytes, not
                    # f64 ones (one-time here; the per-chunk f64
                    # branch below pays per iteration)
                    td = cache[f] = self._put(
                        np.asarray(val, _np_dtype(self.dtype)),
                        repl=True)
                out[f] = jnp.broadcast_to(td[None, :], (rows,) + td.shape)
            elif kind == "int8":
                td = cache.get(f)
                if td is None:
                    # template row + varying column INDEX ship once,
                    # replicated; per chunk the wire carries q over the
                    # varying columns alone — bytes_shipped books the
                    # actually-staged buffer, not the full row width
                    vidx = np.flatnonzero(val.varying).astype(np.int32)
                    td = cache[f] = (
                        self._put(np.asarray(val.tmpl, np.float64),
                                  repl=True),
                        self._put(vidx, repl=True),
                        vidx)
                out[f] = dequantize_cols(
                    td[0], td[1], self._put(val.q[ids][:, td[2]]),
                    self._put(val.scale[ids]),
                    self._put(val.zero[ids]), self.dtype)
            else:
                # cast HOST-side: an f32 engine must not pay f64 wire
                # bytes per chunk per pass (the f64->f32 rounding is
                # identical on host and device, so the values the
                # solver sees — and the equality contract — are
                # unchanged; the resident path's ship_stacked casts
                # the same way)
                out[f] = self._put(val[ids].astype(
                    _np_dtype(self.dtype)))
        if not self._oob_book:   # transition restages aren't chunks
            self._status["chunks_shipped"] += 1
            obs.counter_add("stream.chunks_shipped")
        return out

    def stage_full(self) -> dict:
        """One out-of-band FULL-width staging of every scenario row —
        the compaction transition's build_plan input. Its bytes book on
        ``stream.compacted_restage_bytes`` (not the per-iteration
        ``bytes_shipped`` flatness signal) and it counts as neither a
        chunk nor a direct fetch."""
        self._oob_book = True
        try:
            return self._stage_rows(np.arange(self._S), compacted=False)
        finally:
            self._oob_book = False

    def install_compacted(self, plan):
        """Rebuild the host store at a shrink plan's compacted widths.
        The folded/shifted ``l``/``u`` and kept-column ``lb``/``ub``
        come D2H once per transition from the plan's device blocks,
        then re-run const detection and int8 re-quantization at the
        compacted width (a block that gated full-width may fail the
        gate compacted — it falls back to f64 and books the fallback).
        ``c`` stays the FULL-width store entry: the loop gathers kept
        columns per chunk, and objective assembly wants full width.
        The device values round-trip exactly (engine dtype -> f64 host
        -> engine dtype), so compacted+streamed chunks are bit-equal
        to compacted+resident slices wherever int8 is off."""
        self.close()             # the layout is about to change width
        cstore = {}
        for f, dev in (("l", plan.data_c.l), ("u", plan.data_c.u),
                       ("lb", plan.data_c.lb), ("ub", plan.data_c.ub)):
            # once-per-transition compacted-store pull; the transition
            # already syncs to refactorize
            a = np.asarray(dev, np.float64)
            obs.counter_add("xfer.d2h_bytes", a.nbytes)
            tmpl = a[0]
            if a.shape[0] > 1 and (a == tmpl[None, :]).all():
                cstore[f] = ("const", tmpl.copy())
                continue
            if self._int8:
                fld = quantize_field(a, tmpl, self._int8_tol)
                if fld is not None:
                    cstore[f] = ("int8", fld)
                    continue
                self._status["int8_fallbacks"] += 1
                obs.counter_add("stream.int8_fallbacks")
                obs.event("stream.int8_fallback",
                          {"field": f, "compacted": True})
            cstore[f] = ("f64", a)
        cstore["c"] = self._store["c"]
        self._cstore = cstore
        self._tmpl_dev_c = {}
        self._status["compacted_transitions"] += 1
        obs.counter_add("stream.compacted_transitions")

    def setup_arrays(self, dtype, keep_cols=None):
        """Exact 2-row setup surrogates from one host pass over the
        store (see the module docstring). ``keep_cols``: build the
        COMPACTED problem's surrogates — l/u/lb/ub patterns over the
        compacted store (the folded/shifted values the compacted
        factors actually consume), the cost-scale surrogate as the
        FULL per-column |c| max gathered at the kept columns (gather
        and per-column max commute, so the scale is exact)."""
        import jax.numpy as jnp

        store = self._store if keep_cols is None else self._cstore
        vals = {}
        for f in self.fields:
            kind, val = store[f]
            if kind == "const":
                vals[f] = val[None, :]
            elif kind == "int8":
                # reconstruct exactly what the device will see — the
                # eq pattern must reflect QUANTIZED values
                from .quant import _reconstruct_f32
                vals[f] = _reconstruct_f32(val, slice(None))
            else:
                vals[f] = val
        # patterns + the cost max evaluate on ENGINE-dtype values —
        # exactly what the resident path's shipped arrays carry
        eq_rows = _eq_pattern(vals["l"], vals["u"], dtype).all(axis=0)
        eq_cols = _eq_pattern(vals["lb"], vals["ub"], dtype).all(axis=0)
        c_max = np.abs(np.asarray(vals["c"],
                                  _np_dtype(dtype))).max(axis=0)
        if keep_cols is not None:
            c_max = c_max[np.asarray(keep_cols)]
        l2, u2 = _surrogate_pair(eq_rows)
        lb2, ub2 = _surrogate_pair(eq_cols)
        c2 = np.broadcast_to(c_max, (2,) + c_max.shape)
        return tuple(jnp.asarray(a, dtype)
                     for a in (l2, u2, lb2, ub2, c2))


class SynthesizedSource(ScenarioSource):
    """Template rows on device + a seeded jitted generator: chunk
    staging never ships scenario data (steady-state
    ``xfer.device_put_bytes`` is ZERO — the flat-transfer half of the
    sharding acceptance contract holds trivially)."""

    kind = "synthesized"

    def __init__(self, batch, spec, dtype, depth=2, sharding=None):
        super().__init__(dtype, depth=depth, sharding=sharding)
        self.spec = spec
        self._S = int(batch.S)       # padded S — pad ids synthesize
        #                              fresh p=0 scenarios, harmlessly
        # template rows (batch vectors are broadcast views of them —
        # synth.synth_batch(materialize_values=False))
        self._tmpl = {f: np.asarray(getattr(batch, f), np.float64)[0]
                      for f in self.fields}
        self._tmpl_dev = None
        self._asm = None
        self._ids_dev = None

    # synthesis is device compute — no prefetch thread, no H2D; the
    # in-order pipeline protocol degenerates to calling the jit
    def _make_pipeline(self):
        return None

    def begin_pass(self):
        pass

    def close(self):
        self._layout_key = None
        self._ids_dev = None

    @property
    def prefetch_alive(self) -> bool:
        return False

    def bind(self, key, np_ids, compacted=False):
        # compacted staging never applies: synthesis is full-width by
        # construction (and validate() keeps shrink_compact off it)
        if key == self._layout_key:
            return
        self._layout_key = key
        self._np_ids = [np.asarray(ids) for ids in np_ids]
        # per-chunk id vectors live on device once (a few KB total),
        # sharded like chunk rows under a mesh — their placement is
        # booked as the one deliberate device_put of a synth bind
        self._ids_dev = [self._put(ids.astype(np.int32))
                         for ids in self._np_ids]

    def _assemble_fn(self):
        import jax
        import jax.numpy as jnp

        if self._asm is not None:
            return self._asm
        if self._tmpl_dev is None:
            # replicated shared operands (booked like any placement;
            # once per source, never steady-state)
            self._tmpl_dev = {f: self._put(v, repl=True)
                              for f, v in self._tmpl.items()}
        tmpl, spec, dtype = self._tmpl_dev, self.spec, self.dtype

        def asm(ids):
            rows = ids.shape[0]
            out = {f: jnp.broadcast_to(
                tmpl[f].astype(dtype)[None, :],
                (rows,) + tmpl[f].shape) for f in SOURCE_FIELDS}
            vals = synth_values(spec, ids)
            for fld, v in zip(spec.fields, vals):
                out[fld.field] = out[fld.field].at[
                    :, fld.start:fld.stop].set(v.astype(dtype))
            return out

        self._asm = jax.jit(asm)
        return self._asm

    def chunk(self, ci: int) -> dict:
        self._status["synth_chunks"] += 1
        obs.counter_add("stream.synth_chunks")
        return self._assemble_fn()(self._ids_dev[ci])

    def fetch(self, ci: int) -> dict:
        self._status["direct_fetches"] += 1
        obs.counter_add("stream.direct_fetches")
        return self.chunk(ci)

    def rows(self, np_ids, compacted=None) -> dict:
        self._status["direct_fetches"] += 1
        obs.counter_add("stream.direct_fetches")
        import jax.numpy as jnp
        return self._assemble_fn()(jnp.asarray(np.asarray(np_ids),
                                               jnp.int32))

    def setup_arrays(self, dtype, batch_rows: int = 8192):
        """Exact surrogates via ONE streaming host pass of the
        generator: id batches are generated, their eq patterns folded
        into the running all-scenarios AND, and the batch discarded —
        S=1M costs host time, never host memory. Untouched fields keep
        the template's own pattern (both rows equal the template, so
        the pair's pattern IS the template pair's); c is untouched by
        every synth spec (synth.SYNTH_FIELDS), so the cost-scale
        surrogate is |template c| exactly."""
        import jax
        import jax.numpy as jnp

        tmpl = self._tmpl
        eq_rows = _eq_pattern(tmpl["l"][None], tmpl["u"][None],
                              dtype)[0]
        eq_cols = _eq_pattern(tmpl["lb"][None], tmpl["ub"][None],
                              dtype)[0]
        touched = {f.field for f in self.spec.fields}
        if touched:
            # for a pair the spec touches, the TRUE all-scenario
            # pattern is the generated scenarios' alone (the batch
            # arrays below are template rows with the touched blocks
            # replaced — untouched entries reproduce the template
            # pair, so the reduction is correct over every column);
            # the template's own pattern must be REPLACED, not ANDed:
            # a spec pinning a row to equality the template left open
            # would otherwise lose its eq boost
            gen_rows = np.ones(tmpl["l"].shape, bool)
            gen_cols = np.ones(tmpl["lb"].shape, bool)
            fn = jax.jit(lambda ids: synth_values(self.spec, ids))
            for lo in range(0, self._S, batch_rows):
                ids = np.arange(lo, min(lo + batch_rows, self._S),
                                dtype=np.int32)
                vals = fn(ids)
                blk = {f: np.broadcast_to(
                    tmpl[f], (ids.size,) + tmpl[f].shape).copy()
                    for f in touched}
                for fld, v in zip(self.spec.fields, vals):
                    blk[fld.field][:, fld.start:fld.stop] = \
                        np.asarray(v, np.float64)
                l_b = blk.get("l", tmpl["l"][None])
                u_b = blk.get("u", tmpl["u"][None])
                lb_b = blk.get("lb", tmpl["lb"][None])
                ub_b = blk.get("ub", tmpl["ub"][None])
                if touched & {"l", "u"}:
                    gen_rows &= _eq_pattern(l_b, u_b,
                                            dtype).all(axis=0)
                if touched & {"lb", "ub"}:
                    gen_cols &= _eq_pattern(lb_b, ub_b,
                                            dtype).all(axis=0)
            if touched & {"l", "u"}:
                eq_rows = gen_rows
            if touched & {"lb", "ub"}:
                eq_cols = gen_cols
        c_max = np.abs(np.asarray(tmpl["c"], _np_dtype(dtype)))
        l2, u2 = _surrogate_pair(eq_rows)
        lb2, ub2 = _surrogate_pair(eq_cols)
        c2 = np.broadcast_to(c_max, (2,) + c_max.shape)
        return tuple(jnp.asarray(a, dtype)
                     for a in (l2, u2, lb2, ub2, c2))


def make_source(batch, options: dict, dtype, mesh=None):
    """Factory the engine build calls (core/spbase): resolves the
    ``scenario_source`` option into a bound-ready source, or None for
    the resident path."""
    src = str(options.get("scenario_source", "resident"))
    if src == "resident":
        return None
    sharding = None
    if mesh is not None:
        from ..parallel.mesh import scenario_sharding
        sharding = lambda ndim: scenario_sharding(mesh, ndim)
    depth = int(options.get("stream_depth", 2))
    if src == "streamed":
        return StreamedSource(
            batch, dtype, depth=depth, sharding=sharding,
            int8=bool(options.get("stream_int8", False)),
            int8_tol=float(options.get("stream_int8_tol", 1e-3)))
    if src == "synthesized":
        spec = options.get("synth_spec")
        if spec is None:
            raise ValueError(
                "scenario_source='synthesized' needs a synth_spec "
                "engine option (models exporting scenario_synth_spec "
                "get it via utils/vanilla; see doc/streaming.md)")
        return SynthesizedSource(batch, spec, dtype, depth=depth,
                                 sharding=sharding)
    raise ValueError(f"unknown scenario_source {src!r}; known: "
                     "('resident', 'streamed', 'synthesized')")
