"""Device-side scenario synthesis for randomness-in-rhs families.

The third :class:`~mpisppy_tpu.stream.source.ScenarioSource` kind:
instead of shipping S full vector blocks H2D (or holding them in HBM),
a seeded jitted generator manufactures each scenario's rhs/bound
perturbations IN-KERNEL from ``(seed, scenario_id)`` — chunk staging
becomes pure device compute and the steady-state
``xfer.device_put_bytes`` of a synthesized wheel is ZERO.

The :class:`SynthSpec` is the SINGLE SOURCE of the family's scenario
data: the resident/streamed twins used by the equivalence tests are
built by materializing the SAME generator on host
(:func:`materialize`, jax's threefry PRNG is bit-identical across
backends), so synthesized == resident is exact by construction — not a
tolerance accident.

Contract for ``SynthSpec.fn`` (model modules export it through
``scenario_synth_spec``, e.g. models/farmer.py, models/uc.py):

- pure jax, ``fn(key) -> tuple`` of per-field value arrays in
  ``fields`` order (``key`` is already folded with the scenario id:
  ``fold_in(PRNGKey(seed), scenario_id)`` — chunk composition can
  never change a scenario's data);
- fields address rhs/bound vectors only (``l``/``u``/``lb``/``ub``):
  cost randomness would have to track the per-stage cost split
  (ir/batch's ``c_stage`` consistency rule) and is rejected at spec
  construction;
- the spec must cover EVERY scenario-dependent entry of the family —
  the template (scenario 0's creator output) provides all remaining
  data, shared across scenarios.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np

# fields a synth spec may perturb: rhs rows and variable boxes.
# Deliberately NOT "c" (see module docstring).
SYNTH_FIELDS = ("l", "u", "lb", "ub")
# the per-scenario vector fields a scenario source serves (superset of
# SYNTH_FIELDS: c rides along as a template-shared block)
SOURCE_FIELDS = ("l", "u", "lb", "ub", "c")


@dataclass(frozen=True)
class SynthField:
    """One perturbed block: ``field[start:stop]`` of the stacked
    vector (offsets from the template StandardForm's con_slices /
    var_slices)."""
    field: str
    start: int
    stop: int

    def __post_init__(self):
        if self.field not in SYNTH_FIELDS:
            raise ValueError(
                f"synth specs may perturb {SYNTH_FIELDS} only; got "
                f"{self.field!r} (cost randomness needs the c_stage "
                "split and is not supported)")
        if not (0 <= self.start < self.stop):
            raise ValueError(f"bad synth block [{self.start}, {self.stop})")


@dataclass(frozen=True)
class SynthSpec:
    """Seeded generator + the field layout it writes."""
    seed: int
    fields: tuple          # tuple[SynthField, ...]
    fn: Callable           # fn(folded_key) -> tuple of (stop-start,) arrays


def synth_values(spec: SynthSpec, scen_ids):
    """Per-scenario perturbation values for ``scen_ids`` (any int
    array): vmap of the spec's generator over
    ``fold_in(PRNGKey(seed), id)``. Pure jax — callers trace it into
    their chunk staging jit."""
    import jax
    import jax.numpy as jnp

    key0 = jax.random.PRNGKey(spec.seed)

    def one(s):
        vals = spec.fn(jax.random.fold_in(key0, s))
        if not isinstance(vals, tuple):
            vals = (vals,)
        return vals

    return jax.vmap(one)(jnp.asarray(scen_ids, jnp.int32))


def materialize(spec: SynthSpec, S: int, batch_rows: int = 8192) -> dict:
    """Host materialization of the generator's values for scenarios
    [0, S): ``{field: [(start, stop, (S, w) ndarray), ...]}``. Runs the
    SAME jitted generator the device source traces (threefry is
    backend-deterministic), in id batches so only one batch of values
    is transient at a time."""
    import jax

    fn = jax.jit(lambda ids: synth_values(spec, ids))
    parts = {f.field: [] for f in spec.fields}
    stacks = [[] for _ in spec.fields]
    for lo in range(0, S, batch_rows):
        ids = np.arange(lo, min(lo + batch_rows, S), dtype=np.int32)
        vals = fn(ids)
        for i, v in enumerate(vals):
            stacks[i].append(np.asarray(v, np.float64))
    for f, st in zip(spec.fields, stacks):
        parts[f.field].append((f.start, f.stop, np.concatenate(st)))
    return parts


def _validate_spec(spec: SynthSpec, widths: dict):
    """Check the declared blocks fit their field vectors and the
    generator's output arity/shapes match — at build time, not as a
    deep shape error inside the chunk jit."""
    import jax

    for f in spec.fields:
        w = widths[f.field]
        if f.stop > w:
            raise ValueError(
                f"synth block {f.field}[{f.start}:{f.stop}] exceeds the "
                f"field width {w}")
    shapes = jax.eval_shape(
        lambda ids: synth_values(spec, ids), np.zeros(2, np.int32))
    if not isinstance(shapes, tuple):
        shapes = (shapes,)
    if len(shapes) != len(spec.fields):
        raise ValueError(
            f"synth fn returns {len(shapes)} arrays for "
            f"{len(spec.fields)} declared fields")
    for f, sh in zip(spec.fields, shapes):
        if tuple(sh.shape) != (2, f.stop - f.start):
            raise ValueError(
                f"synth fn output for {f.field}[{f.start}:{f.stop}] has "
                f"per-scenario shape {tuple(sh.shape)[1:]}, block needs "
                f"({f.stop - f.start},)")


def synth_batch(scenario_creator, tree, spec_builder, creator_kwargs=None,
                seed: int = 0, materialize_values: bool = True,
                num_stages=None):
    """Build a (ScenarioBatch, SynthSpec) pair for a synth family: the
    creator runs ONCE (scenario 0 → shared template, like the
    vector_patch fast path) and the spec defines every scenario's
    perturbations — including scenario 0's, so the family's data is
    identical whether it runs resident, streamed, or synthesized.

    ``materialize_values=True`` stacks real (S, ...) host arrays (the
    resident / streamed representation). ``materialize_values=False``
    keeps the batch vectors as zero-stride ``np.broadcast_to`` VIEWS of
    the template (a synthesized-source engine never reads them — its
    data comes from the generator; the views only carry shape), so an
    S=1M batch costs no host memory beyond the template."""
    from ..ir.batch import ScenarioBatch, _nonant_indexing
    from ..ir.standard_form import lower

    creator_kwargs = creator_kwargs or {}
    T = num_stages or tree.num_stages
    f0 = lower(scenario_creator(tree.scen_names[0], **creator_kwargs),
               num_stages=T)
    spec = spec_builder(f0, seed=seed, **creator_kwargs)
    S = len(tree.scen_names)
    widths = {"l": f0.m, "u": f0.m, "lb": f0.n, "ub": f0.n}
    _validate_spec(spec, widths)

    base = {"c": f0.c, "l": f0.l, "u": f0.u, "lb": f0.lb, "ub": f0.ub,
            "c_stage": f0.c_stage, "P_diag": f0.P_diag}
    if materialize_values:
        vecs = {k: np.repeat(np.asarray(v, np.float64)[None], S, axis=0)
                for k, v in base.items()}
        for fname, blocks in materialize(spec, S).items():
            for start, stop, vals in blocks:
                vecs[fname][:, start:stop] = vals
    else:
        vecs = {k: np.broadcast_to(np.asarray(v, np.float64),
                                   (S,) + np.shape(v))
                for k, v in base.items()}

    nonant_idx, nonant_stage, slot_slices = _nonant_indexing(f0, tree)
    batch = ScenarioBatch(
        tree=tree, template=f0,
        c=vecs["c"], c0=np.full(S, np.float64(f0.c0)),
        P_diag=vecs["P_diag"],
        A=f0.A,                               # ONE shared matrix
        l=vecs["l"], u=vecs["u"], lb=vecs["lb"], ub=vecs["ub"],
        c_stage=vecs["c_stage"],
        c0_stage=np.repeat(np.asarray(f0.c0_stage,
                                      np.float64)[None], S, axis=0),
        prob=tree.probabilities.copy(),
        nonant_idx=nonant_idx, nonant_stage=nonant_stage,
        stage_slot_slices=slot_slices,
    )
    return batch, spec
