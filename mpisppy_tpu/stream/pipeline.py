"""The double-buffered chunk prefetch pipeline.

One daemon host thread walks the chunk sequence ahead of the solve
loop, staging each chunk's device blocks into a bounded queue: while
the device solves chunk k, the thread is already pushing chunk k+1's
H2D transfer — the doc/kernels.md "overlap H2D of chunk k+1 under
chunk k's solve" item. The queue bound (``depth``, default 2) IS the
double buffer: the producer blocks once ``depth`` chunks are staged,
so device-side staging residency never exceeds ``depth`` chunk blocks
regardless of S.

The loop consumes chunks strictly in order (``get(ci)``), possibly
several passes per iteration (the solve pass and the objective pass of
core/ph's streamed chunk loop); ``start_pass()`` rewinds the producer
to chunk 0 and discards any stale staged blocks from a superseded
pass.

Shutdown: ``close()`` is idempotent and joins the thread; the thread
is a daemon besides, so a SIGTERM/preemption exit can never hang on a
blocked producer (Hub.handle_preemption closes the source explicitly —
tests/test_stream.py pins the thread's exit).

Accounting (all catalogued in doc/observability.md): the loader books
``xfer.device_put_bytes`` / ``stream.bytes_shipped`` /
``stream.chunks_shipped`` per staged chunk; this class books
``stream.prefetch_stalls`` + the ``stream.prefetch_stall_seconds``
histogram whenever the consumer outran the producer (the prefetch
occupancy signal analyze's streaming section renders).
"""

from __future__ import annotations

import queue
import threading
import time as _time

from .. import obs


class ChunkPipeline:
    """``loader(ci) -> block`` run ``depth`` chunks ahead on a host
    thread. The loader owns the device_put and its byte accounting;
    the pipeline owns ordering, backpressure, and stall accounting."""

    def __init__(self, loader, n_chunks: int, depth: int = 2,
                 name: str = "stream-prefetch"):
        if depth < 1:
            raise ValueError("pipeline depth must be >= 1")
        self.loader = loader
        self.n_chunks = int(n_chunks)
        self.depth = int(depth)
        self._q = queue.Queue(maxsize=self.depth)
        self._wake = threading.Event()
        self._stop = False
        self._gen = 0            # pass generation; bumped by start_pass
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._started = False

    # ---- producer ----
    def _run(self):
        while True:
            self._wake.wait()
            if self._stop:
                return
            with self._lock:
                gen = self._gen
                self._wake.clear()
            for ci in range(self.n_chunks):
                if self._stop or self._gen != gen:
                    break
                try:
                    blk = self.loader(ci)
                except Exception as e:       # surfaced by get()
                    self._q_put((gen, ci, None, e))
                    break
                if not self._q_put((gen, ci, blk, None)):
                    break

    def _q_put(self, item) -> bool:
        """Bounded put that stays responsive to stop/rewind (a plain
        blocking put could deadlock close() against a full queue)."""
        gen = item[0]
        while not self._stop and self._gen == gen:
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # ---- consumer ----
    def start_pass(self):
        """Rewind to chunk 0 for a fresh in-order pass, discarding any
        staged blocks of a superseded pass."""
        with self._lock:
            self._gen += 1
        while True:                      # drain stale blocks
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if not self._started:
            self._thread.start()
            self._started = True
        self._wake.set()

    def get(self, ci: int):
        """Chunk ``ci``'s staged block (strictly in-order consumption).
        Books a prefetch stall when the producer hadn't staged it yet."""
        t0 = None
        while True:
            try:
                gen, got, blk, err = self._q.get(timeout=0.05)
            except queue.Empty:
                if t0 is None:
                    t0 = _time.perf_counter()
                if self._stop or not self._thread.is_alive():
                    raise RuntimeError(
                        "stream prefetch thread is gone (closed or "
                        "crashed) — no staged chunk to consume")
                continue
            if gen != self._gen:
                continue                 # stale pass, drop
            if err is not None:
                raise err
            if got != ci:
                raise RuntimeError(
                    f"stream pipeline out of order: wanted chunk {ci}, "
                    f"staged {got} (chunks must be consumed in order; "
                    "call start_pass() to rewind)")
            if t0 is not None:
                dt = _time.perf_counter() - t0
                obs.counter_add("stream.prefetch_stalls")
                obs.histogram_observe("stream.prefetch_stall_seconds", dt)
            return blk

    # ---- lifecycle ----
    @property
    def alive(self) -> bool:
        return self._started and self._thread.is_alive()

    def close(self):
        """Idempotent shutdown: stop the producer, drain, join."""
        self._stop = True
        self._wake.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if self._started:
            self._thread.join(timeout=5.0)
