"""Resource accounting: XLA compiles, device memory, transfer bytes.

The two costs that dominate a TPU stack are invisible in wall-clock
phase spans: an unexpected *retrace* of a hot-loop jitted entry (a
shape or static-arg drift recompiling a ~200-340 s reference-scale
program mid-run) and *device memory* creeping toward the OOM cliff.
This module makes both first-class metrics, plus explicit byte
counters for the host<->device transfers the chunked PH loop performs
at its `device_put` / stacked-residual sites.

Three surfaces:

 - **Compile hooks** (:func:`install`, process-global, installed once
   by the first :class:`~mpisppy_tpu.obs.recorder.Recorder`): a
   ``jax.monitoring`` duration listener counts backend compiles /
   traces / lowerings into counters + latency histograms and books
   each backend compile as a ``jax.compile`` trace span, and a DEBUG
   handler on the ``jax._src.dispatch`` logger attributes each compile
   to its *jitted entry by name* (``jax.compile.entry.<name>``
   counters + a ``jax.compile`` event) — an unexpected retrace in the
   PH hot loop shows up as a counter, not a mystery slowdown. Both
   forward to whatever recorder is active and no-op when none is.
 - **Memory watermarks** (:func:`sample_memory`): per-device
   ``device.memory_stats()`` gauges (bytes in use + peak) where the
   backend supports it; a guarded no-op on backends that don't (CPU
   returns None) — sampled once per PH iteration and at bench phase
   boundaries.
 - **Transfer byte helpers** (:func:`tree_nbytes`): the instrumented
   sites (core/ph.py gate reads,
   core/spbase.py batch shipping, ops/qp_solver.py host rho
   refactors) guard with ``obs.enabled()`` and add to
   ``xfer.h2d_bytes`` / ``xfer.d2h_bytes`` / ``xfer.device_put_bytes``
   so the disabled path never computes a byte count.
"""

from __future__ import annotations

import logging
import re
import time

_installed = False
# device keys observed without memory_stats support (the CPU backend
# returns None): probed once, then skipped forever — sample_memory sits
# on the per-iteration path and must not re-raise per device per iter
_mem_unsupported: set = set()


def _active():
    from . import active
    return active()


# ---- jax.monitoring duration events -> counters + histograms ----
# name -> (counter, histogram). backend_compile is the expensive one
# (the actual XLA compile); trace/lowering counts reveal *why* (a
# retrace re-traces AND re-lowers AND re-compiles; a python-level
# cache hit does none).
_DUR_EVENTS = {
    "/jax/core/compile/backend_compile_duration":
        ("jax.compiles", "jax.compile_seconds"),
    "/jax/core/compile/jaxpr_trace_duration":
        ("jax.traces", "jax.trace_seconds"),
    "/jax/core/compile/jaxpr_to_mlir_module_duration":
        ("jax.lowerings", "jax.lowering_seconds"),
}


def _on_duration(name, secs, **kw):
    r = _active()
    if r is None:
        return
    ent = _DUR_EVENTS.get(name)
    if ent is None:
        return
    counter, hist = ent
    r.metrics.counter_add(counter)
    r.metrics.histogram_observe(hist, secs)
    if counter == "jax.compiles":
        # book the compile as a span ending now: retraces render as
        # fat blocks interrupting the phase timeline in Perfetto
        now = time.perf_counter()
        r.trace.complete("jax.compile", now - secs, now, cat="resource")
        # compile-ledger attribution (doc/roofline.md): every backend
        # compile books to the instrumented entry in flight on this
        # thread, or the unattributed bucket — the ledger sums to
        # jax.compiles exactly because this is the same firing
        from . import profile as _profile
        _profile.note_compile(secs)


class _CompileLogHandler(logging.Handler):
    """Per-jitted-entry compile attribution. ``jax.monitoring`` events
    carry no function name, but ``jax._src.dispatch`` logs every
    backend compile as ``Finished XLA compilation of jit(<name>) in
    <secs> sec`` at DEBUG — the one place the entry name and its
    compile wall-clock meet."""

    _RE = re.compile(
        r"Finished XLA compilation of (\S+) in ([0-9.eE+-]+) sec")

    def emit(self, record):
        r = _active()
        if r is None:
            return
        try:
            m = self._RE.match(record.getMessage())
        except Exception:
            return
        if not m:
            return
        entry = m.group(1)
        if entry.startswith("jit(") and entry.endswith(")"):
            entry = entry[4:-1]
        try:
            secs = float(m.group(2))
        except ValueError:
            return
        r.metrics.counter_add(f"jax.compile.entry.{entry}")
        r.event("jax.compile", {"entry": entry, "seconds": secs})


class _RootPassthrough(logging.Handler):
    """Re-deliver WARNING+ records to the root handlers. Lowering the
    ``jax._src.dispatch`` logger to DEBUG forces ``propagate=False``
    (absl and friends hang level-0 handlers on root, which would spam
    every compile line to stderr); this preserves the ONE flow the
    original configuration allowed — records at/above root's WARNING
    threshold — so jax warnings still reach the user."""

    def emit(self, record):
        if record.levelno >= logging.WARNING:
            logging.getLogger().handle(record)


def install():
    """Install the process-global compile hooks (idempotent). JAX's
    listener registry has no unregister, so hooks are installed once
    and forward to the *currently active* recorder — reconfiguring or
    disabling telemetry needs no teardown."""
    global _installed
    if _installed:
        return
    _installed = True
    try:
        from jax import monitoring
    except Exception:       # jax absent/ancient: resource hooks off
        return
    monitoring.register_event_duration_secs_listener(_on_duration)
    lg = logging.getLogger("jax._src.dispatch")
    lg.addHandler(_CompileLogHandler(level=logging.DEBUG))
    lg.addHandler(_RootPassthrough(level=logging.WARNING))
    lg.propagate = False
    # the compile lines are DEBUG; enable them for our handler without
    # touching jax_log_compiles (which would promote them to WARNING
    # on the user's screen)
    if lg.level == logging.NOTSET or lg.level > logging.DEBUG:
        lg.setLevel(logging.DEBUG)


# ---- device memory watermarks ----
def sample_memory(event=False):
    """Sample ``memory_stats()`` of every device into gauges
    (``mem.<dev>.bytes_in_use`` + ``.peak_bytes_in_use``). Returns the
    sampled {dev: stats} map ({} when unsupported/disabled). With
    ``event=True`` also emits one ``resource.memory`` event carrying
    the per-device byte counts (the per-iteration record path)."""
    r = _active()
    if r is None:
        return {}
    import jax

    out = {}
    for d in jax.devices():
        key = f"{d.platform}{d.id}"
        if key in _mem_unsupported:
            continue
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            # CPU (and some backends) have no allocator stats — probe
            # once, then no-op forever on this device
            _mem_unsupported.add(key)
            continue
        in_use = stats.get("bytes_in_use")
        peak = stats.get("peak_bytes_in_use")
        if in_use is not None:
            r.metrics.gauge_set(f"mem.{key}.bytes_in_use", in_use)
        if peak is not None:
            r.metrics.gauge_set(f"mem.{key}.peak_bytes_in_use", peak)
        out[key] = {"bytes_in_use": in_use, "peak_bytes_in_use": peak}
    if event and out:
        r.event("resource.memory", {"devices": out})
    return out


# ---- transfer byte accounting ----
def tree_nbytes(tree) -> int:
    """Total array bytes across a pytree's leaves (0 for leaves with
    no ``nbytes``). Callers guard with ``obs.enabled()`` — the byte
    walk must never run on the disabled path."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb:
            total += int(nb)
    return total


def put_nbytes(tree, target_of) -> int:
    """Bytes a ``device_put`` will actually MOVE: leaves already
    committed to their target are free passthroughs and don't count —
    the chunked loop re-pins resident warm-start states every
    iteration, and counting those would overstate traffic by orders of
    magnitude. ``target_of(leaf)`` returns the leaf's destination (a
    Device or a Sharding). Callers guard with ``obs.enabled()``."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if not nb:
            continue
        target = target_of(leaf)
        try:
            if hasattr(target, "is_fully_replicated") \
                    or hasattr(target, "device_set"):   # a Sharding
                if leaf.sharding == target:
                    continue
            elif leaf.devices() == {target}:            # a Device
                continue
        except Exception:
            pass        # host arrays etc.: everything moves
        total += int(nb)
    return total
