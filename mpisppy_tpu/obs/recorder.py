"""The Recorder: one telemetry session = metrics + events + trace.

A Recorder owns the three sinks for one run:
 - :class:`~mpisppy_tpu.obs.metrics.MetricsRegistry` (counters/gauges/
   histograms),
 - :class:`~mpisppy_tpu.obs.events.EventStream` (``events.jsonl``),
 - :class:`~mpisppy_tpu.obs.trace.TraceBuffer` (``trace.json``).

``flush()`` persists the trace file and a ``metrics.json`` snapshot
(events stream incrementally on their own); ``close()`` flushes, emits
a final ``run_footer`` event carrying the metrics snapshot, and closes
the stream. The module facade (``mpisppy_tpu/obs/__init__.py``) holds
the process-wide instance; construct Recorders directly only for
isolated captures (tests).

``role`` names this process's place in a multi-process cylinder run
(e.g. ``spoke0-lagrangian``): artifacts become ``events-<role>.jsonl``
/ ``trace-<role>.json`` / ``metrics-<role>.json`` so every process of
a wheel can write into ONE shared run directory without clobbering the
hub's un-suffixed files. ``obs/merge.py`` joins the role traces onto
one wall-clock-aligned timeline after the wheel terminates.
"""

from __future__ import annotations

import json
import os
import time

from .events import EventStream
from .metrics import MetricsRegistry
from .trace import TraceBuffer


def _suffixed(name, ext, role):
    return f"{name}-{role}{ext}" if role else f"{name}{ext}"


class Recorder:
    def __init__(self, out_dir=None, run_id=None, config=None,
                 jax_annotations=False, role=None):
        self.out_dir = out_dir
        self.role = role
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
        self.run_id = run_id or f"run-{int(time.time())}-{os.getpid()}"
        self.metrics = MetricsRegistry()
        self.events = EventStream(
            path=os.path.join(out_dir, _suffixed("events", ".jsonl", role))
            if out_dir else None,
            run_id=self.run_id, config=config, role=role)
        self.trace = TraceBuffer(
            path=os.path.join(out_dir, _suffixed("trace", ".json", role))
            if out_dir else None,
            run_id=self.run_id, jax_annotations=jax_annotations, role=role)
        self._closed = False
        # resource accounting (obs/resource.py): process-global JAX
        # compile hooks, installed once per process on the first
        # session — they forward to whatever recorder is active and
        # no-op when none is
        from . import resource
        resource.install()

    # thin sink forwarding — these five are the whole hot-path surface
    def event(self, etype, fields=None, t=None):
        return self.events.event(etype, fields, t=t)

    def counter_add(self, name, n=1):
        self.metrics.counter_add(name, n)

    def gauge_set(self, name, value):
        self.metrics.gauge_set(name, value)

    def histogram_observe(self, name, value):
        self.metrics.histogram_observe(name, value)

    def span(self, name, cat="host", args=None, lane=None):
        return self.trace.span(name, cat=cat, args=args, lane=lane)

    def complete_span(self, name, t0, t1, cat="host", args=None,
                      lane=None):
        self.trace.complete(name, t0, t1, cat=cat, args=args, lane=lane)

    def flush(self, nonblocking=False):
        """Persist trace.json + metrics.json. ``nonblocking`` is for
        SIGNAL-HANDLER callers (bench's SIGTERM flush): the interrupted
        main-thread frame may hold a sink lock, and a blocking acquire
        there would deadlock the kill path — skip whatever is locked
        instead."""
        self.trace.flush(nonblocking=nonblocking)
        if self.out_dir:
            snap = self.metrics.snapshot(nonblocking=nonblocking)
            if snap is None:
                return
            path = os.path.join(
                self.out_dir, _suffixed("metrics", ".json", self.role))
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"run_id": self.run_id, **snap}, f, indent=1)
            os.replace(tmp, path)

    def close(self):
        if self._closed:
            return
        self._closed = True
        self.events.event("run_footer",
                          {"run_id": self.run_id,
                           "metrics": self.metrics.snapshot()})
        self.flush()
        self.events.close()
