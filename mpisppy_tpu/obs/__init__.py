"""Unified telemetry for mpisppy_tpu: metrics, events, traces.

One process-wide telemetry session replaces the historical scatter of
per-module sinks (spoke ``trace_prefix`` CSVs, hub ``bound_events``
screen rows, PH hospital prints, ``MPISPPY_TPU_SOLVE_TRACE`` stderr
stamps, bench one-off JSON) with three coherent artifacts:

 - ``events.jsonl`` — structured event stream (monotonic stamps, run
   id, config snapshot in the ``run_header`` line),
 - ``trace.json``  — Chrome trace-event spans of the PH pipeline
   phases (load into Perfetto / chrome://tracing),
 - ``metrics.json``— counters / gauges / histograms snapshot.

This module is the FACADE the rest of the codebase calls: module-level
functions that forward to the process-wide :class:`Recorder` when one
is configured and do (almost) nothing when not. The disabled path is a
single global read + ``is None`` test per call and allocates nothing —
``span(...)`` returns a shared no-op singleton — so instrumentation
can live permanently on the PH hot loop (the <2% disabled-overhead
budget in ISSUE 3's acceptance criteria).

Usage::

    from mpisppy_tpu import obs
    obs.configure(out_dir="runs/t1")        # or None for in-memory
    obs.counter_add("ph.gate_syncs")
    obs.event("hub.bound", kind="outer", value=-1.5)
    with obs.span("ph.iteration", args={"iter": 3}):
        ...
    obs.shutdown()

Environment: ``MPISPPY_TPU_TELEMETRY_DIR`` — when set, the first call
to :func:`maybe_configure_from_env` (drivers, bench, profile) enables
telemetry into that directory without code changes.
"""

from __future__ import annotations

import atexit
import math
import os

from .metrics import Histogram, MetricsRegistry        # noqa: F401
from .events import EventStream, SCHEMA_VERSION        # noqa: F401
from .trace import Span, TraceBuffer                   # noqa: F401
from .recorder import Recorder                         # noqa: F401

_REC: Recorder | None = None


class _NullSpan:
    """Shared no-op context manager: the disabled-mode ``span()``
    result. A singleton so disabled spans allocate nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def configure(out_dir=None, run_id=None, config=None,
              jax_annotations=False, role=None) -> Recorder:
    """Start (or replace) the process-wide telemetry session. The old
    session, if any, is closed first. ``out_dir=None`` records
    in-memory only (events tail + metrics; no files) — useful in tests
    and interactive sessions. ``role`` suffixes the artifact filenames
    (``events-<role>.jsonl`` …) so multi-process cylinder runs can
    share one directory (utils/multiproc.py sets it for spoke
    children)."""
    global _REC
    if _REC is not None:
        _REC.close()
    _REC = Recorder(out_dir=out_dir, run_id=run_id, config=config,
                    jax_annotations=jax_annotations, role=role)
    return _REC


def maybe_configure_from_env(role=None) -> Recorder | None:
    """Enable telemetry when MPISPPY_TPU_TELEMETRY_DIR is set (no-op
    when unset or when a session is already active)."""
    d = os.environ.get("MPISPPY_TPU_TELEMETRY_DIR")
    if d and _REC is None:
        return configure(out_dir=d, role=role)
    return _REC


def shutdown():
    """Close the process-wide session (flushes all artifacts)."""
    global _REC
    if _REC is not None:
        _REC.close()
        _REC = None


@atexit.register
def _atexit_close():
    # a crash-free exit persists trace.json/metrics.json even when the
    # driver never called shutdown(); events streamed incrementally
    shutdown()


def active() -> Recorder | None:
    return _REC


def enabled() -> bool:
    return _REC is not None


# ---- hot-path forwarding (each: one global read + None test) ----
def event(etype, fields=None, t=None):
    r = _REC
    if r is not None:
        r.event(etype, fields, t=t)


def counter_add(name, n=1):
    r = _REC
    if r is not None:
        r.metrics.counter_add(name, n)


def gauge_set(name, value):
    r = _REC
    if r is not None:
        r.metrics.gauge_set(name, value)


def histogram_observe(name, value):
    r = _REC
    if r is not None:
        r.metrics.histogram_observe(name, value)


def span(name, cat="host", args=None, lane=None):
    r = _REC
    if r is None:
        return _NULL_SPAN
    return r.span(name, cat=cat, args=args, lane=lane)


def complete_span(name, t0, t1, cat="host", args=None, lane=None):
    r = _REC
    if r is not None:
        r.trace.complete(name, t0, t1, cat=cat, args=args, lane=lane)


def counters_snapshot() -> dict:
    """Copy of the counter map ({} when telemetry is disabled). Taken
    under the registry lock — spoke cylinder threads may be
    inserting new keys concurrently."""
    r = _REC
    return r.metrics.counters_snapshot() if r is not None else {}


def counter_value(name) -> float:
    r = _REC
    return r.metrics.counter_get(name) if r is not None else 0


def histogram_snapshot(name) -> dict | None:
    """Snapshot of one histogram (None when disabled or never
    observed) — the hub's bound-flow status reads staleness tails
    through this."""
    r = _REC
    return r.metrics.histogram_get(name) if r is not None else None


def flush(nonblocking=False):
    """Persist artifacts. ``nonblocking=True`` is for signal handlers:
    skips any sink whose lock the interrupted frame holds."""
    r = _REC
    if r is not None:
        r.flush(nonblocking=nonblocking)


def finite_or_none(v):
    """THE sanitizer for bound/gap fields in telemetry events: None for
    absent or non-finite values (never-established bounds are ±inf,
    which strict-JSON consumers reject), a plain float otherwise."""
    try:
        v = float(v)
    except (TypeError, ValueError):
        return None
    return v if math.isfinite(v) else None
