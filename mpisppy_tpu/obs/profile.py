"""Measured roofline: XLA cost-model capture and the compile ledger.

Every jitted entry point the engine runs (``qp_solve`` variants, the
fused ADMM block, the Pallas plan, shrink/dispatch ops) routes through
:func:`call` when telemetry is enabled. On the FIRST sighting of a new
argument-shape bucket the lowered computation's XLA cost model is
captured — ``Lowered.cost_analysis()`` FLOPs and bytes-accessed, plus
argument bytes from the live operands — keyed by the same
``config_fingerprint`` the serve cache and shrink registry use for
their shape buckets. Per-call, the capture books cumulative
``profile.flops`` / ``profile.hbm_bytes`` counters whose
PER-ITERATION deltas ``core/ph.py`` records into each ``ph.iteration``
event; ``analyze`` joins those deltas against the span timeline to
report measured MFU and HBM-bandwidth utilization per phase, per
bucket, per engine mode (doc/roofline.md's measured column).

Capture cost discipline: ``fn.lower(...)`` is a trace+lower only — it
fires NO backend compile (verified: the ``jax.compiles`` monitoring
event stays silent), so a new bucket costs one extra trace
(milliseconds), never a compile. ``memory_analysis()`` needs the
compiled executable and the AOT path does NOT share the executable
cache with the normal call path, so it would pay one full extra
backend compile per bucket — it is therefore opt-in via
``MPISPPY_TPU_PROFILE_MEMORY=1``. Capture happens BEFORE the call:
donated operands' buffers are deleted afterwards.

The compile ledger: a thread-local entry context is pushed around
every instrumented call; ``resource._on_duration`` reports each
backend compile here, which books ``profile.ledger.compiles.<key>`` /
``profile.ledger.seconds.<key>`` to the entry|fingerprint in flight
(``(unattributed)`` otherwise — ph-level jits, warmup). Every compile
books exactly once, so the ledger column-sums to ``jax.compiles`` by
construction.

Failures never propagate: any cost-model/capture error books a
``profile.unavailable`` counter with a reasoned event (once per
entry/reason) and the call proceeds uninstrumented.

jax is imported lazily inside capture paths only — importing this
module stays jax-free (the hub status plane and bench signal handler
read :func:`last_iteration` / :func:`peaks` as plain dict lookups).
"""

from __future__ import annotations

import os
import threading
import time

from . import active as _active
from . import counter_add, event, gauge_set

UNATTRIBUTED = "(unattributed)"

# Peak device throughput table by device_kind substring: (peak FLOP/s
# at the engine's working precision, peak HBM GB/s). TPU rows are the
# published bf16 peaks (bench.py's V5E_PEAK_BF16 matches the v5e row).
# The CPU tier gets documented NOMINAL placeholders so CPU-tier MFU is
# finite (doc/roofline.md states those rows are CPU-tier, not
# meaningful absolute utilization). Override either peak with
# MPISPPY_TPU_PEAK_FLOPS / MPISPPY_TPU_PEAK_HBM_GBPS.
_PEAKS_BY_KIND = (
    ("v6e", 918e12, 1640.0),
    ("v5p", 459e12, 2765.0),
    ("v5e", 197e12, 819.0),
    ("v5", 459e12, 2765.0),
    ("v4", 275e12, 1228.0),
    ("cpu", 1e11, 50.0),
)
_CPU_NOMINAL = (1e11, 50.0)


class _State:
    """Per-telemetry-session capture state. Reset whenever the
    process-wide Recorder changes (tests reconfigure sessions
    freely)."""

    __slots__ = ("rec", "lock", "costs", "failed", "seconds",
                 "compile_seconds", "device_emitted", "peaks",
                 "last_iter")

    def __init__(self, rec):
        self.rec = rec
        self.lock = threading.Lock()
        # (entry, shape_key) -> _Cost | None (None = capture failed;
        # the call still runs, just uninstrumented)
        self.costs = {}
        self.failed = set()          # (entry, reason) emitted once
        self.seconds = {}            # ledger key -> cumulative call s
        self.compile_seconds = {}    # ledger key -> cumulative compile s
        self.device_emitted = False
        self.peaks = None            # (flops, gbps, source, kind)
        self.last_iter = {}          # plain dict: the signal-safe view


class _Cost:
    __slots__ = ("entry", "fingerprint", "key", "flops", "bytes",
                 "arg_bytes", "memory")

    def __init__(self, entry, fingerprint, key, flops, nbytes,
                 arg_bytes, memory):
        self.entry = entry
        self.fingerprint = fingerprint
        self.key = key               # ledger key: "entry|fp"
        self.flops = flops
        self.bytes = nbytes
        self.arg_bytes = arg_bytes
        self.memory = memory


_STATE: _State | None = None
_STATE_LOCK = threading.Lock()
_TLS = threading.local()


def _state() -> _State | None:
    """The capture state bound to the CURRENT telemetry session (None
    when telemetry is off). Identity-checked per call so a
    reconfigured session never inherits a prior session's buckets."""
    global _STATE
    rec = _active()
    if rec is None:
        return None
    s = _STATE
    if s is None or s.rec is not rec:
        with _STATE_LOCK:
            s = _STATE
            if s is None or s.rec is not rec:
                s = _STATE = _State(rec)
    return s


# ---------------- peaks ----------------

def _resolve_peaks(s: _State):
    """(peak_flops, peak_hbm_gbps, source, device_kind) — env override
    > device_kind table > nominal CPU default. Emits the one-shot
    ``profile.device`` event so jax-free consumers (analyze) read the
    resolved peaks from the stream."""
    if s.peaks is not None:
        return s.peaks
    kind = "unknown"
    try:
        import jax
        kind = str(jax.devices()[0].device_kind)
    except Exception:
        pass
    flops = gbps = None
    source = "table"
    lk = kind.lower()
    for sub, f, g in _PEAKS_BY_KIND:
        if sub in lk:
            flops, gbps = f, g
            break
    if flops is None:
        flops, gbps = _CPU_NOMINAL
        source = "default"
    env_f = os.environ.get("MPISPPY_TPU_PEAK_FLOPS")
    env_g = os.environ.get("MPISPPY_TPU_PEAK_HBM_GBPS")
    try:
        if env_f:
            flops = float(env_f)
            source = "env"
        if env_g:
            gbps = float(env_g)
            source = "env"
    except ValueError:
        pass
    s.peaks = (flops, gbps, source, kind)
    if not s.device_emitted:
        s.device_emitted = True
        event("profile.device", {
            "device_kind": kind, "peak_flops": flops,
            "peak_hbm_gbps": gbps, "source": source,
            "cpu_tier": "cpu" in lk or kind == "unknown"})
    return s.peaks


def peaks():
    """(peak_flops, peak_hbm_gbps, source, device_kind) for the active
    session, or None when telemetry is off."""
    s = _state()
    return _resolve_peaks(s) if s is not None else None


# ---------------- the shape bucket key ----------------

def _shape_key(args, kwargs):
    """Cheap hashable bucket key over the call operands: arrays key by
    (shape, dtype); ints/bools/strings key by VALUE (they are jit
    statics here — a different value is a different executable);
    floats key by presence only (traced weak-typed scalars like eps
    knobs vary per call without retracing — keying their value would
    mint a bucket per tolerance)."""
    import jax

    key = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs)):
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            key.append((tuple(shape), str(getattr(leaf, "dtype", "?"))))
        elif isinstance(leaf, bool) or isinstance(leaf, int) \
                or isinstance(leaf, str) or leaf is None:
            key.append(leaf)
        elif isinstance(leaf, float):
            key.append("f")
        else:
            key.append(type(leaf).__name__)
    return tuple(key)


def _fingerprint(entry, key):
    """The shape bucket's fingerprint — THE SAME
    ``config_fingerprint`` the serve compile cache and the shrink
    bucket registry key by, so one id joins the three planes."""
    from ..ckpt.bundle import config_fingerprint
    return config_fingerprint({"entry": entry,
                               "key": [str(k) for k in key]})


# ---------------- capture ----------------

def _unavailable(s, entry, reason):
    counter_add("profile.unavailable")
    if (entry, reason) not in s.failed:
        s.failed.add((entry, reason))
        event("profile.unavailable", {"entry": entry,
                                       "reason": reason})


def _capture(s, entry, fn, key, args, kwargs) -> _Cost | None:
    """First sighting of (entry, shape bucket): lower and read the XLA
    cost model. Trace+lower only — no backend compile (unless the
    opt-in memory capture asks for the executable)."""
    _resolve_peaks(s)
    try:
        fp = _fingerprint(entry, key)
    except Exception:
        fp = "nofp"
    ledger_key = f"{entry}|{fp}"
    try:
        lower = getattr(fn, "lower", None)
        if lower is None:
            # a plain callable (e.g. the pallas_call wrapper): a
            # throwaway jit gives the lowering — traced, never
            # executed, so still no backend compile
            import jax
            lower = jax.jit(fn).lower
        lowered = lower(*args, **kwargs)
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            raise TypeError(f"cost_analysis returned {type(ca).__name__}")
        flops = float(ca.get("flops") or 0.0)
        nbytes = float(ca.get("bytes accessed") or 0.0)
    except Exception as e:
        _unavailable(s, entry, f"cost_analysis: {type(e).__name__}: {e}")
        return None
    arg_bytes = 0
    try:
        from .resource import tree_nbytes
        arg_bytes = tree_nbytes((args, kwargs))
    except Exception:
        pass
    if nbytes <= 0.0:
        # backends without a bytes-accessed model: fall back to the
        # operand footprint (one read of every argument) so HBM
        # attribution degrades to a floor instead of zero
        nbytes = float(arg_bytes)
    memory = None
    if os.environ.get("MPISPPY_TPU_PROFILE_MEMORY") == "1":
        # opt-in: pays one EXTRA backend compile per bucket (the AOT
        # executable cache is disjoint from the call path's); the
        # ledger context is already pushed, so that compile books to
        # this key and the ledger still sums to jax.compiles
        try:
            ma = lowered.compile().memory_analysis()
            memory = {
                "argument_bytes": int(
                    getattr(ma, "argument_size_in_bytes", 0)),
                "output_bytes": int(
                    getattr(ma, "output_size_in_bytes", 0)),
                "temp_bytes": int(
                    getattr(ma, "temp_size_in_bytes", 0)),
                "generated_code_bytes": int(
                    getattr(ma, "generated_code_size_in_bytes", 0)),
                "alias_bytes": int(
                    getattr(ma, "alias_size_in_bytes", 0)),
            }
        except Exception as e:
            _unavailable(s, entry,
                         f"memory_analysis: {type(e).__name__}: {e}")
    cost = _Cost(entry, fp, ledger_key, flops, nbytes, arg_bytes,
                 memory)
    counter_add("profile.captures")
    fields = {"entry": entry, "fingerprint": fp, "flops": flops,
              "bytes_accessed": nbytes, "arg_bytes": arg_bytes}
    if memory:
        fields["memory"] = memory
    event("profile.entry", fields)
    return cost


# ---------------- the ledger context ----------------

def _push(key):
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    stack.append(key)


def _pop():
    stack = getattr(_TLS, "stack", None)
    if stack:
        stack.pop()


def current_key():
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


def note_compile(secs):
    """Called by ``resource._on_duration`` for EVERY backend compile
    while a session is active: attribute it to the instrumented entry
    in flight on this thread (or the unattributed bucket). One booking
    per compile — the ledger sums to ``jax.compiles`` exactly."""
    s = _state()
    if s is None:
        return
    key = current_key() or UNATTRIBUTED
    counter_add(f"profile.ledger.compiles.{key}")
    counter_add(f"profile.ledger.seconds.{key}", secs)
    with s.lock:
        tot = s.compile_seconds.get(key, 0.0) + secs
        s.compile_seconds[key] = tot
    if key != UNATTRIBUTED:
        fp = key.rsplit("|", 1)[-1]
        gauge_set(f"profile.bucket.compile_seconds.{fp}", tot)


# ---------------- the instrumented call ----------------

def call(entry, fn, *args, **kwargs):
    """Run ``fn(*args, **kwargs)`` with cost capture + ledger
    attribution. Call sites guard with ``obs.enabled()`` — the
    disabled path never reaches here (the zero-cost-when-off
    contract). Any capture failure degrades to the plain call."""
    s = _state()
    if s is None:
        return fn(*args, **kwargs)
    try:
        key = _shape_key(args, kwargs)
    except Exception as e:
        _unavailable(s, entry, f"shape_key: {type(e).__name__}: {e}")
        return fn(*args, **kwargs)
    ck = (entry, key)
    cost = s.costs.get(ck, False)
    if cost is False:
        # push BEFORE capture: the first real call's backend compile
        # (and the opt-in AOT memory compile) book to this key
        _push(f"{entry}|?")
        try:
            cost = _capture(s, entry, fn, key, args, kwargs)
        finally:
            _pop()
        with s.lock:
            s.costs[ck] = cost
    if cost is None:
        return fn(*args, **kwargs)
    _push(cost.key)
    t0 = time.perf_counter()
    try:
        return fn(*args, **kwargs)
    finally:
        dt = time.perf_counter() - t0
        _pop()
        counter_add("profile.flops", cost.flops)
        counter_add("profile.hbm_bytes", cost.bytes)
        with s.lock:
            tot = s.seconds.get(cost.key, 0.0) + dt
            s.seconds[cost.key] = tot
        # host-side elapsed around the dispatched call: on the async
        # path this undercounts the device tail (the iteration gate
        # absorbs it) — MFU math uses the span timeline, this gauge
        # is the /metrics-plane per-bucket attribution
        gauge_set(f"profile.bucket.device_seconds.{cost.fingerprint}",
                   tot)


# ---------------- the per-iteration plane ----------------

def note_iteration(it, seconds, flops_delta, hbm_delta):
    """Called by ``core/ph.py`` once per iteration with that
    iteration's counter deltas: computes the measured-roofline figures,
    sets the ``profile.iter.*`` gauges, and refreshes the plain-dict
    view :func:`last_iteration` (the hub live plane and bench's
    signal-handler gap rows read THAT — no locks). Returns the figures
    dict (JSON-ready) or None when nothing was instrumented."""
    s = _state()
    if s is None:
        return None
    if not flops_delta and not hbm_delta:
        return None
    peak_f, peak_g, _src, _kind = _resolve_peaks(s)
    secs = float(seconds) if seconds else 0.0
    mfu = hbm_gbps = hbm_util = None
    if secs > 0.0:
        mfu = float(flops_delta) / secs / peak_f
        hbm_gbps = float(hbm_delta) / secs / 1e9
        hbm_util = hbm_gbps / peak_g if peak_g else None
    fig = {"iter": int(it), "seconds": secs,
           "flops_per_iter": float(flops_delta),
           "hbm_bytes_per_iter": float(hbm_delta),
           "mfu": mfu, "hbm_gbps": hbm_gbps, "hbm_util": hbm_util}
    if mfu is not None:
        gauge_set("profile.iter.mfu", mfu)
        gauge_set("profile.iter.hbm_gbps", hbm_gbps)
        if hbm_util is not None:
            gauge_set("profile.iter.hbm_util", hbm_util)
    # rebind, don't mutate: signal-handler readers see either the old
    # complete dict or the new complete dict, never a half-update
    s.last_iter = fig
    return fig


def last_iteration():
    """The most recent iteration's roofline figures as a plain dict
    (None before the first instrumented iteration or when telemetry is
    off). Safe from signal handlers: one attribute read, no locks."""
    s = _STATE
    rec = _active()
    if s is None or rec is None or s.rec is not rec:
        return None
    return s.last_iter or None
