"""Merge per-process telemetry traces onto one wall-clock timeline.

A multi-process cylinder run leaves one Chrome trace per process in
the shared run directory: the hub's ``trace.json`` plus one
``trace-<role>.json`` per spoke child (utils/multiproc.py). Each
process stamps spans with its OWN ``time.perf_counter()`` — monotonic
but with an arbitrary per-process origin, so the raw files cannot be
overlaid. Every :class:`~mpisppy_tpu.obs.trace.TraceBuffer` therefore
records a (wall_time_unix, perf_counter) anchor pair read
back-to-back at construction; this module uses those anchors to map
every span to the shared wall clock and emits ONE Perfetto-loadable
``trace_merged.json`` where the hub's PH phases and each spoke's
bound work render as parallel process tracks.

Alignment: for a process with anchor (w, p), a span stamp ``ts`` (in
perf_counter microseconds) happened at wall time ``w + (ts/1e6 - p)``
seconds. The merge rebases all processes onto the earliest anchor so
merged timestamps stay small. Pre-anchor traces (schema 1) fall back
to their events file's ``run_header`` anchor; a file with no anchor at
all is included unshifted on its own timeline (still loadable, just
not aligned) and flagged in the metadata.
"""

from __future__ import annotations

import glob
import json
import os


def _anchor_from_events(run_dir, role):
    """Fallback anchor for pre-anchor traces: the matching events
    file's run_header carries the same (wall, perf_counter) pair.
    Rotation-transparent by construction: a size-capped rotation
    (obs/events.py) re-emits the ORIGINAL header — same anchor pair,
    plus a ``rotated`` marker — as the new current file's first line,
    so this first-line read stays correct mid-rotation."""
    name = f"events-{role}.jsonl" if role else "events.jsonl"
    path = os.path.join(run_dir, name)
    try:
        with open(path, encoding="utf-8") as f:
            head = json.loads(f.readline())
        if head.get("type") == "run_header":
            return {"wall_time_unix": head["wall_time_unix"],
                    "perf_counter": head["t"]}
    except Exception:
        pass
    return None


def trace_files(run_dir):
    """The hub trace + every role trace in a run directory (merged
    outputs excluded)."""
    out = []
    hub = os.path.join(run_dir, "trace.json")
    if os.path.exists(hub):
        out.append(hub)
    out += sorted(glob.glob(os.path.join(run_dir, "trace-*.json")))
    return [p for p in out if not p.endswith("trace_merged.json")]


def merge_traces(run_dir, out_name="trace_merged.json"):
    """Merge every per-process trace in ``run_dir`` into one aligned
    Chrome trace. Returns the output path, or None when there is
    nothing to merge. Each source file's events keep their relative
    timing exactly; only the origin shifts (monotonic stamps cannot be
    reordered by the alignment — the anchors are the single sanctioned
    monotonic->wall conversion, doc/observability.md "Clocks")."""
    files = trace_files(run_dir)
    if not files:
        return None
    sources = []
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except Exception:
            continue        # a torn write (killed child) skips one file
        meta = data.get("metadata") or {}
        anchor = None
        if "perf_counter" in meta and "wall_time_unix" in meta:
            anchor = {"wall_time_unix": meta["wall_time_unix"],
                      "perf_counter": meta["perf_counter"]}
        else:
            anchor = _anchor_from_events(run_dir, meta.get("role"))
        sources.append((path, data, meta, anchor))
    if not sources:
        return None
    anchored = [a["wall_time_unix"] for _, _, _, a in sources if a]
    wall0 = min(anchored) if anchored else None
    merged = []
    roles = []
    unaligned = []
    for i, (path, data, meta, anchor) in enumerate(sources):
        role = meta.get("role") or ("hub" if i == 0 else
                                    os.path.basename(path))
        roles.append(role)
        if anchor is not None and wall0 is not None:
            # perf µs -> µs since the earliest process's anchor
            shift_us = ((anchor["wall_time_unix"] - wall0)
                        - anchor["perf_counter"]) * 1e6
        else:
            shift_us = 0.0
            unaligned.append(role)
        # remap pids per source: a same-host run CAN reuse pids (and
        # in-process tests share one), which would fold two processes
        # onto one Perfetto track
        pid_map = {}
        for ev in data.get("traceEvents", ()):
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                # replaced by the role-labelled process_name injected
                # on first pid sighting below
                continue
            ev = dict(ev)
            old_pid = ev.get("pid", 0)
            new_pid = pid_map.get(old_pid)
            if new_pid is None:
                new_pid = pid_map[old_pid] = (i + 1) * 1000 \
                    + len(pid_map)
                merged.append({"name": "process_name", "ph": "M",
                               "pid": new_pid, "tid": 0,
                               "args": {"name": f"{role} "
                                                f"(pid {old_pid})"}})
            ev["pid"] = new_pid
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift_us
            merged.append(ev)
    out_path = os.path.join(run_dir, out_name)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": merged,
                   "displayTimeUnit": "ms",
                   "metadata": {"merged_from": [os.path.basename(p)
                                                for p, _, _, _ in sources],
                                "roles": roles,
                                "unaligned_roles": unaligned,
                                "clock": "wall_us_since_first_anchor",
                                "wall_time_unix_origin": wall0}}, f)
    os.replace(tmp, out_path)
    return out_path
