"""Process-wide metrics registry: counters, gauges, histograms.

The registry is deliberately tiny — a dict per kind under one lock —
because it sits on the PH hot loop's host path: a counter bump is a
dict ``get`` + add, a gauge a dict store, and a histogram four scalar
updates (count/sum/min/max; full bucketing would buy nothing the event
stream doesn't already record with timestamps). Everything is keyed by
flat dotted names (``ph.gate_syncs``, ``qp.donated_passes``,
``hub.window_reads`` — see doc/observability.md for the catalog) so a
snapshot is directly JSON-serializable.

Counters are cumulative for the process lifetime: they deliberately
survive ``PHBase.reset_phase_timing`` (which zeroes the *wall-clock*
accumulators) so invariant tests can read "syncs per solve call" as a
pure counter ratio without monkeypatching engine internals.
"""

from __future__ import annotations

import threading


class Histogram:
    """Summary-statistics histogram: count/sum/min/max (+ last)."""

    __slots__ = ("count", "sum", "min", "max", "last")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self.last = None

    def observe(self, value: float):
        v = float(value)
        self.count += 1
        self.sum += v
        self.last = v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v

    def snapshot(self) -> dict:
        return {"count": self.count, "sum": self.sum, "min": self.min,
                "max": self.max, "last": self.last,
                "mean": (self.sum / self.count) if self.count else None}


class MetricsRegistry:
    """Counters, gauges, and histograms under one lock (hot-loop
    counter bumps can arrive from the chunk-spreading host threads and
    the spoke cylinder threads concurrently)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.histograms: dict[str, Histogram] = {}

    def counter_add(self, name: str, n=1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge_set(self, name: str, value: float):
        with self._lock:
            self.gauges[name] = float(value)

    def histogram_observe(self, name: str, value: float):
        with self._lock:
            h = self.histograms.get(name)
            if h is None:
                h = self.histograms[name] = Histogram()
            h.observe(value)

    def counters_snapshot(self) -> dict:
        with self._lock:
            return dict(self.counters)

    def counter_get(self, name):
        with self._lock:
            return self.counters.get(name, 0)

    def snapshot(self, nonblocking=False):
        """JSON-ready snapshot of every metric. With ``nonblocking``
        (signal-handler context: the interrupted frame may HOLD the
        lock), returns None instead of deadlocking when the lock is
        unavailable."""
        if nonblocking:
            if not self._lock.acquire(blocking=False):
                return None
        else:
            self._lock.acquire()
        try:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "histograms": {k: h.snapshot()
                               for k, h in self.histograms.items()},
            }
        finally:
            self._lock.release()
