"""Process-wide metrics registry: counters, gauges, histograms.

The registry is deliberately tiny — a dict per kind under one lock —
because it sits on the PH hot loop's host path: a counter bump is a
dict ``get`` + add, a gauge a dict store, and a histogram a handful of
scalar updates plus one bisect into a FIXED edge table. Everything is
keyed by flat dotted names (``ph.gate_syncs``, ``qp.donated_passes``,
``hub.window_reads`` — see doc/observability.md for the catalog) so a
snapshot is directly JSON-serializable.

Counters are cumulative for the process lifetime: they deliberately
survive ``PHBase.reset_phase_timing`` (which zeroes the *wall-clock*
accumulators) so invariant tests can read "syncs per solve call" as a
pure counter ratio without monkeypatching engine internals.
"""

from __future__ import annotations

import threading
from bisect import bisect_left

# Fixed log-spaced bucket edges for latency histograms: powers of two
# from ~1 µs to ~4096 s. Fixed (not adaptive) so two runs' snapshots
# are directly comparable bucket-for-bucket (`analyze --compare`), and
# so observe() costs one bisect into a shared tuple — no per-histogram
# allocation, no rebucketing. Span-duration observations land between
# sub-millisecond fused farmer phases and multi-minute reference-scale
# chunk solves, hence the wide range.
BUCKET_EDGES = tuple(2.0 ** e for e in range(-20, 13))


class Histogram:
    """Latency histogram: count/sum/min/max/last plus fixed-edge
    bucket counts, so ``snapshot()`` can report tail quantiles
    (p50/p95/p99) and not just means — a recovering chunk retry that
    doubles one iteration's gate time is invisible in a mean over 100
    iterations but owns the p99."""

    __slots__ = ("count", "sum", "min", "max", "last", "buckets")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self.last = None
        # buckets[i] counts observations in (BUCKET_EDGES[i-1],
        # BUCKET_EDGES[i]] — upper-INCLUSIVE, per-bucket counts (NOT
        # Prometheus-style cumulative); buckets[len(edges)] is the
        # +inf overflow bucket
        self.buckets = [0] * (len(BUCKET_EDGES) + 1)

    def observe(self, value: float):
        v = float(value)
        self.count += 1
        self.sum += v
        self.last = v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        # bisect_left: an exact-edge value lands in the bucket whose
        # UPPER edge it equals (upper-inclusive intervals)
        self.buckets[bisect_left(BUCKET_EDGES, v)] += 1

    def quantile(self, q: float):
        """Bucket-interpolated quantile in [0, 1]. Exact at the bucket
        boundaries, linear inside a bucket, clamped to observed
        min/max (so p50 of a single observation is that observation,
        not a bucket edge)."""
        if not self.count:
            return None
        rank = q * self.count
        seen = 0
        for i, n in enumerate(self.buckets):
            if n == 0:
                continue
            if seen + n >= rank:
                lo = 0.0 if i == 0 else BUCKET_EDGES[i - 1]
                hi = BUCKET_EDGES[i] if i < len(BUCKET_EDGES) \
                    else (self.max if self.max is not None else lo)
                frac = (rank - seen) / n
                v = lo + (hi - lo) * frac
                return min(max(v, self.min), self.max)
            seen += n
        return self.max

    def snapshot(self) -> dict:
        # keyed by upper edge; per-bucket counts, NOT cumulative (the
        # name says "upper edge", deliberately not Prometheus's
        # cumulative "le" convention)
        nonzero = {f"{BUCKET_EDGES[i]:g}" if i < len(BUCKET_EDGES)
                   else "+inf": n
                   for i, n in enumerate(self.buckets) if n}
        return {"count": self.count, "sum": self.sum, "min": self.min,
                "max": self.max, "last": self.last,
                "mean": (self.sum / self.count) if self.count else None,
                "p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99),
                "buckets_upper_edge": nonzero}


class MetricsRegistry:
    """Counters, gauges, and histograms under one lock (hot-loop
    counter bumps can arrive from the spoke cylinder threads and the
    hub's iteration concurrently)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.histograms: dict[str, Histogram] = {}

    def counter_add(self, name: str, n=1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge_set(self, name: str, value: float):
        with self._lock:
            self.gauges[name] = float(value)

    def histogram_observe(self, name: str, value: float):
        with self._lock:
            h = self.histograms.get(name)
            if h is None:
                h = self.histograms[name] = Histogram()
            h.observe(value)

    def counters_snapshot(self) -> dict:
        with self._lock:
            return dict(self.counters)

    def counter_get(self, name):
        with self._lock:
            return self.counters.get(name, 0)

    def histogram_get(self, name):
        """Snapshot of ONE histogram (None when it never observed) —
        the live status surface reads single tails without paying for
        a full registry snapshot."""
        with self._lock:
            h = self.histograms.get(name)
            return h.snapshot() if h is not None else None

    def snapshot(self, nonblocking=False):
        """JSON-ready snapshot of every metric. With ``nonblocking``
        (signal-handler context: the interrupted frame may HOLD the
        lock), returns None instead of deadlocking when the lock is
        unavailable."""
        if nonblocking:
            if not self._lock.acquire(blocking=False):
                return None
        else:
            self._lock.acquire()
        try:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "histograms": {k: h.snapshot()
                               for k, h in self.histograms.items()},
            }
        finally:
            self._lock.release()
