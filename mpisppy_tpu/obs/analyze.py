"""Post-hoc run diagnostics: ``python -m mpisppy_tpu analyze <dir>``.

The consumer half of the telemetry layer (the Diagnoser-for-artifacts
the reference ships as a live extension): given a ``--telemetry-dir``
run directory, render a run report — phase breakdown, convergence and
bound trajectory, compile/retrace and gate-sync counts, memory
watermarks, and invariant checks — entirely from the persisted
artifacts, so production runs are debuggable *after the fact* without
re-running anything.

``analyze --compare A B`` diffs two runs' headline metrics with
thresholded verdicts (exit code 3 on REGRESSION), which turns a pair
of bench telemetry dirs into a CI-checkable artifact. Runs whose
``run_header.schema`` versions differ are REFUSED (exit code 2)
instead of mis-parsed — bench.py stamps the same ``schema_version``
into its BENCH JSON rows for the same reason.

Pure host-side JSON work: no jax import, safe to run anywhere.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
from dataclasses import dataclass, field


# ---------------- loading ----------------

@dataclass
class Run:
    """One telemetry directory, parsed."""
    path: str
    header: dict                    # hub run_header (or first role's)
    events: list = field(default_factory=list)   # all events, hub first
    roles: dict = field(default_factory=dict)    # role -> its run_header
    metrics: dict = field(default_factory=dict)  # role ('' = hub) -> snap
    trace: dict | None = None
    bad_lines: int = 0
    # earlier sessions found in a REUSED dir (events.jsonl appends
    # across runs while trace/metrics overwrite): their events are
    # dropped so every artifact describes the same — last — run
    earlier_runs: int = 0

    @property
    def schema(self) -> int:
        return int(self.header.get("schema", 1))

    def of(self, etype, role=None):
        return [e for e in self.events if e.get("type") == etype
                and (role is None or e.get("_role") == role)]

    def counters(self, role=""):
        return (self.metrics.get(role) or {}).get("counters", {})

    def gauges(self, role=""):
        return (self.metrics.get(role) or {}).get("gauges", {})

    def histograms(self, role=""):
        return (self.metrics.get(role) or {}).get("histograms", {})


def _role_of(filename, stem, ext):
    base = os.path.basename(filename)
    inner = base[len(stem):-len(ext)]
    return inner[1:] if inner.startswith("-") else ""


def _rotated_chain(base):
    """A role's event files as ONE logical stream, oldest first:
    ``[base.N, ..., base.1, base]`` (size-capped rotation shifts older
    generations to numeric suffixes — obs/events.py)."""
    rotated = []
    for p in glob.glob(base + ".*"):
        suf = p[len(base) + 1:]
        if suf.isdigit():
            rotated.append((int(suf), p))
    return [p for _, p in sorted(rotated, reverse=True)] + [base]


def load_run(path) -> Run:
    """Parse a telemetry directory (hub artifacts + any role-suffixed
    spoke artifacts). Raises FileNotFoundError when no event stream
    exists — the one artifact every session writes. Rotated event
    files (``events.jsonl.1..N``) are re-chained oldest-first into the
    role's stream; their continuation headers (a ``run_header`` with a
    ``rotated`` field) are splice points, not new sessions."""
    ev_files = sorted(glob.glob(os.path.join(path, "events*.jsonl")),
                      key=lambda p: (os.path.basename(p) != "events.jsonl",
                                     p))
    if not ev_files:
        raise FileNotFoundError(
            f"no events*.jsonl under {path!r} — not a telemetry dir? "
            "(runs write one with --telemetry-dir / "
            "MPISPPY_TPU_TELEMETRY_DIR)")
    run = Run(path=path, header={})
    for base in ev_files:
        role = _role_of(base, "events", ".jsonl")
        file_events = []
        for f in _rotated_chain(base):
            try:
                fh = open(f, encoding="utf-8")
            except OSError:
                continue
            with fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        e = json.loads(line)
                    except ValueError:
                        run.bad_lines += 1
                        continue
                    e["_role"] = role
                    if e.get("type") == "run_header":
                        if e.get("rotated"):
                            # continuation header after a size-capped
                            # rotation: same session, keep reading (it
                            # still serves as the role header when the
                            # original rotated off the end of the cap)
                            if role not in run.roles:
                                run.roles[role] = e
                            continue
                        if file_events:
                            # a REUSED dir: events.jsonl appends across
                            # sessions while trace/metrics overwrite —
                            # keep only the LAST session so every
                            # artifact describes the same run (mixing
                            # them garbles trajectories and falsely
                            # fails the monotone-bounds invariant)
                            run.earlier_runs += 1
                            file_events = []
                        run.roles[role] = e
                    file_events.append(e)
        run.events.extend(file_events)
        head = run.roles.get(role)
        if head is not None and (not run.header or role == ""):
            run.header = head
    for f in sorted(glob.glob(os.path.join(path, "metrics*.json"))):
        role = _role_of(f, "metrics", ".json")
        try:
            with open(f, encoding="utf-8") as fh:
                run.metrics[role] = json.load(fh)
        except ValueError:
            run.bad_lines += 1
    if "" not in run.metrics:
        # a killed run may lack metrics.json; the footer carries the
        # same snapshot
        foot = run.of("run_footer", role="")
        if foot and isinstance(foot[-1].get("metrics"), dict):
            run.metrics[""] = foot[-1]["metrics"]
    tr = os.path.join(path, "trace.json")
    if os.path.exists(tr):
        try:
            with open(tr, encoding="utf-8") as fh:
                run.trace = json.load(fh)
        except ValueError:
            run.bad_lines += 1
    return run


# ---------------- derived metrics ----------------

def phase_breakdown(run: Run) -> dict:
    """{mode: {phase: {"seconds": total, "calls": n}}} from the trace's
    phase spans; falls back to the per-iteration records' phase deltas
    (mode-less) when no trace was captured."""
    phases = ("ph.assemble", "ph.solve", "ph.gate", "ph.reduce")
    out = {}
    if run.trace:
        for ev in run.trace.get("traceEvents", ()):
            if ev.get("ph") != "X" or ev.get("name") not in phases:
                continue
            mode = (ev.get("args") or {}).get("mode", "?")
            ent = out.setdefault(mode, {})
            ph = ent.setdefault(ev["name"][3:],
                                {"seconds": 0.0, "calls": 0})
            ph["seconds"] += ev.get("dur", 0.0) / 1e6
            ph["calls"] += 1
    if not out:
        for e in run.of("ph.iteration"):
            ps = e.get("phase_seconds")
            if not isinstance(ps, dict):
                continue
            ent = out.setdefault("(from iteration records)", {})
            for k, v in ps.items():
                ph = ent.setdefault(k, {"seconds": 0.0, "calls": 0})
                ph["seconds"] += v
                ph["calls"] += 1
    return out


def iteration_rows(run: Run) -> list:
    """Per-iteration convergence rows (schema-2 ``ph.iteration``
    records; schema-1 streams carried iter/conv only)."""
    return [e for e in run.of("ph.iteration") if "iter" in e]


def bound_trajectory(run: Run) -> dict:
    t0 = run.header.get("t", 0.0)
    traj = {"outer": [], "inner": []}
    for e in run.of("hub.bound"):
        kind = e.get("kind")
        if kind in traj:
            traj[kind].append((e.get("t", t0) - t0, e.get("char"),
                               e.get("value")))
    return traj


def memory_watermarks(run: Run) -> dict:
    """{role: {device: peak_bytes}} from the mem.* gauges."""
    out = {}
    for role in run.metrics:
        devs = {}
        for name, v in run.gauges(role).items():
            if name.startswith("mem.") \
                    and name.endswith(".peak_bytes_in_use"):
                devs[name.split(".")[1]] = v
        if devs:
            out[role] = devs
    return out


def compile_summary(run: Run) -> dict:
    c = run.counters()
    h = run.histograms().get("jax.compile_seconds", {})
    entries = sorted(((k[len("jax.compile.entry."):], v)
                      for k, v in c.items()
                      if k.startswith("jax.compile.entry.")),
                     key=lambda kv: -kv[1])
    late = [e["iter"] for e in iteration_rows(run)
            if e.get("counter_deltas", {}).get("jax.compiles")
            and e["iter"] > 1]
    return {"compiles": c.get("jax.compiles", 0),
            "traces": c.get("jax.traces", 0),
            "compile_seconds_total": h.get("sum", 0.0) or 0.0,
            "compile_seconds_p99": h.get("p99"),
            "entries": entries,
            "late_retrace_iters": late}


def sharding_summary(run: Run) -> dict | None:
    """The scenario-axis sharding anatomy of a run (ISSUE 6): device
    count and shard size from the ``ph.iteration`` records' sharding
    block (falling back to ``hub.start``), plus the collective-traffic
    estimate from the ``xfer.collective_bytes`` counter. None when the
    run never sharded."""
    info = None
    iters = 0
    dp_iter = 0
    for e in iteration_rows(run):
        sh = e.get("sharding")
        if isinstance(sh, dict):
            info = sh
            iters += 1
            dp_iter += (e.get("counter_deltas") or {}).get(
                "xfer.device_put_bytes", 0)
    if info is None:
        for e in run.of("hub.start"):
            sh = e.get("sharding")
            if isinstance(sh, dict):
                info = sh
    if info is None:
        return None
    c = run.counters()
    out = dict(info)
    out["collective_bytes_total"] = c.get("xfer.collective_bytes", 0)
    if iters:
        out["collective_bytes_per_iter"] = \
            out["collective_bytes_total"] / iters
    # total includes the legitimate one-time initial shard placement;
    # the ITERATION sum is the steady-state placement contract (must
    # be zero — doc/sharding.md)
    out["device_put_bytes_total"] = c.get("xfer.device_put_bytes", 0)
    out["device_put_bytes_iterations"] = dp_iter
    return out


def fault_summary(run: Run) -> dict:
    """The supervision/ingest-validation story of a run (counters from
    the hub role, per-spoke detail from the events): downs, respawns,
    quarantines, rejected payloads, watchdog — and the derived
    ``degraded`` flag (doc/fault_tolerance.md)."""
    c = run.counters()
    downs = run.of("hub.spoke_down")
    respawns = run.of("hub.spoke_respawn")
    quars = run.of("hub.spoke_quarantined")
    rejects = run.of("hub.bound_rejected")
    watchdog = run.of("hub.watchdog_fired")
    # supervisor events carry the SPOKE kind ("lagrangian"); rejection
    # events carry the BOUND kind ("outer"/"inner"/"cuts") — key rows
    # by spoke index and resolve the spoke kind from the supervisor
    # events, so one spoke's crashes and rejections land on ONE row
    spoke_kind = {e.get("spoke"): e.get("kind", "?")
                  for e in (*downs, *respawns, *quars)
                  if e.get("spoke") is not None}
    per_spoke = {}
    for field_name, evs in (("downs", downs), ("respawns", respawns),
                            ("quarantined", quars),
                            ("rejected", rejects)):
        for e in evs:
            i = e.get("spoke")
            key = "hub" if i is None \
                else f"spoke{i}-{spoke_kind.get(i, '?')}"
            ent = per_spoke.setdefault(key, {"downs": 0, "respawns": 0,
                                             "quarantined": 0,
                                             "rejected": 0,
                                             "reasons": []})
            ent[field_name] += 1
            r = e.get("reason") or e.get("cause")
            if r and r not in ent["reasons"]:
                ent["reasons"].append(r)
    out = {
        # counters are authoritative when metrics survived; a killed
        # run falls back to counting the streamed events
        "downs": int(c.get("hub.spoke_down", 0) or len(downs)),
        "respawns": int(c.get("hub.spoke_respawn", 0) or len(respawns)),
        "quarantined": int(c.get("hub.spoke_quarantined", 0)
                           or len(quars)),
        "rejected_payloads": int(c.get("hub.bound_rejected", 0)
                                 or len(rejects)),
        "crossed_rejections": int(c.get("hub.bound_crossed", 0) or
                                  sum(1 for e in rejects
                                      if e.get("reason") == "crossed")),
        "watchdog_fired": bool(c.get("hub.watchdog_fired", 0)
                               or watchdog),
        "watchdog": (watchdog[-1] if watchdog else None),
        "per_spoke": per_spoke,
    }
    out["degraded"] = bool(out["downs"] or out["quarantined"]
                           or out["rejected_payloads"]
                           or out["watchdog_fired"])
    return out


def incumbent_summary(run: Run) -> dict | None:
    """Device incumbent-pool activity (ops/incumbent, doc/incumbents.md):
    ``incumbent.*`` counters summed across roles (the dive spoke runs
    in its own process in a multi-process wheel, so its counters land
    in a role-suffixed metrics snapshot) plus the per-round
    ``incumbent.round`` event trajectory. None when no pool ever ran —
    the section only renders for wheels with a pool-driven spoke."""
    tot = {}
    for role in run.metrics:
        for k, v in run.counters(role).items():
            if k.startswith("incumbent."):
                tot[k] = tot.get(k, 0) + v
    rounds_ev = run.of("incumbent.round")
    if not tot and not rounds_ev:
        return None
    rounds = int(tot.get("incumbent.rounds", 0)) or len(rounds_ev)
    evaluated = int(tot.get("incumbent.candidates_evaluated", 0))
    improvements = int(tot.get("incumbent.improvements", 0))
    return {
        "rounds": rounds,
        # pool throughput: candidates per round (the static pool size
        # whenever at least one round completed its evaluation)
        "pool_size": (evaluated // rounds) if rounds else 0,
        "candidates_evaluated": evaluated,
        "feasible": int(tot.get("incumbent.feasible", 0)),
        "improvements": improvements,
        "accept_rate": (improvements / rounds) if rounds else 0.0,
        "pool_reused": int(tot.get("incumbent.pool_reused", 0)),
        "oracle_polish": int(tot.get("incumbent.oracle_polish", 0)),
        "gate_syncs": int(tot.get("incumbent.gate_syncs", 0)),
        "trajectory": [
            {"round": e.get("round"), "best": e.get("best"),
             "bound": e.get("bound"),
             "improved": bool(e.get("improved"))}
            for e in rounds_ev],
    }


def shrink_summary(run: Run) -> dict | None:
    """Progressive-shrinking activity (ops/shrink, doc/extensions.md
    §shrinking): the fixed-fraction trajectory off the per-iteration
    records' ``shrink`` blocks, compaction events, per-bucket s/iter
    means, and the est-HBM drop — the ISSUE 14 acceptance evidence
    that per-iteration cost tracks the ACTIVE set. None when shrinking
    never ran."""
    tot = {}
    for role in run.metrics:
        for k, v in run.counters(role).items():
            if k.startswith("shrink."):
                tot[k] = tot.get(k, 0) + v
    compactions = run.of("shrink.compaction")
    fixes = run.of("shrink.fix")
    transplants = run.of("shrink.transplant")
    rows = [e for e in iteration_rows(run) if e.get("shrink")]
    if not tot and not compactions and not fixes and not rows:
        return None
    traj = [{"iter": e["iter"],
             "fixed": e["shrink"].get("fixed"),
             "free": e["shrink"].get("free"),
             "bucket": e["shrink"].get("bucket"),
             "seconds": e.get("seconds"),
             "est_hbm_bytes_per_iter":
                 e["shrink"].get("est_hbm_bytes_per_iter")}
            for e in rows]
    # per-bucket s/iter: group the record stream by the bucket active
    # when each iteration ran — the post-compaction drop is the win
    per_bucket = {}
    for t in traj:
        if isinstance(t.get("seconds"), (int, float)):
            b = t.get("bucket") or 0.0
            per_bucket.setdefault(b, []).append(t["seconds"])
    bucket_rows = [
        {"bucket": b, "iters": len(v), "s_per_iter": sum(v) / len(v),
         "est_hbm_bytes_per_iter": next(
             (t["est_hbm_bytes_per_iter"] for t in traj
              if (t.get("bucket") or 0.0) == b
              and t.get("est_hbm_bytes_per_iter") is not None), None)}
        for b, v in sorted(per_bucket.items())]
    # per-bucket post-transition re-convergence (ISSUE 17): a bucket
    # transition rebuilds the per-scenario ADMM states — warm (the
    # cross-bucket transplant pulled the old bucket's iterates) or
    # cold (a guard booked shrink.transplant_cold_fallbacks). The
    # recovery cost is measured in PH iterations: conv at the
    # transition iteration is the pre level (the compaction lands in
    # that iteration's miditer, so its record still reflects the old
    # system), and recovery is the first later iteration whose conv is
    # back at or under it. Warm should recover in strictly fewer
    # iterations — the --compare cold-fallback verdict reads the
    # counter, this table shows the price actually paid.
    all_rows = [e for e in iteration_rows(run)
                if isinstance(e.get("conv"), (int, float))]
    warm_buckets = {e.get("bucket") for e in transplants}
    reconvergence = []
    for ev in compactions:
        t = ev.get("iter")
        if t is None:
            continue
        pre = next((e["conv"] for e in reversed(all_rows)
                    if e["iter"] <= t), None)
        recovered = None
        if pre is not None:
            recovered = next((e["iter"] for e in all_rows
                              if e["iter"] > t and e["conv"] <= pre),
                             None)
        reconvergence.append({
            "bucket": ev.get("bucket"), "iter": t,
            "mode": ("warm" if ev.get("bucket") in warm_buckets
                     else "cold"),
            "pre_conv": pre,
            "recovered_iter": recovered,
            "iters_to_reconverge":
                (recovered - t) if recovered is not None else None})
    return {
        "fixed_final": (traj[-1]["fixed"] if traj else None),
        "free_final": (traj[-1]["free"] if traj else None),
        "fixed_new_total": int(tot.get("shrink.fixed_new", 0)),
        "compactions": int(tot.get("shrink.compactions", 0))
        or len(compactions),
        "compaction_skipped": int(tot.get("shrink.compaction_skipped",
                                          0)),
        "rho_updates": int(tot.get("shrink.rho_updates", 0)),
        "bucket_compiles": int(tot.get("shrink.bucket.compile", 0)),
        "bucket_cache_hits": int(tot.get("shrink.bucket.cache_hit", 0)),
        "transplants": int(tot.get("shrink.transplants", 0)),
        "transplant_cold_fallbacks":
            int(tot.get("shrink.transplant_cold_fallbacks", 0)),
        "reconvergence": reconvergence,
        "compaction_events": [
            {"iter": e.get("iter"), "bucket": e.get("bucket"),
             "n_cols": e.get("n_cols"), "m_rows": e.get("m_rows"),
             "n_full": e.get("n_full"), "m_full": e.get("m_full"),
             "fingerprint": e.get("fingerprint"),
             "bucket_cached": e.get("bucket_cached")}
            for e in compactions],
        "per_bucket": bucket_rows,
        "trajectory": traj,
    }


def truncated(run: Run) -> bool:
    """True when the hub never wrote its ``run_footer`` — the run was
    killed before shutdown. Every report/compare section stamps this
    uniformly (``TRUNCATED RUN``) so partial artifacts read as partial
    instead of section-dependent silence."""
    return not run.of("run_footer", role="")


def roofline_summary(run: Run) -> dict | None:
    """The measured roofline (obs/profile.py, doc/roofline.md): device
    peaks from the ``profile.device`` event, per-iteration /
    per-bucket / per-mode MFU and HBM-bandwidth utilization from the
    ``profile.flops`` / ``profile.hbm_bytes`` counter deltas joined
    against the span timeline, the per-entry static cost models, and
    the compile ledger (which must sum to ``jax.compiles``). None when
    the run never profiled (telemetry off or pre-profile artifacts)."""
    c = run.counters()
    dev_events = run.of("profile.device")
    entry_events = run.of("profile.entry")
    if not dev_events and not entry_events \
            and not any(k.startswith("profile.") for k in c):
        return None
    dev = {}
    if dev_events:
        dev = {k: v for k, v in dev_events[-1].items()
               if k not in ("t", "type", "_role")}
    peak_f = dev.get("peak_flops") or 0.0
    peak_g = dev.get("peak_hbm_gbps") or 0.0
    per_iter = []
    per_bucket = {}
    per_mode = {}
    solve_flops = solve_secs = 0.0
    tot_flops = tot_bytes = tot_secs = 0.0
    for e in iteration_rows(run):
        cd = e.get("counter_deltas") or {}
        fl = float(cd.get("profile.flops", 0) or 0)
        by = float(cd.get("profile.hbm_bytes", 0) or 0)
        if not fl and not by:
            continue
        secs = e.get("seconds")
        if not isinstance(secs, (int, float)) or secs <= 0:
            continue
        row = {"iter": e.get("iter"), "seconds": secs, "flops": fl,
               "hbm_bytes": by,
               "mfu": (fl / secs / peak_f) if peak_f else None,
               "hbm_gbps": by / secs / 1e9,
               "hbm_util": (by / secs / 1e9 / peak_g) if peak_g
               else None}
        per_iter.append(row)
        tot_flops += fl
        tot_bytes += by
        tot_secs += secs
        ps = e.get("phase_seconds") or {}
        sv = ps.get("solve")
        if isinstance(sv, (int, float)) and sv > 0:
            solve_flops += fl
            solve_secs += sv
        # bucket = the shrink bucket active when the iteration ran
        # (the shrink_summary grouping); 0.0 = the full-width system
        shr = e.get("shrink") or {}
        b = shr.get("bucket") or 0.0
        ent = per_bucket.setdefault(
            b, {"flops": 0.0, "hbm_bytes": 0.0, "seconds": 0.0,
                "iters": 0, "est_hbm_bytes_per_iter": None})
        ent["flops"] += fl
        ent["hbm_bytes"] += by
        ent["seconds"] += secs
        ent["iters"] += 1
        if ent["est_hbm_bytes_per_iter"] is None:
            ent["est_hbm_bytes_per_iter"] = \
                shr.get("est_hbm_bytes_per_iter")
        # engine mode, classified the way kernel_summary does: a
        # kernel.fused_iters delta marks a fused iteration
        mode = "fused" if cd.get("kernel.fused_iters") else "segmented"
        m = per_mode.setdefault(
            mode, {"flops": 0.0, "hbm_bytes": 0.0, "seconds": 0.0,
                   "iters": 0})
        m["flops"] += fl
        m["hbm_bytes"] += by
        m["seconds"] += secs
        m["iters"] += 1

    def _figures(fl, by, secs):
        if secs <= 0:
            return {"mfu": None, "hbm_gbps": None, "hbm_util": None}
        gbps = by / secs / 1e9
        return {"mfu": (fl / secs / peak_f) if peak_f else None,
                "hbm_gbps": gbps,
                "hbm_util": (gbps / peak_g) if peak_g else None}

    bucket_rows = []
    for b, ent in sorted(per_bucket.items()):
        row = {"bucket": b, "iters": ent["iters"],
               "s_per_iter": ent["seconds"] / ent["iters"],
               "flops_per_iter": ent["flops"] / ent["iters"],
               "hbm_bytes_per_iter": ent["hbm_bytes"] / ent["iters"],
               "est_hbm_bytes_per_iter": ent["est_hbm_bytes_per_iter"]}
        row.update(_figures(ent["flops"], ent["hbm_bytes"],
                            ent["seconds"]))
        bucket_rows.append(row)
    mode_rows = {}
    for m, ent in sorted(per_mode.items()):
        row = {"iters": ent["iters"],
               "flops_per_iter": ent["flops"] / ent["iters"],
               "hbm_bytes_per_iter": ent["hbm_bytes"] / ent["iters"]}
        row.update(_figures(ent["flops"], ent["hbm_bytes"],
                            ent["seconds"]))
        mode_rows[m] = row
    ledger = {}
    for k, v in c.items():
        if k.startswith("profile.ledger.compiles."):
            key = k[len("profile.ledger.compiles."):]
            ledger.setdefault(key, {"compiles": 0, "seconds": 0.0})
            ledger[key]["compiles"] = int(v)
        elif k.startswith("profile.ledger.seconds."):
            key = k[len("profile.ledger.seconds."):]
            ledger.setdefault(key, {"compiles": 0, "seconds": 0.0})
            ledger[key]["seconds"] = float(v)
    ledger_compiles = sum(e["compiles"] for e in ledger.values())
    jax_compiles = int(c.get("jax.compiles", 0))
    overall = {"flops_total": tot_flops, "hbm_bytes_total": tot_bytes,
               "seconds_total": tot_secs, "iters": len(per_iter)}
    overall.update(_figures(tot_flops, tot_bytes, tot_secs))
    solve = {"flops_total": solve_flops, "seconds_total": solve_secs}
    solve.update(_figures(solve_flops, 0.0, solve_secs))
    solve.pop("hbm_gbps", None)
    solve.pop("hbm_util", None)
    unavailable = [{k: v for k, v in e.items()
                    if k in ("entry", "reason")}
                   for e in run.of("profile.unavailable")]
    return {
        "device": dev or None,
        "overall": overall,
        "solve_phase": solve if solve_secs > 0 else None,
        "per_bucket": bucket_rows,
        "per_mode": mode_rows,
        "per_iteration": per_iter,
        "entries": [{k: v for k, v in e.items()
                     if k not in ("t", "type", "_role")}
                    for e in entry_events],
        "captures": int(c.get("profile.captures", 0)),
        "ledger": ledger,
        "ledger_compiles": ledger_compiles,
        "jax_compiles": jax_compiles,
        "ledger_matches": ledger_compiles == jax_compiles,
        "unavailable_count": int(c.get("profile.unavailable", 0)),
        "unavailable": unavailable,
    }


def streaming_summary(run: Run) -> dict | None:
    """Scenario-streaming activity (mpisppy_tpu/stream,
    doc/streaming.md): the source kind, bytes shipped vs chunks
    synthesized, prefetch occupancy (how often the consumer outran the
    double buffer), int8 gate fallbacks, and THE acceptance signal —
    whether the per-iteration ``xfer.device_put_bytes`` deltas stayed
    flat across steady-state iterations. None when no scenario source
    ran."""
    tot = {}
    for role in run.metrics:
        for k, v in run.counters(role).items():
            if k.startswith("stream."):
                tot[k] = tot.get(k, 0) + v
    rows = [e for e in iteration_rows(run) if e.get("stream")]
    if not tot and not rows:
        return None
    source = rows[-1]["stream"].get("source") if rows else None
    chunks = int(tot.get("stream.chunks_shipped", 0))
    synth = int(tot.get("stream.synth_chunks", 0))
    stalls = int(tot.get("stream.prefetch_stalls", 0))
    staged = chunks + synth
    # per-iteration device_put deltas from the counter_deltas blocks:
    # steady state starts at the SECOND recorded iteration (iteration 1
    # builds the mode's cold chunk states — one direct fetch)
    per_iter = [
        {"iter": e["iter"],
         "device_put_bytes":
             e.get("counter_deltas", {}).get("xfer.device_put_bytes", 0),
         "bytes_shipped":
             e.get("counter_deltas", {}).get("stream.bytes_shipped", 0),
         "synth_chunks":
             e.get("counter_deltas", {}).get("stream.synth_chunks", 0),
         "compacted_transitions":
             e.get("counter_deltas", {}).get(
                 "stream.compacted_transitions", 0)}
        for e in iteration_rows(run)]
    # steady state starts after the LAST compacted re-block (ISSUE 17
    # shrink×stream): a transition legitimately changes the shipped
    # width (and pays its one out-of-band restage), so flatness is
    # judged on the iterations solving the final layout — otherwise
    # every compacted streamed wheel would read as a leak
    start = 1
    for i, r_ in enumerate(per_iter):
        if r_["compacted_transitions"]:
            start = max(start, i + 1)
    steady = [r["device_put_bytes"] for r in per_iter[start:]]
    return {
        "source": source,
        "chunks_shipped": chunks,
        "bytes_shipped": int(tot.get("stream.bytes_shipped", 0)),
        "synth_chunks": synth,
        "direct_fetches": int(tot.get("stream.direct_fetches", 0)),
        "int8_fallbacks": int(tot.get("stream.int8_fallbacks", 0)),
        "compacted_transitions":
            int(tot.get("stream.compacted_transitions", 0)),
        "compacted_restage_bytes":
            int(tot.get("stream.compacted_restage_bytes", 0)),
        "prefetch_stalls": stalls,
        # fraction of staged chunks the prefetcher had ready before the
        # consumer asked — 1.0 means the H2D fully hid under compute
        "prefetch_occupancy":
            (1.0 - stalls / staged) if staged else None,
        "device_put_flat_steady_state":
            (len(set(steady)) <= 1) if len(steady) >= 2 else None,
        "per_iteration": per_iter,
    }


def aph_summary(run: Run) -> dict | None:
    """APH φ-dispatch activity (core/aph.py + ops/dispatch.py,
    doc/aph.md): the dispatched-fraction trajectory, φ histogram
    stats, skipped-solve savings, dispatch-bucket compile behavior,
    and THE pacing signal — gate syncs per iteration (the stacked-gate
    contract says exactly one D2H per APH iteration). None when no
    APH wheel ran — the section only renders for APH telemetry."""
    tot = {}
    for role in run.metrics:
        for k, v in run.counters(role).items():
            if k.startswith(("aph.", "dispatch.")):
                tot[k] = tot.get(k, 0) + v
    rows = [e for e in iteration_rows(run) if e.get("aph")]
    if not tot and not rows:
        return None
    traj = [{"iter": e["iter"],
             "frac": e["aph"].get("frac"),
             "dispatched": e["aph"].get("dispatched"),
             "S_real": e["aph"].get("S_real"),
             "solve_path": e["aph"].get("solve_path"),
             "phi_min": e["aph"].get("phi_min"),
             "phi_max": e["aph"].get("phi_max"),
             "phi_neg": e["aph"].get("phi_neg")}
            for e in rows]
    iters = len(rows)
    syncs = int(tot.get("aph.gate_syncs", 0))
    solved = int(tot.get("dispatch.solved_scenarios", 0))
    skipped = int(tot.get("dispatch.skipped_scenarios", 0))
    last = rows[-1]["aph"] if rows else {}
    return {
        "iterations": iters,
        "dispatch_frac": last.get("frac"),
        "solve_path": last.get("solve_path"),
        "gate_syncs": syncs,
        # the O(1)-host-traffic acceptance signal: must sit at ~1.0
        "gate_syncs_per_iteration": (syncs / iters) if iters else None,
        "solved_scenarios": solved,
        "skipped_scenarios": skipped,
        # fraction of scenario-solves partial dispatch saved outright
        "skipped_solve_savings":
            (skipped / (solved + skipped)) if (solved + skipped) else None,
        "solved_per_iteration": (solved / iters) if iters else None,
        "bucket_compiles": int(tot.get("dispatch.bucket.compile", 0)),
        "bucket_cache_hits": int(tot.get("dispatch.bucket.cache_hit", 0)),
        "phi_neg_final": last.get("phi_neg"),
        "trajectory": traj,
    }


def forensics_summary(run: Run) -> dict | None:
    """Wheel forensics (ops/forensics.py + obs/diagnose.py,
    doc/forensics.md): the per-slot/per-scenario attribution samples
    off the iteration records (or the dedicated ``forensics.sample``
    stream on merged multi-role runs), the hub bound trajectory, and a
    POST-MORTEM re-run of the same pure diagnosis rules the live
    engine uses — a recorded stall is re-attributed even when the run
    died before the live engine fired. None when the run carries no
    forensic data at all."""
    from . import diagnose as _diagnose
    samples = []
    for e in iteration_rows(run):
        fx = e.get("forensics")
        if isinstance(fx, dict):
            samples.append(fx)
    if not samples:
        samples = [e for e in run.of("forensics.sample")
                   if e.get("it") is not None]
    verdict_events = [
        {"it": e.get("it"), "verdict": e.get("verdict"),
         "prev": e.get("prev"), "summary": e.get("summary"),
         "evidence": e.get("evidence")}
        for e in run.of("forensics.verdict")]
    bound_checks = [
        {"it": e.get("iter"), "outer": e.get("outer"),
         "inner": e.get("inner"), "rel_gap": e.get("rel_gap"),
         "spoke": None}
        for e in run.of("hub.iteration")]
    if not samples and not verdict_events:
        return None
    # stalled-outer spoke attribution, post-mortem: the char that
    # produced the last outer-bound publish (screen rows stop when
    # bounds freeze, so the LAST one names the spoke that froze);
    # merged runs fall back to the live engine's recorded attribution
    spoke = None
    for e in reversed(run.of("hub.screen_row")):
        ch = e.get("ob_char")
        if isinstance(ch, str) and ch.strip():
            spoke = _diagnose.SPOKE_CHARS.get(ch, ch)
            break
    if spoke is None:
        for v in reversed(verdict_events):
            sp = (v.get("evidence") or {}).get("spoke")
            if sp:
                spoke = sp
                break
    for b in bound_checks:
        b["spoke"] = spoke
    verdicts = _diagnose.diagnose(samples, bound_checks)
    last = samples[-1] if samples else {}
    return {
        "verdict": _diagnose.overall(verdicts),
        "verdicts": verdicts,
        "samples": len(samples),
        "bound_checks": len(bound_checks),
        "verdict_events": verdict_events,
        "last": {
            "it": last.get("it"), "conv": last.get("conv"),
            "osc_mean": last.get("osc_mean"),
            "rho_log_ratio_mean": last.get("rho_log_ratio_mean"),
            "xbar_move": last.get("xbar_move"),
            "top_slots": last.get("top_slots"),
            "scen_pri_shares": last.get("scen_pri_shares"),
            "scen_dua_shares": last.get("scen_dua_shares"),
        } if samples else None,
    }


def checkpoint_summary(run: Run) -> dict | None:
    """Durable checkpoint activity (mpisppy_tpu.ckpt,
    doc/fault_tolerance.md): ``ckpt.*`` counters summed across roles
    (spoke warm-state writes land in spoke roles), the capture
    trajectory, resume provenance, and rejected-bundle reasons. None
    when checkpointing never ran — the section only renders for
    checkpointing wheels."""
    tot = {}
    for role in run.metrics:
        for k, v in run.counters(role).items():
            if k.startswith("ckpt."):
                tot[k] = tot.get(k, 0) + v
    captures = run.of("ckpt.capture")
    resumes = run.of("ckpt.resume")
    rejected = run.of("ckpt.resume_rejected")
    preempts = run.of("hub.preempted")
    if not tot and not captures and not resumes and not rejected:
        return None
    rej_reasons = {}
    for k, v in tot.items():
        if k.startswith("ckpt.rejected."):
            rej_reasons[k[len("ckpt.rejected."):]] = \
                rej_reasons.get(k[len("ckpt.rejected."):], 0) + int(v)
    for e in rejected:
        rej_reasons.setdefault(e.get("reason"), 0)
    last = captures[-1] if captures else {}
    return {
        "captures": int(tot.get("ckpt.captures", 0)) or len(captures),
        "write_failed": int(tot.get("ckpt.write_failed", 0)),
        "spoke_writes": int(tot.get("ckpt.spoke_writes", 0)),
        "last_bundle": last.get("bundle"),
        "last_iter": last.get("iter"),
        "reasons": sorted({e.get("reason") for e in captures
                           if e.get("reason")}),
        "resumed": bool(resumes)
        or bool(int(tot.get("ckpt.resumed", 0))),
        "resume": (resumes[-1] if resumes else None),
        "spoke_resumed": int(tot.get("ckpt.spoke_resumed", 0)),
        "rejected": rej_reasons,
        "preempted": bool(preempts)
        or bool(run.counters().get("hub.preempted")),
    }


def serving_summary(run: Run) -> dict | None:
    """Serving-layer activity (mpisppy_tpu/serve, doc/serving.md):
    request admission/outcome totals, warm-cache hit ratio, the batch
    occupancy histogram, and per-bucket compile counts. None when the
    run never served — the section only renders for serve-process
    telemetry dirs."""
    tot = {}
    for role in run.metrics:
        for k, v in run.counters(role).items():
            if k.startswith("serve."):
                tot[k] = tot.get(k, 0) + v
    if not tot and not run.of("serve.start"):
        return None
    hits = int(tot.get("serve.cache.hit", 0))
    misses = int(tot.get("serve.cache.miss", 0))
    per_bucket = {k[len("serve.bucket.compiles."):]: int(v)
                  for k, v in tot.items()
                  if k.startswith("serve.bucket.compiles.")}
    occ = None
    for role in run.metrics:
        h = run.histograms(role).get("serve.batch.occupancy")
        if h:
            occ = h
            break
    # migration ledger (doc/serving.md): every offer settles in the
    # SAME process as exactly one of handed_off / aborted.<reason>, so
    # summed-across-roles totals must reconcile — a gap means an offer
    # path returned without booking its outcome
    mig_offered = int(tot.get("serve.migrate.offered", 0))
    mig_aborted = {k[len("serve.migrate.aborted."):]: int(v)
                   for k, v in tot.items()
                   if k.startswith("serve.migrate.aborted.")}
    mig_rejected = {k[len("serve.migrate.rejected."):]: int(v)
                    for k, v in tot.items()
                    if k.startswith("serve.migrate.rejected.")}
    migration = None
    if mig_offered or mig_aborted or tot.get("serve.migrate.committed"):
        handed = int(tot.get("serve.migrate.handed_off", 0))
        migration = {
            "offered": mig_offered,
            "handed_off": handed,
            "accepted": int(tot.get("serve.migrate.accepted", 0)),
            "committed": int(tot.get("serve.migrate.committed", 0)),
            "completed": int(tot.get("serve.migrate.completed", 0)),
            "aborted": mig_aborted,
            "rejected": mig_rejected,
            "reconciled": mig_offered == handed
            + sum(mig_aborted.values()),
        }
    return {
        "admitted": int(tot.get("serve.requests.admitted", 0)),
        "completed": int(tot.get("serve.requests.completed", 0)),
        "failed": int(tot.get("serve.requests.failed", 0)),
        "rejected": int(tot.get("serve.requests.rejected", 0)),
        "deadline_missed": int(tot.get("serve.requests.deadline_missed",
                                       0)),
        "preempted_requests": int(tot.get("serve.requests.preempted",
                                          0)),
        "resumed": int(tot.get("serve.requests.resumed", 0)),
        "wheels": int(tot.get("serve.wheels", 0)),
        "stacked_wheels": int(tot.get("serve.batch.wheels", 0)),
        "coalesced": int(tot.get("serve.batch.coalesced", 0)),
        "chain_steps": int(tot.get("serve.chain.steps", 0)),
        "cache_hits": hits, "cache_misses": misses,
        "cache_evictions": int(tot.get("serve.cache.evict", 0)),
        "cache_hit_ratio": (hits / (hits + misses))
        if hits + misses else None,
        "batch_occupancy": occ,
        "per_bucket_compiles": per_bucket,
        "service_preempted": bool(int(tot.get("serve.preempted", 0))),
        "drained": bool(int(tot.get("serve.drained", 0))),
        "quarantined": int(tot.get("serve.request.quarantined", 0)),
        "migration": migration,
    }


def bound_flow_summary(run: Run) -> dict | None:
    """Per-spoke bound-flow ledger + verdict — the live-plane answer to
    ROADMAP item 1's diagnostic question ("is the Lagrangian spoke
    starved, too slow, or having its bounds rejected?"). Assembled from
    three independent sources so a killed run still renders:

    - hub metrics: ``hub.spoke.produced_writes/.consumed_writes/.lag``
      gauges, ``hub.spoke.staleness_seconds`` histograms,
      ``hub.spoke.bounds_accepted/.bounds_rejected`` counters,
    - spoke ROLE metrics: ``spoke.bound_updates`` (the spoke-side
      publish truth, summed across respawned generations) and the
      ``spoke.bound_interval_seconds`` cadence histogram,
    - the ``hub.iteration`` events' ``flow`` time series (produced vs
      consumed at every termination check — the silent-starvation
      signal).

    Verdicts (doc/observability.md documents the thresholds):
    REJECTED — the hub quarantined at least as many of this spoke's
    payloads as it accepted; STARVED — publishes advance while hub
    consumption stays flat (streak in the flow series), or the hub
    missed at least half of ≥4 publishes (window overwrites), or
    publishes were never consumed at all; SLOW — the spoke published
    ≤1 bound across ≥10 hub checks, or its publish cadence p50 is
    >5x the hub's iteration p50; HEALTHY otherwise. None when the run
    carries no flow data at all (pre-live-plane artifacts)."""
    spokes: dict[str, dict] = {}
    # verdicts need HUB-SIDE lineage evidence (flow gauges/counters/
    # histograms or the hub.iteration flow series). Spoke-role
    # counters alone (spoke.bound_updates exists since PR 3) must NOT
    # suffice: a pre-live-plane dir would otherwise read "published
    # but never consumed" — a false STARVED on every healthy old run.
    got_hub_flow = False
    g, c, hists = run.gauges(), run.counters(), run.histograms()
    for name, v in g.items():
        for prefix, key in (("hub.spoke.produced_writes.", "produced"),
                            ("hub.spoke.consumed_writes.", "consumed"),
                            ("hub.spoke.lag.", "lag")):
            if name.startswith(prefix):
                spokes.setdefault(name[len(prefix):], {})[key] = int(v)
                got_hub_flow = True
    for name, v in c.items():
        for prefix, key in (("hub.spoke.bounds_accepted.", "accepted"),
                            ("hub.spoke.bounds_rejected.", "rejected")):
            if name.startswith(prefix):
                spokes.setdefault(name[len(prefix):], {})[key] = int(v)
                got_hub_flow = True
    for name, h in hists.items():
        pre = "hub.spoke.staleness_seconds."
        if name.startswith(pre) and isinstance(h, dict):
            ent = spokes.setdefault(name[len(pre):], {})
            ent["staleness_p50"] = h.get("p50")
            ent["staleness_p99"] = h.get("p99")
            got_hub_flow = True
    # spoke-side truth from the role artifacts (summed across
    # respawned generations: role "spoke0-lagrangian-r1" -> "spoke0")
    for role in run.metrics:
        if not role.startswith("spoke"):
            continue
        label, _, kind = role.partition("-")
        ent = spokes.setdefault(label, {})
        if kind:
            ent.setdefault("kind", kind.split("-")[0])
        rc = run.counters(role)
        ent["published"] = ent.get("published", 0) \
            + int(rc.get("spoke.bound_updates", 0))
        hh = run.histograms(role).get("spoke.bound_interval_seconds")
        if isinstance(hh, dict) and hh.get("p50") is not None:
            ent["publish_interval_p50"] = hh["p50"]
    # flow time series: longest streak of checks where produced
    # advanced while consumed stayed flat (the silent-starvation case
    # neither the faults section nor no_late_retraces can see)
    it_events = run.of("hub.iteration", role="")
    series = [e["flow"] for e in it_events
              if isinstance(e.get("flow"), dict)]
    if series:
        got_hub_flow = True
    streaks: dict[str, int] = {}
    cur: dict[str, int] = {}
    prev = None
    for flow in series:
        if prev is not None:
            for label, ent in flow.items():
                p0 = (prev.get(label) or {}).get("produced", 0)
                c0 = (prev.get(label) or {}).get("consumed", 0)
                if ent.get("produced", 0) > p0 \
                        and ent.get("consumed", 0) == c0:
                    cur[label] = cur.get(label, 0) + 1
                    streaks[label] = max(streaks.get(label, 0),
                                         cur[label])
                else:
                    cur[label] = 0
        prev = flow
    if series:
        for label, ent in spokes.items():
            last = series[-1].get(label) or {}
            ent.setdefault("produced", int(last.get("produced", 0)))
            ent.setdefault("consumed", int(last.get("consumed", 0)))
            ent["starvation_streak"] = streaks.get(label, 0)
    if not spokes or not got_hub_flow:
        return None
    it_hist = hists.get("ph.iteration_seconds") or {}
    n_checks = len(it_events)
    for ent in spokes.values():
        ent["verdict"], ent["why"] = _flow_verdict(ent, it_hist,
                                                   n_checks)
    return dict(sorted(spokes.items()))


def _flow_verdict(ent, it_hist, n_checks):
    produced = max(int(ent.get("produced", 0)),
                   int(ent.get("published", 0)))
    consumed = int(ent.get("consumed", 0))
    accepted = int(ent.get("accepted", 0))
    rejected = int(ent.get("rejected", 0))
    lag = produced - consumed
    if rejected and rejected >= max(1, accepted):
        return "REJECTED", (f"{rejected} payload(s) rejected vs "
                            f"{accepted} accepted — see the faults "
                            "section for reasons")
    if produced and not consumed:
        return "STARVED", (f"{produced} publish(es) but the hub never "
                           "consumed one")
    if ent.get("starvation_streak", 0) >= 3:
        return "STARVED", (f"publishes advanced across "
                           f"{ent['starvation_streak']} consecutive hub "
                           "checks while consumption stayed flat")
    if produced >= 4 and lag >= (produced + 1) // 2:
        return "STARVED", (f"hub consumed only {consumed} of {produced} "
                           "publishes (window overwrote the rest)")
    if produced <= 1 and n_checks >= 10:
        return "SLOW", (f"{produced} bound(s) published across "
                        f"{n_checks} hub checks")
    it_p50 = it_hist.get("p50")
    pub_p50 = ent.get("publish_interval_p50")
    # hub p50 floored at 0.2 s: ms-scale toy hubs out-iterate any
    # spoke, and sub-second cadence is never the binding diagnosis
    if it_p50 and pub_p50 and pub_p50 > 5.0 * max(it_p50, 0.2):
        return "SLOW", (f"publish cadence p50 {pub_p50:.2g}s vs hub "
                        f"iteration p50 {it_p50:.2g}s")
    return "HEALTHY", ""


_UNSET = object()


def invariant_checks(run: Run, bound_flow=_UNSET) -> list:
    """[(name, ok, detail, severity)] — the afterward-checkable
    contracts. severity "fail" renders [FAIL] when violated; "warn"
    renders [WARN] for checks whose violation has benign explanations
    (counter deltas are process-global, so an in-process spoke
    thread's legitimate first compile can land inside a hub
    iteration's window). ``bound_flow`` lets callers that already
    computed :func:`bound_flow_summary` (render_report, the --json
    path) pass it in instead of paying its event scans twice."""
    checks = []
    c = run.counters()
    calls = c.get("ph.solve_loop_calls", 0)
    syncs = c.get("ph.gate_syncs", 0)
    if calls:
        per = syncs / calls
        # pipelined chunked mode pays 1/call (+ exceptional retries /
        # hospital); sequential opt-out pays one per chunk. <= 2 is
        # the O(1) contract with recovery headroom.
        checks.append(("gate_syncs_per_solve_call_O1", per <= 2.0,
                       f"{per:.2f} (ph.gate_syncs {syncs} / "
                       f"ph.solve_loop_calls {calls})", "fail"))
    traj = bound_trajectory(run)
    ok_outer = all(prev[2] <= cur[2] for prev, cur in
                   zip(traj["outer"], traj["outer"][1:]))
    ok_inner = all(cur[2] <= prev[2] for prev, cur in
                   zip(traj["inner"], traj["inner"][1:]))
    if traj["outer"] or traj["inner"]:
        checks.append(("bound_updates_monotone", ok_outer and ok_inner,
                       f"{len(traj['outer'])} outer / "
                       f"{len(traj['inner'])} inner updates", "fail"))
    checks.append(("events_parse_clean", run.bad_lines == 0,
                   f"{run.bad_lines} unparseable line(s)", "fail"))
    checks.append(("single_run_in_dir", run.earlier_runs == 0,
                   ("one session" if not run.earlier_runs else
                    f"{run.earlier_runs} earlier session(s) appended in "
                    "this dir were ignored (events.jsonl appends across "
                    "runs; trace/metrics hold only the last) — use a "
                    "fresh --telemetry-dir per run for full history"),
                   "warn"))
    foot = run.of("run_footer", role="")
    checks.append(("clean_shutdown_footer", bool(foot),
                   "run_footer present" if foot else
                   "no run_footer (killed run?)", "fail"))
    schemas = {int(h.get("schema", 1)) for h in run.roles.values()}
    checks.append(("schema_consistent_across_roles", len(schemas) <= 1,
                   f"versions {sorted(schemas)}", "fail"))
    comp = compile_summary(run)
    # WARN, not FAIL: compile counters are process-global, so an
    # in-process spoke thread's legitimate first-time compile can land
    # inside a hub iteration's delta window (threaded spin_the_wheel)
    checks.append(("no_late_retraces", not comp["late_retrace_iters"],
                   ("none" if not comp["late_retrace_iters"] else
                    f"XLA compiles during iterations "
                    f"{comp['late_retrace_iters']} — a hot-loop shape/"
                    "static-arg drift is retracing (or an in-process "
                    "spoke thread's warmup)"), "warn"))
    # WARN, not FAIL: the wheel is DESIGNED to survive these (that is
    # the supervisor's whole job), but a quarantined spoke or a
    # corrupt/crossed payload means the run lost a bound source or
    # fought corruption — a clean run stays all-PASS
    f = fault_summary(run)
    degraded = f["quarantined"] > 0 or f["crossed_rejections"] > 0
    checks.append(("no_quarantines_or_corruption", not degraded,
                   ("clean" if not degraded else
                    f"{f['quarantined']} spoke(s) quarantined, "
                    f"{f['crossed_rejections']} crossed-bound "
                    "rejection(s) — see the faults section"), "warn"))
    # WARN, not FAIL: the silent-starvation case the faults section
    # and no_late_retraces both miss — a spoke whose produced write
    # ids advance while the hub's consumed ids stay flat is wasting
    # its whole compute budget on bounds nobody reads, yet crashes
    # nothing and retraces nothing
    bf = bound_flow_summary(run) if bound_flow is _UNSET else bound_flow
    if bf is not None:
        starved = {label: ent for label, ent in bf.items()
                   if ent.get("verdict") == "STARVED"}
        checks.append((
            "no_silent_starvation", not starved,
            ("all spokes consumed" if not starved else
             "; ".join(f"{label}: {ent['why']}"
                       for label, ent in starved.items())
             + " — see the bound flow section"), "warn"))
    return checks


# ---------------- report rendering ----------------

def _fmt_b(n):
    if n is None:
        return "-"
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024:
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} PB"


def _fmt(v, nd=4):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def lint_summary(run: Run) -> dict | None:
    """The graft-lint stamp (ISSUE 12): when the telemetry dir carries
    a ``lint.json`` report (``python -m tools.lint --out <dir>/lint.json``
    — tools/regression_gate.py writes one beside the fresh bench), the
    report gets a one-line lint-status stamp. None when absent."""
    p = os.path.join(run.path, "lint.json")
    if not os.path.exists(p):
        return None
    try:
        with open(p, encoding="utf-8") as f:
            rep = json.load(f)
    except (OSError, ValueError):
        rep = None
    if not isinstance(rep, dict):
        # unreadable / torn / non-object payload: stamp it as such
        # rather than aborting the whole run report
        return {"status": "unreadable", "findings": None,
                "suppressed": None, "files_checked": None}
    findings = rep.get("findings") or []
    return {"status": "clean" if not findings else "findings",
            "findings": len(findings),
            "suppressed": len(rep.get("suppressed") or []),
            "files_checked": rep.get("files_checked")}


def _lint_line(ls: dict) -> str:
    if ls["status"] == "unreadable":
        return "lint: lint.json present but unreadable"
    head = ("clean" if ls["status"] == "clean"
            else f"{ls['findings']} FINDING(S)")
    return (f"lint: {head}  ({ls['files_checked']} files, "
            f"{ls['suppressed']} suppressed) [lint.json]")


def _stamp_truncated(text: str) -> str:
    """Append the ``TRUNCATED RUN`` stamp to every section header —
    uniform truncated-run handling (a run killed before its
    ``run_footer``): each section explicitly says it reflects the last
    flushed events, instead of section-dependent silence."""
    return "\n".join(
        ln + "  [TRUNCATED RUN]" if ln.startswith("== ") else ln
        for ln in text.splitlines())


def render_report(run: Run) -> str:
    L = []
    h = run.header
    cfg = h.get("config") or {}
    trunc = truncated(run)
    L.append(f"== run == {run.path}")
    if trunc:
        L.append("TRUNCATED RUN: no run_footer — the run was killed "
                 "before shutdown; every section below reflects the "
                 "last flushed events, not a completed run")
    L.append(f"run_id {h.get('run_id')}  schema {run.schema}  "
             f"started {h.get('wall_time_iso')}  "
             f"roles [{', '.join(r or 'hub' for r in sorted(run.roles))}]")
    ls = lint_summary(run)
    if ls is not None:
        L.append(_lint_line(ls))
    if isinstance(cfg, dict) and cfg.get("model"):
        L.append(f"model {cfg.get('model')}  "
                 f"num_scens {cfg.get('num_scens')}  "
                 f"hub {cfg.get('hub')}  "
                 f"spokes {[s.get('kind') for s in cfg.get('spokes', [])]}")
    L.append("")

    L.append("== phase breakdown ==")
    pb = phase_breakdown(run)
    if pb:
        for mode, ent in sorted(pb.items()):
            tot = sum(p["seconds"] for p in ent.values())
            solve = ent.get("solve", {}).get("seconds", 0.0)
            occ = solve / tot if tot > 0 else 0.0
            parts = "  ".join(
                f"{k} {p['seconds']:.3f}s/{p['calls']}"
                for k, p in sorted(ent.items()))
            L.append(f"[{mode}] {parts}  | total {tot:.3f}s "
                     f"occupancy {occ:.2f}")
    else:
        L.append("(no phase spans captured)")
    L.append("")

    L.append("== convergence trajectory ==")
    rows = iteration_rows(run)
    if rows:
        L.append(f"{'iter':>5} {'conv':>11} {'pri_rel_max':>12} "
                 f"{'s/iter':>9} {'gap_rel':>10} {'notes'}")
        shown = rows if len(rows) <= 12 else rows[:6] + rows[-6:]
        prev_it = None
        for e in shown:
            if prev_it is not None and e["iter"] != prev_it + 1:
                L.append(f"{'...':>5}")
            prev_it = e["iter"]
            notes = " ".join(f"{k.split('.')[-1]}={v}" for k, v in
                             (e.get("counter_deltas") or {}).items()
                             if not k.startswith("qp.solve_segments")
                             and not k.startswith("ph.gate_syncs"))
            L.append(f"{e['iter']:>5} {_fmt(e.get('conv')):>11} "
                     f"{_fmt(e.get('pri_rel_max')):>12} "
                     f"{_fmt(e.get('seconds'), 3):>9} "
                     f"{_fmt(e.get('gap_rel')):>10} {notes}")
    else:
        L.append("(no ph.iteration records)")
    L.append("")

    L.append("== bounds ==")
    traj = bound_trajectory(run)
    for kind in ("outer", "inner"):
        tr = traj[kind]
        if tr:
            t_first, ch, v_first = tr[0]
            t_last, ch_l, v_last = tr[-1]
            L.append(f"{kind}: {len(tr)} updates, first {_fmt(v_first)} "
                     f"[{ch}] @ {t_first:.1f}s, best {_fmt(v_last)} "
                     f"[{ch_l}] @ {t_last:.1f}s")
        else:
            L.append(f"{kind}: no updates")
    hub_it = run.of("hub.iteration")
    if hub_it:
        last = hub_it[-1]
        L.append(f"final gap: rel {_fmt(last.get('rel_gap'))} "
                 f"abs {_fmt(last.get('abs_gap'))}")
    L.append("")

    L.append("== resources ==")
    comp = compile_summary(run)
    L.append(f"XLA compiles {comp['compiles']} "
             f"(traces {comp['traces']}, "
             f"{comp['compile_seconds_total']:.2f}s total)")
    for name, n in comp["entries"][:8]:
        L.append(f"  compile x{n}: {name}")
    mems = memory_watermarks(run)
    if mems:
        for role, devs in sorted(mems.items()):
            row = "  ".join(f"{d}={_fmt_b(v)}"
                            for d, v in sorted(devs.items()))
            L.append(f"memory peak [{role or 'hub'}]: {row}")
    else:
        L.append("memory: no allocator stats "
                 "(CPU backend has none — expected off-chip)")
    c = run.counters()
    xfer = {k: v for k, v in c.items() if k.startswith("xfer.")}
    if xfer:
        L.append("transfers: " + "  ".join(
            f"{k.split('.', 1)[1]}={_fmt_b(v)}"
            for k, v in sorted(xfer.items())))
    L.append("")

    rf = roofline_summary(run)
    if rf is not None:
        L.append("== roofline ==")
        dev = rf.get("device") or {}
        if dev:
            tier = " [CPU-TIER — nominal peaks, not meaningful " \
                   "absolute utilization]" if dev.get("cpu_tier") else ""
            L.append(f"device {dev.get('device_kind')}  peaks "
                     f"{dev.get('peak_flops', 0) / 1e12:.2f} TFLOP/s / "
                     f"{dev.get('peak_hbm_gbps') or 0:.0f} GB/s "
                     f"(source {dev.get('source')}){tier}")
        ov = rf["overall"]
        if ov["iters"]:
            L.append(
                f"measured: mfu {_fmt(ov['mfu'], 4)}  hbm "
                f"{_fmt(ov['hbm_gbps'], 2)} GB/s "
                f"(util {_fmt(ov['hbm_util'], 4)})  "
                f"flops/iter {_fmt(ov['flops_total'] / ov['iters'])}  "
                f"bytes/iter "
                f"{_fmt_b(ov['hbm_bytes_total'] / ov['iters'])}  "
                f"over {ov['iters']} iter(s)")
        else:
            L.append("(no instrumented iterations — profile counters "
                     "present but no ph.iteration deltas)")
        sp = rf.get("solve_phase")
        if sp:
            L.append(f"solve-phase mfu {_fmt(sp['mfu'], 4)} "
                     f"({_fmt(sp['seconds_total'], 3)}s in solve)")
        for m, row in sorted(rf["per_mode"].items()):
            L.append(f"  mode {m}: mfu {_fmt(row['mfu'], 4)}  hbm "
                     f"{_fmt(row['hbm_gbps'], 2)} GB/s  "
                     f"{row['iters']} iter(s)")
        if rf["per_bucket"]:
            L.append("per-bucket measured vs predicted "
                     "(doc/roofline.md's est_hbm column):")
            for b in rf["per_bucket"]:
                est = b.get("est_hbm_bytes_per_iter")
                L.append(
                    f"  bucket {b['bucket']:g}: mfu {_fmt(b['mfu'], 4)}"
                    f"  hbm {_fmt(b['hbm_gbps'], 2)} GB/s "
                    f"(util {_fmt(b['hbm_util'], 4)})  measured "
                    f"{_fmt_b(b['hbm_bytes_per_iter'])}/iter"
                    + (f" vs est {_fmt_b(est)}/iter" if est else "")
                    + f"  over {b['iters']} iter(s)")
        lg = rf["ledger"]
        tick = "==" if rf["ledger_matches"] else "!="
        L.append(f"compile ledger: {rf['ledger_compiles']} compile(s) "
                 f"{tick} jax.compiles {rf['jax_compiles']}"
                 + ("" if rf["ledger_matches"] else
                    "  [MISMATCH — a compile escaped attribution]"))
        for key, ent in sorted(lg.items(),
                               key=lambda kv: -kv[1]["seconds"])[:10]:
            L.append(f"  {key}: x{ent['compiles']} "
                     f"{ent['seconds']:.2f}s")
        if rf["unavailable_count"]:
            reasons = {u.get("reason") for u in rf["unavailable"]}
            L.append(f"profile.unavailable: {rf['unavailable_count']} "
                     f"(reasons: {sorted(r for r in reasons if r)})")
        L.append("")

    sh = sharding_summary(run)
    if sh is not None:
        L.append("== sharding ==")
        L.append(f"mode {sh.get('mode')}  devices {sh.get('n_devices')}  "
                 f"shard {sh.get('shard_scenarios')} scenario(s)/device")
        per = sh.get("collective_bytes_per_iter")
        L.append(f"collective bytes: {_fmt_b(sh['collective_bytes_total'])}"
                 + (f" total, {_fmt_b(per)}/iter" if per else " total")
                 + " (psum operand estimate)")
        dp = sh.get("device_put_bytes_iterations", 0)
        L.append(f"device_put bytes: "
                 f"{_fmt_b(sh.get('device_put_bytes_total', 0))} total "
                 f"(setup placement), {_fmt_b(dp)} across iterations"
                 + ("" if dp == 0 else
                    "  [NONZERO — steady-state sharded iterations "
                    "should not device_put]"))
        L.append("")

    ck = checkpoint_summary(run)
    if ck is not None:
        L.append("== checkpoint ==")
        L.append(f"captures {ck['captures']} "
                 f"(reasons {ck['reasons'] or ['-']})  spoke-state "
                 f"writes {ck['spoke_writes']}  write failures "
                 f"{ck['write_failed']}")
        if ck.get("last_bundle"):
            L.append(f"last bundle: {ck['last_bundle']} "
                     f"(iter {ck['last_iter']})")
        if ck["resumed"]:
            r = ck.get("resume") or {}
            L.append(f"RESUMED from {r.get('bundle')} "
                     f"(iter {r.get('iter')}, outer "
                     f"{_fmt(r.get('outer'))}, inner "
                     f"{_fmt(r.get('inner'))}); spoke resumes "
                     f"{ck['spoke_resumed']}")
        if ck["rejected"]:
            L.append("rejected bundles: " + "  ".join(
                f"{k}={v}" for k, v in sorted(ck["rejected"].items()))
                + " (cold start fallback)")
        if ck["preempted"]:
            L.append("PREEMPTED: SIGTERM notice handled — final "
                     "bundle captured before terminate")
        L.append("")

    sv = serving_summary(run)
    if sv is not None:
        L.append("== serving ==")
        L.append(f"requests: {sv['admitted']} admitted  "
                 f"{sv['completed']} completed  {sv['failed']} failed  "
                 f"{sv['deadline_missed']} deadline-missed  "
                 f"{sv['rejected']} rejected  "
                 f"{sv['preempted_requests']} preempted  "
                 f"{sv['resumed']} resumed")
        ratio = sv["cache_hit_ratio"]
        L.append(f"warm cache: {sv['cache_hits']} hit(s) / "
                 f"{sv['cache_misses']} miss(es)"
                 + (f" (hit ratio {_fmt(ratio, 2)})"
                    if ratio is not None else "")
                 + f"  evictions {sv['cache_evictions']}")
        L.append(f"wheels: {sv['wheels']} total  "
                 f"{sv['stacked_wheels']} stacked "
                 f"({sv['coalesced']} requests coalesced)  "
                 f"chain steps {sv['chain_steps']}")
        occ = sv.get("batch_occupancy")
        if occ:
            L.append(f"batch occupancy: mean "
                     f"{_fmt(occ.get('mean'), 2)}  max "
                     f"{_fmt(occ.get('max'), 0)}  over "
                     f"{int(occ.get('count', 0))} wheel(s)")
        if sv["per_bucket_compiles"]:
            L.append("per-bucket compiles: " + "  ".join(
                f"{k}={v}" for k, v in
                sorted(sv["per_bucket_compiles"].items())))
        if sv["service_preempted"]:
            L.append("SERVICE PREEMPTED: in-flight wheels "
                     "checkpointed; requests resume at next start")
        if sv.get("quarantined"):
            L.append(f"QUARANTINED: {sv['quarantined']} request(s) "
                     "failed after exhausting --max-recoveries "
                     "(poison pill suspected)")
        mig = sv.get("migration")
        if mig is not None:
            L.append(f"migration: {mig['offered']} offered  "
                     f"{mig['handed_off']} handed off  "
                     f"{mig['committed']} committed  "
                     f"{mig['completed']} completed"
                     + ("  [drained]" if sv.get("drained") else ""))
            if mig["aborted"]:
                L.append("  aborted: " + "  ".join(
                    f"{k}={v}" for k, v in sorted(mig["aborted"].items())))
            if mig["rejected"]:
                L.append("  rejected by receiver: " + "  ".join(
                    f"{k}={v}" for k, v in
                    sorted(mig["rejected"].items())))
            if not mig["reconciled"]:
                L.append("  LEDGER MISMATCH: offered != handed_off + "
                         "aborted — an offer path returned without "
                         "booking its outcome (doc/serving.md)")
        L.append("")

    shr = shrink_summary(run)
    if shr is not None:
        L.append("== shrinking ==")
        L.append(f"fixed {shr['fixed_final']} / free {shr['free_final']}"
                 f"  (+{shr['fixed_new_total']} fixed over the run)  "
                 f"compactions {shr['compactions']}"
                 + (f" (skipped {shr['compaction_skipped']})"
                    if shr['compaction_skipped'] else "")
                 + f"  rho updates {shr['rho_updates']}")
        if shr["compactions"]:
            L.append(f"bucket compiles {shr['bucket_compiles']}  "
                     f"bucket cache hits {shr['bucket_cache_hits']}")
            for e in shr["compaction_events"]:
                L.append(f"  iter {e['iter']}: bucket {e['bucket']:g} "
                         f"-> {e['n_cols']}/{e['n_full']} cols, "
                         f"{e['m_rows']}/{e['m_full']} rows"
                         + (" [cached]" if e.get("bucket_cached")
                            else ""))
        if shr["transplants"] or shr["transplant_cold_fallbacks"]:
            L.append(f"cross-bucket transplants {shr['transplants']}  "
                     "cold fallbacks "
                     f"{shr['transplant_cold_fallbacks']}")
        if shr["reconvergence"]:
            L.append("post-transition re-convergence "
                     "(iterations back to the pre-transition conv):")
            for r in shr["reconvergence"]:
                k = r["iters_to_reconverge"]
                L.append(
                    f"  bucket {r['bucket']:g} (iter {r['iter']}, "
                    f"{r['mode']}): "
                    + (f"{k} iter(s)" if k is not None else
                       "not recovered in the record"))
        if shr["per_bucket"]:
            L.append("per-bucket s/iter (active-set verdict source):")
            for b in shr["per_bucket"]:
                hbm = b.get("est_hbm_bytes_per_iter")
                L.append(f"  bucket {b['bucket']:g}: "
                         f"{_fmt(b['s_per_iter'], 4)} s/iter over "
                         f"{b['iters']} iter(s)"
                         + (f", est HBM {_fmt_b(hbm)}/iter"
                            if hbm else ""))
        tr = [t for t in shr["trajectory"]
              if t.get("fixed") is not None]
        if tr:
            L.append("fixed-fraction trajectory (iter: fixed/free): "
                     + "  ".join(f"{t['iter']}: {t['fixed']}/{t['free']}"
                                 for t in tr[-8:]))
        L.append("")

    stm = streaming_summary(run)
    if stm is not None:
        L.append("== streaming ==")
        occ = stm["prefetch_occupancy"]
        L.append(f"source {stm['source'] or '?'}  chunks shipped "
                 f"{stm['chunks_shipped']} ({_fmt_b(stm['bytes_shipped'])})"
                 f"  synthesized {stm['synth_chunks']}  direct fetches "
                 f"{stm['direct_fetches']}")
        L.append(f"prefetch stalls {stm['prefetch_stalls']}"
                 + (f"  occupancy {_fmt(occ, 3)}" if occ is not None
                    else "")
                 + f"  int8 fallbacks {stm['int8_fallbacks']}")
        if stm["compacted_transitions"]:
            L.append(f"compacted re-blocks "
                     f"{stm['compacted_transitions']}  (out-of-band "
                     f"restage "
                     f"{_fmt_b(stm['compacted_restage_bytes'])}; "
                     "steady state judged after the last transition)")
        flat = stm["device_put_flat_steady_state"]
        if flat is not None:
            L.append("steady-state device_put: "
                     + ("FLAT (the streaming acceptance contract)"
                        if flat else
                        "NOT FLAT — per-iteration transfer grew or "
                        "leaked (see per_iteration in --json)"))
        L.append("")

    ap = aph_summary(run)
    if ap is not None:
        L.append("== aph ==")
        sav = ap["skipped_solve_savings"]
        L.append(f"dispatch_frac {_fmt(ap['dispatch_frac'], 3)}  "
                 f"path {ap['solve_path'] or '?'}  solved "
                 f"{ap['solved_scenarios']}  skipped "
                 f"{ap['skipped_scenarios']}"
                 + (f"  (savings {_fmt(sav, 3)})"
                    if sav is not None else ""))
        gpi = ap["gate_syncs_per_iteration"]
        L.append(f"gate syncs {ap['gate_syncs']}"
                 + (f"  ({_fmt(gpi, 2)}/iter — the stacked-gate "
                    "contract says 1)" if gpi is not None else "")
                 + f"  bucket compiles {ap['bucket_compiles']}  "
                 f"bucket cache hits {ap['bucket_cache_hits']}")
        tr = [t for t in ap["trajectory"]
              if t.get("dispatched") is not None]
        if tr:
            L.append("dispatched trajectory (iter: n/S φneg): "
                     + "  ".join(
                         f"{t['iter']}: {t['dispatched']}/{t['S_real']} "
                         f"{t['phi_neg']}" for t in tr[-8:]))
        L.append("")

    inc = incumbent_summary(run)
    if inc is not None:
        L.append("== incumbent ==")
        L.append(f"pool rounds {inc['rounds']}  pool size "
                 f"{inc['pool_size']}  candidates "
                 f"{inc['candidates_evaluated']} ({inc['feasible']} "
                 f"feasible)  improvements {inc['improvements']} "
                 f"(accept rate {_fmt(inc['accept_rate'], 2)})")
        L.append(f"pool reuse skips {inc['pool_reused']}  oracle "
                 f"polish {inc['oracle_polish']}  gate syncs "
                 f"{inc['gate_syncs']}")
        traj = [t for t in inc["trajectory"]
                if t.get("best") is not None]
        if traj:
            L.append("best-value trajectory (round: best): "
                     + "  ".join(f"{t['round']}: {_fmt(t['best'], 2)}"
                                 for t in traj[-6:]))
        L.append("")

    L.append("== counters ==")
    for k in sorted(c):
        if k.split(".")[0] in ("ph", "qp", "hub", "spoke", "incumbent",
                               "serve", "shrink", "stream", "aph",
                               "dispatch"):
            L.append(f"  {k} = {_fmt(c[k])}")
    L.append("")

    L.append("== faults ==")
    f = fault_summary(run)
    if not f["degraded"]:
        L.append("(none — no spoke downs, respawns, quarantines, "
                 "rejected payloads, or watchdog)")
    else:
        L.append(f"DEGRADED RUN: {f['downs']} down(s), "
                 f"{f['respawns']} respawn(s), "
                 f"{f['quarantined']} quarantined, "
                 f"{f['rejected_payloads']} rejected payload(s) "
                 f"({f['crossed_rejections']} crossed)")
        for key, ent in sorted(f["per_spoke"].items()):
            reasons = f" [{', '.join(ent['reasons'])}]" \
                if ent["reasons"] else ""
            L.append(f"  {key}: downs {ent['downs']} "
                     f"respawns {ent['respawns']} "
                     f"quarantined {ent['quarantined']} "
                     f"rejected {ent['rejected']}{reasons}")
        if f["watchdog_fired"]:
            w = f["watchdog"] or {}
            L.append(f"  watchdog fired: source {w.get('source', '?')} "
                     f"after {_fmt(w.get('elapsed'))}s "
                     f"(partial bounds outer {_fmt(w.get('outer'))} / "
                     f"inner {_fmt(w.get('inner'))})")
    L.append("")

    bf = bound_flow_summary(run)
    if bf is not None:
        L.append("== bound flow ==")
        for label, ent in bf.items():
            kind = f" [{ent['kind']}]" if ent.get("kind") else ""
            stal = ""
            if ent.get("staleness_p50") is not None:
                stal = (f"  staleness p50 {_fmt(ent['staleness_p50'], 2)}s"
                        f" p99 {_fmt(ent.get('staleness_p99'), 2)}s")
            cad = ""
            if ent.get("publish_interval_p50") is not None:
                cad = (f"  cadence p50 "
                       f"{_fmt(ent['publish_interval_p50'], 2)}s")
            why = f" ({ent['why']})" if ent.get("why") else ""
            L.append(
                f"  {label}{kind}: produced "
                f"{ent.get('produced', ent.get('published', 0))} "
                f"consumed {ent.get('consumed', 0)} "
                f"lag {ent.get('lag', 0)}  accepted "
                f"{ent.get('accepted', 0)} rejected "
                f"{ent.get('rejected', 0)}{stal}{cad}  -> "
                f"{ent['verdict']}{why}")
        L.append("")

    fo = forensics_summary(run)
    if fo is not None:
        # ranked diagnosis (ops/forensics.py + obs/diagnose.py,
        # doc/forensics.md): verdicts most-severe first, then the last
        # sample's culprit leaderboards
        L.append("== forensics ==")
        L.append(f"verdict: {fo['verdict']}  (samples {fo['samples']}, "
                 f"bound checks {fo['bound_checks']})")
        for v in fo["verdicts"]:
            L.append(f"  [{v['verdict']}] {v['summary']}"
                     + (f" — advice: {v['advice']}"
                        if v.get("advice") else ""))
        last = fo.get("last")
        if last:
            slots = last.get("top_slots") or []
            if slots:
                L.append("top culprit slots (slot: |x-xbar| mass): "
                         + "  ".join(f"{int(s)}: {_fmt(m)}"
                                     for s, m in slots[:5]))
            scens = last.get("scen_pri_shares") or []
            if scens:
                L.append("scenario residual shares (scen: share): "
                         + "  ".join(f"{int(s)}: {_fmt(sh, 3)}"
                                     for s, sh in scens[:5]))
            L.append(f"osc_mean {_fmt(last.get('osc_mean'), 3)}  "
                     f"rho log-ratio "
                     f"{_fmt(last.get('rho_log_ratio_mean'), 3)}  "
                     f"xbar move {_fmt(last.get('xbar_move'))}")
        for v in fo["verdict_events"][-4:]:
            L.append(f"  verdict event @iter {v.get('it')}: "
                     f"{v.get('prev')} -> {v.get('verdict')}")
        L.append("")

    L.append("== invariant checks ==")
    for name, ok, detail, severity in invariant_checks(run,
                                                       bound_flow=bf):
        tag = "PASS" if ok else severity.upper()
        L.append(f"  [{tag}] {name}: {detail}")
    text = "\n".join(L)
    return _stamp_truncated(text) if trunc else text


# ---------------- compare ----------------

# (metric, kind): kind "time" uses the time threshold + an absolute
# floor (sub-millisecond jitter is not a regression), kind "count"
# uses a fixed 1.25x ratio gate
_ABS_FLOOR_S = 1e-3


def comparison_metrics(run: Run) -> dict:
    out = {}
    rows = iteration_rows(run)
    secs = [e["seconds"] for e in rows if
            isinstance(e.get("seconds"), (int, float))]
    if secs:
        out[("ph_seconds_per_iteration", "time")] = sum(secs) / len(secs)
    for mode, ent in phase_breakdown(run).items():
        for ph, p in ent.items():
            if p["calls"]:
                out[(f"phase_{ph}_seconds_per_call[{mode}]", "time")] = \
                    p["seconds"] / p["calls"]
    c = run.counters()
    calls = c.get("ph.solve_loop_calls", 0)
    if calls:
        out[("gate_syncs_per_solve_call", "count")] = \
            c.get("ph.gate_syncs", 0) / calls
        # ABSOLUTE compile count, not per-solve-call: compiles are
        # per-process structural cost (cold-start + retraces) while
        # solve-call counts jitter with async wheel timing, so the
        # ratio of the two flakes across identical trees. A retrace
        # regression moves the absolute count directly.
        out[("xla_compiles_total", "count")] = c.get("jax.compiles", 0)
        # sharded runs (ISSUE 6): collective traffic per solve call and
        # steady-state device_put leakage — a sharded-vs-sharded
        # compare flags a collective-volume or placement regression;
        # keys absent on unsharded runs are skipped by compare()
        if "xfer.collective_bytes" in c:
            out[("collective_kbytes_per_solve_call", "count")] = \
                c["xfer.collective_bytes"] / 1024.0 / calls
            sh = sharding_summary(run)
            if sh is not None:
                out[("device_put_kbytes_across_iterations", "count")] = \
                    sh.get("device_put_bytes_iterations", 0) / 1024.0
    h = run.histograms().get("ph.iteration_seconds", {})
    if h.get("p99") is not None:
        out[("ph_iteration_seconds_p99", "time")] = h["p99"]
    if calls and "kernel.fused_iters" in c:
        # fused-vs-fused pairings compare kernel iteration volume too
        # (a jump means the fused programs are burning more budget for
        # the same work); fused-vs-segmented pairings skip this row —
        # the dedicated verdict row in compare() handles those
        out[("kernel_fused_iters_per_solve_call", "count")] = \
            c["kernel.fused_iters"] / calls
    if calls and "stream.bytes_shipped" in c:
        # streamed runs (ISSUE 15, doc/streaming.md): shipped volume
        # per solve call — a streamed-vs-streamed compare flags a
        # staging regression (e.g. an int8 field regressing to f64, or
        # a third restage pass sneaking into the iteration); absent on
        # resident/synthesized runs, skipped by compare()
        out[("stream_kbytes_per_solve_call", "count")] = \
            c["stream.bytes_shipped"] / 1024.0 / calls
    return out


def kernel_summary(run: Run) -> dict:
    """Kernel-backend activity of one run (the ops/kernels counters,
    doc/kernels.md): which subproblem kernel mode actually executed and
    the trade volumes the fused-vs-segmented compare row reports."""
    c = run.counters()
    calls = c.get("ph.solve_loop_calls", 0)
    fused = c.get("kernel.fused_iters", 0)
    return {
        "mode": "fused" if fused else "segmented",
        "fused_iters": fused,
        "fused_iters_per_solve_call": (fused / calls) if calls else 0.0,
        "l_inv_factorizations": c.get("kernel.l_inv_factorizations", 0),
        "bf16_fallbacks": c.get("kernel.bf16_fallbacks", 0),
    }


def compare(a: Run, b: Run, threshold=1.5,
            abs_floor=_ABS_FLOOR_S) -> tuple[str, bool]:
    """Render the A-vs-B diff; returns (text, passed). Raises
    ValueError on a schema mismatch — two formats must not be
    numerically compared.

    ``abs_floor`` (seconds) suppresses time-metric verdicts whose
    absolute delta is below it: micro-phases (sub-ms per call) ride
    scheduler noise, so a 3x ratio on 0.5 ms is jitter, not a
    regression. Same-machine compares keep the tight 1 ms default;
    cross-machine gates (tools/regression_gate.py) pass a looser
    floor."""
    if a.schema != b.schema:
        raise ValueError(
            f"schema mismatch: {a.path} is v{a.schema}, {b.path} is "
            f"v{b.schema} — re-run one side or analyze separately "
            "(refusing to mis-parse)")
    ma, mb = comparison_metrics(a), comparison_metrics(b)
    trunc = [t for t, r in (("A", a), ("B", b)) if truncated(r)]
    L = [f"== compare ==\nA: {a.path}\nB: {b.path}\n"
         f"time regression threshold: {threshold:.2f}x "
         f"(abs floor {abs_floor * 1e3:.0f} ms)"]
    if trunc:
        L.append(f"TRUNCATED RUN ({', '.join(trunc)}): no run_footer — "
                 "that side was killed before shutdown; every section "
                 "below compares against its last flushed events, not "
                 "a completed run")
    regressions = []
    for key in sorted(set(ma) & set(mb), key=lambda k: k[0]):
        name, kind = key
        va, vb = ma[key], mb[key]
        ratio = (vb / va) if va > 0 else (math.inf if vb > 0 else 1.0)
        if kind == "time":
            bad = ratio > threshold and (vb - va) > abs_floor
            better = ratio < 1.0 / threshold and (va - vb) > abs_floor
        else:
            bad = ratio > 1.25 and (vb - va) > 0.5
            better = ratio < 0.8 and (va - vb) > 0.5
        tag = ("REGRESSION" if bad else
               "improved" if better else "ok")
        if bad:
            regressions.append(name)
        L.append(f"  {name}: A={_fmt(va)} B={_fmt(vb)} "
                 f"ratio={_fmt(ratio, 3)} [{tag}]")
    ka, kb = kernel_summary(a), kernel_summary(b)
    if ka["fused_iters"] or kb["fused_iters"]:
        # fused-vs-segmented verdict row (ISSUE 7, doc/kernels.md):
        # when the two runs executed different subproblem kernel modes,
        # the per-iteration time rows above ARE the evidence — restate
        # them against the kernel modes so the pairing reads as one
        # explicit accept/reject line, not a diff to interpret.
        per_iter_bad = [r for r in regressions
                        if r.startswith(("ph_seconds_per_iteration",
                                         "ph_iteration_seconds",
                                         "phase_solve"))]
        tag = "REGRESSION" if per_iter_bad else "PASS"
        L.append(
            f"  kernel: A={ka['mode']} "
            f"({_fmt(ka['fused_iters_per_solve_call'])} fused "
            f"iters/solve, l_inv={ka['l_inv_factorizations']}, "
            f"bf16_fallbacks={ka['bf16_fallbacks']}) "
            f"B={kb['mode']} "
            f"({_fmt(kb['fused_iters_per_solve_call'])}, "
            f"l_inv={kb['l_inv_factorizations']}, "
            f"bf16_fallbacks={kb['bf16_fallbacks']}) — "
            f"per-iteration verdict [{tag}]")
    # streaming verdict row (ISSUE 15, doc/streaming.md): for a run
    # with an active scenario source, the acceptance contract is FLAT
    # steady-state device_put deltas — restate each side's flatness +
    # staging anatomy as one explicit line; a side whose steady-state
    # transfer grew books a regression.
    for tag, run_ in (("A", a), ("B", b)):
        sm = streaming_summary(run_)
        if sm is None:
            continue
        flat = sm["device_put_flat_steady_state"]
        verdict = "PASS"
        if flat is False:
            verdict = "REGRESSION"
            regressions.append(f"stream_flat_device_put[{tag}]")
        occ = sm["prefetch_occupancy"]
        L.append(
            f"  stream[{tag}]: source={sm['source'] or '?'} "
            f"shipped={_fmt_b(sm['bytes_shipped'])} "
            f"synth_chunks={sm['synth_chunks']} "
            f"int8_fallbacks={sm['int8_fallbacks']}"
            + (f" occupancy={_fmt(occ, 3)}" if occ is not None else "")
            + f" — steady-state device_put verdict [{verdict}]")
    # APH dispatch verdict row (ISSUE 16, doc/aph.md): at EQUAL
    # dispatch_frac, the φ-dispatch promise is that B launches no more
    # scenario-solves per iteration than A — a grown count means the
    # skip machinery silently degraded to full-width launches (the
    # exact regression the counter exists to catch). Different fracs
    # are a config change, not a regression; the row says so and
    # abstains.
    apa, apb = aph_summary(a), aph_summary(b)
    if apa is not None and apb is not None:
        va = apa.get("solved_per_iteration")
        vb = apb.get("solved_per_iteration")
        fa, fb = apa.get("dispatch_frac"), apb.get("dispatch_frac")
        if fa is not None and fb is not None and fa != fb:
            L.append(f"  aph: dispatch_frac differs (A={_fmt(fa, 3)} "
                     f"B={_fmt(fb, 3)}) — dispatch verdict [skipped]")
        elif va is not None and vb is not None:
            verdict = "PASS"
            if vb > va + 0.5:
                verdict = "REGRESSION"
                regressions.append("aph_dispatched_solves")
            L.append(
                f"  aph: solved/iter A={_fmt(va)} B={_fmt(vb)} "
                f"(frac {_fmt(fa, 3)})  gate syncs/iter "
                f"A={_fmt(apa['gate_syncs_per_iteration'], 2)} "
                f"B={_fmt(apb['gate_syncs_per_iteration'], 2)} — "
                f"dispatch verdict [{verdict}]")
    # per-iteration-time-vs-active-set verdict row (ISSUE 14,
    # doc/extensions.md §shrinking): for a run with compactions, the
    # shrinking promise is that post-compaction iterations get
    # CHEAPER as the active set shrinks — restate each side's
    # per-bucket s/iter as one explicit line. A side whose
    # last-bucket mean runs >1.5x its bucket-0 mean (over the abs
    # floor) broke the promise and books a regression.
    sha = shb = None
    for tag, run_ in (("A", a), ("B", b)):
        sh = shrink_summary(run_)
        if tag == "A":
            sha = sh
        else:
            shb = sh
        if sh is None or not sh.get("per_bucket"):
            continue
        pb = sh["per_bucket"]
        head, tail = pb[0], pb[-1]
        line = "  ".join(
            f"bucket {r['bucket']:g}={_fmt(r['s_per_iter'], 4)}s/iter"
            f"({r['iters']})" for r in pb)
        verdict = "PASS"
        if len(pb) > 1 and tail["s_per_iter"] > head["s_per_iter"] \
                * threshold \
                and (tail["s_per_iter"] - head["s_per_iter"]) \
                > abs_floor:
            verdict = "REGRESSION"
            regressions.append(f"shrink_active_set[{tag}]")
        if len(pb) > 1:
            line += (f" — active-set verdict [{verdict}] "
                     f"(compactions {sh['compactions']})")
        L.append(f"  shrink[{tag}]: {line}")
    # transplant verdict row (ISSUE 17, doc/extensions.md §shrinking):
    # at an EQUAL bucket schedule (the same compaction sequence ran on
    # both sides), the cross-bucket transplant promise is that B's
    # guarded cold restarts did not grow — a grown count means warm
    # states stopped surviving the transition (width-mismatch, dirty
    # donated passes, lost source factors: exactly the silent decay
    # the counter exists to catch). Different schedules are a config
    # change, not a regression; the row says so and abstains.
    if sha is not None and shb is not None:
        sched_a = [e.get("bucket") for e in sha["compaction_events"]]
        sched_b = [e.get("bucket") for e in shb["compaction_events"]]
        ca = sha["transplant_cold_fallbacks"]
        cb = shb["transplant_cold_fallbacks"]
        if sched_a and sched_a != sched_b:
            L.append(f"  transplant: bucket schedule differs "
                     f"(A={sched_a} B={sched_b}) — cold-fallback "
                     "verdict [skipped]")
        elif sched_a and (sha["transplants"] or ca
                          or shb["transplants"] or cb):
            verdict = "PASS"
            if cb > ca:
                verdict = "REGRESSION"
                regressions.append("shrink_transplant_cold_fallbacks")
            L.append(
                f"  transplant: warm A={sha['transplants']} "
                f"B={shb['transplants']}  cold A={ca} B={cb} — "
                f"cold-fallback verdict [{verdict}]")
    # measured-MFU verdict row (ISSUE 18, doc/roofline.md): when both
    # sides carry profile captures, the roofline promise is that B's
    # measured model-FLOP utilization did not collapse — the per-
    # iteration time rows can stay flat while the work per iteration
    # silently grew (shape-bucket drift, fallback kernels), and MFU is
    # the one figure that catches it. A >1.25x drop with a real
    # absolute delta books a regression; one-sided captures abstain,
    # and so do runs whose FLOPs/iter differ materially — different
    # arithmetic per iteration (e.g. segmented vs fused engines)
    # makes MFU apples-to-oranges, not a regression.
    ra, rb = roofline_summary(a), roofline_summary(b)
    if ra is not None and rb is not None:
        va = ra["overall"]["mfu"]
        vb = rb["overall"]["mfu"]

        def _fpi(r):
            o = r["overall"]
            return o["flops_total"] / o["iters"] if o["iters"] else 0.0

        fa, fb = _fpi(ra), _fpi(rb)
        same_work = (fa > 0 and fb > 0
                     and 0.9 < fa / fb < 1.1111)
        if va is not None and vb is not None and va > 0:
            verdict = "PASS" if same_work else "skipped"
            if same_work and (vb <= 0 or (va / max(vb, 1e-12) > 1.25
                                          and (va - vb) > 1e-4)):
                verdict = "REGRESSION"
                regressions.append("profile_mfu")
            L.append(
                f"  roofline: mfu A={_fmt(va, 4)} B={_fmt(vb, 4)}  "
                f"hbm A={_fmt(ra['overall']['hbm_gbps'], 2)} "
                f"B={_fmt(rb['overall']['hbm_gbps'], 2)} GB/s  "
                f"compiles A={ra['ledger_compiles']} "
                f"B={rb['ledger_compiles']} — MFU verdict [{verdict}]")
    elif ra is not None or rb is not None:
        L.append("  roofline: profile captures on one side only — "
                 "MFU verdict [skipped]")
    # forensics verdict row (ISSUE 19, doc/forensics.md): when a side
    # carries forensic data, restate its diagnosis as one explicit
    # line. A candidate whose wheel shows a stall signature the
    # baseline lacks books a regression — a faster wheel that stopped
    # converging is not an improvement; sides without forensic data
    # abstain (runs predating the layer).
    fza, fzb = forensics_summary(a), forensics_summary(b)
    if fza is not None or fzb is not None:
        va = fza["verdict"] if fza else None
        vb = fzb["verdict"] if fzb else None
        verdict = "PASS" if (fza is not None and fzb is not None) \
            else "skipped"
        if fzb is not None and vb != "HEALTHY" \
                and (fza is None or va == "HEALTHY"):
            verdict = "REGRESSION"
            regressions.append(f"forensics_{vb.lower()}")
        why = ""
        if fzb is not None and fzb["verdicts"]:
            why = f" (B: {fzb['verdicts'][0]['summary']})"
        L.append(f"  forensics: A={va or 'n/a'} B={vb or 'n/a'}{why} "
                 f"— stall verdict [{verdict}]")
    only = [k[0] for k in (set(ma) ^ set(mb))]
    if only:
        L.append(f"  (not in both runs, skipped: {sorted(only)})")
    passed = not regressions
    L.append(f"VERDICT: {'PASS' if passed else 'REGRESSION'}"
             + (f" ({', '.join(regressions)})" if regressions else ""))
    text = "\n".join(L)
    return (_stamp_truncated(text) if trunc else text), passed


# ---------------- watch (the live tail) ----------------

def _rel_age(now, wall):
    if not isinstance(wall, (int, float)):
        return "?"
    return f"{max(0.0, now - wall):.1f}s ago"


def render_watch(path) -> tuple[str, bool]:
    """One refresh frame of ``analyze --watch``: the live.json snapshot
    the hub atomically rewrites on every termination check, plus the
    tail of the event streams. Returns (frame, done) — done once a
    ``run_footer`` has landed (the run is over; the next refresh would
    show the same thing forever)."""
    import time

    now = time.time()
    L = [f"== live wheel == {path}"]
    live = None
    lp = os.path.join(path, "live.json")
    if os.path.exists(lp):
        try:
            with open(lp, encoding="utf-8") as fh:
                live = json.load(fh)
        except ValueError:
            live = None     # racing the atomic replace; next tick wins
    if live is not None:
        L.append(
            f"run {live.get('run_id')}  iter {live.get('iter')}  "
            f"updated {_rel_age(now, live.get('wall_time_unix'))}"
            + ("  [WATCHDOG FIRED]" if live.get("watchdog_fired")
               else ""))
        L.append(
            f"outer {_fmt(live.get('outer'), 8)} "
            f"[{live.get('ob_char', ' ')}]  "
            f"inner {_fmt(live.get('inner'), 8)} "
            f"[{live.get('ib_char', ' ')}]  "
            f"rel gap {_fmt(live.get('rel_gap'))}  "
            f"elapsed {_fmt(live.get('elapsed_seconds'), 4)}s")
        ph = live.get("phases")
        if ph:
            L.append(f"phases [{ph.get('mode')}] occupancy "
                     f"{_fmt(ph.get('occupancy'), 3)}  s/call "
                     + "  ".join(f"{k} {_fmt(v, 3)}" for k, v in
                                 (ph.get("seconds_per_call")
                                  or {}).items()))
        rf = live.get("roofline")
        if rf:
            # current-iteration measured roofline (obs/profile.py):
            # one line — MFU + HBM utilization of the last completed
            # iteration, straight off the live plane
            L.append(
                f"roofline iter {rf.get('iter')}: "
                f"mfu {_fmt(rf.get('mfu'), 4)}  "
                f"hbm {_fmt(rf.get('hbm_gbps'), 2)} GB/s "
                f"(util {_fmt(rf.get('hbm_util'), 4)})  "
                f"flops/iter {_fmt(rf.get('flops_per_iter'))}")
        fo = live.get("forensics")
        if fo:
            # wheel-forensics tile (obs/diagnose.py): the current
            # verdict + top culprit slot/scenario, straight off the
            # live plane (doc/forensics.md)
            L.append(
                f"forensics {fo.get('verdict', '?')}: "
                f"top slot {fo.get('top_slot')} "
                f"(mass {_fmt(fo.get('top_slot_mass'))})  "
                f"top scen {fo.get('top_scen')} "
                f"(share {_fmt(fo.get('top_scen_share'), 3)})  "
                f"samples {fo.get('samples', 0)}")
        for sp in live.get("spokes", ()):
            flags = []
            if sp.get("alive") is False:
                flags.append("DEAD")
            if sp.get("crashes"):
                flags.append(f"crashes {sp['crashes']}")
            stal = sp.get("staleness_last_seconds")
            L.append(
                f"  spoke{sp.get('index')} "
                f"[{sp.get('kind') or sp.get('spoke', '?')}] "
                f"{sp.get('state', '?')} gen {sp.get('gen', 0)}  "
                f"produced {sp.get('produced', 0)} consumed "
                f"{sp.get('consumed', 0)} lag {sp.get('lag', 0)}  "
                f"accepted {sp.get('accepted', 0)} rejected "
                f"{sp.get('rejected', 0)}"
                + (f"  staleness {_fmt(stal, 2)}s"
                   if stal is not None else "")
                + ("  " + " ".join(flags) if flags else ""))
    else:
        L.append("(no live.json yet — hub has not reached a "
                 "termination check, or the run predates the live "
                 "plane)")
    # event tail across every role stream, newest last
    tail = []
    done = False
    for f in glob.glob(os.path.join(path, "events*.jsonl")):
        role = _role_of(f, "events", ".jsonl")
        try:
            # bounded tail read: the hub stream grows every termination
            # check, and --watch re-renders every ~2 s — reading whole
            # multi-hour files each frame would peg IO on the machine
            # hosting the run this view is meant to observe passively
            with open(f, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                size = fh.tell()
                fh.seek(max(0, size - 65536))
                chunk = fh.read().decode("utf-8", "replace")
            lines = chunk.splitlines()[-40:]
        except OSError:
            continue
        for ln in lines:
            try:
                e = json.loads(ln)
            except ValueError:
                continue
            if e.get("type") == "run_footer" and role == "":
                done = True
            tail.append((e.get("t", 0.0), role, e))
    tail.sort(key=lambda t: t[0])
    L.append("recent events:")
    for t, role, e in tail[-8:]:
        fields = " ".join(
            f"{k}={_fmt(v) if isinstance(v, float) else v}"
            for k, v in e.items()
            if k not in ("t", "type", "_role", "config", "metrics")
            and not isinstance(v, (dict, list)))
        L.append(f"  [{role or 'hub':>18}] {e.get('type')} "
                 f"{fields[:120]}")
    if done:
        L.append("(run complete — footer landed; watch exiting. "
                 "Run `analyze` on the dir for the full report.)")
    return "\n".join(L), done


def watch(path, interval=2.0, refreshes=None) -> int:
    """Refreshing terminal view of a live run directory: tail
    live.json + events.jsonl until the run footer lands (or
    ``refreshes`` frames for tests / one-shot peeks)."""
    import time

    n = 0
    while True:
        frame, done = render_watch(path)
        # ANSI clear + home; falls out harmlessly on dumb terminals
        print("\x1b[2J\x1b[H" + frame, flush=True)
        n += 1
        if done or (refreshes is not None and n >= refreshes):
            return 0
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            return 0


# ---------------- CLI ----------------

def _json_sanitize(o):
    """Non-finite floats → None, recursively. Default ``json.dumps``
    serializes them as bare ``NaN``/``Infinity`` — a JavaScript
    extension, not JSON, so strict downstream parsers reject the whole
    document. Applied at the ``--json`` emit boundary (pinned by a
    ``parse_constant``-raising round-trip test)."""
    if isinstance(o, float):
        return o if math.isfinite(o) else None
    if isinstance(o, dict):
        return {k: _json_sanitize(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_json_sanitize(v) for v in o]
    return o


def make_parser():
    p = argparse.ArgumentParser(
        prog="python -m mpisppy_tpu analyze",
        description="Render a diagnostics report from a --telemetry-dir "
                    "run directory, or diff two runs.")
    p.add_argument("dirs", nargs="*",
                   help="one telemetry dir (report) — or two with "
                        "--compare")
    p.add_argument("--compare", action="store_true",
                   help="diff two runs: analyze --compare A B")
    p.add_argument("--watch", action="store_true",
                   help="live mode: refreshing terminal view tailing "
                        "the dir's live.json + events.jsonl while the "
                        "run iterates (exits when the run footer "
                        "lands)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="--watch refresh seconds (default 2)")
    p.add_argument("--refreshes", type=int, default=None,
                   help="--watch: stop after N frames (default: until "
                        "the run ends)")
    p.add_argument("--threshold", type=float, default=1.5,
                   help="time-metric regression ratio (default 1.5)")
    p.add_argument("--abs-floor-ms", type=float,
                   default=_ABS_FLOOR_S * 1e3,
                   help="ignore time-metric deltas below this many ms "
                        "per call/iteration (default 1 — raise for "
                        "cross-machine compares where micro-phase "
                        "timings are scheduler noise)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    try:
        if args.watch:
            if len(args.dirs) != 1:
                print("analyze --watch needs exactly one telemetry dir")
                return 2
            return watch(args.dirs[0], interval=args.interval,
                         refreshes=args.refreshes)
        if args.compare:
            if len(args.dirs) != 2:
                print("analyze --compare needs exactly two telemetry "
                      "dirs")
                return 2
            a, b = load_run(args.dirs[0]), load_run(args.dirs[1])
            try:
                text, passed = compare(
                    a, b, threshold=args.threshold,
                    abs_floor=args.abs_floor_ms / 1e3)
            except ValueError as e:
                print(f"analyze: {e}")
                return 2
            if args.as_json:
                print(json.dumps(_json_sanitize(
                    {"a": {str(k[0]): v
                           for k, v in comparison_metrics(a).items()},
                     "b": {str(k[0]): v
                           for k, v in comparison_metrics(b).items()},
                     "kernel": {"a": kernel_summary(a),
                                "b": kernel_summary(b)},
                     "shrink": {"a": shrink_summary(a),
                                "b": shrink_summary(b)},
                     "streaming": {"a": streaming_summary(a),
                                   "b": streaming_summary(b)},
                     "aph": {"a": aph_summary(a),
                             "b": aph_summary(b)},
                     "roofline": {"a": roofline_summary(a),
                                  "b": roofline_summary(b)},
                     "forensics": {"a": forensics_summary(a),
                                   "b": forensics_summary(b)},
                     "truncated": {"a": truncated(a),
                                   "b": truncated(b)},
                     "verdict": "PASS" if passed else "REGRESSION"})))
            else:
                print(text)
            return 0 if passed else 3
        if len(args.dirs) != 1:
            make_parser().print_usage()
            return 2
        run = load_run(args.dirs[0])
        if args.as_json:
            print(json.dumps(_json_sanitize({
                "run_id": run.header.get("run_id"),
                "schema": run.schema,
                "phase_breakdown": phase_breakdown(run),
                "iterations": iteration_rows(run),
                "counters": run.counters(),
                "memory": memory_watermarks(run),
                "compile": {k: v for k, v in compile_summary(run).items()
                            if k != "entries"},
                "sharding": sharding_summary(run),
                "roofline": roofline_summary(run),
                "truncated": truncated(run),
                "shrink": shrink_summary(run),
                "streaming": streaming_summary(run),
                "aph": aph_summary(run),
                "incumbent": incumbent_summary(run),
                "checkpoint": checkpoint_summary(run),
                "serving": serving_summary(run),
                "faults": fault_summary(run),
                "forensics": forensics_summary(run),
                "lint": lint_summary(run),
                "bound_flow": (bf := bound_flow_summary(run)),
                "invariants": [
                    {"name": n, "ok": ok, "detail": d, "severity": sv}
                    for n, ok, d, sv in invariant_checks(
                        run, bound_flow=bf)],
            })))
        else:
            print(render_report(run))
        return 0
    except FileNotFoundError as e:
        print(f"analyze: {e}")
        return 2
