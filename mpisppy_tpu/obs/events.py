"""Structured JSONL event stream.

One line per event: ``{"t": <perf_counter>, "type": <str>, ...fields}``.
``t`` is ``time.perf_counter()`` — MONOTONIC, jitter-proof under NTP
slews — and the stream's first line is a ``run_header`` recording the
(wall_time_unix, perf_counter) anchor pair plus the run id and a config
snapshot, so any consumer can convert monotonic stamps to wall clock
and merge streams from concurrent processes. This stream subsumes the
historical scatter of per-module sinks: spoke ``trace_prefix`` CSVs,
hub ``bound_events``, PH hospital/recovery screen traces, and the
``MPISPPY_TPU_SOLVE_TRACE`` stderr stamps all emit here when telemetry
is configured (doc/observability.md documents every event type).

Lines are written incrementally (line-buffered append) so a killed run
keeps everything emitted before the kill; a bounded in-memory tail is
kept for tests and interactive consumers that never touch the disk.

Rotation: a serve-hosted process lives for days, so the stream is
size-capped — when the current file passes ``max_bytes`` (default
256 MiB, ``MPISPPY_TPU_TELEMETRY_ROTATE_BYTES``) it is renamed to
``events.jsonl.1`` (older files shift to ``.2..N``, the oldest beyond
``MPISPPY_TPU_TELEMETRY_ROTATE_FILES``, default 8, is dropped) and a
fresh file opens with a CONTINUATION HEADER — the original
``run_header`` plus a ``rotated: <k>`` field — so every consumer that
anchors on the first line (``obs/merge.py``) keeps working, and
``analyze`` re-chains the files oldest-first into one logical stream
(a header carrying ``rotated`` is a splice point, not a new session).
A ``telemetry.rotated`` event opens each new file after the header.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

# Telemetry artifact schema version, stamped into every run_header (and
# by bench.py into its BENCH JSON rows). Consumers that join artifacts
# across runs (``analyze --compare``) refuse mismatched versions instead
# of mis-parsing. Bump when an event/trace/metrics field changes
# meaning; absent = 1 (the PR-3 format).
SCHEMA_VERSION = 2

# rotation defaults (documented in doc/observability.md): cap one
# events file at 256 MiB, keep 8 rotated generations
_ROTATE_BYTES_DEFAULT = 256 * 1024 * 1024
_ROTATE_FILES_DEFAULT = 8


def _env_int(name, default):
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


class EventStream:
    """Append-only JSONL sink with a bounded in-memory tail."""

    def __init__(self, path=None, run_id=None, config=None, tail=4096,
                 role=None, max_bytes=None, max_files=None):
        self.path = path
        self.run_id = run_id
        self.max_bytes = max_bytes if max_bytes is not None else \
            _env_int("MPISPPY_TPU_TELEMETRY_ROTATE_BYTES",
                     _ROTATE_BYTES_DEFAULT)
        self.max_files = max_files if max_files is not None else \
            _env_int("MPISPPY_TPU_TELEMETRY_ROTATE_FILES",
                     _ROTATE_FILES_DEFAULT)
        self.rotations = 0
        self._lock = threading.Lock()
        self._fh = open(path, "a", buffering=1) if path else None
        self._bytes = 0
        if path:
            try:
                self._bytes = os.path.getsize(path)
            except OSError:
                pass
        self.tail = deque(maxlen=tail)
        self.emitted = 0
        self.header = {
            "type": "run_header",
            "schema": SCHEMA_VERSION,
            "run_id": run_id,
            "role": role,
            "t": time.perf_counter(),
            "wall_time_unix": time.time(),
            "wall_time_iso": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "pid": os.getpid(),
            "clock": "perf_counter",
            "config": config,
        }
        self._write(self.header)

    def event(self, etype: str, fields=None, t=None):
        """Emit one event. ``t`` defaults to now (perf_counter); pass an
        explicit stamp to record an event measured earlier (e.g. hub
        bound events re-emitted with their original stamps)."""
        obj = {"t": time.perf_counter() if t is None else float(t),
               "type": etype}
        if fields:
            obj.update(fields)
        self._write(obj)
        return obj

    def _write(self, obj):
        with self._lock:
            self.tail.append(obj)
            self.emitted += 1
            if self._fh is None:
                return
            try:
                line = json.dumps(obj, default=_jsonable)
            except ValueError:
                # unserializable event (e.g. a circular reference the
                # default hook never sees): drop THIS line only — the
                # sink must stay alive for every later event
                return
            try:
                self._fh.write(line + "\n")
                self._bytes += len(line) + 1
            except ValueError:
                # stream closed under us (interpreter teardown races
                # the atexit flush) — keep the memory tail
                self._fh = None
                return
            if self.max_bytes and self._bytes >= self.max_bytes:
                self._rotate_locked()

    def _rotate_locked(self):
        """Shift the current file to ``.1`` (``.k`` -> ``.k+1``, the
        oldest dropped) and reopen fresh, first line a continuation
        header. Caller holds ``self._lock``; writes go through the
        file handle directly — no re-entry into ``_write``."""
        try:
            self._fh.close()
            for k in range(self.max_files - 1, 0, -1):
                src = f"{self.path}.{k}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{k + 1}")
            drop = f"{self.path}.{self.max_files}"
            if os.path.exists(drop):
                os.remove(drop)
            os.replace(self.path, f"{self.path}.1")
            self._fh = open(self.path, "a", buffering=1)
        except OSError:
            # a hostile filesystem must not kill the emitting hot
            # path: reopen in place (uncapped) and carry on
            try:
                self._fh = open(self.path, "a", buffering=1)
            except OSError:
                self._fh = None
            self._bytes = 0
            return
        self.rotations += 1
        self._bytes = 0
        # continuation header: the ORIGINAL anchor pair + run id with a
        # rotation marker, so first-line consumers (merge anchors)
        # still see a run_header and analyze knows not to treat the
        # splice as a new session
        for obj in (dict(self.header, rotated=self.rotations),
                    {"t": time.perf_counter(),
                     "type": "telemetry.rotated",
                     "seq": self.rotations,
                     "max_bytes": self.max_bytes,
                     "max_files": self.max_files}):
            try:
                self._fh.write(json.dumps(obj, default=_jsonable)
                               + "\n")
            except (ValueError, OSError):
                return

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def _jsonable(o):
    """Last-resort JSON coercion: numpy scalars/arrays and anything
    else stringify instead of killing the emitting hot path."""
    try:
        import numpy as np
        if isinstance(o, np.ndarray):
            return o.tolist()
        if isinstance(o, np.generic):
            return o.item()
    except Exception:
        pass
    return str(o)
