"""Structured JSONL event stream.

One line per event: ``{"t": <perf_counter>, "type": <str>, ...fields}``.
``t`` is ``time.perf_counter()`` — MONOTONIC, jitter-proof under NTP
slews — and the stream's first line is a ``run_header`` recording the
(wall_time_unix, perf_counter) anchor pair plus the run id and a config
snapshot, so any consumer can convert monotonic stamps to wall clock
and merge streams from concurrent processes. This stream subsumes the
historical scatter of per-module sinks: spoke ``trace_prefix`` CSVs,
hub ``bound_events``, PH hospital/recovery screen traces, and the
``MPISPPY_TPU_SOLVE_TRACE`` stderr stamps all emit here when telemetry
is configured (doc/observability.md documents every event type).

Lines are written incrementally (line-buffered append) so a killed run
keeps everything emitted before the kill; a bounded in-memory tail is
kept for tests and interactive consumers that never touch the disk.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

# Telemetry artifact schema version, stamped into every run_header (and
# by bench.py into its BENCH JSON rows). Consumers that join artifacts
# across runs (``analyze --compare``) refuse mismatched versions instead
# of mis-parsing. Bump when an event/trace/metrics field changes
# meaning; absent = 1 (the PR-3 format).
SCHEMA_VERSION = 2


class EventStream:
    """Append-only JSONL sink with a bounded in-memory tail."""

    def __init__(self, path=None, run_id=None, config=None, tail=4096,
                 role=None):
        self.path = path
        self.run_id = run_id
        self._lock = threading.Lock()
        self._fh = open(path, "a", buffering=1) if path else None
        self.tail = deque(maxlen=tail)
        self.emitted = 0
        self.header = {
            "type": "run_header",
            "schema": SCHEMA_VERSION,
            "run_id": run_id,
            "role": role,
            "t": time.perf_counter(),
            "wall_time_unix": time.time(),
            "wall_time_iso": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "pid": os.getpid(),
            "clock": "perf_counter",
            "config": config,
        }
        self._write(self.header)

    def event(self, etype: str, fields=None, t=None):
        """Emit one event. ``t`` defaults to now (perf_counter); pass an
        explicit stamp to record an event measured earlier (e.g. hub
        bound events re-emitted with their original stamps)."""
        obj = {"t": time.perf_counter() if t is None else float(t),
               "type": etype}
        if fields:
            obj.update(fields)
        self._write(obj)
        return obj

    def _write(self, obj):
        with self._lock:
            self.tail.append(obj)
            self.emitted += 1
            if self._fh is None:
                return
            try:
                line = json.dumps(obj, default=_jsonable)
            except ValueError:
                # unserializable event (e.g. a circular reference the
                # default hook never sees): drop THIS line only — the
                # sink must stay alive for every later event
                return
            try:
                self._fh.write(line + "\n")
            except ValueError:
                # stream closed under us (interpreter teardown races
                # the atexit flush) — keep the memory tail
                self._fh = None

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def _jsonable(o):
    """Last-resort JSON coercion: numpy scalars/arrays and anything
    else stringify instead of killing the emitting hot path."""
    try:
        import numpy as np
        if isinstance(o, np.ndarray):
            return o.tolist()
        if isinstance(o, np.generic):
            return o.item()
    except Exception:
        pass
    return str(o)
