"""The live plane: in-run status/metrics endpoint + live.json.

Everything the PR 3/4 telemetry stack records is post-mortem —
events.jsonl / trace.json / ``analyze`` are consumed after the run
ends. This module is the *in-run* consumer surface (ROADMAP item 3's
"the obs stack becomes the service's metrics endpoint", and the live
half of ROADMAP item 1's starved/slow/rejected diagnosis):

- :class:`LiveStatusServer` — an opt-in stdlib ``http.server`` on a
  daemon thread, owned by the HUB process
  (``RunConfig.status_port`` / ``--status-port``; port 0 binds an
  ephemeral port), serving

  * ``/metrics`` — Prometheus text exposition rendered from the
    process-wide Recorder registry: counters, gauges, and histograms
    with the PR 4 fixed log-spaced edges re-expressed as cumulative
    ``le`` buckets (the registry keeps per-bucket upper-inclusive
    counts; Prometheus wants cumulative upper-inclusive — same
    intervals, so the conversion is a running sum). Metric names are
    the registry's dotted names, sanitized and prefixed
    (``ph.gate_syncs`` → ``mpisppy_tpu_ph_gate_syncs``). A handful of
    hub-state gauges (iteration, bounds, gap, per-spoke liveness) are
    appended from :meth:`Hub.status_snapshot` so a scraper sees the
    wheel even before the registry fills.
  * ``/status`` — the hub's status snapshot as JSON: run id,
    iteration, current outer/inner bounds + gap, per-spoke supervisor
    state (alive / generation / quarantined / respawns) and bound
    flow, phase occupancy.
  * ``/`` and ``/healthz`` — liveness ping.

- :func:`write_live_snapshot` — the SAME snapshot persisted as
  ``live.json`` beside the telemetry artifacts on every hub
  termination check (atomically renamed, so a SIGKILL mid-write never
  leaves a torn file): multi-host and jax-free consumers — and
  ``analyze --watch`` — tail it without the port.

Pure host-side stdlib: no jax import anywhere on this path.
"""

from __future__ import annotations

import json
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .metrics import BUCKET_EDGES

PROM_PREFIX = "mpisppy_tpu"
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return f"{PROM_PREFIX}_{_NAME_RE.sub('_', name)}"


def _prom_num(v) -> str:
    if v is None:
        return "NaN"
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(snapshot: dict | None, extra_gauges=None) -> str:
    """Prometheus text exposition (format version 0.0.4) from a
    MetricsRegistry snapshot ({"counters", "gauges", "histograms"}).

    Histograms: the registry keeps PER-BUCKET counts over the fixed
    upper-inclusive edges (metrics.BUCKET_EDGES); Prometheus buckets
    are CUMULATIVE over the same upper-inclusive intervals, so the
    running sum below is exact — ``_bucket{le="+Inf"}`` equals
    ``_count`` by construction. ``extra_gauges`` ({name: value}) lets
    the status server append live hub state not kept in the registry.
    """
    L = []
    snapshot = snapshot or {}
    for name, v in sorted((snapshot.get("counters") or {}).items()):
        p = _prom_name(name)
        L.append(f"# TYPE {p} counter")
        L.append(f"{p} {_prom_num(v)}")
    gauges = dict(snapshot.get("gauges") or {})
    gauges.update(extra_gauges or {})
    for name, v in sorted(gauges.items()):
        p = _prom_name(name)
        L.append(f"# TYPE {p} gauge")
        L.append(f"{p} {_prom_num(v)}")
    for name, h in sorted((snapshot.get("histograms") or {}).items()):
        p = _prom_name(name)
        per_bucket = h.get("buckets_upper_edge") or {}
        L.append(f"# TYPE {p} histogram")
        cum = 0
        for edge in BUCKET_EDGES:
            cum += per_bucket.get(f"{edge:g}", 0)
            L.append(f'{p}_bucket{{le="{edge:g}"}} {cum}')
        cum += per_bucket.get("+inf", 0)
        L.append(f'{p}_bucket{{le="+Inf"}} {cum}')
        L.append(f"{p}_sum {_prom_num(h.get('sum', 0.0))}")
        L.append(f"{p}_count {int(h.get('count', 0))}")
    return "\n".join(L) + "\n"


def _status_gauges(status: dict) -> dict:
    """Live hub state worth scraping that the registry does not carry
    (bounds move through events, not gauges). Names join the registry
    namespace under ``live.*`` so they can never collide with it."""
    out = {}
    for key in ("iter", "outer", "inner", "abs_gap", "rel_gap",
                "elapsed_seconds"):
        v = status.get(key)
        if isinstance(v, (int, float)):
            out[f"live.{key}"] = v
    out["live.watchdog_fired"] = 1 if status.get("watchdog_fired") else 0
    for ent in status.get("spokes", ()):
        i = ent.get("index")
        up = 1
        if ent.get("state") not in (None, "running") \
                or ent.get("alive") is False:
            up = 0
        out[f"live.spoke.up.spoke{i}"] = up
        out[f"live.spoke.generation.spoke{i}"] = ent.get("gen", 0)
    return out


def write_live_snapshot(out_dir: str, status: dict) -> str:
    """Atomically persist ``live.json`` under ``out_dir``. The rename
    is the crash-safety contract: consumers either see the previous
    complete snapshot or the new complete snapshot, never a torn
    write — required by the SIGKILL'd-run acceptance criterion."""
    path = os.path.join(out_dir, "live.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(status, f, indent=1)
    os.replace(tmp, path)
    return path


class _StatusHandler(BaseHTTPRequestHandler):
    # the wheel's stdout is the screen trace — never log HTTP chatter
    def log_message(self, *args):
        pass

    def do_GET(self):
        try:
            code, ctype, body = self.server._respond(self.path)
        except Exception as e:      # introspection must never crash
            code, ctype = 500, "text/plain; charset=utf-8"
            body = f"status server error: {e!r}\n".encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class _StatusHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, hub):
        super().__init__(addr, _StatusHandler)
        self._hub = hub

    def _respond(self, path):
        from .. import obs

        path = path.split("?", 1)[0]
        obs.counter_add("hub.status_requests")
        if path == "/metrics":
            rec = obs.active()
            snap = rec.metrics.snapshot() if rec is not None else None
            status = self._hub.status_snapshot()
            body = render_prometheus(snap,
                                     extra_gauges=_status_gauges(status))
            # the de-facto standard exposition content type
            return (200, "text/plain; version=0.0.4; charset=utf-8",
                    body.encode())
        if path == "/status":
            return (200, "application/json; charset=utf-8",
                    (json.dumps(self._hub.status_snapshot(), indent=1)
                     + "\n").encode())
        if path in ("/", "/healthz"):
            return (200, "application/json; charset=utf-8",
                    b'{"ok": true}\n')
        return (404, "text/plain; charset=utf-8",
                b"unknown path; try /metrics /status /healthz\n")


class LiveStatusServer:
    """The hub-owned in-run status server. ``start()`` binds and spins
    a daemon serve thread (port 0 = ephemeral; read ``.port`` after
    start); ``stop()`` releases the socket. Idempotent both ways.

    Binds LOOPBACK by default: /status and /metrics expose the whole
    run state with no auth, so reaching them from another host is an
    explicit opt-in (``RunConfig.status_host`` / ``--status-host
    0.0.0.0`` for a Prometheus scraper; live.json covers the passive
    multi-host tail case without opening a port at all)."""

    def __init__(self, hub, port: int, host: str = "127.0.0.1"):
        self._hub = hub
        self._requested = (host, int(port))
        self._httpd = None
        self._thread = None
        self.port = None

    def start(self):
        if self._httpd is not None:
            return self
        self._httpd = _StatusHTTPServer(self._requested, self._hub)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="mpisppy-tpu-status", daemon=True)
        self._thread.start()
        from .. import global_toc, obs
        global_toc(f"live status server on port {self.port} "
                   "(/metrics /status)")
        obs.event("hub.status_server", {"port": self.port})
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
