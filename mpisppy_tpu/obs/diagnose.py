"""The wheel diagnosis engine: symptom → verdict rules over the
forensic samples (jax-free).

``ops/forensics.py`` produces per-sample attribution stats (top
disagreeing slots, scenario residual shares, W-oscillation, rho
health); the hub's termination check contributes the outer/inner bound
trajectory. This module turns both streams into NAMED, evidence-
carrying verdicts — the answer to "why is the wheel stuck", not
another scalar:

- ``STALLED_OUTER(spoke=lagrangian, bound flat N checks)`` — the
  outer-bound spoke stopped improving while a real gap remains.
- ``OSCILLATING(slots=[...], advice: rho up)`` — W sign-flips
  persist on specific slots: the consensus is bouncing, not closing.
- ``CULPRIT_SCENARIOS([ids], residual share ≥ x%)`` — a few
  scenarios carry most of the primal residual mass.
- ``FIXING_STALL(bucket 0.25 never crossed)`` — progressive
  shrinking armed but the first fixed-fraction bucket never arrived.
- ``HEALTHY`` — none of the above fired.

Two consumption modes share ONE set of pure rule functions
(:func:`diagnose` and the ``rule_*`` helpers take plain lists/dicts):
the LIVE engine below (session-bound state in the ``obs/profile.py``
mold — identity-checked against the active Recorder, rebind-don't-
mutate snapshots so signal handlers and the hub status thread read
without locks), and ``obs/analyze.py``'s post-mortem re-diagnosis over
the recorded event streams. Emits ``forensics.*`` counters/gauges and
the ``forensics.verdict`` transition event (doc/forensics.md has the
full rule table).

jax-free by contract (graft-lint PURE001): the hub status plane, the
bench signal handler, and serve read :func:`snapshot` as plain dict
lookups.
"""

from __future__ import annotations

import threading

from . import active as _active
from . import counter_add, event, gauge_set

# post-mortem spoke attribution: converger_spoke_char → the spoke kind
# string the CLI roles use (live runs resolve kinds straight from the
# supervisor; analyze maps the last screen_row's ob_char through this)
SPOKE_CHARS = {
    "L": "lagrangian", "A": "lagranger", "X": "xhatshuffle",
    "D": "xhatdive", "E": "ef", "F": "fwph", "S": "slam",
    "C": "cross_scenario",
}

# rule thresholds (one table so analyze's re-diagnosis and the live
# engine agree; doc/forensics.md documents every knob)
DEFAULTS = {
    "stall_checks": 5,       # consecutive flat outer-bound checks
    "stall_rel_tol": 1e-8,   # flatness tolerance, relative to |outer|
    "stall_gap_floor": 1e-4, # rel gap below this = effectively done
    "osc_mean_thresh": 0.25, # mean slot flip-EMA
    "osc_slot_thresh": 0.5,  # single-slot flip-EMA
    "osc_min_samples": 3,    # flip EMA needs two deltas to be real
    "culprit_share": 0.5,    # residual concentration threshold
    "culprit_max_frac": 0.25,  # ...carried by ≤ this fraction of scens
    "fixing_stall_iters": 25,  # iterations before a bucket is overdue
}

_SEVERITY = {"STALLED_OUTER": 3, "OSCILLATING": 2,
             "CULPRIT_SCENARIOS": 2, "FIXING_STALL": 1}


def _cfg(cfg):
    if not cfg:
        return DEFAULTS
    out = dict(DEFAULTS)
    out.update(cfg)
    return out


# ---------------- the pure rules ----------------

def rule_stalled_outer(bound_checks, cfg=None):
    """Outer bound flat across ≥ ``stall_checks`` consecutive checks
    while the rel gap stays above ``stall_gap_floor``. ``bound_checks``
    is a list of ``{"it", "outer", "inner", "rel_gap", "spoke"}`` in
    check order (``spoke`` = the kind that produced the current outer
    bound, None when unknown)."""
    c = _cfg(cfg)
    checks = [b for b in bound_checks
              if isinstance(b.get("outer"), (int, float))]
    if len(checks) < c["stall_checks"]:
        return None
    last = checks[-1]
    anchor = last["outer"]
    tol = c["stall_rel_tol"] * max(1.0, abs(anchor))
    flat = 0
    for b in reversed(checks):
        if abs(b["outer"] - anchor) > tol:
            break
        flat += 1
    gap = last.get("rel_gap")
    if flat < c["stall_checks"] or not isinstance(gap, (int, float)) \
            or gap <= c["stall_gap_floor"]:
        return None
    spoke = next((b.get("spoke") for b in reversed(checks)
                  if b.get("spoke")), None)
    return {
        "verdict": "STALLED_OUTER",
        "severity": _SEVERITY["STALLED_OUTER"],
        "summary": f"outer bound flat {flat} checks at {anchor:g} "
                   f"while rel gap {gap:.3g}"
                   + (f" (spoke={spoke})" if spoke else ""),
        "evidence": {"spoke": spoke, "flat_checks": flat,
                     "outer": anchor, "rel_gap": gap,
                     "it": last.get("it")},
        "advice": "the outer-bound spoke stopped improving — check "
                  "its subproblem budget, dual step, or rho scale",
    }


def rule_oscillating(samples, cfg=None):
    """Persistent W sign-flips: the last sample's flip-EMA exceeds the
    threshold on average or on specific slots. ``samples`` is a list
    of ``ops.forensics.unpack`` dicts in sample order."""
    c = _cfg(cfg)
    if not samples:
        return None
    fx = samples[-1]
    if fx.get("samples", 0) < c["osc_min_samples"]:
        return None
    slots = [int(sid) for sid, v in fx.get("osc_slots", ())
             if v >= c["osc_slot_thresh"]]
    mean = fx.get("osc_mean") or 0.0
    if mean < c["osc_mean_thresh"] and not slots:
        return None
    return {
        "verdict": "OSCILLATING",
        "severity": _SEVERITY["OSCILLATING"],
        "summary": f"W sign-flip EMA {mean:.2f}"
                   + (f", slots {slots}" if slots else ""),
        "evidence": {"slots": slots, "osc_mean": mean,
                     "it": fx.get("it")},
        "advice": "rho up",
    }


def rule_culprit_scenarios(samples, cfg=None):
    """Residual concentration: the smallest scenario set carrying
    ``culprit_share`` of the primal residual is at most
    ``culprit_max_frac`` of the real scenarios."""
    c = _cfg(cfg)
    if not samples:
        return None
    fx = samples[-1]
    shares = fx.get("scen_pri_shares") or []
    n = fx.get("n_scens") or len(shares)
    if n < 4 or not shares:
        return None       # concentration is meaningless on tiny S
    cum, ids = 0.0, []
    for sid, share in shares:
        cum += share
        ids.append(int(sid))
        if cum >= c["culprit_share"]:
            break
    if cum < c["culprit_share"] or len(ids) > max(1, int(
            n * c["culprit_max_frac"])):
        return None
    return {
        "verdict": "CULPRIT_SCENARIOS",
        "severity": _SEVERITY["CULPRIT_SCENARIOS"],
        "summary": f"scenarios {ids} carry {cum:.0%} of the primal "
                   f"residual ({len(ids)}/{n})",
        "evidence": {"ids": ids, "share": cum, "n_scens": n,
                     "it": fx.get("it")},
        "advice": "inspect those scenarios' subproblems (bounds, "
                  "conditioning) or rebalance their rho rows",
    }


def rule_fixing_stall(shrink, it, cfg=None):
    """Progressive shrinking armed but the first fixed-fraction bucket
    was never crossed after ``fixing_stall_iters`` iterations.
    ``shrink`` is the engine's plain shrink-status dict plus a
    ``"first_bucket"`` key."""
    c = _cfg(cfg)
    if not shrink or not isinstance(it, (int, float)) \
            or it < c["fixing_stall_iters"] \
            or shrink.get("compactions", 0) > 0:
        return None
    bucket = shrink.get("first_bucket")
    fixed = shrink.get("fixed", 0)
    free = shrink.get("free", 0)
    total = fixed + free
    frac = fixed / total if total else 0.0
    if bucket is None or frac >= bucket:
        return None
    return {
        "verdict": "FIXING_STALL",
        "severity": _SEVERITY["FIXING_STALL"],
        "summary": f"bucket {bucket:g} never crossed "
                   f"(fixed {frac:.0%} after {int(it)} iters)",
        "evidence": {"bucket": bucket, "fixed_frac": frac,
                     "it": int(it)},
        "advice": "loosen the fixer tolerance or drop the first "
                  "bucket — the active set is not shrinking",
    }


def diagnose(samples, bound_checks, shrink=None, it=None, cfg=None):
    """Run every rule; returns the fired verdicts ranked most-severe
    first (empty list = HEALTHY). Pure — both the live engine and
    analyze's post-mortem path call exactly this."""
    if it is None and samples:
        it = samples[-1].get("it")
    verdicts = [v for v in (
        rule_stalled_outer(bound_checks, cfg),
        rule_oscillating(samples, cfg),
        rule_culprit_scenarios(samples, cfg),
        rule_fixing_stall(shrink, it, cfg),
    ) if v is not None]
    verdicts.sort(key=lambda v: -v["severity"])
    return verdicts


def overall(verdicts) -> str:
    return verdicts[0]["verdict"] if verdicts else "HEALTHY"


# ---------------- the live engine ----------------

_MAX_SAMPLES = 64          # bounded history: rules read the tail
_MAX_CHECKS = 256


class _State:
    """Per-telemetry-session diagnosis state (the ``obs/profile.py``
    mold: identity-checked against the active Recorder so tests that
    reconfigure sessions never inherit stale history)."""

    __slots__ = ("rec", "lock", "samples", "bound_checks", "shrink",
                 "verdict", "last")

    def __init__(self, rec):
        self.rec = rec
        self.lock = threading.Lock()
        self.samples = []          # forensic sample dicts, tail-capped
        self.bound_checks = []     # hub bound-check dicts, tail-capped
        self.shrink = None         # latest shrink status (plain dict)
        self.verdict = "HEALTHY"
        self.last = {}             # plain dict: the signal-safe view


_STATE: _State | None = None
_STATE_LOCK = threading.Lock()


def _state() -> _State | None:
    global _STATE
    rec = _active()
    if rec is None:
        return None
    s = _STATE
    if s is None or s.rec is not rec:
        with _STATE_LOCK:
            s = _STATE
            if s is None or s.rec is not rec:
                s = _STATE = _State(rec)
    return s


def _refresh(s: _State, it=None):
    """Re-run the rules and rebind the snapshot; emit the transition
    event when the overall verdict changes."""
    with s.lock:
        samples = list(s.samples)
        checks = list(s.bound_checks)
        shrink = dict(s.shrink) if s.shrink else None
    verdicts = diagnose(samples, checks, shrink, it=it)
    name = overall(verdicts)
    fx = samples[-1] if samples else {}
    top_slot = (fx.get("top_slots") or [[None, None]])[0]
    top_scen = (fx.get("scen_pri_shares") or [[None, None]])[0]
    snap = {
        "verdict": name,
        "verdicts": verdicts,
        "top_slot": top_slot[0],
        "top_slot_mass": top_slot[1],
        "top_scen": top_scen[0],
        "top_scen_share": top_scen[1],
        "osc_mean": fx.get("osc_mean"),
        "samples": len(samples),
        "it": it if it is not None else fx.get("it"),
    }
    if name != s.verdict:
        counter_add("forensics.verdict_changes")
        event("forensics.verdict", {
            "verdict": name, "prev": s.verdict, "it": snap["it"],
            "summary": verdicts[0]["summary"] if verdicts else "",
            "evidence": verdicts[0]["evidence"] if verdicts else {}})
    gauge_set("forensics.unhealthy", 0.0 if name == "HEALTHY" else 1.0)
    s.verdict = name
    # rebind, don't mutate: signal handlers and the hub status thread
    # see either the old complete dict or the new one, never a torn mix
    s.last = snap
    return snap


def note_sample(fx: dict, shrink=None):
    """One forensic sample from ``core/ph.py``'s iteration record:
    append to the bounded history, book the ``forensics.*`` gauges,
    emit the compact ``forensics.sample`` event, re-diagnose. Returns
    the refreshed snapshot (None when telemetry is off)."""
    s = _state()
    if s is None:
        return None
    with s.lock:
        s.samples.append(fx)
        del s.samples[:-_MAX_SAMPLES]
        if shrink is not None:
            s.shrink = dict(shrink)
    counter_add("forensics.samples")
    top_slot = (fx.get("top_slots") or [[None, None]])[0]
    top_scen = (fx.get("scen_pri_shares") or [[None, None]])[0]
    if top_slot[0] is not None:
        gauge_set("forensics.top_slot", float(top_slot[0]))
        gauge_set("forensics.top_slot_mass", float(top_slot[1]))
    if top_scen[0] is not None:
        gauge_set("forensics.top_scen", float(top_scen[0]))
        gauge_set("forensics.top_scen_share", float(top_scen[1]))
    if fx.get("osc_mean") is not None:
        gauge_set("forensics.osc_mean", fx["osc_mean"])
    if fx.get("rho_log_ratio_mean") is not None:
        gauge_set("forensics.rho_log_ratio", fx["rho_log_ratio_mean"])
    event("forensics.sample", {
        "it": fx.get("it"), "conv": fx.get("conv"),
        "osc_mean": fx.get("osc_mean"),
        "rho_log_ratio_mean": fx.get("rho_log_ratio_mean"),
        "xbar_move": fx.get("xbar_move"),
        "top_slots": fx.get("top_slots"),
        "scen_pri_shares": fx.get("scen_pri_shares"),
        "scen_dua_shares": fx.get("scen_dua_shares")})
    return _refresh(s, it=fx.get("it"))


def note_bound_check(it, outer, inner, rel_gap, spoke=None):
    """One hub termination check (``cylinders/hub.py``): the bound
    trajectory the STALLED_OUTER rule watches. ``spoke`` = the kind
    that produced the current outer bound, when the hub knows it."""
    s = _state()
    if s is None:
        return None
    with s.lock:
        s.bound_checks.append({"it": it, "outer": outer,
                               "inner": inner, "rel_gap": rel_gap,
                               "spoke": spoke})
        del s.bound_checks[:-_MAX_CHECKS]
    return _refresh(s, it=it)


def snapshot():
    """The current diagnosis as a plain dict (None when telemetry is
    off or nothing has been noted). Safe from signal handlers: one
    attribute read, no locks."""
    s = _STATE
    rec = _active()
    if s is None or rec is None or s.rec is not rec:
        return None
    return s.last or None
