"""Span recording + Chrome trace-event export.

Spans are host wall-clock intervals (``time.perf_counter`` pairs)
buffered as Chrome trace-event "X" (complete) records and written as
one ``trace.json`` loadable in Perfetto / chrome://tracing. The PH
pipeline phases (assemble/solve/gate/reduce), per-chunk solves and
per-chunk lanes all land here; lanes map to Chrome ``tid`` so
concurrent work renders as parallel tracks.

Two recording styles:
 - ``complete(name, t0, t1)`` — the hot-loop style: the caller already
   holds the perf_counter marks (PH's ``_lap`` accounting), so the span
   costs one list append and stays EXACTLY consistent with
   ``PHBase.phase_timing`` (same timestamps, same totals).
 - ``span(name)`` — a context manager for code that isn't already
   timing itself. With ``jax_annotations=True`` it also enters a
   ``jax.profiler.TraceAnnotation`` so host spans line up with XLA
   device activity inside a ``jax.profiler.trace`` capture.
"""

from __future__ import annotations

import json
import os
import threading
import time


class Span:
    """Context-manager span; records a complete event on exit."""

    __slots__ = ("_buf", "name", "cat", "args", "lane", "_t0", "_ann")

    def __init__(self, buf, name, cat, args, lane, jax_annotation=False):
        self._buf = buf
        self.name = name
        self.cat = cat
        self.args = args
        self.lane = lane
        self._t0 = None
        self._ann = None
        if jax_annotation:
            try:
                from jax.profiler import TraceAnnotation
                self._ann = TraceAnnotation(name)
            except Exception:   # profiler unavailable: host span only
                self._ann = None

    def __enter__(self):
        if self._ann is not None:
            self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        self._buf.complete(self.name, self._t0, t1, cat=self.cat,
                           args=self.args, lane=self.lane)
        return False


class TraceBuffer:
    """In-memory Chrome trace-event buffer, flushed to one JSON file."""

    def __init__(self, path=None, run_id=None, jax_annotations=False,
                 role=None):
        self.path = path
        self.run_id = run_id
        self.role = role
        self.jax_annotations = bool(jax_annotations)
        self._lock = threading.Lock()
        self._events = []
        self._pid = os.getpid()
        # (wall clock, perf_counter) pair read back-to-back: the only
        # sanctioned way to put this process's monotonic span stamps on
        # a cross-process timeline (obs/merge.py aligns role traces
        # from exactly this anchor)
        self.anchor = {"wall_time_unix": time.time(),
                       "perf_counter": time.perf_counter()}
        self._lanes = {}          # lane name -> tid + emitted metadata
        name = f"mpisppy_tpu:{run_id or self._pid}"
        if role:
            name += f":{role}"
        self._meta(self._pid, 0, "process_name", {"name": name})

    def _meta(self, pid, tid, name, args):
        self._events.append({"name": name, "ph": "M", "pid": pid,
                             "tid": tid, "args": args})

    def _tid(self, lane):
        """Map a logical lane (None = host thread, str = named track
        like ``dev0``) to a stable Chrome tid, emitting thread_name
        metadata on first use."""
        if lane is None:
            return threading.get_ident() % 2 ** 31
        tid = self._lanes.get(lane)
        if tid is None:
            tid = self._lanes[lane] = 1 + len(self._lanes)
            self._meta(self._pid, tid, "thread_name", {"name": str(lane)})
        return tid

    def complete(self, name, t0, t1, cat="host", args=None, lane=None):
        """Record a complete ("X") span from explicit perf_counter
        marks; timestamps convert to the microseconds Chrome expects."""
        ev = {"name": name, "ph": "X", "cat": cat,
              "ts": t0 * 1e6, "dur": max(t1 - t0, 0.0) * 1e6,
              "pid": self._pid}
        with self._lock:
            ev["tid"] = self._tid(lane)
            if args:
                ev["args"] = args
            self._events.append(ev)

    def instant(self, name, cat="host", args=None, lane=None):
        ev = {"name": name, "ph": "i", "s": "t", "cat": cat,
              "ts": time.perf_counter() * 1e6, "pid": self._pid}
        with self._lock:
            ev["tid"] = self._tid(lane)
            if args:
                ev["args"] = args
            self._events.append(ev)

    def span(self, name, cat="host", args=None, lane=None):
        return Span(self, name, cat, args, lane,
                    jax_annotation=self.jax_annotations)

    def to_json(self, nonblocking=False):
        """Trace dict, or None when ``nonblocking`` and the lock is
        held (signal-handler context: the interrupted frame underneath
        may own it — blocking there would self-deadlock)."""
        if nonblocking:
            if not self._lock.acquire(blocking=False):
                return None
        else:
            self._lock.acquire()
        try:
            return {"traceEvents": list(self._events),
                    "displayTimeUnit": "ms",
                    "metadata": {"run_id": self.run_id,
                                 "role": self.role,
                                 "clock": "perf_counter_us",
                                 **self.anchor}}
        finally:
            self._lock.release()

    def flush(self, nonblocking=False):
        """Atomically (re)write the whole trace file. Nonblocking mode
        skips (returns) when the buffer lock is unavailable."""
        if self.path is None:
            return
        data = self.to_json(nonblocking=nonblocking)
        if data is None:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, self.path)
