"""Structure-packed shared constraint matrix: the matvec representation
that stops the ADMM hot loop from streaming gigabytes of zeros.

The reference hands each scenario LP/MIP to Gurobi, whose simplex works
the ~101k-nonzero sparse matrix directly (ref. examples/uc/2013-05-11:
~0.03% dense at 25836 x 25836-ish scale). The TPU kernel's dense matmul
formulation (ops/qp_solver._Ax) instead reads the full (m, n) f32 pair
from HBM on every pass — at reference-UC scale that is ~2.7 GB per
split matvec and ~80% of the hot loop's memory traffic, which is why
BENCH_r04 measured 3.8% MFU (the chip spends its bandwidth on zeros).

TPUs have no efficient general gather/scatter sparse matmul, but SP
constraint matrices are not generally sparse — they are STRUCTURED:

 - a few GLOBAL rows coupling most columns (UC: the per-hour balance
   and reserve rows — 96 of 25836 rows), and
 - a block-local remainder: rows touching only one small column group
   (UC: capacity/startup/min-up/min-down/ramp rows of one generator
   touch only that generator's u/st/p columns).

Union-find on the host sparsity pattern (already in hand at ship time —
core/spbase.ship_shared_matrix scatters from it) discovers this
generically, with no model-specific code: rows above an nnz threshold
go global, the rest partition into connected components of shared
columns. The packed form is then

    A x  =  scatter_rows( einsum over (C, mr, nc) component blocks )
          + scatter_rows( G @ x )            with G the (R, n) global rows

— one small batched MXU matmul plus one thin dense matmul plus two
gathers/scatters, all XLA-native. On the 90x48 UC instance the packed
operand set is ~1.5% of the dense matrix's bytes (C=90 components of
286 x 144 plus 96 global rows), turning every A-pass from ~3.4 ms of
HBM streaming into ~0.2 ms of mostly-MXU work. Models without local
structure simply fail the profitability test and keep the dense path.

Exactness: each nonzero lands in exactly one term (component blocks are
bounding boxes over disjoint row/column sets; global rows are disjoint
from local rows), so packed apply equals dense apply up to f32 summation
order. df32 callers accumulate the three split passes in f64 exactly as
the dense path does (ops/qp_solver.SplitMatrix).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class PackStructure(NamedTuple):
    """Host-derived index skeleton (values not yet attached). Index
    arrays are pytree children (device-shippable); padding entries are
    -1 and masked at pack() time — padding with a real index would
    gather that row's true values into slots that must read zero."""
    g_rows: jax.Array      # (R,) int32 global-row indices (may be empty)
    l_rows: jax.Array      # (C, mr) int32, -1 padded
    l_cols: jax.Array      # (C, nc) int32, -1 padded


class Packed(NamedTuple):
    """PackStructure + gathered values for ONE dense matrix. Indices
    here are clamped to valid range (masking already applied to vals)."""
    g_rows: jax.Array      # (R,) int32
    g_vals: jax.Array      # (R, n)
    l_rows: jax.Array      # (C, mr) int32, padding clamped to 0
    l_cols: jax.Array      # (C, nc) int32, padding clamped to 0
    l_vals: jax.Array      # (C, mr, nc), padded rows/cols zeroed


def analyze_structure(rows, cols, m, n, nnz_thresholds=None,
                      max_tile=2048, max_traffic_ratio=0.35,
                      max_global_frac=0.25, max_attempts=16):
    """Host structure discovery from the COO pattern (rows, cols).
    Returns a PackStructure, or None when the matrix has no profitable
    global/local split (callers keep the dense path).

    Tries progressively stricter nnz thresholds for the global-row set:
    a looser threshold keeps more rows local (cheaper), but a hub-like
    row (UC balance: 182 nnz) left local would union every generator
    into one giant component. The ladder is DERIVED from the distinct
    per-row nnz values (descending) — fixed rungs miss instances whose
    coupling rows (reserve: G nnz) sit between them at small G.
    Accepts the first threshold whose components fit (max_tile) and
    whose packed operand bytes are below ``max_traffic_ratio`` of
    dense."""
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    if rows.size == 0:
        return None
    row_nnz = np.bincount(rows, minlength=m)
    if nnz_thresholds is None:
        # thr = v keeps rows with nnz <= v local; each distinct value
        # is a potential cut between "local" and "coupling" rows
        distinct = np.unique(row_nnz[row_nnz > 1])[::-1]
        if distinct.size > max_attempts:
            # keep the small end dense (fine cuts matter there) and
            # subsample the large end
            head = distinct[distinct <= 64]
            tail = distinct[distinct > 64]
            if tail.size > max_attempts - head.size:
                sel = np.linspace(0, tail.size - 1,
                                  max(1, max_attempts - head.size))
                tail = tail[sel.astype(int)]
            distinct = np.concatenate([tail, head])[:max_attempts]
        nnz_thresholds = [int(v) for v in distinct]

    for thr in nnz_thresholds:
        g_mask = row_nnz > thr
        if g_mask.sum() > max_global_frac * m:
            continue
        local = ~g_mask[rows]
        lr, lc = rows[local], cols[local]
        if lr.size == 0:
            return None
        # connected components of the bipartite row/column adjacency
        # graph through scipy's C union-find (ADVICE r5: the previous
        # pure-Python per-threshold union-find cost seconds of
        # single-core host time per shipped matrix at reference scale;
        # csgraph runs the same partition in milliseconds). Nodes
        # 0..n-1 are columns, n.. are the local rows (reindexed); a
        # row node links every column it touches, so column components
        # match the row-merged column partition exactly.
        from scipy.sparse import coo_matrix, csgraph
        row_ids, rpos = np.unique(lr, return_inverse=True)
        g = coo_matrix((np.ones(lr.size, np.int8), (lc, n + rpos)),
                       shape=(n + row_ids.size, n + row_ids.size))
        _, labels = csgraph.connected_components(g, directed=False)
        used_cols = np.unique(lc)
        # deterministic component ids: first appearance over ascending
        # used-column index (the layout the union-find produced)
        comp_ids = {}
        for lab in labels[used_cols]:
            comp_ids.setdefault(int(lab), len(comp_ids))
        C = len(comp_ids)
        col_lists = [[] for _ in range(C)]
        for c in used_cols:
            col_lists[comp_ids[int(labels[c])]].append(c)
        row_lists = [[] for _ in range(C)]
        for i, r in enumerate(row_ids):
            row_lists[comp_ids[int(labels[n + i])]].append(r)
        mr = max(len(x) for x in row_lists)
        nc = max(len(x) for x in col_lists)
        if mr > max_tile or nc > max_tile:
            continue
        R = int(g_mask.sum())
        packed_elems = C * mr * nc + R * n
        if packed_elems > max_traffic_ratio * m * n:
            continue
        l_rows = np.full((C, mr), -1, np.int32)
        l_cols = np.full((C, nc), -1, np.int32)
        for i, (rl, cl) in enumerate(zip(row_lists, col_lists)):
            l_rows[i, :len(rl)] = rl
            l_cols[i, :len(cl)] = cl
        return PackStructure(
            g_rows=jnp.asarray(np.flatnonzero(g_mask).astype(np.int32)),
            l_rows=jnp.asarray(l_rows), l_cols=jnp.asarray(l_cols))
    return None


def pk_nbytes(pk: Packed) -> int:
    """Bytes of matrix operands one packed A-pass streams from HBM (the
    value arrays; index vectors are noise). The observability companion
    to the per-phase pipeline timing: bench.py records the hi+lo packed
    operand footprint in its uc1024 JSON row next to MFU, making the
    bandwidth-bound cost basis of the hot loop auditable (see
    doc/roofline.md — dense-equivalent MFU understates a packed kernel
    by the sparsity factor)."""
    return int(pk.g_vals.size * pk.g_vals.dtype.itemsize
               + pk.l_vals.size * pk.l_vals.dtype.itemsize)


@jax.jit
def pack(structure: PackStructure, dense) -> Packed:
    """Gather one dense (m, n) device matrix into packed form. Padded
    index slots (-1) clamp to 0 for the gather and their values are
    zeroed — position (0, c) holds real matrix data, which must not
    leak into padding. Narrow-storage (bf16) twins of a packed set are
    built from it by ops/kernels.reference.bf16_packed, behind that
    layer's quantization gate; the matvecs below keep f32 ACCUMULATION
    regardless of value-storage dtype (see _pk_einsum)."""
    lr = jnp.maximum(structure.l_rows, 0)
    lc = jnp.maximum(structure.l_cols, 0)
    vals = dense[lr[:, :, None], lc[:, None, :]]
    mask = (structure.l_rows >= 0)[:, :, None] \
        & (structure.l_cols >= 0)[:, None, :]
    vals = jnp.where(mask, vals, 0)
    return Packed(g_rows=structure.g_rows, g_vals=dense[structure.g_rows],
                  l_rows=lr, l_cols=lc, l_vals=vals)


def _pk_einsum(spec, a, vals):
    """Block einsum with the accumulator pinned to the ACTIVATION dtype:
    bf16-stored blocks stream half the bytes but must not accumulate in
    bf16 (the MXU consumes narrow operands natively; XLA fuses the
    widening into the dot read). Same-dtype operands keep the exact
    historical spelling — bit-identical to the pre-bf16 path."""
    if vals.dtype != a.dtype:
        return jnp.einsum(spec, a, vals, preferred_element_type=a.dtype)
    return jnp.einsum(spec, a, vals)


def _pk_gmat(a, g_vals):
    """Thin global-row matmul twin of _pk_einsum (a @ g_vals.T or
    a @ g_vals spelled by the caller via pre-transposition)."""
    if g_vals.dtype != a.dtype:
        return jnp.matmul(a, g_vals, preferred_element_type=a.dtype)
    return a @ g_vals


def pk_Ax(pk: Packed, x, m):
    """A x via the packed form: x (S, n) -> (S, m). Low-precision value
    storage (bf16 blocks) accumulates in x's dtype (see _pk_einsum)."""
    S = x.shape[0]
    xg = x[:, pk.l_cols]                          # (S, C, nc)
    loc = _pk_einsum("scn,cmn->scm", xg, pk.l_vals)
    out = jnp.zeros((S, m), x.dtype)
    out = out.at[:, pk.l_rows.reshape(-1)].add(loc.reshape(S, -1))
    if pk.g_rows.size:
        out = out.at[:, pk.g_rows].add(_pk_gmat(x, pk.g_vals.T))
    return out


def pk_ATy(pk: Packed, y, n):
    """Aᵀ y via the packed form: y (S, m) -> (S, n). Low-precision value
    storage (bf16 blocks) accumulates in y's dtype (see _pk_einsum)."""
    S = y.shape[0]
    yg = y[:, pk.l_rows]                          # (S, C, mr)
    loc = _pk_einsum("scm,cmn->scn", yg, pk.l_vals)
    out = jnp.zeros((S, n), y.dtype)
    out = out.at[:, pk.l_cols.reshape(-1)].add(loc.reshape(S, -1))
    if pk.g_rows.size:
        out = out + _pk_gmat(y[:, pk.g_rows], pk.g_vals)
    return out


def pk_Ax_split(pk_hi: Packed, pk_lo: Packed, xh, xl, m):
    """The df32 three-pass matvec (hi·xh + lo·xh + hi·xl, f64 accum —
    the SplitMatrix contract) through the packed form. hi and lo share
    one index skeleton, so x gathers once per operand and the three
    f32 einsum results accumulate in f64 BEFORE a single scatter —
    one f64 scatter instead of three f32 ones."""
    S = xh.shape[0]
    f64 = jnp.float64
    xgh = xh[:, pk_hi.l_cols]
    xgl = xl[:, pk_hi.l_cols]
    loc = (jnp.einsum("scn,cmn->scm", xgh, pk_hi.l_vals).astype(f64)
           + jnp.einsum("scn,cmn->scm", xgh, pk_lo.l_vals).astype(f64)
           + jnp.einsum("scn,cmn->scm", xgl, pk_hi.l_vals).astype(f64))
    out = jnp.zeros((S, m), f64)
    out = out.at[:, pk_hi.l_rows.reshape(-1)].add(loc.reshape(S, -1))
    if pk_hi.g_rows.size:
        g = ((xh @ pk_hi.g_vals.T).astype(f64)
             + (xh @ pk_lo.g_vals.T).astype(f64)
             + (xl @ pk_hi.g_vals.T).astype(f64))
        out = out.at[:, pk_hi.g_rows].add(g)
    return out


def pk_ATy_split(pk_hi: Packed, pk_lo: Packed, yh, yl, n):
    """Transpose twin of pk_Ax_split."""
    S = yh.shape[0]
    f64 = jnp.float64
    ygh = yh[:, pk_hi.l_rows]
    ygl = yl[:, pk_hi.l_rows]
    loc = (jnp.einsum("scm,cmn->scn", ygh, pk_hi.l_vals).astype(f64)
           + jnp.einsum("scm,cmn->scn", ygh, pk_lo.l_vals).astype(f64)
           + jnp.einsum("scm,cmn->scn", ygl, pk_hi.l_vals).astype(f64))
    out = jnp.zeros((S, n), f64)
    out = out.at[:, pk_hi.l_cols.reshape(-1)].add(loc.reshape(S, -1))
    if pk_hi.g_rows.size:
        g = ((yh[:, pk_hi.g_rows] @ pk_hi.g_vals).astype(f64)
             + (yh[:, pk_hi.g_rows] @ pk_lo.g_vals).astype(f64)
             + (yl[:, pk_hi.g_rows] @ pk_hi.g_vals).astype(f64))
        out = out + g
    return out
