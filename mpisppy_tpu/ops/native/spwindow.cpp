// Native shared-memory communication windows for multi-process cylinders.
//
// The reference's cylinders exchange bounds/weights through MPI one-sided
// RMA windows with a write-id freshness protocol (ref. mpisppy/cylinders/
// spcommunicator.py:97-124: each buffer is length+1 doubles, the last slot
// a monotonically increasing write-id; -1 is the kill signal, hub.py:356).
// This is the same protocol over POSIX shared memory with a SEQLOCK in
// place of MPI passive-target locks: the single writer bumps an atomic
// sequence to odd, writes the payload and the write-id, and bumps back to
// even; readers retry while the sequence is odd or changed mid-copy.
// One writer, many readers, no locks held across processes, no reader can
// block the writer — the same progress guarantees the reference leans on
// MPI RMA for (README.rst:41-56 async-progress warnings).
//
// Python binding: ctypes (see __init__.py); exposed to the framework as
// Window.shared(...) in cylinders/spcommunicator.py.

#include <atomic>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <unistd.h>

namespace {

struct Header {
    std::atomic<int64_t> seq;        // seqlock: odd while a write is in flight
    std::atomic<int64_t> write_id;   // monotone counter; -1 == kill
    int64_t length;                  // payload doubles
};

struct Handle {
    Header *h;
    double *data;
    size_t bytes;
    char name[256];
};

Handle *map_window(const char *name, int64_t length, bool create) {
    size_t bytes = sizeof(Header) + static_cast<size_t>(length) * sizeof(double);
    int flags = create ? (O_CREAT | O_RDWR) : O_RDWR;
    int fd = shm_open(name, flags, 0600);
    if (fd < 0) return nullptr;
    if (create && ftruncate(fd, static_cast<off_t>(bytes)) != 0) {
        close(fd);
        shm_unlink(name);
        return nullptr;
    }
    void *mem = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    close(fd);
    if (mem == MAP_FAILED) return nullptr;
    Handle *hd = new Handle;
    hd->h = static_cast<Header *>(mem);
    hd->data = reinterpret_cast<double *>(static_cast<char *>(mem) + sizeof(Header));
    hd->bytes = bytes;
    strncpy(hd->name, name, sizeof(hd->name) - 1);
    hd->name[sizeof(hd->name) - 1] = '\0';
    if (create) {
        hd->h->seq.store(0, std::memory_order_relaxed);
        hd->h->write_id.store(0, std::memory_order_relaxed);
        hd->h->length = length;
        memset(hd->data, 0, static_cast<size_t>(length) * sizeof(double));
    }
    return hd;
}

}  // namespace

extern "C" {

void *spw_create(const char *name, int64_t length) {
    return map_window(name, length, true);
}

void *spw_open(const char *name, int64_t length) {
    return map_window(name, length, false);
}

// owner side (ref. hub.py:310-331 hub_to_spoke / spoke.py:59-80)
void spw_put(void *p, const double *vals, int64_t n) {
    Handle *hd = static_cast<Handle *>(p);
    hd->h->seq.fetch_add(1, std::memory_order_acq_rel);       // -> odd
    memcpy(hd->data, vals, static_cast<size_t>(n) * sizeof(double));
    int64_t id = hd->h->write_id.load(std::memory_order_relaxed);
    if (id >= 0)
        hd->h->write_id.store(id + 1, std::memory_order_relaxed);
    hd->h->seq.fetch_add(1, std::memory_order_release);       // -> even
}

void spw_kill(void *p) {
    static_cast<Handle *>(p)->h->write_id.store(-1, std::memory_order_release);
}

// reader side (ref. hub.py:333-354 hub_from_spoke / spoke.py:82-99).
// The retry loop is BOUNDED: if the writer died mid-put (seq left odd)
// the reader must not spin forever — after ~1e8 retries it returns
// INT64_MIN, which every caller treats as "not fresh" and skips.
int64_t spw_read(void *p, double *out, int64_t n) {
    Handle *hd = static_cast<Handle *>(p);
    for (int64_t tries = 0; tries < 100000000LL; ++tries) {
        int64_t s0 = hd->h->seq.load(std::memory_order_acquire);
        if (s0 & 1) continue;                                 // write in flight
        memcpy(out, hd->data, static_cast<size_t>(n) * sizeof(double));
        int64_t id = hd->h->write_id.load(std::memory_order_acquire);
        int64_t s1 = hd->h->seq.load(std::memory_order_acquire);
        if (s0 == s1) return id;                              // consistent copy
    }
    return INT64_MIN;                                         // dead writer
}

int64_t spw_read_id(void *p) {
    return static_cast<Handle *>(p)->h->write_id.load(std::memory_order_acquire);
}

void spw_close(void *p, int unlink_it) {
    Handle *hd = static_cast<Handle *>(p);
    munmap(static_cast<void *>(hd->h), hd->bytes);
    if (unlink_it) shm_unlink(hd->name);
    delete hd;
}

}  // extern "C"
