"""Build + ctypes binding for the native shared-memory window backend.

Compiled on demand with g++ (no pybind11 in this image; the C ABI +
ctypes is all the binding this needs). The .so is cached next to the
source and rebuilt when the source is newer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "spwindow.cpp")
_SO = os.path.join(_HERE, "libspwindow.so")
_lock = threading.Lock()
_lib = None


def _build():
    import sys

    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", _SO,
           _SRC]
    if sys.platform.startswith("linux"):
        # shm_open/shm_unlink live in librt on pre-2.34 glibc (the flag
        # is harmless where they moved into libc); macOS has no librt
        # and keeps them in libc, so the flag must stay Linux-only
        cmd.append("-lrt")
    subprocess.run(cmd, check=True, capture_output=True)


def load():
    """Compile (if stale) and load the spwindow library."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            _build()
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            # a prebuilt .so from a different toolchain (e.g. missing
            # the librt link, surfacing as "undefined symbol:
            # shm_open") — rebuild in place for THIS toolchain
            _build()
            lib = ctypes.CDLL(_SO)
        lib.spw_create.restype = ctypes.c_void_p
        lib.spw_create.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.spw_open.restype = ctypes.c_void_p
        lib.spw_open.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.spw_put.restype = None
        lib.spw_put.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_double),
                                ctypes.c_int64]
        lib.spw_read.restype = ctypes.c_int64
        lib.spw_read.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_double),
                                 ctypes.c_int64]
        lib.spw_read_id.restype = ctypes.c_int64
        lib.spw_read_id.argtypes = [ctypes.c_void_p]
        lib.spw_kill.restype = None
        lib.spw_kill.argtypes = [ctypes.c_void_p]
        lib.spw_close.restype = None
        lib.spw_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        _lib = lib
        return _lib
