from .qp_solver import (QPData, QPFactors, QPState, qp_setup, qp_solve,  # noqa: F401
                        qp_cold_state, qp_objective, qp_dual_objective,
                        qp_repair_duals, qp_state_duals, benders_cut)
