from .qp_solver import QPData, QPFactors, QPState, qp_setup, qp_solve, fold_bounds  # noqa: F401
