"""Pallas TPU backend: the whole ADMM iteration block — x-update
matmul, relaxed z-projections, dual updates, and the stacked residual
reduction — as ONE kernel whose operands load into VMEM once and stay
there for every iteration, instead of the XLA program's one-HBM-round-
trip-per-op dataflow.

This is the PRIMARY backend for real chips: at chunk scale the fused
iteration's working set (the (n, n) solve operator + the packed blocks
+ the (S, m)/(S, n) iterates) is what the roofline says the loop
streams from HBM every iteration — holding it in VMEM across the
in-kernel ``fori_loop`` converts the bandwidth-bound tail into compute.
Off-chip (tier-1 CPU), the same kernel runs under ``interpret=True`` so
the backend's MATH is covered without TPU hardware; the parity test
pins it against the reference fused-scan backend.

Deliberate scope (the production tiling plan lives in doc/kernels.md):

 - SHARED-structure dense operands only (one (m, n) A, one solve
   operator) — the representation the chunked PH loop requires anyway;
 - the solve operator is an EXPLICIT inverse: the f64 M⁻¹ the shared
   factorization already carries (one MXU matmul per x-update) or the
   kernel layer's L⁻¹ pair (two matmuls — qp_solver.LInv). Triangular
   back-substitution has no efficient Pallas spelling, which is the
   same latency argument behind roofline headroom item 1;
 - rho is FIXED for the duration of one block (the OSQP adaptation
   rule needs a refactorization the kernel cannot express) — the
   driver folds ``state.rho_scale`` into the row patterns and the
   reference path handles adaptation between blocks;
 - SCENARIO-AXIS GRID TILING (the production tiling item of
   doc/kernels.md, landed): per-scenario operands (q/l/u/lb/ub and the
   five iterate blocks) split into ``scen_tile``-row blocks over a 1-D
   grid while the shared operands (A, the solve operator, scalings)
   broadcast to every program instance — so a COMPACTED block
   (ops/shrink: small K after active-set compaction) keeps its whole
   working set VMEM-resident per tile instead of spilling the full
   scenario axis. Scenario rows are independent through the entire
   iteration block (A/F are shared; projections, dual updates, and the
   residual maxima are row-local), so tiling is exact — the parity
   test pins tiled == untiled bit-for-bit under interpret mode.
   ``scen_tile=None`` picks the largest divisor of S at or under
   SCEN_TILE_TARGET (S itself when S is small); ``scen_tile=0``
   disables tiling (one program instance owns the whole chunk, the
   pre-tiling behavior).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..qp_solver import LInv, _scaled_problem

try:  # pallas ships with jax>=0.4.30 everywhere this repo runs
    from jax.experimental import pallas as pl
    HAVE_PALLAS = True
except Exception:  # pragma: no cover - environment without pallas
    pl = None
    HAVE_PALLAS = False

__all__ = ["HAVE_PALLAS", "pallas_supported", "fused_admm_block",
           "pick_scen_tile", "SCEN_TILE_TARGET"]

# target rows per grid tile: small enough that a tile's iterate
# working set stays VMEM-resident beside the shared operator at
# compacted-block sizes, large enough to keep the MXU matmuls square-
# ish. Power of two on purpose (the chunked loop's row counts are).
SCEN_TILE_TARGET = 128


def pick_scen_tile(S: int, target: int = SCEN_TILE_TARGET) -> int:
    """Largest divisor of S that is <= target (pallas grids need exact
    tiling — padding the scenario axis would fabricate rows whose
    residual maxima pollute the fused reduction). S itself when S is
    already at or under the target; 1-row tiles only for prime S."""
    S = int(S)
    if S <= target:
        return S
    for tile in range(target, 1, -1):
        if S % tile == 0:
            return tile
    return 1    # prime S: row tiles


def pallas_supported(factors, state) -> bool:
    """Whether THIS solve's operands fit the kernel's scope: shared
    dense A with an explicit-inverse solve operator (f64 M⁻¹ or LInv)."""
    if not HAVE_PALLAS:
        return False
    A_s = factors.A_s
    if getattr(A_s, "ndim", 0) != 2 or not isinstance(A_s, jax.Array):
        return False
    L = state.L
    if isinstance(L, LInv):
        return True
    return getattr(L, "ndim", 0) == 2 and L.dtype == jnp.float64


def _admm_block_kernel(A_ref, F_ref, Ps_ref, g_ref, q_ref,
                       l_ref, u_ref, lb_ref, ub_ref, rA_ref, rB_ref,
                       Einv_ref, Ebinv_ref, Dinvc_ref, D_ref,
                       x_ref, yA_ref, yB_ref, zA_ref, zB_ref,
                       ox_ref, oyA_ref, oyB_ref, ozA_ref, ozB_ref,
                       opri_ref, odua_ref, *, n_steps, sigma, alpha,
                       l_inv_pair):
    """The fused iteration block. Mirrors ops/qp_solver._solve_impl's
    ``one()`` update and ``_unscaled_residuals`` EXACTLY — the parity
    test compares against those, so any drift here is a test failure,
    not a silent divergence. ``sigma``/``alpha`` are compile-time
    constants (closing traced values over a pallas kernel body is not
    expressible; sigma is constant per factorization anyway)."""
    A = A_ref[:]
    F = F_ref[:]
    Ps, g, q_s = Ps_ref[:], g_ref[:], q_ref[:]
    l_s, u_s, lb_s, ub_s = l_ref[:], u_ref[:], lb_ref[:], ub_ref[:]
    rA, rB = rA_ref[:], rB_ref[:]

    def m_solve(rhs):
        if l_inv_pair:
            # x = L⁻ᵀ (L⁻¹ rhs): two MXU matmuls of the factor's bytes
            return (rhs @ F.T) @ F
        return rhs @ F          # explicit symmetric M⁻¹: one matmul

    def one(i, c):
        x, yA, yB, zA, zB = c
        rhs = sigma * x - q_s + (rA * zA - yA) @ A + g * (rB * zB - yB)
        x_t = m_solve(rhs)
        x_new = alpha * x_t + (1 - alpha) * x
        zA_t = x_t @ A.T
        zA_mix = alpha * zA_t + (1 - alpha) * zA
        zA_new = jnp.clip(zA_mix + yA / rA, l_s, u_s)
        yA_new = yA + rA * (zA_mix - zA_new)
        zB_t = g * x_t
        zB_mix = alpha * zB_t + (1 - alpha) * zB
        zB_new = jnp.clip(zB_mix + yB / rB, lb_s, ub_s)
        yB_new = yB + rB * (zB_mix - zB_new)
        return x_new, yA_new, yB_new, zA_new, zB_new

    x, yA, yB, zA, zB = jax.lax.fori_loop(
        0, n_steps, one,
        (x_ref[:], yA_ref[:], yB_ref[:], zA_ref[:], zB_ref[:]))
    ox_ref[:] = x
    oyA_ref[:] = yA
    oyB_ref[:] = yB
    ozA_ref[:] = zA
    ozB_ref[:] = zB
    # stacked residual reduction, fused: the UNSCALED primal/dual
    # maxima of _unscaled_residuals, computed while the iterates are
    # still VMEM-resident (the chunked PH gate consumes exactly these)
    Einv, Ebinv, Dinv_c, D = (Einv_ref[:], Ebinv_ref[:], Dinvc_ref[:],
                              D_ref[:])
    Ax = x @ A.T
    Aty = yA @ A
    opri_ref[:] = jnp.maximum(
        jnp.max(jnp.abs(Einv * (Ax - zA)), axis=1),
        jnp.max(jnp.abs(D * x - Ebinv * zB), axis=1))
    odua_ref[:] = jnp.max(
        jnp.abs(Dinv_c * (Ps * x + q_s + Aty + g * yB)), axis=1)


@partial(jax.jit,
         static_argnames=("sigma", "n_steps", "alpha", "interpret",
                          "l_inv_pair", "scen_tile"))
def _block_call(A, F, Ps, g, q_s, l_s, u_s, lb_s, ub_s, rA, rB,
                Einv, Ebinv, Dinv_c, D, x, yA, yB, zA, zB, sigma,
                n_steps, alpha, interpret, l_inv_pair, scen_tile=0):
    S, n = x.shape
    m = zA.shape[1]
    dt = x.dtype
    kern = partial(_admm_block_kernel, n_steps=n_steps, sigma=sigma,
                   alpha=alpha, l_inv_pair=l_inv_pair)
    out_shape = [jax.ShapeDtypeStruct((S, n), dt),   # x
                 jax.ShapeDtypeStruct((S, m), dt),   # yA
                 jax.ShapeDtypeStruct((S, n), dt),   # yB
                 jax.ShapeDtypeStruct((S, m), dt),   # zA
                 jax.ShapeDtypeStruct((S, n), dt),   # zB
                 jax.ShapeDtypeStruct((S,), dt),     # pri
                 jax.ShapeDtypeStruct((S,), dt)]     # dua
    operands = (A, F, Ps, g, q_s, l_s, u_s, lb_s, ub_s, rA, rB,
                Einv, Ebinv, Dinv_c, D, x, yA, yB, zA, zB)
    if not scen_tile or scen_tile >= S:
        # one program instance owns the whole chunk
        return pl.pallas_call(kern, out_shape=out_shape,
                              interpret=interpret)(*operands)
    # scenario-axis grid (doc/kernels.md production tiling): shared
    # operands broadcast (index map pinned at block 0), per-scenario
    # operands and ALL outputs tile the leading axis. Scenario rows
    # are independent through the whole iteration block, so this is
    # exact — no halo, no cross-tile reduction.
    assert S % scen_tile == 0, "scen_tile must divide the chunk rows"
    grid = (S // scen_tile,)

    def shared(shape):
        nd = len(shape)
        return pl.BlockSpec(shape, lambda i, _n=nd: (0,) * _n)

    def scen(shape):
        nd = len(shape)
        return pl.BlockSpec((scen_tile,) + shape[1:],
                            lambda i, _n=nd: (i,) + (0,) * (_n - 1))

    def scaling(arr):
        # factor scalings are (m,)/(n,) for shared factorizations; a
        # batched spelling (rank 2, leading S) tiles with the rows
        return scen(arr.shape) if arr.ndim == 2 else shared(arr.shape)

    in_specs = [shared(A.shape), shared(F.shape), scaling(Ps),
                scaling(g), scen(q_s.shape), scen(l_s.shape),
                scen(u_s.shape), scen(lb_s.shape), scen(ub_s.shape),
                scaling(rA), scaling(rB), scaling(Einv),
                scaling(Ebinv), scaling(Dinv_c),
                scaling(D), scen(x.shape), scen(yA.shape),
                scen(yB.shape), scen(zA.shape), scen(zB.shape)]
    out_specs = [scen((S, n)), scen((S, m)), scen((S, n)),
                 scen((S, m)), scen((S, n)), scen((S,)), scen((S,))]
    return pl.pallas_call(kern, out_shape=out_shape, grid=grid,
                          in_specs=in_specs, out_specs=out_specs,
                          interpret=interpret)(*operands)


def fused_admm_block(factors, data, q, state, n_steps, interpret=None,
                     sigma=None, scen_tile=None):
    """Run ``n_steps`` fused ADMM iterations on the scaled problem
    (factors, data, q) from ``state``; returns (x, yA, yB, zA, zB,
    pri, dua) — SCALED iterates (the QPState carry convention) plus the
    unscaled residual maxima. Scaling comes from the shared
    qp_solver._scaled_problem helper so this block iterates the exact
    problem _solve_impl would.

    ``sigma``: the host float of ``factors.sigma`` (a compile-time
    constant of the kernel). kernel_solve passes the plan's copy read
    once at prepare() time; the fallback below is for direct callers
    (parity tests) and pays one scalar D2H per block.

    ``scen_tile``: rows per grid tile over the scenario axis (None =
    pick_scen_tile's auto choice, 0 = untiled single program — see the
    module docstring; tiling is exact, pinned by the parity test)."""
    if interpret is None:
        # tier-1 coverage without a chip: interpret everywhere but TPU
        interpret = jax.default_backend() != "tpu"
    if sigma is None:
        # lint: ok[SYNC001] direct-caller fallback: kernel_solve passes the plan's host sigma (read once per factorization)
        sigma = float(factors.sigma)
    g, l_s, u_s, lb_s, ub_s, csx, q_s = _scaled_problem(factors, data, q)
    rs = state.rho_scale
    rA = factors.rho_A * rs
    rB = factors.rho_b * rs
    Einv = 1.0 / factors.E
    Ebinv = 1.0 / factors.Eb
    Dinv_c = 1.0 / (factors.D * csx)
    L = state.L
    l_inv_pair = isinstance(L, LInv)
    F = L.inv if l_inv_pair else L
    if scen_tile is None:
        scen_tile = pick_scen_tile(state.x.shape[0])
    return _block_call(factors.A_s, F, factors.P_s, g, q_s,
                       l_s, u_s, lb_s, ub_s, rA, rB, Einv, Ebinv,
                       Dinv_c, factors.D, state.x, state.yA, state.yB,
                       state.zA, state.zB, sigma=sigma,
                       n_steps=int(n_steps), alpha=1.6,
                       interpret=bool(interpret),
                       l_inv_pair=l_inv_pair, scen_tile=int(scen_tile))
