"""Kernel-backend layer for the ADMM subproblem solver: one fused
device program per solve instead of a host-driven segment loop.

Three modes, selected by ``subproblem_kernel_mode`` (utils/config /
engine options; anatomy in doc/kernels.md):

  ``segmented``  today's ops/qp_solver host-segmented drivers,
                 BIT-FOR-BIT — the dispatch below is never entered, so
                 the existing pipeline-equivalence suite is the
                 guarantee;
  ``fused``      the whole solve (f32 bulk + factor handoff + accurate
                 tail + polish) as one device program. Backends:
                 ``reference`` (XLA fused-scan — default everywhere,
                 the correctness oracle; reference.py) and ``pallas``
                 (the TPU VMEM-resident iteration block, exercised on
                 CPU via ``interpret=True``; pallas_kernel.py);
  ``auto``       fused wherever the solve is eligible (see
                 resolve_mode), segmented otherwise — the default.

Inside the fused program ride the two doc/roofline.md §5 trades:
explicit L⁻¹ matmuls for the df32 tail's triangular solves (behind
``l_inv_profitable``) and bf16 storage of the packed A-blocks for the
f32 bulk phase (explicit opt-in, behind ``bf16_gate`` with f32
fallback on trip — see prepare() on why "auto" never engages it).
Recovery solves (chunk retries, the scenario hospital) ALWAYS take
the segmented path in native precision — the existing quality-gate
machinery doubles as the fused path's full-precision fallback.

Counters: ``kernel.fused_iters`` (ADMM iterations executed by fused
programs), ``kernel.l_inv_factorizations`` (eager L⁻¹ builds),
``kernel.bf16_fallbacks`` (gate trips) — catalogued in
doc/observability.md.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ... import obs
from ...utils.config import (FUSED_IR_SWEEPS, KERNEL_BACKENDS,
                             KERNEL_BLOCK_DTYPES as BLOCK_DTYPES,
                             KERNEL_L_INV_MODES as L_INV_MODES,
                             KERNEL_MODES)
from ..qp_solver import (LInv, PackedMatrix, SplitMatrix,
                         _needs_host_factor, _trace_seg, qp_solve)
from . import pallas_kernel
from .reference import (BF16_GATE_REL, bf16_gate, bf16_packed,
                        fused_mixed_solve, l_inv_profitable)


# the measured TPU per-execution watchdog ceiling for f64-involving
# device programs (qp_solve_segmented's raison d'être: hard worker
# crashes on UC-size solves above ~500 f64 iterations per call; the
# f32 bulk is exempt — "the measured watchdog ceiling binds
# f64-involving executions only", qp_solve_mixed). ``auto`` refuses to
# fuse a longer f64 stretch on TPU; explicit ``fused`` is the
# driver-run experiment knob (fusion removes the per-iteration host
# syncs, which may change the wall-time-per-execution math — that is
# exactly what the chip run measures).
WATCHDOG_F64_ITERS = 500


def resolve_mode(mode: str, factors, *, f64_stretch=0) -> str:
    """``auto`` resolution. A solve is fused-eligible unless (a) its
    rho adaptation must run on the HOST (non-shared f64 factors on a
    backend with untrusted f64 device linalg — qp_solver
    ._needs_host_factor): the fused program cannot call back out for
    the host-exact refactorization mid-loop; or (b) on TPU, its
    longest single-program f64 iteration stretch would exceed the
    measured watchdog ceiling (WATCHDOG_F64_ITERS)."""
    if mode == "segmented":
        return "segmented"
    if mode == "fused":
        return "fused"
    if _needs_host_factor(factors):
        return "segmented"
    if f64_stretch > WATCHDOG_F64_ITERS \
            and jax.default_backend() == "tpu":
        return "segmented"
    return "fused"


@dataclass
class KernelPlan:
    """One mode's resolved kernel decisions, prepared once per
    factorization and reused every solve call (core/ph caches plans
    beside the factor cache and invalidates them together)."""
    mode: str                    # "fused" | "segmented" (resolved)
    backend: str                 # "reference" | "pallas" (effective)
    precision: str               # the precision the plan serves
    l_inv: bool = False
    block_dtype: str = "f32"     # "f32" | "bf16" (effective)
    A_lo: object = None          # bulk-phase A_s operand (mixed/df32)
    bf16_err: float | None = None
    # host copy of factors.sigma, read ONCE at prepare() time: the
    # pallas block needs it as a compile-time constant, and reading it
    # per solve call would put a scalar D2H on every chunk dispatch
    # (graft-lint SYNC001 caught exactly that)
    sigma_host: float | None = None

    def descriptor(self) -> dict:
        """The bench/telemetry kernel block."""
        return {"mode": self.mode, "backend": self.backend,
                "l_inv": bool(self.l_inv),
                "block_dtype": self.block_dtype}


SEGMENTED_PLAN = KernelPlan(mode="segmented", backend="reference",
                            precision="native")


def prepare(factors, *, mode="auto", backend="reference",
            l_inv="auto", block_dtype="auto", precision="native",
            bulk_iter=0, tail_iter=0, ir_sweeps=1, s_chunk=1):
    """Resolve the kernel decisions for one mode's factors (host,
    eager, once per factorization): mode, effective backend, the L⁻¹
    profitability verdict, and — for mixed/df32 — the bulk phase's
    A operand with bf16 blocks substituted when the gate admits them.

    Out-of-band ``ir_sweeps`` (the fused program unrolls them
    statically — utils/config.FUSED_IR_SWEEPS): explicit ``fused`` is a
    config error the engine raises before any trace; ``auto`` falls
    back to segmented here, so exotic sweep counts keep working through
    the host-segmented drivers."""
    if int(ir_sweeps) not in FUSED_IR_SWEEPS:
        if mode == "fused":
            raise ValueError(
                f"kernel mode 'fused' supports ir_sweeps in "
                f"[{FUSED_IR_SWEEPS.start}, {FUSED_IR_SWEEPS.stop - 1}]"
                f"; got {ir_sweeps} (use 'segmented')")
        return SEGMENTED_PLAN
    if mode == "fused" and _needs_host_factor(factors):
        # explicit fused cannot serve these factors: the tail handoff
        # and in-loop rho adaptation would call _factorize in-trace on
        # non-shared f64 KKTs whose device inverse is garbage on this
        # backend (qp_solver._device_f64_linalg_trusted — measured
        # |M@inv - I|max = 0.9, iterates -> 1e33 -> NaN). A config
        # error here beats NaN solves deep inside the jit.
        raise ValueError(
            "kernel mode 'fused' cannot serve non-shared f64 factors "
            "whose rho adaptation must refactorize on the host "
            "(untrusted f64 device linalg on this backend); use "
            "'segmented', or 'auto' which falls back automatically")
    # the f64 stretch one fused program would run without a host
    # dispatch: the whole budget for a native-f64 solve, only the tail
    # for precision-escalated solves (the bulk iterates in f32)
    f64_stretch = int(tail_iter) if precision in ("mixed", "df32") else (
        int(bulk_iter)
        if getattr(factors.A_s, "dtype", None) == jnp.float64 else 0)
    if resolve_mode(mode, factors, f64_stretch=f64_stretch) == "segmented":
        return SEGMENTED_PLAN
    split = isinstance(factors.A_s, SplitMatrix)
    use_linv = False
    if split:
        n = factors.A_s.shape[-1]
        if l_inv == "on":
            use_linv = True
        elif l_inv == "auto":
            # budget = TAIL only: the f32 bulk phase never applies the
            # explicit inverse (un-refined solves hand L.tri to the
            # componentwise-stable back-substitution — see LInv)
            use_linv = l_inv_profitable(n, s_chunk, tail_iter, ir_sweeps)
    A_lo, bdt, err = None, "f32", None
    if precision in ("mixed", "df32"):
        if split:
            A_hi = factors.A_s.hi
            pk_hi = factors.A_s.pk_hi
            if pk_hi is not None:
                pk_bulk = pk_hi
                # bf16 blocks are EXPLICIT OPT-IN ("bf16"), never
                # "auto": measured on the UC LP relaxation, the ~2⁻⁸
                # coefficient rounding relocates the degenerate
                # optimum by tens of percent while every residual
                # converges — an error the residual-based gates
                # (quantization pre-gate here, quality-gate recovery
                # in the chunked loop) are structurally blind to.
                # See doc/kernels.md §bf16 for the measurement; the
                # driver-run objective cross-checks are the evidence
                # that could justify widening this per model family.
                if block_dtype == "bf16":
                    trips, err = bf16_gate(pk_hi)
                    if trips:
                        obs.counter_add("kernel.bf16_fallbacks")
                        obs.event("kernel.bf16_fallback",
                                  {"quant_err": err,
                                   "gate": BF16_GATE_REL})
                    else:
                        pk_bulk = bf16_packed(pk_hi)
                        bdt = "bf16"
                A_lo = PackedMatrix(A_hi, pk_bulk)
            else:
                A_lo = A_hi
        else:
            # non-split mixed: the bulk casts the dense operand
            # in-trace, exactly as qp_solve_mixed does eagerly
            A_lo = factors.A_s
    eff_backend = backend
    if backend == "pallas" and not (
            pallas_kernel.HAVE_PALLAS
            and precision == "native"
            and getattr(factors.A_s, "ndim", 0) == 2
            and not isinstance(factors.A_s, (SplitMatrix, PackedMatrix))):
        # outside the pallas block's scope (see pallas_kernel), or no
        # pallas in this environment: the reference backend is the
        # default stand-in everywhere
        eff_backend = "reference"
    # host copy of sigma, read once here (prepare is host+eager by
    # contract) so the per-solve pallas launch never pays a scalar
    # D2H; partial factor stubs (scope tests) simply carry None and
    # fused_admm_block's direct-caller fallback covers them
    sig = getattr(factors, "sigma", None)
    return KernelPlan(mode="fused", backend=eff_backend,
                      precision=precision, l_inv=use_linv,
                      block_dtype=bdt, A_lo=A_lo, bf16_err=err,
                      sigma_host=None if sig is None else float(sig))


def kernel_solve(plan: KernelPlan, factors, data, q, state, *,
                 precision, max_iter, tail_iter, e_pri, e_dua,
                 stall_rel, polish, polish_chunk, ir_sweeps,
                 check_every=25, polish_iters=12, adaptive_rho=True,
                 donate=False):
    """The fused-mode twin of core/ph._solver_call's segmented
    dispatch: same (state, x, yA, yB) contract, same tolerance policy
    (the caller computed e_pri/e_dua), one device program per call.
    ``adaptive_rho=False`` freezes the stepsize trajectory — the
    incumbent-pool evaluator needs it because shared-mode adaptation
    pools statistics over rows that include INFEASIBLE candidates
    (doc/incumbents.md)."""
    t0 = time.perf_counter()
    if plan.backend == "pallas" and precision not in ("mixed", "df32") \
            and not pallas_kernel.pallas_supported(factors, state):
        # the state-dependent half of the scope check (the solve
        # operator must be an explicit inverse — prepare() only sees
        # the factors): demote the CACHED plan so phase_timing / the
        # bench row / analyze report the backend that actually runs,
        # not the one that was asked for
        plan.backend = "reference"
        obs.event("kernel.pallas_demotion",
                  {"reason": "solve operator not an explicit inverse"})
    if precision in ("mixed", "df32"):
        st, x, yA, yB = fused_mixed_solve(
            factors, plan.A_lo, data, q, state, bulk_iter=max_iter,
            tail_iter=tail_iter, check_every=check_every, eps_abs=e_pri,
            eps_rel=e_pri, eps_abs_dua=e_dua, eps_rel_dua=e_dua,
            polish=polish, polish_iters=polish_iters,
            polish_chunk=polish_chunk, stall_rel=stall_rel,
            ir_sweeps=ir_sweeps, l_inv=plan.l_inv,
            adaptive_rho=adaptive_rho, donate=donate)
        tag = "fused-mixed"
    elif plan.backend == "pallas":
        # the pallas block runs the WHOLE budget at fixed rho (the
        # kernel cannot refactorize — pallas_kernel.py), then the
        # oracle finisher polishes and unscales the block's iterates
        # through the very code the reference runs. The finisher
        # recomputes the residuals post-polish, so the block's fused
        # pri/dua outputs serve the parity tests and the on-chip
        # production tiling (where they gate WITHOUT leaving VMEM),
        # not this driver. ``donate`` flows to the finisher: ``st``
        # aliases the block's outputs plus the caller's factor/rho
        # buffers, exactly the ownership donate=True relinquishes.
        if obs.enabled():
            # roofline capture for the pallas block (obs/profile.py);
            # degrades to profile.unavailable if the backend's cost
            # model cannot see through the pallas lowering
            from ...obs import profile as _profile
            x_s, yA_s, yB_s, zA_s, zB_s, _, _ = _profile.call(
                "kernel.pallas", pallas_kernel.fused_admm_block,
                factors, data, q, state, n_steps=max_iter,
                sigma=plan.sigma_host)
        else:
            x_s, yA_s, yB_s, zA_s, zB_s, _, _ = \
                pallas_kernel.fused_admm_block(
                    factors, data, q, state, n_steps=max_iter,
                    sigma=plan.sigma_host)
        st = state._replace(x=x_s, yA=yA_s, yB=yB_s, zA=zA_s, zB=zB_s)
        st, x, yA, yB = qp_solve(
            factors, data, q, st, donate=donate, max_iter=0,
            check_every=check_every, eps_abs=e_pri, eps_rel=e_pri,
            polish=polish, polish_iters=polish_iters,
            polish_chunk=polish_chunk, eps_abs_dua=e_dua,
            eps_rel_dua=e_dua, stall_rel=stall_rel, ir_sweeps=ir_sweeps,
            adaptive_rho=adaptive_rho)
        st = st._replace(iters=jnp.asarray(int(max_iter), jnp.int32))
        tag = "fused-pallas"
    else:
        st, x, yA, yB = qp_solve(
            factors, data, q, state, donate=donate, max_iter=max_iter,
            check_every=check_every, eps_abs=e_pri, eps_rel=e_pri,
            polish=polish, polish_iters=polish_iters,
            polish_chunk=polish_chunk, eps_abs_dua=e_dua,
            eps_rel_dua=e_dua, stall_rel=stall_rel, ir_sweeps=ir_sweeps,
            adaptive_rho=adaptive_rho)
        tag = "fused-native"
    # same observability contract as the segmented drivers' per-segment
    # stamps (counter + optional MPISPPY_TPU_SOLVE_TRACE event), one
    # stamp per fused program. ``kernel.fused_iters`` is deliberately
    # NOT booked here: reading ``int(st.iters)`` now would block on the
    # whole fused program and serialize chunk k's solve with chunk
    # k+1's dispatch — the exact overlap fusion exists to create. The
    # core/ph callers book it after their existing post-solve sync
    # (the chunked loop's phase-honesty block / _ph_step's), where the
    # scalar read is a copy, not a stall.
    _trace_seg(tag, t0, st)
    return st, x, yA, yB


def est_hbm_bytes_per_iter(*, n, m, s_chunk, pk_pass_bytes=None,
                           ir_sweeps=1, l_inv=True, block_dtype="f32",
                           factor_bytes=4, vec_bytes=8):
    """doc/roofline.md traffic model of ONE fused df32 tail iteration
    (per chunk), the number the bench's uc1024 row records so a driver
    re-run can confirm the predicted drop:

      factor applies : 2 triangle passes x (1 seed + ir_sweeps IR
                       solves) x n² x 4 B — identical bytes for
                       triangular solves and L⁻¹ matmuls (the trade
                       converts latency, not traffic; l_inv=False only
                       flags that the latency win is off);
      A passes       : (2 + 2·ir_sweeps) packed split passes (1 rhs Aᵀy
                       + ir_sweeps x (Ax + Aᵀy) + 1 zAx) over the
                       hi+lo packed operand bytes (dense m·n·8 when
                       unpacked);
      vectors        : ~6 (S, m)/(S, n) f64 sweeps (rhs assembly,
                       projections, dual updates).

    Returns {"tail": bytes, "bulk": bytes}; the bulk model halves the
    A-operand bytes under bf16 blocks and books f32 vectors/factor."""
    a_pass = pk_pass_bytes if pk_pass_bytes is not None else m * n * 8
    tail_factor = 2 * (1 + int(ir_sweeps)) * n * n * factor_bytes
    tail_a = (2 + 2 * int(ir_sweeps)) * a_pass
    tail_vec = 6 * (m + n) * s_chunk * vec_bytes
    bulk_a_pass = a_pass / 2  # hi only, no lo operand in the bulk
    if block_dtype == "bf16":
        bulk_a_pass /= 2
    bulk = int(2 * n * n * factor_bytes + 2 * bulk_a_pass
               + 6 * (m + n) * s_chunk * 4)
    return {"tail": int(tail_factor + tail_a + tail_vec), "bulk": bulk}
