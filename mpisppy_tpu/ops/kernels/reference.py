"""XLA fused-scan REFERENCE backend of the kernel layer (and its
correctness oracle): one full precision-escalated ADMM solve — f32 bulk
phase, factor handoff, accurate tail, polish — traced as a SINGLE
device program, so no iterate, factor, or residual ever round-trips
through the host between phases.

What this removes, relative to the segmented driver it replaces
(ops/qp_solver.qp_solve_segmented / qp_solve_mixed):

 - the per-segment host dispatch + blocking ``int(state.iters)`` D2H
   readback (one per ~100-500 iterations per chunk — at uc1024 scale,
   8 chunks x 5+ segments of sync per PH iteration);
 - the per-phase state casts materialized between separate jits (the
   lo->hi handoff now fuses into the tail's first iteration);
 - the host's opportunity to interleave — the whole solve is one
   enqueue, so chunk k+1's assembly genuinely overlaps chunk k's solve
   in the pipelined PH loop instead of waiting on segment syncs.

The MATH is deliberately not new: both phases call the same
``_solve_impl`` body every segmented solve runs, so this backend is
bit-compatible with ``segmented`` whenever the iteration budget fits
one segment (the micro-parity CI test pins that at 1e-10), and
tolerance-equivalent beyond (segment boundaries reset the stall window
and rho-adaptation cadence, which a continuous loop does not — see
doc/kernels.md).

Two roofline trades live here (doc/roofline.md §5 headroom item 1):

 - ``l_inv``: the df32 tail's two triangular solves become two MXU
   matmuls of the same bytes by carrying the EXPLICIT L⁻¹
   (qp_solver.LInv) in the solver state, behind ``l_inv_profitable``
   (the n-RHS inverse build must amortize over the iteration budget);
 - bf16 packed blocks: the f32 bulk phase streams the structure-packed
   A-blocks at half width with f32 accumulation (ops/packed), behind
   ``bf16_gate`` (entries that bf16 would FLUSH — sub-normal-range
   magnitudes, 100% relative error — force the f32 fallback).
   EXPLICIT OPT-IN only: normal-range rounding is ≤ 2⁻⁸, which sounds
   admissible for a 1e-3-plateau bulk phase, but measured on the UC LP
   relaxation it relocates the DEGENERATE OPTIMUM by ~35% while the
   residuals converge normally — the bulk's real job is picking the
   vertex, and no residual gate can see a wrong-vertex answer. The
   kernel layer's "auto" therefore never engages bf16 (see
   prepare()); doc/kernels.md records the measurement.

A solve that goes wrong under either trade is caught by the SAME df32
gate machinery that already guards the segmented path: the chunked PH
loop's quality gate retries flagged chunks in native precision through
the segmented driver (core/ph._solve_loop_chunked pass 2), which uses
neither bf16 blocks nor the fused program — the recovery path IS the
full-precision fallback.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ... import obs
from ..packed import Packed
from ..qp_solver import (LInv, PackedMatrix, QPData, QPState, SplitMatrix,
                         _cast_floats, _factorize, _make_l_inv,
                         _solve_impl, make_l_inv)

__all__ = ["fused_mixed_solve", "l_inv_profitable", "bf16_gate",
           "bf16_packed", "BF16_GATE_REL"]


# ---------------- roofline trade guards ----------------

def l_inv_profitable(n, s_chunk, tail_iter, ir_sweeps=1):
    """Whether the explicit L⁻¹ build amortizes. The inverse
    back-substitutes n RHS columns ONCE; only the TAIL applies it
    (``s_chunk`` columns ``(1 + ir_sweeps)`` times per iteration — the
    f32 bulk hands ``LInv.tri`` to the plain back-substitution, see
    qp_solver.LInv), so the break-even test is one tail's column
    solves >= the build's n. That is deliberately the margin, not a
    multiple: the df32 chunk chain flows ONE factor across every chunk
    and every warm-started PH iteration until rho refactorizes, so
    each solve past the first applies the same inverse for free —
    break-even within one solve makes the chain pure win. A short
    exploratory solve (small s_chunk·tail) still must not pay an
    (n, n) inversion it never recoups."""
    applies = int(tail_iter) * (1 + int(ir_sweeps)) * max(int(s_chunk), 1)
    return applies >= int(n)


# bf16 rounds normal-range values within 2⁻⁸ ≈ 3.9e-3 relative —
# RESIDUAL-level noise the f32 bulk phase tolerates (though NOT
# objective-level noise on degenerate LPs; that measured hazard is why
# bf16 is opt-in — see the module docstring). What no consumer can
# tolerate is INFORMATION LOSS: magnitudes below bf16's normal range
# flush toward zero (up to 100% relative error), silently deleting
# matrix entries. The gate measures the worst per-entry relative
# quantization error and trips above this threshold — normal-range
# blocks always pass, blocks with flush-range entries always trip.
BF16_GATE_REL = 1e-2


def _bf16_elem_err(vals):
    """Max per-entry |v - bf16(v)| / |v| over the nonzero entries.

    Measured on HOST via ml_dtypes, deliberately not through an XLA
    cast: the flush-prone entries are f32 SUBNORMALS (f32 and bf16
    share the 8-bit exponent, so every f32-normal value is bf16-normal
    and rounds within 2⁻⁸), and XLA's flush-to-zero erases exactly
    those entries before the device cast ever sees them — a jitted
    gate measures 0 error on the blocks it exists to reject. The gate
    runs once per factorization on small packed blocks, so the host
    pull is noise."""
    import ml_dtypes

    v = np.asarray(vals, np.float32)
    q = v.astype(ml_dtypes.bfloat16).astype(np.float32)
    nz = np.abs(v) > 0
    if not nz.any():
        return 0.0
    return float((np.abs(v - q)[nz] / np.abs(v)[nz]).max())


def bf16_gate(pk: Packed, gate_rel=BF16_GATE_REL):
    """(trips, measured_err) for bf16 storage of one packed block set."""
    err = _bf16_elem_err(pk.l_vals)
    if pk.g_rows.size:
        err = max(err, _bf16_elem_err(pk.g_vals))
    return err > gate_rel, err


def bf16_packed(pk: Packed) -> Packed:
    """bf16-storage twin of a packed f32 block set (indices shared; the
    matvecs keep f32 accumulation — ops/packed._pk_einsum)."""
    return pk._replace(g_vals=pk.g_vals.astype(jnp.bfloat16),
                       l_vals=pk.l_vals.astype(jnp.bfloat16))


# ---------------- the fused mixed/df32 program ----------------

def _fused_mixed_impl(factors, A_lo, data, q, iterates, aux,
                      eps_abs, eps_rel, eps_abs_dua, eps_rel_dua, *,
                      bulk_iter, tail_iter, check_every, adaptive_rho,
                      polish, polish_iters, polish_chunk, stall_rel,
                      ir_sweeps, l_inv, alpha=1.6):
    """Traceable body of the fused precision-escalated solve. Faithful
    to qp_solve_mixed's phase semantics (same eps floors, same factor
    handoff, same budget split) with the host segment loops replaced by
    the in-jit while_loops ``_solve_impl`` already owns.

    ``iterates`` = (x, yA, yB, zA, zB) — donated by the donating twin;
    ``aux`` = (L, rho_scale, iters) — NEVER donated: the df32 chunked
    loop deliberately shares one flowed factor across every chunk state
    (core/ph pass-3 unify), so L is not uniquely owned and must be
    copied, exactly as qp_solve_mixed's ``owned_lo = donate and not
    split`` protects it today."""
    x, yA, yB, zA, zB = iterates
    L, rho_scale, iters0 = aux
    S = x.shape[0]
    dt_hi = x.dtype
    inf0 = jnp.full((S,), jnp.inf, dt_hi)
    state = QPState(x=x, yA=yA, yB=yB, zA=zA, zB=zB, L=L,
                    rho_scale=rho_scale, iters=iters0, pri_res=inf0,
                    dua_res=inf0, pri_rel=inf0, dua_rel=inf0)
    lo = jnp.float32
    split = isinstance(factors.A_s, SplitMatrix)
    if not isinstance(A_lo, (SplitMatrix, PackedMatrix)) \
            and getattr(A_lo, "dtype", lo) != lo:
        # non-split mixed: the plan stages the RAW dense operand and
        # the bulk casts it in-trace, exactly as qp_solve_mixed's eager
        # _cast_floats does (a packed A_lo is already f32/bf16 storage)
        A_lo = A_lo.astype(lo)

    # lo-phase operands: factors cast around the pre-staged A_lo (cast
    # AFTER detaching A_s — _cast_floats on a bf16 packed block would
    # widen the very arrays the trade narrows)
    f_lo = _cast_floats(factors._replace(A_s=jnp.zeros((), lo)), lo)
    f_lo = f_lo._replace(A_s=A_lo)
    d_lo = QPData(P_diag=data.P_diag.astype(lo), A=A_lo,
                  l=data.l.astype(lo), u=data.u.astype(lo),
                  lb=data.lb.astype(lo), ub=data.ub.astype(lo))
    st_lo = _cast_floats(state, lo)
    L_lo0, rho_lo0 = st_lo.L, st_lo.rho_scale
    if split and isinstance(L_lo0, LInv):
        # the bulk never applies the explicit inverse (its un-refined
        # x-update hands L.tri to the back-substitution — see LInv), so
        # carry the RAW factor through the bulk loop: an LInv carry
        # would make every in-bulk rho refactorization rebuild an n-RHS
        # inverse it immediately discards. The handoff below restores
        # the flowed inverse when rho never moved, and builds a fresh
        # one exactly once when it did.
        st_lo = st_lo._replace(L=L_lo0.tri)
    if not split:
        st_lo = st_lo._replace(L=_factorize(f_lo, st_lo.rho_scale))
    # the f32 phase is a WARM START for the tail: same noise-floor
    # clamps as qp_solve_mixed
    eps_lo = jnp.maximum(jnp.asarray(eps_abs, lo), 1e-4)
    eps_rel_lo = jnp.maximum(jnp.asarray(eps_rel, lo), 1e-3)
    eps_rel_lo_dua = jnp.maximum(jnp.asarray(eps_rel_dua, lo), 1e-2)
    st_lo, _, _, _ = _solve_impl(
        f_lo, d_lo, q.astype(lo), st_lo, bulk_iter, check_every,
        eps_lo, eps_rel_lo, alpha, adaptive_rho, False, polish_iters, 0,
        eps_lo, eps_rel_lo_dua, stall_rel)

    # handoff: rho and (in split mode) the f32 factor carry over — the
    # factorization's (n, n) transients are the biggest allocations in
    # the whole solve path, so the tail must not rebuild one the bulk
    # already holds
    rho_hi = st_lo.rho_scale.astype(dt_hi)
    L_lo = st_lo.L
    st_hi = _cast_floats(st_lo._replace(L=jnp.zeros((), lo)), dt_hi)
    if split:
        L_hi = L_lo
        if l_inv and not isinstance(L_hi, LInv):
            # the bulk carries the raw factor (stripped above), so THIS
            # is where the tail's explicit inverse comes from. The
            # factor is a pure function of rho_scale, so when the
            # bulk's rho adaptation never moved it the flowed inverse
            # from the chunk chain is still exact — reuse it; build a
            # fresh one (once per solve, not once per in-bulk
            # refactorization) only when rho actually changed.
            if isinstance(L_lo0, LInv):
                L_hi = jax.lax.cond(
                    jnp.all(st_lo.rho_scale == rho_lo0),
                    lambda: L_lo0, lambda: _make_l_inv(L_lo))
            else:
                L_hi = _make_l_inv(L_hi)
    else:
        L_hi = _factorize(factors, rho_hi)
    st_hi = st_hi._replace(L=L_hi, rho_scale=rho_hi)
    st, x_un, yA_un, yB_un = _solve_impl(
        factors, data, q, st_hi, tail_iter, check_every, eps_abs,
        eps_rel, alpha, adaptive_rho, polish, polish_iters, polish_chunk,
        eps_abs_dua, eps_rel_dua, stall_rel, ir_sweeps)
    st = st._replace(iters=st_lo.iters + st.iters)
    return st, x_un, yA_un, yB_un


_FUSED_STATICS = ("bulk_iter", "tail_iter", "check_every", "adaptive_rho",
                  "polish", "polish_iters", "polish_chunk", "stall_rel",
                  "ir_sweeps", "l_inv", "alpha")
_fused_mixed_jit = jax.jit(_fused_mixed_impl, static_argnames=_FUSED_STATICS)
# donated twin: consumes the ITERATE buffers only (see _fused_mixed_impl
# on why aux must be copied)
_fused_mixed_jit_donated = jax.jit(_fused_mixed_impl,
                                   static_argnames=_FUSED_STATICS,
                                   donate_argnames=("iterates",))


def fused_mixed_solve(factors, A_lo, data, q, state, *, bulk_iter,
                      tail_iter, check_every, eps_abs, eps_rel,
                      eps_abs_dua, eps_rel_dua, polish, polish_iters,
                      polish_chunk, stall_rel, ir_sweeps, l_inv,
                      adaptive_rho=True, donate=False):
    """One fused mixed/df32 solve call (see _fused_mixed_impl).
    ``l_inv`` states arriving with a raw 2-D f32 Cholesky factor are
    wrapped to LInv EAGERLY so the jit sees one pytree structure for the
    whole chunk chain (a mid-chain structure flip would recompile the
    UC-sized program)."""
    if l_inv and not isinstance(state.L, LInv):
        L = state.L
        if getattr(L, "ndim", 0) == 2 and L.dtype == jnp.float32:
            obs.counter_add("kernel.l_inv_factorizations")
            state = state._replace(L=make_l_inv(L))
    iterates = (state.x, state.yA, state.yB, state.zA, state.zB)
    aux = (state.L, state.rho_scale, state.iters)
    fn = _fused_mixed_jit_donated if donate else _fused_mixed_jit
    kw = dict(bulk_iter=int(bulk_iter), tail_iter=int(tail_iter),
              check_every=int(check_every),
              adaptive_rho=bool(adaptive_rho), polish=bool(polish),
              polish_iters=int(polish_iters),
              polish_chunk=int(polish_chunk), stall_rel=float(stall_rel),
              ir_sweeps=int(ir_sweeps), l_inv=bool(l_inv))
    if obs.enabled():
        # measured-roofline capture + compile-ledger attribution
        # (obs/profile.py) — zero-cost when telemetry is off
        from ...obs import profile as _profile
        return _profile.call("kernel.fused_mixed", fn, factors, A_lo,
                             data, q, iterates, aux, eps_abs, eps_rel,
                             eps_abs_dua, eps_rel_dua, **kw)
    return fn(factors, A_lo, data, q, iterates, aux,
              eps_abs, eps_rel, eps_abs_dua, eps_rel_dua, **kw)
