"""Progressive problem shrinking: device-native fixing counters,
active-set compaction plans, and per-slot adaptive rho (ROADMAP item 5,
doc/extensions.md §shrinking).

Three device-paced mechanics that make late-wheel per-iteration cost
track the ACTIVE set instead of the original model:

1. ``fixer_update`` — the WW-style Fixer's test-and-fix
   (extensions/fixer.py, ref. mpisppy/extensions/fixer.py:50) as ONE
   jitted op over the sharded (S, K) hub state: per-slot
   consecutive-converged counters, bound-parking votes, and the
   accumulated fix mask/values, with a single scalar (the fixed-slot
   count) for the host to read AFTER the iteration's existing
   convergence sync — no big-array D2H per ``miditer`` (the host
   Fixer pulled xbar/xsqbar/x down every pass).

2. ``ShrinkPlan`` / ``build_plan`` + the gather/fold/expand ops —
   active-set compaction: when the fixed fraction crosses a bucketed
   threshold, the unfixed columns (and the constraint rows they touch)
   are gathered into a smaller packed system; fixed-variable
   contributions fold into per-scenario constants (``c0_fold``, rhs
   shifts) so the EXPANDED solution of the compacted system equals the
   uncompacted pinned solve to solver tolerance. Bucketed thresholds
   keep the compacted shapes few: a wheel pays at most one XLA compile
   per bucket transition, tracked through the module-level
   shape-bucket registry (fingerprinted like serve/cache buckets).

3. ``per_slot_rho_update`` — NormRhoUpdater's residual balancing
   (Boyd et al. §3.4.1) per SLOT instead of per whole vector: a jitted
   op producing the vector rho for the prox diagonal plus one packed
   (3,) stats row ([changed, prim_sum, dual_sum]) so the host pays one
   tiny D2H per update, not one per history sample.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..ckpt.bundle import config_fingerprint
from ..utils.config import parse_shrink_buckets as parse_buckets  # noqa: F401
#   (re-exported: the jax-free parser lives in utils/config so CLI/serve
#   validation never imports this jax-touching module)
from .qp_solver import QPData, ScaledView, SplitMatrix

# "never fix" threshold sentinel: must survive an int32 cast (x64-off
# environments) — 2^30 consecutive converged iterations is never
INT_NEVER = 2 ** 30


# ---------------- device fixer counters ----------------

@jax.jit
def _fixer_update_jit(conv_count, lb_count, ub_count, fixed_mask,
                      fixed_vals, xbar, xsqbar, xn, slot_lb, slot_ub,
                      tol, boundtol, nbc, lbc, ubc, imask):
    var = jnp.max(jnp.abs(xsqbar - xbar * xbar), axis=0)
    agree = var <= tol * tol + 1e-15
    conv_count = jnp.where(agree, conv_count + 1, 0)
    at_lb = jnp.all(jnp.abs(xn - slot_lb) <= boundtol, axis=0)
    at_ub = jnp.all(jnp.abs(xn - slot_ub) <= boundtol, axis=0)
    lb_count = jnp.where(agree & at_lb, lb_count + 1, 0)
    ub_count = jnp.where(agree & at_ub, ub_count + 1, 0)
    fix_lb = lb_count >= lbc
    fix_ub = (ub_count >= ubc) & ~fix_lb
    fix_nb = (conv_count >= nbc) & ~fix_lb & ~fix_ub
    newly = (fix_lb | fix_ub | fix_nb) & ~fixed_mask[0]
    value = jnp.where(fix_lb[None, :], slot_lb,
                      jnp.where(fix_ub[None, :], slot_ub, xbar))
    value = jnp.where(imask[None, :], jnp.round(value), value)
    fixed_vals = jnp.where(newly[None, :], value, fixed_vals)
    fixed_mask = fixed_mask | newly[None, :]
    n_fixed = jnp.sum(fixed_mask[0].astype(jnp.int32))
    return conv_count, lb_count, ub_count, fixed_mask, fixed_vals, n_fixed


def fixer_update(*args):
    """One ``miditer`` of the WW fixer as a device op. Mirrors
    extensions/fixer.py Fixer.miditer EXACTLY (the parity test pins
    identical fix decisions): variance test per slot, parked-at-bound
    streaks, lb > ub > nb precedence, integral snap, accumulate-only
    fixing. Returns the updated counters/mask/values plus the fixed
    slot count as a device scalar — the ONE number the host reads."""
    if obs.enabled():
        # measured-roofline capture (obs/profile.py) — zero-cost off
        from ..obs import profile as _profile
        return _profile.call("shrink.fixer_update", _fixer_update_jit,
                             *args)
    return _fixer_update_jit(*args)


# ---------------- per-slot adaptive rho ----------------

@jax.jit
def _per_slot_rho_update_jit(rho, prob, xn, xbar, xbar_prev, mult,
                             factor):
    """Residual-balancing rho update PER NONANT SLOT (the vector
    analog of extensions/norm_rho_updater.py): prim_k is the
    probability-weighted primal residual of slot k, dual_k the
    rho-scaled dual residual; slots with prim > mult*dual scale up,
    dual > mult*prim scale down. rho stays uniform across scenarios
    (the update factor is per-slot), so the single-factor prox path
    keeps working. Returns (new_rho, stats) with stats a packed (3,)
    row [changed, prim_sum, dual_sum] — one tiny D2H for the host."""
    S = xn.shape[0]
    prim = jnp.einsum("s,sk->k", prob, jnp.abs(xn - xbar))
    dual = jnp.mean(rho, axis=0) \
        * jnp.sum(jnp.abs(xbar - xbar_prev), axis=0) / S
    up = prim > mult * dual
    down = (dual > mult * prim) & ~up
    scale = jnp.where(up, factor, jnp.where(down, 1.0 / factor, 1.0))
    new_rho = rho * scale[None, :]
    changed = jnp.any(up | down).astype(rho.dtype)
    stats = jnp.stack([changed, jnp.sum(prim), jnp.sum(dual)])
    return new_rho, stats


def per_slot_rho_update(*args):
    """See ``_per_slot_rho_update_jit`` — the public name adds the
    measured-roofline capture when telemetry is on."""
    if obs.enabled():
        from ..obs import profile as _profile
        return _profile.call("shrink.rho_update",
                             _per_slot_rho_update_jit, *args)
    return _per_slot_rho_update_jit(*args)


# ---------------- active-set compaction ----------------

@dataclass
class ShrinkPlan:
    """One compacted system: device tensors + host metadata. Built by
    :func:`build_plan` at a bucket transition; the engine solves the
    compacted system and expands solutions back through
    :func:`expand_solution`."""
    bucket: float                 # the threshold fraction crossed
    fingerprint: str              # shape-bucket id (serve-style hash)
    n_full: int
    m_full: int
    n_c: int                      # kept columns
    m_c: int                      # kept rows
    n_fixed_slots: int
    free_slots: np.ndarray        # (K_c,) host slot ids kept
    fixed_slots: np.ndarray       # (K_f,) host slot ids folded out
    # device arrays
    keep_cols: jax.Array          # (n_c,) original column ids
    fixed_cols: jax.Array         # (n_f,) folded column ids
    free_slots_dev: jax.Array     # (K_c,)
    fixed_slots_dev: jax.Array = None   # (K_f,) for the dual fold
    idx_c: jax.Array = None       # (K_c,) free-slot positions in keep_cols
    fixed_colvals: jax.Array = None     # (S, n_f) folded values
    data_c: QPData = None         # compacted problem data
    c_c: jax.Array = None         # (S, n_c) compacted linear cost
    c0_fold: jax.Array = None     # (S,) c0 + fixed-var cost contributions
    rhs_shift: jax.Array = None   # (S, m_c) folded rhs shift (l/u moved
    #                               by -shift; transplant re-centers row
    #                               slacks through it)
    keep_rows_np: np.ndarray = None   # (m_c,) host row ids kept
    keep_cols_np: np.ndarray = None   # (n_c,) host column ids kept
    fac_base: object = None       # df32: first-mode QPFactors — pinned
    #                               here because data_c.A becomes the
    #                               ScaledView after that build, so
    #                               later rebuilds need this base's
    #                               equilibration (core/ph
    #                               _shrink_get_factors)
    meta: dict = field(default_factory=dict)


@jax.jit
def _fold_compact(A, l, u, lb, ub, P_diag, c, c0, keep_rows, keep_cols,
                  fixed_cols, fv):
    """Device-side compaction of one system: gather the kept
    rows/columns and fold the fixed columns' contributions into the
    rhs (l/u shifts) and the objective constant. Handles the shared
    (m, n) layout AND the batched per-scenario (S, m, n) layout (the
    branch is on static rank, one trace each). Exact arithmetic — the
    expanded solution is the pinned full solve to solver tolerance
    (the equivalence suite pins this)."""
    if A.ndim == 2:
        A_keep = A[keep_rows]
        A_c = A_keep[:, keep_cols]
        A_f = A_keep[:, fixed_cols]
        shift = fv @ A_f.T                     # (S, m_c)
    else:
        A_keep = A[:, keep_rows]
        A_c = A_keep[..., keep_cols]
        A_f = A_keep[..., fixed_cols]          # (S, m_c, n_f)
        shift = jnp.einsum("smf,sf->sm", A_f, fv)
    l_c, u_c, lb_c, ub_c, P_c, c_c, c0_fold = _fold_vectors(
        l, u, lb, ub, P_diag, c, c0, keep_rows, keep_cols, fixed_cols,
        fv, shift)
    return A_c, l_c, u_c, lb_c, ub_c, P_c, c_c, c0_fold, shift


@jax.jit
def _fold_vectors(l, u, lb, ub, P_diag, c, c0, keep_rows, keep_cols,
                  fixed_cols, fv, shift):
    """The vector half of :func:`_fold_compact` with the rhs shift
    supplied externally — the df32 paths compute the shift from the
    split/scaled fixed-column block (see ``_split_fixed_shift``) and
    share these folds with the dense path bit-for-bit."""
    l_c = l[:, keep_rows] - shift
    u_c = u[:, keep_rows] - shift
    lb_c = lb[:, keep_cols]
    ub_c = ub[:, keep_cols]
    P_c = P_diag[..., keep_cols]
    c_c = c[:, keep_cols]
    c0_fold = c0 + jnp.sum(c[:, fixed_cols] * fv, axis=1) \
        + 0.5 * jnp.sum(P_diag[..., fixed_cols] * fv * fv, axis=-1)
    return l_c, u_c, lb_c, ub_c, P_c, c_c, c0_fold


@jax.jit
def _split_fixed_shift(hi_f, lo_f, inv_e, inv_d_f, fv):
    """rhs shift of the folded columns from a df32 fixed-column block:
    the f64 value of the (already row/col-gathered) split block,
    unscaled by ``inv_e``/``inv_d_f`` (ones for a raw SplitMatrix;
    1/E / 1/D for a ScaledView), contracted with the folded values.
    The block is (m_c, n_f) — small next to A — so one f64
    materialization per bucket transition is fine."""
    A_f = (hi_f.astype(jnp.float64) + lo_f.astype(jnp.float64)) \
        * inv_e[:, None] * inv_d_f[None, :]
    return fv @ A_f.T


@partial(jax.jit, static_argnames=("nblocks",))
def _unscale_split_blocks(hi, lo, inv_e, inv_d, nblocks=8):
    """Unscale an (already gathered) compacted ScaledView block back to
    a raw df32 pair: blk = (hi+lo)·(1/E)·(1/D) re-split, in ROW BLOCKS
    so the f64 transient exists one block at a time (the
    _scale_split_blocks discipline in reverse)."""
    m = hi.shape[0]
    his, los = [], []
    bounds = [(m * i) // nblocks for i in range(nblocks + 1)]
    for i in range(nblocks):
        sl = slice(bounds[i], bounds[i + 1])
        blk = (hi[sl].astype(jnp.float64) + lo[sl].astype(jnp.float64)) \
            * inv_e[sl, None] * inv_d[None, :]
        h = blk.astype(jnp.float32)
        los.append((blk - h.astype(jnp.float64)).astype(jnp.float32))
        his.append(h)
    return jnp.concatenate(his), jnp.concatenate(los)


@partial(jax.jit, static_argnames=("w_on", "prox_on"))
def dual_fold(c0_fold, vals, W, xbar, rho, wscale, *, w_on, prox_on):
    """Per-iteration dual-bound constant of the compacted system: the
    assembled-objective contribution of the FOLDED columns. The base
    fold (c·v + ½P·v², computed once at compaction) rides ``c0_fold``;
    the W / prox-center terms move every PH iteration, so they fold
    here from the fixed-slot blocks — the same wvec combination
    core/ph._ph_assemble scatters for the free slots. With this
    constant, the compacted solve's qp_dual_objective certifies
    exactly the bound the uncompacted PINNED solve would."""
    Weff = W if wscale is None else W * wscale
    if w_on and prox_on:
        wvec = Weff - rho * xbar
    elif w_on:
        wvec = Weff
    elif prox_on:
        wvec = -rho * xbar
    else:
        wvec = jnp.zeros_like(W)
    fold = c0_fold + jnp.sum(wvec * vals, axis=1)
    if prox_on:
        fold = fold + 0.5 * jnp.sum(rho * vals * vals, axis=1)
    return fold


@jax.jit
def expand_solution(x_c, fv, keep_cols, fixed_cols, n_template):
    """Scatter a compacted solution block back to full width:
    x_full[:, keep] = x_c, x_full[:, fixed] = the folded values.
    ``n_template`` is a (n,)-shaped array (shape carrier only — a
    static int would re-trace per call site)."""
    S = x_c.shape[0]
    out = jnp.zeros((S, n_template.shape[0]), x_c.dtype)
    out = out.at[:, keep_cols].set(x_c)
    return out.at[:, fixed_cols].set(fv)


# shape-bucket registry (module-level, process-global like the jit
# cache it mirrors): fingerprint -> shapes. A wheel pays at most one
# XLA compile per bucket transition; a SECOND wheel of the same
# fingerprint reuses the first's traced programs entirely (the jit
# cache keys on shapes, which the fingerprint determines) — counters
# ``shrink.bucket.compile`` / ``shrink.bucket.cache_hit`` record which
# happened, the serve/cache.py discipline applied to compaction.
_BUCKET_REGISTRY: dict = {}


def bucket_fingerprint(fields: dict) -> str:
    """Stable 16-hex shape-bucket id (same hashing as serve/cache and
    checkpoint fingerprints — ckpt/bundle.config_fingerprint)."""
    return config_fingerprint(fields)


def bucket_registry():
    """Read-only view for tests/telemetry."""
    return dict(_BUCKET_REGISTRY)


def build_plan(qp_data: QPData, c, c0, nonant_idx, fixed_mask,
               fixed_vals, bucket, *, dtype, ident=None) -> ShrinkPlan | None:
    """Build the compaction plan for the CURRENT fixed set against the
    ORIGINAL full system (plans are always derived from the full data,
    never incrementally — transitions stay independent and exact).

    Host staging happens ONCE per bucket transition (never per
    iteration): the fixed-slot mask comes down as one (S, K) bool
    block, and the kept-row pattern is a device reduction read back as
    one (m,) bool vector. Returns None when nothing (or everything)
    would compact."""
    fm = np.asarray(fixed_mask)            # one D2H per bucket transition
    slot_fixed = fm.all(axis=0)
    idx_np = np.asarray(nonant_idx)
    fixed_slots = np.flatnonzero(slot_fixed)
    free_slots = np.flatnonzero(~slot_fixed)
    if fixed_slots.size == 0 or free_slots.size == 0:
        return None
    A = qp_data.A
    n = int(A.shape[-1])
    m = int(A.shape[-2])
    fixed_cols = np.sort(idx_np[fixed_slots])
    keep_cols = np.setdiff1d(np.arange(n), fixed_cols)
    # rows that still touch a kept column IN ANY SCENARIO; rows whose
    # every nonzero is a fixed column reduce to constants and are
    # dropped with them. df32 representations read the pattern off the
    # split pair (a ScaledView's A_s shares A's zero pattern — Ruiz
    # scalings are diagonal and positive)
    keep_dev = jnp.asarray(keep_cols)
    pat = A.A_s if isinstance(A, ScaledView) else A
    if isinstance(pat, SplitMatrix):
        touched = (pat.hi[:, keep_dev] != 0) | (pat.lo[:, keep_dev] != 0)
    else:
        touched = pat[..., keep_dev] != 0
    row_touch = np.asarray(
        jnp.any(touched, axis=(0, 2) if touched.ndim == 3 else 1))
    keep_rows = np.flatnonzero(row_touch)                # (m,) one D2H
    if keep_rows.size == 0:
        return None
    fixed_cols_d = jnp.asarray(fixed_cols)
    keep_rows_d = jnp.asarray(keep_rows)
    # folded values per ORIGINAL column order (nonant slots -> columns)
    order = np.argsort(idx_np[fixed_slots])
    fv = jnp.asarray(fixed_vals, dtype)[:, jnp.asarray(fixed_slots[order])]
    if isinstance(A, (SplitMatrix, ScaledView)):
        # df32 compacted gather: exact hi/lo row/column gathers of the
        # split pair; a ScaledView gathers the SCALED pair and unscales
        # blockwise back to a raw split (the compacted system gets its
        # own Ruiz pass in _shrink_get_factors, so plans carry the raw
        # representation either way). Packed layouts are screened out
        # by the engine guard (core/ph.maybe_compact) before this.
        if isinstance(A, ScaledView):
            if isinstance(A.A_s, SplitMatrix):
                hi, lo = A.A_s.hi, A.A_s.lo
            else:       # dense scaled matrix: two-term split, exact
                hi = A.A_s.astype(jnp.float32)
                lo = (A.A_s - hi.astype(jnp.float64)) \
                    .astype(jnp.float32)
            inv_e = 1.0 / A.E
            inv_d = 1.0 / A.D
        else:
            hi, lo = A.hi, A.lo
            inv_e = jnp.ones((m,), jnp.float64)
            inv_d = jnp.ones((n,), jnp.float64)
        hi_k, lo_k = hi[keep_rows_d], lo[keep_rows_d]
        shift = _split_fixed_shift(
            hi_k[:, fixed_cols_d], lo_k[:, fixed_cols_d],
            inv_e[keep_rows_d], inv_d[fixed_cols_d], fv)
        if isinstance(A, ScaledView):
            hi_c, lo_c = _unscale_split_blocks(
                hi_k[:, keep_dev], lo_k[:, keep_dev],
                inv_e[keep_rows_d], inv_d[keep_dev])
        else:
            hi_c, lo_c = hi_k[:, keep_dev], lo_k[:, keep_dev]
        A_c = SplitMatrix(hi_c, lo_c)
        l_c, u_c, lb_c, ub_c, P_c, c_c, c0_fold = _fold_vectors(
            qp_data.l, qp_data.u, qp_data.lb, qp_data.ub,
            qp_data.P_diag, c, c0, keep_rows_d, keep_dev, fixed_cols_d,
            fv, shift)
    else:
        A_c, l_c, u_c, lb_c, ub_c, P_c, c_c, c0_fold, shift = \
            _fold_compact(
                A, qp_data.l, qp_data.u, qp_data.lb, qp_data.ub,
                qp_data.P_diag, c, c0, keep_rows_d, keep_dev,
                fixed_cols_d, fv)
    data_c = QPData(P_c, A_c, l_c, u_c, lb_c, ub_c)
    idx_c = np.searchsorted(keep_cols, idx_np[free_slots])
    fp = bucket_fingerprint({
        "bucket": float(bucket), "n": n, "m": m,
        "n_c": int(keep_cols.size), "m_c": int(keep_rows.size),
        "K_c": int(free_slots.size), "dtype": str(dtype),
        **(ident or {})})
    seen = fp in _BUCKET_REGISTRY
    _BUCKET_REGISTRY[fp] = (int(keep_rows.size), int(keep_cols.size))
    if seen:
        obs.counter_add("shrink.bucket.cache_hit")
    else:
        obs.counter_add("shrink.bucket.compile")
    return ShrinkPlan(
        bucket=float(bucket), fingerprint=fp, n_full=n, m_full=m,
        n_c=int(keep_cols.size), m_c=int(keep_rows.size),
        n_fixed_slots=int(fixed_slots.size),
        free_slots=free_slots, fixed_slots=fixed_slots,
        keep_cols=keep_dev, fixed_cols=fixed_cols_d,
        free_slots_dev=jnp.asarray(free_slots),
        fixed_slots_dev=jnp.asarray(fixed_slots),
        idx_c=jnp.asarray(idx_c), fixed_colvals=fv,
        data_c=data_c, c_c=c_c, c0_fold=c0_fold,
        rhs_shift=shift, keep_rows_np=keep_rows, keep_cols_np=keep_cols,
        meta={"bucket_cached": seen})


# ---------------- cross-bucket warm transplant ----------------

@jax.jit
def _transplant_rescale(x, yA, yB, zA, zB, pos_cols, pos_rows,
                        D_old, D_new, E_old, E_new, Eb_old, Eb_new,
                        cs_ratio, shift_old, shift_new, ok):
    """Gather + rescale one mode's SCALED warm ADMM iterates from the
    old width into a new compacted width (full→compacted or
    compacted→compacted; the host caller verifies the new kept set is
    a subset of the old and builds ``pos_cols``/``pos_rows`` — new
    position j came from old position pos[j]).

    Scaling algebra (all quantities scaled, per ops/qp_solver): an
    UNSCALED iterate x_u relates to the scaled one by x = x_u / D, row
    duals by yA = cs·y_u/E, bound duals by yB = cs·y_u/Eb, row slacks
    by zA = E·(A x_u − shift) (the compacted rhs moved by −shift), and
    bound slacks by zB = Eb·x_u. Re-expressing the same unscaled point
    under the new factors' (D, E, Eb, cost_scale, shift):

        x'  = x[pos_c]  · D_old[pos_c] / D_new
        yA' = yA[pos_r] · cs_ratio · E_old[pos_r] / E_new
        yB' = yB[pos_c] · cs_ratio · Eb_old[pos_c] / Eb_new
        zA' = E_new · (zA[pos_r]/E_old[pos_r] + shift_old[:,pos_r]
                       − shift_new)
        zB' = zB[pos_c] · Eb_new / Eb_old[pos_c]

    Scaling vectors may be shared (1-D) or per-scenario (2-D, batched-A
    or per-scenario-rho factors); ``cs_ratio`` scalar or (S,). Both
    sides normalize to broadcastable (1|S, ·) rows, so old and new
    factor forms can even differ.

    ``ok`` is an (S,) keep mask (hospital/dirty scenarios excluded):
    excluded rows multiply to exactly the cold-state zeros."""
    def b2(v):
        return v if v.ndim == 2 else v[None, :]

    csr = cs_ratio if jnp.ndim(cs_ratio) == 0 else cs_ratio[:, None]
    okf = ok.astype(x.dtype)[:, None]
    x_n = x[:, pos_cols] * b2(D_old)[:, pos_cols] / b2(D_new) * okf
    yA_n = yA[:, pos_rows] * csr \
        * (b2(E_old)[:, pos_rows] / b2(E_new)) * okf
    yB_n = yB[:, pos_cols] * csr \
        * (b2(Eb_old)[:, pos_cols] / b2(Eb_new)) * okf
    zA_n = (b2(E_new)
            * (zA[:, pos_rows] / b2(E_old)[:, pos_rows]
               + shift_old[:, pos_rows] - shift_new)) * okf
    zB_n = zB[:, pos_cols] * (b2(Eb_new) / b2(Eb_old)[:, pos_cols]) * okf
    return x_n, yA_n, yB_n, zA_n, zB_n
