"""Wheel forensics: the per-iteration convergence-attribution reduction.

The hub reduces all convergence signal to ONE scalar (``conv`` in
``core/ph.py``) — per-scenario and per-nonant-slot attribution, which
the reference exposes ad hoc via WW fixer streaks and xbar prints, is
lost on the device. This module computes the attribution ON the device
as one jitted reduction over the sharded ``(S, K)`` hub state and packs
everything into a single small vector, so the host pays exactly one
extra transfer per SAMPLED iteration (riding the already-synced gate,
``residual_summary``'s license) and ``ph.gate_syncs`` stays O(1).

Per sample (every ``forensics_interval`` iterations, telemetry on):

- **slot mass** ``m_k = Σ_s p_s · |x_sk − x̄_sk|`` — the prob-weighted
  disagreement carried by nonant slot k. Decomposes the convergence
  scalar exactly: ``conv = Σ_k m_k / K``. Top-k slots by mass are the
  culprit slots.
- **scenario primal share** ``p_s · Σ_k |x_sk − x̄_sk| / Σ`` and
  **scenario dual share** ``p_s · Σ_k |ΔW_sk| / Σ`` — which scenarios
  carry the residual. Mesh pads (zero-probability rows) score −1 and
  can never win a top-k slot over a real scenario.
- **W-oscillation score** — per-slot EMA of the prob-weighted
  sign-flip fraction of ΔW against the previous sample's ΔW. A slot
  whose multipliers flip sign sample after sample is bouncing around
  the consensus value: the classic rho-too-large signature.
- **rho health** — per-slot log10 of primal mass vs dual mass
  ``(m_k + ε) / (Σ_s p_s|ΔW_sk| + ε)``. Large positive: primal
  residual dominates (rho too small); large negative: dual churn
  dominates (rho too large). The mean drives the diagnosis engine's
  rho advice.
- **xbar movement** — mean per-slot |x̄ − x̄_prev|, the inner-movement
  half of the bound-gap decomposition (``obs/diagnose.py`` joins it
  with the hub's outer-bound trajectory and the bound-flow ledger).

The carried :class:`ForensicState` (prev W, prev ΔW, flip EMA, prev
x̄-by-slot, sample count) lives on the device next to the hub state;
dual/oscillation stats are validity-gated by the sample count so the
first samples never report garbage deltas. Everything here except
:func:`unpack` is jit-traced; :func:`unpack` performs the ONE designed
host fetch. See doc/forensics.md for the stat/verdict tables and the
gate-sync cost argument.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# top-k width for both the slot and scenario leaderboards (callers
# clamp to the actual K / S at trace time — the packed layout is
# static per (kk, ks))
TOPK = 8
# EMA decay for the per-slot sign-flip fraction: ~2-sample memory, so
# a transient flip washes out while a persistent oscillation saturates
FLIP_DECAY = 0.5
_EPS = 1e-12
_HDR = 8          # header scalars in the packed vector


class ForensicState(NamedTuple):
    """Device-resident carry between forensic samples."""

    prev_w: jax.Array      # (S, K) W at the previous sample
    prev_dw: jax.Array     # (S, K) ΔW of the previous sample
    flip_ema: jax.Array    # (K,)  EMA'd sign-flip fraction per slot
    prev_xbar: jax.Array   # (K,)  prob-collapsed x̄ at previous sample
    samples: jax.Array     # ()    completed samples (validity gate)


def init_state(S: int, K: int, dtype=jnp.float64) -> ForensicState:
    z = jnp.zeros
    return ForensicState(z((S, K), dtype), z((S, K), dtype),
                         z((K,), dtype), z((K,), dtype),
                         jnp.zeros((), jnp.int32))


def packed_size(kk: int, ks: int) -> int:
    """Length of the packed stats vector for top-``kk`` slots and
    top-``ks`` scenarios: 8 header scalars + three slot (id, value)
    blocks + two scenario (id, value) blocks."""
    return _HDR + 6 * kk + 4 * ks


@partial(jax.jit, static_argnames=("kk", "ks"))
def forensic_reduce(state: ForensicState, xn, xbar, w, prob, rho, *,
                    kk: int, ks: int):
    """One forensic sample over the hub state: returns
    ``(new_state, packed)`` where ``packed`` is the flat stats vector
    :func:`unpack` decodes. Pure reductions + two ``top_k`` calls —
    O(S·K) work, a rounding error next to one subproblem solve — and
    NO host interaction: the caller fetches ``packed`` at the gate."""
    dtype = xn.dtype
    xbar_full = jnp.broadcast_to(xbar, xn.shape)
    adev = jnp.abs(xn - xbar_full)                    # (S, K)
    slot_mass = prob @ adev                           # (K,)
    pri = prob * jnp.sum(adev, axis=1)                # (S,)
    pri_total = jnp.sum(pri)
    K = xn.shape[1]
    conv = pri_total / K

    # dual movement since the previous sample (valid from sample 2;
    # sign flips need the previous delta too, so valid from sample 3)
    dw = w - state.prev_w
    valid_dw = (state.samples >= 1).astype(dtype)
    valid_flip = (state.samples >= 2).astype(dtype)
    dwa = jnp.abs(dw)
    dua_slot = (prob @ dwa) * valid_dw                # (K,)
    dua = prob * jnp.sum(dwa, axis=1) * valid_dw      # (S,)
    dua_total = jnp.sum(dua)

    flip = (jnp.sign(dw) * jnp.sign(state.prev_dw) < 0).astype(dtype)
    flip_frac = (prob @ flip) * valid_flip            # (K,)
    flip_ema = FLIP_DECAY * state.flip_ema \
        + (1.0 - FLIP_DECAY) * flip_frac
    flip_ema = flip_ema * valid_flip

    # rho health: signed log-ratio of primal vs dual mass per slot
    log_ratio = jnp.log10((slot_mass + _EPS) / (dua_slot + _EPS))
    log_ratio = jnp.clip(log_ratio, -6.0, 6.0) * valid_dw
    ratio_mean = jnp.mean(log_ratio)

    # inner-movement half of the bound-gap decomposition: how much the
    # consensus point itself moved since the previous sample
    xbar_slot = prob @ xbar_full                      # (K,)
    xbar_move = jnp.mean(jnp.abs(xbar_slot - state.prev_xbar)) \
        * valid_dw
    rhobar_mean = jnp.mean(prob @ rho)

    # leaderboards (static widths; pads excluded by the prob mask —
    # a pad's score of −1 never beats a real scenario's share ≥ 0)
    sm_v, sm_i = jax.lax.top_k(slot_mass, kk)
    os_v, os_i = jax.lax.top_k(flip_ema, kk)
    rh_v, rh_i = jax.lax.top_k(jnp.abs(log_ratio), kk)
    real = prob > 0
    pri_share = jnp.where(real, pri / (pri_total + _EPS), -1.0)
    dua_share = jnp.where(real, dua / (dua_total + _EPS), -1.0)
    ps_v, ps_i = jax.lax.top_k(pri_share, ks)
    ds_v, ds_i = jax.lax.top_k(dua_share, ks)

    samples = state.samples + 1
    f = lambda a: a.astype(dtype).ravel()
    packed = jnp.concatenate([
        f(samples[None]), f(conv[None]), f(pri_total[None]),
        f(dua_total[None]),
        f(jnp.mean(flip_ema)[None]), f(ratio_mean[None]),
        f(xbar_move[None]), f(rhobar_mean[None]),
        f(sm_i), f(sm_v),
        f(os_i), f(os_v),
        f(rh_i), f(jnp.take(log_ratio, rh_i)),   # signed, abs-ranked
        f(ps_i), f(ps_v),
        f(ds_i), f(ds_v),
    ])
    new_state = ForensicState(w, dw, flip_ema, xbar_slot, samples)
    return new_state, packed


def unpack(packed, kk: int, ks: int) -> dict:
    """Decode one packed stats vector into the plain host dict the
    diagnosis engine / telemetry record consume. THE designed fetch:
    by record-emission time the iteration already synced ``conv``
    (``residual_summary``'s license), so this transfers
    ``packed_size(kk, ks)`` floats without adding a pipeline stall."""
    # the designed per-sample fetch (allowlisted gate site — see
    # tools/lint engine SYNC_ALLOW and doc/forensics.md)
    v = np.asarray(packed, dtype=np.float64)
    if v.shape != (packed_size(kk, ks),):
        raise ValueError(
            f"packed forensics vector has shape {v.shape}, expected "
            f"({packed_size(kk, ks)},) for kk={kk} ks={ks}")
    o = _HDR
    blocks = {}
    for name in ("slots", "osc_slots", "rho_slots"):
        ids, vals = v[o:o + kk], v[o + kk:o + 2 * kk]
        blocks[name] = (ids, vals)
        o += 2 * kk
    for name in ("scens_pri", "scens_dua"):
        ids, vals = v[o:o + ks], v[o + ks:o + 2 * ks]
        blocks[name] = (ids, vals)
        o += 2 * ks

    def pairs(name, drop_below=None):
        ids, vals = blocks[name]
        out = []
        for i, x in zip(ids, vals):
            if drop_below is not None and x < drop_below:
                continue       # masked pad row (score −1), never real
            out.append([int(i), float(x)])
        return out

    return {
        "samples": int(v[0]),
        "conv": float(v[1]),
        "pri_total": float(v[2]),
        "dua_total": float(v[3]),
        "osc_mean": float(v[4]),
        "rho_log_ratio_mean": float(v[5]),
        "xbar_move": float(v[6]),
        "rho_mean": float(v[7]),
        "top_slots": pairs("slots"),
        "osc_slots": pairs("osc_slots"),
        "rho_slots": pairs("rho_slots"),
        "scen_pri_shares": pairs("scens_pri", drop_below=0.0),
        "scen_dua_shares": pairs("scens_dua", drop_below=0.0),
    }
