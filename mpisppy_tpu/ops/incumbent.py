"""Device-side batched incumbent search: candidate pools over the nonants.

The reference gets MIP-quality incumbents by handing every candidate to a
commercial B&B solver (ref. mpisppy/cylinders/xhatshufflelooper_bounder.py
:108 uses solved MIP subproblem first stages); the TPU port's host analog
(utils/host_oracle.OraclePool) pays per-scenario HiGHS subprocesses — at
reference UC scale that host wall is the binding constraint on
time-to-gap (BENCH_r05: the uc1024 incumbent sat 7.4% off for 841 s while
oracle MILPs ground away). SURVEY.md ranks "batched MIP-quality
incumbents without a B&B solver" the #1 hard part.

This module is the device answer (doc/incumbents.md): manufacture a POOL
of rounding candidates from the hub's consensus block as ONE jitted op
over the (scenario x var) nonant matrix, then evaluate the whole pool as
ordinary chunks of batched fix-and-dive repair solves
(core/ph.PHBase.evaluate_incumbent_pool): each candidate's binaries are
FIXED (bound-tightening l = u = x̂_b on the standard form, batched over
the pool axis) and the continuous recourse re-solves through the
existing donated warm-start kernel path. No host solver anywhere in the
loop; the pool is literally another chunk of the pipelined dispatch, so
gate syncs stay O(1) per round and sharded meshes split the rows across
devices.

Pool anatomy (``build_pool``), P = len(thresholds) + flips + n_random + 4
(two slam rows + two bound rows):

- VOTE rows: per-variable scenario-probability-weighted vote rounding of
  the consensus at multiple thresholds (commit every dive slot the fleet
  runs at >= tau in the mean — the classic UC consensus rounding,
  generalizing xhat_bounders._stash_consensus's single threshold);
- FLIP rows: the local-branching ball — the top-k MOST fractional dive
  slots of the consensus each flipped individually on the tau=0.5 base
  candidate (the slots the fleet most disagrees on are where a single
  flip most plausibly improves the rounding);
- RANDOM rows: seeded radius-``ball`` random flip neighborhoods of the
  base candidate (jax PRNG folded with the round index — deterministic
  per (seed, round), fresh diversity across rounds);
- SLAM rows: the per-variable max/min over scenarios — the existing slam
  heuristics' candidates (cylinders/slam_heuristic.py) as pool members,
  so the pool's best is at least as good as the best slam by
  construction whenever the slam rows are feasible;
- BOUND rows: the dive slots slammed to their upper / lower bounds
  (maximum / minimum commitment). The max-commitment row is the
  covering-model feasible ANCHOR — always demand-covering and constant
  across hours, so min-up/down coupling cannot reject it — exactly the
  role xhat_bounders' ``xhat_union_fallback`` plays for the oracle
  candidates; rounded vote profiles routinely violate those coupling
  rows, and a pool with no feasible member publishes nothing.

``pool_verdict`` fuses the feasibility screen and the expected-objective
reduction into one device program so the caller pays exactly ONE stacked
D2H verdict per round.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def slam_rows(X):
    """(up, down): per-variable max/min over the scenario axis of a
    (S, K) nonant block — the slam heuristics' two candidates
    (ref. mpisppy/cylinders/slam_heuristic.py:24-153, the
    local-then-Allreduce(MAX/MIN) two-step collapsed to one axis
    reduction). The ONE host implementation, shared by the slam spokes
    and mirrored in-trace by ``_build_pool``'s slam block."""
    X = np.asarray(X)
    return X.max(axis=0), X.min(axis=0)


def pool_size(n_dive, thresholds=(0.3, 0.5, 0.7), flips=8, n_random=4):
    """Static pool row count for the given dive-slot count — the shape
    contract between ``build_pool`` and the compiled evaluation
    programs (P is identical for the deterministic and the
    ``random_only`` builds, so one solve program serves every round).
    The +4 is the two slam rows plus the two bound rows."""
    n_dive = int(n_dive)
    return (len(tuple(thresholds)) + min(int(flips), n_dive)
            + (int(n_random) if n_dive else 0) + 4)


@partial(jax.jit, static_argnames=("thresholds", "flips", "ball",
                                   "n_random", "random_only"))
def _build_pool(X, prob, dive_mask, int_mask, dive_idx, lb_row, ub_row,
                seed, round_index,
                *, thresholds, flips, ball, n_random, random_only):
    """The jitted pool builder (one op over the (S, K) nonant matrix).

    ``dive_mask`` (K,) bool: the BINARY nonant slots a candidate
    decides (vote-rounded / flipped); everything else carries the raw
    consensus value and is typically left unpinned by the evaluator's
    ``pin_mask``. ``int_mask`` (K,) bool: all integer slots — snapped
    to integral values so every row is evaluation-ready.
    ``random_only``: replace the deterministic blocks with seeded
    random neighborhoods of the base candidate — SAME static row count,
    used when the hub block is unchanged and rebuilding the
    deterministic rows would reproduce the previous pool bit for bit
    (the incumbent.pool_reused path, doc/incumbents.md)."""
    w = prob / jnp.maximum(prob.sum(), 1e-300)
    cons = w @ X                                            # (K,)
    base = jnp.where(dive_mask, (cons >= 0.5).astype(X.dtype), cons)

    def flip_at(sel):
        return base.at[sel].set(1.0 - base[sel])

    key = jax.random.fold_in(jax.random.PRNGKey(seed), round_index)

    def rand_cand(i):
        ki = jax.random.fold_in(key, i)
        sel = jax.random.choice(ki, dive_idx, (ball,), replace=False)
        return flip_at(sel)

    n_total = len(thresholds) + flips + n_random + 4
    if random_only:
        pool = jax.vmap(rand_cand)(jnp.arange(n_total))
    else:
        rows = [jnp.where(dive_mask, (cons >= tau).astype(X.dtype),
                          cons)[None]
                for tau in thresholds]
        if flips:
            # most-fractional-first: the slots the fleet most disagrees
            # on (non-dive slots key to -1 so they never enter the ball)
            frac = jnp.where(dive_mask, jnp.abs(cons - jnp.round(cons)),
                             -1.0)
            _, top = jax.lax.top_k(frac, flips)
            rows.append(jax.vmap(flip_at)(top))
        if n_random:
            rows.append(jax.vmap(rand_cand)(jnp.arange(n_random)))
        up, down = jnp.max(X, axis=0), jnp.min(X, axis=0)
        rows.append(jnp.stack([up, down]))
        # bound rows: max/min commitment on the dive slots (see the
        # module docstring — the covering-model feasible anchor)
        rows.append(jnp.stack(
            [jnp.where(dive_mask, ub_row, cons),
             jnp.where(dive_mask, lb_row, cons)]))
        pool = jnp.concatenate(rows)
    # integral snap on EVERY integer slot (vote/flip rows are already
    # 0/1 on the dive slots; slam/consensus values may be fractional)
    return jnp.where(int_mask[None, :], jnp.round(pool), pool)


def build_pool(X, prob, dive_mask, integer_mask, lb_row=None, ub_row=None,
               *, thresholds=(0.3, 0.5, 0.7), flips=8, n_random=4, ball=4,
               seed=42, round_index=0, random_only=False):
    """(P, K) candidate pool from the hub's (S, K) nonant block (device
    array; see ``_build_pool`` for the row anatomy). Host wrapper: it
    resolves the STATIC sizes (flips/ball clamp to the dive-slot count,
    random rows need dive slots at all) so the jitted builder compiles
    once per configuration. Returns None for a ``random_only`` build
    with no dive slots — there is no neighborhood to vary, so the
    caller skips the round instead of re-evaluating an identical
    pool."""
    dive_mask = np.asarray(dive_mask, bool)
    n_dive = int(dive_mask.sum())
    flips_eff = min(int(flips), n_dive)
    n_rand_eff = int(n_random) if n_dive else 0
    ball_eff = max(1, min(int(ball), n_dive)) if n_dive else 1
    if random_only and n_dive == 0:
        return None
    dive_idx = np.flatnonzero(dive_mask) if n_dive \
        else np.zeros(1, np.int64)          # placeholder, never selected
    K = np.asarray(X).shape[-1]
    lb_row = np.zeros(K) if lb_row is None else np.asarray(lb_row,
                                                           np.float64)
    ub_row = np.ones(K) if ub_row is None else np.asarray(ub_row,
                                                          np.float64)
    return _build_pool(
        jnp.asarray(X), jnp.asarray(prob), jnp.asarray(dive_mask),
        jnp.asarray(np.asarray(integer_mask, bool)),
        jnp.asarray(dive_idx), jnp.asarray(lb_row), jnp.asarray(ub_row),
        jnp.uint32(int(seed) & 0xFFFFFFFF),
        jnp.uint32(int(round_index) & 0xFFFFFFFF),
        thresholds=tuple(float(t) for t in thresholds), flips=flips_eff,
        ball=ball_eff, n_random=n_rand_eff, random_only=bool(random_only))


@partial(jax.jit, static_argnames=("P", "S"))
def pool_verdict(obj_rows, pri_res, pri_rel, prob, live, feas_tol, *, P, S):
    """Fused feasibility screen + Eobjective over the (P*S,) solved
    rows -> a (2, P) verdict [expected objective; all-scenarios-feasible
    flag]. A row passes on EITHER the absolute or the relative primal
    residual (the engine-wide feasibility predicate); rows of
    zero-probability mesh pad scenarios (``live`` False) are exempt —
    they duplicate a real scenario and carry no objective weight. ONE
    ``np.asarray`` of the result is the round's single D2H."""
    feas = (pri_res <= feas_tol) | (pri_rel <= feas_tol)
    feas = feas.reshape(P, S) | ~live[None, :]
    eobj = obj_rows.reshape(P, S) @ prob
    return jnp.concatenate([eobj[None],
                            feas.all(axis=1)[None].astype(eobj.dtype)])
