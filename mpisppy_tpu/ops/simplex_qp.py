"""Batched simplex-constrained QP solver (FWPH's per-scenario weight QP).

FWPH maintains, per scenario, a convex-combination QP over previously
generated subproblem solutions ("columns"): the reference builds a Pyomo QP
with weight vars `a`, x = Σ a_j x_j links, and hands it to Gurobi
(ref. mpisppy/fwph/fwph.py:691-777 _initialize_QP_subproblems, :943-987
_set_QP_objective). Here the x variables are eliminated (x = aᵀX with X the
(C, n) column stack), leaving a C-dimensional QP over the probability
simplex per scenario:

    min_a  b·a + w·(aG) + (ρ/2)‖aG − x̄‖²    s.t. a ≥ 0, Σa = 1

with G = X[:, nonant] (C, K), b = X c the per-column base costs. C is a
small static pad (rolling column buffer), so the whole thing batches over
scenarios as (S, C) / (S, C, K) tensors and solves with accelerated
projected gradient — ~hundreds of tiny fused MXU matmuls, no host loop.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def project_simplex(v):
    """Batched Euclidean projection onto the probability simplex
    (Held et al.; sort-based, jit-friendly). v: (..., C)."""
    C = v.shape[-1]
    mu = jnp.sort(v, axis=-1)[..., ::-1]
    cssv = jnp.cumsum(mu, axis=-1) - 1.0
    rho_idx = jnp.arange(1, C + 1)
    cond = mu - cssv / rho_idx > 0
    k = jnp.sum(cond, axis=-1, keepdims=True)  # number of positive coords
    tau = jnp.take_along_axis(cssv, k - 1, axis=-1) / k
    return jnp.maximum(v - tau, 0.0)


@partial(jax.jit, static_argnames=("iters",))
def simplex_qp_solve(G, b, w, rho, xbar, a0, iters=300):
    """Solve the weight QP for every scenario.

    G: (S, C, K) column nonant blocks; b: (S, C) base costs; w: (S, K) dual
    weights; rho: (S, K); xbar: (S, K) prox center; a0: (S, C) warm start.
    Returns (a, xn) with xn = aG the QP-optimal nonant values.

    FISTA with a per-scenario Lipschitz bound L = ‖G diag(ρ) Gᵀ‖_F + sum
    of linear curvature; the objective is smooth so acceleration gives
    1/t² decay — plenty for the SDM's Γ tolerance.
    """
    # gradient: ∇ = b + G(w − ρ x̄) + G diag(ρ) Gᵀ a
    lin = b + (G @ ((w - rho * xbar)[..., None]))[..., 0]      # (S, C)
    H = (G * rho[:, None, :]) @ G.swapaxes(1, 2)               # (S, C, C)
    L = jnp.sqrt(jnp.sum(H * H, axis=(1, 2))) + 1e-12          # (S,)
    step = (1.0 / L)[:, None]

    def body(carry, _):
        a, y, t = carry
        grad = lin + (H @ y[..., None])[..., 0]
        a_new = project_simplex(y - step * grad)
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        y_new = a_new + ((t - 1.0) / t_new) * (a_new - a)
        return (a_new, y_new, t_new), None

    (a, _, _), _ = jax.lax.scan(body, (a0, a0, jnp.ones(())), None,
                                length=iters)
    xn = (a[:, None, :] @ G)[:, 0, :]
    return a, xn


def qp_objective_value(G, b, w, rho, xbar, a):
    """φ(a) per scenario (for Γ calculations)."""
    xn = (a[:, None, :] @ G)[:, 0, :]
    return (jnp.sum(b * a, axis=-1) + jnp.sum(w * xn, axis=-1)
            + 0.5 * jnp.sum(rho * (xn - xbar) ** 2, axis=-1))
