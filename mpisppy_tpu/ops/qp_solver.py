"""Batched dense ADMM QP/LP solver (OSQP-style), the framework's native
subproblem kernel.

The reference rents a commercial MIP solver per scenario through Pyomo
(ref. mpisppy/phbase.py:1304-1362, solve_loop :999) — one process-boundary
solver call per subproblem per PH iteration, which is where ~all of its
wall-clock goes. Here the whole scenario batch is solved simultaneously on
the TPU: every operation below is a batched matmul / triangular solve /
elementwise op over the leading scenario axis, so S scenarios cost one MXU
pass, not S solver calls.

Form:   min ½ xᵀ diag(P) x + qᵀx   s.t.  l ≤ A x ≤ u
(variable bounds are folded into A as identity rows by ``fold_bounds``).

Method: ADMM as in OSQP (Stellato et al. 2020) with
 - Ruiz equilibration of the KKT matrix for conditioning,
 - per-row stepsize rho (boosted on equality rows),
 - a cached dense Cholesky factor of M = diag(P) + σI + Aᵀdiag(ρ)A — the key
   PH synergy: PH iterations change only q (W and the prox center x̄), so the
   factorization amortizes across the entire PH run,
 - warm starting from the previous (x, y, z),
 - periodic residual checks inside a lax.while_loop (compiler-friendly
   control flow; no Python in the loop).

Why ADMM and not simplex/IPM: the iteration is pure BLAS-3 over the batch
(MXU-friendly, no pivoting/branching), tolerances ~1e-6..1e-8 in f64 and
~1e-4 in f32 are ample for PH/bounding, and the factor-caching matches PH's
access pattern exactly.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class QPData(NamedTuple):
    """Stacked problem data; leading axis S = scenarios."""
    P_diag: jax.Array   # (S, n)
    A: jax.Array        # (S, m, n) with bound rows folded in
    l: jax.Array        # (S, m)
    u: jax.Array        # (S, m)


class QPFactors(NamedTuple):
    """Setup artifacts reused across solves with different q."""
    L: jax.Array        # (S, n, n) Cholesky factor of M
    rho: jax.Array      # (S, m) per-row stepsize
    sigma: jax.Array    # scalar
    D: jax.Array        # (S, n) column equilibration
    E: jax.Array        # (S, m) row equilibration
    cost_scale: jax.Array  # (S,) objective scaling
    A_s: jax.Array      # (S, m, n) scaled A
    P_s: jax.Array      # (S, n) scaled P diagonal


class QPState(NamedTuple):
    x: jax.Array        # (S, n) scaled iterate
    y: jax.Array        # (S, m) scaled dual
    z: jax.Array        # (S, m) scaled slack
    iters: jax.Array    # (S,) or scalar total iterations run
    pri_res: jax.Array  # (S,)
    dua_res: jax.Array  # (S,)


def fold_bounds(P_diag, A, l, u, lb, ub):
    """Append identity rows for variable bounds -> pure two-sided row form."""
    S, m, n = A.shape
    eye = jnp.broadcast_to(jnp.eye(n, dtype=A.dtype), (S, n, n))
    return QPData(
        P_diag=jnp.asarray(P_diag),
        A=jnp.concatenate([A, eye], axis=1),
        l=jnp.concatenate([l, lb], axis=1),
        u=jnp.concatenate([u, ub], axis=1),
    )


def _ruiz_equilibrate(P_diag, A, iters=15):
    """Modified Ruiz equilibration of the KKT matrix [[P, Aᵀ],[A, 0]].

    Returns (D, E) with scaled P̄ = D P D (diag), Ā = E A D, all batched.
    Infinite bounds are untouched (they scale to ±inf harmlessly).
    """
    S, m, n = A.shape
    D = jnp.ones((S, n), A.dtype)
    E = jnp.ones((S, m), A.dtype)

    def body(_, DE):
        D, E = DE
        As = E[:, :, None] * A * D[:, None, :]
        Ps = D * P_diag * D
        # column norms of the KKT block column for x: max(|Ps|, colmax|As|)
        cnorm = jnp.maximum(jnp.abs(Ps), jnp.max(jnp.abs(As), axis=1))
        rnorm = jnp.max(jnp.abs(As), axis=2)
        d = 1.0 / jnp.sqrt(jnp.maximum(cnorm, 1e-8))
        e = 1.0 / jnp.sqrt(jnp.maximum(rnorm, 1e-8))
        # guard empty rows/cols
        d = jnp.where(cnorm < 1e-12, 1.0, d)
        e = jnp.where(rnorm < 1e-12, 1.0, e)
        return D * d, E * e

    D, E = jax.lax.fori_loop(0, iters, body, (D, E))
    return D, E


@partial(jax.jit, static_argnames=("eq_boost",))
def qp_setup(data: QPData, rho_base=0.1, sigma=1e-6, eq_boost=1e3):
    """Equilibrate, choose per-row rho, factor M. O(S·n³) once per problem
    (and once per PH rho change); solves reuse the factor."""
    P_diag, A, l, u = data
    dt = A.dtype
    D, E = _ruiz_equilibrate(P_diag, A)
    A_s = E[:, :, None] * A * D[:, None, :]
    P_s = D * P_diag * D
    l_s = E * l
    u_s = E * u
    # cost scaling: normalize scaled gradient magnitude ~ 1 (OSQP uses
    # 1/max(mean col norms); a cheap robust proxy here)
    cost_scale = 1.0 / jnp.maximum(jnp.max(jnp.abs(P_s), axis=1), 1.0)
    P_s = P_s * cost_scale[:, None]

    is_eq = jnp.abs(u_s - l_s) < 1e-12
    rho = jnp.where(is_eq, rho_base * eq_boost, rho_base).astype(dt)

    n = A.shape[2]
    M = (A_s * rho[:, :, None]).swapaxes(1, 2) @ A_s
    M = M + jnp.eye(n, dtype=dt) * sigma
    M = M + jax.vmap(jnp.diag)(P_s)
    L = jnp.linalg.cholesky(M)
    return QPFactors(L=L, rho=rho, sigma=jnp.asarray(sigma, dt), D=D, E=E,
                     cost_scale=cost_scale, A_s=A_s, P_s=P_s)


def _chol_solve(L, b):
    """Batched solve M x = b given Cholesky factor L (S,n,n), b (S,n)."""
    y = jax.lax.linalg.triangular_solve(L, b[..., None], left_side=True,
                                        lower=True, transpose_a=False)
    x = jax.lax.linalg.triangular_solve(L, y, left_side=True,
                                        lower=True, transpose_a=True)
    return x[..., 0]


def cold_state(S, n, m, dtype=jnp.float32):
    z = jnp.zeros((S, m), dtype)
    return QPState(x=jnp.zeros((S, n), dtype), y=jnp.zeros((S, m), dtype),
                   z=z, iters=jnp.zeros((), jnp.int32),
                   pri_res=jnp.full((S,), jnp.inf, dtype),
                   dua_res=jnp.full((S,), jnp.inf, dtype))


@partial(jax.jit, static_argnames=("max_iter", "check_every"))
def qp_solve(factors: QPFactors, data: QPData, q, state: QPState,
             max_iter=4000, check_every=25, eps_abs=1e-6, eps_rel=1e-6,
             alpha=1.6):
    """Run ADMM until residuals pass (eps_abs, eps_rel) or max_iter.

    Returns (state, x_unscaled (S,n), y_unscaled (S,m)). `q` is the UNscaled
    linear cost; scaling uses the cached factors. Warm start by passing the
    previous state; cold start with `cold_state`.
    """
    L, rho, sigma, D, E, cs, A_s, P_s = factors
    l_s = E * data.l
    u_s = E * data.u
    q_s = cs[:, None] * D * q
    dt = A_s.dtype
    eps_abs = jnp.asarray(eps_abs, dt)
    eps_rel = jnp.asarray(eps_rel, dt)

    def admm_iter(carry, _):
        x, y, z = carry
        rhs = sigma * x - q_s + (A_s.swapaxes(1, 2) @ ((rho * z - y)[..., None]))[..., 0]
        x_t = _chol_solve(L, rhs)
        x_new = alpha * x_t + (1 - alpha) * x
        z_t = (A_s @ x_t[..., None])[..., 0]
        z_mix = alpha * z_t + (1 - alpha) * z
        z_new = jnp.clip(z_mix + y / rho, l_s, u_s)
        y_new = y + rho * (z_mix - z_new)
        return (x_new, y_new, z_new), None

    def residuals(x, y, z):
        Ax = (A_s @ x[..., None])[..., 0]
        Aty = (A_s.swapaxes(1, 2) @ y[..., None])[..., 0]
        pri = jnp.max(jnp.abs(Ax - z), axis=1)
        dua = jnp.max(jnp.abs(P_s * x + q_s + Aty), axis=1)
        # relative scalings (OSQP-style)
        pri_sc = jnp.maximum(jnp.max(jnp.abs(Ax), axis=1),
                             jnp.max(jnp.abs(z), axis=1))
        dua_sc = jnp.maximum(jnp.max(jnp.abs(P_s * x), axis=1),
                             jnp.maximum(jnp.max(jnp.abs(q_s), axis=1),
                                         jnp.max(jnp.abs(Aty), axis=1)))
        return pri, dua, pri_sc, dua_sc

    def cond(carry):
        x, y, z, it, done = carry
        return jnp.logical_and(it < max_iter, jnp.logical_not(done))

    def body(carry):
        x, y, z, it, _ = carry
        (x, y, z), _ = jax.lax.scan(admm_iter, (x, y, z), None, length=check_every)
        pri, dua, pri_sc, dua_sc = residuals(x, y, z)
        done = jnp.all(jnp.logical_and(pri <= eps_abs + eps_rel * pri_sc,
                                       dua <= eps_abs + eps_rel * dua_sc))
        return (x, y, z, it + check_every, done)

    x, y, z, it, _ = jax.lax.while_loop(
        cond, body, (state.x, state.y, state.z, jnp.zeros((), jnp.int32), jnp.array(False)))

    pri, dua, _, _ = residuals(x, y, z)
    new_state = QPState(x=x, y=y, z=z, iters=it, pri_res=pri, dua_res=dua)
    x_un = D * x
    y_un = cs[:, None] ** -1 * E * y  # unscale duals
    return new_state, x_un, y_un


def qp_objective(data: QPData, q, c0, x):
    """½xᵀPx + qᵀx + c0 per scenario (unscaled)."""
    return 0.5 * jnp.sum(data.P_diag * x * x, axis=-1) + jnp.sum(q * x, axis=-1) + c0
