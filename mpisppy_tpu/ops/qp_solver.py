"""Batched dense ADMM QP/LP solver (OSQP-style), the framework's native
subproblem kernel.

The reference rents a commercial MIP solver per scenario through Pyomo
(ref. mpisppy/phbase.py:1304-1362, solve_loop :999) — one process-boundary
solver call per subproblem per PH iteration, which is where ~all of its
wall-clock goes. Here the whole scenario batch is solved simultaneously on
the TPU: every operation below is a batched matmul / triangular solve /
elementwise op over the leading scenario axis, so S scenarios cost one MXU
pass, not S solver calls.

Form:   min ½ xᵀ diag(P) x + qᵀx   s.t.  l ≤ A x ≤ u
(variable bounds are folded into A as identity rows by ``fold_bounds``).

Method: ADMM as in OSQP (Stellato et al. 2020) with
 - Ruiz equilibration of the KKT matrix plus cost normalization,
 - per-row stepsize rho (boosted on equality rows) with OSQP's adaptive
   rho rule: rho <- rho * sqrt(rel_pri_res / rel_dua_res), refactorizing
   the KKT matrix inside the solve loop when the change exceeds 5x,
 - a dense Cholesky factor of M = diag(P) + sigma*I + A'diag(rho)A carried
   in the *solver state*: PH iterations change only q (W and the prox
   center x-bar), so both the factor and the adapted rho persist across
   warm-started solves and refactorization becomes rare at steady state,
 - periodic residual checks inside a lax.while_loop (compiler-friendly
   control flow; no Python in the loop).

Why ADMM and not simplex/IPM: the iteration is pure BLAS-3 over the batch
(MXU-friendly, no pivoting/branching), tolerances ~1e-6..1e-8 in f64 and
~1e-4 in f32 are ample for PH/bounding, and the factor-caching matches PH's
access pattern exactly.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


class QPData(NamedTuple):
    """Stacked problem data; leading axis S = scenarios."""
    P_diag: jax.Array   # (S, n)
    A: jax.Array        # (S, m, n) with bound rows folded in
    l: jax.Array        # (S, m)
    u: jax.Array        # (S, m)


class QPFactors(NamedTuple):
    """Static setup artifacts (scaling + scaled matrices)."""
    sigma: jax.Array       # scalar
    D: jax.Array           # (S, n) column equilibration
    E: jax.Array           # (S, m) row equilibration
    cost_scale: jax.Array  # (S,) objective scaling
    A_s: jax.Array         # (S, m, n) scaled A
    P_s: jax.Array         # (S, n) scaled P diagonal
    rho_pattern: jax.Array  # (S, m) relative per-row rho (eq rows boosted)


class QPState(NamedTuple):
    """Warm-startable solver state; L and rho persist across solves."""
    x: jax.Array        # (S, n) scaled iterate
    y: jax.Array        # (S, m) scaled dual
    z: jax.Array        # (S, m) scaled slack
    L: jax.Array        # (S, n, n) Cholesky factor of current KKT matrix
    rho_scale: jax.Array  # (S,) scalar multiplier on rho_pattern
    iters: jax.Array    # scalar total ADMM iterations in last solve
    pri_res: jax.Array  # (S,) unscaled
    dua_res: jax.Array  # (S,) unscaled
    pri_rel: jax.Array  # (S,) pri_res / problem scale (feasibility metric)


def fold_bounds(P_diag, A, l, u, lb, ub):
    """Append identity rows for variable bounds -> pure two-sided row form."""
    S, m, n = A.shape
    eye = jnp.broadcast_to(jnp.eye(n, dtype=A.dtype), (S, n, n))
    return QPData(
        P_diag=jnp.asarray(P_diag),
        A=jnp.concatenate([A, eye], axis=1),
        l=jnp.concatenate([l, lb], axis=1),
        u=jnp.concatenate([u, ub], axis=1),
    )


def _ruiz_equilibrate(P_diag, A, iters=15):
    """Modified Ruiz equilibration of the KKT matrix [[P, A'],[A, 0]].
    Returns (D, E) with scaled P = D P D (diag), A = E A D, all batched."""
    S, m, n = A.shape
    D = jnp.ones((S, n), A.dtype)
    E = jnp.ones((S, m), A.dtype)

    def body(_, DE):
        D, E = DE
        As = E[:, :, None] * A * D[:, None, :]
        Ps = D * P_diag * D
        cnorm = jnp.maximum(jnp.abs(Ps), jnp.max(jnp.abs(As), axis=1))
        rnorm = jnp.max(jnp.abs(As), axis=2)
        d = jnp.where(cnorm < 1e-12, 1.0, 1.0 / jnp.sqrt(jnp.maximum(cnorm, 1e-12)))
        e = jnp.where(rnorm < 1e-12, 1.0, 1.0 / jnp.sqrt(jnp.maximum(rnorm, 1e-12)))
        return D * d, E * e

    D, E = jax.lax.fori_loop(0, iters, body, (D, E))
    return D, E


def _factorize(factors: QPFactors, rho_scale):
    """Batched Cholesky of M = diag(P_s) + sigma I + A_s' diag(rho) A_s."""
    A_s, P_s = factors.A_s, factors.P_s
    rho = factors.rho_pattern * rho_scale[:, None]
    n = A_s.shape[2]
    M = (A_s * rho[:, :, None]).swapaxes(1, 2) @ A_s
    M = M + jnp.eye(n, dtype=A_s.dtype) * factors.sigma
    M = M + jax.vmap(jnp.diag)(P_s)
    return jnp.linalg.cholesky(M)


@partial(jax.jit, static_argnames=("eq_boost",))
def qp_setup(data: QPData, q_ref=None, rho_base=0.1, sigma=1e-6, eq_boost=1e3):
    """Equilibrate and scale. O(S n^2) + one batched n^3 Cholesky in
    qp_cold_state; re-solves with new q reuse everything."""
    P_diag, A, l, u = data
    dt = A.dtype
    D, E = _ruiz_equilibrate(P_diag, A)
    A_s = E[:, :, None] * A * D[:, None, :]
    P_s = D * P_diag * D
    # cost normalization (OSQP sec 5.1): scale so the objective gradient is O(1)
    if q_ref is None:
        q_ref = jnp.zeros_like(P_diag)
    qs = D * q_ref
    gnorm = jnp.maximum(jnp.max(jnp.abs(P_s), axis=1), jnp.max(jnp.abs(qs), axis=1))
    cost_scale = 1.0 / jnp.maximum(gnorm, 1.0)
    P_s = P_s * cost_scale[:, None]

    is_eq = jnp.abs(E * u - E * l) < 1e-12
    rho_pattern = jnp.where(is_eq, rho_base * eq_boost, rho_base).astype(dt)
    return QPFactors(sigma=jnp.asarray(sigma, dt), D=D, E=E,
                     cost_scale=cost_scale, A_s=A_s, P_s=P_s,
                     rho_pattern=rho_pattern)


@jax.jit
def qp_cold_state(factors: QPFactors) -> QPState:
    S, m, n = factors.A_s.shape
    dt = factors.A_s.dtype
    rho_scale = jnp.ones((S,), dt)
    L = _factorize(factors, rho_scale)
    z = jnp.zeros((S, m), dt)
    return QPState(x=jnp.zeros((S, n), dt), y=jnp.zeros((S, m), dt), z=z,
                   L=L, rho_scale=rho_scale, iters=jnp.zeros((), jnp.int32),
                   pri_res=jnp.full((S,), jnp.inf, dt),
                   dua_res=jnp.full((S,), jnp.inf, dt),
                   pri_rel=jnp.full((S,), jnp.inf, dt))


def _chol_solve(L, b):
    """Batched solve M x = b given Cholesky factor L (S,n,n), b (S,n)."""
    y = jax.lax.linalg.triangular_solve(L, b[..., None], left_side=True,
                                        lower=True, transpose_a=False)
    x = jax.lax.linalg.triangular_solve(L, y, left_side=True,
                                        lower=True, transpose_a=True)
    return x[..., 0]


@partial(jax.jit, static_argnames=("max_iter", "check_every", "adaptive_rho"))
def qp_solve(factors: QPFactors, data: QPData, q, state: QPState,
             max_iter=4000, check_every=25, eps_abs=1e-6, eps_rel=1e-6,
             alpha=1.6, adaptive_rho=True):
    """Run ADMM until residuals pass (eps_abs, eps_rel) or max_iter.

    Returns (state, x_unscaled (S,n), y_unscaled (S,m)). `q` is the UNscaled
    linear cost. Warm start by passing the previous state (its adapted rho
    and factor carry over); cold start with `qp_cold_state(factors)`.
    """
    sigma, D, E, cs, A_s, P_s, rho_pattern = factors
    l_s = E * data.l
    u_s = E * data.u
    q_s = cs[:, None] * D * q
    dt = A_s.dtype
    eps_abs = jnp.asarray(eps_abs, dt)
    eps_rel = jnp.asarray(eps_rel, dt)

    def admm_chunk(x, y, z, L, rho):
        def one(carry, _):
            x, y, z = carry
            rhs = sigma * x - q_s + (A_s.swapaxes(1, 2) @ ((rho * z - y)[..., None]))[..., 0]
            x_t = _chol_solve(L, rhs)
            x_new = alpha * x_t + (1 - alpha) * x
            z_t = (A_s @ x_t[..., None])[..., 0]
            z_mix = alpha * z_t + (1 - alpha) * z
            z_new = jnp.clip(z_mix + y / rho, l_s, u_s)
            y_new = y + rho * (z_mix - z_new)
            return (x_new, y_new, z_new), None

        (x, y, z), _ = jax.lax.scan(one, (x, y, z), None, length=check_every)
        return x, y, z

    def residuals(x, y, z):
        """UNSCALED residuals (OSQP's default termination convention): the
        scaled ones can be orders of magnitude smaller than problem-unit
        errors, which would poison the dual-objective bounds."""
        Ax = (A_s @ x[..., None])[..., 0]
        Aty = (A_s.swapaxes(1, 2) @ y[..., None])[..., 0]
        Einv = 1.0 / E
        Dinv_c = 1.0 / (D * cs[:, None])
        pri = jnp.max(jnp.abs(Einv * (Ax - z)), axis=1)
        dua = jnp.max(jnp.abs(Dinv_c * (P_s * x + q_s + Aty)), axis=1)
        pri_sc = jnp.maximum(jnp.maximum(jnp.max(jnp.abs(Einv * Ax), axis=1),
                                         jnp.max(jnp.abs(Einv * z), axis=1)), 1e-6)
        dua_sc = jnp.maximum(jnp.maximum(
            jnp.max(jnp.abs(Dinv_c * P_s * x), axis=1),
            jnp.maximum(jnp.max(jnp.abs(Dinv_c * q_s), axis=1),
                        jnp.max(jnp.abs(Dinv_c * Aty), axis=1))), 1e-6)
        return pri, dua, pri_sc, dua_sc

    def cond(carry):
        *_, it, done = carry
        return jnp.logical_and(it < max_iter, jnp.logical_not(done))

    def body(carry):
        x, y, z, L, rho_scale, it, _ = carry
        rho = rho_pattern * rho_scale[:, None]
        x, y, z = admm_chunk(x, y, z, L, rho)
        pri, dua, pri_sc, dua_sc = residuals(x, y, z)
        done = jnp.all(jnp.logical_and(pri <= eps_abs + eps_rel * pri_sc,
                                       dua <= eps_abs + eps_rel * dua_sc))
        if adaptive_rho:
            # OSQP-style infrequent adaptation: every 4th residual check, and
            # only scenarios whose ideal rho moved by > 5x adopt the new
            # value (per-scenario; adapting all on any trigger thrashes)
            adapt_now = ((it // check_every) % 4) == 3
            ratio = jnp.sqrt((pri / pri_sc) / jnp.maximum(dua / dua_sc, 1e-30))
            new_scale = jnp.clip(rho_scale * ratio, 1e-6, 1e6)
            change = jnp.maximum(new_scale / rho_scale, rho_scale / new_scale)
            mask = (change > 5.0) & adapt_now & jnp.logical_not(done)
            rho_scale = jnp.where(mask, new_scale, rho_scale)
            need = jnp.any(mask)
            L = jax.lax.cond(need, lambda: _factorize(factors, rho_scale),
                             lambda: L)
        return (x, y, z, L, rho_scale, it + check_every, done)

    x, y, z, L, rho_scale, it, _ = jax.lax.while_loop(
        cond, body,
        (state.x, state.y, state.z, state.L, state.rho_scale,
         jnp.zeros((), jnp.int32), jnp.array(False)))

    pri, dua, pri_sc, _ = residuals(x, y, z)
    new_state = QPState(x=x, y=y, z=z, L=L, rho_scale=rho_scale, iters=it,
                        pri_res=pri, dua_res=dua, pri_rel=pri / pri_sc)
    x_un = D * x
    y_un = (1.0 / cs[:, None]) * E * y  # unscale duals
    return new_state, x_un, y_un


def qp_objective(data: QPData, q, c0, x):
    """½x'Px + q'x + c0 per scenario (unscaled)."""
    return 0.5 * jnp.sum(data.P_diag * x * x, axis=-1) + jnp.sum(q * x, axis=-1) + c0


def _boxmin(P, r, lb, ub):
    """Coordinate-wise min of ½P x² + r x over [lb, ub] (P >= 0 diagonal).
    Returns -inf where a linear piece descends toward an infinite bound."""
    x_unc = jnp.where(P > 0, -r / jnp.where(P > 0, P, 1.0), 0.0)
    x_star = jnp.clip(x_unc, lb, ub)
    quad_val = 0.5 * P * x_star * x_star + r * x_star
    lin_lo = jnp.where(r > 0, jnp.where(jnp.isneginf(lb), -jnp.inf, r * lb), 0.0)
    lin_hi = jnp.where(r < 0, jnp.where(jnp.isposinf(ub), -jnp.inf, r * ub), 0.0)
    return jnp.where(P > 0, quad_val, lin_lo + lin_hi)


def _sup_rows(l, u, y, inf_tol=1e-9):
    """sup over the row box of y'z: u'y+ − l'y−, +inf when a positive dual
    pushes on an infinite bound. Shared by qp_dual_objective/benders_cut."""
    yp = jnp.maximum(y, 0.0)
    ym = jnp.maximum(-y, 0.0)
    u_fin = jnp.where(jnp.isfinite(u), u, 0.0)
    l_fin = jnp.where(jnp.isfinite(l), l, 0.0)
    return jnp.sum(u_fin * yp - l_fin * ym, axis=-1) \
        + jnp.sum(jnp.where((jnp.isposinf(u) & (yp > inf_tol))
                            | (jnp.isneginf(l) & (ym > inf_tol)), jnp.inf, 0.0),
                  axis=-1)


def _column_bound(P, q, r, y_b, lb, ub, x_witness, r_rel_tol):
    """Per-column contribution to the dual bound: best of (a) keep the
    bound-row dual, (b) drop it; plus the witness fallback when both are
    -inf. Shared by qp_dual_objective/benders_cut (see the docstrings
    there for the derivation)."""
    tol = r_rel_tol * jnp.maximum(1.0, jnp.abs(q))
    r_a = jnp.where(jnp.abs(r) <= tol, 0.0, r)
    ybp = jnp.maximum(y_b, 0.0)
    ybm = jnp.maximum(-y_b, 0.0)
    ub_fin = jnp.where(jnp.isfinite(ub), ub, 0.0)
    lb_fin = jnp.where(jnp.isfinite(lb), lb, 0.0)
    sup_b = ub_fin * ybp - lb_fin * ybm \
        + jnp.where((jnp.isposinf(ub) & (ybp > 1e-9))
                    | (jnp.isneginf(lb) & (ybm > 1e-9)), jnp.inf, 0.0)
    contrib_a = _boxmin(P, r_a, lb, ub) - sup_b
    contrib_b = _boxmin(P, r - y_b, lb, ub)
    best = jnp.maximum(contrib_a, contrib_b)
    if x_witness is not None:
        r_fix = jnp.where(jnp.isposinf(ub) & (r_a < 0), 0.0, r_a)
        r_fix = jnp.where(jnp.isneginf(lb) & (r_fix > 0), 0.0, r_fix)
        penalty = jnp.abs(r_a - r_fix) * (2.0 * jnp.abs(x_witness) + 1.0)
        fallback = _boxmin(P, r_fix, lb, ub) - sup_b - penalty
        best = jnp.maximum(best, jnp.where(jnp.isneginf(best), fallback, best))
    return best


def qp_dual_objective(data: QPData, q, c0, y, n_rows, x_witness=None,
                      r_rel_tol=1e-6):
    """Per-scenario LOWER bound on min ½x'Px + q'x + c0 s.t. l <= Ax <= u,
    lb <= x <= ub, from an (approximately) dual-feasible y.

    An inexact *primal* solution over-estimates the subproblem minimum, so
    bounds built from primal objectives (what the reference gets for free
    from its exact MIP solver, ref. phbase.py:314 Ebound) would be invalid
    here. Instead evaluate a Lagrangian dual at y. With y split into
    constraint-row duals y_c (first n_rows rows) and folded bound-row duals
    y_b, *any* choice of bound-row duals yields a valid bound when x is also
    kept in its box, so per coordinate we take the better of:

      (a) keep y_b_j:  boxmin(½Px² + r_j x) - (ub_j y_bj+ - lb_j y_bj-)
          with r = q + A'y the full dual residual, entries below
          r_rel_tol*max(1,|q_j|) zeroed (epsilon-valid convention), and
      (b) drop y_b_j:  boxmin(½Px² + (r_j - y_bj) x)   [pure reduced cost]

    plus, where both are -inf (an infinite-direction residual above
    tolerance), a witness fallback: clamp the offending residual part and
    pay |clamped|*(2|x_witness_j| + 1) — valid whenever the true optimum
    satisfies |x*_j| <= 2|x_witness_j| + 1.

    The total is  -sup_c + sum_j best_j + c0  with
    sup_c = u_c'y_c+ - l_c'y_c- over constraint rows only.
    """
    lb = data.l[..., n_rows:]
    ub = data.u[..., n_rows:]
    y_b = y[..., n_rows:]
    r = q + (data.A.swapaxes(-1, -2) @ y[..., None])[..., 0]
    best = _column_bound(data.P_diag, q, r, y_b, lb, ub, x_witness, r_rel_tol)
    sup_c = _sup_rows(data.l[..., :n_rows], data.u[..., :n_rows],
                      y[..., :n_rows])
    return jnp.sum(best, axis=-1) - sup_c + c0


def benders_cut(data: QPData, q, c0, y, n_rows, param_mask, b0,
                r_rel_tol=1e-6):
    """Affine minorant of the *value function* V(b) =
    min ½x'Px + q'x + c0 s.t. l <= Ax <= u, box bounds, with the columns in
    `param_mask` fixed at b (their box rows carry l=u=b in `data`).

    Returns (const (S,), g (S, n) zero outside param_mask) such that
    V(b) >= const + g·b[param] for all b, up to the r_rel_tol
    residual-zeroing convention — the L-shaped optimality cut (the
    reference gets these from exact solver duals via
    pyomo.contrib.benders, ref. mpisppy/opt/lshaped.py:639; here they come
    from ADMM dual vectors, so inexact subproblem solves still yield
    tolerance-valid cuts).

    Derivation: split the dual y into constraint-row duals y_c (first
    n_rows) and bound-row duals y_b. Dropping y_b on the parameterized
    columns, the dual function's dependence on b is
      sum_{j in param} [ (q + A_c'y_c)_j b_j + ½P_j b_j² ],
    and the quadratic is linearized at b0 (valid: a convex function's
    tangent is a global minorant). Non-parameter columns contribute the
    same per-coordinate best-of-two boxmin terms as qp_dual_objective.
    No x_witness fallback here: its validity box is tied to the solve at
    b0, but a cut must minorize V at EVERY b — a -inf free column simply
    yields an inactive (-inf) cut instead."""
    lb = data.l[..., n_rows:]
    ub = data.u[..., n_rows:]
    y_b = y[..., n_rows:]
    pm = param_mask  # (n,) bool
    P = data.P_diag

    r = q + (data.A.swapaxes(-1, -2) @ y[..., None])[..., 0]
    r_c = r - y_b  # bound rows are identity, so A_b'y_b = y_b

    # parameterized columns: affine in b, quadratic linearized at b0
    g = jnp.where(pm, r_c + P * b0, 0.0)
    const_param = jnp.sum(jnp.where(pm, -0.5 * P * b0 * b0, 0.0), axis=-1)

    best = _column_bound(P, q, r, y_b, lb, ub, None, r_rel_tol)
    const_free = jnp.sum(jnp.where(pm, 0.0, best), axis=-1)
    sup_c = _sup_rows(data.l[..., :n_rows], data.u[..., :n_rows],
                      y[..., :n_rows])
    return const_param + const_free - sup_c + c0, g
