"""Batched dense ADMM QP/LP solver (OSQP-style), the framework's native
subproblem kernel.

The reference rents a commercial MIP solver per scenario through Pyomo
(ref. mpisppy/phbase.py:1304-1362, solve_loop :999) — one process-boundary
solver call per subproblem per PH iteration, which is where ~all of its
wall-clock goes. Here the whole scenario batch is solved simultaneously on
the TPU: every operation below is a batched matmul / triangular solve /
elementwise op over the leading scenario axis, so S scenarios cost one MXU
pass, not S solver calls.

Form:   min ½ xᵀ diag(P) x + qᵀx   s.t.  l ≤ A x ≤ u,  lb ≤ x ≤ ub.

Variable boxes are handled NATIVELY in the ADMM splitting (they are a
second, diagonal constraint block), not folded into A as identity rows:
the identity block's KKT contribution is a pure diagonal, so the fold
would only double the row count and materialize (S, n, n) of zeros.

Structure sharing: ``A`` (and ``P_diag``) may be given UNBATCHED —
``A (m, n)``, ``P_diag (n,)`` — when every scenario shares the same
matrix and only (c, l, u, lb, ub) differ (true for UC/sizes/sslp/hydro,
where scenarios differ in the rhs only). The KKT factorization is then a
single shared (n, n) Cholesky instead of (S, n, n), the per-iteration
matmuls become one (m, n) × (n, S) MXU pass, and HBM stops scaling as
S·n² — this is what makes the 1000-scenario north star
(ref. paperruns/larger_uc/1000scenarios_wind) fit one chip.

Method: ADMM as in OSQP (Stellato et al. 2020) with
 - Ruiz equilibration of the KKT matrix (bound rows enter analytically),
 - per-row stepsize rho (boosted on equality rows/fixed columns) with
   OSQP's adaptive rho rule, refactorizing inside the solve loop when the
   change exceeds 5x (tied to a single scalar in shared-structure mode so
   the factor stays shared),
 - the Cholesky factor of M = diag(P) + sigma*I + Aᵀdiag(ρ_A)A + diag(g²ρ_b)
   carried in the *solver state*: PH iterations change only q, so the
   factor and adapted rho persist across warm-started solves,
 - periodic residual checks inside a lax.while_loop (compiler-friendly
   control flow; no Python in the loop).

Why ADMM and not simplex/IPM: the iteration is pure BLAS-3 over the batch
(MXU-friendly, no pivoting/branching), tolerances ~1e-6..1e-8 in f64 and
~1e-4 in f32 are ample for PH/bounding, and the factor-caching matches PH's
access pattern exactly.

Known limitation: on scenarios whose optimum is DEGENERATE (more active
constraints than variables), the polished duals retain O(dual tolerance)
residual components along the rank-deficient directions, and the
certified dual bound is then loose by ~1e-4 RELATIVE (residual times the
widest variable box). Non-degenerate scenarios polish to machine-level
exactness. 1e-4 relative matches the reference's own target MIP gaps
(0.01-0.07%, see BASELINE.md), and the bound stays VALID either way.
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs


# MPISPPY_TPU_SOLVE_TRACE=1: wall-time stamps per solver segment (each
# stamp forces a device sync, serializing host work behind device
# compute — a measurement tool, never a default). The r4 verdict's MFU
# question is unanswerable without knowing where a 15-second chunk solve
# actually spends its time: f32 bulk vs df32 tail vs handoffs.


def _trace_enabled() -> bool:
    """Re-read the env flag LAZILY on every segment: the historical
    import-time freeze meant tests (and long-lived processes) could
    never toggle the trace after the first ``import qp_solver``."""
    return bool(int(os.environ.get("MPISPPY_TPU_SOLVE_TRACE", "0") or 0))


def _trace_seg(tag, t0, state):
    obs.counter_add("qp.solve_segments")
    if not _trace_enabled():
        return
    jax.block_until_ready(state.x)
    dt = time.perf_counter() - t0
    iters = int(state.iters)
    pri = float(jnp.max(state.pri_rel))
    msg = (f"[solve-trace] {tag}: {dt:7.3f}s ran={iters:4d} "
           f"pri_rel_max={pri:.2e}")
    # telemetry first (structured, mergeable), raw stderr second (the
    # historical greppable form tools already parse)
    obs.event("qp.solve_segment",
              {"tag": tag, "seconds": dt, "iters": iters,
               "pri_rel_max": pri})
    # latency histogram: segment durations are multi-modal (f32 bulk
    # vs df32 tail vs polish) — the bucketed tails tell them apart
    # where a mean cannot
    obs.histogram_observe("qp.solve_segment_seconds", dt)
    print(msg, file=sys.stderr, flush=True)


class SplitMatrix(NamedTuple):
    """Double-float ("df32") matrix: hi + lo ≈ the f64 matrix, both f32.

    TPU MXUs have no f64 datapath — XLA emulates f64 matmuls by
    splitting BOTH operands into multiple f32 terms and materializing
    every cross product, which at reference-UC scale (25836 × 13056)
    exceeds HBM (measured: 17.6 G needed vs 15.75 G for ONE A @ x).
    The classic double-float compensation (Dekker 1971 two-term split)
    gets ~2× the f32 mantissa from THREE ordinary f32 MXU passes:

        A @ x ≈ hi @ x_hi + lo @ x_hi + hi @ x_lo      (drop lo·lo)

    with the three f32 products accumulated in f64 (cheap: products are
    (S, m)-shaped vectors, not matrices). Input quantization error
    drops from ~6e-8 to ~4e-15 relative; what remains is the f32
    accumulation noise of each pass (~1e-7 relative, sqrt(n)·eps32),
    which sets the ADMM residual floor — measured ample for the 1e-4
    solver-grade target where plain f32 plateaus at ~1e-2. This is the
    kernel's big-instance representation: no f64 copy of A ever sits
    in HBM and no emulated-f64 matmul is ever compiled.

    ``struct``/``pk_hi``/``pk_lo`` (optional): the structure-packed
    matvec form (see ops/packed.py). ``struct`` is the host-derived
    index skeleton attached at ship time; setup gathers the SCALED
    hi/lo into ``pk_hi``/``pk_lo``, after which every _Ax/_ATy pass
    reads ~1.5% of the dense bytes (the r5 MFU fix — BENCH_r04
    measured 3.8% MFU with the dense passes dominating HBM traffic).
    The dense pair stays resident for the factorization matmul and
    support_touch."""
    hi: jax.Array
    lo: jax.Array
    struct: object = None      # packed.PackStructure | None
    pk_hi: object = None       # packed.Packed | None
    pk_lo: object = None       # packed.Packed | None

    @property
    def ndim(self):
        return self.hi.ndim

    @property
    def shape(self):
        return self.hi.shape

    @property
    def dtype(self):
        # the VALUE dtype the pair represents (consumers dispatch on it)
        return jnp.float64


class PackedMatrix(NamedTuple):
    """Single-precision matrix with a packed matvec form riding along:
    the f32 bulk phase's view of a packed SplitMatrix (dense ``hi`` for
    in-loop refactorization, packed for every matvec)."""
    dense: jax.Array
    pk: object                 # packed.Packed

    @property
    def ndim(self):
        return self.dense.ndim

    @property
    def shape(self):
        return self.dense.shape

    @property
    def dtype(self):
        return self.dense.dtype


def split_f32(a) -> SplitMatrix:
    """Two-term split of an f64 array (hi = f32 round, lo = residual)."""
    hi = a.astype(jnp.float32)
    lo = (a - hi.astype(jnp.float64)).astype(jnp.float32)
    return SplitMatrix(hi, lo)


def split_f32_np(a):
    """Host-numpy twin of split_f32 (the ONE split convention — data
    shipping and tests must not re-derive it). Returns (hi, lo)."""
    a = np.asarray(a, np.float64)
    hi = a.astype(np.float32)
    lo = (a - hi.astype(np.float64)).astype(np.float32)
    return hi, lo


def merged64(A):
    """The f64 value of a SplitMatrix (or a plain array cast to f64).
    Materializes (m, n) in f64 — use only inside fused elementwise/
    reduce computations or on host."""
    if isinstance(A, SplitMatrix):
        return A.hi.astype(jnp.float64) + A.lo.astype(jnp.float64)
    return A.astype(jnp.float64) if hasattr(A, "astype") else A


class ScaledView(NamedTuple):
    """QPData.A as a VIEW over the factors' scaled matrix:
    A = diag(1/E) · A_s · diag(1/D). At df32 scale the raw split A and
    the scaled split A_s cannot both live in HBM (2.7 GB each on the
    reference-UC instance); once the base factors exist, engines swap
    their QPData.A for this view and the raw pair frees. Matvec
    consumers (_Ax/_ATy — residual checks, dual objectives, dives)
    dispatch on it transparently."""
    A_s: jax.Array          # SplitMatrix or dense (m, n)
    D: jax.Array            # (n,)
    E: jax.Array            # (m,)

    @property
    def ndim(self):
        return 2

    @property
    def shape(self):
        return self.A_s.shape

    @property
    def dtype(self):
        return jnp.float64


def host_dense_A(A):
    """Host numpy f64 of a QPData.A under any representation. A
    ScaledView at df32 scale would need a multi-GB device->host pull
    (~minutes on tunneled links) — consumers must use the device
    dispatch paths instead."""
    if isinstance(A, ScaledView):
        raise TypeError("host_dense_A on a ScaledView: reconstructing "
                        "the dense matrix host-side defeats the view's "
                        "purpose; use _Ax/_ATy/support_touch on device")
    if isinstance(A, SplitMatrix):
        return np.asarray(A.hi, np.float64) + np.asarray(A.lo, np.float64)
    return np.asarray(A, np.float64)


@jax.jit
def _support_touch_jit(hi, viol):
    # jitted so the abs/mask/cast fuse into the matmul operand instead
    # of materializing eager (m, n) transients (GBs at df32 scale)
    supp = (jnp.abs(hi) > 1e-10).astype(jnp.float32)
    v = viol.astype(jnp.float32)
    if hi.ndim == 2:
        return v @ supp
    return jnp.einsum("sm,smn->sn", v, supp)


def support_touch(A, viol):
    """(S, n) column-touch counts of the (S, m) bool row mask ``viol``
    through A's sparsity support — on DEVICE for the big
    representations (the dive's targeted-repair column selection)."""
    hi = A
    if isinstance(A, ScaledView):
        hi = A.A_s
    if isinstance(hi, SplitMatrix):
        hi = hi.hi
    return _support_touch_jit(hi, jnp.asarray(viol))


class QPData(NamedTuple):
    """Stacked problem data; leading axis S = scenarios. ``A`` and
    ``P_diag`` may be unbatched ((m, n) / (n,)) when shared across the
    batch — see the module docstring. A shared ``A`` may further be a
    SplitMatrix (df32 big-instance representation)."""
    P_diag: jax.Array   # (S, n) or (n,) shared
    A: jax.Array        # (S, m, n) or (m, n) shared; maybe SplitMatrix
    l: jax.Array        # (S, m)
    u: jax.Array        # (S, m)
    lb: jax.Array       # (S, n)
    ub: jax.Array       # (S, n)


class QPFactors(NamedTuple):
    """Static setup artifacts (scaling + scaled matrices). Shapes follow
    QPData's sharing: batched (S, ...) or shared (no S axis)."""
    sigma: jax.Array       # scalar
    D: jax.Array           # (S, n) | (n,) column equilibration
    E: jax.Array           # (S, m) | (m,) row equilibration (A rows)
    Eb: jax.Array          # (S, n) | (n,) row equilibration (bound rows)
    cost_scale: jax.Array  # (S,) | () objective scaling
    A_s: jax.Array         # (S, m, n) | (m, n) scaled A
    P_s: jax.Array         # (S, n) | (n,) scaled P diagonal
    rho_A: jax.Array       # (S, m) | (m,) relative per-row rho (eq boosted)
    rho_b: jax.Array       # (S, n) | (n,) bound-row rho (fixed cols boosted)


class QPState(NamedTuple):
    """Warm-startable solver state; L and rho persist across solves."""
    x: jax.Array          # (S, n) scaled iterate
    yA: jax.Array         # (S, m) scaled row duals
    yB: jax.Array         # (S, n) scaled bound duals
    zA: jax.Array         # (S, m) scaled row slacks
    zB: jax.Array         # (S, n) scaled bound slacks
    L: jax.Array          # (S,n,n)|(n,n) KKT inverse (f64) / Cholesky (f32)
    rho_scale: jax.Array  # (S,) | () multiplier on the rho patterns
    iters: jax.Array      # scalar total ADMM iterations in last solve
    pri_res: jax.Array    # (S,) unscaled
    dua_res: jax.Array    # (S,) unscaled
    pri_rel: jax.Array    # (S,) pri_res / problem scale (feasibility metric)
    dua_rel: jax.Array    # (S,) dua_res / dual scale (drives host rho adapt)


def _Ax(A, x):
    """A x with A (m,n) shared, (S,m,n) batched, SplitMatrix (df32),
    PackedMatrix, or ScaledView; x (S,n) -> (S,m). The split path runs
    three f32 MXU passes and accumulates in f64 (see SplitMatrix);
    packed representations route through ops/packed.py."""
    if isinstance(A, ScaledView):
        return _Ax(A.A_s, x / A.D) / A.E
    if isinstance(A, PackedMatrix):
        from .packed import pk_Ax
        return pk_Ax(A.pk, x, A.dense.shape[0])
    if isinstance(A, SplitMatrix):
        xh = x.astype(jnp.float32)
        xl = (x - xh.astype(jnp.float64)).astype(jnp.float32)
        if A.pk_hi is not None:
            from .packed import pk_Ax_split
            return pk_Ax_split(A.pk_hi, A.pk_lo, xh, xl, A.hi.shape[0])
        f64 = jnp.float64
        return ((xh @ A.hi.T).astype(f64) + (xh @ A.lo.T).astype(f64)
                + (xl @ A.hi.T).astype(f64))
    if A.ndim == 2:
        return x @ A.T
    return jnp.einsum("smn,sn->sm", A, x)


def _ATy(A, y):
    """Aᵀ y with A (m,n) shared, (S,m,n) batched, SplitMatrix,
    PackedMatrix, or ScaledView; y (S,m) -> (S,n)."""
    if isinstance(A, ScaledView):
        return _ATy(A.A_s, y / A.E) / A.D
    if isinstance(A, PackedMatrix):
        from .packed import pk_ATy
        return pk_ATy(A.pk, y, A.dense.shape[1])
    if isinstance(A, SplitMatrix):
        yh = y.astype(jnp.float32)
        yl = (y - yh.astype(jnp.float64)).astype(jnp.float32)
        if A.pk_hi is not None:
            from .packed import pk_ATy_split
            return pk_ATy_split(A.pk_hi, A.pk_lo, yh, yl, A.hi.shape[1])
        f64 = jnp.float64
        return ((yh @ A.hi).astype(f64) + (yh @ A.lo).astype(f64)
                + (yl @ A.hi).astype(f64))
    if A.ndim == 2:
        return y @ A
    return jnp.einsum("smn,sm->sn", A, y)




def _ruiz_equilibrate(P_diag, A, iters=15):
    """Modified Ruiz equilibration of the KKT matrix [[P, Āᵀ],[Ā, 0]] with
    Ā = [A; I] — the identity (bound-row) block is handled analytically:
    its scaled row j is the single value g_j = Eb_j·D_j. Returns (D, E, Eb)
    with scaled P = D P D (diag), A = E A D, bound rows = diag(Eb·D).
    df32 callers pass the f32 hi part (see _qp_setup_split)."""
    n = A.shape[-1]
    m = A.shape[-2]
    bshape = A.shape[:-2]
    D = jnp.ones(bshape + (n,), A.dtype)
    E = jnp.ones(bshape + (m,), A.dtype)
    Eb = jnp.ones(bshape + (n,), A.dtype)

    def body(_, DEE):
        D, E, Eb = DEE
        As = E[..., :, None] * A * D[..., None, :]
        Ps = D * P_diag * D
        g = Eb * D
        cnorm = jnp.maximum(jnp.maximum(jnp.abs(Ps),
                                        jnp.max(jnp.abs(As), axis=-2)),
                            jnp.abs(g))
        rnorm = jnp.max(jnp.abs(As), axis=-1)
        d = jnp.where(cnorm < 1e-12, 1.0,
                      1.0 / jnp.sqrt(jnp.maximum(cnorm, 1e-12)))
        e = jnp.where(rnorm < 1e-12, 1.0,
                      1.0 / jnp.sqrt(jnp.maximum(rnorm, 1e-12)))
        eb = 1.0 / jnp.sqrt(jnp.maximum(jnp.abs(g), 1e-12))
        return D * d, E * e, Eb * eb

    D, E, Eb = jax.lax.fori_loop(0, iters, body, (D, E, Eb))
    return D, E, Eb


def _factorize(factors: QPFactors, rho_scale):
    """EXPLICIT INVERSE of M = diag(P_s) + sigma I + A_sᵀ diag(ρ_A) A_s
    + diag(g²ρ_b). Shared mode (A_s (m,n), rho_scale scalar) returns one
    (n, n) inverse.

    Why an inverse and not the Cholesky factor (f64): the ADMM x-update
    runs thousands of times per solve, and a TPU triangular solve is a
    SEQUENTIAL back-substitution — milliseconds of latency at small
    batch — while applying a precomputed inverse is one MXU matmul
    (microseconds). The inverse is computed ONCE per (re)factorization
    via two n-RHS triangular solves (themselves MXU-blocked), and in f64
    the equilibrated, sigma-regularized M keeps the inverse-apply error
    far below the ADMM's own tolerance. In F32 the inverse's κ(M)·eps
    error (~1e-1 on UC-class conditioning) destabilizes the iteration —
    measured NaN blowups at S=256 — so the f32 path keeps the Cholesky
    factor and pays the triangular solves. _chol_solve dispatches on the
    stored matrix's dtype. The ill-conditioned penalty systems in the
    POLISH always use honest Cholesky solves."""
    A_s, P_s = factors.A_s, factors.P_s
    g = factors.Eb * factors.D
    n = A_s.shape[-1]
    if isinstance(A_s, SplitMatrix):
        return _factorize_split(factors, rho_scale)
    if isinstance(A_s, PackedMatrix):
        # in-loop rho refactorization during the f32 bulk phase: the
        # packed form serves matvecs only — the (n, n) product wants
        # the one dense MXU pass
        A_s = A_s.dense
    invert = A_s.dtype == jnp.float64
    if A_s.ndim == 2:
        rA = factors.rho_A * rho_scale
        rB = factors.rho_b * rho_scale
        M = A_s.T @ (rA[:, None] * A_s)
        M = M + jnp.diag(P_s + factors.sigma + g * g * rB)
        L = jnp.linalg.cholesky(M)
        if not invert:
            return L
        eye = jnp.eye(n, dtype=A_s.dtype)
        w = jax.lax.linalg.triangular_solve(L, eye, left_side=True,
                                            lower=True)
        return jax.lax.linalg.triangular_solve(L, w, left_side=True,
                                               lower=True, transpose_a=True)
    rA = factors.rho_A * rho_scale[:, None]
    rB = factors.rho_b * rho_scale[:, None]
    M = (A_s * rA[:, :, None]).swapaxes(1, 2) @ A_s
    M = M + jnp.eye(n, dtype=A_s.dtype) * factors.sigma
    M = M + jax.vmap(jnp.diag)(P_s + g * g * rB)
    L = jnp.linalg.cholesky(M)
    if not invert:
        return L
    eye = jnp.broadcast_to(jnp.eye(n, dtype=A_s.dtype), M.shape)
    w = jax.lax.linalg.triangular_solve(L, eye, left_side=True, lower=True)
    return jax.lax.linalg.triangular_solve(L, w, left_side=True,
                                           lower=True, transpose_a=True)


def _factorize_split(factors: QPFactors, rho_scale):
    """df32 factorization: a plain f32 Cholesky factor of M, built from
    ONE f32 MXU pass — no f64 matmul (which would OOM at big-instance
    scale, see SplitMatrix), no host roundtrip, fully traceable (so
    in-jit rho refactorization stays available).

    The factor is a PRECONDITIONER-quality object, not the solver: the
    df32 x-update (see _m_solve_ir in _solve_impl) wraps each
    triangular solve in mixed-precision iterative refinement whose
    residuals come from split-f32 matvecs with f64 accumulation. The
    f32 quantization of M and the κ(M)·eps32 solve error are both
    corrected by the refinement — the classic IR contraction argument
    (error × κ·eps32 per sweep) that Newton–Schulz on an explicit
    inverse does NOT enjoy here (measured: split-product cancellation
    noise ~κ·1e-7 makes Newton DEGRADE a 2e-5 seed to 7e-3)."""
    A_s, P_s = factors.A_s, factors.P_s
    f32 = jnp.float32
    g32 = (factors.Eb * factors.D).astype(f32)
    rA32 = (factors.rho_A * rho_scale).astype(f32)
    rB32 = (factors.rho_b * rho_scale).astype(f32)
    M32 = A_s.hi.T @ (rA32[:, None] * A_s.hi)
    M32 = M32 + jnp.diag(P_s.astype(f32) + jnp.asarray(factors.sigma, f32)
                         + g32 * g32 * rB32)
    return jnp.linalg.cholesky(M32)


def _device_f64_linalg_trusted():
    """TPU-family backends execute "f64" cholesky/triangular_solve at
    ~f32 INTERNAL precision (measured on v5e via axon: batched explicit
    inverse of the eq-boosted UC KKT comes back with |M@inv - I|max =
    0.9 at cond 6e3, vs 2e-13 for the same matrix in numpy; even benign
    random SPD matrices show 1e-6-level f64 residuals). An inverse that
    wrong turns the ADMM x-update into an expanding map — iterates
    reach 1e33 within 100 iterations, then NaN (the scenario-hospital's
    rescue-to-NaN failure mode). CPU/GPU have native f64 linalg."""
    return jax.default_backend() in ("cpu", "gpu", "cuda", "rocm")


def _needs_host_factor(factors) -> bool:
    """Non-shared f64 factors on an untrusted-f64-linalg backend must be
    inverted on the HOST (and in-jit rho refactorization disabled — the
    axon runtime supports no host callbacks). The SHARED f64 branch
    keeps the device path: its single unbatched factor measures
    accurate enough in practice (1024-scenario chunked runs converge to
    ~1e-5) and sits on the hub's hot path."""
    return factors.A_s.ndim == 3 and factors.A_s.dtype == jnp.float64 \
        and not _device_f64_linalg_trusted()


def _factorize_host(factors: QPFactors, rho_scale, rows=None):
    """numpy twin of _factorize's non-shared f64 explicit-inverse branch
    (see _device_f64_linalg_trusted for why it exists). Eager-only.
    ``rows``: optional index array — invert only those scenarios' KKTs
    and return a (len(rows), n, n) block for the caller to scatter."""
    sel = (lambda a: a if rows is None else a[rows])
    A_s = sel(np.asarray(factors.A_s))
    P_s = sel(np.asarray(factors.P_s))
    g = sel(np.asarray(factors.Eb * factors.D))
    rho_scale = sel(np.asarray(rho_scale))
    rA = sel(np.asarray(factors.rho_A)) * rho_scale[:, None]
    rB = sel(np.asarray(factors.rho_b)) * rho_scale[:, None]
    M = np.einsum("smi,sm,smj->sij", A_s, rA, A_s)
    M += np.eye(A_s.shape[-1]) * float(factors.sigma)
    diag = P_s + g * g * rB
    idx = np.arange(A_s.shape[-1])
    M[:, idx, idx] += diag
    return jnp.asarray(np.linalg.inv(M))


_factorize_jit = jax.jit(_factorize)


def factorize_dispatch(factors: QPFactors, rho_scale):
    """The ONE eager factorization entry: host-exact inverse on
    untrusted-f64 backends, device path otherwise. Every eager
    (re)factorization site must come through here — a site calling
    _factorize directly silently reintroduces the garbage device
    inverse (see _device_f64_linalg_trusted)."""
    if _needs_host_factor(factors):
        return _factorize_host(factors, rho_scale)
    return _factorize_jit(factors, rho_scale)


def _tri_solve(L, b):
    """Solve M x = b given a true Cholesky factor L; b (S, n). Used by the
    POLISH only (its rho_big penalty systems are too ill-conditioned for
    an explicit inverse); the main loop applies _chol_solve's inverse."""
    y = jax.lax.linalg.triangular_solve(L, b[..., None], left_side=True,
                                        lower=True, transpose_a=False)
    x = jax.lax.linalg.triangular_solve(L, y, left_side=True,
                                        lower=True, transpose_a=True)
    return x[..., 0]


class LInv(NamedTuple):
    """EXPLICIT inverse of a (shared, 2-D) Cholesky factor, carried in
    QPState.L alongside the factor itself: the x-update's M⁻¹ apply
    becomes TWO MXU MATMULS of exactly the factor's bytes
    (x = L⁻ᵀ(L⁻¹b) — roofline headroom item 1, doc/roofline.md §5)
    instead of two sequential back-substitutions, which on TPU are
    latency-bound at chunk batch sizes.

    Distinct from _factorize's f64 explicit M⁻¹: inverting M composes
    κ(M)·eps error (measured NaN blowups in f32 — see _factorize), but
    each triangular factor only carries κ(L)=sqrt(κ(M)) — and the df32
    x-update wraps every solve in iterative refinement whose residuals
    come from split matvecs, so the remaining ~sqrt(κ)·eps32 forward
    error is contracted exactly like the triangular solve's own (see
    _m_solve_ir). That contraction argument is the trade's WHOLE
    license, which is why ``tri`` (the raw factor) rides along: solves
    with NO refinement around them — the fused driver's f32 bulk phase
    — keep the componentwise-stable back-substitution (measured: an
    un-refined L⁻¹ bulk shifts the degenerate-UC plateau objective by
    ~0.5%, outside the packed path's calibrated band). Residency is
    two f32 (n, n) buffers — the same bytes as the one f64 factor the
    non-split path carries; per-iteration HBM traffic is unchanged
    (the trade converts solve latency, not bytes). Built by the
    ops/kernels layer behind a profitability check (the n-RHS inverse
    build must amortize over the iteration budget); every _chol_solve
    consumer dispatches on the container, so a state carrying L or
    L⁻¹ flows through the same solver code."""
    inv: jax.Array          # (n, n) = L⁻¹ (NOT M⁻¹), factor dtype
    tri: jax.Array          # (n, n) = L itself (non-IR consumers)

    @property
    def dtype(self):
        return self.inv.dtype

    @property
    def ndim(self):
        return self.inv.ndim

    @property
    def shape(self):
        return self.inv.shape


def _make_l_inv(L) -> LInv:
    """Traceable L -> (L⁻¹, L) (one n-RHS triangular solve,
    MXU-blocked)."""
    eye = jnp.eye(L.shape[-1], dtype=L.dtype)
    return LInv(jax.lax.linalg.triangular_solve(
        L, eye, left_side=True, lower=True), L)


make_l_inv = jax.jit(_make_l_inv)


def _refactor_like(factors, rho_scale, like):
    """In-loop refactorization that preserves the CONTAINER of the
    carried factor: a state running the L⁻¹-matmul x-update must get a
    fresh L⁻¹ when rho adaptation refactorizes mid-solve, or the
    while_loop carry would change pytree structure. The isinstance test
    is trace-time (pytree structure is static)."""
    L_new = _factorize(factors, rho_scale)
    if isinstance(like, LInv):
        return _make_l_inv(L_new)
    return L_new


def _chol_solve(F, b):
    """Solve M x = b given _factorize's output F: an explicit inverse in
    f64 (one MXU matmul — M⁻¹ is symmetric) or a Cholesky factor in f32
    (triangular solves; see _factorize's docstring for why), or an LInv
    (explicit L⁻¹: two MXU matmuls of the same bytes as the triangular
    solves — the ops/kernels roofline trade). An f64 b against an f32
    factor (the df32 x-update seed) solves in f32 and returns f64 — the
    refinement sweeps in _m_solve_ir own the accuracy."""
    if isinstance(F, LInv):
        out_dt = b.dtype
        u = b.astype(F.inv.dtype) @ F.inv.T     # u = L⁻¹ b (rows)
        return (u @ F.inv).astype(out_dt)       # x = L⁻ᵀ u
    if F.dtype == jnp.float64:
        if F.ndim == 2:
            return b @ F
        return jnp.einsum("sij,sj->si", F, b)
    out_dt = b.dtype
    b = b.astype(F.dtype)
    if F.ndim == 2:
        y = jax.lax.linalg.triangular_solve(F, b.T, left_side=True,
                                            lower=True, transpose_a=False)
        x = jax.lax.linalg.triangular_solve(F, y, left_side=True,
                                            lower=True, transpose_a=True)
        return x.T.astype(out_dt)
    return _tri_solve(F, b).astype(out_dt)


@partial(jax.jit, static_argnames=("eq_boost", "shared"))
def _setup_vectors(P_diag, l, u, lb, ub, D, q_ref, rho_base, eq_boost,
                   shared):
    """Everything in qp_setup AFTER the scaled matrix exists: cost
    normalization + equality-boost rho patterns (vector math only).
    Deliberately takes/returns NO matrix: a jit that passes a matrix
    through to its output makes XLA COPY it per call — measured
    +2.7 GB per invocation at reference-UC scale. Returns
    (P_s, cost_scale, rho_A, rho_b); callers attach the matrix
    eagerly."""
    dt = D.dtype
    P_s = D * P_diag * D
    # cost normalization (OSQP sec 5.1): scale so the objective gradient is O(1)
    if q_ref is None:
        q_ref = jnp.zeros(lb.shape, dt)
    qs = D * q_ref
    gn_P = jnp.max(jnp.abs(P_s), axis=-1)
    gn_q = jnp.max(jnp.abs(qs), axis=-1)
    if shared:
        gnorm = jnp.maximum(gn_P, jnp.max(gn_q))          # scalar
        cost_scale = 1.0 / jnp.maximum(gnorm, 1.0)
        P_s = P_s * cost_scale
    else:
        gnorm = jnp.maximum(gn_P, gn_q)                   # (S,)
        cost_scale = 1.0 / jnp.maximum(gnorm, 1.0)
        P_s = P_s * cost_scale[:, None]

    def _is_eq(lo, hi):
        d_ = hi - lo
        return jnp.isfinite(d_) & (jnp.abs(d_)
                                   <= 1e-9 * (1.0 + jnp.abs(hi)))

    is_eq = _is_eq(l, u)      # (S, m)
    is_eq_b = _is_eq(lb, ub)  # (S, n)
    if shared:
        # a row must be an equality in EVERY scenario to earn the shared
        # boost (rho is only a stepsize, so the conservative AND is safe)
        is_eq = jnp.all(is_eq, axis=0)
        is_eq_b = jnp.all(is_eq_b, axis=0)
    rho_A = jnp.where(is_eq, rho_base * eq_boost, rho_base).astype(dt)
    rho_b = jnp.where(is_eq_b, rho_base * eq_boost, rho_base).astype(dt)
    return P_s, cost_scale, rho_A, rho_b


@partial(jax.jit, static_argnames=("eq_boost",))
def _qp_setup_dense(data: QPData, q_ref, rho_base, sigma, eq_boost):
    # one jit: A_s is CREATED inside, so returning it costs nothing
    # extra (unlike pass-through returns — see _setup_vectors)
    P_diag, A, l, u, lb, ub = data
    D, E, Eb = _ruiz_equilibrate(P_diag, A)
    A_s = E[..., :, None] * A * D[..., None, :]
    dt = A.dtype
    P_s, cost_scale, rho_A, rho_b = _setup_vectors(
        P_diag, l, u, lb, ub, D, q_ref, rho_base, eq_boost, A.ndim == 2)
    return QPFactors(sigma=jnp.asarray(sigma, dt), D=D, E=E, Eb=Eb,
                     cost_scale=cost_scale, A_s=A_s, P_s=P_s,
                     rho_A=rho_A, rho_b=rho_b)


@partial(jax.jit, static_argnames=("nblocks",))
def _scale_split_blocks(A: SplitMatrix, D, E, nblocks=8) -> SplitMatrix:
    """A_s = split(E·A·D) computed in ROW BLOCKS so the f64 value of
    the scaled matrix only ever exists one block at a time — the
    full-matrix form materializes several (m, n) f64 transients and
    OOMs a 16 G chip at reference-UC scale (measured)."""
    m = A.hi.shape[0]
    his, los = [], []
    bounds = [(m * i) // nblocks for i in range(nblocks + 1)]
    for i in range(nblocks):
        sl = slice(bounds[i], bounds[i + 1])
        blk = (A.hi[sl].astype(jnp.float64)
               + A.lo[sl].astype(jnp.float64)) \
            * E[sl, None] * D[None, :]
        hi = blk.astype(jnp.float32)
        los.append((blk - hi.astype(jnp.float64)).astype(jnp.float32))
        his.append(hi)
    return SplitMatrix(jnp.concatenate(his), jnp.concatenate(los))


def _qp_setup_split(data: QPData, q_ref, rho_base, sigma, eq_boost):
    """df32 setup: Ruiz on the f32 hi part (D/E/Eb are heuristic
    scalings — a 1e-7-relative view of |A| changes nothing), scaled
    split built blockwise, vector tail shared with the dense path. The
    QPFactors tuple is assembled EAGERLY so A_s never passes through a
    jit boundary (see _setup_vectors)."""
    A = data.A
    f64 = jnp.float64
    D32, E32, Eb32 = _ruiz_equilibrate(data.P_diag.astype(jnp.float32),
                                       A.hi)
    D, E, Eb = D32.astype(f64), E32.astype(f64), Eb32.astype(f64)
    A_s = _scale_split_blocks(A, D, E)
    if A.struct is not None:
        # gather the SCALED hi/lo into the packed matvec form (same
        # index skeleton for both — scaling preserves structure); from
        # here every hot-loop A-pass is packed (see ops/packed.py)
        from .packed import pack
        A_s = A_s._replace(struct=A.struct,
                           pk_hi=pack(A.struct, A_s.hi),
                           pk_lo=pack(A.struct, A_s.lo))
    P_s, cost_scale, rho_A, rho_b = _setup_vectors(
        data.P_diag, data.l, data.u, data.lb, data.ub, D, q_ref,
        rho_base, eq_boost, True)
    return QPFactors(sigma=jnp.asarray(sigma, f64), D=D, E=E, Eb=Eb,
                     cost_scale=cost_scale, A_s=A_s, P_s=P_s,
                     rho_A=rho_A, rho_b=rho_b)


def qp_setup(data: QPData, q_ref=None, rho_base=0.1, sigma=1e-6,
             eq_boost=1e3):
    """Equilibrate and scale. Cheap relative to the solve; re-solves with a
    new q reuse everything. The equality-row rho boost pattern depends only
    on which rows/columns are pinned (l==u / lb==ub), so one setup serves
    every PH iteration of a mode."""
    if isinstance(data.A, SplitMatrix):
        return _qp_setup_split(data, q_ref, rho_base, sigma, eq_boost)
    return _qp_setup_dense(data, q_ref, rho_base, sigma, eq_boost)


@partial(jax.jit, static_argnames=("eq_boost", "shared"))
def _setup_like_vectors(P_diag, l, u, lb, ub, D, cost_scale, rho_base,
                        eq_boost, shared):
    csx = cost_scale if shared else cost_scale[:, None]
    P_s = D * P_diag * D * csx

    def _is_eq(lo, hi):
        d_ = hi - lo
        return jnp.isfinite(d_) & (jnp.abs(d_)
                                   <= 1e-9 * (1.0 + jnp.abs(hi)))

    is_eq = _is_eq(l, u)
    is_eq_b = _is_eq(lb, ub)
    if shared:
        is_eq = jnp.all(is_eq, axis=0)
        is_eq_b = jnp.all(is_eq_b, axis=0)
    dt = D.dtype
    rho_A = jnp.where(is_eq, rho_base * eq_boost, rho_base).astype(dt)
    rho_b = jnp.where(is_eq_b, rho_base * eq_boost, rho_base).astype(dt)
    return P_s, rho_A, rho_b


def qp_setup_like(base: QPFactors, data: QPData, rho_base=0.1,
                  eq_boost=1e3):
    """Factors for a RELATED mode (prox on/off, pinned boxes) REUSING
    ``base``'s equilibration and scaled matrix: only the scaled
    quadratic diagonal and the rho boost patterns are recomputed
    (vector math, jitted). The _replace happens EAGERLY — running it
    inside a jit would pass the multi-GB A_s through the jit boundary,
    which XLA copies per call (measured +2.7 GB per mode at
    reference-UC scale, the exact duplication this function exists to
    avoid). The Ruiz scalings are heuristic — a mode whose P differs
    on a diagonal block is equally well served by the base mode's
    D/E."""
    shared = base.A_s.ndim == 2
    P_s, rho_A, rho_b = _setup_like_vectors(
        data.P_diag, data.l, data.u, data.lb, data.ub, base.D,
        base.cost_scale, rho_base, eq_boost, shared)
    return base._replace(P_s=P_s, rho_A=rho_A, rho_b=rho_b)


def qp_reset_rho(factors: QPFactors, state: QPState) -> QPState:
    """Reset the adaptive-rho trajectory: rho_scale back to 1 with the
    matching refactorization — the recovery move for a warm-started
    state whose adaptation went pathological (the same pattern
    qp_cold_state and the mixed escalation's phase handoffs use).
    Iterates are kept; only the stepsize/factor reset."""
    ones = jnp.ones_like(state.rho_scale)
    return state._replace(rho_scale=ones, L=factorize_dispatch(factors, ones))


def _zero_state(factors: QPFactors, data: QPData, L) -> QPState:
    """The ONE cold-state literal (zeros + inf residuals + the given
    factor) — every QPState field addition must land here exactly once."""
    S, m = data.l.shape
    n = data.lb.shape[-1]
    dt = factors.A_s.dtype
    shared = factors.A_s.ndim == 2
    rho_scale = jnp.ones((), dt) if shared else jnp.ones((S,), dt)
    return QPState(x=jnp.zeros((S, n), dt), yA=jnp.zeros((S, m), dt),
                   yB=jnp.zeros((S, n), dt), zA=jnp.zeros((S, m), dt),
                   zB=jnp.zeros((S, n), dt), L=L, rho_scale=rho_scale,
                   iters=jnp.zeros((), jnp.int32),
                   pri_res=jnp.full((S,), jnp.inf, dt),
                   dua_res=jnp.full((S,), jnp.inf, dt),
                   pri_rel=jnp.full((S,), jnp.inf, dt),
                   dua_rel=jnp.full((S,), jnp.inf, dt))


@jax.jit
def _cold_state_jit(factors: QPFactors, data: QPData) -> QPState:
    S = data.l.shape[0]
    dt = factors.A_s.dtype
    shared = factors.A_s.ndim == 2
    rho_scale = jnp.ones((), dt) if shared else jnp.ones((S,), dt)
    return _zero_state(factors, data, _factorize(factors, rho_scale))


def qp_cold_state(factors: QPFactors, data: QPData) -> QPState:
    if _needs_host_factor(factors):
        # host-exact inverse (see _device_f64_linalg_trusted) — not
        # worth a device program that would compute (and discard) the
        # garbage batched inverse
        S = data.l.shape[0]
        rho_scale = jnp.ones((S,), factors.A_s.dtype)
        return _zero_state(factors, data,
                           factorize_dispatch(factors, rho_scale))
    return _cold_state_jit(factors, data)


def _scaled_problem(factors: QPFactors, data: QPData, q):
    """The scaled problem vectors one solve iterates in:
    (g, l_s, u_s, lb_s, ub_s, csx, q_s). Shared by _solve_impl and the
    ops/kernels pallas driver — the two MUST scale identically, or the
    kernel-backend parity tests would be comparing different problems
    (a second copy of these six lines would silently drift)."""
    _, D, E, Eb, cs, A_s, _, _, _ = factors
    shared = A_s.ndim == 2
    g = Eb * D
    l_s, u_s = E * data.l, E * data.u
    lb_s, ub_s = Eb * data.lb, Eb * data.ub
    csx = cs if shared else cs[:, None]
    q_s = csx * D * q
    return g, l_s, u_s, lb_s, ub_s, csx, q_s


def _solve_impl(factors: QPFactors, data: QPData, q, state: QPState,
                max_iter=4000, check_every=25, eps_abs=1e-6, eps_rel=1e-6,
                alpha=1.6, adaptive_rho=True, polish=True, polish_iters=12,
                polish_chunk=0, eps_abs_dua=None, eps_rel_dua=None,
                stall_rel=0.0, ir_sweeps=1):
    """Traceable body of qp_solve (shared by the jitted single-precision
    entry and the mixed-precision escalation driver below).

    ``eps_*_dua`` (default: same as the primal pair) let a caller loosen
    the DUAL termination test independently: on degenerate LPs the ADMM
    dual residual plateaus (y drifts along redundant-row null spaces)
    orders of magnitude above the primal one, and a consumer that only
    needs primal iterates (the PH hot loop — bounds come from separate
    prox-off solves) would otherwise burn its whole iteration budget
    waiting on a test that cannot pass. The polish still runs and still
    recovers the best certified duals it can.

    STALL EXIT: degenerate LPs also plateau the PRIMAL residual above any
    tight tolerance (first-order methods converge slowly along degenerate
    faces). A scenario counts as finished when its residuals improved
    less than 5% since the previous check AND its primal residual is
    below the coarse ``stall_rel`` gate (relative) — at that point
    further iterations tread water and the active-set polish is the
    productive step. Checks immediately after a rho refactorize are
    exempt (the residual jump would false-trigger). OFF by default
    (stall_rel=0): exact consumers (tests, small well-conditioned
    models) keep the strict contract; plateau-prone model configs (UC)
    opt in via engine options.
    POLISH: detect the active set from the final slacks, factor the
    penalty KKT matrix restricted to active rows, and run a few
    augmented-Lagrangian refinement steps. First-order ADMM stalls on the
    dual residual for degenerate LPs (y drifts along redundant-constraint
    null spaces); polishing recovers near-exact primal/dual pairs — which
    every certified bound in the framework (Ebound, Lagrangian spokes,
    Benders cuts) consumes — at the cost of a few extra batched Choleskys.
    Polished results are accepted PER SCENARIO only where they improve
    max(pri, dua), and the returned duals are the per-scenario argmax of
    the certified dual objective over all candidates (any dual vector
    yields a valid bound, so the argmax is valid), so a wrong active-set
    guess can never degrade a solve.

    The polish factors are per-scenario (S, n, n) even in shared-structure
    mode (active sets differ per scenario). For large S set
    ``polish_chunk`` (must divide S) to bound that transient: the polish
    tail is lax.map'ed over S/polish_chunk chunks.

    Returns (state, x (S,n), yA (S,m), yB (S,n)) — all UNscaled; yA are the
    constraint-row duals, yB the variable-bound duals. `q` is the unscaled
    linear cost. Warm start by passing the previous state (its adapted rho
    and factor carry over); cold start with `qp_cold_state(factors, data)`.
    """
    sigma, D, E, Eb, cs, A_s, P_s, rho_A, rho_b = factors
    shared = A_s.ndim == 2
    if isinstance(A_s, SplitMatrix):
        # the polish broadcasts A_s per scenario ((S, n, n) penalty
        # factors) — structurally impossible at the scale the df32
        # representation exists for; duals come from the ADMM iterates
        # (still a VALID bound via qp_dual_objective) and exact
        # tightening, when needed, from the host oracle
        polish = False
    g, l_s, u_s, lb_s, ub_s, csx, q_s = _scaled_problem(factors, data, q)
    dt = A_s.dtype
    eps_abs = jnp.asarray(eps_abs, dt)
    eps_rel = jnp.asarray(eps_rel, dt)
    eps_abs_dua = eps_abs if eps_abs_dua is None else jnp.asarray(eps_abs_dua, dt)
    eps_rel_dua = eps_rel if eps_rel_dua is None else jnp.asarray(eps_rel_dua, dt)

    def rho_of(rho_scale):
        rs = rho_scale if shared else rho_scale[:, None]
        return rho_A * rs, rho_b * rs

    def _m_solve_ir(L, rhs, rA, rB):
        """df32 x-update: f32 triangular solves + ``ir_sweeps`` sweeps
        of mixed-precision iterative refinement. The residual
        r = rhs − Mx is computed through the SPLIT matvecs (f64
        accumulation of f32 MXU passes), so each sweep contracts the
        error by ~κ(M)·eps32 — the standard IR argument — landing well
        below the ADMM tolerance without a single f64 matmul. M is
        applied in factored form (P, σ, A_sᵀρA_s, bound rows); no
        (n, n) product is ever stored.

        ONE sweep is the default (r5): the f32 seed's relative error is
        ~κ(M)·eps32 ≈ 4e-4 on the equilibrated UC KKT (κ ≈ 6e3), so one
        sweep lands at ~(κ·eps32)² ≈ 2e-7 — two decades below the
        tightest tolerance any caller runs at df32 scale (1e-5) and
        below the split representation's own ~1e-7 accumulation floor.
        The second sweep bought nothing measurable while costing an
        extra m_apply + solve (~1/3 of the tail iteration's HBM
        traffic). ``subproblem_ir_sweeps`` raises it back."""
        def m_apply(v):
            return P_s * v + sigma * v + _ATy(A_s, rA * _Ax(A_s, v)) \
                + g * g * rB * v

        x = _chol_solve(L, rhs)
        for _ in range(ir_sweeps):
            x = x + _chol_solve(L, rhs - m_apply(x))
        return x

    def admm_chunk(x, yA, yB, zA, zB, L, rA, rB):
        split_mode = isinstance(A_s, SplitMatrix)

        def one(carry, _):
            x, yA, yB, zA, zB = carry
            rhs = sigma * x - q_s + _ATy(A_s, rA * zA - yA) \
                + g * (rB * zB - yB)
            # un-refined solves must NOT use an explicit L⁻¹ (see LInv:
            # the inverse is licensed only under IR contraction) — an
            # LInv carry hands its raw factor to this branch
            x_t = _m_solve_ir(L, rhs, rA, rB) if split_mode \
                else _chol_solve(L.tri if isinstance(L, LInv) else L,
                                 rhs)
            x_new = alpha * x_t + (1 - alpha) * x
            zA_t = _Ax(A_s, x_t)
            zA_mix = alpha * zA_t + (1 - alpha) * zA
            zA_new = jnp.clip(zA_mix + yA / rA, l_s, u_s)
            yA_new = yA + rA * (zA_mix - zA_new)
            zB_t = g * x_t
            zB_mix = alpha * zB_t + (1 - alpha) * zB
            zB_new = jnp.clip(zB_mix + yB / rB, lb_s, ub_s)
            yB_new = yB + rB * (zB_mix - zB_new)
            return (x_new, yA_new, yB_new, zA_new, zB_new), None

        (x, yA, yB, zA, zB), _ = jax.lax.scan(one, (x, yA, yB, zA, zB), None,
                                              length=check_every)
        return x, yA, yB, zA, zB

    def residuals(x, yA, yB, zA, zB):
        return _unscaled_residuals(A_s, P_s, g, D, E, Eb, csx, q_s,
                                   x, yA, yB, zA, zB)

    def cond(carry):
        it, done = carry[7], carry[8]
        return jnp.logical_and(it < max_iter, jnp.logical_not(done))

    def body(carry):
        (x, yA, yB, zA, zB, L, rho_scale, it, _, best_pri, best_dua,
         stall_ct) = carry
        rA, rB = rho_of(rho_scale)
        x, yA, yB, zA, zB = admm_chunk(x, yA, yB, zA, zB, L, rA, rB)
        pri, dua, pri_sc, dua_sc = residuals(x, yA, yB, zA, zB)
        conv_ok = jnp.logical_and(
            pri <= eps_abs + eps_rel * pri_sc,
            dua <= eps_abs_dua + eps_rel_dua * dua_sc)
        # stall exit (window-based, oscillation-robust): a scenario whose
        # BEST residual pair hasn't improved 5% in 4 consecutive checks
        # while its primal passes the coarse gate is plateaued — the
        # productive next step is the polish, not more iterations
        if stall_rel:
            improved = (pri <= 0.95 * best_pri) | (dua <= 0.95 * best_dua)
            best_pri = jnp.minimum(best_pri, pri)
            best_dua = jnp.minimum(best_dua, dua)
        rho_changed = jnp.zeros_like(conv_ok)   # per-scenario where possible
        if adaptive_rho:
            # OSQP-style infrequent adaptation: every 4th residual check;
            # adopt only when the ideal rho moved by > 5x. In shared mode
            # the scale is a single scalar (geometric mean of the
            # per-scenario ideals) so the factor stays shared.
            adapt_now = ((it // check_every) % 4) == 3
            not_conv = jnp.logical_not(jnp.all(conv_ok))
            ratio_s = jnp.sqrt((pri / pri_sc)
                               / jnp.maximum(dua / dua_sc, 1e-30))
            if shared:
                ratio = jnp.exp(jnp.mean(jnp.log(
                    jnp.clip(ratio_s, 1e-6, 1e6))))
                new_scale = jnp.clip(rho_scale * ratio, 1e-6, 1e6)
                change = jnp.maximum(new_scale / rho_scale,
                                     rho_scale / new_scale)
                upd = (change > 5.0) & adapt_now & not_conv
                rho_scale = jnp.where(upd, new_scale, rho_scale)
                need = upd
                # one shared scalar: a refactorize resets every
                # scenario's stall window (their stepsize DID change)
                rho_changed = jnp.broadcast_to(need, conv_ok.shape)
            else:
                new_scale = jnp.clip(rho_scale * ratio_s, 1e-6, 1e6)
                change = jnp.maximum(new_scale / rho_scale,
                                     rho_scale / new_scale)
                mask = (change > 5.0) & adapt_now & not_conv
                rho_scale = jnp.where(mask, new_scale, rho_scale)
                need = jnp.any(mask)
                # per-scenario rho: only the scenarios whose rho moved
                # restart their stall window — an unrelated scenario's
                # refactorize must not postpone another's plateau exit
                # (ADVICE r2)
                rho_changed = mask
            L = jax.lax.cond(need,
                             lambda: _refactor_like(factors, rho_scale, L),
                             lambda: L)
        if stall_rel:
            # a rho refactorize resets the window (the residual jump is
            # expected, not a plateau)
            stall_ct = jnp.where(improved | rho_changed, 0, stall_ct + 1)
            stalled = (stall_ct >= 4) & (pri <= stall_rel * pri_sc)
        else:
            stalled = jnp.zeros_like(conv_ok)
        done = jnp.all(conv_ok | stalled)
        return (x, yA, yB, zA, zB, L, rho_scale, it + check_every, done,
                best_pri, best_dua, stall_ct)

    S_ = data.l.shape[0]
    inf0 = jnp.full((S_,), jnp.inf, dt)
    ct0 = jnp.zeros((S_,), jnp.int32)
    x, yA, yB, zA, zB, L, rho_scale, it, _, _, _, _ = jax.lax.while_loop(
        cond, body,
        (state.x, state.yA, state.yB, state.zA, state.zB, state.L,
         state.rho_scale, jnp.zeros((), jnp.int32), jnp.array(False),
         inf0, inf0, ct0))

    pri, dua, pri_sc, dua_sc = residuals(x, yA, yB, zA, zB)
    # the ADMM iterates are what the NEXT solve warm-starts from (the
    # polished point sits exactly on the active set — a bad center when the
    # next q moves it)
    new_state = QPState(x=x, yA=yA, yB=yB, zA=zA, zB=zB, L=L,
                        rho_scale=rho_scale, iters=it,
                        pri_res=pri, dua_res=dua, pri_rel=pri / pri_sc,
                        dua_rel=dua / dua_sc)

    if not polish:
        return new_state, D * x, (E / csx) * yA, (Eb / csx) * yB

    # ---- polish tail (chunkable over the scenario axis) ----
    per = dict(x=x, yA=yA, yB=yB, zA=zA, zB=zB, q_s=q_s,
               l_s=l_s, u_s=u_s, lb_s=lb_s, ub_s=ub_s,
               l=data.l, u=data.u, lb=data.lb, ub=data.ub, q=q,
               pri=pri, dua=dua, pri_sc=pri_sc, dua_sc=dua_sc)
    if not shared:
        per.update(A_s=A_s, P_s=P_s, D=D, E=E, Eb=Eb, cs=cs,
                   Pd=data.P_diag, A_raw=data.A)

    def tail(ps):
        A_l = ps.get("A_s", A_s)
        P_l = ps.get("P_s", P_s)
        D_l = ps.get("D", D)
        E_l = ps.get("E", E)
        Eb_l = ps.get("Eb", Eb)
        cs_l = ps.get("cs", cs)
        csx_l = cs_l if shared else cs_l[:, None]
        g_l = Eb_l * D_l
        # the dual-objective evaluation needs the UNSCALED problem data
        d_l = QPData(ps.get("Pd", data.P_diag), ps.get("A_raw", data.A),
                     ps["l"], ps["u"], ps["lb"], ps["ub"])
        out = _polish_select(
            A_l, P_l, g_l, D_l, E_l, Eb_l, cs_l, csx_l, sigma, d_l,
            ps["q"], ps["q_s"], ps["l_s"], ps["u_s"], ps["lb_s"], ps["ub_s"],
            ps["x"], ps["yA"], ps["yB"], ps["zA"], ps["zB"],
            ps["pri"], ps["dua"], ps["pri_sc"], ps["dua_sc"],
            polish_iters, shared, eps_abs, eps_rel)
        return out

    S = data.l.shape[0]
    if polish_chunk and 0 < polish_chunk < S:
        # pad to a chunk multiple with copies of scenario 0 so a
        # non-dividing chunk size still bounds the (chunk, n, n) transient
        # instead of silently falling back to the full-batch polish
        rem = (-S) % polish_chunk
        Sp = S + rem
        if rem:
            per = jax.tree.map(
                lambda a: jnp.concatenate(
                    [a, jnp.broadcast_to(a[:1], (rem,) + a.shape[1:])]), per)
        nc = Sp // polish_chunk
        resh = lambda a: a.reshape((nc, polish_chunk) + a.shape[1:])
        unresh = lambda a: a.reshape((Sp,) + a.shape[2:])[:S]
        out = jax.lax.map(tail, jax.tree.map(resh, per))
        x_un, yA_un, yB_un, pri, dua, pri_sc = jax.tree.map(unresh, out)
    else:
        x_un, yA_un, yB_un, pri, dua, pri_sc = tail(per)

    # dua_rel keeps the pre-polish dual scale (the polish tail returns
    # no dua_sc); the rel metrics' consumer is the host rho adaptation,
    # which runs between LOOP segments, before any polish
    new_state = new_state._replace(pri_res=pri, dua_res=dua,
                                   pri_rel=pri / pri_sc)
    return new_state, x_un, yA_un, yB_un


_SOLVE_STATICS = ("max_iter", "check_every", "adaptive_rho", "polish",
                  "polish_iters", "polish_chunk", "stall_rel", "ir_sweeps")


@partial(jax.jit, static_argnames=_SOLVE_STATICS)
def _qp_solve_jit(factors: QPFactors, data: QPData, q, state: QPState,
                  max_iter=4000, check_every=25, eps_abs=1e-6, eps_rel=1e-6,
                  alpha=1.6, adaptive_rho=True, polish=True, polish_iters=12,
                  polish_chunk=0, eps_abs_dua=None, eps_rel_dua=None,
                  stall_rel=0.0, ir_sweeps=1):
    """Jitted single-precision solve — see _solve_impl for the algorithm."""
    return _solve_impl(factors, data, q, state, max_iter, check_every,
                       eps_abs, eps_rel, alpha, adaptive_rho, polish,
                       polish_iters, polish_chunk, eps_abs_dua, eps_rel_dua,
                       stall_rel, ir_sweeps)


# DONATED twin of _qp_solve_jit: the incoming QPState's buffers are handed
# to XLA for reuse (``jax.jit(donate_argnames=("state",))``), so a solve
# that carries L through unchanged ALIASES it into the output instead of
# materializing a fresh (n, n) copy per call — at reference-UC scale each
# warm-started segment call otherwise produces a new ~0.7 GB factor buffer
# (4 segments/solve ≈ the +2.7 GB-per-chunk churn noted at core/ph.py's
# assemble boundary). CALLER CONTRACT: every leaf of ``state`` must be
# uniquely owned — after the call the input state's arrays are DELETED
# (reads raise), including leaves the program only passed through. The
# chunked PH driver tracks ownership (first pass after a (re)build shares
# cold-state buffers across chunks and must not donate); everyone else
# defaults to the copying twin.
_qp_solve_jit_donated = jax.jit(
    _solve_impl, static_argnames=_SOLVE_STATICS, donate_argnames=("state",))


_WARNED_FROZEN_RHO = False


def qp_solve(factors: QPFactors, data: QPData, q, state: QPState,
             donate=False, **kw):
    """Single-precision solve (see _solve_impl). On backends whose f64
    device linalg is untrusted (see _device_f64_linalg_trusted),
    non-shared f64 solves run with IN-JIT rho refactorization disabled —
    the warm state's host-exact inverse (qp_cold_state / qp_reset_rho /
    the mixed handoff) stays valid for the whole call, and the axon
    runtime offers no host callback to refactorize mid-loop.

    ``donate=True`` routes through the donated jit (see
    _qp_solve_jit_donated): ``state``'s buffers are consumed — only pass
    a state no other live object references."""
    if kw.get("adaptive_rho", True) and _needs_host_factor(factors):
        kw["adaptive_rho"] = False
        # direct callers (not qp_solve_segmented, which substitutes
        # _host_adapt_rho at segment boundaries) silently lose rho
        # adaptation here, and badly scaled scenarios then keep dual
        # residuals orders of magnitude loose at rho_scale=1 (ADVICE
        # r3). Tell them once so they can route through
        # qp_solve_segmented instead.
        if not kw.pop("_segmented_caller", False):
            global _WARNED_FROZEN_RHO
            if not _WARNED_FROZEN_RHO:
                _WARNED_FROZEN_RHO = True
                import warnings

                warnings.warn(
                    "qp_solve: in-jit rho adaptation force-disabled "
                    "(non-shared f64 factors on a backend with "
                    "untrusted f64 device linalg). Dual residuals may "
                    "stay loose at the warm-start rho; use "
                    "qp_solve_segmented, which adapts rho host-side at "
                    "segment boundaries.", RuntimeWarning, stacklevel=2)
    else:
        kw.pop("_segmented_caller", None)
    fn = _qp_solve_jit_donated if donate else _qp_solve_jit
    if obs.enabled():
        # measured-roofline capture + compile-ledger attribution
        # (obs/profile.py) — zero-cost when telemetry is off
        from ..obs import profile as _profile
        return _profile.call("qp.solve", fn, factors, data, q, state,
                             **kw)
    return fn(factors, data, q, state, **kw)


def qp_solve_segmented(factors: QPFactors, data: QPData, q, state: QPState,
                       max_iter=4000, segment=500, donate=False, **kw):
    """Host-driven segmented solve: run the jitted loop in warm-started
    SEGMENTS of at most ``segment`` iterations (polish deferred to one
    final call), accumulating until convergence/stall or ``max_iter``.

    Exists because a single long device execution (thousands of ADMM
    iterations in one while_loop) can exceed an accelerator runtime's
    per-execution watchdog — observed as hard TPU worker crashes on
    UC-size solves above ~500 f64 iterations per call. Segmenting costs
    one host dispatch per ``segment`` iterations (microseconds against
    tens of milliseconds of device work) and buys bounded execution
    times, warm-started continuation, and a natural place for host-side
    progress control. Returns the same (state, x, yA, yB) contract.

    NOTE: segments always run FULL (``segment`` is a static jit arg),
    so the total can overshoot ``max_iter`` by up to one segment —
    ``max_iter=100, segment=500`` runs up to 500 iterations. Callers
    that need a hard ceiling pass ``segment <= max_iter``.

    ``donate`` applies to the CALLER's ``state`` only; once the first
    segment has produced a chain-owned successor, every later segment
    donates it regardless (the chain is this function's private state,
    so per-segment factor copies die even for non-donating callers)."""
    final_polish = kw.pop("polish", True)
    host_adapt = kw.get("adaptive_rho", True) and _needs_host_factor(factors)
    total = 0
    owned = donate
    while total < max_iter:
        # always run FULL segments: max_iter is a static jit arg, so a
        # data-dependent remainder would compile a whole extra UC-sized
        # program per distinct remainder (~minutes each on a slow
        # compile path); overshoot is bounded by one segment and the
        # convergence/stall exit stops early anyway
        t_seg = time.perf_counter()
        state, _, _, _ = qp_solve(factors, data, q, state,
                                  max_iter=segment, polish=False,
                                  donate=owned, _segmented_caller=True,
                                  **kw)
        owned = True
        _trace_seg("hi-seg", t_seg, state)
        ran = int(state.iters)
        total += ran
        if ran < segment:   # early exit: converged or stalled
            break
        if host_adapt:
            # in-jit rho adaptation is disabled on untrusted-f64
            # backends (qp_solve); the segment boundary is the host's
            # natural stand-in — same OSQP ratio rule, host-exact
            # refactorization. Without it, badly scaled scenarios keep
            # a huge DUAL residual at rho_scale=1 (measured on farmer:
            # primal 1e-14 but dual objectives thousands of times too
            # loose), poisoning every certified bound.
            state = _host_adapt_rho(factors, state)
    # final call: loop skipped (max_iter=0), polish runs
    state, x, yA, yB = qp_solve(factors, data, q, state, max_iter=0,
                                polish=final_polish, donate=owned,
                                _segmented_caller=True, **kw)
    state = state._replace(iters=jnp.asarray(total, jnp.int32))
    return state, x, yA, yB


def _host_adapt_rho(factors: QPFactors, state: QPState) -> QPState:
    """Per-scenario OSQP rho adaptation at a segment boundary, with the
    refactorization on the HOST (see _device_f64_linalg_trusted): adopt
    sqrt(pri_rel/dua_rel) when the ideal moved > 5x — the same rule the
    in-jit non-shared branch applies every 4th residual check."""
    pr = np.asarray(state.pri_rel)
    dr = np.asarray(state.dua_rel)
    if obs.enabled():
        obs.counter_add("xfer.d2h_bytes",
                        pr.nbytes + dr.nbytes
                        + int(state.rho_scale.nbytes))
    ratio = np.sqrt(np.maximum(pr, 1e-30) / np.maximum(dr, 1e-30))
    old = np.asarray(state.rho_scale)
    new = np.clip(old * np.clip(ratio, 1e-6, 1e6), 1e-6, 1e6)
    change = np.maximum(new / old, old / new)
    mask = np.isfinite(change) & (change > 5.0)
    if not mask.any():
        return state
    rho_np = np.where(mask, new, old)
    rho = jnp.asarray(rho_np, state.rho_scale.dtype)
    # invert only the changed scenarios' KKTs and scatter — a full
    # (S, n, n) host inversion per segment would grow linearly with S
    rows = np.flatnonzero(mask)
    obs.counter_add("qp.host_rho_refactors", rows.size)
    L_rows = _factorize_host(factors, rho_np, rows=rows)
    if obs.enabled():
        # nbytes is metadata — no readback of the freshly shipped block
        obs.counter_add("xfer.h2d_bytes", int(L_rows.nbytes))
    return state._replace(rho_scale=rho,
                          L=state.L.at[jnp.asarray(rows)].set(L_rows))


def _cast_floats(tree, dt):
    """Cast the floating leaves of a NamedTuple pytree; ints ride along."""
    return jax.tree.map(
        lambda a: a.astype(dt)
        if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)


def qp_solve_mixed(factors: QPFactors, data: QPData, q, state: QPState,
                   max_iter=4000, tail_iter=1000, check_every=25,
                   eps_abs=1e-6, eps_rel=1e-6, alpha=1.6, adaptive_rho=True,
                   polish=True, polish_iters=12, polish_chunk=0,
                   eps_abs_dua=None, eps_rel_dua=None, stall_rel=0.0,
                   segment=500, segment_lo=None, ir_sweeps=1, donate=False):
    """Precision-escalated solve: an f32 bulk phase (MXU-friendly — the
    thousands of ADMM matmuls run at accelerator speed) followed by an f64
    tail (one refactorization + a few hundred iterations + the polish).

    Rationale: pure-f32 ADMM stalls at a relative-residual noise floor of
    ~1e-2 on badly scaled LPs (UC: costs spanning 1e1..5e3, loads ~2e3),
    far above the 1e-4..1e-6 the certified bounds and incumbent
    feasibility checks need; pure f64 wastes the accelerator on iterations
    that don't need the precision. The f32 phase does the convergence
    work, the f64 tail does the accuracy work. Everything (factors, data,
    state) arrives in f64; the f32 copies are cast inside the jit.

    BUDGET SEMANTICS: ``max_iter`` bounds only the f32 bulk phase and
    ``tail_iter`` the f64 tail — total work can reach max_iter +
    tail_iter (plus one segment of overshoot each, see
    qp_solve_segmented). PH's ``subproblem_max_iter`` therefore caps
    the bulk, not the sum, when subproblem_precision='mixed'; the tail
    is bounded separately by ``subproblem_tail_iter``. rho adaptation
    stays on in both phases (the tail refactorizes in f64 when the
    ratio moves >5x — worth it when the f32 handoff mis-scaled rho).
    Both phases run SEGMENTED for the same watchdog reason as
    qp_solve_segmented; ``segment_lo`` (default: ``segment``) sets the
    f32 phase's segment separately — the measured watchdog ceiling
    binds f64-involving executions only, and on high-latency device
    links (tunneled TPUs) fewer, longer f32 calls cut the dominant
    per-dispatch overhead. Returns the same (state, x, yA, yB) contract
    as qp_solve, with the state in f64.
    """
    lo = jnp.float32
    # df32 factors/data carry SplitMatrix A — the f32 bulk phase wants
    # the PLAIN hi part (one MXU pass per matvec, not three) and a plain
    # f32 Cholesky factor; a packed split hands the bulk its packed-hi
    # view (dense hi rides along for in-loop refactorization)
    if isinstance(factors.A_s, SplitMatrix):
        A_hi = factors.A_s.hi
        if factors.A_s.pk_hi is not None:
            A_hi = PackedMatrix(A_hi, factors.A_s.pk_hi)
        factors_lo_src = factors._replace(A_s=A_hi)
    else:
        factors_lo_src = factors
    data_lo_src = data._replace(A=data.A.hi) \
        if isinstance(data.A, SplitMatrix) else data
    f_lo = _cast_floats(factors_lo_src, lo)
    d_lo = _cast_floats(data_lo_src, lo)
    st_lo = _cast_floats(state, lo)
    if isinstance(factors.A_s, SplitMatrix):
        # df32 state already carries the f32 Cholesky of THIS M at the
        # state's rho — recomputing it per solve call would add an
        # (n, n) factorization (plus its transients) to every chunk
        # call for an identical result
        pass
    else:
        # jitted: the eager path materializes every factorization
        # transient (the weighted matrix, the product, the factor) as
        # separate buffers — at big scale ~4 GB of avoidable peak
        st_lo = st_lo._replace(L=_factorize_jit(f_lo, st_lo.rho_scale))
    # the f32 phase is a WARM START for the f64 phase: stop it at its
    # noise floor (~1e-3 relative on badly-scaled LPs) — iterating f32
    # past that treads water and, worse, feeds the rho adaptation noise
    eps_lo = jnp.maximum(jnp.asarray(eps_abs, lo), 1e-4)
    eps_rel_lo = jnp.maximum(jnp.asarray(eps_rel, lo), 1e-3)
    # the f32 dual residual plateaus well above the primal one; require
    # only a coarse dual level before handing off
    eps_rel_lo_dua = jnp.maximum(
        jnp.asarray(eps_rel if eps_rel_dua is None else eps_rel_dua, lo),
        1e-2)
    if segment_lo is not None and int(segment_lo) <= 0:
        raise ValueError("segment_lo must be positive (None = use "
                         "`segment` for both phases)")
    seg_lo = segment if segment_lo is None else int(segment_lo)
    # donation ownership through the f32 chain: the initial st_lo is
    # fresh casts of the caller's f64 state EXCEPT two leaves that alias
    # it outright — iters (int, never cast) and, in df32 mode, the f32
    # factor L (same-dtype astype is a no-op). So the FIRST lo segment
    # may donate only when the caller donated AND the factor is not the
    # aliased df32 one; every later segment owns its input outright.
    split = isinstance(factors.A_s, SplitMatrix)
    owned_lo = donate and not split
    lo_ran = False
    q_lo = q.astype(lo)
    lo_total = 0
    while lo_total < max_iter:
        # constant segment size — see qp_solve_segmented on why the
        # remainder must not become a fresh static max_iter
        t_seg = time.perf_counter()
        fn_lo = _solve_lo_jit_donated if owned_lo else _solve_lo_jit
        if obs.enabled():
            from ..obs import profile as _profile
            st_lo, _, _, _ = _profile.call(
                "qp.solve_lo", fn_lo, f_lo, d_lo, q_lo, st_lo,
                seg_lo, check_every, eps_lo, eps_rel_lo, alpha,
                adaptive_rho, polish_iters, eps_rel_lo_dua,
                stall_rel)
        else:
            st_lo, _, _, _ = fn_lo(f_lo, d_lo, q_lo, st_lo,
                                   seg_lo, check_every, eps_lo,
                                   eps_rel_lo, alpha, adaptive_rho,
                                   polish_iters, eps_rel_lo_dua,
                                   stall_rel)
        owned_lo = True
        lo_ran = True
        _trace_seg("lo-seg", t_seg, st_lo)
        ran = int(st_lo.iters)
        lo_total += ran
        if ran < seg_lo:
            break
    dt_hi = state.x.dtype
    rho_hi = st_lo.rho_scale.astype(dt_hi)
    # swap L out for a scalar before the cast: _cast_floats would
    # otherwise materialize a throwaway f64 copy of the (n, n) factor
    L_lo = st_lo.L
    st_hi = _cast_floats(st_lo._replace(L=jnp.zeros((), jnp.float32)),
                         dt_hi)
    if isinstance(factors.A_s, SplitMatrix):
        # the df32 tail's factor IS an f32 Cholesky of the same M at
        # the same (adapted) rho the bulk phase ended on — reuse it
        # instead of recomputing (the factorization's (n, n) transients
        # are the biggest allocations in the whole solve path)
        L_hi = L_lo
    else:
        L_hi = factorize_dispatch(factors, rho_hi)
    st_hi = st_hi._replace(L=L_hi, rho_scale=rho_hi)
    # the f64 tail is the real solver: full termination test, rho
    # adaptation on (it refactorizes in f64 when needed), early exit when
    # the warm start was already good (prox-regularized solves).
    # Ownership of st_hi: its float leaves are fresh f32->f64 casts and
    # L_hi is either the lo chain's output (df32, lo_ran) or a fresh
    # factorization — but iters passes through uncast, so when the lo
    # loop never ran it still aliases the CALLER's state (and in df32
    # L_hi aliases the caller's factor too); donate only when the chain
    # ran or the caller consented on a non-split state.
    st_hi, x, yA, yB = qp_solve_segmented(
        factors, data, q, st_hi, max_iter=tail_iter, segment=segment,
        check_every=check_every, eps_abs=eps_abs, eps_rel=eps_rel,
        alpha=alpha, adaptive_rho=adaptive_rho, polish=polish,
        polish_iters=polish_iters, polish_chunk=polish_chunk,
        eps_abs_dua=eps_abs_dua, eps_rel_dua=eps_rel_dua,
        stall_rel=stall_rel, ir_sweeps=ir_sweeps,
        donate=lo_ran or (donate and not split))
    # total iteration count across both phases
    st_hi = st_hi._replace(iters=jnp.asarray(lo_total, jnp.int32)
                           + st_hi.iters)
    return st_hi, x, yA, yB


def _solve_lo_impl(f_lo, d_lo, q_lo, st_lo, max_iter, check_every, eps_abs,
                   eps_rel, alpha, adaptive_rho, polish_iters, eps_rel_dua,
                   stall_rel):
    """One polish-free f32 segment of qp_solve_mixed."""
    st_lo, _, _, _ = _solve_impl(f_lo, d_lo, q_lo, st_lo, max_iter,
                                 check_every, eps_abs, eps_rel, alpha,
                                 adaptive_rho, False, polish_iters, 0,
                                 eps_abs, eps_rel_dua, stall_rel)
    return st_lo, None, None, None


_LO_STATICS = ("max_iter", "check_every", "adaptive_rho", "polish_iters",
               "stall_rel")
_solve_lo_jit = jax.jit(_solve_lo_impl, static_argnames=_LO_STATICS)
# donated twin — same ownership contract as _qp_solve_jit_donated; the
# f32 chain is qp_solve_mixed's private state after the first segment
_solve_lo_jit_donated = jax.jit(_solve_lo_impl, static_argnames=_LO_STATICS,
                                donate_argnames=("st_lo",))


def stacked_residuals(states, field="pri_rel"):
    """One device-side stack of per-chunk residual vectors ->
    (n_chunks, chunk). The chunked PH quality gates read EVERY chunk's
    residuals each iteration; transferring them one chunk at a time
    costs ceil(S/chunk) blocking D2H syncs — stacking on device first
    means the caller pays exactly ONE host transfer
    (``np.asarray(stacked_residuals(...))``) per PH iteration. Sharded
    chunk states all carry the same mesh placement (colocate passes
    through); the stack compiles to a sharded (n_chunks, chunk) array
    and the host read gathers it in one transfer."""
    from ..parallel.mesh import colocate
    return jnp.stack(colocate([getattr(s, field) for s in states]))


def _unscaled_residuals(A_s, P_s, g, D, E, Eb, csx, q_s, x, yA, yB, zA, zB):
    """UNSCALED residuals (OSQP's default termination convention): the
    scaled ones can be orders of magnitude smaller than problem-unit
    errors, which would poison the dual-objective bounds."""
    Ax = _Ax(A_s, x)
    Aty = _ATy(A_s, yA)
    Einv = 1.0 / E
    Ebinv = 1.0 / Eb
    Dinv_c = 1.0 / (D * csx)
    pri = jnp.maximum(
        jnp.max(jnp.abs(Einv * (Ax - zA)), axis=1),
        jnp.max(jnp.abs(D * x - Ebinv * zB), axis=1))
    dua = jnp.max(jnp.abs(Dinv_c * (P_s * x + q_s + Aty + g * yB)), axis=1)
    pri_sc = jnp.maximum(jnp.maximum(
        jnp.maximum(jnp.max(jnp.abs(Einv * Ax), axis=1),
                    jnp.max(jnp.abs(Einv * zA), axis=1)),
        jnp.maximum(jnp.max(jnp.abs(D * x), axis=1),
                    jnp.max(jnp.abs(Ebinv * zB), axis=1))), 1e-6)
    dua_sc = jnp.maximum(jnp.maximum(
        jnp.maximum(jnp.max(jnp.abs(Dinv_c * P_s * x), axis=1),
                    jnp.max(jnp.abs(Dinv_c * q_s), axis=1)),
        jnp.maximum(jnp.max(jnp.abs(Dinv_c * Aty), axis=1),
                    jnp.max(jnp.abs(Dinv_c * g * yB), axis=1))), 1e-6)
    return pri, dua, pri_sc, dua_sc


def _polish_select(A_s, P_s, g, D, E, Eb, cs, csx, sigma, data, q, q_s,
                   l_s, u_s, lb_s, ub_s, x, yA, yB, zA, zB,
                   pri, dua, pri_sc, dua_sc, polish_iters, shared,
                   eps_abs=1e-6, eps_rel=1e-6):
    """Active-set polish (OSQP sec 5.2, batched) + dual-candidate
    selection. Three candidates are produced:

      1. proximal AL on the slack-detected active set (exact for
         non-degenerate scenarios),
      2. the same after dropping rows whose round-1 dual has the wrong
         sign (fixes weakly-active misdetection),
      3. a sign-projected AL (per-iteration projection of each active dual
         onto its valid orthant) — never catastrophic under degeneracy,
         merely a little loose.

    The returned x/pri/dua are the best-KKT point among {ADMM, 1, 2}; the
    returned duals are the per-scenario argmax of the certified dual
    objective over {ADMM, 1, 2, 3} (any dual vector yields a valid bound,
    so the argmax is valid)."""
    dt = A_s.dtype
    rho_big = jnp.asarray(1e5, dt)
    S = x.shape[0]
    A_b = A_s if A_s.ndim == 3 else jnp.broadcast_to(A_s, (S,) + A_s.shape)
    Pdiag_b = P_s if P_s.ndim == 2 else jnp.broadcast_to(P_s, (S,) + P_s.shape)

    def residuals(x_, yA_, yB_, zA_, zB_):
        return _unscaled_residuals(A_s, P_s, g, D, E, Eb, csx, q_s,
                                   x_, yA_, yB_, zA_, zB_)

    # active-set detection tolerance adapts to the achieved primal
    # accuracy: with pri_rel at the tolerance floor, a fixed 1e-5 cutoff
    # misclassifies marginal rows and every polish candidate inherits the
    # bad set
    act_tol = jnp.maximum(1e-5, 10.0 * (pri / pri_sc))[:, None]

    def act(lo, hi, zv):
        a_l = jnp.isfinite(lo) & (zv - lo <= act_tol * (1.0 + jnp.abs(lo)))
        a_u = jnp.isfinite(hi) & (hi - zv <= act_tol * (1.0 + jnp.abs(hi)))
        b = jnp.where(a_u, jnp.where(jnp.isfinite(hi), hi, 0.0),
                      jnp.where(a_l, jnp.where(jnp.isfinite(lo), lo, 0.0),
                                0.0))
        return a_l | a_u, b

    def penalty_factor(actA, actB):
        rpA = jnp.where(actA, rho_big, 0.0)
        rpB = jnp.where(actB, rho_big, 0.0)
        Mp = jnp.einsum("smi,sm,smj->sij", A_b, rpA, A_b)
        Mp = Mp + jax.vmap(jnp.diag)(Pdiag_b + sigma + g * g * rpB)
        Lp = jnp.linalg.cholesky(Mp)

        def apply_Mp(v):
            return Pdiag_b * v + sigma * v \
                + _ATy(A_b, rpA * _Ax(A_b, v)) + g * g * rpB * v

        return rpA, rpB, Lp, apply_Mp

    def polish_round(actA, bA, actB, bB, x0):
        """Proximal augmented-Lagrangian solve on the guessed active set.
        The per-scenario penalty factor is always batched (active sets
        differ per scenario): M_p = P + sigma I + A'diag(rpA)A +
        diag(g^2 rpB). Each inner solve gets two rounds of iterative
        refinement (the penalty system's conditioning is ~rho_big/sigma;
        the Cholesky solve alone leaves O(100) stationarity error at
        problem scale), and sigma*x_prev in the rhs cancels the
        regularization bias at the fixed point. Duals start from ZERO:
        stalled ADMM duals carry huge drift components along degenerate
        dual rays."""
        rpA, rpB, Lp, apply_Mp = penalty_factor(actA, actB)

        def al_step(carry, _):
            x_prev, yA_p, yB_p = carry
            rhs = sigma * x_prev - q_s + _ATy(A_b, rpA * bA - yA_p) \
                + g * (rpB * bB - yB_p)
            x_p = _tri_solve(Lp, rhs)
            x_p = x_p + _tri_solve(Lp, rhs - apply_Mp(x_p))
            x_p = x_p + _tri_solve(Lp, rhs - apply_Mp(x_p))
            yA_p = yA_p + rpA * (_Ax(A_b, x_p) - bA)
            yB_p = yB_p + rpB * (g * x_p - bB)
            return (x_p, yA_p, yB_p), None

        (x_p, yA_p, yB_p), _ = jax.lax.scan(
            al_step, (x0, jnp.zeros_like(yA), jnp.zeros_like(yB)),
            None, length=polish_iters)
        return x_p, yA_p, yB_p

    def sign_projected_round(alA, auA, eqA, bA, alB, auB, eqB, bB, x0,
                             iters):
        """AL with per-iteration dual SIGN PROJECTION (upper-active duals
        >= 0, lower-active <= 0, equalities free): wrong-sign junk along
        degenerate dual rays cannot persist, at the cost of slower
        convergence. Used as a safe dual CANDIDATE."""
        rpA, rpB, Lp, apply_Mp = penalty_factor(alA | auA, alB | auB)

        def clampy(y, al, au, eq):
            y = jnp.where(au & ~eq, jnp.maximum(y, 0.0), y)
            y = jnp.where(al & ~eq, jnp.minimum(y, 0.0), y)
            return jnp.where(al | au, y, 0.0)

        def step_(carry, _):
            x_prev, yA_p, yB_p = carry
            rhs = sigma * x_prev - q_s + _ATy(A_b, rpA * bA - yA_p) \
                + g * (rpB * bB - yB_p)
            x_p = _tri_solve(Lp, rhs)
            x_p = x_p + _tri_solve(Lp, rhs - apply_Mp(x_p))
            yA_p = clampy(yA_p + rpA * (_Ax(A_b, x_p) - bA), alA, auA, eqA)
            yB_p = clampy(yB_p + rpB * (g * x_p - bB), alB, auB, eqB)
            return (x_p, yA_p, yB_p), None

        (x_p, yA_p, yB_p), _ = jax.lax.scan(
            step_, (x0, jnp.zeros_like(yA), jnp.zeros_like(yB)),
            None, length=iters)
        return x_p, yA_p, yB_p

    def accept(x, yA, yB, pri, dua, pri_sc, dua_sc, x_p, yA_p, yB_p):
        zA_p = jnp.clip(_Ax(A_b, x_p), l_s, u_s)
        zB_p = jnp.clip(g * x_p, lb_s, ub_s)
        pri_p, dua_p, pri_sc_p, dua_sc_p = residuals(x_p, yA_p, yB_p,
                                                     zA_p, zB_p)
        score = jnp.maximum(pri / pri_sc, dua / dua_sc)
        score_p = jnp.maximum(pri_p / pri_sc_p, dua_p / dua_sc_p)
        # a candidate may trade primal for dual accuracy on the max-score
        # ONLY while staying inside the requested primal tolerance band —
        # PH/incumbent consumers read x for primal feasibility, and a
        # polish that "improves" a converged point to 1e-3 violation
        # breaks them (duals still improve via the separate dual-argmax)
        band = jnp.maximum(pri, eps_abs + eps_rel * pri_sc)
        ok = ((score_p < score) & (pri_p <= band))[:, None]
        return (jnp.where(ok, x_p, x), jnp.where(ok, yA_p, yA),
                jnp.where(ok, yB_p, yB),
                jnp.where(ok[:, 0], pri_p, pri),
                jnp.where(ok[:, 0], dua_p, dua),
                jnp.where(ok[:, 0], pri_sc_p, pri_sc),
                jnp.where(ok[:, 0], dua_sc_p, dua_sc))

    # round 1: active set from the ADMM slacks
    actA, bA = act(l_s, u_s, zA)
    actB, bB = act(lb_s, ub_s, zB)
    x_p, yA_p, yB_p = polish_round(actA, bA, actB, bB, x)
    cand1 = (yA_p, yB_p)
    x, yA, yB, pri, dua, pri_sc, dua_sc = accept(
        x, yA, yB, pri, dua, pri_sc, dua_sc, x_p, yA_p, yB_p)

    # round 2: re-detect at the polished point and drop rows whose
    # polished dual has the WRONG SIGN (weakly-active/degenerate rows
    # wrongly pinned in round 1); equalities are exempt
    def refilter(lo, hi, zv, yv):
        a, b = act(lo, hi, zv)
        eq = jnp.isfinite(hi - lo) & (jnp.abs(hi - lo)
                                      <= 1e-9 * (1.0 + jnp.abs(hi)))
        at_u = a & (b == jnp.where(jnp.isfinite(hi), hi, 0.0)) \
            & (zv >= hi - act_tol * (1.0 + jnp.abs(hi)))
        wrong = jnp.where(at_u, yv < 0.0, yv > 0.0) & ~eq
        return a & ~wrong, b

    zA_p = jnp.clip(_Ax(A_b, x_p), l_s, u_s)
    zB_p = jnp.clip(g * x_p, lb_s, ub_s)
    actA2, bA2 = refilter(l_s, u_s, zA_p, yA_p)
    actB2, bB2 = refilter(lb_s, ub_s, zB_p, yB_p)
    x_p2, yA_p2, yB_p2 = polish_round(actA2, bA2, actB2, bB2, x_p)
    cand2 = (yA_p2, yB_p2)
    x, yA, yB, pri, dua, pri_sc, dua_sc = accept(
        x, yA, yB, pri, dua, pri_sc, dua_sc, x_p2, yA_p2, yB_p2)

    # round 3: sign-projected candidate
    def act2(lo, hi, zv):
        a_l = jnp.isfinite(lo) & (zv - lo <= act_tol * (1.0 + jnp.abs(lo)))
        a_u = jnp.isfinite(hi) & (hi - zv <= act_tol * (1.0 + jnp.abs(hi)))
        return a_l, a_u, a_l & a_u

    alA, auA, eqA = act2(l_s, u_s, zA)
    alB, auB, eqB = act2(lb_s, ub_s, zB)
    _, yA_p3, yB_p3 = sign_projected_round(
        alA, auA, eqA, bA, alB, auB, eqB, bB, x, 3 * polish_iters)
    cand3 = (yA_p3, yB_p3)

    def unscale_y(yA_, yB_):
        return (E / csx) * yA_, (Eb / csx) * yB_

    x_un = D * x
    yA_un, yB_un = unscale_y(yA, yB)
    # the certified-bound consumer wants the dual pair with the BEST dual
    # objective — evaluate every candidate and keep the winner. NaN
    # candidates (a degenerate active set can break the penalty Cholesky)
    # must never poison best_val, so it only updates where strictly better.
    best_val = qp_dual_objective(data, q, 0.0, yA_un, yB_un, x_witness=x_un)
    best_val = jnp.where(jnp.isnan(best_val), -jnp.inf, best_val)
    for yA_c, yB_c in (cand1, cand2, cand3):
        yA_cu, yB_cu = unscale_y(yA_c, yB_c)
        val = qp_dual_objective(data, q, 0.0, yA_cu, yB_cu, x_witness=x_un)
        better = (val > best_val)[:, None]
        yA_un = jnp.where(better, yA_cu, yA_un)
        yB_un = jnp.where(better, yB_cu, yB_un)
        best_val = jnp.where(better[:, 0], val, best_val)
    return x_un, yA_un, yB_un, pri, dua, pri_sc


def qp_objective(data: QPData, q, c0, x):
    """½x'Px + q'x + c0 per scenario (unscaled)."""
    return 0.5 * jnp.sum(data.P_diag * x * x, axis=-1) \
        + jnp.sum(q * x, axis=-1) + c0


@jax.jit
def qp_state_duals(factors: QPFactors, state: QPState):
    """UNSCALED (yA, yB) dual iterates straight from a warm solver
    state — the dual-extraction entry for bound consumers that want
    the current iterates WITHOUT another solve call (e.g. a bounder
    publishing between warm-started passes). The unscaling is the one
    _solve_impl applies to its return values; any dual vector yields a
    valid bound via qp_dual_objective, so mid-trajectory iterates are
    legitimate (if loose) bound sources."""
    cs = factors.cost_scale
    shared = factors.A_s.ndim == 2
    csx = cs if shared else cs[:, None]
    return (factors.E / csx) * state.yA, (factors.Eb / csx) * state.yB


@jax.jit
def qp_repair_duals(l, u, lb, ub, yA, yB):
    """Project unscaled duals onto the dual-feasible cone: zero every
    component pushing on an infinite bound (always sign-infeasible
    there). This is a *choice of a different valid dual vector*, not an
    approximation — the repaired pair certifies a bound wherever the
    raw pair would certify −inf. Run it on device BEFORE pulling duals
    to host for certification (utils/certify): the repaired arrays
    compress losslessly to f32 for the transfer (quantized duals are
    still exact duals)."""
    return (_sanitize_row_duals(l, u, yA),
            _sanitize_row_duals(lb, ub, yB))


def _boxmin(P, r, lb, ub):
    """Coordinate-wise min of ½P x² + r x over [lb, ub] (P >= 0 diagonal).
    Returns -inf where a linear piece descends toward an infinite bound."""
    x_unc = jnp.where(P > 0, -r / jnp.where(P > 0, P, 1.0), 0.0)
    x_star = jnp.clip(x_unc, lb, ub)
    quad_val = 0.5 * P * x_star * x_star + r * x_star
    lin_lo = jnp.where(r > 0, jnp.where(jnp.isneginf(lb), -jnp.inf, r * lb), 0.0)
    lin_hi = jnp.where(r < 0, jnp.where(jnp.isposinf(ub), -jnp.inf, r * ub), 0.0)
    return jnp.where(P > 0, quad_val, lin_lo + lin_hi)


def _sanitize_row_duals(lo, hi, y):
    """Zero dual components that push on an infinite bound (always
    sign-infeasible there). Any dual vector gives a valid bound, so this
    only trades a guaranteed -inf for a finite, witness-penalized term."""
    y = jnp.where(jnp.isposinf(hi) & (y > 0), 0.0, y)
    return jnp.where(jnp.isneginf(lo) & (y < 0), 0.0, y)


def _sup_rows(l, u, y, inf_tol=1e-9):
    """sup over the row box of y'z: u'y+ − l'y−, +inf when a positive dual
    pushes on an infinite bound. Shared by qp_dual_objective/benders_cut."""
    yp = jnp.maximum(y, 0.0)
    ym = jnp.maximum(-y, 0.0)
    u_fin = jnp.where(jnp.isfinite(u), u, 0.0)
    l_fin = jnp.where(jnp.isfinite(l), l, 0.0)
    return jnp.sum(u_fin * yp - l_fin * ym, axis=-1) \
        + jnp.sum(jnp.where((jnp.isposinf(u) & (yp > inf_tol))
                            | (jnp.isneginf(l) & (ym > inf_tol)), jnp.inf, 0.0),
                  axis=-1)


def _column_bound(P, q, r, y_b, lb, ub, x_witness, r_rel_tol):
    """Per-column contribution to the dual bound: best of (a) keep the
    bound-row dual, (b) drop it; plus the witness fallback when both are
    -inf. Shared by qp_dual_objective/benders_cut (see the docstrings
    there for the derivation)."""
    tol = r_rel_tol * jnp.maximum(1.0, jnp.abs(q))
    r_a = jnp.where(jnp.abs(r) <= tol, 0.0, r)
    ybp = jnp.maximum(y_b, 0.0)
    ybm = jnp.maximum(-y_b, 0.0)
    ub_fin = jnp.where(jnp.isfinite(ub), ub, 0.0)
    lb_fin = jnp.where(jnp.isfinite(lb), lb, 0.0)
    sup_b = ub_fin * ybp - lb_fin * ybm \
        + jnp.where((jnp.isposinf(ub) & (ybp > 1e-9))
                    | (jnp.isneginf(lb) & (ybm > 1e-9)), jnp.inf, 0.0)
    contrib_a = _boxmin(P, r_a, lb, ub) - sup_b
    contrib_b = _boxmin(P, r - y_b, lb, ub)
    best = jnp.maximum(contrib_a, contrib_b)
    if x_witness is not None:
        def clamped(rv):
            r_fix = jnp.where(jnp.isposinf(ub) & (rv < 0), 0.0, rv)
            r_fix = jnp.where(jnp.isneginf(lb) & (r_fix > 0), 0.0, r_fix)
            penalty = jnp.abs(rv - r_fix) * (2.0 * jnp.abs(x_witness) + 1.0)
            return _boxmin(P, r_fix, lb, ub) - penalty

        # two fallbacks, mirroring (a) and (b): keeping y_b is useless when
        # sup_b itself is +inf (a wrong-sign dual pushing on an infinite
        # bound), so the dropped-y_b clamp must exist independently
        fallback = jnp.maximum(clamped(r_a) - sup_b, clamped(r - y_b))
        best = jnp.maximum(best, jnp.where(jnp.isneginf(best), fallback, best))
    return best


def qp_dual_objective(data: QPData, q, c0, yA, yB, x_witness=None,
                      r_rel_tol=1e-6):
    """Per-scenario LOWER bound on min ½x'Px + q'x + c0 s.t. l <= Ax <= u,
    lb <= x <= ub, from (approximately) dual-feasible (yA, yB).

    An inexact *primal* solution over-estimates the subproblem minimum, so
    bounds built from primal objectives (what the reference gets for free
    from its exact MIP solver, ref. phbase.py:314 Ebound) would be invalid
    here. Instead evaluate a Lagrangian dual at y. *Any* choice of
    bound duals yB yields a valid bound when x is also kept in its box, so
    per coordinate we take the better of:

      (a) keep yB_j:  boxmin(½Px² + r_j x) - (ub_j yB_j+ - lb_j yB_j-)
          with r = q + AᵀyA + yB the full dual residual, entries below
          r_rel_tol*max(1,|q_j|) zeroed (epsilon-valid convention), and
      (b) drop yB_j:  boxmin(½Px² + (r_j - yB_j) x)   [pure reduced cost]

    plus, where both are -inf (an infinite-direction residual above
    tolerance), a witness fallback: clamp the offending residual part and
    pay |clamped|*(2|x_witness_j| + 1) — valid whenever the true optimum
    satisfies |x*_j| <= 2|x_witness_j| + 1.

    The total is  -sup_c + sum_j best_j + c0  with
    sup_c = u'yA+ - l'yA- over the constraint rows.

    Wrong-sign dual components at INFINITE bounds (drift artifacts of a
    degenerate solve) would make the sup terms +inf and the bound -inf;
    since any dual vector yields a valid bound, those components are
    zeroed first — the error moves into r where the per-column machinery
    absorbs it.
    """
    yA = _sanitize_row_duals(data.l, data.u, yA)
    yB = _sanitize_row_duals(data.lb, data.ub, yB)
    r = q + _ATy(data.A, yA) + yB
    best = _column_bound(data.P_diag, q, r, yB, data.lb, data.ub,
                         x_witness, r_rel_tol)
    sup_c = _sup_rows(data.l, data.u, yA)
    return jnp.sum(best, axis=-1) - sup_c + c0


def benders_cut(data: QPData, q, c0, yA, yB, param_mask, b0,
                r_rel_tol=1e-6):
    """Affine minorant of the *value function* V(b) =
    min ½x'Px + q'x + c0 s.t. l <= Ax <= u, box bounds, with the columns in
    `param_mask` fixed at b (their boxes carry lb=ub=b in `data`).

    Returns (const (S,), g (S, n) zero outside param_mask) such that
    V(b) >= const + g·b[param] for all b, up to the r_rel_tol
    residual-zeroing convention — the L-shaped optimality cut (the
    reference gets these from exact solver duals via
    pyomo.contrib.benders, ref. mpisppy/opt/lshaped.py:639; here they come
    from ADMM dual vectors, so inexact subproblem solves still yield
    tolerance-valid cuts).

    Derivation: dropping the bound dual yB on the parameterized columns,
    the dual function's dependence on b is
      sum_{j in param} [ (q + AᵀyA)_j b_j + ½P_j b_j² ],
    and the quadratic is linearized at b0 (valid: a convex function's
    tangent is a global minorant). Non-parameter columns contribute the
    same per-coordinate best-of-two boxmin terms as qp_dual_objective.
    No x_witness fallback here: its validity box is tied to the solve at
    b0, but a cut must minorize V at EVERY b — a -inf free column simply
    yields an inactive (-inf) cut instead."""
    pm = param_mask  # (n,) bool
    P = data.P_diag

    yA = _sanitize_row_duals(data.l, data.u, yA)
    yB = _sanitize_row_duals(data.lb, data.ub, yB)
    r = q + _ATy(data.A, yA) + yB
    r_c = r - yB     # residual without the bound dual

    # parameterized columns: affine in b, quadratic linearized at b0
    g = jnp.where(pm, r_c + P * b0, 0.0)
    const_param = jnp.sum(jnp.where(pm, -0.5 * P * b0 * b0, 0.0), axis=-1)

    best = _column_bound(P, q, r, yB, data.lb, data.ub, None, r_rel_tol)
    const_free = jnp.sum(jnp.where(pm, 0.0, best), axis=-1)
    sup_c = _sup_rows(data.l, data.u, yA)
    return const_param + const_free - sup_c + c0, g
