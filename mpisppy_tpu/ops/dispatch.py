"""Device-side φ-dispatch for APH (doc/aph.md).

The reference's APH worker re-ranks its scenario pool on the host every
iteration: most-negative post-step φ first, least-recently-dispatched
fill for the shortfall (ref. mpisppy/opt/aph.py:592-640 _dispatch_list).
``core/aph.py`` kept that as host numpy over a full (S,) D2H pull of
phis — at S=100k that is an 800 KB blocking transfer plus an O(S log S)
host sort sitting on the critical path between the projective step and
the dispatched solves.

This module moves the whole selection on device:

- :func:`dispatch_select` — the jitted rank-based selection. Both pools
  and their tie-breaks are encoded as one lexicographic key and sorted
  with two stable argsorts (LSD radix), so the result is bit-identical
  to the host reference (``APH._dispatch_mask``) including tie order.
  The key is INTEGER (group, rank) — a float composite key such as
  ``last_dispatch * S + idx`` would silently collide once S·iter
  exceeds the 24-bit f32 mantissa, and the engine dtype is f32 whenever
  x64 is off (utils/runtime enables it only under ``--x64``).
- :func:`dispatch_gate` / :func:`scalar_gate` — the PR 13 packed-row
  discipline applied to APH's per-iteration host traffic: every scalar
  the host loop reads (τ, φ, θ, conv + the φ-histogram stats analyze
  renders) and the dispatch mask ride ONE device vector, read by ONE
  D2H transfer per iteration (``aph.gate_syncs``).
- the dispatch-bucket registry — serve-cache-style fingerprints over
  the (n_chunks, chunk, S, K) shapes a partial-dispatch solve compiles
  for, so ``dispatch.bucket.compile`` counts exactly the bucket
  transitions and steady-state iterations are compile-free
  (``dispatch.bucket.cache_hit``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .. import obs
from ..ckpt.bundle import config_fingerprint


@partial(jax.jit, static_argnames=("scnt", "S_real"))
def dispatch_select(phis, last_dispatch, scnt: int, S_real: int):
    """Device twin of ``APH._dispatch_mask`` for the partial case
    (``scnt < S_real``): the ``scnt`` most-negative-φ scenarios, then
    least-recently-dispatched fill, as a boolean (S,) mask.

    Selection = take the first ``scnt`` rows of the ascending
    lexicographic order of (group, rank, index) where
      group 0: real rows with φ < 0, ranked by ascending φ;
      group 1: remaining real rows, ranked by ``last_dispatch``
               (oldest first — the fill pool);
      group 2: zero-probability mesh pad rows (never dispatched).
    Two stable argsorts implement the radix: sort by the secondary
    rank, then stably by group; stability makes the index the final
    tie-break, matching the host reference's stable fill sort."""
    S = phis.shape[0]
    idx = jnp.arange(S, dtype=jnp.int32)
    real = idx < S_real
    neg = (phis < 0) & real
    group = jnp.where(neg, 0, jnp.where(real, 1, 2)).astype(jnp.int32)
    # ascending-φ rank within the negative pool (inverse permutation of
    # a stable argsort — non-pool rows push to the end via +inf)
    p = jnp.argsort(jnp.where(neg, phis, jnp.inf), stable=True)
    phi_rank = jnp.zeros(S, jnp.int32).at[p].set(idx)
    sec = jnp.where(neg, phi_rank, last_dispatch.astype(jnp.int32))
    perm1 = jnp.argsort(sec, stable=True)
    order = perm1[jnp.argsort(group[perm1], stable=True)]
    mask = jnp.zeros(S, bool).at[order[:scnt]].set(True)
    return mask


def _phi_stats(phis, S_real: int):
    """φ-histogram row for the gate: (min, max, negative count) over
    the real rows (pad rows carry probability 0 ⇒ φ ≡ 0 and would
    pollute max/count)."""
    pr = phis[:S_real]
    return jnp.stack([jnp.min(pr), jnp.max(pr),
                      jnp.sum(pr < 0).astype(pr.dtype)])


@partial(jax.jit, static_argnames=("scnt", "S_real"))
def _dispatch_gate_jit(tau, phi, theta, conv, phis, last_dispatch,
                       scnt: int, S_real: int):
    mask = dispatch_select(phis, last_dispatch, scnt=scnt, S_real=S_real)
    head = jnp.concatenate([jnp.stack([tau, phi, theta, conv]),
                            _phi_stats(phis, S_real)])
    return jnp.concatenate([head, mask.astype(head.dtype)])


def dispatch_gate(*args, **kwargs):
    """One packed device row for APH's per-iteration host read:
    ``[τ, φ, θ, conv, φ_min, φ_max, φ_neg_count] ++ mask`` — the
    projective-step scalars, the φ stats, and the dispatch selection,
    concatenated so the host loop syncs exactly once (the PR 13
    ``(3,)``-packed-stats discipline, scaled up)."""
    if obs.enabled():
        # measured-roofline capture (obs/profile.py) — zero-cost off
        from ..obs import profile as _profile
        return _profile.call("aph.dispatch_gate", _dispatch_gate_jit,
                             *args, **kwargs)
    return _dispatch_gate_jit(*args, **kwargs)


@partial(jax.jit, static_argnames=("S_real",))
def _scalar_gate_jit(tau, phi, theta, conv, phis, S_real: int):
    return jnp.concatenate([jnp.stack([tau, phi, theta, conv]),
                            _phi_stats(phis, S_real)])


def scalar_gate(*args, **kwargs):
    """The full-dispatch twin of :func:`dispatch_gate`: every real row
    dispatches, so only the scalar head ships — no selection runs and
    the trajectory stays bit-identical to the pre-dispatch engine."""
    if obs.enabled():
        from ..obs import profile as _profile
        return _profile.call("aph.scalar_gate", _scalar_gate_jit,
                             *args, **kwargs)
    return _scalar_gate_jit(*args, **kwargs)


GATE_HEAD = 7   # scalar head width of both gate spellings


# dispatch-layout row ops: one gather per chunk (constant shapes — one
# compile per mode) and one padded-width scatter per pass (shape keyed
# by the bucket registry below). ``rows`` may repeat trailing ids (the
# chunk-pad convention); duplicates carry bit-identical values, so the
# scatter outcome is deterministic despite XLA's unordered scatter.

@jax.jit
def gather_rows(full, idx):
    return full[idx]


@jax.jit
def scatter_rows(full, idx, rows):
    return full.at[idx].set(rows)


# serve-cache-style shape-bucket registry (module-level, process-global
# like the jit cache it mirrors): a partial-dispatch pass compiles its
# scatter-back programs per padded dispatch width — fingerprint the
# shape tuple so a wheel pays one compile per bucket TRANSITION and the
# counters prove it (``dispatch.bucket.compile`` vs ``.cache_hit``).
_BUCKET_REGISTRY: dict = {}


def bucket_fingerprint(fields: dict) -> str:
    """Stable 16-hex shape-bucket id (same hashing as serve/cache and
    checkpoint fingerprints — ckpt/bundle.config_fingerprint)."""
    return config_fingerprint(fields)


def bucket_registry():
    """Read-only view for tests/telemetry."""
    return dict(_BUCKET_REGISTRY)


def register_bucket(fields: dict) -> str:
    """Book one dispatch-shape bucket use: first sighting of a
    fingerprint is a compile (new scatter-back shapes reach XLA),
    repeats are cache hits. Returns the fingerprint."""
    fp = bucket_fingerprint(fields)
    if fp in _BUCKET_REGISTRY:
        _BUCKET_REGISTRY[fp]["hits"] += 1
        obs.counter_add("dispatch.bucket.cache_hit")
    else:
        _BUCKET_REGISTRY[fp] = {"fields": dict(fields), "hits": 0}
        obs.counter_add("dispatch.bucket.compile")
    return fp
