"""NETDES: two-stage stochastic network design.

Same problem class as the reference's netdes example (ref. examples/netdes/
netdes.py:33-76): first stage builds arcs (binary x_e, cost c_e), second
stage routes flow y_e at cost d_e subject to arc capacity u_e·x_e and node
flow balance b_i(ξ). The reference reads 100+ pre-generated .dat instances;
here instances are seeded random strongly-connected digraphs scalable via
num_nodes, with per-scenario random demand vectors.
"""

from __future__ import annotations

import re

import numpy as np

from ..ir.model import Model
from ..ir.tree import two_stage_tree


def build_graph(num_nodes=5, extra_arc_prob=0.5, base_seed=7):
    """A ring (guarantees feasibility of any balanced demand) plus seeded
    random chords. Returns (edge list, incidence matrix, c, d, u)."""
    rng = np.random.RandomState(base_seed)
    edges = [(i, (i + 1) % num_nodes) for i in range(num_nodes)]
    for i in range(num_nodes):
        for j in range(num_nodes):
            if i != j and (i, j) not in edges and rng.rand() < extra_arc_prob:
                edges.append((i, j))
    E = len(edges)
    inc = np.zeros((num_nodes, E))   # +1 out, -1 in (flow balance rows)
    for e, (i, j) in enumerate(edges):
        inc[i, e] = 1.0
        inc[j, e] = -1.0
    c = rng.uniform(10.0, 40.0, size=E)    # build cost
    d = rng.uniform(1.0, 5.0, size=E)      # per-unit routing cost
    u = rng.uniform(10.0, 30.0, size=E)    # capacity
    return edges, inc, c, d, u


def scenario_demand(scennum, num_nodes, scale=5.0):
    """b_i(ξ): seeded supply/demand vector summing to zero."""
    rng = np.random.RandomState(2000 + scennum)
    b = rng.uniform(-scale, scale, size=num_nodes)
    return b - b.mean()


def scenario_creator(scenario_name, num_nodes=5, extra_arc_prob=0.5,
                     base_seed=7, demand_scale=5.0) -> Model:
    scennum = int(re.search(r"(\d+)$", scenario_name).group(1))
    edges, inc, c, d, u = build_graph(num_nodes, extra_arc_prob, base_seed)
    b = scenario_demand(scennum, num_nodes, demand_scale)
    E = len(edges)

    m = Model(scenario_name, sense="min")
    x = m.var("BuildArc", E, lb=0.0, ub=1.0, integer=True, stage=1)
    y = m.var("Flow", E, lb=0.0, stage=2)

    # variable upper bounds y_e <= u_e x_e (ref. netdes.py:59-62)
    m.constr(y - (np.diag(u) @ x) <= 0.0, name="ArcCapacity")
    # flow balance per node (ref. netdes.py:65-71); drop the last row — it
    # is implied (rows of inc sum to 0 and b sums to 0) and keeping it makes
    # the equality block rank-deficient
    m.constr(inc[:-1] @ y == b[:-1], name="FlowBalance")

    m.stage_cost(1, x.dot(c))
    m.stage_cost(2, y.dot(d))
    return m


def make_tree(num_scens, **_):
    names = [f"Scenario{i}" for i in range(num_scens)]
    return two_stage_tree(names, nonant_names=["BuildArc"])


def scenario_denouement(rank, scenario_name, values):
    pass
