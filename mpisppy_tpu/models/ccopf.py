"""ccopf: 4-stage DC optimal power flow under demand uncertainty.

The acopf3-class multistage stress model (ref. examples/acopf3/
ccopf2wood.py, fourstage.py, ACtree.py: a 4-stage chance-constrained
AC-OPF on small networks via egret). The TPU-native analog keeps the
structure that stresses the framework — a FOUR-stage tree (branching
2×2×2 = 8 scenarios by default), per-stage nonanticipative generator
setpoints, network flow physics, ramping that couples stages, and a
QUADRATIC generation cost (exercising the kernel's P_diag path) — on a
deterministic 5-bus DC network instead of egret's AC data files.

  min  Σ_t [ Σ_g (a_g·gen²  + b_g·gen) + VOLL·Σ_b shed ]
  s.t. per stage t:  A_gᵀ gen_t − d_t^s + shed_t = B_bus θ_t   (balance)
       |θ_i − θ_j|/x_l ≤ cap_l                                (flow limits)
       |gen_t − gen_{t−1}| ≤ ramp                             (t ≥ 2)
       θ_ref = 0,   0 ≤ gen ≤ gmax,   0 ≤ shed ≤ d_t^s

Nonants: Gen1..Gen3 (stages 1..3); stage 4 is pure recourse. Demand
scales along the tree-node path, so only the rhs varies per scenario and
the shared-structure kernel path applies.
"""

from __future__ import annotations

import re

import numpy as np

from ..ir.model import Model
from ..ir.tree import balanced_tree

NBUS = 5
# lines: (from, to, reactance, capacity) — ring + two chords
LINES = [(0, 1, 0.2, 120.0), (1, 2, 0.25, 100.0), (2, 3, 0.2, 110.0),
         (3, 4, 0.3, 90.0), (4, 0, 0.25, 120.0), (0, 2, 0.4, 80.0),
         (1, 3, 0.5, 70.0)]
NL = len(LINES)
# generators: (bus, gmax, a_quad, b_lin, ramp)
GENS = [(0, 180.0, 0.020, 18.0, 60.0), (2, 140.0, 0.035, 24.0, 50.0),
        (4, 100.0, 0.055, 32.0, 40.0)]
NG = len(GENS)
BASE_DEMAND = np.array([38.0, 58.0, 46.0, 66.0, 34.0])
STAGE_SHAPE = np.array([0.9, 1.0, 1.15, 1.05])   # diurnal-ish profile
VOLL = 2000.0
T = 4


def _network():
    inc = np.zeros((NL, NBUS))       # line-bus incidence
    binv = np.zeros(NL)
    cap = np.zeros(NL)
    for i, (a, b, xr, cp) in enumerate(LINES):
        inc[i, a] = 1.0
        inc[i, b] = -1.0
        binv[i] = 1.0 / xr
        cap[i] = cp
    Bbus = inc.T @ np.diag(binv) @ inc
    Ag = np.zeros((NBUS, NG))
    for j, (bus, *_rest) in enumerate(GENS):
        Ag[bus, j] = 1.0
    return inc, binv, cap, Bbus, Ag


def demand_path(scennum: int, branching=(2, 2, 2)):
    """Per-stage demand multipliers along the scenario's node path
    (stage 1 is common). Branch digit d of a b-way node moves demand by
    a multiplier spread EVENLY over [+10%, -10%] — d=0 is +10%, d=b-1
    is -10%, intermediate digits interpolate — so every sibling node
    carries DISTINCT demand data at any branching factor (a constant
    per-digit move would collapse b>2 siblings into duplicates)."""
    mults = [1.0]
    digits = []
    s = scennum
    for b in reversed(branching):
        digits.append((s % b, b))
        s //= b
    digits = digits[::-1]
    level = 1.0
    for d, b in digits:
        move = 0.10 if b <= 1 else 0.10 * (1.0 - 2.0 * d / (b - 1))
        level *= 1.0 + move
        mults.append(level)
    return np.asarray(mults)          # (T,) with mults[0] = 1.0


def scenario_creator(scenario_name, branching=(2, 2, 2)) -> Model:
    scennum = int(re.search(r"(\d+)$", scenario_name).group(1)) - 1
    inc, binv, cap, Bbus, Ag = _network()
    mults = demand_path(scennum, branching)

    m = Model(scenario_name, sense="min")
    gens, thetas, sheds = [], [], []
    for t in range(1, T + 1):
        g = m.var(f"Gen{t}", NG, lb=0.0,
                  ub=np.array([gm for _, gm, *_ in GENS]), stage=t)
        th = m.var(f"Theta{t}", NBUS, lb=-np.pi, ub=np.pi, stage=t)
        d_t = BASE_DEMAND * STAGE_SHAPE[t - 1] * mults[t - 1]
        sh = m.var(f"Shed{t}", NBUS, lb=0.0, ub=d_t, stage=t)
        gens.append(g)
        thetas.append(th)
        sheds.append(sh)
        # bus balance: Ag g − Bbus θ + shed = d
        m.constr((Ag @ g) - (Bbus @ th) + sh == d_t, name=f"Balance{t}")
        # reference angle
        ref = np.zeros((1, NBUS))
        ref[0, 0] = 1.0
        m.constr((ref @ th) == 0.0, name=f"RefAngle{t}")
        # line flow limits: |diag(binv) inc θ| ≤ cap
        F = np.diag(binv) @ inc
        m.constr((F @ th) <= cap, name=f"FlowUB{t}")
        m.constr((F @ th) >= -cap, name=f"FlowLB{t}")
        # ramping couples consecutive stages
        if t > 1:
            ramp = np.array([r for *_x, r in GENS])
            m.constr(g - gens[t - 2] <= ramp, name=f"RampUp{t}")
            m.constr(g - gens[t - 2] >= -ramp, name=f"RampDn{t}")
        # costs: quadratic + linear generation, VOLL shedding
        a = np.array([aq for _, _, aq, _, _ in GENS])
        b = np.array([bl for _, _, _, bl, _ in GENS])
        m.quad_cost(g, 2.0 * a)
        m.stage_cost(t, g.dot(b) + VOLL * sh.sum())
    return m


def make_tree(branching=(2, 2, 2)):
    """4-stage balanced tree; nonants are the stage-1..3 gen setpoints
    (stage-4 decisions are leaf recourse)."""
    return balanced_tree(list(branching),
                         [["Gen1"], ["Gen2"], ["Gen3"]],
                         scen_name_fmt="CCopf{}")


def scenario_denouement(*args, **kwargs):
    pass
