"""Battery: hybrid solar-battery storage arbitrage (Singh & Knueven).

Same problem as the reference's battery example (ref. examples/battery/
battery.py:19-90, the Lagrangian relaxation (4) of the chance-constrained
model): sell y_t (first-stage nonant), charge p_t, discharge q_t, state of
charge x_t, and a recourse indicator z; flow balance
x_{t+1} = x_t + eff·p_t − q_t/eff, big-M solar availability
y_t − q_t + p_t <= solar_t(ξ) + M·z, objective
−rev·y + char·Σp + disc·Σq + λ·z. Solar traces are seeded per scenario
instead of read from a file.
"""

from __future__ import annotations

import re

import numpy as np

from ..ir.model import Model
from ..ir.tree import two_stage_tree

DEFAULTS = dict(T=24, eff=0.9, cMax=5.0, dMax=5.0, eMin=1.0, eMax=10.0,
                char=0.1, disc=0.1, lam=100.0, bigM=50.0)


def solar_trace(scennum, T, peak=8.0):
    """Seeded diurnal solar curve with scenario-level cloud noise."""
    rng = np.random.RandomState(3000 + scennum)
    t = np.arange(T)
    clear = peak * np.maximum(0.0, np.sin(np.pi * (t - 6.0) / 12.0))
    cloud = rng.uniform(0.4, 1.0, size=T)
    return clear * cloud


def revenue_prices(T, base_seed=11):
    rng = np.random.RandomState(base_seed)
    return rng.uniform(1.0, 3.0, size=T)


def scenario_creator(scenario_name, T=None, use_LP=True, lam=None,
                     base_seed=11, **over) -> Model:
    cfg = dict(DEFAULTS)
    cfg.update(over)
    if T is not None:
        cfg["T"] = T
    if lam is not None:
        cfg["lam"] = lam
    T = int(cfg["T"])
    scennum = int(re.search(r"(\d+)$", scenario_name).group(1))
    solar = solar_trace(scennum, T)
    rev = revenue_prices(T, base_seed)

    m = Model(scenario_name, sense="min")
    y = m.var("Sell", T, lb=0.0, stage=1)                      # the nonant
    p = m.var("Charge", T, lb=0.0, ub=cfg["cMax"], stage=2)
    q = m.var("Discharge", T, lb=0.0, ub=cfg["dMax"], stage=2)
    x = m.var("StateOfCharge", T, lb=cfg["eMin"], ub=cfg["eMax"], stage=2)
    z = m.var("Recourse", 1, lb=0.0, ub=1.0, integer=not use_LP, stage=2)

    # x_{t+1} = x_t + eff p_t - q_t/eff for t = 0..T-2
    # (ref. battery.py:60-64 flow_balance_constraint_rule)
    shift = np.eye(T)[1:]            # rows select x_{t+1}
    keep = np.eye(T)[:-1]            # rows select x_t
    m.constr((shift @ x) - (keep @ x) - cfg["eff"] * (keep @ p)
             + (1.0 / cfg["eff"]) * (keep @ q) == 0.0, name="FlowBalance")

    # y_t - q_t + p_t <= solar_t + M z (ref. battery.py:67-71)
    onesM = np.full((T, 1), cfg["bigM"])
    m.constr(y - q + p - (onesM @ z) <= solar, name="SolarBigM")

    # first-stage cost is the (negative) revenue on y
    # (ref. battery.py:74-81: obj = -rev.y + char sum p + disc sum q + lam z)
    m.stage_cost(1, y.dot(-rev))
    m.stage_cost(2, cfg["char"] * p.sum() + cfg["disc"] * q.sum()
                 + cfg["lam"] * z.sum())
    return m


def make_tree(num_scens, **_):
    names = [f"Scenario{i}" for i in range(num_scens)]
    return two_stage_tree(names, nonant_names=["Sell"])


def scenario_denouement(rank, scenario_name, values):
    pass
