"""Hydro: the reference's 3-stage hydro-thermal scheduling example.

Same model and data as the reference (ref. mpisppy/tests/examples/hydro/
hydro.py, data in PySP/scenariodata/Scen*.dat): 3 stages, 9 scenarios from
branching factors [3, 3]; per-stage thermal generation Pgt, hydro Pgh,
unserved demand PDns, reservoir volume Vol; demand balance, water
conservation with stochastic inflows A, and a terminal future-cost "fcfe"
constraint. Nonants at stage t are (Pgt[t], Pgh[t], PDns[t], Vol[t])
(ref. hydro.py MakeNodesforScen).

Stochastic data: stage-2 inflow A2 in {10, 50, 90}, stage-3 inflow
A3 in {40, 50, 60}; the reference's EF trivial bound is ~180 and PH
Eobjective ~190 (ref. mpisppy/tests/test_ef_ph.py:554-559).
"""

from __future__ import annotations

import numpy as np

from ..ir.model import Model
from ..ir.tree import balanced_tree

T = 3
D = np.array([90.0, 160.0, 110.0])          # demand per stage
BETA_GT, BETA_GH, BETA_DNS = 1.0, 0.0, 10.0
PGT_MAX, PGH_MAX, V_MAX = 100.0, 100.0, 100.0
U = np.array([0.6048, 0.6048, 1.2096])      # conversion factors
DURACION = np.array([168.0, 168.0, 336.0])
V0 = 60.48
T_HOURS = 8760.0
A2_VALUES = [10.0, 50.0, 90.0]
A3_VALUES = [40.0, 50.0, 60.0]
FCFE_COEF = 4166.67

DISCOUNT = (1.0 / 1.1) ** (DURACION / T_HOURS)   # r[t]


def scenario_inflows(scen_one_based: int) -> np.ndarray:
    """Inflow vector A for scenario s in 1..9 (matches Scen{s}.dat)."""
    s = scen_one_based - 1
    return np.array([50.0, A2_VALUES[s // 3], A3_VALUES[s % 3]])


def scenario_creator(scenario_name, branching_factors=None) -> Model:
    snum = int("".join(ch for ch in scenario_name if ch.isdigit()))
    A = scenario_inflows(snum)

    m = Model(scenario_name, sense="min")
    # one var block per stage so the tree can name per-stage nonants
    pgt = [m.var(f"Pgt{t+1}", 1, lb=0.0, ub=PGT_MAX, stage=t + 1) for t in range(T)]
    pgh = [m.var(f"Pgh{t+1}", 1, lb=0.0, ub=PGH_MAX, stage=t + 1) for t in range(T)]
    pdns = [m.var(f"PDns{t+1}", 1, lb=0.0, ub=D[t], stage=t + 1) for t in range(T)]
    vol = [m.var(f"Vol{t+1}", 1, lb=0.0, ub=V_MAX, stage=t + 1) for t in range(T)]
    sl = m.var("sl", 1, lb=0.0, stage=T)

    for t in range(T):
        m.constr(pgt[t] + pgh[t] + pdns[t] == D[t], name=f"demand{t+1}")
        prev = vol[t - 1] if t > 0 else None
        # Vol[t] - Vol[t-1] <= u[t] (A[t] - Pgh[t])
        lhs = vol[t] - prev if prev is not None else vol[t] - V0
        m.constr(lhs + U[t] * pgh[t] <= U[t] * A[t], name=f"conserv{t+1}")
    m.constr(sl + FCFE_COEF * vol[T - 1] >= FCFE_COEF * V0, name="fcfe")

    for t in range(T):
        cost = DISCOUNT[t] * (BETA_GT * pgt[t] + BETA_GH * pgh[t] + BETA_DNS * pdns[t])
        if t == T - 1:
            cost = cost + sl
        m.stage_cost(t + 1, cost)
    return m


def make_tree(branching_factors=(3, 3)):
    BFs = list(branching_factors)
    nonants = [["Pgt1", "Pgh1", "PDns1", "Vol1"],
               ["Pgt2", "Pgh2", "PDns2", "Vol2"]]
    return balanced_tree(BFs, nonant_names_per_stage=nonants,
                         scen_name_fmt="Scen{}")


def scenario_denouement(rank, scenario_name, values):
    pass
