"""Model families (the reference's examples corpus, ref. SURVEY §2.6).

Each module provides ``scenario_creator(name, **kwargs) -> Model``,
``make_tree(num_scens, ...) -> ScenarioTree`` and
``scenario_denouement`` mirroring the reference's per-example contract.
"""

from . import farmer, hydro, uc, sizes, sslp, netdes, battery, ccopf  # noqa: F401
