"""Stochastic unit commitment — the framework's benchmark workhorse.

The reference's UC example (ref. examples/uc/uc_funcs.py, uc_cylinders.py;
paperruns/larger_uc/ up to 1000 wind scenarios) builds egret-based Pyomo
models from data files. This is a self-contained generator of the same
*shape* of problem — two-stage SMIP where first-stage commitment/startup
decisions are nonanticipative and second-stage dispatch responds to a wind
scenario — with deterministic seeded data so results are reproducible:

  min  E_s[ sum_{g,t} (noload_g u_{gt} + mc_g p_{gt} + su_g st_{gt})
            + sum_t VOLL shed_t ]
  s.t. sum_g (Pmin_g u_{gt} + p_{gt}) + wind_t^s - spill_t + shed_t = load_t
       p_{gt} <= (Pmax_g - Pmin_g) u_{gt}
       st_{gt} >= u_{gt} - u_{g,t-1}          (startup definition)
       sum_g Pmax_g u_{gt} >= load_t - wind_t^s + r*load_t   (reserve)
       u, st in [0,1] (integer), p >= 0, shed in [0,load], spill in [0,wind]

Nonants: u and st (commitment schedule), matching the reference's
first-stage variable set.
"""

from __future__ import annotations

import numpy as np

from ..ir.model import Model
from ..ir.tree import two_stage_tree

VOLL = 5000.0          # value of lost load ($/MWh)
RESERVE_FRAC = 0.10


def fleet(num_gens: int, seed: int = 1234):
    """Deterministic generator fleet: a cost-ordered mix from big cheap
    baseload to small expensive peakers."""
    rng = np.random.RandomState(seed)
    frac = np.linspace(0.0, 1.0, num_gens)
    pmax = 50.0 + 400.0 * (1.0 - frac) ** 1.5 + rng.rand(num_gens) * 20.0
    pmin = 0.3 * pmax
    mc = 10.0 + 70.0 * frac ** 1.2 + rng.rand(num_gens) * 5.0   # $/MWh
    noload = 2.0 * pmax * 0.5 + rng.rand(num_gens) * 50.0        # $/h
    startup = 30.0 * pmax + rng.rand(num_gens) * 500.0           # $/start
    return dict(pmax=pmax, pmin=pmin, mc=mc, noload=noload, startup=startup)


def load_profile(num_hours: int, num_gens: int):
    """Diurnal load sized to ~70% of fleet capacity at peak."""
    t = np.arange(num_hours)
    shape = 0.7 + 0.25 * np.sin((t - 6) * 2 * np.pi / 24.0) \
        + 0.05 * np.sin(t * 4 * np.pi / 24.0)
    cap = fleet(num_gens)["pmax"].sum()
    return 0.7 * cap * shape


def wind_scenario(scennum: int, num_hours: int, num_gens: int):
    """Seeded smooth wind trace, ~15% of fleet capacity on average."""
    rng = np.random.RandomState(100000 + scennum)
    cap = fleet(num_gens)["pmax"].sum()
    steps = rng.randn(num_hours) * 0.25
    level = 0.15 + 0.1 * np.cumsum(steps) / np.sqrt(np.arange(1, num_hours + 1))
    return np.clip(level, 0.0, 0.4) * cap


def scenario_creator(scenario_name, num_gens=10, num_hours=24,
                     relax_integrality=True) -> Model:
    import re
    scennum = int(re.search(r"(\d+)$", scenario_name).group(1))
    fl = fleet(num_gens)
    load = load_profile(num_hours, num_gens)
    wind = wind_scenario(scennum, num_hours, num_gens)
    G, T = num_gens, num_hours
    dP = fl["pmax"] - fl["pmin"]

    m = Model(scenario_name, sense="min")
    # commitment u[g,t] and startups st[g,t] flattened g-major
    u = m.var("u", G * T, lb=0.0, ub=1.0, integer=not relax_integrality, stage=1)
    st = m.var("st", G * T, lb=0.0, ub=1.0, integer=not relax_integrality, stage=1)
    p = m.var("p", G * T, lb=0.0, stage=2)
    shed = m.var("shed", T, lb=0.0, ub=load, stage=2)
    spill = m.var("spill", T, lb=0.0, ub=np.maximum(wind, 0.0), stage=2)

    gt = lambda g, t: g * T + t

    # balance rows: one per hour (vectorized via coefficient matrices)
    Bu = np.zeros((T, G * T))
    Bp = np.zeros((T, G * T))
    for g in range(G):
        for t in range(T):
            Bu[t, gt(g, t)] = fl["pmin"][g]
            Bp[t, gt(g, t)] = 1.0
    m.constr((Bu @ u) + (Bp @ p) - spill + shed == load - wind, name="balance")

    # capacity: p - dP*u <= 0
    Du = np.zeros((G * T, G * T))
    for g in range(G):
        for t in range(T):
            Du[gt(g, t), gt(g, t)] = dP[g]
    m.constr(p - (Du @ u) <= 0.0, name="capacity")

    # startup definition: st[g,t] >= u[g,t] - u[g,t-1] (u[g,-1] = 0)
    Su = np.zeros((G * T, G * T))
    for g in range(G):
        for t in range(T):
            Su[gt(g, t), gt(g, t)] = 1.0
            if t > 0:
                Su[gt(g, t), gt(g, t - 1)] = -1.0
    m.constr(st - (Su @ u) >= 0.0, name="startup_def")

    # reserve: sum_g Pmax_g u_gt >= (1+r)load_t - wind_t
    Ru = np.zeros((T, G * T))
    for g in range(G):
        for t in range(T):
            Ru[t, gt(g, t)] = fl["pmax"][g]
    m.constr((Ru @ u) >= (1.0 + RESERVE_FRAC) * load - wind, name="reserve")

    cu = np.repeat(fl["noload"], T)
    cst = np.repeat(fl["startup"], T)
    cp = np.repeat(fl["mc"], T)
    m.stage_cost(1, u.dot(cu) + st.dot(cst))
    m.stage_cost(2, p.dot(cp) + shed.sum() * VOLL)
    return m


def make_tree(num_scens):
    names = [f"scen{i}" for i in range(num_scens)]
    return two_stage_tree(names, nonant_names=["u", "st"])


def scenario_denouement(rank, scenario_name, values):
    pass
