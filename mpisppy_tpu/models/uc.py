"""Stochastic unit commitment — the framework's benchmark workhorse.

The reference's UC example (ref. examples/uc/uc_funcs.py, uc_cylinders.py;
paperruns/larger_uc/ up to 1000 wind scenarios) builds egret-based Pyomo
models from data files. This is a self-contained generator of the same
*shape* of problem — two-stage SMIP where first-stage commitment/startup
decisions are nonanticipative and second-stage dispatch responds to a wind
scenario — with deterministic seeded data so results are reproducible:

  min  E_s[ sum_{g,t} (noload_g u_{gt} + mc_g p_{gt} + su_g st_{gt})
            + sum_t VOLL shed_t ]
  s.t. sum_g (Pmin_g u_{gt} + p_{gt}) + wind_t^s - spill_t + shed_t = load_t
       p_{gt} <= (Pmax_g - Pmin_g) u_{gt}
       st_{gt} >= u_{gt} - u_{g,t-1}          (startup definition)
       sum_g Pmax_g u_{gt} >= load_t - wind_t^s + r*load_t   (reserve)
       u, st in [0,1] (integer), p >= 0, shed in [0,load], spill in [0,wind]

Nonants: u and st (commitment schedule), matching the reference's
first-stage variable set.
"""

from __future__ import annotations

import numpy as np

from ..ir.model import Model
from ..ir.tree import two_stage_tree

VOLL = 5000.0          # value of lost load ($/MWh)
RESERVE_FRAC = 0.10


def fleet(num_gens: int, seed: int = 1234):
    """Deterministic generator fleet: a cost-ordered mix from big cheap
    baseload to small expensive peakers."""
    rng = np.random.RandomState(seed)
    frac = np.linspace(0.0, 1.0, num_gens)
    pmax = 50.0 + 400.0 * (1.0 - frac) ** 1.5 + rng.rand(num_gens) * 20.0
    pmin = 0.3 * pmax
    mc = 10.0 + 70.0 * frac ** 1.2 + rng.rand(num_gens) * 5.0   # $/MWh
    noload = 2.0 * pmax * 0.5 + rng.rand(num_gens) * 50.0        # $/h
    startup = 30.0 * pmax + rng.rand(num_gens) * 500.0           # $/start
    return dict(pmax=pmax, pmin=pmin, mc=mc, noload=noload, startup=startup)


def load_profile(num_hours: int, num_gens: int):
    """Diurnal load sized to ~70% of fleet capacity at peak."""
    t = np.arange(num_hours)
    shape = 0.7 + 0.25 * np.sin((t - 6) * 2 * np.pi / 24.0) \
        + 0.05 * np.sin(t * 4 * np.pi / 24.0)
    cap = fleet(num_gens)["pmax"].sum()
    return 0.7 * cap * shape


def wind_scenario(scennum: int, num_hours: int, num_gens: int):
    """Seeded smooth wind trace, ~15% of fleet capacity on average."""
    rng = np.random.RandomState(100000 + scennum)
    cap = fleet(num_gens)["pmax"].sum()
    steps = rng.randn(num_hours) * 0.25
    level = 0.15 + 0.1 * np.cumsum(steps) / np.sqrt(np.arange(1, num_hours + 1))
    return np.clip(level, 0.0, 0.4) * cap


def min_up_down_times(num_gens: int):
    """Per-generator minimum up/down times in hours: big baseload units
    are slow to cycle (8h/8h), peakers fast (1h/1h) — the shape of the
    egret fleet data (ref. examples/uc/uc_funcs.py via egret's
    *_uptime/*_downtime parameters)."""
    frac = np.linspace(0.0, 1.0, num_gens)
    ut = np.maximum(1, np.round(8.0 * (1.0 - frac) ** 1.5)).astype(int)
    return ut, ut.copy()


def t0_fleet_state(num_gens: int, seed: int = 4321):
    """Warm-fleet initial conditions — the UnitOnT0State /
    PowerGeneratedT0 parameter block of the reference's data files
    (ref. examples/uc/2013-05-11/Scenario_1.dat: per-generator signed
    on/off hours at t=0 plus the T0 dispatch level). A cold fleet
    (u[g,-1]=0 everywhere) lets every unit start fresh, which distorts
    early-horizon commitment economics against the instance the
    baselines were earned on (VERDICT r4 #6/missing #3).

    Returns (on0 bool, spent hours in the current state [1..UT/DT], p0
    MW): the baseload-heavy ~55% of the fleet arrives ON partway
    through its min-up window (so remaining-obligation rows BIND),
    the rest OFF partway through min-down."""
    fl = fleet(num_gens)
    ut, dt_ = min_up_down_times(num_gens)
    rng = np.random.RandomState(seed)
    on0 = np.linspace(0.0, 1.0, num_gens) < 0.55
    window = np.where(on0, ut, dt_).astype(int)
    spent = 1 + (np.arange(num_gens) % np.maximum(1, window))
    p0 = np.where(on0, fl["pmin"]
                  + 0.6 * (fl["pmax"] - fl["pmin"]) * rng.rand(num_gens),
                  0.0)
    return on0, spent, p0


def quick_start_set(num_gens: int):
    """The quick-start generator subset (the reference data files'
    ``QuickStart`` parameter, ref. examples/uc/2013-05-11/
    Scenario_1.dat): the smallest/fastest ~20% of the fleet — peakers
    that can be brought online within the hour, so their capacity
    counts toward spinning reserve even when not committed."""
    frac = np.linspace(0.0, 1.0, num_gens)
    return frac >= 0.8


def scenario_creator(scenario_name, num_gens=10, num_hours=24,
                     relax_integrality=True, min_up_down=False,
                     ramping=False, t0_state=False,
                     startup_shutdown_ramps=False,
                     quick_start=False) -> Model:
    """``min_up_down`` adds the Rajan–Takriti turn-on inequalities
    (sum of startups in a UT_g window <= u, and in a DT_g window <=
    1 - u shifted) and ``ramping`` adds second-stage dispatch ramp rows
    |p_t - p_{t-1}| <= r_g — the constraint families that make egret's
    UC a real unit-commitment model rather than a static dispatch
    (ref. examples/uc/uc_funcs.py egret model; both default OFF to keep
    the benchmark instance definition stable).

    ``t0_state`` (r5) threads warm-fleet initial conditions through the
    model the way the reference's data files do (UnitOnT0State /
    PowerGeneratedT0, ref. examples/uc/2013-05-11/Scenario_1.dat):
    the t=0 startup definition sees u[g,-1], remaining min-up/down
    obligations pin the early-horizon commitment bounds (the standard
    lowering of pre-horizon R-T windows), the early min-down rhs uses
    the pre-horizon schedule, and — with ramping on — t=0 ramp rows
    tie first-hour output to PowerGeneratedT0.

    ``startup_shutdown_ramps`` (r5) replaces the symmetric implicit
    allowance (pmin + ramp on every row) with DISTINCT startup and
    shutdown limits (StartupRampLimit / ShutdownRampLimit in the
    reference's parameter block), linear in the existing variables:
        up:   p̄_t − p̄_{t−1} ≤ RU·u_{t−1} + SU·st_t
        down: p̄_{t−1} − p̄_t ≤ SD·u_{t−1} + (RD − SD)·u_t
    with p̄ = pmin·u + p total output; both reduce to the classic
    Carrión–Arroyo rows on the {0,1} commitment patterns."""
    import re
    scennum = int(re.search(r"(\d+)$", scenario_name).group(1))
    fl = fleet(num_gens)
    load = load_profile(num_hours, num_gens)
    wind = wind_scenario(scennum, num_hours, num_gens)
    G, T = num_gens, num_hours
    dP = fl["pmax"] - fl["pmin"]

    on0 = spent0 = p0 = None
    u_lb = np.zeros(G * T)
    u_ub = np.ones(G * T)
    if t0_state:
        on0, spent0, p0 = t0_fleet_state(G)
        ut0, dt0 = min_up_down_times(G)
        if min_up_down:
            # remaining min-up/down obligation at t=0 pins the early
            # commitments — the standard lowering of pre-horizon
            # Rajan–Takriti windows into variable bounds
            for g in range(G):
                if on0[g]:
                    for t in range(min(T, int(ut0[g]) - int(spent0[g]))):
                        u_lb[g * T + t] = 1.0
                else:
                    for t in range(min(T, int(dt0[g]) - int(spent0[g]))):
                        u_ub[g * T + t] = 0.0

    m = Model(scenario_name, sense="min")
    # commitment u[g,t] and startups st[g,t] flattened g-major
    u = m.var("u", G * T, lb=u_lb, ub=u_ub,
              integer=not relax_integrality, stage=1)
    st = m.var("st", G * T, lb=0.0, ub=1.0, integer=not relax_integrality, stage=1)
    p = m.var("p", G * T, lb=0.0, stage=2)
    shed = m.var("shed", T, lb=0.0, ub=load, stage=2)
    spill = m.var("spill", T, lb=0.0, ub=np.maximum(wind, 0.0), stage=2)

    gt = lambda g, t: g * T + t

    # balance rows: one per hour (vectorized via coefficient matrices)
    Bu = np.zeros((T, G * T))
    Bp = np.zeros((T, G * T))
    for g in range(G):
        for t in range(T):
            Bu[t, gt(g, t)] = fl["pmin"][g]
            Bp[t, gt(g, t)] = 1.0
    m.constr((Bu @ u) + (Bp @ p) - spill + shed == load - wind, name="balance")

    # capacity: p - dP*u <= 0
    Du = np.zeros((G * T, G * T))
    for g in range(G):
        for t in range(T):
            Du[gt(g, t), gt(g, t)] = dP[g]
    m.constr(p - (Du @ u) <= 0.0, name="capacity")

    # startup definition: st[g,t] >= u[g,t] - u[g,t-1]; at t=0 the
    # predecessor is the T0 state (u[g,-1] = on0, a constant on the
    # rhs) — cold fleet (0) without t0_state
    Su = np.zeros((G * T, G * T))
    rhs_su = np.zeros(G * T)
    for g in range(G):
        for t in range(T):
            Su[gt(g, t), gt(g, t)] = 1.0
            if t > 0:
                Su[gt(g, t), gt(g, t - 1)] = -1.0
            elif t0_state and on0[g]:
                rhs_su[gt(g, 0)] = -1.0
    m.constr(st - (Su @ u) >= rhs_su, name="startup_def")

    # reserve: sum_g Pmax_g u_gt >= (1+r)load_t - wind_t. With
    # ``quick_start``, the quick-start subset's capacity counts toward
    # reserve regardless of commitment (they can come online within
    # the hour — the reference's QuickStart parameter semantics,
    # ref. examples/uc/2013-05-11/Scenario_1.dat); their constant
    # contribution moves to the rhs
    qs = quick_start_set(G) if quick_start else np.zeros(G, bool)
    Ru = np.zeros((T, G * T))
    for g in range(G):
        if qs[g]:
            continue
        for t in range(T):
            Ru[t, gt(g, t)] = fl["pmax"][g]
    qs_cap = float(fl["pmax"][qs].sum())
    m.constr((Ru @ u) >= (1.0 + RESERVE_FRAC) * load - wind - qs_cap,
             name="reserve")

    if min_up_down:
        # Rajan–Takriti window inequalities on the startup indicators:
        #   sum_{tau in (t-UT_g, t]} st[g,tau] <= u[g,t]        (min up)
        #   sum_{tau in (t-DT_g, t]} st[g,tau] <= 1 - u[g,t-DT] (min down)
        ut, dt_ = min_up_down_times(G)

        def u_past(g, tau):
            """Pre-horizon commitment at hour tau < 0 under the T0
            state: the unit has held its current state for spent0[g]
            hours, and (by construction) the opposite state before."""
            if not t0_state:
                return 0.0
            if tau >= -int(spent0[g]):
                return 1.0 if on0[g] else 0.0
            return 0.0 if on0[g] else 1.0

        Mu = np.zeros((G * T, G * T))   # window-sum of st
        Uu = np.zeros((G * T, G * T))   # u[g,t]
        Md = np.zeros((G * T, G * T))
        Ud = np.zeros((G * T, G * T))
        rhs_d = np.zeros(G * T)
        for g in range(G):
            for t in range(T):
                Uu[gt(g, t), gt(g, t)] = 1.0
                for tau in range(max(0, t - int(ut[g]) + 1), t + 1):
                    Mu[gt(g, t), gt(g, tau)] = 1.0
                t0 = t - int(dt_[g])
                for tau in range(max(0, t0 + 1), t + 1):
                    Md[gt(g, t), gt(g, tau)] = 1.0
                if t0 >= 0:
                    Ud[gt(g, t), gt(g, t0)] = 1.0
                    rhs_d[gt(g, t)] = 1.0
                else:
                    # pre-horizon u[g,t0] is a constant: rhs absorbs it
                    rhs_d[gt(g, t)] = 1.0 - u_past(g, t0)
        m.constr((Mu @ st) - (Uu @ u) <= 0.0, name="min_uptime")
        m.constr((Md @ st) + (Ud @ u) <= rhs_d, name="min_downtime")

    if ramping:
        # ramp rows on TOTAL output p̄ = pmin_g·u + p (a pure-p ramp
        # would let commitment flips jump real output by pmin with no
        # limit). Classic symmetric form: allowance ramp + pmin on
        # every row (egret-style startup ramp relaxation). With
        # startup_shutdown_ramps, DISTINCT startup/shutdown limits
        # enter linearly through u/st (see the docstring; RU/RD the
        # hot ramp, SU/SD the start/stop allowances — the
        # StartupRampLimit/ShutdownRampLimit block of the reference's
        # data files, ref. examples/uc/2013-05-11/Scenario_1.dat)
        ramp = 0.5 * dP + 0.1 * fl["pmax"]
        # validity of the down row's linear form needs SD − RD ≤ pmin
        # (else the startup pattern would get a spurious output floor
        # above the pmin the capacity rows already imply): holds here
        # since SD − RD = pmin − ½·ramp < pmin
        su_lim = fl["pmin"] + 0.5 * ramp      # startup: reach pmin + ½RU
        sd_lim = fl["pmin"] + 0.5 * ramp      # shutdown allowance
        if not startup_shutdown_ramps:
            # rows run t = 0..T-1 when the T0 dispatch anchors t=0
            # (p̄[g,-1] = PowerGeneratedT0 moves to the rhs with the
            # symmetric allowance), t = 1..T-1 otherwise
            tlo = 0 if t0_state else 1
            nr = G * (T - tlo)
            Rp = np.zeros((nr, G * T))
            Rut = np.zeros((nr, G * T))
            rr_up = np.zeros(nr)
            rr_dn = np.zeros(nr)
            for g in range(G):
                for t in range(tlo, T):
                    r = g * (T - tlo) + (t - tlo)
                    Rp[r, gt(g, t)] = 1.0
                    Rut[r, gt(g, t)] = fl["pmin"][g]
                    allow = ramp[g] + fl["pmin"][g]
                    if t > 0:
                        Rp[r, gt(g, t - 1)] = -1.0
                        Rut[r, gt(g, t - 1)] = -fl["pmin"][g]
                        rr_up[r] = allow
                        rr_dn[r] = -allow
                    else:
                        rr_up[r] = allow + p0[g]
                        rr_dn[r] = -allow + p0[g]
            m.constr((Rp @ p) + (Rut @ u) <= rr_up, name="ramp_up")
            m.constr((Rp @ p) + (Rut @ u) >= rr_dn, name="ramp_down")
        else:
            # rows run t = 0..T-1 when the T0 dispatch anchors t=0
            # (p̄[g,-1] = PowerGeneratedT0, a constant on the rhs),
            # t = 1..T-1 otherwise
            tlo = 0 if t0_state else 1
            nr = G * (T - tlo)
            Ru_p = np.zeros((nr, G * T))      # up rows: coeffs on p
            Ru_u = np.zeros((nr, G * T))      # up rows: coeffs on u
            Ru_st = np.zeros((nr, G * T))     # up rows: coeffs on st
            rr_u = np.zeros(nr)
            Rd_p = np.zeros((nr, G * T))
            Rd_u = np.zeros((nr, G * T))
            rr_d = np.zeros(nr)
            pmin = fl["pmin"]
            for g in range(G):
                for t in range(tlo, T):
                    r = g * (T - tlo) + (t - tlo)
                    # up: p̄_t − p̄_{t−1} − RU·u_{t−1} − SU·st_t ≤ 0
                    Ru_p[r, gt(g, t)] = 1.0
                    Ru_u[r, gt(g, t)] = pmin[g]
                    Ru_st[r, gt(g, t)] = -su_lim[g]
                    # down: p̄_{t−1} − p̄_t − SD·u_{t−1} − (RD−SD)·u_t ≤ 0
                    Rd_p[r, gt(g, t)] = -1.0
                    Rd_u[r, gt(g, t)] = -pmin[g] - (ramp[g] - sd_lim[g])
                    if t > 0:
                        Ru_p[r, gt(g, t - 1)] = -1.0
                        Ru_u[r, gt(g, t - 1)] = -pmin[g] - ramp[g]
                        Rd_p[r, gt(g, t - 1)] = 1.0
                        Rd_u[r, gt(g, t - 1)] = pmin[g] - sd_lim[g]
                    else:
                        # T0 anchors: p̄_{-1} = p0_g, u_{-1} = on0_g
                        rr_u[r] = p0[g] + ramp[g] * float(on0[g])
                        rr_d[r] = -p0[g] + sd_lim[g] * float(on0[g])
            m.constr((Ru_p @ p) + (Ru_u @ u) + (Ru_st @ st) <= rr_u,
                     name="ramp_up")
            m.constr((Rd_p @ p) + (Rd_u @ u) <= rr_d, name="ramp_down")

    cu = np.repeat(fl["noload"], T)
    cst = np.repeat(fl["startup"], T)
    cp = np.repeat(fl["mc"], T)
    m.stage_cost(1, u.dot(cu) + st.dot(cst))
    m.stage_cost(2, p.dot(cp) + shed.sum() * VOLL)
    return m


def scenario_vector_patch(scenario_name, num_gens=10, num_hours=24,
                          relax_integrality=True, min_up_down=False,
                          ramping=False, t0_state=False,
                          startup_shutdown_ramps=False,
                          quick_start=False):
    """Structure-shared fast path for build_batch(vector_patch=...): the
    ONLY scenario-dependent data in a UC scenario is the wind trace,
    which enters the balance rhs, the reserve rhs, and the spill upper
    bound. Rebuilding the (m, n) constraint matrix per scenario at
    reference scale (~90 gens × 48 h, ref. examples/uc/2013-05-11)
    costs minutes of host time and gigabytes per scenario; this patch
    costs three vectors. Drift against scenario_creator is caught by
    build_batch's scenario-0 identity assertion plus
    tests/test_models.py::test_uc_vector_patch_matches_creator."""
    import re
    scennum = int(re.search(r"(\d+)$", scenario_name).group(1))
    load = load_profile(num_hours, num_gens)
    wind = wind_scenario(scennum, num_hours, num_gens)
    rhs_reserve = (1.0 + RESERVE_FRAC) * load - wind
    if quick_start:
        fl = fleet(num_gens)
        rhs_reserve = rhs_reserve \
            - float(fl["pmax"][quick_start_set(num_gens)].sum())
    return {("l", "balance"): load - wind,
            ("u", "balance"): load - wind,
            ("l", "reserve"): rhs_reserve,
            ("ub", "spill"): np.maximum(wind, 0.0)}


def scenario_synth_spec(template, seed=0, num_gens=10, num_hours=24,
                        relax_integrality=True, min_up_down=False,
                        ramping=False, t0_state=False,
                        startup_shutdown_ramps=False, quick_start=False):
    """The UC-family synth spec (stream/synth.py, doc/streaming.md):
    the same three wind touch points as ``scenario_vector_patch``
    (balance rhs, reserve rhs, spill upper bound), but the wind trace
    is a jax-expressible seeded random walk — same shape discipline as
    ``wind_scenario`` (smooth ~15%-of-capacity walk, clipped to
    [0, 40%]) with jax's threefry replacing the numpy RandomState the
    device generator cannot reproduce. A synth-UC scenario is therefore
    a DIFFERENT instance from the RandomState one at the same id —
    deliberately: the spec is the single source of the family's data,
    and resident/streamed/synthesized runs of the synth family are
    identical by construction."""
    import jax
    import jax.numpy as jnp

    from ..stream.synth import SynthField, SynthSpec

    T = num_hours
    load = jnp.asarray(load_profile(num_hours, num_gens))
    cap = float(fleet(num_gens)["pmax"].sum())
    qs_cap = float(fleet(num_gens)["pmax"][
        quick_start_set(num_gens)].sum()) if quick_start else 0.0
    inv_sqrt = 1.0 / jnp.sqrt(jnp.arange(1, T + 1, dtype=jnp.float64))

    def fn(key):
        steps = jax.random.normal(key, (T,)) * 0.25
        wind = jnp.clip(0.15 + 0.1 * jnp.cumsum(steps) * inv_sqrt,
                        0.0, 0.4) * cap
        bal = load - wind
        res = (1.0 + RESERVE_FRAC) * load - wind - qs_cap
        return bal, bal, res, wind

    bal = template.con_slices["balance"]
    resv = template.con_slices["reserve"]
    spill = template.var_slices["spill"]
    return SynthSpec(
        seed=int(seed),
        fields=(SynthField("l", bal.start, bal.stop),
                SynthField("u", bal.start, bal.stop),
                SynthField("l", resv.start, resv.stop),
                SynthField("ub", spill.start, spill.stop)),
        fn=fn)


def make_tree(num_scens):
    names = [f"scen{i}" for i in range(num_scens)]
    return two_stage_tree(names, nonant_names=["u", "st"])


def scenario_denouement(rank, scenario_name, values):
    pass
