"""SIZES: two-stage production-sizing MIP (Løkketangen & Woodruff 1996).

Same problem data as the reference's test fixture (ref. mpisppy/tests/
examples/sizes/ReferenceModel.py:24-200 and SIZES3/SIZES10 .dat files):
10 product sizes, capacity 200000, setup cost 453, unit production cost
0.748 + 0.0104·(i−1), cut-down cost 0.008; scenario s scales the
second-stage demands by a multiplier (3-scenario set: {0.7, 1.0, 1.3};
10-scenario set: {0.5..1.5}\\{1.0}), equally likely.

First-stage nonants are NumProducedFirstStage and NumUnitsCutFirstStage
(ref. tests/examples/sizes/sizes.py:26-27 varlist).
"""

from __future__ import annotations

import re

import numpy as np

from ..ir.model import Model
from ..ir.tree import two_stage_tree

NUM_SIZES = 10
CAPACITY = 200000.0
DEMANDS_FIRST = np.array([2500, 7500, 12500, 10000, 35000,
                          25000, 15000, 12500, 12500, 5000], dtype=np.float64)
UNIT_COST = 0.748 + 0.0104 * np.arange(NUM_SIZES)
SETUP_COST = np.full(NUM_SIZES, 453.0)
UNIT_REDUCTION_COST = 0.008

MULT3 = [0.7, 1.0, 1.3]
MULT10 = [0.5, 0.6, 0.7, 0.8, 0.9, 1.1, 1.2, 1.3, 1.4, 1.5]

# (i, j) pairs with i >= j (0-based): units of size i cut down to size j
PAIRS = [(i, j) for i in range(NUM_SIZES) for j in range(i + 1)]
NP = len(PAIRS)
# D_cut[j, p] = 1 iff pair p supplies size j;  I_cut[i, p] = 1 iff pair p
# consumes inventory of size i;  offdiag[p] = 1 iff i != j (cut cost)
D_CUT = np.zeros((NUM_SIZES, NP))
I_CUT = np.zeros((NUM_SIZES, NP))
OFFDIAG = np.zeros(NP)
for p, (i, j) in enumerate(PAIRS):
    D_CUT[j, p] = 1.0
    I_CUT[i, p] = 1.0
    if i != j:
        OFFDIAG[p] = 1.0


def demand_multiplier(scennum: int, scenario_count: int) -> float:
    mults = MULT3 if scenario_count == 3 else MULT10
    return mults[scennum % len(mults)]


def scenario_creator(scenario_name, scenario_count=3) -> Model:
    """ref. tests/examples/sizes/sizes.py:7 (scenario_count in {3, 10})."""
    if scenario_count not in (3, 10):
        raise ValueError("sizes scenario count must be 3 or 10")
    scennum = int(re.search(r"(\d+)$", scenario_name).group(1))
    mults = MULT3 if scenario_count == 3 else MULT10
    d2 = DEMANDS_FIRST * demand_multiplier(scennum, scenario_count)

    # Demand-implied bound strengthening (valid tightening; the optimum is
    # unchanged — producing or cutting beyond total possible demand only
    # adds cost). Size i can only supply sizes j <= i, so the useful
    # production of size i is capped by the cumulative demand of sizes
    # <= i over both stages; a cut pair (i, j) is capped by size j's
    # demand. This replaces the reference's loose CAPACITY big-M with a
    # per-size big-M that HiGHS's B&B prunes orders of magnitude faster.
    # The 1.5 slack factor keeps the caps from sitting exactly on the
    # covering rows (exactly-tight boxes make the LP degenerate, which
    # stalls the first-order ADMM kernel); B&B pruning only needs the
    # order of magnitude.
    SLACK = 1.5
    d2_max = DEMANDS_FIRST * max(mults)
    ub_made1 = np.minimum(CAPACITY, SLACK * np.cumsum(DEMANDS_FIRST + d2_max))
    ub_made2 = np.minimum(CAPACITY, SLACK * np.cumsum(d2))
    ub_cut1 = SLACK * np.array([DEMANDS_FIRST[j] for (_, j) in PAIRS])
    ub_cut2 = SLACK * np.array([d2[j] for (_, j) in PAIRS])

    m = Model(scenario_name, sense="min")
    produce1 = m.var("ProduceSizeFirstStage", NUM_SIZES, lb=0.0, ub=1.0,
                     integer=True, stage=1)
    produce2 = m.var("ProduceSizeSecondStage", NUM_SIZES, lb=0.0, ub=1.0,
                     integer=True, stage=2)
    made1 = m.var("NumProducedFirstStage", NUM_SIZES, lb=0.0, ub=ub_made1,
                  integer=True, stage=1)
    made2 = m.var("NumProducedSecondStage", NUM_SIZES, lb=0.0, ub=ub_made2,
                  integer=True, stage=2)
    cut1 = m.var("NumUnitsCutFirstStage", NP, lb=0.0, ub=ub_cut1,
                 integer=True, stage=1)
    cut2 = m.var("NumUnitsCutSecondStage", NP, lb=0.0, ub=ub_cut2,
                 integer=True, stage=2)

    # demand satisfaction (ref. ReferenceModel.py:97-104)
    m.constr(D_CUT @ cut1 >= DEMANDS_FIRST, name="DemandSatisfiedFirstStage")
    m.constr(D_CUT @ cut2 >= d2, name="DemandSatisfiedSecondStage")
    # big-M setup enforcement (ref. :107-115), with the tightened M
    m.constr(made1 - ub_made1 * produce1 <= 0.0,
             name="EnforceProductionBinaryFirstStage")
    m.constr(made2 - ub_made2 * produce2 <= 0.0,
             name="EnforceProductionBinarySecondStage")
    # per-stage capacity (ref. :118-125)
    m.constr(made1.sum() <= CAPACITY, name="EnforceCapacityLimitFirstStage")
    m.constr(made2.sum() <= CAPACITY, name="EnforceCapacityLimitSecondStage")
    # inventory conservation (ref. :128-141): cuts from size i can't exceed
    # what has been produced at size i so far
    m.constr(I_CUT @ cut1 - made1 <= 0.0, name="EnforceInventoryFirstStage")
    m.constr((I_CUT @ cut1) + (I_CUT @ cut2) - made1 - made2 <= 0.0,
             name="EnforceInventorySecondStage")

    m.stage_cost(1, produce1.dot(SETUP_COST) + made1.dot(UNIT_COST)
                 + cut1.dot(UNIT_REDUCTION_COST * OFFDIAG))
    m.stage_cost(2, produce2.dot(SETUP_COST) + made2.dot(UNIT_COST)
                 + cut2.dot(UNIT_REDUCTION_COST * OFFDIAG))
    return m


def make_tree(num_scens=3):
    names = [f"Scenario{i + 1}" for i in range(num_scens)]
    return two_stage_tree(names, nonant_names=["NumProducedFirstStage",
                                               "NumUnitsCutFirstStage"])


def _rho_setter(batch, rho_factor=0.001):
    """Cost-proportional rho (ref. tests/examples/sizes/sizes.py:37-57):
    production slots get RF·unit_cost, cut slots RF·reduction_cost."""
    K = batch.K
    rho = np.empty(K)
    rho[:NUM_SIZES] = rho_factor * UNIT_COST
    rho[NUM_SIZES:] = rho_factor * UNIT_REDUCTION_COST
    return rho


def id_fix_list_fct(batch):
    """Fixer spec matching the reference's iterk tuples (ref. sizes.py:62-98:
    th=0.2, nb=3, lb=1, ub=2 on all first-stage quantity vars)."""
    K = batch.K
    return {"tol": np.full(K, 0.2),
            "nb": np.full(K, 3, dtype=np.int64),
            "lb": np.full(K, 1, dtype=np.int64),
            "ub": np.full(K, 2, dtype=np.int64)}


def scenario_denouement(rank, scenario_name, values):
    pass
