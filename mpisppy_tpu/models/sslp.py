"""SSLP: two-stage stochastic server location (Ntaimo & Sen).

Same problem class as the reference's sslp example (ref. examples/sslp/
sslp.py:18-110, which instantiates an abstract Pyomo model from
sslp_<m>_<n>_<s> .dat files): first stage opens servers (binary y_i, cost
c_i), second stage assigns present clients to open servers (x_ij) for
revenue r_ij, subject to server capacity u; client presence h_j(ξ) is the
stochastic element. Instances here are generated from a seeded RNG in the
published SSLP data ranges instead of .dat files, scalable via
(num_servers, num_clients).
"""

from __future__ import annotations

import re

import numpy as np

from ..ir.model import Model
from ..ir.tree import two_stage_tree


def instance_data(num_servers=5, num_clients=25, base_seed=1):
    """Instance-level (scenario-independent) data, seeded like the SSLP
    generators: c_i ~ U[40,80], client demand d_j ~ U[1,10], revenue
    r_ij ~ U[0,25], capacity scaled so ~half the servers suffice."""
    rng = np.random.RandomState(base_seed)
    c = rng.uniform(40.0, 80.0, size=num_servers)
    d = rng.uniform(1.0, 10.0, size=num_clients)
    r = rng.uniform(0.0, 25.0, size=(num_servers, num_clients))
    u = 2.0 * d.sum() / num_servers
    return {"c": c, "d": d, "r": r, "u": u}


def client_presence(scennum, num_clients, presence_prob=0.5):
    """h_j(ξ) ~ Bernoulli(presence_prob), seeded per scenario (the SSLP
    uncertainty model: a client either shows up or doesn't)."""
    rng = np.random.RandomState(1000 + scennum)
    h = (rng.rand(num_clients) < presence_prob).astype(np.float64)
    if not h.any():
        h[rng.randint(num_clients)] = 1.0
    return h


def scenario_creator(scenario_name, num_servers=5, num_clients=25,
                     presence_prob=0.5, base_seed=1) -> Model:
    scennum = int(re.search(r"(\d+)$", scenario_name).group(1))
    data = instance_data(num_servers, num_clients, base_seed)
    h = client_presence(scennum, num_clients, presence_prob)
    nS, nC = num_servers, num_clients

    m = Model(scenario_name, sense="min")
    y = m.var("OpenServer", nS, lb=0.0, ub=1.0, integer=True, stage=1)
    x = m.var("Assign", nS * nC, lb=0.0, ub=1.0, integer=True, stage=2)

    # each present client assigned exactly once (ref. sslp abstract model's
    # client satisfaction constraint); absent clients: x forced to 0
    assign_of_client = np.zeros((nC, nS * nC))
    for j in range(nC):
        assign_of_client[j, j::nC] = 1.0
    m.constr(assign_of_client @ x == h, name="ClientAssignment")

    # server capacity with open-gate: sum_j d_j x_ij <= u * y_i
    demand_on_server = np.zeros((nS, nS * nC))
    for i in range(nS):
        demand_on_server[i, i * nC:(i + 1) * nC] = data["d"]
    gate = -data["u"] * np.eye(nS)
    m.constr((demand_on_server @ x) + (gate @ y) <= 0.0,
             name="ServerCapacity")

    m.stage_cost(1, y.dot(data["c"]))
    m.stage_cost(2, x.dot(-data["r"].reshape(-1)))   # revenue: negative cost
    return m


def make_tree(num_scens, **_):
    names = [f"Scenario{i}" for i in range(num_scens)]
    return two_stage_tree(names, nonant_names=["OpenServer"])


def scenario_denouement(rank, scenario_name, values):
    pass
