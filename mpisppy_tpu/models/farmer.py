"""Farmer: the canonical scalable 2-stage stochastic LP/MIP.

Same mathematical problem and scenario-generation scheme as the reference
(ref. mpisppy/tests/examples/farmer.py:23-225, examples/farmer/farmer.py):
Birge & Louveaux's farmer with 3·crops_multiplier crops; scenario i maps to
{below, average, above}-average yields by i mod 3, and scenario groups
beyond the first add U[0,1) noise from a RandomState seeded with the
scenario number — reproduced exactly so objective values are comparable.
Expressed in the mpisppy_tpu DSL instead of Pyomo.
"""

from __future__ import annotations

import re

import numpy as np

from ..ir.model import Model
from ..ir.tree import two_stage_tree

CROPS = ["WHEAT", "CORN", "SUGAR_BEETS"]
BASE_YIELD = {
    "BelowAverage": np.array([2.0, 2.4, 16.0]),
    "Average": np.array([2.5, 3.0, 20.0]),
    "AboveAverage": np.array([3.0, 3.6, 24.0]),
}
PRICE_QUOTA = np.array([100000.0, 100000.0, 6000.0])
SUBQUOTA_PRICE = np.array([170.0, 150.0, 36.0])
SUPERQUOTA_PRICE = np.array([0.0, 0.0, 10.0])
CATTLE_FEED = np.array([200.0, 240.0, 0.0])
PURCHASE_PRICE = np.array([238.0, 210.0, 100000.0])
PLANTING_COST = np.array([150.0, 230.0, 260.0])
BASENAMES = ["BelowAverage", "Average", "AboveAverage"]


def extract_num(name: str) -> int:
    """Scenario number scraped off the right of the name (ref. sputils.extract_num)."""
    return int(re.search(r"(\d+)$", name).group(1))


def scenario_yields(scennum: int, crops_multiplier: int = 1) -> np.ndarray:
    basenum = scennum % 3
    groupnum = scennum // 3
    y = np.tile(BASE_YIELD[BASENAMES[basenum]], crops_multiplier)
    if groupnum != 0:
        # same RNG discipline as the reference: RandomState seeded with the
        # scenario number, one rand() per crop in declaration order
        stream = np.random.RandomState(scennum)
        y = y + stream.rand(3 * crops_multiplier)
    return y


def scenario_creator(scenario_name, use_integer=False, crops_multiplier=1,
                     sense="min") -> Model:
    scennum = extract_num(scenario_name)
    cm = crops_multiplier
    nC = 3 * cm
    y = scenario_yields(scennum, cm)
    total_acreage = 500.0 * cm

    tile = lambda a: np.tile(a, cm)
    m = Model(scenario_name, sense="min")
    acres = m.var("DevotedAcreage", nC, lb=0.0, ub=total_acreage,
                  integer=use_integer, stage=1)
    sell_sub = m.var("QuantitySubQuotaSold", nC, lb=0.0, ub=tile(PRICE_QUOTA), stage=2)
    sell_super = m.var("QuantitySuperQuotaSold", nC, lb=0.0, stage=2)
    buy = m.var("QuantityPurchased", nC, lb=0.0, stage=2)

    m.constr(acres.sum() <= total_acreage, name="ConstrainTotalAcreage")
    m.constr(acres * y + buy - sell_sub - sell_super >= tile(CATTLE_FEED),
             name="EnforceCattleFeedRequirement")
    m.constr(sell_sub + sell_super - acres * y <= 0.0, name="LimitAmountSold")

    sign = 1.0 if sense == "min" else -1.0
    m.stage_cost(1, sign * acres.dot(tile(PLANTING_COST)))
    m.stage_cost(2, sign * (buy.dot(tile(PURCHASE_PRICE))
                            - sell_sub.dot(tile(SUBQUOTA_PRICE))
                            - sell_super.dot(tile(SUPERQUOTA_PRICE))))
    return m


def scenario_synth_spec(template, seed=0, use_integer=False,
                        crops_multiplier=1, sense="min",
                        feed_spread=0.1):
    """The farmer-family randomness-in-rhs synth spec (stream/synth.py,
    doc/streaming.md): yields are pinned at the template scenario's
    (shared constraint matrix — the chunked/streamed representation
    needs one A), and the scenario randomness moves to the cattle-feed
    REQUIREMENT rhs instead: scenario s demands
    ``CATTLE_FEED * (1 + feed_spread * (2u - 1))`` with
    ``u ~ U[0,1)^crops`` drawn from ``fold_in(PRNGKey(seed), s)`` —
    random second-stage demand, the classic farmer variant whose
    randomness the rhs can carry. Zero-requirement crops (sugar beets)
    keep a zero rhs exactly (the spread multiplies the base).

    The generator is pure jax, so the synthesized source manufactures
    the same values in-kernel that :func:`~mpisppy_tpu.stream.synth
    .materialize` stacks for the resident/streamed twins — equivalence
    by construction."""
    import jax
    import jax.numpy as jnp

    from ..stream.synth import SynthField, SynthSpec

    sl = template.con_slices["EnforceCattleFeedRequirement"]
    base = jnp.asarray(np.tile(CATTLE_FEED, crops_multiplier))
    spread = float(feed_spread)

    def fn(key):
        u = jax.random.uniform(key, base.shape)
        return (base * (1.0 + spread * (2.0 * u - 1.0)),)

    return SynthSpec(seed=int(seed),
                     fields=(SynthField("l", sl.start, sl.stop),),
                     fn=fn)


def make_tree(num_scens, crops_multiplier=1):
    names = [f"scen{i}" for i in range(num_scens)]
    return two_stage_tree(names, nonant_names=["DevotedAcreage"])


def scenario_denouement(rank, scenario_name, values):
    pass
