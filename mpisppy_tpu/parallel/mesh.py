"""Device-mesh scenario parallelism.

The reference shards scenario *objects* over MPI ranks and Allreduces the
per-node x̄/x̄² vectors (ref. mpisppy/spbase.py:172 _calculate_scenario_ranks,
phbase.py:196-201). Here the scenario axis of every batch tensor is sharded
over a 1-D `jax.sharding.Mesh` axis ("scen"); the PH step is an ordinary
jitted function, and GSPMD turns the membership matmuls of
SPBase.compute_xbar (B_tᵀ(p⊙x) followed by B_t @ ...) into the
all-reduce/all-gather collectives that ride the ICI — the direct analog of
the reference's per-tree-node comm.Allreduce, chosen by the compiler
instead of hand-written.

Node contiguity (ScenarioTree.validate) guarantees that multistage
sub-node reductions touch contiguous mesh slices, minimizing cross-slice
traffic — the same property the reference engineers into its scenario->rank
map (ref. sputils.py:635-659).

Scenario counts that don't divide the mesh are padded with zero-probability
copies of the last scenario (probability renormalization is a no-op since
the pads carry p=0; xbar membership matmuls are probability-weighted, so
pads contribute nothing).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SCEN_AXIS = "scen"


def make_mesh(n_devices=None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SCEN_AXIS,))


def scenario_sharding(mesh: Mesh, rank: int = 1) -> NamedSharding:
    """Sharding that splits the leading (scenario) axis, replicates the rest."""
    spec = P(SCEN_AXIS, *([None] * (rank - 1)))
    return NamedSharding(mesh, spec)


def shard_arrays(mesh: Mesh, arrays: dict[str, jax.Array]) -> dict[str, jax.Array]:
    """device_put each (S, ...) array with the scenario axis sharded."""
    out = {}
    for k, v in arrays.items():
        out[k] = jax.device_put(v, scenario_sharding(mesh, v.ndim))
    return out


def replicated_sharding(mesh: Mesh, rank: int) -> NamedSharding:
    """Fully-replicated placement on the mesh — the 'home' placement the
    pipelined chunk driver returns spread-solve outputs to, so they can
    mix with the engine's GSPMD-sharded reduction inputs (a single-device
    commitment would refuse to colocate with mesh-committed arrays)."""
    return NamedSharding(mesh, P(*([None] * rank)))


def spread_devices(mesh=None):
    """Device list for round-robin CHUNK spreading (core/ph pipelined
    dispatch), or None when there is nothing to spread over. Unlike the
    GSPMD scenario sharding above — which partitions ONE batched solve
    across the mesh — chunk spreading places whole microbatch solves on
    single devices with explicit device_put, turning the host-looped
    sequential chunk chain into ~ceil(n_chunks/n_dev) concurrent waves.
    The two compose: the mesh keeps the reductions collective while the
    chunk solves ride per-device execution streams."""
    if mesh is None:
        return None
    devs = list(np.asarray(mesh.devices).flat)
    return devs if len(devs) > 1 else None


def put_chunk(tree, device):
    """device_put a pytree (QPData/QPFactors/QPState/arrays) onto one
    device. Arrays already committed there pass through without a copy,
    so per-iteration re-pinning of resident chunk states is free."""
    return jax.device_put(tree, device)


def colocate(parts):
    """Normalize a list of arrays onto one placement (the first part's
    device) when chunk spreading left them committed to different
    devices — the shared precondition of jnp.stack/concatenate over
    per-chunk results. Single-placement inputs pass through untouched."""
    if len({tuple(sorted(map(str, p.devices()))) for p in parts}) <= 1:
        return parts
    dev = next(iter(parts[0].devices()))
    return [jax.device_put(p, dev) for p in parts]


def pad_batch_for_mesh(batch, n_shards: int):
    """Pad a ScenarioBatch to a multiple of n_shards scenarios with
    zero-probability copies of the last scenario. Returns (batch, S_orig)."""
    S = batch.S
    rem = (-S) % n_shards
    if rem == 0:
        return batch, S
    import dataclasses

    def pad(a):
        a = np.asarray(a)
        return np.concatenate([a, np.repeat(a[-1:], rem, axis=0)], axis=0)

    tree = batch.tree
    from ..ir.tree import ScenarioTree
    new_tree = ScenarioTree(
        scen_names=tree.scen_names + [f"_pad{i}" for i in range(rem)],
        node_paths=np.concatenate([tree.node_path,
                                   np.repeat(tree.node_path[-1:], rem, axis=0)]),
        nodes_per_stage=tree.nodes_per_stage,
        nonant_names_per_stage=tree.nonant_names_per_stage,
        probabilities=np.concatenate([tree.probabilities, np.zeros(rem)]),
    )
    return dataclasses.replace(
        batch, tree=new_tree,
        c=pad(batch.c), c0=pad(batch.c0), P_diag=pad(batch.P_diag),
        A=batch.A if batch.shared_A else pad(batch.A),
        l=pad(batch.l), u=pad(batch.u),
        lb=pad(batch.lb), ub=pad(batch.ub),
        c_stage=pad(batch.c_stage), c0_stage=pad(batch.c0_stage),
        prob=new_tree.probabilities.copy(),
    ), S
