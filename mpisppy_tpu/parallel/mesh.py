"""Device-mesh scenario parallelism.

The reference shards scenario *objects* over MPI ranks and Allreduces the
per-node x̄/x̄² vectors (ref. mpisppy/spbase.py:172 _calculate_scenario_ranks,
phbase.py:196-201). Here the scenario axis of every batch tensor is sharded
over a 1-D `jax.sharding.Mesh` axis ("scen"); the PH step is an ordinary
jitted function, and GSPMD turns the membership matmuls of
SPBase.compute_xbar (B_tᵀ(p⊙x) followed by B_t @ ...) into the
all-reduce/all-gather collectives that ride the ICI — the direct analog of
the reference's per-tree-node comm.Allreduce, chosen by the compiler
instead of hand-written.

Node contiguity (ScenarioTree.validate) guarantees that multistage
sub-node reductions touch contiguous mesh slices, minimizing cross-slice
traffic — the same property the reference engineers into its scenario->rank
map (ref. sputils.py:635-659).

Scenario counts that don't divide the mesh are padded with zero-probability
copies of the last scenario (probability renormalization is a no-op since
the pads carry p=0; xbar membership matmuls are probability-weighted, so
pads contribute nothing).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SCEN_AXIS = "scen"


def make_mesh(n_devices=None, devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SCEN_AXIS,))


def scenario_sharding(mesh: Mesh, rank: int = 1) -> NamedSharding:
    """Sharding that splits the leading (scenario) axis, replicates the rest."""
    spec = P(SCEN_AXIS, *([None] * (rank - 1)))
    return NamedSharding(mesh, spec)


def shard_arrays(mesh: Mesh, arrays: dict[str, jax.Array]) -> dict[str, jax.Array]:
    """device_put each (S, ...) array with the scenario axis sharded."""
    out = {}
    for k, v in arrays.items():
        out[k] = jax.device_put(v, scenario_sharding(mesh, v.ndim))
    return out


def replicated_sharding(mesh: Mesh, rank: int) -> NamedSharding:
    """Fully-replicated placement on the mesh (per-leaf; batch shipping
    of shared operands in core/spbase uses the same spec inline)."""
    return NamedSharding(mesh, P(*([None] * rank)))


def local_chunk_layout(shard_rows: int, chunk: int) -> tuple[int, int]:
    """(n_chunks, lc) for a per-device shard of ``shard_rows`` scenarios
    under the ``subproblem_chunk`` per-device microbatch bound: lc is
    rounded so n_chunks · lc covers the shard with the pad below one
    chunk-row per device. The SINGLE source of this formula — both the
    construction-time mesh padding (core/spbase) and the runtime chunk
    staging (core/ph._local_chunk) derive from it, and chunk_layout's
    "lc divides shard" invariant holds because the map is idempotent
    (re-applying it to n_chunks·lc returns the same lc)."""
    n_chunks = -(-shard_rows // int(chunk))
    return n_chunks, -(-shard_rows // n_chunks)


def colocate(parts):
    """Normalize a list of arrays onto one placement (the first part's
    device) when callers hand in arrays committed to different devices
    — the shared precondition of jnp.stack/concatenate. Same-placement
    inputs (the common case: single-device chunk states, or sharded
    states that all carry the mesh placement) pass through untouched."""
    if len({tuple(sorted(map(str, p.devices()))) for p in parts}) <= 1:
        return parts
    dev = next(iter(parts[0].devices()))
    return [jax.device_put(p, dev) for p in parts]


class ShardedScenarioOps:
    """Explicit-collective scenario-axis operations over the "scen" mesh
    axis — the SURVEY §5.7/§5.8 mapping made literal instead of left to
    GSPMD's partitioner:

    - ``xbar``/``combine``: Compute_Xbar, Update_W and the scaled-L1
      convergence as LOCAL segment-sums over the tree-node index
      followed by one ``psum`` over the named axis per stage — the
      subgroup reduction over axis slices for multistage trees (a node's
      scenarios occupy contiguous index ranges, so its partial sums are
      nonzero only on the mesh slice that owns them; the psum of the
      (N_t, k_t) node table IS the per-node Allreduce of the reference,
      ref. phbase.py:196-201). O(S·k) work replaces the O(S·N·k)
      membership matmuls — at 10k+ scenarios the (S, N) membership
      matrix stops being materialized at all.
    - ``to_chunks``/``from_chunks``: the sharded chunked hot loop's data
      staging. Chunk ci of the scenario axis is rows [ci·lc, (ci+1)·lc)
      of EVERY device's local shard (a local reshape — no device_put, no
      cross-device traffic), so each microbatch solve is one SPMD
      program with every device solving ``lc`` scenarios. The global
      scenario ids of a chunk are strided (``chunk_global_index``); the
      reassembled full batch comes back in natural order because each
      device's chunks concatenate to exactly its contiguous shard.

    All entry points are shard_map programs cached per (structure,
    shape) signature; every call is one jitted dispatch.
    """

    def __init__(self, mesh: Mesh, tree, slot_bounds, S: int):
        from jax.experimental.shard_map import shard_map  # noqa: F401
        self.mesh = mesh
        self.n_devices = int(mesh.devices.size)
        if S % self.n_devices:
            raise ValueError(f"S={S} not divisible by the "
                             f"{self.n_devices}-device mesh (pad first: "
                             "pad_batch_for_mesh)")
        self.S = S
        self.shard_size = S // self.n_devices
        self.slot_bounds = tuple(slot_bounds)
        self.n_nodes = tuple(int(n) for n in tree.nodes_per_stage)
        # per-stage (S,) GLOBAL node ids, sharded like every other
        # per-scenario tensor so shard_map bodies see their local slice
        sh = scenario_sharding(mesh, 1)
        self.node_idx = tuple(
            jax.device_put(jnp.asarray(tree.node_path[:, t],
                                       dtype=jnp.int32), sh)
            for t in range(tree.node_path.shape[1]))
        self._fns = {}

    # ---- builders (cached shard_map programs) ----
    def _shard_map(self, body, in_specs, out_specs):
        from jax.experimental.shard_map import shard_map
        return jax.jit(shard_map(body, mesh=self.mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False))

    def _spec(self, ndim, sharded=True):
        if not sharded:
            return P()
        return P(SCEN_AXIS, *([None] * (ndim - 1)))

    def _combine_fn(self, w_ndim, has_wmask, full):
        """The collective PH reduce: per-stage segment-sum + psum.
        ``full=True`` returns (xbar, xsqbar, W_new, conv) — the
        _ph_combine contract; ``full=False`` just xbar (the
        Compute_Xbar / APH FirstReduce surface)."""
        key = ("combine", w_ndim, has_wmask, full)
        if key in self._fns:
            return self._fns[key]
        import jax.ops as jops
        bounds, n_nodes = self.slot_bounds, self.n_nodes

        def _stage_means(xn, w, nidx, want_sq):
            outs, outs_sq = [], []
            for ni, N, (lo, hi) in zip(nidx, n_nodes, bounds):
                xt = xn[:, lo:hi]
                wt = w[:, lo:hi] if w_ndim == 2 \
                    else jnp.broadcast_to(w[:, None], xt.shape)
                num = jops.segment_sum(wt * xt, ni, num_segments=N)
                den = jops.segment_sum(wt, ni, num_segments=N)
                parts = [num, den]
                if want_sq:
                    parts.append(jops.segment_sum(wt * xt * xt, ni,
                                                  num_segments=N))
                parts = jax.lax.psum(tuple(parts), SCEN_AXIS)
                outs.append((parts[0] / parts[1])[ni])
                if want_sq:
                    outs_sq.append((parts[2] / parts[1])[ni])
            xbar = jnp.concatenate(outs, axis=1)
            return (xbar, jnp.concatenate(outs_sq, axis=1)) if want_sq \
                else (xbar, None)

        if full:
            def body(xn, prob, w, W, rho, wmask, *nidx):
                K = xn.shape[1]
                xbar, xsqbar = _stage_means(xn, w, nidx, True)
                W_new = W + rho * (xn - xbar)
                if has_wmask:
                    W_new = jnp.where(wmask, W_new, 0.0)
                conv = jax.lax.psum(
                    jnp.dot(prob, jnp.sum(jnp.abs(xn - xbar), axis=1)),
                    SCEN_AXIS) / K
                return xbar, xsqbar, W_new, conv

            n_idx = len(self.node_idx)
            in_specs = (self._spec(2), self._spec(1), self._spec(w_ndim),
                        self._spec(2), self._spec(2),
                        self._spec(2) if has_wmask else P()) \
                + (self._spec(1),) * n_idx
            out_specs = (self._spec(2), self._spec(2), self._spec(2), P())
        else:
            def body(xn, w, *nidx):
                xbar, _ = _stage_means(xn, w, nidx, False)
                return xbar

            in_specs = (self._spec(2), self._spec(w_ndim)) \
                + (self._spec(1),) * len(self.node_idx)
            out_specs = self._spec(2)
        fn = self._shard_map(body, in_specs, out_specs)
        self._fns[key] = fn
        return fn

    def _book_collective(self, dtype, full):
        """xfer.collective_bytes accounting lives HERE so every consumer
        of the collective entry points is counted — a call site that
        forgot its own counter_add would silently undercount the
        analyze sharding section's collective-traffic totals."""
        from .. import obs
        if obs.enabled():
            obs.counter_add(
                "xfer.collective_bytes",
                self.combine_collective_bytes(jnp.dtype(dtype).itemsize,
                                              full=full))

    def xbar(self, weights, xn):
        """Collective Compute_Xbar (nonanticipative per-node mean,
        broadcast back to scenarios)."""
        self._book_collective(xn.dtype, full=False)
        fn = self._combine_fn(int(weights.ndim), False, full=False)
        return fn(xn, weights, *self.node_idx)

    def combine(self, xn, prob, weights, W, rho, wmask):
        """Collective _ph_combine: (xbar, xsqbar, W_new, conv)."""
        self._book_collective(xn.dtype, full=True)
        fn = self._combine_fn(int(weights.ndim), wmask is not None,
                              full=True)
        if wmask is None:
            wmask = jnp.zeros((), xn.dtype)   # unused placeholder leaf
        return fn(xn, prob, weights, W, rho, wmask, *self.node_idx)

    def combine_collective_bytes(self, itemsize, full=True):
        """Estimated bytes one combine's psums reduce (operand sizes:
        the per-stage (N_t, k_t) num/den[/sq] node tables + the conv
        scalar) — the ``xfer.collective_bytes`` accounting basis. An
        ESTIMATE of logical all-reduce payload, not measured link
        traffic (ring/tree algorithms multiply by ~2(n-1)/n)."""
        total = 0
        for N, (lo, hi) in zip(self.n_nodes, self.slot_bounds):
            per_stage = 3 if full else 2          # num + den (+ sq)
            total += per_stage * N * (hi - lo) * itemsize
        if full:
            total += itemsize                     # conv scalar
        return total

    # ---- sharded chunk staging ----
    def chunk_layout(self, lc: int):
        """(n_chunks, chunk_rows_global) for local chunk size ``lc``;
        raises unless lc divides the shard (pad the batch so it does —
        core/spbase sizes the mesh padding from subproblem_chunk)."""
        if self.shard_size % lc:
            raise ValueError(
                f"local chunk {lc} does not divide the per-device shard "
                f"{self.shard_size} (S={self.S} on {self.n_devices} "
                "devices) — the batch padding should have rounded S up")
        return self.shard_size // lc, lc * self.n_devices

    def chunk_global_index(self, ci: int, lc: int) -> np.ndarray:
        """Global scenario ids of sharded chunk ``ci`` in chunk-row
        order (device-major: row d·lc + r is local row ci·lc + r of
        device d's shard) — the gate/hospital bookkeeping map."""
        L = self.shard_size
        return np.concatenate([d * L + ci * lc + np.arange(lc)
                               for d in range(self.n_devices)])

    def to_chunks(self, tree, lc: int):
        """Reshape every (S, ...) leaf to (n_chunks, lc·n_dev, ...) with
        the chunk-row axis sharded — a LOCAL reshape per device, no
        collectives, no device_put. ``tree[ci]`` (leading-axis index)
        is then chunk ci's sharded slice."""
        leaves, treedef = jax.tree.flatten(tree)
        key = ("to_chunks", lc, treedef, tuple(v.ndim for v in leaves))
        fn = self._fns.get(key)
        if fn is None:
            n_chunks, _ = self.chunk_layout(lc)

            def body(*ls):
                return tuple(
                    a.reshape((n_chunks, lc) + a.shape[1:]) for a in ls)

            in_specs = tuple(self._spec(v.ndim) for v in leaves)
            out_specs = tuple(P(None, SCEN_AXIS, *([None] * (v.ndim - 1)))
                              for v in leaves)
            fn = self._shard_map(body, in_specs, out_specs)
            self._fns[key] = fn
        return jax.tree.unflatten(treedef, fn(*leaves))

    def from_chunks(self, parts):
        """Concatenate per-chunk (lc·n_dev, ...) sharded arrays back to
        the natural-order (S, ...) batch — each device concatenates its
        own chunk rows, which ARE its contiguous shard."""
        key = ("from_chunks", len(parts), parts[0].ndim)
        fn = self._fns.get(key)
        if fn is None:
            def body(*ps):
                return jnp.concatenate(ps, axis=0)

            in_specs = tuple(self._spec(p.ndim) for p in parts)
            fn = self._shard_map(body, in_specs, self._spec(parts[0].ndim))
            self._fns[key] = fn
        return fn(*parts)


def pad_batch_for_mesh(batch, n_shards: int):
    """Pad a ScenarioBatch to a multiple of n_shards scenarios with
    zero-probability copies of the last scenario. Returns (batch, S_orig)."""
    S = batch.S
    rem = (-S) % n_shards
    if rem == 0:
        return batch, S
    import dataclasses

    def pad(a):
        a = np.asarray(a)
        return np.concatenate([a, np.repeat(a[-1:], rem, axis=0)], axis=0)

    tree = batch.tree
    from ..ir.tree import ScenarioTree
    new_tree = ScenarioTree(
        scen_names=tree.scen_names + [f"_pad{i}" for i in range(rem)],
        node_paths=np.concatenate([tree.node_path,
                                   np.repeat(tree.node_path[-1:], rem, axis=0)]),
        nodes_per_stage=tree.nodes_per_stage,
        nonant_names_per_stage=tree.nonant_names_per_stage,
        probabilities=np.concatenate([tree.probabilities, np.zeros(rem)]),
    )
    return dataclasses.replace(
        batch, tree=new_tree,
        c=pad(batch.c), c0=pad(batch.c0), P_diag=pad(batch.P_diag),
        A=batch.A if batch.shared_A else pad(batch.A),
        l=pad(batch.l), u=pad(batch.u),
        lb=pad(batch.lb), ub=pad(batch.ub),
        c_stage=pad(batch.c_stage), c0_stage=pad(batch.c0_stage),
        prob=new_tree.probabilities.copy(),
    ), S
