from .mesh import (make_mesh, shard_arrays, scenario_sharding,  # noqa: F401
                   pad_batch_for_mesh, ShardedScenarioOps)
