from .mesh import make_mesh, shard_arrays, scenario_sharding  # noqa: F401
