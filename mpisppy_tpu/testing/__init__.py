"""Test-only machinery (fault injection, harness helpers).

Nothing in the production wheel imports this package on a clean run —
the fault-injection hooks in ``utils/multiproc._spoke_worker`` gate the
import behind an explicit fault plan (spoke option or
``MPISPPY_TPU_FAULT_PLAN``), so the disabled path pays zero imports and
zero per-call overhead. ``tests/test_faults.py`` asserts this with a
clean-interpreter import check.
"""
