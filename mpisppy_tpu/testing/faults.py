"""Deterministic, seeded fault injection for cylinder wheels.

The supervisor layer (cylinders/supervisor.py, doc/fault_tolerance.md)
exists to survive crashed, hung, and garbage-publishing spokes — faults
that are miserable to reproduce organically. This module makes them
reproducible: a *fault plan* names, per spoke index, exactly which
fault fires and when (the Nth bound publish, a wall-clock delay), so a
test can SIGKILL spoke 0 at its first publish on every run and assert
the same recovery path every time.

Activation is explicit and child-side only: `_spoke_worker` imports
this module IFF the spoke's options carry a ``fault_plan`` or the
``MPISPPY_TPU_FAULT_PLAN`` env var is set. A clean run never imports
it (asserted by tests/test_faults.py), so production wheels pay zero
overhead — the injection points are plain instance-attribute wrappers
installed on one spoke object, not patches to the framework.

Fault-plan schema (dict, JSON string, or path to a JSON file)::

    {"seed": 42,                      # optional, default 0
     "spokes": {
       "0": [                         # spoke index (string or int keys)
         {"action": "crash",  "at_update": 1},        # SIGKILL self
         {"action": "crash",  "after_s": 3.0},        # ... on a timer
         {"action": "hang",   "after_s": 2.0},        # stop responding
         {"action": "delay_hello", "seconds": 5.0},   # late handshake
         {"action": "preempt", "at_update": 2},       # SIGTERM self
         {"action": "corrupt", "from_update": 2,      # poison payloads
          "value": "inf"}                             # inf|nan|garbage|float
       ]},
     "hub": [                         # the HUB process (wheel launcher)
       {"action": "preempt", "at_iteration": 5}       # preemption notice
     ],
     "serve": [                       # the SERVE process (serving fleet)
       {"action": "kill",    "after_s": 3.0},         # SIGKILL self
       {"action": "preempt", "at_wheel": 2},          # SIGTERM mid-wheel
       {"action": "wedge_wheel", "at_wheel": 1,       # hang a wheel past
        "seconds": 30.0},                             #  its deadline
       {"action": "tear_transfer", "at_transfer": 1}, # truncate a bundle
       {"action": "refuse_peer", "at_offer": 1},      # refuse a handoff
       {"action": "timeout_peer", "at_offer": 2,      # stall a handoff
        "seconds": 20.0}
     ]}

Triggers: ``at_update`` fires on exactly the Nth ``spoke_to_hub``
publish (1-based); ``from_update`` on every publish >= N; ``after_s``
on the first poll/publish after that many seconds from install;
``at_iteration`` (hub specs) on the first termination check at that
engine iteration. A spec may carry ``gen`` (default 0): faults apply
only to that incarnation of the spoke, so a respawned replacement
(gen 1) runs clean unless the plan says otherwise — the property the
respawn tests rely on.

``crash`` fires *before* the write (the poisoned value never lands);
``preempt`` delivers SIGTERM to the process' own pid — the preemption
notice: a checkpointing wheel's handler captures a final bundle and
terminates cleanly (doc/fault_tolerance.md), a bare spoke dies and is
respawned warm. ``corrupt`` replaces the payload and lets the write
proceed. ``garbage`` corruption values are drawn from a RandomState
keyed on (seed, spoke index, update number) — deterministic across
runs and processes.

Hub-side plans (the ``"hub"`` key) are installed by
``spin_the_wheel_processes`` when the ``MPISPPY_TPU_FAULT_PLAN`` env
var is set — same explicit-activation contract as the spoke side: the
clean path never imports this module.

Serve-side plans (the ``"serve"`` key) target the SERVING process
(serve/manager, doc/serving.md): ``kill``/``preempt`` die at the Nth
wheel launch or on a timer; ``wedge_wheel`` sleeps the Nth wheel for
``seconds`` — past its deadline, the WheelDeadline watchdog fires
exactly as for an organically hung iteration; ``tear_transfer``
truncates the Nth migration bundle member mid-stream (the receiver's
sha256 gate refuses it); ``refuse_peer``/``timeout_peer`` make this
host's receiver endpoint refuse or stall the Nth incoming offer.
Installed by ``serve_main`` under the same env var; the chaos driver
(tools/chaos_serve.py) composes these into randomized schedules
against a 2-process fleet.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time

import numpy as np

_ACTIONS = ("crash", "hang", "delay_hello", "corrupt", "preempt")
_TRIGGERS = ("at_update", "from_update", "after_s", "seconds")
# hub specs trade the publish-count triggers for the iteration one:
# the hub has no spoke_to_hub, spokes have no engine iteration
_HUB_TRIGGERS = ("at_iteration", "after_s")
_VALUES = ("inf", "-inf", "nan", "garbage")
# service-level faults (the "serve" plan key): process kills, wedged
# wheels, torn migration transfers, refused/stalled peer endpoints
_SERVE_ACTIONS = ("kill", "preempt", "wedge_wheel", "tear_transfer",
                  "refuse_peer", "timeout_peer")
_SERVE_TRIGGERS = ("at_wheel", "at_transfer", "at_offer", "after_s",
                   "seconds")


def _load_spec(spec):
    """dict | JSON string | path-to-JSON-file -> plan dict."""
    if isinstance(spec, dict):
        return spec
    s = str(spec)
    if os.path.exists(s):
        with open(s, encoding="utf-8") as f:
            return json.load(f)
    return json.loads(s)


def validate_plan(plan: dict) -> dict:
    """Schema check (fail at install time, not mid-wheel)."""
    if not isinstance(plan, dict):
        raise ValueError(f"fault plan must be a dict, got {type(plan)}")
    unknown = set(plan) - {"seed", "spokes", "hub", "serve"}
    if unknown:
        raise ValueError(f"unknown fault-plan keys {sorted(unknown)}")

    def _check_specs(specs, triggers, actions=_ACTIONS):
        for sp in specs:
            act = sp.get("action")
            if act not in actions:
                raise ValueError(f"unknown fault action {act!r}; known: "
                                 f"{actions}")
            bad = set(sp) - {"action", "value", "gen", *triggers}
            if bad:
                raise ValueError(f"unknown fault-spec keys {sorted(bad)} "
                                 f"in {sp}")
            v = sp.get("value")
            if act == "corrupt" and v is not None \
                    and not isinstance(v, (int, float)) and v not in _VALUES:
                raise ValueError(f"corrupt value {v!r}; known: {_VALUES} "
                                 "or a number")

    for idx, specs in (plan.get("spokes") or {}).items():
        int(idx)            # keys must be spoke indices
        _check_specs(specs, _TRIGGERS)
    _check_specs(plan.get("hub") or [], _HUB_TRIGGERS)
    _check_specs(plan.get("serve") or [], _SERVE_TRIGGERS,
                 actions=_SERVE_ACTIONS)
    return plan


class FaultInjector:
    """The per-spoke fault machine: wraps ONE spoke instance's
    ``spoke_to_hub`` (publish-count triggers) and ``got_kill_signal``
    (time triggers) with the specs resolved for (index, gen)."""

    def __init__(self, specs, index=0, gen=0, seed=0):
        self.index = int(index)
        self.gen = int(gen)
        self.seed = int(seed)
        self.specs = [s for s in specs
                      if int(s.get("gen", 0)) == int(gen)]
        self.n_puts = 0
        self._t0 = time.monotonic()

    @classmethod
    def from_spec(cls, spec, index=0, gen=0):
        plan = validate_plan(_load_spec(spec))
        spokes = plan.get("spokes") or {}
        specs = spokes.get(str(index)) or spokes.get(int(index)) or []
        return cls(specs, index=index, gen=gen,
                   seed=plan.get("seed", 0))

    # -- triggers --
    def _timed_out(self, spec):
        s = spec.get("after_s")
        return s is not None and time.monotonic() - self._t0 >= float(s)

    def _update_hit(self, spec):
        at = spec.get("at_update")
        frm = spec.get("from_update")
        return (at is not None and self.n_puts == int(at)) or \
            (frm is not None and self.n_puts >= int(frm))

    # -- actions --
    def _die(self):
        os.kill(os.getpid(), signal.SIGKILL)
        os._exit(137)           # unreachable unless SIGKILL is blocked

    def _preempt(self):
        """The preemption notice: SIGTERM to our own pid. A process
        with the checkpointing handler installed (the hub — see
        utils/multiproc) captures a final bundle and terminates
        cleanly; a handler-less spoke child dies and is respawned
        warm. Unlike _die, execution CONTINUES after a handled
        signal — the wheel winds down through its normal exit path."""
        os.kill(os.getpid(), signal.SIGTERM)

    def _hang(self):
        while True:             # ignores the kill signal on purpose
            time.sleep(3600.0)

    def _corrupted(self, values, spec):
        v = spec.get("value", "inf")
        out = np.array(values, dtype=np.float64, copy=True).reshape(-1)
        if v == "garbage":
            rng = np.random.RandomState(
                (self.seed * 1000003 + self.index * 9176
                 + self.n_puts) % (2 ** 32))
            out[:] = rng.standard_normal(out.shape[0]) * 1e30
        elif v in ("inf", "-inf", "nan"):
            out[:] = float(v)
        else:
            out[:] = float(v)
        return out

    # -- hook bodies --
    def hello_delay(self) -> float:
        return sum(float(s.get("seconds", 0.0)) for s in self.specs
                   if s["action"] == "delay_hello")

    def sleep_before_hello(self):
        d = self.hello_delay()
        if d > 0:
            time.sleep(d)

    def on_publish(self, values):
        """Called with every outgoing payload; may not return (crash),
        may return a corrupted copy."""
        self.n_puts += 1
        for s in self.specs:
            if s["action"] == "crash" and (self._update_hit(s)
                                           or self._timed_out(s)):
                self._die()
        for s in self.specs:
            if s["action"] == "preempt" and (self._update_hit(s)
                                             or self._timed_out(s)):
                self._preempt()
        for s in self.specs:
            if s["action"] == "hang" and self._update_hit(s):
                self._hang()
        for s in self.specs:
            if s["action"] == "corrupt" and (self._update_hit(s)
                                             or self._timed_out(s)):
                values = self._corrupted(values, s)
        return values

    def on_poll(self):
        """Called from the spoke's kill-signal poll loop (time
        triggers for spokes that never publish)."""
        for s in self.specs:
            if s["action"] == "crash" and self._timed_out(s):
                self._die()
        for s in self.specs:
            if s["action"] == "preempt" and self._timed_out(s):
                self._preempt()
        for s in self.specs:
            if s["action"] == "hang" and self._timed_out(s):
                self._hang()

    def install(self, spoke):
        """Wrap the spoke instance's publish + poll methods. Instance
        attributes only — the class (and every other spoke) stays
        untouched."""
        orig_put = spoke.spoke_to_hub
        orig_poll = spoke.got_kill_signal

        def _put(values, **kw):
            # kwargs (lineage t_compute) pass through untouched: faults
            # corrupt the semantic payload, never the lineage stamps
            return orig_put(self.on_publish(values), **kw)

        def _poll():
            self.on_poll()
            return orig_poll()

        spoke.spoke_to_hub = _put
        spoke.got_kill_signal = _poll
        return self

    # -- hub-side triggers --
    def on_iteration(self, it):
        """Called once per hub termination check with the engine's
        current iteration: ``at_iteration`` / ``after_s`` triggers for
        HUB specs (preempt = the deterministic preemption notice the
        checkpoint-resume tests drive; crash/hang for completeness).
        Each spec fires at most once — termination checks repeat at
        the same iteration."""
        fired = getattr(self, "_fired", None)
        if fired is None:
            fired = self._fired = set()
        for i, s in enumerate(self.specs):
            if i in fired:
                continue
            at = s.get("at_iteration")
            hit = (at is not None and it is not None
                   and int(it) >= int(at)) or self._timed_out(s)
            if not hit:
                continue
            fired.add(i)
            if s["action"] == "crash":
                self._die()
            elif s["action"] == "preempt":
                self._preempt()
            elif s["action"] == "hang":
                self._hang()


def install_hub_faults(hub, spec):
    """Wrap ``hub.determine_termination`` with the plan's ``"hub"``
    specs (instance attribute only — the class stays untouched, same
    discipline as the spoke install). Returns the injector, or None
    when the plan carries no hub specs. Activated exclusively by
    ``spin_the_wheel_processes`` under the MPISPPY_TPU_FAULT_PLAN env
    var — the deterministic harness's handle on the WHEEL process."""
    plan = validate_plan(_load_spec(spec))
    specs = plan.get("hub") or []
    if not specs:
        return None
    inj = FaultInjector(specs, index=-1, seed=plan.get("seed", 0))
    orig = hub.determine_termination

    def _check():
        inj.on_iteration(getattr(hub.opt, "_iter", None))
        return orig()

    hub.determine_termination = _check
    return inj


class ServeFaultInjector:
    """The serving-process fault machine (the plan's ``"serve"`` key).

    Counted triggers are 1-based like the spoke side: ``at_wheel``
    fires on the Nth wheel launch, ``at_transfer`` on the Nth outgoing
    migration bundle member, ``at_offer`` on the Nth INCOMING
    ``/migrate/offer``; ``after_s`` arms a timer from
    :meth:`start_timers`. Each spec fires at most once. Installed by
    ``serve_main`` under the MPISPPY_TPU_FAULT_PLAN env var — the
    clean serving path never imports this module (tests assert it)."""

    def __init__(self, specs, seed=0):
        self.specs = list(specs)
        self.seed = int(seed)
        self._fired = set()
        self._lock = threading.Lock()
        self.n_wheels = 0
        self.n_transfers = 0
        self.n_offers = 0

    @classmethod
    def from_spec(cls, spec):
        plan = validate_plan(_load_spec(spec))
        specs = plan.get("serve") or []
        if not specs:
            return None
        return cls(specs, seed=plan.get("seed", 0))

    @classmethod
    def from_env(cls):
        spec = os.environ.get("MPISPPY_TPU_FAULT_PLAN")
        return cls.from_spec(spec) if spec else None

    def _die(self):
        os.kill(os.getpid(), signal.SIGKILL)
        os._exit(137)           # unreachable unless SIGKILL is blocked

    def _preempt(self):
        os.kill(os.getpid(), signal.SIGTERM)

    def _take(self, i) -> bool:
        """Claim spec ``i`` (once-only, thread-safe: wheel workers and
        HTTP handler threads consult the same injector)."""
        with self._lock:
            if i in self._fired:
                return False
            self._fired.add(i)
            return True

    def start_timers(self):
        """Arm daemon timers for ``after_s`` kill/preempt specs — the
        process-level faults that must fire even while the service is
        idle (no wheel to count)."""
        for i, s in enumerate(self.specs):
            if s["action"] not in ("kill", "preempt"):
                continue
            delay = s.get("after_s")
            if delay is None:
                continue

            def _fire(i=i, s=s):
                if self._take(i):
                    (self._die if s["action"] == "kill"
                     else self._preempt)()

            t = threading.Timer(float(delay), _fire)
            t.daemon = True
            t.start()
        return self

    def on_wheel_start(self):
        """Called by the wheel worker right before ``hub.main()``:
        counted kill/preempt/wedge faults. ``wedge_wheel`` sleeps here
        with the WheelDeadline watchdog already armed — the wedge is
        indistinguishable from a hung iteration, which is the point."""
        with self._lock:
            self.n_wheels += 1
            n = self.n_wheels
        for i, s in enumerate(self.specs):
            at = s.get("at_wheel")
            if at is None or n != int(at) or not self._take(i):
                continue
            if s["action"] == "kill":
                self._die()
            elif s["action"] == "preempt":
                self._preempt()
            elif s["action"] == "wedge_wheel":
                time.sleep(float(s.get("seconds", 30.0)))

    def on_transfer(self) -> bool:
        """Called by the donor's MigrationClient per outgoing bundle
        member; True = tear THIS member (truncate mid-stream with the
        full Content-Length still promised — the receiver's sha256
        gate refuses it, exercising the retry/abort path)."""
        with self._lock:
            self.n_transfers += 1
            n = self.n_transfers
        for i, s in enumerate(self.specs):
            if s["action"] != "tear_transfer":
                continue
            at = s.get("at_transfer")
            if at is not None and n == int(at) and self._take(i):
                return True
        return False

    def on_offer(self):
        """Called by the receiver per incoming ``/migrate/offer`` ->
        ``(verdict, sleep_seconds)``: ``("refuse", 0)`` rejects the
        handoff with a reasoned 4xx, ``(None, s)`` stalls the reply so
        the donor's per-transfer deadline machinery takes over."""
        with self._lock:
            self.n_offers += 1
            n = self.n_offers
        for i, s in enumerate(self.specs):
            at = s.get("at_offer")
            if at is None or n != int(at):
                continue
            if s["action"] == "refuse_peer" and self._take(i):
                return "refuse", 0.0
            if s["action"] == "timeout_peer" and self._take(i):
                return None, float(s.get("seconds", 20.0))
        return None, 0.0
