"""Logging setup (ref. mpisppy/log.py:44-67).

The reference configures a root ``mpisppy`` logger plus per-module file
logs at CRITICAL default (hub.log, xhatlp.log, ...). Same surface here:
``setup_logger(name, fname, level)`` attaches a file handler; cylinder
classes call it when a ``log_prefix`` option is present.
"""

from __future__ import annotations

import logging

_FORMAT = "%(asctime)s %(name)s %(levelname)s: %(message)s"

root = logging.getLogger("mpisppy_tpu")
if not root.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter(_FORMAT))
    root.addHandler(_h)
    root.setLevel(logging.CRITICAL)   # quiet by default, like the reference


def setup_logger(name: str, fname: str | None = None,
                 level: int = logging.DEBUG) -> logging.Logger:
    """Per-module logger with an optional file sink
    (ref. mpisppy/log.py:44 setup_logger)."""
    lg = logging.getLogger(name)
    lg.setLevel(level)
    if fname is not None:
        fh = logging.FileHandler(fname)
        fh.setFormatter(logging.Formatter(_FORMAT))
        lg.addHandler(fh)
    return lg
