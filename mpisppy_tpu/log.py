"""Logging setup (ref. mpisppy/log.py:44-67).

The reference configures a root ``mpisppy`` logger plus per-module file
logs at CRITICAL default (hub.log, xhatlp.log, ...). Same surface here:
``setup_logger(name, fname, level)`` attaches a file handler; cylinder
classes call it when a ``log_prefix`` option is present.
"""

from __future__ import annotations

import logging

_FORMAT = "%(asctime)s %(name)s %(levelname)s: %(message)s"

root = logging.getLogger("mpisppy_tpu")
if not root.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter(_FORMAT))
    root.addHandler(_h)
    root.setLevel(logging.CRITICAL)   # quiet by default, like the reference


def setup_logger(name: str, fname: str | None = None,
                 level: int = logging.DEBUG) -> logging.Logger:
    """Per-module logger with an optional file sink
    (ref. mpisppy/log.py:44 setup_logger). File-logged records do NOT
    propagate to the (quiet) console root, and repeat calls for the same
    name/file don't stack duplicate handlers."""
    lg = logging.getLogger(name)
    lg.setLevel(level)
    if fname is not None:
        lg.propagate = False
        have = {h.baseFilename for h in lg.handlers
                if isinstance(h, logging.FileHandler)}
        import os
        if os.path.abspath(fname) not in have:
            fh = logging.FileHandler(fname)
            fh.setFormatter(logging.Formatter(_FORMAT))
            lg.addHandler(fh)
    return lg
