"""Converger ABC (ref. mpisppy/convergers/converger.py:13-29)."""

from __future__ import annotations

import abc


class Converger(abc.ABC):
    """Constructed with the engine after iter 0; ``is_converged`` is polled
    once per iteration after the solve/update."""

    def __init__(self, opt):
        self.opt = opt

    @abc.abstractmethod
    def is_converged(self) -> bool:
        ...
