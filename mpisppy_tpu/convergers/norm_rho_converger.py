"""NormRhoConverger: primal+dual residual norm criterion.

ref. mpisppy/convergers/norm_rho_converger.py:12 — pairs with
NormRhoUpdater: converged when the prob-weighted primal residual
‖x − x̄‖₁ plus the dual residual ρ‖x̄ − x̄_prev‖₁ falls below
``norm_rho_converger_conv_thresh``.
"""

from __future__ import annotations

import numpy as np

from .converger import Converger


class NormRhoConverger(Converger):
    def __init__(self, opt):
        super().__init__(opt)
        self.thresh = float(opt.options.get("norm_rho_converger_conv_thresh", 1e-4))
        self._prev_xbar = None
        self.last_norm = np.inf

    def is_converged(self) -> bool:
        opt = self.opt
        xn = np.asarray(opt._hub_nonants())
        xbar = np.asarray(opt.xbar)
        prob = np.asarray(opt.prob)
        prim = float(prob @ np.abs(xn - xbar).sum(axis=1))
        dual = 0.0
        if self._prev_xbar is not None:
            dual = float(np.mean(np.asarray(opt.rho)) *
                         np.abs(xbar - self._prev_xbar).sum() / max(opt.batch.S, 1))
        self._prev_xbar = xbar
        self.last_norm = prim + dual
        return self.last_norm < self.thresh
