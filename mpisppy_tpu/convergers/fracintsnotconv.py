"""FractionalConverger: fraction of integer nonants not yet agreed.

ref. mpisppy/convergers/fracintsnotconv.py:12 — converged when the fraction
of integer nonant variables whose scenario values still differ (x̄² vs
x̄²-bar variance test) drops below ``fracintsnotconv_conv_thresh``.
"""

from __future__ import annotations

import numpy as np

from .converger import Converger


class FractionalConverger(Converger):
    def __init__(self, opt):
        super().__init__(opt)
        o = opt.options
        self.thresh = float(o.get("fracintsnotconv_conv_thresh", 0.05))
        self.tol = float(o.get("fracintsnotconv_tol", 1e-4))
        self.imask = opt.nonant_integer_mask
        self.nints = max(int(self.imask.sum()), 1)
        self.last_frac = 1.0

    def is_converged(self) -> bool:
        xbar = np.asarray(self.opt.xbar)
        xsqbar = np.asarray(self.opt.xsqbar)
        var = np.max(np.abs(xsqbar - xbar * xbar), axis=0)   # (K,)
        notconv = (var > self.tol * self.tol) & self.imask
        self.last_frac = float(notconv.sum()) / self.nints
        return self.last_frac < self.thresh
