"""Convergers: pluggable supplemental termination criteria.

ref. mpisppy/convergers/converger.py:13 — engines construct the converger
after iter 0 and call ``is_converged()`` each iteration
(ref. phbase.py:1527-1531).
"""

from .converger import Converger
from .fracintsnotconv import FractionalConverger
from .norm_rho_converger import NormRhoConverger

__all__ = ["Converger", "FractionalConverger", "NormRhoConverger"]
