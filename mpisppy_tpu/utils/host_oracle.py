"""Host-side exact LP oracle for bound certification.

The Lagrangian outer bound L(W) = sum_s p_s min_x [f_s(x) + W_s x_nonant]
is an accuracy-critical, latency-insensitive quantity: it gates hub
termination (time-to-gap), runs once per spoke sync (not per PH
iteration), and its tightness is what the headline gap metric measures.
The batched first-order kernel's certified-from-inexact-duals bound
(ops/qp_solver.qp_dual_objective) is VALID at any accuracy but pays
|reduced cost| x box width per column — on UC-scale problems that can sit
1-3% below the true Lagrangian value until the duals are extremely
converged. A simplex solve is exact.

So, like the reference architecture — cylinders on heterogeneous
resources, bound spokes renting CPU solvers (ref.
mpisppy/cylinders/lagrangian_bounder.py:5-87 solves per-scenario models
with Gurobi/CPLEX) — the TPU framework keeps the HOT loop (PH iterations)
on the accelerator and offers a host HiGHS oracle for the bound spokes.
10 UC scenarios solve in ~0.2 s on host; the spoke is asynchronous, so
even 1000 scenarios (~20 s) only delays bound refresh, never the hub.

Only LINEAR objectives are supported (a Lagrangian bound of an LP/MIP
relaxation); quadratic models keep the on-device certified bound.
"""

from __future__ import annotations

import numpy as np


def exact_scenario_lp_values(batch, W=None, time_limit=None):
    """Per-scenario EXACT LP values of min c_s·x (+ W_s on nonant slots)
    s.t. l <= Ax <= u, lb <= x <= ub, via host HiGHS.

    Returns (values (S,), ok (S,) bool). ``W`` is an (S, K) nonant-slot
    dual block or None. Infeasible/failed scenarios get -inf (a valid
    lower bound contribution is impossible, so the caller must treat
    ok=False as "no bound this round")."""
    from scipy.optimize import milp, LinearConstraint, Bounds

    S = batch.S
    A = np.asarray(batch.A)
    l = np.asarray(batch.l)
    u = np.asarray(batch.u)
    lb = np.asarray(batch.lb)
    ub = np.asarray(batch.ub)
    c = np.asarray(batch.c, dtype=np.float64)
    c0 = np.asarray(batch.c0, dtype=np.float64)
    if np.abs(np.asarray(batch.P_diag)).max() > 0:
        raise ValueError("host LP oracle supports linear objectives only")
    idx = np.asarray(batch.nonant_idx)
    opts = {}
    if time_limit is not None:
        opts["time_limit"] = float(time_limit)
    vals = np.full(S, -np.inf)
    ok = np.zeros(S, bool)
    for s in range(S):
        q = c[s].copy()
        if W is not None:
            q[idx] += np.asarray(W[s], dtype=np.float64)
        A_s = A if A.ndim == 2 else A[s]
        res = milp(q, constraints=LinearConstraint(A_s, l[s], u[s]),
                   bounds=Bounds(lb[s], ub[s]),
                   integrality=np.zeros(q.shape[0], int), options=opts)
        if res.status == 0 and res.x is not None:
            vals[s] = res.fun + c0[s]
            ok[s] = True
    return vals, ok


def exact_lagrangian_bound(batch, prob, W=None):
    """E_p[exact scenario LP value with W] — the exact Lagrangian outer
    bound when sum_s p_s W_s = 0 per (node, slot) (the caller projects).
    Returns None when any scenario solve failed."""
    vals, ok = exact_scenario_lp_values(batch, W)
    if not ok.all():
        return None
    return float(np.dot(np.asarray(prob, dtype=np.float64), vals))
