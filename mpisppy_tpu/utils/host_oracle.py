"""Host-side exact LP/MILP oracle for bound certification.

The Lagrangian outer bound L(W) = sum_s p_s min_x [f_s(x) + W_s x_nonant]
is an accuracy-critical, latency-insensitive quantity: it gates hub
termination (time-to-gap), runs once per spoke sync (not per PH
iteration), and its tightness is what the headline gap metric measures.
The batched first-order kernel's certified-from-inexact-duals bound
(ops/qp_solver.qp_dual_objective) is VALID at any accuracy but pays
|reduced cost| x box width per column — on UC-scale problems that can sit
1-3% below the true Lagrangian value until the duals are extremely
converged. A simplex solve is exact.

Two oracle modes, mirroring the two bound regimes of the reference:

- **LP**: exact L(W) of the LP relaxation. Floor: the instance's
  LP integrality gap — no W can push an LP-relaxation bound past it.
- **MILP**: min over the INTEGER-feasible set per scenario (the true
  Lagrangian dual function), the analog of the reference's Lagrangian
  spoke solving MIP subproblems with W on (ref.
  mpisppy/cylinders/lagrangian_bounder.py:54-56 driving
  phbase.py:947-949 MIP solves) — which is how the reference's UC gaps
  reach 0.026-0.073% while LP bounds stall near the ~1% integrality
  gap. Each scenario value is HiGHS's B&B dual bound, valid at any
  time_limit / mip_rel_gap stop.

Scenario solves fan out over a persistent pool of dedicated worker
subprocesses (the reference's per-rank parallel solve fan-out, ref.
phbase.py:999); see _oracle_worker for why plain subprocesses rather
than multiprocessing. n_workers=0 runs solves inline — same results, no
IPC.

Only LINEAR objectives are supported (a Lagrangian bound of an LP/MIP
relaxation); quadratic models keep the on-device certified bound.
"""

from __future__ import annotations

import os
import queue
import subprocess
import sys
import threading

import numpy as np
from scipy import sparse as sparse_mod

from . import _oracle_worker


class _ProcWorker:
    """One oracle subprocess: ``python -m ..._oracle_worker`` with the
    static payload shipped as its first stdin frame. See the worker
    module's docstring for why this is a subprocess, not
    multiprocessing."""

    def __init__(self, payload_bytes):
        """``payload_bytes``: the PRE-PICKLED static payload — pickled
        once per pool, not per worker (multi-MB for large batches)."""
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "mpisppy_tpu.utils._oracle_worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)
        _oracle_worker.write_frame(self.proc.stdin, payload_bytes)

    def solve(self, task):
        _oracle_worker.write_msg(self.proc.stdin, task)
        r = _oracle_worker.read_msg(self.proc.stdout)
        if r is None:
            raise RuntimeError("oracle worker subprocess died")
        return r

    def kill(self):
        try:
            self.proc.kill()
            self.proc.wait(timeout=5.0)
        except Exception:
            pass


class OraclePool:
    """Persistent per-scenario LP/MILP solve fan-out for one batch.

    Ships the static problem data (A, row/box bounds, integrality) to
    each worker once at pool startup; per-call messages carry only the
    objective vectors. Keep ONE instance alive across bound refreshes —
    worker startup and data shipping are paid once (the warm-start
    analog of the reference's persistent solver plugins,
    ref. phbase.py:1304-1362).
    """

    def __init__(self, batch, n_workers=None):
        if np.abs(np.asarray(batch.P_diag)).max() > 0:
            raise ValueError("host oracle supports linear objectives only")
        A = np.asarray(batch.A, dtype=np.float64)
        if A.ndim == 3 and all(np.array_equal(A[s], A[0])
                               for s in range(1, A.shape[0])):
            # shared structure (scenarios differ in bounds/costs only —
            # every shipped model family): ship ONE matrix, not S copies
            # ((S,m,n) at S=1024 would be gigabytes of payload)
            A = A[0]
        self._init_arrays(
            A, np.asarray(batch.l, dtype=np.float64),
            np.asarray(batch.u, dtype=np.float64),
            np.asarray(batch.lb, dtype=np.float64),
            np.asarray(batch.ub, dtype=np.float64),
            np.asarray(batch.integer, dtype=np.uint8),
            np.asarray(batch.c, dtype=np.float64),
            np.asarray(batch.c0, dtype=np.float64),
            np.asarray(batch.nonant_idx), n_workers)

    @classmethod
    def from_arrays(cls, A, l, u, lb, ub, integrality, c, c0,
                    nonant_idx=None, n_workers=None):
        """Pool over explicit standard-form arrays (no ScenarioBatch) —
        e.g. ONE extensive-form problem as a batch of one. ``A`` may be
        scipy-sparse (shared) or dense (2-D shared / 3-D per-row)."""
        self = cls.__new__(cls)
        self._init_arrays(A, np.atleast_2d(l), np.atleast_2d(u),
                          np.atleast_2d(lb), np.atleast_2d(ub),
                          np.asarray(integrality, dtype=np.uint8),
                          np.atleast_2d(c), np.atleast_1d(c0),
                          nonant_idx, n_workers)
        return self

    def _init_arrays(self, A, l, u, lb, ub, integrality, c, c0,
                     nonant_idx, n_workers):
        self.S = int(l.shape[0])
        self.c = c
        self.c0 = c0
        self.nonant_idx = nonant_idx
        if not sparse_mod.issparse(A) and A.ndim == 2:
            # a shared dense matrix ships to every worker subprocess
            # through a pipe — at reference-UC scale that is a 2.7 GB
            # pickle (~45 s, measured) for a 0.03%-dense matrix whose
            # CSR is ~2 MB. The worker consumes CSR natively.
            nnz = np.count_nonzero(A)
            if nnz < 0.05 * A.size:
                A = sparse_mod.csr_matrix(A)
        self._payload = {
            "A": A, "l": l, "u": u, "lb": lb, "ub": ub,
            "integrality": integrality,
        }
        # n_workers=0 → inline (no subprocesses); None → one worker per
        # host core, capped at S. Even on a 1-core host the default is a
        # 1-worker subprocess pool: the wheel's hub drives the
        # accelerator, so an oracle SUBPROCESS overlaps bound refreshes
        # with hub iterations where an inline solve would hold this
        # spoke's thread (and, GIL permitting, the whole process)
        cpus = os.cpu_count() or 1
        if n_workers is not None and int(n_workers) == 0:
            self.n_workers = 0
        else:
            self.n_workers = max(1, min(self.S, cpus if n_workers is None
                                        else int(n_workers)))
        self._pool = None          # created lazily on first pooled call
        self._inline_state = None

    # -- execution backends --
    def _ensure_inline(self):
        if self._inline_state is None:
            # run the worker init in-process; the state is PER-POOL so
            # concurrent inline pools over different batches coexist
            self._inline_state = _oracle_worker.init_worker(self._payload)
        return self._inline_state

    def _ensure_pool(self):
        if self._pool is None:
            import pickle
            pb = pickle.dumps(self._payload,
                              protocol=pickle.HIGHEST_PROTOCOL)
            self._pool = [_ProcWorker(pb) for _ in range(self.n_workers)]
        return self._pool

    def _terminate_pool(self):
        if self._pool is not None:
            for w in self._pool:
                w.kill()
            self._pool = None

    def _run(self, tasks, kill_check=None):
        """Run solve tasks; returns results (scenario ids inside).

        ``kill_check()`` (optional) is polled while waiting; when it
        returns True remaining work is abandoned (the worker
        subprocesses are killed and respawn on next use) and None is
        returned — bound refreshes can take tens of seconds and must
        not hold a terminating wheel hostage (VERDICT r2 weak #5).
        Inline mode (n_workers=0) can only poll BETWEEN scenario
        solves — there is no subprocess to kill mid-solve — so its
        abort latency is one scenario's time_limit; callers that need
        prompt termination should keep per-scenario limits modest or
        use the pooled mode."""
        if self.n_workers == 0:
            state = self._ensure_inline()
            out = []
            for t in tasks:
                if kill_check is not None and kill_check():
                    return None
                out.append(_oracle_worker.solve_scenario(state, t))
            return out
        workers = self._ensure_pool()
        tq = queue.Queue()
        for t in tasks:
            tq.put(t)
        results, errors = [], []
        lock = threading.Lock()
        abort = threading.Event()

        def drive(w):
            try:
                while not abort.is_set():
                    if kill_check is not None and kill_check():
                        # respect the kill BETWEEN queued tasks too
                        # (ISSUE 9 satellite): a worker finishing one
                        # MIP used to grab the next task in the window
                        # before the main poll loop reacted, so a
                        # quarantined/terminating spoke could wait out
                        # a full oracle batch one time_limit at a time
                        abort.set()
                        return
                    try:
                        t = tq.get_nowait()
                    except queue.Empty:
                        return
                    r = w.solve(t)
                    with lock:
                        results.append(r)
            except BaseException as e:   # worker death surfaces to caller
                if not abort.is_set():
                    with lock:
                        errors.append(e)
                    # stop the surviving workers too: their results are
                    # discarded anyway once the call raises
                    abort.set()

        threads = [threading.Thread(target=drive, args=(w,), daemon=True)
                   for w in workers]
        for th in threads:
            th.start()
        while any(th.is_alive() for th in threads):
            for th in threads:
                th.join(timeout=0.05)
            if kill_check is not None and kill_check():
                abort.set()
                # killing the subprocesses EOFs the blocked reads, so
                # the driver threads exit promptly
                self._terminate_pool()
                return None
        if errors:
            self._terminate_pool()
            raise RuntimeError("oracle pool worker failed") from errors[0]
        if kill_check is not None and kill_check():
            # the kill may have landed via a drive thread's own check
            # (or between the last join and here) with every thread
            # already exited — partial results must not masquerade as a
            # completed batch
            self._terminate_pool()
            return None
        return results

    # -- public API --
    def scenario_values(self, W=None, milp=False, time_limit=None,
                        mip_gap=None, scenarios=None, kill_check=None,
                        return_x=False):
        """Per-scenario certified lower values of
        min (c_s + W_s on nonant slots)·x over the LP (milp=False) or
        integer-feasible (milp=True) set, c0 included.

        Returns (vals (S,), ok (S,), optimal (S,)) — non-selected /
        failed scenarios get -inf and ok=False — or None if kill_check
        tripped mid-refresh. With ``return_x`` a fourth element holds
        the per-scenario primal feasible point (obj_with_c0, x) or None
        — for MILPs that is the INCUMBENT (upper bound), while vals
        stay the certified dual bounds."""
        if W is not None and self.nonant_idx is None:
            # a None index would silently act as np.newaxis below,
            # smearing W over every objective entry
            raise ValueError("this pool has no nonant index map "
                             "(from_arrays without nonant_idx); W terms "
                             "are not supported")
        sel = range(self.S) if scenarios is None else scenarios
        tasks = []
        for s in sel:
            q = self.c[s].copy()
            if W is not None:
                q[self.nonant_idx] += np.asarray(W[s], dtype=np.float64)
            tasks.append((s, q, bool(milp), time_limit, mip_gap,
                          bool(return_x)))
        results = self._run(tasks, kill_check)
        if results is None:
            return None
        vals = np.full(self.S, -np.inf)
        ok = np.zeros(self.S, bool)
        opt = np.zeros(self.S, bool)
        xs = [None] * self.S
        for s, v, o, is_opt, primal in results:
            vals[s] = v + (self.c0[s] if np.isfinite(v) else 0.0)
            ok[s] = o
            opt[s] = is_opt
            if primal is not None:
                xs[s] = (primal[0] + self.c0[s], primal[1])
        if return_x:
            return vals, ok, opt, xs
        return vals, ok, opt

    def incumbent_value(self, xhat, prob, milp=None, time_limit=None,
                        mip_gap=None, kill_check=None, pin_mask=None):
        """EXACT expected objective of candidate first-stage plan
        ``xhat`` ((K,) or (S, K), fixed on the nonant columns): one
        host solve per scenario with lb=ub pinned — the certified
        INNER-bound evaluator for scales where the device evaluator's
        tolerance-level feasibility can mis-state penalty-dominated
        objectives by (violation × penalty) (see doc/tpu_numerics.md).
        ``milp`` defaults to True exactly when integer RECOURSE columns
        exist (first-stage integrality is already pinned by x̂).
        Returns the expected objective, or None on any infeasible /
        unfinished scenario or kill."""
        if self.nonant_idx is None:
            raise ValueError("this pool has no nonant index map")
        idx = np.asarray(self.nonant_idx)
        xhat = np.asarray(xhat, dtype=np.float64)
        if xhat.ndim == 1:
            xhat = np.broadcast_to(xhat, (self.S, idx.size))
        if pin_mask is not None:
            # pin only the deciding slots (see PHBase.calculate_incumbent
            # pin_mask) — derived nonants are left to the exact solve
            pm = np.asarray(pin_mask, bool)
            idx = idx[pm]
            xhat = xhat[:, pm]
        if milp is None:
            # conservative default: any integer column NOT pinned by x̂
            # forces a MILP (callers who know the unpinned integers are
            # DERIVED — integral at the LP optimum, e.g. UC startups
            # under positive startup costs — pass milp=False)
            rec = np.asarray(self._payload["integrality"], bool).copy()
            rec[idx] = False
            milp = bool(rec.any())
        # zero-probability rows (wheel padding: duplicates of real
        # scenarios added to reuse compiled device shapes) contribute
        # nothing to the expectation and duplicate a real row's
        # feasibility check — skipping them is exact, not a shortcut
        prob = np.asarray(prob, dtype=np.float64)
        live = np.flatnonzero(prob > 0.0)
        tasks = [(int(s), self.c[s].copy(), bool(milp), time_limit,
                  mip_gap, False, (idx, xhat[s])) for s in live]
        results = self._run(tasks, kill_check)
        if results is None:
            return None
        # poison-not-zero (ADVICE r5): live rows start NaN so a result
        # that silently never lands cannot enter the probability dot
        # product as a free 0.0 objective; padding (p=0) rows stay 0
        vals = np.zeros(self.S)
        vals[live] = np.nan
        for s, v, ok, is_opt, _ in results:
            if not (ok and is_opt):
                return None
            vals[s] = v + self.c0[s]
        if not np.isfinite(vals[live]).all():
            # a live row missing from the results (should be impossible
            # through _run, but a certified inner bound must not ride
            # on "should be"): refuse to publish rather than let a NaN
            # or zero placeholder enter the expectation. A plain check,
            # not an assert — the guard must survive python -O.
            return None
        return float(np.dot(prob, vals))

    def lagrangian_bound(self, prob, W=None, milp=False, time_limit=None,
                         mip_gap=None, kill_check=None):
        """E_p[scenario value with W] — the exact (LP) or MIP-tight
        Lagrangian outer bound when sum_s p_s W_s = 0 per (node, slot)
        (the caller projects). None when any scenario solve failed or
        the kill check tripped."""
        prob = np.asarray(prob, dtype=np.float64)
        live = np.flatnonzero(prob > 0.0)
        res = self.scenario_values(W, milp=milp, time_limit=time_limit,
                                   mip_gap=mip_gap, kill_check=kill_check,
                                   scenarios=live)
        if res is None:
            return None
        vals, ok, _ = res
        # zero-probability padding rows are unsolved (-inf) by design;
        # only the live rows carry the expectation
        if not ok[live].all():
            return None
        return float(np.dot(prob[live], vals[live]))

    def close(self):
        self._terminate_pool()

    def __del__(self):  # best-effort; spokes call close() in finalize
        try:
            self.close()
        except Exception:
            pass


def make_w_projector(batch):
    """Host-f64 projector onto the dual-feasible manifold
    sum_s p_s W_s = 0 per (node, slot): W -> W minus its p-weighted
    node mean, stage by stage. The per-stage (membership, node-mass)
    pairs are precomputed — they are static per batch and the projector
    runs on every bound refresh. Single implementation: the Lagrangian
    spoke and solve_lp_ef must project IDENTICALLY or their bound
    certificates diverge."""
    prob = np.asarray(batch.prob, dtype=np.float64)
    stages = []
    for t, sl in enumerate(batch.stage_slot_slices):
        B = np.asarray(batch.tree.membership(t + 1), dtype=np.float64)
        stages.append((sl, B, B.T @ prob))

    def project(W):
        W = np.asarray(W, dtype=np.float64).reshape(len(prob), -1).copy()
        for sl, B, pnode in stages:
            num = B.T @ (prob[:, None] * W[:, sl])
            W[:, sl] -= B @ (num / pnode[:, None])
        return W

    return project


def build_ef_parts(batch):
    """Sparse EQUALITY-ROW extensive-form pieces for host solvers.

    Variables [x_0 .. x_{S-1}, z-blocks per non-leaf tree node];
    per-scenario rows l <= A x_s <= u; linking rows
    x_s[nonant] - z_{node(s,t)} = 0. Shared by the LP-dual extractor
    (solve_lp_ef) and the host EF-MIP bounder — built sparse because
    the EF of a 1000-scenario batch is far too big dense. (The DEVICE
    EF engine (core/ef.py) substitutes shared columns instead; the
    equality-row form exists exactly because its linking-row duals are
    the Lagrangian warm start.)

    Returns dict with A_ineq ((S*m, nv) csr), l_all/u_all (S*m,),
    A_eq ((n_link, nv) csr), cv/lbv/ubv (nv,), integrality (nv,),
    c0 (scalar), nv, n_link."""
    from scipy import sparse

    S, n, m, K = batch.S, batch.n, batch.m, batch.K
    A = np.asarray(batch.A, dtype=np.float64)
    lb = np.asarray(batch.lb, dtype=np.float64)
    ub = np.asarray(batch.ub, dtype=np.float64)
    c = np.asarray(batch.c, dtype=np.float64)
    prob = np.asarray(batch.prob, dtype=np.float64)
    idx = np.asarray(batch.nonant_idx)
    integ = np.asarray(batch.integer, dtype=np.uint8)
    if np.abs(np.asarray(batch.P_diag)).max() > 0:
        raise ValueError("host oracle supports linear objectives only")

    # z-block layout: per non-leaf stage, per node, that stage's slots
    tree = batch.tree
    slot_counts = [sl.stop - sl.start for sl in batch.stage_slot_slices]
    z_off, off = [], S * n
    for t, N in enumerate(tree.nodes_per_stage):
        z_off.append(off)
        off += N * slot_counts[t]
    nv = off

    blocks = []
    for s in range(S):
        A_s = A if A.ndim == 2 else A[s]
        blocks.append(sparse.hstack(
            [sparse.csr_matrix((m, s * n)), sparse.csr_matrix(A_s),
             sparse.csr_matrix((m, nv - (s + 1) * n))]))
    A_ineq = sparse.vstack(blocks).tocsr()
    rows, cols, vals = [], [], []
    r = 0
    for s in range(S):
        for t, sl in enumerate(batch.stage_slot_slices):
            node = int(tree.node_path[s, t])
            zbase = z_off[t] + node * slot_counts[t]
            for k_local, j in enumerate(idx[sl.start:sl.stop]):
                rows += [r, r]
                cols += [s * n + int(j), zbase + k_local]
                vals += [1.0, -1.0]
                r += 1
    A_eq = sparse.csr_matrix((vals, (rows, cols)), shape=(r, nv))
    cv = np.zeros(nv)
    lbv = np.full(nv, -np.inf)
    ubv = np.full(nv, np.inf)
    integrality = np.zeros(nv, dtype=np.uint8)
    for s in range(S):
        cv[s * n:(s + 1) * n] = prob[s] * c[s]
        lbv[s * n:(s + 1) * n] = lb[s]
        ubv[s * n:(s + 1) * n] = ub[s]
        integrality[s * n:(s + 1) * n] = integ
    return {
        "A_ineq": A_ineq, "l_all": np.asarray(batch.l).reshape(-1),
        "u_all": np.asarray(batch.u).reshape(-1), "A_eq": A_eq,
        "cv": cv, "lbv": lbv, "ubv": ubv, "integrality": integrality,
        "c0": float(np.dot(prob, np.asarray(batch.c0, np.float64))),
        "nv": nv, "n_link": r,
    }


def solve_lp_ef(batch, time_limit=None):
    """Solve the LP relaxation of the equality-row extensive form on
    host and return ``(lp_obj, W_star)`` — the LP-EF optimum and the
    nonant linking-row duals mapped to PH convention.

    This is the decomposition-theory shortcut the tensor representation
    makes nearly free: the Lagrangian dual of the LP relaxation is
    MAXIMIZED at the LP-EF's linking-constraint duals, so
    ``W_star = -mu / p`` (projected onto sum_s p_s W_s = 0 per node)
    warm-starts any Lagrangian bounder at the LP ceiling instantly —
    no W iteration needed — and the MIP oracle evaluated AT ``W_star``
    starts within a whisker of the full Lagrangian dual. The reference
    reaches comparable W only after ~100 PH iterations of Gurobi solves
    (ref. examples/uc/quartz/10scen_nofw.baseline.out trajectory).

    Returns (None, None) when the LP fails (caller falls back to
    iterative bounds). Linear objectives, uniform-probability manifolds
    only (the standard oracle eligibility)."""
    from scipy import sparse
    from scipy.optimize import linprog

    S, K = batch.S, batch.K
    prob = np.asarray(batch.prob, dtype=np.float64)
    p = build_ef_parts(batch)
    fin_u = np.isfinite(p["u_all"])
    fin_l = np.isfinite(p["l_all"])
    A_ub = sparse.vstack([p["A_ineq"][fin_u], -p["A_ineq"][fin_l]])
    b_ub = np.concatenate([p["u_all"][fin_u], -p["l_all"][fin_l]])
    opts = {}
    if time_limit is not None:
        opts["time_limit"] = float(time_limit)
    res = linprog(p["cv"], A_ub=A_ub, b_ub=b_ub, A_eq=p["A_eq"],
                  b_eq=np.zeros(p["n_link"]),
                  bounds=list(zip(p["lbv"], p["ubv"])), method="highs",
                  options=opts)
    if res.status != 0 or res.eqlin is None:
        return None, None
    lp_obj = float(res.fun + p["c0"])
    mu = np.asarray(res.eqlin.marginals).reshape(S, K)
    # PH convention: subproblem objective carries +W_s·x with implied
    # multipliers p_s W_s; the EF row  x_s - z = 0  carries -mu (HiGHS
    # marginal sign), hence W = -mu/p. Re-project: simplex marginals of
    # degenerate LPs can be off-manifold at 1e-9-level, and the bound
    # certificate requires exact membership at f64.
    return lp_obj, make_w_projector(batch)(-mu / prob[:, None])


def ef_mip_pool(batch, n_workers=None):
    """OraclePool holding the equality-row EF as a batch of ONE
    problem — the host analog of the reference handing the monolithic
    EF to a rented B&B solver (ref. mpisppy/opt/ef.py:61,
    phbase.py:1307 SolverFactory). ``scenario_values(milp=True,
    return_x=True)`` then yields (dual bound, incumbent, x_EF) with
    kill-abortable subprocess execution."""
    from scipy import sparse

    p = build_ef_parts(batch)
    A = sparse.vstack([p["A_ineq"], p["A_eq"]]).tocsr()
    l = np.concatenate([p["l_all"], np.zeros(p["n_link"])])
    u = np.concatenate([p["u_all"], np.zeros(p["n_link"])])
    return OraclePool.from_arrays(
        A, l, u, p["lbv"], p["ubv"], p["integrality"],
        p["cv"], np.array([p["c0"]]), n_workers=n_workers)


def exact_scenario_lp_values(batch, W=None, time_limit=None):
    """Per-scenario EXACT LP values (inline, transient) — see OraclePool
    for the persistent/pooled path. Returns (values (S,), ok (S,) bool);
    failed scenarios get -inf. A ``time_limit`` (seconds per scenario)
    bounds each solve so one degenerate LP cannot stall a caller's bound
    refresh indefinitely; timeouts come back ok=False."""
    pool = OraclePool(batch, n_workers=0)
    vals, ok, _ = pool.scenario_values(W, milp=False, time_limit=time_limit)
    return vals, ok


def exact_lagrangian_bound(batch, prob, W=None):
    """E_p[exact scenario LP value with W] — the exact Lagrangian outer
    bound when sum_s p_s W_s = 0 per (node, slot) (the caller projects).
    Returns None when any scenario solve failed."""
    vals, ok = exact_scenario_lp_values(batch, W)
    if not ok.all():
        return None
    return float(np.dot(np.asarray(prob, dtype=np.float64), vals))
