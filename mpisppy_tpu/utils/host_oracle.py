"""Host-side exact LP/MILP oracle for bound certification.

The Lagrangian outer bound L(W) = sum_s p_s min_x [f_s(x) + W_s x_nonant]
is an accuracy-critical, latency-insensitive quantity: it gates hub
termination (time-to-gap), runs once per spoke sync (not per PH
iteration), and its tightness is what the headline gap metric measures.
The batched first-order kernel's certified-from-inexact-duals bound
(ops/qp_solver.qp_dual_objective) is VALID at any accuracy but pays
|reduced cost| x box width per column — on UC-scale problems that can sit
1-3% below the true Lagrangian value until the duals are extremely
converged. A simplex solve is exact.

Two oracle modes, mirroring the two bound regimes of the reference:

- **LP**: exact L(W) of the LP relaxation. Floor: the instance's
  LP integrality gap — no W can push an LP-relaxation bound past it.
- **MILP**: min over the INTEGER-feasible set per scenario (the true
  Lagrangian dual function), the analog of the reference's Lagrangian
  spoke solving MIP subproblems with W on (ref.
  mpisppy/cylinders/lagrangian_bounder.py:54-56 driving
  phbase.py:947-949 MIP solves) — which is how the reference's UC gaps
  reach 0.026-0.073% while LP bounds stall near the ~1% integrality
  gap. Each scenario value is HiGHS's B&B dual bound, valid at any
  time_limit / mip_rel_gap stop.

Scenario solves fan out over a persistent pool of dedicated worker
subprocesses (the reference's per-rank parallel solve fan-out, ref.
phbase.py:999); see _oracle_worker for why plain subprocesses rather
than multiprocessing. n_workers=0 runs solves inline — same results, no
IPC.

Only LINEAR objectives are supported (a Lagrangian bound of an LP/MIP
relaxation); quadratic models keep the on-device certified bound.
"""

from __future__ import annotations

import os
import queue
import subprocess
import sys
import threading

import numpy as np

from . import _oracle_worker


class _ProcWorker:
    """One oracle subprocess: ``python -m ..._oracle_worker`` with the
    static payload shipped as its first stdin frame. See the worker
    module's docstring for why this is a subprocess, not
    multiprocessing."""

    def __init__(self, payload):
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "mpisppy_tpu.utils._oracle_worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)
        _oracle_worker.write_msg(self.proc.stdin, payload)

    def solve(self, task):
        _oracle_worker.write_msg(self.proc.stdin, task)
        r = _oracle_worker.read_msg(self.proc.stdout)
        if r is None:
            raise RuntimeError("oracle worker subprocess died")
        return r

    def kill(self):
        try:
            self.proc.kill()
            self.proc.wait(timeout=5.0)
        except Exception:
            pass


class OraclePool:
    """Persistent per-scenario LP/MILP solve fan-out for one batch.

    Ships the static problem data (A, row/box bounds, integrality) to
    each worker once at pool startup; per-call messages carry only the
    objective vectors. Keep ONE instance alive across bound refreshes —
    worker startup and data shipping are paid once (the warm-start
    analog of the reference's persistent solver plugins,
    ref. phbase.py:1304-1362).
    """

    def __init__(self, batch, n_workers=None):
        if np.abs(np.asarray(batch.P_diag)).max() > 0:
            raise ValueError("host oracle supports linear objectives only")
        self.S = int(batch.S)
        self.c = np.asarray(batch.c, dtype=np.float64)
        self.c0 = np.asarray(batch.c0, dtype=np.float64)
        self.nonant_idx = np.asarray(batch.nonant_idx)
        A = np.asarray(batch.A, dtype=np.float64)
        if A.ndim == 3 and all(np.array_equal(A[s], A[0])
                               for s in range(1, A.shape[0])):
            # shared structure (scenarios differ in bounds/costs only —
            # every shipped model family): ship ONE matrix, not S copies
            # ((S,m,n) at S=1024 would be gigabytes of payload)
            A = A[0]
        self._payload = {
            "A": A,
            "l": np.asarray(batch.l, dtype=np.float64),
            "u": np.asarray(batch.u, dtype=np.float64),
            "lb": np.asarray(batch.lb, dtype=np.float64),
            "ub": np.asarray(batch.ub, dtype=np.float64),
            "integrality": np.asarray(batch.integer, dtype=np.uint8),
        }
        # n_workers=0 → inline (no subprocesses); None → one worker per
        # host core, capped at S. Even on a 1-core host the default is a
        # 1-worker subprocess pool: the wheel's hub drives the
        # accelerator, so an oracle SUBPROCESS overlaps bound refreshes
        # with hub iterations where an inline solve would hold this
        # spoke's thread (and, GIL permitting, the whole process)
        cpus = os.cpu_count() or 1
        if n_workers is not None and int(n_workers) == 0:
            self.n_workers = 0
        else:
            self.n_workers = max(1, min(self.S, cpus if n_workers is None
                                        else int(n_workers)))
        self._pool = None          # created lazily on first pooled call
        self._inline_state = None

    # -- execution backends --
    def _ensure_inline(self):
        if self._inline_state is None:
            # run the worker init in-process; the state is PER-POOL so
            # concurrent inline pools over different batches coexist
            self._inline_state = _oracle_worker.init_worker(self._payload)
        return self._inline_state

    def _ensure_pool(self):
        if self._pool is None:
            self._pool = [_ProcWorker(self._payload)
                          for _ in range(self.n_workers)]
        return self._pool

    def _terminate_pool(self):
        if self._pool is not None:
            for w in self._pool:
                w.kill()
            self._pool = None

    def _run(self, tasks, kill_check=None):
        """Run solve tasks; returns results (scenario ids inside).

        ``kill_check()`` (optional) is polled while waiting; when it
        returns True remaining work is abandoned (the worker
        subprocesses are killed and respawn on next use) and None is
        returned — bound refreshes can take tens of seconds and must
        not hold a terminating wheel hostage (VERDICT r2 weak #5).
        Inline mode (n_workers=0) can only poll BETWEEN scenario
        solves — there is no subprocess to kill mid-solve — so its
        abort latency is one scenario's time_limit; callers that need
        prompt termination should keep per-scenario limits modest or
        use the pooled mode."""
        if self.n_workers == 0:
            state = self._ensure_inline()
            out = []
            for t in tasks:
                if kill_check is not None and kill_check():
                    return None
                out.append(_oracle_worker.solve_scenario(state, t))
            return out
        workers = self._ensure_pool()
        tq = queue.Queue()
        for t in tasks:
            tq.put(t)
        results, errors = [], []
        lock = threading.Lock()
        abort = threading.Event()

        def drive(w):
            try:
                while not abort.is_set():
                    try:
                        t = tq.get_nowait()
                    except queue.Empty:
                        return
                    r = w.solve(t)
                    with lock:
                        results.append(r)
            except BaseException as e:   # worker death surfaces to caller
                if not abort.is_set():
                    with lock:
                        errors.append(e)
                    # stop the surviving workers too: their results are
                    # discarded anyway once the call raises
                    abort.set()

        threads = [threading.Thread(target=drive, args=(w,), daemon=True)
                   for w in workers]
        for th in threads:
            th.start()
        while any(th.is_alive() for th in threads):
            for th in threads:
                th.join(timeout=0.05)
            if kill_check is not None and kill_check():
                abort.set()
                # killing the subprocesses EOFs the blocked reads, so
                # the driver threads exit promptly
                self._terminate_pool()
                return None
        if errors:
            self._terminate_pool()
            raise RuntimeError("oracle pool worker failed") from errors[0]
        return results

    # -- public API --
    def scenario_values(self, W=None, milp=False, time_limit=None,
                        mip_gap=None, scenarios=None, kill_check=None):
        """Per-scenario certified lower values of
        min (c_s + W_s on nonant slots)·x over the LP (milp=False) or
        integer-feasible (milp=True) set, c0 included.

        Returns (vals (S,), ok (S,), optimal (S,)) — non-selected /
        failed scenarios get -inf and ok=False — or None if kill_check
        tripped mid-refresh."""
        sel = range(self.S) if scenarios is None else scenarios
        tasks = []
        for s in sel:
            q = self.c[s].copy()
            if W is not None:
                q[self.nonant_idx] += np.asarray(W[s], dtype=np.float64)
            tasks.append((s, q, bool(milp), time_limit, mip_gap))
        results = self._run(tasks, kill_check)
        if results is None:
            return None
        vals = np.full(self.S, -np.inf)
        ok = np.zeros(self.S, bool)
        opt = np.zeros(self.S, bool)
        for s, v, o, is_opt in results:
            vals[s] = v + (self.c0[s] if np.isfinite(v) else 0.0)
            ok[s] = o
            opt[s] = is_opt
        return vals, ok, opt

    def lagrangian_bound(self, prob, W=None, milp=False, time_limit=None,
                         mip_gap=None, kill_check=None):
        """E_p[scenario value with W] — the exact (LP) or MIP-tight
        Lagrangian outer bound when sum_s p_s W_s = 0 per (node, slot)
        (the caller projects). None when any scenario solve failed or
        the kill check tripped."""
        res = self.scenario_values(W, milp=milp, time_limit=time_limit,
                                   mip_gap=mip_gap, kill_check=kill_check)
        if res is None:
            return None
        vals, ok, _ = res
        if not ok.all():
            return None
        return float(np.dot(np.asarray(prob, dtype=np.float64), vals))

    def close(self):
        self._terminate_pool()

    def __del__(self):  # best-effort; spokes call close() in finalize
        try:
            self.close()
        except Exception:
            pass


def exact_scenario_lp_values(batch, W=None, time_limit=None):
    """Per-scenario EXACT LP values (inline, transient) — see OraclePool
    for the persistent/pooled path. Returns (values (S,), ok (S,) bool);
    failed scenarios get -inf. A ``time_limit`` (seconds per scenario)
    bounds each solve so one degenerate LP cannot stall a caller's bound
    refresh indefinitely; timeouts come back ok=False."""
    pool = OraclePool(batch, n_workers=0)
    vals, ok, _ = pool.scenario_values(W, milp=False, time_limit=time_limit)
    return vals, ok


def exact_lagrangian_bound(batch, prob, W=None):
    """E_p[exact scenario LP value with W] — the exact Lagrangian outer
    bound when sum_s p_s W_s = 0 per (node, slot) (the caller projects).
    Returns None when any scenario solve failed."""
    vals, ok = exact_scenario_lp_values(batch, W)
    if not ok.all():
        return None
    return float(np.dot(np.asarray(prob, dtype=np.float64), vals))
