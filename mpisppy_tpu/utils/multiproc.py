"""Multi-process cylinders over the native shared-memory windows.

The reference runs each cylinder as its own MPI process group and wires
the hub-spoke star with MPI RMA windows (ref. mpisppy/utils/sputils.py:
133-151 make_comms, cylinders/spcommunicator.py:97-124). Here each spoke
runs as its own OS process with its own engine (and its own Python/GIL,
solver state, and — on a multi-chip host — its own device), talking to
the hub through the native seqlock windows (ops/native/spwindow). The
write-id/kill protocol is byte-identical to the in-process backend, so
hub and spoke code runs unchanged.

Resource split: spoke processes default to the CPU backend
(JAX_PLATFORMS=cpu) so the accelerator stays exclusively the hub's —
bound evaluation rides host cores, the batched PH iteration rides the
chip. On a multi-chip host, per-spoke ``jax_platform`` /
``jax_visible_devices`` options pin each cylinder to its own chip (see
_spoke_worker) — the real deployment shape of the reference's
process grid (one cylinder per rank group, ref. sputils.py:133-151).

The full spoke taxonomy runs as processes, including the
cross-scenario cut spoke (its larger cut-window layout is sized by the
hub-side proxy).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import secrets
import time

from .. import global_toc, obs
from ..cylinders.spcommunicator import Window
from ..cylinders.spoke import ConvergerSpokeType
from .config import RunConfig, config_from_dict


def _telemetry_out_dir(cfg):
    """The run directory spoke children should capture into: the
    config's explicit ``telemetry_dir`` wins, then a programmatically
    configured parent session (``obs.configure(out_dir=...)`` with no
    config field — the path the env-var-only propagation silently
    dropped), then the env var the spawn children inherit anyway."""
    d = getattr(cfg, "telemetry_dir", None)
    if d:
        return d
    rec = obs.active()
    if rec is not None and rec.out_dir:
        return rec.out_dir
    return os.environ.get("MPISPPY_TPU_TELEMETRY_DIR") or None


class SpokeProxy:
    """Hub-side stand-in for a spoke living in another process: just the
    classification surface + the shared window pair."""

    def __init__(self, spoke_cls, S, K, hub_window, my_window):
        self._spoke_cls = spoke_cls
        self.converger_spoke_types = spoke_cls.converger_spoke_types
        self.converger_spoke_char = spoke_cls.converger_spoke_char
        self.is_cut_spoke = bool(getattr(spoke_cls, "is_cut_spoke", False))
        self._S, self._K = S, K
        self.hub_window = hub_window
        self.my_window = my_window

    def hub_read_layout(self):
        ts = self.converger_spoke_types
        return (ConvergerSpokeType.W_GETTER in ts,
                ConvergerSpokeType.NONANT_GETTER in ts)

    def remote_window_length(self) -> int:
        has_w, has_x = self.hub_read_layout()
        return self._S * self._K * (int(has_w) + int(has_x))

    def local_window_length(self) -> int:
        # the spoke class owns its payload layout (Spoke.payload_length:
        # 1 for bound spokes, 2 for the dual-typed EF-MIP spoke,
        # S*(1+K) for the cut spoke) — sizing it here too would let the
        # hub-side and child-side windows drift apart. Every spoke→hub
        # window carries the bound-flow lineage suffix
        # (spcommunicator.LINEAGE_SLOTS).
        from ..cylinders.spcommunicator import LINEAGE_SLOTS
        return self._spoke_cls.payload_length(self._S, self._K) \
            + LINEAGE_SLOTS


def _spoke_worker(cfg_dict, spoke_cfg_dict, hub_name, my_name, f32,
                  telemetry=None):
    """Runs in the child process: build the engine from the config, wire
    the shared windows, loop until the hub's kill signal.

    Per-process device assignment (the real multi-chip deployment shape:
    one cylinder per chip, ref. sputils.py:133-151 process-grid): the
    spoke's options may carry ``jax_platform`` ("cpu" default — the
    accelerator stays the hub's) and ``jax_visible_devices`` (a
    TPU_VISIBLE_DEVICES / CUDA_VISIBLE_DEVICES value pinning this
    cylinder to its chip). Both must land in the environment BEFORE jax
    imports in this process."""
    opts = spoke_cfg_dict.get("options") or {}
    platform = str(opts.get("jax_platform", "cpu"))
    os.environ["JAX_PLATFORMS"] = platform
    vis = opts.get("jax_visible_devices")
    if vis is not None:
        env_key = {"tpu": "TPU_VISIBLE_DEVICES",
                   "gpu": "CUDA_VISIBLE_DEVICES",
                   "cuda": "CUDA_VISIBLE_DEVICES"}.get(platform)
        if env_key:
            os.environ[env_key] = str(vis)
    # the env var alone is NOT enough: jax binds jax_platforms from the
    # environment at import time, and the spawn machinery imports jax
    # (module-level jax.numpy imports in the pickled call graph) before
    # this worker body runs — under a tunneled-TPU parent the child
    # would silently fight the hub for the single-process device link
    import jax

    jax.config.update("jax_platforms", platform)
    from .runtime import maybe_init_distributed, setup_jax_runtime

    setup_jax_runtime(f32)
    # a spoke pinned to its own accelerator slice on another host may
    # carry its own coordinator spec (options["coordinator"]) and join
    # a multi-process JAX cluster of its own; the HUB's coordinator
    # (cfg.coordinator) is deliberately NOT inherited here — spoke
    # processes default to isolated single-process runtimes
    maybe_init_distributed(opts.get("coordinator"))

    # telemetry capture for THIS cylinder process: role-suffixed
    # artifacts (events-<role>.jsonl / trace-<role>.json) in the run
    # directory the hub propagated through the bootstrap — spawned
    # children share no recorder with the parent, so without this the
    # spoke's bound events and spans silently vanish. The env-var path
    # still works when no explicit dir was propagated.
    from .. import obs as _obs
    if telemetry and telemetry.get("out_dir"):
        _obs.configure(out_dir=telemetry["out_dir"],
                       role=telemetry.get("role"), config=spoke_cfg_dict)
    elif telemetry:
        _obs.maybe_configure_from_env(role=telemetry.get("role"))

    from .config import SpokeConfig
    from .vanilla import spoke_dict

    cfg = config_from_dict(cfg_dict)
    sd = spoke_dict(cfg, SpokeConfig(**spoke_cfg_dict))
    opt = sd["opt_class"](**sd["opt_kwargs"])
    spoke = sd["spoke_class"](opt, **sd.get("spoke_kwargs", {}))
    spoke.hub_window = Window.shared(hub_name,
                                     spoke.remote_window_length(),
                                     create=False)
    spoke.my_window = Window.shared(my_name, spoke.local_window_length(),
                                    create=False)
    # fault injection (testing/faults.py) is gated on an EXPLICIT plan
    # (spoke option or env var): the import — and every wrapper it
    # installs — exists only in faulted test children, never on the
    # production path (tests/test_faults.py asserts the clean path
    # imports nothing from mpisppy_tpu.testing)
    fault_spec = opts.get("fault_plan") \
        or os.environ.get("MPISPPY_TPU_FAULT_PLAN")
    if fault_spec:
        # lint: ok[PURE001] env/option-gated: reached only in children given an explicit fault plan (clean-path probe backstops)
        from ..testing.faults import FaultInjector
        injector = FaultInjector.from_spec(
            fault_spec,
            index=(telemetry or {}).get("index", 0),
            gen=(telemetry or {}).get("gen", 0))
        injector.sleep_before_hello()
        injector.install(spoke)
    # startup handshake: a NaN hello tells the hub this spoke is wired and
    # looping (the reference's window-size Send/Recv handshake analog,
    # ref. hub.py:285-308). NaN never wins a bound comparison, so the
    # hub consumes it harmlessly.
    import numpy as np
    spoke.my_window.put(np.full(spoke.local_window_length(), np.nan))
    try:
        # warm resume (mpisppy_tpu.ckpt): a spoke handed a
        # ``resume_state`` option re-publishes its checkpointed best
        # bound as its FIRST publish — after the hello (the hub's
        # readiness gate) and before main() recomputes anything, so a
        # respawned incarnation's first bound is never worse than its
        # predecessor's best
        spoke.resume_publish()
        spoke.main()
        spoke.finalize()
    finally:
        # flush + close this process's telemetry BEFORE the windows
        # drop, so a hub-side merge running right after the join sees
        # complete role artifacts (atexit would also flush, but later
        # than the parent's join returns)
        _obs.shutdown()
        spoke.hub_window.close(unlink=False)
        spoke.my_window.close(unlink=False)


def _spoke_window_names(run_id, i, gen=0):
    """THE window naming scheme (creator and opener must agree).
    ``gen`` > 0 names a respawned incarnation's FRESH pair — a dead
    generation's windows are never reused (a crashed writer may have
    died mid-seqlock); they stay in the launcher's owned list and are
    unlinked at wheel teardown."""
    suffix = f"r{gen}" if gen else ""
    return f"{run_id}h{i}{suffix}", f"{run_id}s{i}{suffix}"


def _spoke_proxy(kind, run_id, i, S, K, create, gen=0):
    """One spoke's proxy with its window pair, on either side of the
    shm handshake (create=True: wheel launcher; False: a consumer in
    another process, e.g. the sharded-APH hub shard)."""
    from .vanilla import spoke_classes

    spoke_cls, _ = spoke_classes(kind)
    hub_name, my_name = _spoke_window_names(run_id, i, gen)
    proxy = SpokeProxy(spoke_cls, S, K, None, None)
    proxy.hub_window = Window.shared(
        hub_name, proxy.remote_window_length(), create=create)
    proxy.my_window = Window.shared(
        my_name, proxy.local_window_length(), create=create)
    return proxy


def open_spoke_proxies(spoke_kinds, run_id, S, K):
    """Open (create=False) the window pairs spawn_spoke_processes
    created — the consumer side of the ONE naming scheme."""
    return [_spoke_proxy(kind, run_id, i, S, K, create=False)
            for i, kind in enumerate(spoke_kinds)]


def _spawn_one_spoke(cfg: RunConfig, i, run_id, ctx, S, K, f32, tdir,
                     gen=0):
    """Window pair + worker process for ONE spoke (generation ``gen``).
    The single spawn body shared by the initial launch and the
    supervisor's respawn path — both incarnations are wired
    identically, only the window names and the telemetry role carry
    the generation."""
    from dataclasses import asdict

    sp = cfg.spokes[i]
    sp_dict = asdict(sp)
    if cfg.checkpoint_dir or cfg.resume_from:
        # checkpoint/resume wiring (mpisppy_tpu.ckpt): where this
        # incarnation WRITES its warm state, and — for respawns
        # (gen > 0, the supervisor path) or a --resume-from launch —
        # the state file it resumes FROM. This is what turns the
        # supervisor's respawn into "resume the spoke": generation N
        # starts from the freshest state generation N-1 persisted.
        from ..ckpt.spoke_state import spoke_resume_options
        for k, v in spoke_resume_options(
                cfg.checkpoint_dir, cfg.resume_from, i, sp.kind,
                gen=gen).items():
            sp_dict["options"].setdefault(k, v)
    proxy = _spoke_proxy(sp.kind, run_id, i, S, K, create=True, gen=gen)
    # explicit telemetry propagation (not only the inherited env var):
    # each child captures into the shared run dir under its own role
    # so artifacts never clobber; a respawned incarnation gets a
    # gen-suffixed role so the dead child's events survive beside it
    role = f"spoke{i}-{sp.kind}" + (f"-r{gen}" if gen else "")
    telemetry = {"out_dir": tdir, "role": role, "index": i, "gen": gen}
    p = ctx.Process(target=_spoke_worker,
                    args=(cfg.to_dict(), sp_dict,
                          *_spoke_window_names(run_id, i, gen), f32,
                          telemetry),
                    daemon=True)
    p.start()
    return proxy, p


def spawn_spoke_processes(cfg: RunConfig, run_id, ctx, S, K, f32=False):
    """Create the window pair + worker process for every spoke in
    ``cfg`` (window names ``{run_id}h{i}`` / ``{run_id}s{i}`` — the ONE
    naming scheme; spin_the_wheel_processes and the sharded-APH wheel
    launcher both spawn through here). Returns (proxies, procs,
    owned_windows); the caller owns window unlink and process joins."""
    tdir = _telemetry_out_dir(cfg)
    proxies, procs, owned = [], [], []
    for i in range(len(cfg.spokes)):
        proxy, p = _spawn_one_spoke(cfg, i, run_id, ctx, S, K, f32, tdir)
        owned += [proxy.hub_window, proxy.my_window]
        proxies.append(proxy)
        procs.append(p)
    return proxies, procs, owned


def wait_spoke_hellos(cfg: RunConfig, proxies, procs, timeout, hub=None):
    """Block until every spoke's startup hello lands (so gap-based
    termination cannot fire before cold-starting spoke processes have
    joined the wheel). With ``hub`` given, a fired wheel watchdog
    aborts the wait — the deadline covers startup too."""
    deadline = time.monotonic() + timeout
    for i, proxy in enumerate(proxies):
        while proxy.my_window.read_id() == 0:
            if hub is not None and hub._watchdog_fired:
                raise TimeoutError(
                    "wheel deadline fired while waiting for spoke "
                    f"hellos (spoke {cfg.spokes[i].kind} still silent)")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"spoke {cfg.spokes[i].kind} (pid {procs[i].pid}) "
                    "never sent its startup hello")
            if not procs[i].is_alive():
                raise RuntimeError(
                    f"spoke {cfg.spokes[i].kind} died during startup")
            time.sleep(0.05)


def spin_the_wheel_processes(cfg: RunConfig, join_timeout=None, f32=False,
                             spoke_ready_timeout=None):
    """One hub (this process) + one OS process per spoke. Returns the hub
    after termination; ``hub._spoke_last_ids`` counts consumed updates
    (>= 1 is the startup hello; > 1 means real bound traffic).

    The hub waits up to ``spoke_ready_timeout`` for every spoke's startup
    hello before iterating, so a gap-based termination cannot fire before
    cold-starting spoke processes (JAX init + first compile) have joined
    the wheel. The spawn context is used so children re-initialize JAX
    cleanly (a forked JAX runtime is unsupported).

    The wheel is SUPERVISED (cylinders/supervisor.py, configured by
    ``cfg.supervisor``): dead spokes are detected from the hub's sync
    path and respawned on fresh window pairs with capped backoff,
    repeat offenders are quarantined while the wheel continues, and
    ``cfg.wheel_deadline`` arms a watchdog that terminates a hung
    wheel cleanly (telemetry flushed, partial bounds reported). Both
    timeouts default from the config (``cfg.join_timeout`` /
    ``cfg.spoke_ready_timeout``); explicit arguments win."""
    cfg.validate()
    # multi-host wheels: bring up multi-process JAX (DCN) before the
    # hub engine touches devices, so a ``mesh_devices`` hub shards over
    # the GLOBAL device set while spokes keep their per-process
    # runtimes (doc/sharding.md) — the PR 5 supervision layer
    # (heartbeats, respawn on fresh windows, quarantine) is exactly the
    # fault model a pod needs
    from .runtime import maybe_init_distributed

    maybe_init_distributed(cfg.coordinator)
    join_timeout = cfg.join_timeout if join_timeout is None \
        else join_timeout
    spoke_ready_timeout = cfg.spoke_ready_timeout \
        if spoke_ready_timeout is None else spoke_ready_timeout

    # a config-carried telemetry dir enables the parent's session too
    # (programmatic callers bypass __main__.run, which does this for
    # the CLI) — the hub's own events/trace must land beside the
    # spokes' role artifacts for the merge to mean anything
    if cfg.telemetry_dir and not obs.enabled():
        obs.configure(out_dir=cfg.telemetry_dir, config=cfg.to_dict())

    from .vanilla import hub_dict

    hub_d = hub_dict(cfg)
    hub_opt = hub_d["opt_class"](**hub_d["opt_kwargs"])
    # the cylinder wire format carries REAL scenarios only: a sharded
    # hub pads its batch to the mesh (doc/sharding.md) but spokes run
    # unpadded engines and the window lengths must agree on both sides
    S, K = getattr(hub_opt, "_S_orig", hub_opt.batch.S), hub_opt.batch.K
    run_id = f"/spw{os.getpid():x}{secrets.token_hex(4)}"

    ctx = mp.get_context("spawn")
    proxies, procs, owned = [], [], []
    supervisor = None
    hub = None
    prev_sigterm = None
    try:
        proxies, procs, owned = spawn_spoke_processes(cfg, run_id, ctx,
                                                      S, K, f32)
        hub = hub_d["hub_class"](hub_opt, spokes=proxies,
                                 **hub_d.get("hub_kwargs", {}))
        hub.classify_spokes()
        hub.windows_made = True
        hub.setup_hub()
        # supervision: liveness + respawn + quarantine polled from the
        # hub's sync path; the respawner re-enters _spawn_one_spoke on
        # a generation-suffixed fresh window pair
        from ..cylinders.supervisor import WheelSupervisor

        tdir = _telemetry_out_dir(cfg)

        def _respawner(i, gen):
            return _spawn_one_spoke(cfg, i, run_id, ctx, S, K, f32,
                                    tdir, gen=gen)

        supervisor = WheelSupervisor(
            proxies, procs, kinds=[sp.kind for sp in cfg.spokes],
            options=cfg.supervisor, respawner=_respawner, owned=owned)
        supervisor.attach(hub)
        if cfg.wheel_deadline:
            supervisor.start_watchdog(cfg.wheel_deadline)
        # deterministic hub-side faults (testing/faults.py): the
        # harness can preempt (SIGTERM) or crash the HUB process at a
        # named iteration, the way spoke plans crash spokes. Import
        # gated on the env var — the clean path imports nothing from
        # mpisppy_tpu.testing (tests/test_faults.py asserts it).
        hub_fault_spec = os.environ.get("MPISPPY_TPU_FAULT_PLAN")
        if hub_fault_spec:
            # lint: ok[PURE001] env-gated: MPISPPY_TPU_FAULT_PLAN only — the clean path never imports testing (probe backstops)
            from ..testing.faults import install_hub_faults
            install_hub_faults(hub, hub_fault_spec)
        # the preemption notice path (doc/fault_tolerance.md): with
        # checkpointing armed, SIGTERM forces one final bundle +
        # nonblocking telemetry flush + clean terminate (bench.py's
        # signal-safe flush pattern) instead of losing the whole
        # optimization state. Handler restored on every exit path
        # (outermost finally).
        if cfg.checkpoint_dir:
            import signal as _signal

            def _on_sigterm(signum, frame):
                hub.handle_preemption("sigterm")
            try:
                prev_sigterm = _signal.signal(_signal.SIGTERM,
                                              _on_sigterm)
            except ValueError:
                prev_sigterm = None     # not the main thread
        wait_spoke_hellos(cfg, proxies, procs, spoke_ready_timeout,
                          hub=hub)
        try:
            hub.main()
        finally:
            # no respawns once termination starts; then release the
            # spokes (the in-process wheel guards the same way,
            # utils/sputils.py) — otherwise the children poll forever
            # on windows the cleanup unlinks
            supervisor.shutdown()
            hub.send_terminate()
            for p in procs:
                p.join(timeout=join_timeout)
                if p.is_alive():
                    global_toc(f"multiproc: spoke pid {p.pid} missed the "
                               "join timeout; terminating")
                    p.terminate()
        hub.receive_bounds()
        hub.hub_finalize()
        tdir = _telemetry_out_dir(cfg)
        if tdir:
            # every child flushed its role artifacts before its join
            # returned; persist the hub's own trace, then merge all
            # processes onto one wall-clock-aligned Perfetto timeline
            obs.flush()
            from ..obs.merge import merge_traces
            try:
                merged = merge_traces(tdir)
                if merged:
                    global_toc(f"telemetry: merged multi-process trace "
                               f"-> {merged}")
            except Exception as e:   # diagnostics must not kill a run
                global_toc(f"telemetry: trace merge failed: {e!r}")
        return hub
    except BaseException:
        # startup-failure cleanup: a hello timeout (or any raise before
        # the normal terminate/join path) must not leak live children —
        # daemon processes would otherwise linger, polling windows the
        # finally below unlinks, until interpreter exit. The status
        # server's port is released the same way (the normal path stops
        # it in hub_finalize).
        if hub is not None:
            hub.shutdown_live()
        if supervisor is not None:
            supervisor.shutdown()
        for p in procs:
            if p.is_alive():
                p.terminate()
        for p in procs:
            p.join(timeout=10.0)
            if p.is_alive():
                p.kill()
                p.join(timeout=10.0)
        raise
    finally:
        if prev_sigterm is not None:
            import signal as _signal
            _signal.signal(_signal.SIGTERM, prev_sigterm)
        for w in owned:
            w.close(unlink=True)
