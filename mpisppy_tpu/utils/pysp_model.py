"""PySP interop: ingest legacy ScenarioStructure.dat trees.

The reference's PySPModel adapter (ref. mpisppy/utils/pysp_model.py:41)
consumes PySP 1.0 inputs — an abstract Pyomo model plus a
``ScenarioStructure.dat`` describing stages, nodes, conditional
probabilities, and per-stage variables — and produces the
scenario_creator/names the framework needs. The model half of that
contract is Pyomo-specific (abstract AMPL-data models); the TPU port
keeps the reference's boundary by splitting it:

  - ``read_scenario_structure(text)`` parses the ScenarioStructure.dat
    grammar into this framework's ScenarioTree (stages, node paths,
    scenario probabilities, per-stage nonant variable names), and
  - ``PySPModel`` pairs that tree with a scenario_creator callback
    written against the native Model DSL (the analog of the reference's
    requirement that the abstract model be instantiable per scenario).

Scenario order follows leaf-node declaration order; the parser reorders
to node-contiguity when needed (the same guarantee the reference's rank
map engineers, ref. sputils.py:635-659).
"""

from __future__ import annotations

import re

import numpy as np

from ..ir.tree import ScenarioTree


def _set_block(text, name):
    """``set Name := a b c ;`` -> [a, b, c] (None if absent)."""
    m = re.search(rf"set\s+{re.escape(name)}\s*:=\s*([^;]*);", text)
    return m.group(1).split() if m else None


def _indexed_set_blocks(text, name):
    """``set Name[idx] := a b ;`` -> {idx: [a, b]}."""
    out = {}
    for m in re.finditer(rf"set\s+{re.escape(name)}\s*\[\s*([^\]]+)\s*\]"
                         rf"\s*:=\s*([^;]*);", text):
        out[m.group(1).strip()] = m.group(2).split()
    return out
def _param_block(text, name):
    """``param Name := k1 v1 k2 v2 ;`` -> {k1: v1, ...}."""
    m = re.search(rf"param\s+{re.escape(name)}\s*:=\s*([^;]*);", text)
    if not m:
        return {}
    toks = m.group(1).split()
    return {toks[i]: toks[i + 1] for i in range(0, len(toks) - 1, 2)}


def read_scenario_structure(text: str) -> ScenarioTree:
    """Parse a PySP ScenarioStructure.dat into a ScenarioTree."""
    stages = _set_block(text, "Stages")
    if not stages:
        raise ValueError("no `set Stages` block found")
    node_stage = _param_block(text, "NodeStage")
    children = _indexed_set_blocks(text, "Children")
    cond_prob = {k: float(v)
                 for k, v in _param_block(text,
                                          "ConditionalProbability").items()}
    scen_leaf = _param_block(text, "ScenarioLeafNode")
    stage_vars = _indexed_set_blocks(text, "StageVariables")

    if not scen_leaf:
        raise ValueError("no `param ScenarioLeafNode` block found")
    parent = {c: p for p, cs in children.items() for c in cs}

    def path_to_root(node):
        path = [node]
        while path[-1] in parent:
            path.append(parent[path[-1]])
        return path[::-1]            # root .. leaf

    T = len(stages)
    stage_idx = {s: i for i, s in enumerate(stages)}   # 0-based

    # depth-first leaf order from the root keeps scenarios node-contiguous
    roots = [n for n, s in node_stage.items() if stage_idx[s] == 0]
    if len(roots) != 1:
        raise ValueError(f"expected one root node, found {roots}")
    order = []

    def dfs(node):
        kids = children.get(node, [])
        if not kids:
            order.append(node)
        for k in kids:
            dfs(k)

    dfs(roots[0])
    leaf_to_scen = {leaf: s for s, leaf in scen_leaf.items()}
    scen_names = [leaf_to_scen[leaf] for leaf in order
                  if leaf in leaf_to_scen]

    # per-stage node numbering in dfs-encounter order
    node_ids = [dict() for _ in range(T - 1)]   # non-leaf stages only

    def number(node):
        t = stage_idx[node_stage[node]]
        if t < T - 1 and node not in node_ids[t]:
            node_ids[t][node] = len(node_ids[t])
        for k in children.get(node, []):
            number(k)

    number(roots[0])

    S = len(scen_names)
    node_paths = np.zeros((S, T - 1), dtype=np.int32)
    probs = np.zeros(S)
    for i, name in enumerate(scen_names):
        path = path_to_root(scen_leaf[name])
        p = 1.0
        for node in path:
            p *= cond_prob.get(node, 1.0)
            t = stage_idx[node_stage[node]]
            if t < T - 1:
                node_paths[i, t] = node_ids[t][node]
        probs[i] = p

    def clean(names):
        # DevotedAcreage[*] / QuantitySubQuotaSold -> bare var group name
        return [re.sub(r"\[.*\]$", "", v) for v in names]

    nonants = [clean(stage_vars.get(s, [])) for s in stages[:-1]]
    tree = ScenarioTree(scen_names=scen_names, node_paths=node_paths,
                        nodes_per_stage=[len(d) for d in node_ids],
                        nonant_names_per_stage=nonants,
                        probabilities=probs)
    tree.validate()
    return tree


class PySPModel:
    """Tree-from-.dat + native-creator pairing (the reference's adapter
    boundary, ref. utils/pysp_model.py:41: it produces scenario_creator,
    scenario names and denouement for the rest of the framework)."""

    def __init__(self, scenario_creator, structure_text: str):
        self.scenario_creator = scenario_creator
        self.tree = read_scenario_structure(structure_text)

    @property
    def all_scenario_names(self):
        return list(self.tree.scen_names)

    def build_batch(self, creator_kwargs=None):
        from ..ir.batch import build_batch
        return build_batch(self.scenario_creator, self.tree,
                           creator_kwargs=creator_kwargs)
