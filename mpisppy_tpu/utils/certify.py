"""Host-f64 SAFE-ROUNDING certification of device-derived dual bounds.

The device kernel's ``qp_dual_objective`` yields a lower bound that is
exact *in real arithmetic* for any dual vector, but its df32 evaluation
path (three f32 MXU passes accumulated in f64, ops/qp_solver.SplitMatrix)
carries ~1e-7-relative accumulation noise — enough that the printed
number is not *provably* below the true optimum. This module closes that
last gap on the host: given the raw row duals of a batched solve, it

 1. treats the (possibly f32-cast) dual vector as EXACT — any dual
    vector certifies a valid bound, so quantizing the duals costs
    tightness, never validity (the transfer-economy trick: pull (S, m)
    duals at half the bytes);
 2. projects them onto the dual-feasible cone in f64 (zeroing
    components that push on infinite bounds — always sign-infeasible
    there, and a different-but-valid dual choice);
 3. TIGHTENS infinite variable boxes by one sweep of activity-based
    implied bounds from the constraint rows (classic presolve: the UC
    capacity row p − pmax·u <= 0 caps the otherwise-unbounded p at
    pmax). Valid because the Lagrangian bound argument only needs a
    relaxation SET containing the feasible set — the implied box is
    one. Without this, the eps-level negative reduced costs that
    first-order duals leave on unbounded columns certify −inf;
 4. evaluates the Lagrangian dual value per scenario in f64 with
    *directed-rounding margins*: every float sum/product's worst-case
    rounding error (the standard gamma_k = k·u/(1−k·u) forward bound)
    is SUBTRACTED from the result, so the published value is provably
    <= the exact dual value, which is <= the true scenario optimum;
 5. charges the W off-manifold residual: the Lagrangian decomposition
    is an outer bound only when sum_s p_s W_s = 0 per (node, slot);
    after the f64 projection an eps-level residual delta remains, and
    the bound is debited |delta| x (tightest member box magnitude) per
    slot instead of assuming exact membership.

The margins are ~1e-13 relative on UC-class data — invisible tightness
cost for a bound that is certified end to end with no LP oracle call.
Linear objectives only (the standard host-certification eligibility;
quadratic models keep the device certificate).
"""

from __future__ import annotations

import numpy as np
from scipy import sparse as sparse_mod

# unit roundoff of IEEE double
_U = 0.5 * np.finfo(np.float64).eps


def _gamma(k):
    """Standard forward-error factor: |fl(sum of k products) − exact|
    <= gamma_k · sum|terms| (Higham, Accuracy and Stability, §3.1)."""
    ku = float(k) * _U
    return ku / (1.0 - ku)


def _boxmin_endpoint(r, lb, ub):
    """min_{x in [lb, ub]} r·x for a KNOWN r (elementwise): r·lb where
    r > 0 (−inf if lb = −inf), r·ub where r < 0 (−inf if ub = +inf),
    0 where r == 0."""
    out = np.zeros_like(r)
    pos = r > 0
    neg = r < 0
    out[pos] = r[pos] * lb[pos]
    out[neg] = r[neg] * ub[neg]
    return out


def _boxmin_certified(r, err, lb, ub):
    """Certified lower bound of min_x r_true·x over [lb, ub] given only
    |r_true − r| <= err. The box minimum is concave in r (a min of
    linear functions), so its minimum over the uncertainty interval is
    attained at an endpoint; one extra multiplication-rounding margin
    makes the float evaluation itself safe."""
    lo = np.minimum(_boxmin_endpoint(r - err, lb, ub),
                    _boxmin_endpoint(r + err, lb, ub))
    fin = np.isfinite(lo)
    lo[fin] -= _gamma(2) * np.abs(lo[fin])
    return lo


def implied_box_tightening(A_csr, l, u, lb, ub):
    """ONE sweep of activity-based implied bounds, restricted to the
    columns with an infinite box side — the presolve step that makes
    unbounded-column LPs certifiable (see the module docstring, step 3).

    For row i (l_i <= Σ A_ik x_k <= u_i) and column j with a = A_ij:
    x_j <= (u_i − minact_{k≠j}) / a when a > 0, and
    x_j <= (l_i − maxact_{k≠j}) / a when a < 0 (mirrored for lower
    bounds), usable only when every OTHER term's needed activity side
    is finite. Derived caps are inflated by the rounding envelope of
    their own evaluation, so the tightened box provably contains the
    feasible set. Returns (lb2, ub2) copies ((S, n))."""
    A = A_csr.tocsr()
    m, n = A.shape
    lb = np.asarray(lb, np.float64)
    ub = np.asarray(ub, np.float64)
    l = np.asarray(l, np.float64)
    u = np.asarray(u, np.float64)
    lb2, ub2 = lb.copy(), ub.copy()
    pos = A.maximum(0).tocsr()
    neg = A.minimum(0).tocsr()
    ppat = (pos != 0).astype(np.float64)
    npat = (neg != 0).astype(np.float64)
    lbf = np.where(np.isfinite(lb), lb, 0.0)
    ubf = np.where(np.isfinite(ub), ub, 0.0)
    inf_lb = (~np.isfinite(lb)).astype(np.float64)
    inf_ub = (~np.isfinite(ub)).astype(np.float64)
    # (S, m) finite-side activities + per-row counts of infinite terms
    minact = pos.dot(lbf.T).T + neg.dot(ubf.T).T
    maxact = pos.dot(ubf.T).T + neg.dot(lbf.T).T
    cnt_min = ppat.dot(inf_lb.T).T + npat.dot(inf_ub.T).T
    cnt_max = ppat.dot(inf_ub.T).T + npat.dot(inf_lb.T).T
    # rounding envelope of the activity sums (per row, per scenario)
    absact = abs(A).dot(np.maximum(np.abs(lbf), np.abs(ubf)).T).T
    row_nnz = np.diff(A.indptr)
    A_csc = A.tocsc()
    cols_inf_ub = np.flatnonzero((~np.isfinite(ub)).any(axis=0))
    cols_inf_lb = np.flatnonzero((~np.isfinite(lb)).any(axis=0))

    def tighten(j, want_upper):
        best = np.full(lb.shape[0], np.inf if want_upper else -np.inf)
        for idx in range(A_csc.indptr[j], A_csc.indptr[j + 1]):
            i = A_csc.indices[idx]
            a = A_csc.data[idx]
            genv = _gamma(int(row_nnz[i]) + 4)
            if want_upper:
                if a > 0:
                    # own min-term of j used side lb (finite iff lb_j)
                    own_inf = inf_lb[:, j]
                    own = a * lbf[:, j]
                    ok = np.isfinite(u[:, i]) & (cnt_min[:, i] - own_inf
                                                 <= 0.5)
                    cand = (u[:, i] - (minact[:, i] - own)) / a
                else:
                    # a < 0 contributes a·lb to MAXact: own side is lb
                    own_inf = inf_lb[:, j]
                    own = a * lbf[:, j]
                    ok = np.isfinite(l[:, i]) & (cnt_max[:, i] - own_inf
                                                 <= 0.5)
                    cand = (l[:, i] - (maxact[:, i] - own)) / a
                env = genv * (np.abs(u[:, i] if a > 0 else l[:, i])
                              + absact[:, i] + np.abs(own)) / abs(a)
                cand = cand + env          # safe-side: inflate upward
                best = np.where(ok, np.minimum(best, cand), best)
            else:
                if a > 0:
                    own_inf = inf_ub[:, j]
                    own = a * ubf[:, j]
                    ok = np.isfinite(l[:, i]) & (cnt_max[:, i] - own_inf
                                                 <= 0.5)
                    cand = (l[:, i] - (maxact[:, i] - own)) / a
                else:
                    # a < 0 contributes a·ub to MINact: own side is ub
                    own_inf = inf_ub[:, j]
                    own = a * ubf[:, j]
                    ok = np.isfinite(u[:, i]) & (cnt_min[:, i] - own_inf
                                                 <= 0.5)
                    cand = (u[:, i] - (minact[:, i] - own)) / a
                env = genv * (np.abs(l[:, i] if a > 0 else u[:, i])
                              + absact[:, i] + np.abs(own)) / abs(a)
                cand = cand - env          # safe-side: deflate downward
                best = np.where(ok, np.maximum(best, cand), best)
        return best

    for j in cols_inf_ub:
        cap = tighten(j, want_upper=True)
        take = ~np.isfinite(ub2[:, j]) & np.isfinite(cap)
        ub2[take, j] = cap[take]
    for j in cols_inf_lb:
        cap = tighten(j, want_upper=False)
        take = ~np.isfinite(lb2[:, j]) & np.isfinite(cap)
        lb2[take, j] = cap[take]
    return lb2, ub2


class DualBoundCertifier:
    """Reusable host certifier for one scenario batch (shared-structure
    or per-scenario matrices). Build once per spoke/test; ``bound`` runs
    per refresh. See the module docstring for the guarantee."""

    def __init__(self, A, l, u, lb, ub, c, c0, prob, nonant_idx=None,
                 P_diag=None, w_stages=None, tighten_boxes=True):
        if P_diag is not None and np.abs(np.asarray(P_diag)).max() > 0:
            raise ValueError("host certification supports linear "
                             "objectives only")
        self.l = np.asarray(l, np.float64)
        self.u = np.asarray(u, np.float64)
        self.c = np.asarray(c, np.float64)
        self.c0 = np.asarray(c0, np.float64)
        self.prob = np.asarray(prob, np.float64)
        S = self.l.shape[0]
        if sparse_mod.issparse(A):
            self._As = [sparse_mod.csr_matrix(A)]
        else:
            A = np.asarray(A, np.float64)
            if A.ndim == 2:
                self._As = [sparse_mod.csr_matrix(A)]
            elif all(np.array_equal(A[s], A[0]) for s in range(1, S)):
                self._As = [sparse_mod.csr_matrix(A[0])]
            else:
                self._As = [sparse_mod.csr_matrix(A[s]) for s in range(S)]
        self.shared = len(self._As) == 1
        self._absAs = [abs(a) for a in self._As]
        lb = np.asarray(lb, np.float64)
        ub = np.asarray(ub, np.float64)
        if tighten_boxes and not (np.isfinite(lb).all()
                                  and np.isfinite(ub).all()):
            if self.shared:
                lb, ub = implied_box_tightening(self._As[0], self.l,
                                                self.u, lb, ub)
            else:
                parts = [implied_box_tightening(
                    self._As[s], self.l[s:s + 1], self.u[s:s + 1],
                    lb[s:s + 1], ub[s:s + 1]) for s in range(S)]
                lb = np.concatenate([p[0] for p in parts])
                ub = np.concatenate([p[1] for p in parts])
        self.lb, self.ub = lb, ub
        # max terms in any (AᵀyA + q)_j sum, + headroom for the q add
        # and the f64 construction of q = c + W itself
        kmax = max(int(np.diff(a.tocsc().indptr).max(initial=0))
                   for a in self._As)
        self._g_r = _gamma(kmax + 4)
        self.nonant_idx = None if nonant_idx is None \
            else np.asarray(nonant_idx)
        # (slice, membership (S, N)) per non-leaf stage, for the W
        # off-manifold residual margin
        self._w_stages = w_stages
        self._g_sup = _gamma(self._As[0].shape[0] + 4)
        self._g_col = _gamma(self._As[0].shape[1] + 4)

    @classmethod
    def from_batch(cls, batch):
        stages = []
        for t, sl in enumerate(batch.stage_slot_slices):
            B = np.asarray(batch.tree.membership(t + 1), np.float64)
            stages.append((sl, B))
        return cls(batch.A, batch.l, batch.u, batch.lb, batch.ub,
                   batch.c, batch.c0, batch.prob,
                   nonant_idx=batch.nonant_idx, P_diag=batch.P_diag,
                   w_stages=stages)

    # -- pieces --
    def _sanitize(self, y):
        """Project row duals onto the dual-feasible cone: a component
        pushing on an infinite bound is always sign-infeasible; zeroing
        it is a different (still valid) dual choice, not an
        approximation."""
        y = np.array(y, np.float64, copy=True)
        y[np.broadcast_to(np.isposinf(self.u), y.shape) & (y > 0)] = 0.0
        y[np.broadcast_to(np.isneginf(self.l), y.shape) & (y < 0)] = 0.0
        return y

    def _sup_rows_upper(self, y):
        """Certified UPPER bound on sup_{l<=z<=u} yᵀz per scenario
        (sanitized y ⇒ finite)."""
        yp = np.maximum(y, 0.0)
        ym = np.maximum(-y, 0.0)
        u_fin = np.where(np.isfinite(self.u), self.u, 0.0)
        l_fin = np.where(np.isfinite(self.l), self.l, 0.0)
        sup = np.sum(u_fin * yp - l_fin * ym, axis=1)
        mag = np.sum(np.abs(u_fin) * yp + np.abs(l_fin) * ym, axis=1)
        return sup + self._g_sup * mag

    def _w_manifold_margin(self, W):
        """Upper bound on the bound slip from W's off-manifold residual
        after f64 projection: sum over (node, slot) of |sum_{s in node}
        p_s W_sk| x (tightest member-box magnitude for that column).
        Returns +inf when a nonzero residual meets an unbounded column
        (cannot be certified) — callers fall back to the device value."""
        if W is None:
            return 0.0
        if self._w_stages is None or self.nonant_idx is None:
            return np.inf
        W = np.asarray(W, np.float64)
        total = 0.0
        for sl, B in self._w_stages:
            cols = self.nonant_idx[sl]
            # per-slot residual mass per node, + its own summation error
            pw = self.prob[:, None] * W[:, sl]
            num = B.T @ pw                                    # (N, k)
            num_abs = np.abs(num) \
                + _gamma(B.shape[0] + 2) * (np.abs(B).T @ np.abs(pw))
            # |z_node| <= min over member scenarios of max(|lb|,|ub|)
            mag = np.maximum(np.abs(self.lb[:, cols]),
                             np.abs(self.ub[:, cols]))       # (S, k)
            big = 1e300
            mag = np.where(np.isfinite(mag), mag, big)
            node_mag = np.full(num.shape, big)
            for node in range(B.shape[1]):
                members = np.flatnonzero(B[:, node] > 0)
                if members.size:
                    node_mag[node] = mag[members].min(axis=0)
            slip = num_abs * node_mag
            if np.any((num_abs > 0) & (node_mag >= big)):
                return np.inf
            total += float(np.sum(slip) * (1.0 + _gamma(num.size + 2)))
        return total

    def _repair_scale(self, r, err, q):
        """Per-scenario dual scale t in [0, 1] making every
        unbounded-direction reduced cost provably sign-feasible under
        the error envelope: for ub=+inf columns, q + t(r−q) >= err
        (mirrored for lb=−inf). t is taken safe-side (the envelope at
        t <= 1 is bounded by the envelope at 1). Scenarios with no
        violation keep t=1."""
        S = r.shape[0]
        t = np.ones(S)
        up_inf = np.broadcast_to(~np.isfinite(self.ub), r.shape)
        lo_inf = np.broadcast_to(~np.isfinite(self.lb), r.shape)
        # target 4·err of slack: the scaled reduced cost is RECOMPUTED
        # under its own (≤ err) envelope, so landing exactly at err
        # would leave zero certified margin
        slack = 4.0 * err
        with np.errstate(divide="ignore", invalid="ignore"):
            # ub=+inf columns need r >= err; violated where r < slack
            viol_u = up_inf & (r < slack)
            # q + t(r−q) >= slack ⇒ t <= (q − slack)/(q − r) (q > r here)
            tu = np.where(viol_u,
                          (q - slack) / np.maximum(q - r, 1e-300), 1.0)
            # lb=−inf columns need r <= −err; violated where r > −slack
            viol_l = lo_inf & (r > -slack)
            tl = np.where(viol_l,
                          (-q - slack) / np.maximum(r - q, 1e-300), 1.0)
        t = np.minimum(t, np.clip(np.nan_to_num(tu, nan=0.0), 0.0, 1.0)
                       .min(axis=1))
        t = np.minimum(t, np.clip(np.nan_to_num(tl, nan=0.0), 0.0, 1.0)
                       .min(axis=1))
        return t

    def _reduced_costs(self, yA, q):
        """(r, err_r): f64 reduced costs q + Aᵀy with their directed
        forward-error envelope, under either matrix layout."""
        if self.shared:
            A, absA = self._As[0], self._absAs[0]
            r = A.T.dot(yA.T).T + q
            err = self._g_r * (absA.T.dot(np.abs(yA).T).T + np.abs(q))
            return r, err
        r = np.empty_like(q)
        err = np.empty_like(q)
        for s, (A, absA) in enumerate(zip(self._As, self._absAs)):
            r[s] = A.T.dot(yA[s]) + q[s]
            err[s] = self._g_r * (absA.T.dot(np.abs(yA[s])) + np.abs(q[s]))
        return r, err

    # -- public API --
    def scenario_bounds(self, yA, W=None):
        """Per-scenario certified lower values of
        min (c_s + W on nonant slots)·x over each scenario's feasible
        set, from row duals ``yA`` ((S, m), any precision — treated as
        exact). −inf rows mean "uncertifiable there" (an unbounded
        column whose reduced-cost sign the margins cannot pin, and no
        implied cap either)."""
        yA = self._sanitize(np.asarray(yA, np.float64))
        q = self.c.copy()
        if W is not None:
            if self.nonant_idx is None:
                raise ValueError("W terms need a nonant index map")
            q[:, self.nonant_idx] += np.asarray(W, np.float64)
        r, err_r = self._reduced_costs(yA, q)
        # DUAL SCALING repair for genuinely unbounded columns (no
        # implied cap): first-order duals leave eps-level wrong-sign
        # reduced costs there, which certify −inf. r(t) = q + t·(r − q)
        # is the reduced cost of the scaled dual t·yA — still a valid
        # dual vector for every t — and at t slightly below 1 the
        # wrong-sign components provably clear zero (their q side is
        # sign-correct, or the LP really is unbounded that direction).
        # Cost: ~(1−t) relative tightness, i.e. ~the violation itself.
        t = self._repair_scale(r, err_r, q)
        scaled = t < 1.0
        if np.any(scaled):
            yA = np.where(scaled[:, None], t[:, None] * yA, yA)
            r, err_r = self._reduced_costs(yA, q)
        contrib = _boxmin_certified(r, err_r, self.lb, self.ub)
        fin = np.isfinite(contrib)
        ssum = np.where(fin, contrib, 0.0).sum(axis=1)
        smag = np.abs(np.where(fin, contrib, 0.0)).sum(axis=1)
        vals = ssum - self._g_col * smag - self._sup_rows_upper(yA) \
            + self.c0
        vals -= _gamma(8) * np.abs(vals)
        vals[~fin.all(axis=1)] = -np.inf
        return vals

    def bound(self, yA, W=None):
        """Certified Lagrangian outer bound E_p[scenario value] from row
        duals ``yA`` at (projected) ``W``. Returns (bound, vals); the
        bound is −inf when any live scenario is uncertifiable or the W
        residual cannot be charged."""
        vals = self.scenario_bounds(yA, W)
        live = np.flatnonzero(self.prob > 0.0)
        if not np.isfinite(vals[live]).all():
            return -np.inf, vals
        margin = self._w_manifold_margin(W)
        if not np.isfinite(margin):
            return -np.inf, vals
        pv = self.prob[live] * vals[live]
        total = float(pv.sum() - _gamma(live.size + 4) * np.abs(pv).sum()
                      - margin)
        return total, vals
