"""spin_the_wheel: the top-level multi-cylinder launcher.

Mirrors mpisppy/utils/sputils.py:24-131: validate the hub/spoke dicts,
instantiate one algorithm object per cylinder, wire the windows, run every
cylinder concurrently, send the terminate signal when the hub's algorithm
finishes, and finalize.

Process-grid redesign: the reference factors MPI ranks into a
strata x cylinder grid (ref. sputils.py:133-151 make_comms). Here each
cylinder is a host thread driving batched device computation; the
"cylinder_comm" axis (scenario parallelism) lives inside each engine as the
sharded scenario axis of its batch, and the "strata_comm" axis is the
window star wired by Hub.make_windows. The write-id/kill protocol is
identical, so cylinder asynchrony semantics carry over.
"""

from __future__ import annotations

import threading
import time

from .. import global_toc


def nonant_slot_names(batch):
    """Human-readable name per nonant slot, stage-concatenated like
    ``nonant_idx`` — "Var" for scalars, "Var[k]" for vector entries
    (the naming the reference's CSV exports carry,
    ref. mpisppy/utils/sputils.py:426 ef_nonants)."""
    names = []
    f0 = batch.template
    for varnames in batch.tree.nonant_names_per_stage:
        for vn in varnames:
            sl = f0.var_slices[vn]
            ln = sl.stop - sl.start
            names += [vn] if ln == 1 else [f"{vn}[{k}]" for k in range(ln)]
    return names


def ef_nonants_csv(ef, filename):
    """Write a solved ExtensiveForm's nonant values as
    ``scenario, varname, value`` rows
    (ref. mpisppy/utils/sputils.py:438 ef_nonants_csv)."""
    import numpy as np

    batch = ef.batch
    if not hasattr(ef, "x_batch"):
        raise RuntimeError("solve the EF before exporting "
                           "(ef_nonants_csv needs ef.x_batch)")
    names = nonant_slot_names(batch)
    xn = np.asarray(ef.x_batch)[:, np.asarray(batch.nonant_idx)]
    with open(filename, "w") as f:
        f.write("scenario, varname, value\n")
        for s, scen in enumerate(batch.tree.scen_names):
            for k, vn in enumerate(names):
                f.write(f"{scen}, {vn}, {xn[s, k]}\n")


def write_xhat_csv(xhat, filename, batch):
    """Write an incumbent first-stage plan (a (K,) or (S, K) nonant
    block, e.g. WheelResult.best_xhat()) as ``varname, value`` rows per
    scenario (ref. mpisppy/extensions/xhatbase.py:147-189 csv dumps)."""
    import numpy as np

    names = nonant_slot_names(batch)
    xh = np.asarray(xhat)
    with open(filename, "w") as f:
        if xh.ndim == 1:
            f.write("varname, value\n")
            for k, vn in enumerate(names):
                f.write(f"{vn}, {xh[k]}\n")
        else:
            f.write("scenario, varname, value\n")
            for s, scen in enumerate(batch.tree.scen_names):
                for k, vn in enumerate(names):
                    f.write(f"{scen}, {vn}, {xh[s, k]}\n")


class WheelResult:
    """What a finished wheel run exposes (the reference returns
    (spcomm, opt_dict) tuples, ref. sputils.py:131)."""

    def __init__(self, hub, spokes, spoke_results):
        self.hub = hub
        self.spokes = spokes
        self.spoke_results = spoke_results
        self.BestOuterBound, self.BestInnerBound = hub.hub_finalize()

    @property
    def best_inner_bound(self):
        return self.BestInnerBound

    @property
    def best_outer_bound(self):
        return self.BestOuterBound

    def gap(self):
        abs_gap, rel_gap = self.hub.compute_gaps()
        return abs_gap, rel_gap

    def best_xhat(self):
        """Best incumbent nonants over all xhat-style spokes."""
        best, best_obj = None, None
        for sp, res in zip(self.spokes, self.spoke_results):
            if isinstance(res, tuple) and len(res) == 2:
                obj, xhat = res
                if obj is not None and xhat is not None and \
                        (best_obj is None or obj < best_obj):
                    best, best_obj = xhat, obj
        return best


def _check_dict(d, keys, what):
    for k in keys:
        if k not in d:
            raise RuntimeError(f"{what} must contain key '{k}' "
                               "(ref. sputils.py:36-60 dict validation)")


def spin_the_wheel(hub_dict, list_of_spoke_dicts=(), spin_timeout=None,
                   register_hub=None):
    """Run one hub + N spokes concurrently; returns a WheelResult.

    hub_dict:   {"hub_class", "hub_kwargs", "opt_class", "opt_kwargs"}
    spoke dict: {"spoke_class", "spoke_kwargs", "opt_class", "opt_kwargs"}
    (the reference's dict schema, ref. sputils.py:24-60)

    ``register_hub``: optional callable invoked with the constructed
    hub before the spin starts — lets a driver observe live progress
    (gap marks) from a signal handler when it may be killed mid-spin.
    """
    _check_dict(hub_dict, ("hub_class", "opt_class"), "hub_dict")
    for sd in list_of_spoke_dicts:
        _check_dict(sd, ("spoke_class", "opt_class"), "spoke dict")

    hub_opt = hub_dict["opt_class"](**hub_dict.get("opt_kwargs", {}))
    spokes = []
    for sd in list_of_spoke_dicts:
        opt = sd["opt_class"](**sd.get("opt_kwargs", {}))
        spokes.append(sd["spoke_class"](
            opt, **sd.get("spoke_kwargs", {})))

    hub = hub_dict["hub_class"](hub_opt, spokes=spokes,
                                **hub_dict.get("hub_kwargs", {}))
    hub.make_windows()
    hub.setup_hub()
    if register_hub is not None:
        register_hub(hub)

    spoke_errors: list[BaseException | None] = [None] * len(spokes)

    def _run_spoke(i, sp):
        try:
            # warm resume (mpisppy_tpu.ckpt): a spoke built with a
            # ``resume_state`` option re-publishes its checkpointed
            # best bound first — same contract as the process
            # launcher's post-hello hook (utils/multiproc)
            if hasattr(sp, "resume_publish"):
                sp.resume_publish()
            sp.main()
        except BaseException as e:  # surface spoke crashes to the caller
            spoke_errors[i] = e

    threads = [threading.Thread(target=_run_spoke, args=(i, sp),
                                name=f"spoke{i}", daemon=True)
               for i, sp in enumerate(spokes)]
    for t in threads:
        t.start()

    # the preemption notice path (doc/fault_tolerance.md), in-process
    # spelling: with checkpointing armed, SIGTERM forces one final
    # bundle + clean terminate exactly like the process wheel
    # (utils/multiproc) — a hub-only wheel (e.g. a streamed/synthesized
    # engine, doc/streaming.md) is preemption-tolerant too, and the
    # handler also stops a streamed source's prefetch thread through
    # Hub.handle_preemption. Handler restored on every exit path.
    prev_sigterm = None
    if hub.ckpt is not None:
        import signal as _signal

        def _on_sigterm(signum, frame):
            hub.handle_preemption("sigterm")
        try:
            prev_sigterm = _signal.signal(_signal.SIGTERM, _on_sigterm)
        except ValueError:
            prev_sigterm = None         # not the main thread
    try:
        hub.main()                      # ref. sputils.py:115 spcomm.main()
    except BaseException:
        # exceptional exit skips hub_finalize — release the status
        # server's port here (normal path: hub_finalize stops it after
        # serving the final state; shutdown_live is idempotent)
        hub.shutdown_live()
        raise
    finally:
        if prev_sigterm is not None:
            import signal as _signal
            _signal.signal(_signal.SIGTERM, prev_sigterm)
        hub.send_terminate()            # ref. sputils.py:117 / hub.py:356
    # two-phase join: spokes poll the kill signal between candidate
    # evaluations / oracle tasks, but one in-flight batched solve or
    # dive round can take tens of seconds on a contended device — give
    # the full budget before declaring a spoke stuck (a stuck spoke's
    # finalize is skipped, dropping its best incumbent: VERDICT r2
    # weak #5)
    budget = 120.0 if spin_timeout is None else spin_timeout
    deadline = time.monotonic() + budget
    stuck = []
    for t in threads:
        t.join(timeout=max(0.0, deadline - time.monotonic()))
    for t in threads:
        if t.is_alive():
            stuck.append(t.name)
            global_toc(f"WARNING: {t.name} did not exit cleanly "
                       f"(budget {budget:.0f}s)")
    for i, err in enumerate(spoke_errors):
        if err is not None:
            raise RuntimeError(
                f"spoke {i} ({type(spokes[i]).__name__}) crashed") from err
    # don't race finalize() against a still-running spoke thread
    spoke_results = [None if f"spoke{i}" in stuck else sp.finalize()
                     for i, sp in enumerate(spokes)]
    return WheelResult(hub, spokes, spoke_results)
