"""spin_the_wheel: the top-level multi-cylinder launcher.

Mirrors mpisppy/utils/sputils.py:24-131: validate the hub/spoke dicts,
instantiate one algorithm object per cylinder, wire the windows, run every
cylinder concurrently, send the terminate signal when the hub's algorithm
finishes, and finalize.

Process-grid redesign: the reference factors MPI ranks into a
strata x cylinder grid (ref. sputils.py:133-151 make_comms). Here each
cylinder is a host thread driving batched device computation; the
"cylinder_comm" axis (scenario parallelism) lives inside each engine as the
sharded scenario axis of its batch, and the "strata_comm" axis is the
window star wired by Hub.make_windows. The write-id/kill protocol is
identical, so cylinder asynchrony semantics carry over.
"""

from __future__ import annotations

import threading

from .. import global_toc


class WheelResult:
    """What a finished wheel run exposes (the reference returns
    (spcomm, opt_dict) tuples, ref. sputils.py:131)."""

    def __init__(self, hub, spokes, spoke_results):
        self.hub = hub
        self.spokes = spokes
        self.spoke_results = spoke_results
        self.BestOuterBound, self.BestInnerBound = hub.hub_finalize()

    @property
    def best_inner_bound(self):
        return self.BestInnerBound

    @property
    def best_outer_bound(self):
        return self.BestOuterBound

    def gap(self):
        abs_gap, rel_gap = self.hub.compute_gaps()
        return abs_gap, rel_gap

    def best_xhat(self):
        """Best incumbent nonants over all xhat-style spokes."""
        best, best_obj = None, None
        for sp, res in zip(self.spokes, self.spoke_results):
            if isinstance(res, tuple) and len(res) == 2:
                obj, xhat = res
                if obj is not None and xhat is not None and \
                        (best_obj is None or obj < best_obj):
                    best, best_obj = xhat, obj
        return best


def _check_dict(d, keys, what):
    for k in keys:
        if k not in d:
            raise RuntimeError(f"{what} must contain key '{k}' "
                               "(ref. sputils.py:36-60 dict validation)")


def spin_the_wheel(hub_dict, list_of_spoke_dicts=(), spin_timeout=None):
    """Run one hub + N spokes concurrently; returns a WheelResult.

    hub_dict:   {"hub_class", "hub_kwargs", "opt_class", "opt_kwargs"}
    spoke dict: {"spoke_class", "spoke_kwargs", "opt_class", "opt_kwargs"}
    (the reference's dict schema, ref. sputils.py:24-60)
    """
    _check_dict(hub_dict, ("hub_class", "opt_class"), "hub_dict")
    for sd in list_of_spoke_dicts:
        _check_dict(sd, ("spoke_class", "opt_class"), "spoke dict")

    hub_opt = hub_dict["opt_class"](**hub_dict.get("opt_kwargs", {}))
    spokes = []
    for sd in list_of_spoke_dicts:
        opt = sd["opt_class"](**sd.get("opt_kwargs", {}))
        spokes.append(sd["spoke_class"](
            opt, **sd.get("spoke_kwargs", {})))

    hub = hub_dict["hub_class"](hub_opt, spokes=spokes,
                                **hub_dict.get("hub_kwargs", {}))
    hub.make_windows()
    hub.setup_hub()

    spoke_errors: list[BaseException | None] = [None] * len(spokes)

    def _run_spoke(i, sp):
        try:
            sp.main()
        except BaseException as e:  # surface spoke crashes to the caller
            spoke_errors[i] = e

    threads = [threading.Thread(target=_run_spoke, args=(i, sp),
                                name=f"spoke{i}", daemon=True)
               for i, sp in enumerate(spokes)]
    for t in threads:
        t.start()

    try:
        hub.main()                      # ref. sputils.py:115 spcomm.main()
    finally:
        hub.send_terminate()            # ref. sputils.py:117 / hub.py:356
    stuck = []
    for t in threads:
        t.join(timeout=60.0 if spin_timeout is None else spin_timeout)
        if t.is_alive():
            stuck.append(t.name)
            global_toc(f"WARNING: {t.name} did not exit cleanly")
    for i, err in enumerate(spoke_errors):
        if err is not None:
            raise RuntimeError(
                f"spoke {i} ({type(spokes[i]).__name__}) crashed") from err
    # don't race finalize() against a still-running spoke thread
    spoke_results = [None if f"spoke{i}" in stuck else sp.finalize()
                     for i, sp in enumerate(spokes)]
    return WheelResult(hub, spokes, spoke_results)
