"""Worker side of the host oracle pool (utils/host_oracle.py).

Runs as a standalone subprocess (``python -m
mpisppy_tpu.utils._oracle_worker``) speaking length-prefixed pickle
frames over stdin/stdout: first frame in is the static problem payload,
then one frame per solve task, one result frame back per task. A
dedicated subprocess — not multiprocessing — because every stdlib start
method is wrong here: fork clones the parent's accelerator runtime
(jax/grpc threads are not fork-safe), and spawn/forkserver re-import
the user's ``__main__`` in every worker, re-executing unguarded driver
scripts wholesale. This module imports ONLY numpy/scipy, so worker
startup is light and jax never loads.

This is the TPU framework's analog of the reference's per-rank rented
CPU solvers (ref. mpisppy/phbase.py:1304-1362 SolverFactory per
subproblem; ref. mpisppy/phbase.py:999 parallel solve fan-out across
ranks): the host cores are the "ranks", HiGHS is the solver.
"""

from __future__ import annotations

import pickle
import struct

import numpy as np


def read_msg(f):
    """Read one length-prefixed pickle frame; None on EOF/short read."""
    hdr = f.read(8)
    if len(hdr) < 8:
        return None
    (ln,) = struct.unpack("<Q", hdr)
    data = f.read(ln)
    if len(data) < ln:
        return None
    return pickle.loads(data)


def write_frame(f, b: bytes):
    f.write(struct.pack("<Q", len(b)))
    f.write(b)
    f.flush()


def write_msg(f, obj):
    write_frame(f, pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))

def init_worker(payload: dict) -> dict:
    """Build a solver state dict from the static payload, pre-seeding
    the A→CSR conversion cache. Returned (not stored in a module
    global) so multiple INLINE pools in one process can coexist — a
    shared global would let a second pool silently clobber the first's
    problem data. The subprocess main() holds exactly one state.

    payload keys: A ((m,n) shared or (S,m,n)), l, u, lb, ub (per-scenario
    row/box bounds), integrality ((n,) int), with A possibly shared.
    """
    from scipy import sparse

    state = dict(payload)
    A = payload["A"]
    if sparse.issparse(A):
        state["A_csr"] = sparse.csr_matrix(A)
        state["A_shared"] = True
    elif A.ndim == 2:
        state["A_csr"] = sparse.csr_matrix(A)
        state["A_shared"] = True
    else:
        # convert lazily per scenario — a 1000-scenario batch would
        # otherwise pay the full conversion in every worker
        state["A_csr"] = {}
        state["A_shared"] = False
    return state


def _A_of(state: dict, s: int):
    from scipy import sparse

    if state["A_shared"]:
        return state["A_csr"]
    cache = state["A_csr"]
    if s not in cache:
        cache[s] = sparse.csr_matrix(state["A"][s])
    return cache[s]


def solve_scenario(state: dict, task):
    """Solve one scenario LP/MILP: min q·x s.t. l<=Ax<=u, lb<=x<=ub
    (+ integrality when milp=True).

    task = (s, q, milp, time_limit, mip_gap[, want_x[, fixed]]).
    ``fixed`` — optional (idx, vals) pinning columns idx at vals via
    lb=ub (incumbent evaluation: first-stage nonants fixed at a
    candidate x̂, the dispatch solved exactly on host).
    Returns (s, value, ok, optimal, primal):
      value — a certified LOWER bound on the scenario minimum (the LP
        optimum, or HiGHS's B&B dual bound for MILPs — valid even when
        the solve stops on time_limit/mip_gap);
      ok — value is a usable finite bound;
      optimal — the solve finished proven-optimal (so re-solving with a
        tighter budget cannot improve it);
      primal — (obj, x) of the solver's feasible point when want_x and
        one exists, else None. For MILPs obj is the INCUMBENT objective
        (an upper bound), distinct from the dual `value`.
    """
    from scipy.optimize import Bounds, LinearConstraint, milp as _milp

    s, q, want_milp, time_limit, mip_gap = task[:5]
    want_x = bool(task[5]) if len(task) > 5 else False
    fixed = task[6] if len(task) > 6 else None
    integrality = state["integrality"] if want_milp else None
    opts = {"presolve": True}
    if time_limit is not None:
        opts["time_limit"] = float(time_limit)
    if want_milp and mip_gap is not None:
        opts["mip_rel_gap"] = float(mip_gap)
    lb, ub = state["lb"][s], state["ub"][s]
    if fixed is not None:
        idx, vals = fixed
        lb, ub = lb.copy(), ub.copy()
        lb[idx] = vals
        ub[idx] = vals
    res = _milp(
        q,
        constraints=LinearConstraint(_A_of(state, s),
                                     state["l"][s], state["u"][s]),
        bounds=Bounds(lb, ub),
        integrality=(integrality if integrality is not None
                     else np.zeros(q.shape[0], dtype=np.uint8)),
        options=opts,
    )
    primal = (float(res.fun), np.asarray(res.x)) \
        if want_x and res.x is not None else None
    if want_milp:
        # HiGHS's dual (best) bound is a valid lower bound at ANY stop
        # reason; -inf / None means nothing was proven. On a model with
        # no integer columns scipy returns mip_dual_bound=None even at
        # optimality — the LP optimum IS the dual bound there
        val = res.mip_dual_bound
        if val is None and res.status == 0 and res.fun is not None:
            val = res.fun
        ok = val is not None and np.isfinite(val)
        optimal = bool(res.status == 0)
        return s, (float(val) if ok else -np.inf), ok, optimal, primal
    ok = bool(res.status == 0 and res.x is not None)
    return s, (float(res.fun) if ok else -np.inf), ok, ok, primal


def main():
    """Subprocess entry: payload frame, then task frames until EOF."""
    import os
    import sys

    # claim the protocol channel and route stray library prints (HiGHS
    # logs, warnings) to stderr so they can never corrupt a frame
    out = os.fdopen(os.dup(sys.stdout.fileno()), "wb")
    os.dup2(sys.stderr.fileno(), sys.stdout.fileno())
    inp = os.fdopen(os.dup(sys.stdin.fileno()), "rb")
    payload = read_msg(inp)
    if payload is None:
        return
    state = init_worker(payload)
    while True:
        task = read_msg(inp)
        if task is None:
            return
        write_msg(out, solve_scenario(state, task))


if __name__ == "__main__":
    main()
