"""Asynchronous reduction engine over shared-memory windows.

The reference's ``listener_util.Synchronizer`` (ref. mpisppy/utils/
listener_util/listener_util.py:22-327) is the machinery under APH: a
listener thread on every rank periodically Allreduces named summand
vectors while the worker thread solves, so reduction communication
overlaps subproblem compute wall-clock, and workers read whatever global
landed last ("one notch behind", staleness tolerated by design).

TPU-native redesign. Within one chip the reduction is a membership
matmul inside the jitted step — nothing to overlap. The surface where
the listener pattern genuinely survives is ACROSS PROCESSES: scenario
shards living in different host processes (the multi-host deployment
shape, one process per TPU host, summands crossing DCN). MPI's
symmetric Allreduce becomes an asymmetric, wait-free exchange over the
native seqlock windows (ops/native/spwindow):

  - every participant owns one window per named reduction and writes
    ONLY its own summand there (the windows' one-writer discipline);
  - a listener daemon thread per participant beats: publish my latest
    summand -> read every peer's window -> global = sum -> side gigs ->
    sleep(min of everyone's advertised sleep).

No beat ever blocks on a peer: a slow shard simply contributes its last
published summand — exactly the staleness semantics the reference gets
from Allreduce-ing a stale ``local_data`` buffer. Freshness accounting
(which shards are "new enough", ref. aph.py:204-324 enough-fresh check)
stays with the caller, which embeds per-participant timestamps in its
vectors just as APH does.

The worker-facing API mirrors the reference where the semantics match:
``compute_global_data(local_in, global_out, keep_up=...)`` caches the
newest local summand for the listener and copies out the last-reduced
global, with ``keep_up`` folding the caller's newest summand into the
stale global (ref. listener_util.py:164-182). ``quitting`` propagates
through a control window: ANY participant quitting stops every listener
(the reference's summed quitting allreduce, ref. listener_util.py:306).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..cylinders.spcommunicator import Window


_CTRL = "_ctrl"          # control reduction: [quitting, sleep_secs]
# dedicated rows for blocking sync_allreduce beats, double-buffered by
# round parity: a peer can be at most one round ahead (round r+1 blocks
# on the OTHER row until everyone arrives), so the row a slow reader is
# still summing is never overwritten mid-round
_SYNC = ("_sync0", "_sync1")


def _augment_lens(names_lens):
    """User reductions + internal rows — the ONE definition of the wire
    layout every participant (thread- or process-mode) must share."""
    lens = dict(names_lens)
    lens[_CTRL] = 2
    for s in _SYNC:
        lens[s] = max(names_lens.values())
    return lens


class Synchronizer:
    """Staleness-tolerant async sum-reductions among N participants.

    Args:
        names_lens: ordered {reduction name: vector length}. Reductions
            are always SUMs (as in the reference, listener_util.py:6).
        n_participants / my_index: the shard group and my slot in it.
        shm_prefix: if given, windows are the native shared-memory
            backend named ``{prefix}.{red}.{participant}`` — participants
            are OS processes. If None, ``windows`` must be supplied
            (thread mode, for tests and in-process wheels).
        windows: optional prebuilt {red: [Window] * n_participants}
            shared by all thread-mode participants.
        sleep_secs: my listener's beat sleep; the group beats at the MIN
            over participants (ref. listener_util.py:308-316).
        listener_gigs: optional {red: (fct, kwargs)} side gigs run by the
            listener after that reduction's global lands, once enabled
            via compute_global_data(enable_side_gig=True)
            (ref. listener_util.py:137-144, 296-303).
    """

    def __init__(self, names_lens, n_participants, my_index, shm_prefix=None,
                 windows=None, sleep_secs=0.01, listener_gigs=None,
                 open_timeout=60.0, ondemand_lens=None):
        self.names_lens = dict(names_lens)
        # on-demand reductions: windows exist, but the LISTENER never
        # touches them — they are summed only when a worker calls
        # reduce_now. For big once-per-iteration payloads (the sharded
        # wheel's full-(W, x) gather: 2·S·K doubles) that would
        # otherwise be republished and re-summed on every ~5 ms beat.
        self.ondemand_lens = dict(ondemand_lens or {})
        assert _CTRL not in self.names_lens
        assert not set(self.ondemand_lens) & set(self.names_lens)
        self.n = int(n_participants)
        self.me = int(my_index)
        self.sleep_secs = float(sleep_secs)
        self.listener_gigs = listener_gigs or {}
        self.enable_side_gig = False
        self.quitting = 0
        self.global_quitting = 0
        self.data_lock = threading.Lock()
        self.local_data = {r: np.zeros(l) for r, l in self.names_lens.items()}
        self.global_data = {r: np.zeros(l) for r, l in self.names_lens.items()}
        self._beats = 0                 # completed listener beats
        self._listener = None

        lens = _augment_lens(self.names_lens)
        lens.update(self.ondemand_lens)
        self._sync_round = 0
        if windows is not None:
            missing = set(lens) - set(windows)
            if missing:
                raise ValueError(
                    f"prebuilt window table is missing {sorted(missing)}; "
                    "thread-mode embedders must size on-demand rows too "
                    "(make_thread_windows(..., ondemand_lens=...))")
            self._windows = windows
        elif shm_prefix is not None:
            self._windows = self._open_shm(shm_prefix, lens, open_timeout)
        else:
            raise ValueError("need shm_prefix (process mode) or windows "
                             "(thread mode)")

    # ---- construction helpers ----
    @staticmethod
    def make_thread_windows(names_lens, n_participants, ondemand_lens=None):
        """One shared window table for an n-thread group (test/in-process
        mode): {red: [Window]*n}. Pass the SAME table to every
        participant's constructor."""
        lens = _augment_lens(names_lens)
        lens.update(ondemand_lens or {})
        return {r: [Window(l) for _ in range(n_participants)]
                for r, l in lens.items()}

    def _open_shm(self, prefix, lens, timeout):
        out = {}
        opened = []                     # (window, i_own_it) for cleanup
        deadline = time.monotonic() + timeout
        try:
            for red, l in lens.items():
                row = []
                for p in range(self.n):
                    name = f"{prefix}.{red}.{p}"
                    if p == self.me:
                        row.append(Window.shared(name, l, create=True))
                    else:
                        while True:
                            try:
                                row.append(
                                    Window.shared(name, l, create=False))
                                break
                            except OSError:
                                if time.monotonic() > deadline:
                                    raise
                                time.sleep(0.05)
                    opened.append((row[-1], p == self.me))
                out[red] = row
        except Exception:
            # don't leak the segments already created/opened: a peer that
            # died mid-startup would otherwise strand /dev/shm entries
            for w, mine in opened:
                w.close(unlink=mine)
            raise
        return out

    def close(self):
        self.quitting = 1
        if self._listener is not None and self._listener.is_alive():
            self._listener.join(timeout=10.0)
            if self._listener.is_alive():
                # a hung listener still put/reads the windows; closing
                # them under it would crash in the native layer instead
                # of failing gracefully (ADVICE r3). Leak the segments
                # (cleanup_shm reaps them) and tell the operator.
                import warnings

                warnings.warn(
                    "Synchronizer.close(): listener thread still alive "
                    "after 10 s join — leaving shm windows open "
                    "(cleanup_shm can reap the segments later)",
                    RuntimeWarning, stacklevel=2)
                return
        for row in self._windows.values():
            for p, w in enumerate(row):
                if hasattr(w, "close"):
                    w.close(unlink=(p == self.me))

    # ---- worker side ----
    def compute_global_data(self, local_in, global_out, enable_side_gig=False,
                            rednames=None, keep_up=False):
        """Cache my newest summands for the listener; copy out the last
        reduced globals. With keep_up, the copied-out global swaps my
        stale contribution for the new one (ref. listener_util.py:164-182:
        "global that is one notch behind" otherwise)."""
        with self.data_lock:
            for red in (rednames if rednames is not None else self.names_lens):
                if keep_up:
                    np.copyto(global_out[red],
                              self.global_data[red] - self.local_data[red]
                              + local_in[red])
                    np.copyto(self.global_data[red], global_out[red])
                else:
                    np.copyto(global_out[red], self.global_data[red])
                np.copyto(self.local_data[red], local_in[red])
        if enable_side_gig:
            # run-once authorization, exactly the reference's contract
            # (ref. listener_util.py:186-190): the SIDE GIG is responsible
            # for clearing ``sync.enable_side_gig = False`` once it has
            # consumed the data; re-enabling before it does is a caller
            # protocol error. Until cleared, the gig re-runs each beat —
            # gigs gate themselves on their own freshness checks
            # (ref. aph.py:204-324 enough-fresh check).
            if self.enable_side_gig:
                raise RuntimeError("side gig already enabled")
            self.enable_side_gig = True

    def publish_now(self, redname, local_vec):
        """Publish my summand of an ON-DEMAND reduction without summing
        (non-consumers of a gather publish only — the read+sum over all
        peers is the consumer's cost, see reduce_now)."""
        self._windows[redname][self.me].put(
            np.asarray(local_vec, dtype=np.float64))

    def reduce_now(self, redname, local_vec, return_min_wid=False):
        """One wait-free sum of an ON-DEMAND reduction (see
        ondemand_lens): publish my summand, read every peer's latest,
        return the sum. Same staleness semantics as the listener
        reductions — a slow peer contributes its last published vector
        — at zero listener-beat cost.

        ``return_min_wid=True`` also returns the minimum peer write-id:
        0 means some peer has NEVER published, i.e. the sum contains
        that peer's zero row — consumers staging the gather for third
        parties (the APH-shard wheel hub) gate on it rather than hand
        out partially-zero data (ADVICE r4)."""
        row = self._windows[redname]
        row[self.me].put(np.asarray(local_vec, dtype=np.float64))
        total = np.zeros(row[self.me].length)
        min_wid = None
        for p in range(self.n):
            vals, wid = row[p].read()
            total += vals
            min_wid = wid if min_wid is None else min(min_wid, wid)
        if return_min_wid:
            return total, min_wid
        return total

    def get_global_data(self, global_out):
        with self.data_lock:
            for red in self.names_lens:
                np.copyto(global_out[red], self.global_data[red])

    def peek_tail(self, redname, k):
        """Copy of the last ``k`` entries of a reduction's global — the
        cheap poll for callers whose freshness gate lives in a vector
        tail (per-shard timestamps), sparing the full-vector memcpy
        under the data lock at spin frequency."""
        with self.data_lock:
            return self.global_data[redname][-k:].copy()

    # side-gig accessors — called WITH the lock already held by the
    # listener (ref. listener_util.py:229-274 "_unsafe_*")
    def _unsafe_get_global_data(self, redname, global_out):
        np.copyto(global_out[redname], self.global_data[redname])

    def _unsafe_put_local_data(self, redname, local_in):
        np.copyto(self.local_data[redname], local_in[redname])

    # ---- synchronous barrier-allreduce (the reference's asynch=False
    # path, listener_util.py:193-199) over a DEDICATED window row (ids
    # stay aligned because only these collective calls write it — every
    # participant must call it the same number of times, the usual
    # collective-op contract) ----
    def sync_allreduce(self, vec, timeout=300.0, abort_on_quit=True):
        """Blocking sum over all participants of ``vec``: publish on this
        round's parity row, wait until every peer's write-id there
        reaches this round's, sum. ``abort_on_quit=False`` is for
        collectives where a peer's (graceful) quit is expected — e.g. a
        final wrap-up reduce after the group has quit the async loop."""
        red = _SYNC[self._sync_round % 2]
        expect = self._sync_round // 2 + 1
        self._sync_round += 1
        vec = np.asarray(vec, dtype=np.float64)
        row_len = self._windows[red][self.me].length
        assert vec.size <= row_len, "sync_allreduce vector too long"
        pad = np.zeros(row_len)
        pad[:vec.size] = vec
        self._windows[red][self.me].put(pad)
        deadline = time.monotonic() + timeout
        total = np.zeros_like(pad)
        while True:
            ready = True
            total[:] = 0.0
            for p in range(self.n):
                vals, wid = self._windows[red][p].read()
                if wid < expect:
                    ready = False
                    break
                total += vals
            if ready:
                return total[:vec.size]
            if abort_on_quit and self.global_quitting:
                # a peer failed/quit mid-collective: surface that instead
                # of masking it behind a 300 s TimeoutError
                raise RuntimeError(
                    "sync_allreduce: group quit while waiting for peers")
            if time.monotonic() > deadline:
                raise TimeoutError("sync_allreduce: peers never caught up")
            time.sleep(0.005)

    # ---- the listener ----
    def _beat(self):
        with self.data_lock:
            for red in self.names_lens:
                self._windows[red][self.me].put(self.local_data[red])
            for red in self.names_lens:
                acc = self.global_data[red]
                acc[:] = 0.0
                for p in range(self.n):
                    vals, _ = self._windows[red][p].read()
                    acc += vals
                gig = self.listener_gigs.get(red)
                if self.enable_side_gig and gig is not None:
                    fct, kwargs = gig
                    fct(self, **(kwargs or {}))
            # control: [quitting, sleep] — sum of quits, min of sleeps
            self._windows[_CTRL][self.me].put(
                np.array([float(self.quitting), self.sleep_secs]))
            quit_sum, sleep_min = 0.0, self.sleep_secs
            for p in range(self.n):
                vals, wid = self._windows[_CTRL][p].read()
                if wid > 0:             # peer has published at least once
                    quit_sum += vals[0]
                    sleep_min = min(sleep_min, vals[1]) if vals[1] > 0 \
                        else sleep_min
            self.global_quitting = int(quit_sum > 0)
            self._beats += 1
        return sleep_min

    def _listener_loop(self):
        # any beat failure (a raising side gig, a torn window) must not
        # kill the daemon SILENTLY: freeze-without-quit stalls every
        # peer until their wait timeouts. Publish quit on the way out,
        # and keep the exception so run() can re-raise it — a crashed
        # listener must not demote the run to a quiet partial result.
        try:
            while self.global_quitting == 0:
                sleep_for = self._beat()
                time.sleep(sleep_for)
        except BaseException as e:
            self._listener_error = e
            raise
        finally:
            self.quitting = 1
            try:
                self._beat()            # final beat publishes my quit flag
            except Exception:
                pass

    def run(self, work_fct, args=(), kwargs=None):
        """Start the listener daemon, run the worker inline, then quit the
        group (any participant finishing stops every listener — the
        reference's summed quitting reduce, listener_util.py:306)."""
        self._listener_error = None
        self._listener = threading.Thread(target=self._listener_loop,
                                          name="sp-listener", daemon=True)
        self._listener.start()
        try:
            result = work_fct(*args, **(kwargs or {}))
        finally:
            self.quitting = 1
            self._listener.join(timeout=30.0)
        if self._listener_error is not None:
            raise RuntimeError("listener thread failed mid-run; the "
                               "worker's result is built on stale "
                               "reductions") from self._listener_error
        return result

    @property
    def beats(self):
        """Completed listener beats (observability: a worker solving for
        seconds should see this advance — the wall-clock overlap)."""
        return self._beats


def cleanup_shm(prefix: str):
    """Best-effort unlink of every shm segment a participant group with
    this prefix may have left behind (crashed/terminated children never
    reach Synchronizer.close()). POSIX shm names surface under /dev/shm
    on Linux; missing files are fine."""
    import glob
    import os

    for f in glob.glob(f"/dev/shm{prefix}.*"):
        try:
            os.unlink(f)
        except OSError:
            pass
