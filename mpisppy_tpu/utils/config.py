"""Typed, validated configuration tree — the baseparsers/PHoptions analog.

The reference stacks three stringly layers with NO unknown-key checking
(PHoptions dicts + argparse builders + vanilla, ref. utils/baseparsers.py
:11-451, doc/src/drivers.rst:80-86 "design choice"). SURVEY §5.6 calls for
one typed validated tree instead; this is it. The three reference roles
survive as three dataclasses:

  AlgoConfig   — engine options (PHoptions analog, ref. phbase.py:1240
                 options_check keys)
  SpokeConfig  — one cylinder beyond the hub (vanilla's *_spoke dicts)
  RunConfig    — the whole run: model family + algo + hub + spokes
                 (the drivers' argparse surface, baseparsers.py:11-132)

``RunConfig.validate()`` rejects unknown model names, non-positive
scenario counts, unknown spoke kinds, and contradictory termination
settings — errors the reference only surfaces as mid-run KeyErrors.
"""

from __future__ import annotations

from dataclasses import dataclass, field, asdict

KNOWN_MODELS = ("farmer", "sizes", "sslp", "netdes", "hydro", "uc",
                "battery", "ccopf")
# subproblem kernel-backend selection (ops/kernels, doc/kernels.md).
# Defined HERE (not in ops.kernels) so validation never imports jax:
# config validation runs in process workers and the jax-free analyze
# CLI; ops.kernels imports these as its single source of truth.
KERNEL_MODES = ("auto", "fused", "segmented")
KERNEL_BACKENDS = ("reference", "pallas")
KERNEL_L_INV_MODES = ("auto", "on", "off")
KERNEL_BLOCK_DTYPES = ("auto", "bf16", "f32")
# the fused program unrolls the df32 IR sweeps statically (and the
# pallas block bakes them into its instruction stream): sweep counts
# outside this band must fail HERE as a config error, not as a deep
# trace explosion inside the fused jit (ISSUE 7 small fix)
FUSED_IR_SWEEPS = range(1, 5)
KNOWN_SPOKES = ("lagrangian", "lagranger", "xhatshuffle", "xhatlooper",
                "xhatspecific", "xhatlshaped", "fwph", "slamup",
                "slamdown", "cross_scenario", "efmip", "dive")
# incumbent source policy for the x̂ / dive spokes (doc/incumbents.md):
# "device" = batched on-device pool/dive only (host OraclePool never
# constructed), "oracle" = host-oracle sources only, "auto" = device
# sources with the oracle as the opt-in fallback/polish. Defined HERE
# (jax-free) like the kernel constants: cylinder validation and the
# CLI both read it.
INCUMBENT_MODES = ("device", "oracle", "auto")
# scenario-source selection for the chunked hot loop (mpisppy_tpu/
# stream, doc/streaming.md): "resident" = full-width device arrays
# (today's path), "streamed" = host store + double-buffered H2D chunk
# pipeline, "synthesized" = device-side seeded generation for
# randomness-in-rhs families. Defined HERE (jax-free) like the kernel
# constants: engine validation, the CLI, and the serve payload
# whitelist all read one tuple.
STREAM_SOURCES = ("resident", "streamed", "synthesized")
KNOWN_HUBS = ("ph", "aph", "lshaped")


def parse_shrink_buckets(spec) -> tuple:
    """``shrink_buckets`` knob -> strictly increasing fractions in
    (0, 1). Accepts the CLI's comma-separated string or any iterable
    of numbers. Defined HERE (jax-free) like the kernel constants:
    AlgoConfig validation, the serve payload whitelist, and the
    jax-touching ops/shrink module all read one parser."""
    if isinstance(spec, str):
        parts = [p for p in (s.strip() for s in spec.split(",")) if p]
        vals = tuple(float(p) for p in parts)
    else:
        vals = tuple(float(v) for v in spec)
    if not vals:
        raise ValueError("shrink_buckets must name at least one "
                         "threshold fraction")
    if any(not (0.0 < v < 1.0) for v in vals):
        raise ValueError(f"shrink_buckets fractions must lie in (0, 1); "
                         f"got {vals}")
    if list(vals) != sorted(set(vals)):
        raise ValueError(f"shrink_buckets must be strictly increasing; "
                         f"got {vals}")
    return vals


@dataclass
class AlgoConfig:
    """Engine options (the PHoptions analog)."""
    default_rho: float = 1.0
    max_iterations: int = 100
    convthresh: float = 1e-4
    # keep in sync with PHBase's own defaults (core/ph.py) so a CLI run
    # with no flags matches a programmatic run with no options
    subproblem_max_iter: int = 5000
    subproblem_eps: float = 1e-8
    subproblem_polish_chunk: int = 0
    # df32 x-update iterative-refinement sweeps (ops/qp_solver
    # ._m_solve_ir); validated against the kernel mode below
    subproblem_ir_sweeps: int = 1
    # kernel-backend selection (ops/kernels, doc/kernels.md):
    # "segmented" = today's host-segmented drivers bit-for-bit,
    # "fused" = one device program per solve, "auto" = fused wherever
    # the solve is eligible (the default)
    subproblem_kernel_mode: str = "auto"
    subproblem_kernel_backend: str = "reference"
    subproblem_kernel_l_inv: str = "auto"       # explicit L⁻¹ matmuls
    subproblem_kernel_block_dtype: str = "auto"  # bf16 packed blocks
    # pipelined chunk dispatch (doc/pipelining.md): pre-assembled
    # chunks + fused quality-gate sync + donated warm starts; 0 opts
    # back into the strictly sequential debug loop
    subproblem_pipeline: int = 1
    # ---- progressive problem shrinking (ops/shrink, doc/extensions.md
    # §shrinking): device-side WW fixing counters, active-set
    # compaction, per-slot adaptive rho ----
    shrink_fix: bool = False        # jitted per-var convergence counters
    shrink_fix_iters: int = 3       # consecutive converged iterations
    shrink_fix_tol: float = 1e-4    # variance-test tolerance
    shrink_compact: bool = False    # active-set compaction at bucket
    #                                 thresholds (requires shrink_fix)
    shrink_buckets: str = "0.25,0.5,0.75"   # fixed-fraction thresholds
    shrink_rho: bool = False        # per-slot device-side adaptive rho
    shrink_rho_interval: int = 1    # iterations between rho updates
    shrink_transplant: bool = True  # warm-state transplant across
    #                                 bucket transitions (iterates-only
    #                                 free-slot gather; False = the old
    #                                 cold-rebuild spelling)
    # ---- scenario streaming (mpisppy_tpu/stream, doc/streaming.md):
    # per-chunk staging of the per-scenario vector blocks instead of
    # full-width HBM residency ----
    scenario_source: str = "resident"   # STREAM_SOURCES
    stream_int8: bool = False       # int8 delta-packed host storage
    #                                 (explicit opt-in, host-side gate)
    stream_int8_tol: float = 1e-3   # gate: max per-entry recon error
    stream_depth: int = 2           # prefetch pipeline double-buffer
    # ---- APH φ-dispatch (core/aph.py + ops/dispatch.py, doc/aph.md):
    # fraction of scenarios solved per iteration (most-negative-φ first,
    # least-recently-dispatched fill; ref. aph.py dispatch_frac) plus
    # the ν/γ projective-step parameters. 1.0 = full dispatch (every
    # scenario solves; bit-identical to the pre-dispatch engine) ----
    dispatch_frac: float = 1.0      # ∈ (0, 1]; partial needs hub="aph"
    aph_nu: float = 1.0             # APHnu: step scale θ = ν·φ/τ
    aph_gamma: float = 1.0          # APHgamma: z-update damping
    linearize_proximal_terms: bool = False   # accepted + ignored (see ph.py)
    verbose: bool = False

    def to_options(self) -> dict:
        return {
            "defaultPHrho": self.default_rho,
            "PHIterLimit": self.max_iterations,
            "convthresh": self.convthresh,
            "subproblem_max_iter": self.subproblem_max_iter,
            "subproblem_eps": self.subproblem_eps,
            "subproblem_polish_chunk": self.subproblem_polish_chunk,
            "subproblem_ir_sweeps": self.subproblem_ir_sweeps,
            "subproblem_kernel_mode": self.subproblem_kernel_mode,
            "subproblem_kernel_backend": self.subproblem_kernel_backend,
            "subproblem_kernel_l_inv": self.subproblem_kernel_l_inv,
            "subproblem_kernel_block_dtype":
                self.subproblem_kernel_block_dtype,
            "subproblem_pipeline": self.subproblem_pipeline,
            # shrink_* knobs ride to_options() so they reach the engine
            # AND the serve bucket fingerprint (serve/batch.bucket_key
            # hashes algo.to_options(): shrink-enabled and
            # shrink-disabled requests never share a leased engine)
            "shrink_fix": self.shrink_fix,
            "shrink_fix_iters": self.shrink_fix_iters,
            "shrink_fix_tol": self.shrink_fix_tol,
            "shrink_compact": self.shrink_compact,
            "shrink_buckets": self.shrink_buckets,
            "shrink_rho": self.shrink_rho,
            "shrink_rho_interval": self.shrink_rho_interval,
            "shrink_transplant": self.shrink_transplant,
            # stream knobs ride to_options() so they reach the engine
            # AND the serve bucket fingerprint (a streamed engine's
            # surrogate qp_data and host store must never be leased to
            # a resident-source request, and int8-packed data is a
            # different numerical contract than exact storage)
            "scenario_source": self.scenario_source,
            "stream_int8": self.stream_int8,
            "stream_int8_tol": self.stream_int8_tol,
            "stream_depth": self.stream_depth,
            # APH knobs ride to_options() under the reference's names so
            # they reach the engine AND the serve bucket fingerprint (a
            # partial-dispatch APH engine compiles dispatch-width
            # buckets a full-dispatch engine never sees — the leases
            # must not mix)
            "dispatch_frac": self.dispatch_frac,
            "APHnu": self.aph_nu,
            "APHgamma": self.aph_gamma,
            "verbose": self.verbose,
        }

    def validate(self):
        if self.default_rho <= 0:
            raise ValueError("default_rho must be positive")
        if self.max_iterations < 0:
            raise ValueError("max_iterations must be >= 0")
        if self.subproblem_max_iter <= 0:
            raise ValueError("subproblem_max_iter must be positive")
        if self.subproblem_ir_sweeps < 1:
            raise ValueError("subproblem_ir_sweeps must be >= 1")
        if self.subproblem_kernel_mode not in KERNEL_MODES:
            raise ValueError(
                f"unknown subproblem_kernel_mode "
                f"{self.subproblem_kernel_mode!r}; known: {KERNEL_MODES}")
        if self.subproblem_kernel_backend not in KERNEL_BACKENDS:
            raise ValueError(
                f"unknown subproblem_kernel_backend "
                f"{self.subproblem_kernel_backend!r}; known: "
                f"{KERNEL_BACKENDS}")
        if self.subproblem_kernel_l_inv not in KERNEL_L_INV_MODES:
            raise ValueError(
                f"unknown subproblem_kernel_l_inv "
                f"{self.subproblem_kernel_l_inv!r}; known: "
                f"{KERNEL_L_INV_MODES}")
        if self.subproblem_kernel_block_dtype not in KERNEL_BLOCK_DTYPES:
            raise ValueError(
                f"unknown subproblem_kernel_block_dtype "
                f"{self.subproblem_kernel_block_dtype!r}; known: "
                f"{KERNEL_BLOCK_DTYPES}")
        if self.shrink_fix_iters < 1:
            raise ValueError("shrink_fix_iters must be >= 1")
        if self.shrink_fix_tol <= 0:
            raise ValueError("shrink_fix_tol must be positive")
        if self.shrink_rho_interval < 1:
            raise ValueError("shrink_rho_interval must be >= 1")
        if self.shrink_compact and not self.shrink_fix:
            raise ValueError("shrink_compact needs shrink_fix (the "
                             "compaction triggers on the device fixer's "
                             "fixed-fraction trajectory)")
        parse_shrink_buckets(self.shrink_buckets)
        if self.scenario_source not in STREAM_SOURCES:
            raise ValueError(
                f"unknown scenario_source {self.scenario_source!r}; "
                f"known: {STREAM_SOURCES}")
        if self.stream_int8 and self.scenario_source != "streamed":
            raise ValueError(
                "stream_int8 packs the STREAMED host store — it needs "
                "scenario_source='streamed' (synthesized sources ship "
                "nothing; resident arrays are not packed)")
        if self.stream_int8_tol <= 0:
            raise ValueError("stream_int8_tol must be positive")
        if self.stream_depth < 1:
            raise ValueError("stream_depth must be >= 1")
        if not (0.0 < self.dispatch_frac <= 1.0):
            raise ValueError(f"dispatch_frac must lie in (0, 1]; got "
                             f"{self.dispatch_frac}")
        if self.aph_nu <= 0:
            raise ValueError("aph_nu must be positive (θ = ν·φ/τ)")
        if self.aph_gamma <= 0:
            raise ValueError("aph_gamma must be positive (z-update "
                             "damping γ)")
        if self.scenario_source == "synthesized" and self.shrink_compact:
            raise ValueError(
                "shrink_compact cannot run over a SYNTHESIZED scenario "
                "source (the generator manufactures full-width blocks "
                "in-kernel; there is no host store to re-block at the "
                "compacted width — streamed sources compose, and the "
                "device fixer alone — shrink_fix — composes with "
                "everything)")
        # the combined rule (ISSUE 7 small fix): an explicitly-fused
        # kernel unrolls the IR sweeps statically — out-of-band counts
        # must fail here with a clear error, not as a deep jit failure.
        # "auto" instead falls back to segmented (ops/kernels.prepare).
        if self.subproblem_kernel_mode == "fused" \
                and self.subproblem_ir_sweeps not in FUSED_IR_SWEEPS:
            raise ValueError(
                f"subproblem_kernel_mode='fused' supports "
                f"subproblem_ir_sweeps in "
                f"[{FUSED_IR_SWEEPS.start}, {FUSED_IR_SWEEPS.stop - 1}] "
                f"(the fused program unrolls the sweeps statically); "
                f"got {self.subproblem_ir_sweeps}. Use "
                f"subproblem_kernel_mode='segmented' for larger sweep "
                f"counts.")


@dataclass
class SpokeConfig:
    """One spoke cylinder (vanilla's *_spoke dict analog,
    ref. utils/vanilla.py:95-408)."""
    kind: str
    options: dict = field(default_factory=dict)

    def validate(self):
        if self.kind not in KNOWN_SPOKES:
            raise ValueError(f"unknown spoke kind {self.kind!r}; "
                             f"known: {KNOWN_SPOKES}")


@dataclass
class RunConfig:
    """A full cylinder run (the driver-script surface)."""
    model: str = "farmer"
    num_scens: int = 3
    model_kwargs: dict = field(default_factory=dict)
    num_bundles: int = 0             # 0 = no bundling
    hub: str = "ph"
    algo: AlgoConfig = field(default_factory=AlgoConfig)
    hub_options: dict = field(default_factory=dict)  # hub-engine overrides
    spokes: list = field(default_factory=list)   # list[SpokeConfig]
    rel_gap: float | None = None
    abs_gap: float | None = None
    # run-level incumbent source policy (INCUMBENT_MODES above): seeds
    # every inner-bound spoke's ``incumbent_mode`` option (per-spoke
    # options win). None keeps each spoke's own default ("auto"; the
    # dive spoke defaults to "device").
    incumbent_mode: str | None = None
    solve_ef: bool = False           # solve the EF instead of a wheel
    ef_integer: bool = False
    trace_prefix: str | None = None
    # telemetry output directory (mpisppy_tpu.obs): when set, the run
    # writes events.jsonl + trace.json + metrics.json there and the
    # config snapshot lands in the stream's run_header
    telemetry_dir: str | None = None
    # ---- live plane (obs/live.py, doc/observability.md) ----
    # in-run status server owned by the hub process: /metrics
    # (Prometheus text exposition of the Recorder registry) + /status
    # (JSON wheel state). None = off; 0 = bind an ephemeral port.
    # live.json rides telemetry_dir and needs no port. The bind host
    # defaults to LOOPBACK — the endpoints serve full run state with
    # no auth; "0.0.0.0" is the explicit opt-in for remote scrapers.
    status_port: int | None = None
    status_host: str = "127.0.0.1"
    # ---- robustness (doc/fault_tolerance.md) ----
    # wheel watchdog: terminate a wheel that outlives this many seconds
    # (telemetry flushed, partial bounds reported); None = no deadline
    wheel_deadline: float | None = None
    # spoke kill-poll cadence (None = the SPOKE_SLEEP_TIME module
    # default) and the process-wheel handshake/join deadlines — typed
    # config instead of module-constant monkeypatching, so fault tests
    # can run fast scenarios
    spoke_sleep_time: float | None = None
    spoke_ready_timeout: float = 300.0
    join_timeout: float = 120.0
    # WheelSupervisor options (cylinders/supervisor.KNOWN_OPTIONS):
    # heartbeat_timeout, max_respawns, respawn_backoff(+_cap),
    # max_rejections, poll_interval, crossed_bound_tol
    supervisor: dict = field(default_factory=dict)
    # ---- durable checkpoints + resume (mpisppy_tpu.ckpt) ----
    # checkpoint_dir arms hub-owned run-state bundles (periodic from
    # the termination-check path; forced on watchdog fire and SIGTERM
    # — the preemption notice), per-spoke warm-state files the
    # supervisor hands back to respawned incarnations, and LATEST/
    # retention bookkeeping. resume_from relaunches the wheel from a
    # bundle (or a checkpoint dir, resolved through LATEST); a
    # corrupt/mismatched bundle falls back to cold start with a
    # reasoned event, never a crash (doc/fault_tolerance.md).
    checkpoint_dir: str | None = None
    checkpoint_interval: float = 30.0
    checkpoint_keep: int = 3
    resume_from: str | None = None
    # ---- scenario-axis sharding (doc/sharding.md) ----
    # mesh over the local (or, with ``coordinator``, global) device
    # set for the hub engine: None = single-device; 0 = all devices;
    # n > 0 = the first n. The engine shards every per-scenario tensor
    # over the mesh's "scen" axis and runs the PH step SPMD.
    mesh_devices: int | None = None
    # multi-process JAX over DCN (jax.distributed.initialize), so the
    # supervised process wheel spans hosts: {"address": "host:port",
    # "num_processes": N, "process_id": I, "local_device_ids": [...]}
    # — every field but ``address`` optional (TPU pods self-discover).
    coordinator: dict | None = None

    def validate(self):
        if self.model not in KNOWN_MODELS:
            raise ValueError(f"unknown model {self.model!r}; "
                             f"known: {KNOWN_MODELS}")
        if self.num_scens <= 0:
            raise ValueError("num_scens must be positive")
        if self.hub not in KNOWN_HUBS:
            raise ValueError(f"unknown hub {self.hub!r}; known: "
                             f"{KNOWN_HUBS}")
        if self.num_bundles:
            if self.num_scens % self.num_bundles != 0:
                raise ValueError("num_bundles must divide num_scens")
        if self.rel_gap is not None and not (0 <= self.rel_gap):
            raise ValueError("rel_gap must be >= 0")
        if self.abs_gap is not None and not (0 <= self.abs_gap):
            raise ValueError("abs_gap must be >= 0")
        if self.wheel_deadline is not None and self.wheel_deadline <= 0:
            raise ValueError("wheel_deadline must be positive")
        if self.incumbent_mode is not None \
                and self.incumbent_mode not in INCUMBENT_MODES:
            raise ValueError(
                f"unknown incumbent_mode {self.incumbent_mode!r}; "
                f"known: {INCUMBENT_MODES}")
        if self.status_port is not None \
                and not (0 <= int(self.status_port) <= 65535):
            raise ValueError("status_port must be in [0, 65535] "
                             "(0 = ephemeral) or None (off)")
        if self.spoke_sleep_time is not None and self.spoke_sleep_time < 0:
            raise ValueError("spoke_sleep_time must be >= 0")
        if self.spoke_ready_timeout <= 0 or self.join_timeout <= 0:
            raise ValueError("spoke_ready_timeout and join_timeout must "
                             "be positive")
        if self.checkpoint_interval <= 0:
            raise ValueError("checkpoint_interval must be positive "
                             "(seconds between periodic bundles)")
        if self.checkpoint_keep < 1:
            raise ValueError("checkpoint_keep must be >= 1")
        from ..cylinders.supervisor import KNOWN_OPTIONS
        bad = set(self.supervisor) - set(KNOWN_OPTIONS)
        if bad:
            raise ValueError(f"unknown supervisor options {sorted(bad)}; "
                             f"known: {sorted(KNOWN_OPTIONS)}")
        if self.mesh_devices is not None and self.mesh_devices < 0:
            raise ValueError("mesh_devices must be None (no mesh), 0 "
                             "(all devices), or a positive count")
        if self.coordinator is not None:
            known = {"address", "num_processes", "process_id",
                     "local_device_ids"}
            bad = set(self.coordinator) - known
            if bad:
                raise ValueError(f"unknown coordinator keys {sorted(bad)};"
                                 f" known: {sorted(known)}")
            if not self.coordinator.get("address"):
                raise ValueError("coordinator needs an 'address' "
                                 "(\"host:port\" of process 0)")
            for k in ("num_processes", "process_id"):
                v = self.coordinator.get(k)
                if v is not None and int(v) < 0:
                    raise ValueError(f"coordinator.{k} must be >= 0")
        self.algo.validate()
        if self.algo.dispatch_frac < 1.0 and self.hub != "aph":
            raise ValueError(
                "dispatch_frac < 1 is φ-based partial dispatch — only "
                "the APH hub scores φ and can skip solves (hub='aph'); "
                "synchronous PH must solve every scenario each iteration")
        for sp in self.spokes:
            sp.validate()
        if self.hub == "lshaped" and any(
                sp.kind == "fwph" for sp in self.spokes):
            raise ValueError("fwph spoke expects a PH-family hub")
        if self.hub != "ph" and any(
                sp.kind == "cross_scenario" for sp in self.spokes):
            raise ValueError("cross_scenario cuts require the 'ph' hub "
                             "(only CrossScenarioHub consumes cut windows)")
        return self

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class ServeConfig:
    """The serving layer's surface (``python -m mpisppy_tpu serve``,
    mpisppy_tpu/serve/ — doc/serving.md). jax-free like the rest of
    this module: the HTTP/queue plane validates it without a runtime.
    """
    state_dir: str = ""
    host: str = "127.0.0.1"          # loopback default, like status_host
    port: int = 8765                 # 0 = ephemeral (serve.json records it)
    # wheel workers: concurrent wheels; same-bucket wheels additionally
    # serialize on the warm engine lease (serve/cache)
    max_wheels: int = 1
    queue_limit: int = 64            # bounded admission (full = 429)
    # scenario-axis batcher: wait up to batch_window seconds for
    # same-bucket stragglers, stack at most batch_max requests into one
    # wheel (1 disables coalescing)
    batch_window: float = 0.25
    batch_max: int = 8
    cache_buckets: int = 8           # warm-cache LRU capacity
    checkpoint_interval: float = 5.0  # per-wheel bundle cadence
    default_deadline: float | None = None   # per-request SLO seconds
    # terminal request records (and their ckpt namespaces + stale
    # group files) are swept at startup once older than this — the
    # request-store twin of checkpoint_keep retention. Results remain
    # durable for the whole window; a production service must not
    # accrete one json per request forever.
    request_retention: float = 7 * 24 * 3600.0
    telemetry_dir: str | None = None
    # fleet (serve/migrate): peer base URLs this host may hand live
    # wheels to (empty = solo host, SIGTERM stays bundle-and-exit);
    # per-transfer wall-clock budget + per-call retry attempts for one
    # handoff; and the poison-pill bound — a request re-admitted by
    # startup recovery more than max_recoveries times quarantines
    # (settles failed) instead of crash-looping the service forever.
    peers: tuple = ()
    migrate_deadline: float = 60.0
    migrate_retries: int = 3
    max_recoveries: int = 3

    def validate(self):
        if not self.state_dir:
            raise ValueError("serve needs a state_dir (durable request "
                             "records + ckpt bundles live there)")
        if not (0 <= int(self.port) <= 65535):
            raise ValueError("port must be in [0, 65535] (0 = ephemeral)")
        if self.max_wheels < 1:
            raise ValueError("max_wheels must be >= 1")
        if self.queue_limit < 1:
            raise ValueError("queue_limit must be >= 1")
        if self.batch_window < 0:
            raise ValueError("batch_window must be >= 0 seconds")
        if self.batch_max < 1:
            raise ValueError("batch_max must be >= 1")
        if self.cache_buckets < 1:
            raise ValueError("cache_buckets must be >= 1")
        if self.checkpoint_interval <= 0:
            raise ValueError("checkpoint_interval must be positive")
        if self.default_deadline is not None and self.default_deadline <= 0:
            raise ValueError("default_deadline must be positive seconds")
        if self.request_retention <= 0:
            raise ValueError("request_retention must be positive seconds")
        for p in self.peers:
            if not str(p).strip():
                raise ValueError("peers must be non-empty host[:port] "
                                 "or http:// base URLs")
        if self.migrate_deadline <= 0:
            raise ValueError("migrate_deadline must be positive seconds")
        if self.migrate_retries < 1:
            raise ValueError("migrate_retries must be >= 1")
        if self.max_recoveries < 1:
            raise ValueError("max_recoveries must be >= 1")
        return self

    def to_dict(self) -> dict:
        return asdict(self)


def config_from_dict(d: dict) -> RunConfig:
    """Inverse of RunConfig.to_dict() (for process workers)."""
    d = dict(d)
    d["algo"] = AlgoConfig(**d["algo"])
    d["spokes"] = [SpokeConfig(**s) for s in d["spokes"]]
    return RunConfig(**d)
