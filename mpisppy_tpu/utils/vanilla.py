"""Config -> hub/spoke construction dicts (the vanilla analog).

Mirrors mpisppy/utils/vanilla.py:30-408: canned factories that turn the
validated RunConfig into the hub/spoke dict schema spin_the_wheel
consumes, one factory per cylinder kind. Every cylinder gets its OWN
engine over its own batch (the reference's cylinders each own an opt
object the same way, ref. sputils.py:99-108).
"""

from __future__ import annotations

from .. import obs
from .config import RunConfig, SpokeConfig

_DTYPES = {"float32": "float32", "f32": "float32",
           "float64": "float64", "f64": "float64"}


def _pop_dtype(options):
    """Extract an optional per-cylinder "dtype" option ("float32"/"f64"/…)
    into an engine dtype kwarg — e.g. an f32 hub for hot-loop speed with
    f64 bound spokes for certified tightness in the same wheel."""
    name = options.pop("dtype", None)
    if name is None:
        return {}
    import jax.numpy as jnp
    return {"dtype": getattr(jnp, _DTYPES[str(name)])}


def build_batch_for(cfg: RunConfig):
    """Model registry: name -> stacked batch (+ bundling). Models that
    export ``scenario_vector_patch`` get the structure-shared fast path
    (ir/batch.py build_batch(vector_patch=...)) automatically — at
    reference-UC scale that is the difference between one template
    lowering and S of them."""
    from ..ir.batch import build_batch
    from .. import models

    mod = getattr(models, cfg.model)
    kwargs = dict(cfg.model_kwargs)
    if cfg.model in ("hydro", "ccopf"):
        # the creator decodes scenario numbers with the SAME branching the
        # tree was built with — they must never diverge, whether the user
        # passed the branching under tree_kwargs or directly in
        # model_kwargs. Merge both into one source of truth.
        tk = dict(kwargs.pop("tree_kwargs", {}))
        bkey = "branching" if cfg.model == "ccopf" else "branching_factors"
        if bkey in kwargs:
            if bkey in tk and tuple(tk[bkey]) != tuple(kwargs[bkey]):
                raise ValueError(
                    f"{cfg.model}: {bkey} given in both model_kwargs and "
                    "tree_kwargs with different values")
            tk.setdefault(bkey, kwargs[bkey])
        tree = mod.make_tree(**tk)
        kwargs.update(tk)
    else:
        tree = mod.make_tree(cfg.num_scens)
    if cfg.algo.scenario_source == "synthesized":
        # synthesized scenario source (mpisppy_tpu/stream,
        # doc/streaming.md): the model's synth spec is the single
        # source of the family's data — the creator runs once for the
        # shared template, the batch vectors are zero-stride broadcast
        # VIEWS of it (an S=1M batch costs no host memory), and the
        # engine manufactures the per-scenario rhs perturbations
        # in-kernel. The spec rides the batch to hub_dict, which
        # forwards it as the ``synth_spec`` engine option.
        if not hasattr(mod, "scenario_synth_spec"):
            raise ValueError(
                f"scenario_source='synthesized' needs model "
                f"{cfg.model!r} to export scenario_synth_spec "
                "(doc/streaming.md; farmer and uc do)")
        if cfg.num_bundles:
            raise ValueError("bundling merges scenario blocks and is "
                             "not supported with a synthesized "
                             "scenario source")
        from ..stream.synth import synth_batch
        seed = int(kwargs.pop("synth_seed", 0))
        batch, spec = synth_batch(
            mod.scenario_creator, tree, mod.scenario_synth_spec,
            creator_kwargs=kwargs, seed=seed, materialize_values=False)
        batch._synth_spec = spec
        obs.event("batch.build", {"model": cfg.model, "S": batch.S,
                                  "K": batch.K, "n": batch.n,
                                  "shared_A": True,
                                  "scenario_source": "synthesized"})
        return batch
    batch = build_batch(mod.scenario_creator, tree, creator_kwargs=kwargs,
                        vector_patch=getattr(mod, "scenario_vector_patch",
                                             None))
    if cfg.num_bundles:
        from ..core.bundles import form_bundles
        batch = form_bundles(batch, cfg.num_bundles)
    obs.event("batch.build", {"model": cfg.model, "S": batch.S,
                              "K": batch.K, "n": batch.n,
                              "shared_A": bool(batch.shared_A)})
    return batch


def ckpt_fingerprint(cfg: RunConfig) -> str:
    """The run-identity fingerprint stamped into checkpoint bundles
    (ckpt/bundle.config_fingerprint): a bundle only resumes into a
    wheel with the same model family, scenario count, model kwargs,
    bundling, and hub algorithm — anything else would install
    foreign (or shape-mismatched) state."""
    from ..ckpt.bundle import config_fingerprint
    return config_fingerprint({
        "model": cfg.model, "num_scens": cfg.num_scens,
        "model_kwargs": cfg.model_kwargs,
        "num_bundles": cfg.num_bundles, "hub": cfg.hub})


def hub_dict(cfg: RunConfig, batch=None):
    """ref. vanilla.py:54 ph_hub (+ aph/lshaped variants). ``batch``:
    optionally a prebuilt batch shared across cylinders (engines never
    mutate the host arrays; wheel_dicts passes one build to all)."""
    from ..core.ph import PH
    from ..core.aph import APH
    from ..core.lshaped import LShapedMethod
    from ..core.cross_scenario import CrossScenarioPH
    from ..cylinders.hub import PHHub, APHHub, LShapedHub, CrossScenarioHub

    options = cfg.algo.to_options()
    options.update(cfg.hub_options)
    dtype_kw = _pop_dtype(options)
    hub_kwargs = {"options": {}}
    if cfg.rel_gap is not None:
        hub_kwargs["options"]["rel_gap"] = cfg.rel_gap
    if cfg.abs_gap is not None:
        hub_kwargs["options"]["abs_gap"] = cfg.abs_gap
    if cfg.wheel_deadline is not None:
        hub_kwargs["options"]["wheel_deadline"] = cfg.wheel_deadline
    if cfg.status_port is not None:
        # the hub process owns the live status server (obs/live.py)
        hub_kwargs["options"]["status_port"] = cfg.status_port
        hub_kwargs["options"]["status_host"] = cfg.status_host
    if "crossed_bound_tol" in cfg.supervisor:
        hub_kwargs["options"]["crossed_bound_tol"] = \
            cfg.supervisor["crossed_bound_tol"]
    if cfg.checkpoint_dir or cfg.resume_from:
        # durable run-state checkpoints + resume (mpisppy_tpu.ckpt):
        # the hub owns capture; resume installs before iter 0. The
        # fingerprint makes a bundle from a different configuration
        # refuse cleanly at load.
        if cfg.checkpoint_dir:
            hub_kwargs["options"]["checkpoint_dir"] = cfg.checkpoint_dir
            hub_kwargs["options"]["checkpoint_interval"] = \
                cfg.checkpoint_interval
            hub_kwargs["options"]["checkpoint_keep"] = cfg.checkpoint_keep
        if cfg.resume_from:
            hub_kwargs["options"]["resume_from"] = cfg.resume_from
        hub_kwargs["options"]["checkpoint_fingerprint"] = \
            ckpt_fingerprint(cfg)

    cross = any(sp.kind == "cross_scenario" for sp in cfg.spokes)
    if cfg.hub == "ph":
        opt_cls, hub_cls = (CrossScenarioPH, CrossScenarioHub) if cross \
            else (PH, PHHub)
    elif cfg.hub == "aph":
        opt_cls, hub_cls = APH, APHHub
    else:
        opt_cls, hub_cls = LShapedMethod, LShapedHub
    opt_kwargs = {"batch": batch if batch is not None
                  else build_batch_for(cfg),
                  "options": options, **dtype_kw}
    spec = getattr(opt_kwargs["batch"], "_synth_spec", None)
    if spec is not None:
        # the synthesized source's generator (build_batch_for attached
        # it): an engine option rather than config — SynthSpec holds a
        # jax callable and cannot ride the jax-free config tree
        options["synth_spec"] = spec
    if cfg.mesh_devices is not None:
        if cfg.hub in ("ph", "aph") and not cross:
            # scenario-axis sharding for the hub engine
            # (doc/sharding.md): 0 = every visible device (the whole
            # slice — or the whole pod when
            # utils/runtime.maybe_init_distributed ran first)
            import warnings

            import jax

            from ..parallel.mesh import make_mesh
            n_vis = len(jax.devices())
            if cfg.mesh_devices > n_vis:
                warnings.warn(
                    f"mesh_devices={cfg.mesh_devices} exceeds the "
                    f"{n_vis} visible device(s) — sharding over all "
                    f"{n_vis} (multi-host runs need the coordinator "
                    "knob so jax sees the global set, doc/sharding.md)",
                    RuntimeWarning, stacklevel=2)
            opt_kwargs["mesh"] = make_mesh(
                n_devices=min(cfg.mesh_devices, n_vis) or None)
        else:
            # the lshaped hub and the cross-scenario cut engine keep
            # the unsharded path (the cut store is not sharding-
            # audited) — say so instead of silently dropping the knob
            import warnings
            warnings.warn(
                f"mesh_devices is ignored for this wheel (hub="
                f"{cfg.hub!r}{', cross_scenario' if cross else ''}): "
                "scenario-axis sharding covers the ph/aph hubs only "
                "(doc/sharding.md)", RuntimeWarning, stacklevel=2)
    return {"hub_class": hub_cls, "hub_kwargs": hub_kwargs,
            "opt_class": opt_cls, "opt_kwargs": opt_kwargs}


def spoke_classes(kind: str):
    """(spoke_class, opt_class) for a spoke kind — importable without
    building any batch (the multi-process launcher sizes windows from
    the class alone)."""
    from ..core.ph import PHBase
    from ..core.fwph import FWPH
    from ..core.lshaped import LShapedMethod
    from ..cylinders.lagrangian_bounder import (LagrangianOuterBound,
                                                LagrangerOuterBound)
    from ..cylinders.xhat_bounders import (DiveInnerBound,
                                           XhatLooperInnerBound,
                                           XhatShuffleInnerBound,
                                           XhatSpecificInnerBound,
                                           XhatLShapedInnerBound)
    from ..cylinders.slam_heuristic import (SlamUpHeuristic,
                                            SlamDownHeuristic)
    from ..cylinders.fwph_spoke import FrankWolfeOuterBound
    from ..cylinders.cross_scen_spoke import CrossScenarioCutSpoke
    from ..cylinders.ef_bounder import EFMipBound

    return {
        "lagrangian": (LagrangianOuterBound, PHBase),
        "efmip": (EFMipBound, PHBase),
        "lagranger": (LagrangerOuterBound, PHBase),
        "xhatshuffle": (XhatShuffleInnerBound, PHBase),
        "xhatlooper": (XhatLooperInnerBound, PHBase),
        "xhatspecific": (XhatSpecificInnerBound, PHBase),
        "xhatlshaped": (XhatLShapedInnerBound, PHBase),
        "fwph": (FrankWolfeOuterBound, FWPH),
        "slamup": (SlamUpHeuristic, PHBase),
        "slamdown": (SlamDownHeuristic, PHBase),
        "cross_scenario": (CrossScenarioCutSpoke, LShapedMethod),
        # device-side batched incumbent search (doc/incumbents.md)
        "dive": (DiveInnerBound, PHBase),
    }[kind]


def spoke_dict(cfg: RunConfig, sp: SpokeConfig, batch=None):
    """ref. vanilla.py:95-408 — one factory per spoke kind."""
    spoke_cls, opt_cls = spoke_classes(sp.kind)
    options = cfg.algo.to_options()
    options.update(sp.options)
    # run-level spoke knobs (per-spoke options win): the typed config
    # replaces SPOKE_SLEEP_TIME monkeypatching in fast fault scenarios
    if cfg.spoke_sleep_time is not None:
        options.setdefault("spoke_sleep_time", cfg.spoke_sleep_time)
    if cfg.incumbent_mode is not None:
        # run-level incumbent source policy (doc/incumbents.md); only
        # the x̂-family spokes read it, and per-spoke options still win
        options.setdefault("incumbent_mode", cfg.incumbent_mode)
    dtype_kw = _pop_dtype(options)
    spoke_kwargs = {}
    if cfg.trace_prefix:
        spoke_kwargs["trace_prefix"] = cfg.trace_prefix
    return {"spoke_class": spoke_cls, "spoke_kwargs": spoke_kwargs,
            "opt_class": opt_cls,
            "opt_kwargs": {"batch": batch if batch is not None
                           else build_batch_for(cfg),
                           "options": options, **dtype_kw}}


def wheel_dicts(cfg: RunConfig):
    """The full (hub_dict, spoke_dicts) pair for spin_the_wheel. The
    batch is built ONCE and shared by every cylinder (engines read the
    host arrays, they never write them) — at reference-UC scale each
    template lowering costs ~a minute, so per-cylinder rebuilds would
    multiply a fixed cost by the wheel width."""
    cfg.validate()
    if cfg.algo.scenario_source != "resident" and cfg.spokes:
        # v1 scope (doc/streaming.md): spoke engines read full-width
        # batch arrays (incumbent pools, Lagrangian warm states) that
        # a streamed hub deliberately never ships — a streaming wheel
        # runs hub-only until the spoke surfaces are stream-audited
        raise ValueError(
            "scenario_source='streamed'/'synthesized' wheels are "
            "hub-only (doc/streaming.md v1 scope); drop the spokes or "
            "use scenario_source='resident'")
    obs.event("wheel.build", {"model": cfg.model,
                              "num_scens": cfg.num_scens,
                              "hub": cfg.hub,
                              "spokes": [sp.kind for sp in cfg.spokes]})
    batch = build_batch_for(cfg)
    spoke_ds = [spoke_dict(cfg, sp, batch=batch) for sp in cfg.spokes]
    if cfg.checkpoint_dir or cfg.resume_from:
        # per-spoke checkpoint/resume wiring needs the spoke INDEX
        # (file naming), which spoke_dict alone never sees; the
        # process launcher does the same injection per spawn
        # (utils/multiproc._spawn_one_spoke, generation-aware)
        from ..ckpt.spoke_state import spoke_resume_options
        for i, (sp, sd) in enumerate(zip(cfg.spokes, spoke_ds)):
            for k, v in spoke_resume_options(
                    cfg.checkpoint_dir, cfg.resume_from, i,
                    sp.kind).items():
                sd["opt_kwargs"]["options"].setdefault(k, v)
    return hub_dict(cfg, batch=batch), spoke_ds
