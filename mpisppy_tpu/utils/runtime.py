"""Process-level JAX runtime setup shared by the CLI and process workers.

One place for the precision policy and the persistent compile cache so
hub and spoke processes can never silently diverge (the cache is only
shared when every process configures the same directory).
"""

from __future__ import annotations

COMPILE_CACHE_DIR = "/tmp/jax_cache"


def enable_honest_f32():
    """TPU f32 matmuls default to reduced (bf16-pass) precision —
    enough to stall the f32 ADMM phase near 1e-1 where true f32
    converges to ~1e-3 (measured: the f32 hub's iter-0 feasibility
    gate fails on TPU but passes on CPU with identical code). Solver
    math needs honest f32. ONE policy point: every entry path
    (setup_jax_runtime, bench.py, __graft_entry__.py) calls this."""
    import jax

    jax.config.update("jax_default_matmul_precision", "highest")


def setup_jax_runtime(f32: bool = False):
    import jax

    if not f32:
        jax.config.update("jax_enable_x64", True)
    enable_honest_f32()
    jax.config.update("jax_compilation_cache_dir", COMPILE_CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


_DISTRIBUTED_UP = False


def maybe_init_distributed(coordinator) -> bool:
    """Multi-process JAX over DCN behind the ``RunConfig.coordinator``
    knob: when ``coordinator`` is set, call
    ``jax.distributed.initialize`` so every participating process sees
    the GLOBAL device set and ``parallel/mesh.make_mesh()`` builds a
    multi-host "scen" axis (the sharded PH step's psums then ride ICI
    within a host and DCN across hosts — doc/sharding.md). Idempotent;
    returns True when initialization ran (now or earlier).

    ``coordinator`` is a dict: ``address`` ("host:port", required),
    ``num_processes``, ``process_id``, optional ``local_device_ids``.
    Must run BEFORE the backend initializes — call it ahead of engine
    construction (the CLI and spin_the_wheel_processes both do)."""
    global _DISTRIBUTED_UP
    if not coordinator:
        return False
    if _DISTRIBUTED_UP:
        return True
    import jax

    kw = {"coordinator_address": coordinator["address"]}
    for src, dst in (("num_processes", "num_processes"),
                     ("process_id", "process_id"),
                     ("local_device_ids", "local_device_ids")):
        if coordinator.get(src) is not None:
            kw[dst] = coordinator[src]
    jax.distributed.initialize(**kw)
    _DISTRIBUTED_UP = True
    return True
