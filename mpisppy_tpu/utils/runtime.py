"""Process-level JAX runtime setup shared by the CLI and process workers.

One place for the precision policy and the persistent compile cache so
hub and spoke processes can never silently diverge (the cache is only
shared when every process configures the same directory).
"""

from __future__ import annotations

COMPILE_CACHE_DIR = "/tmp/jax_cache"


def setup_jax_runtime(f32: bool = False):
    import jax

    if not f32:
        jax.config.update("jax_enable_x64", True)
    jax.config.update("jax_compilation_cache_dir", COMPILE_CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
