"""Wheel supervisor: liveness, spoke respawn, quarantine, watchdog.

The reference inherits MPI's fault model — one dead rank kills the
whole job. Our wheel runs spokes as OS processes over shared-memory
seqlock windows (utils/multiproc.py), so a crashed, hung, or
garbage-publishing spoke is *survivable*: the supervisor is the piece
that makes it actually survived instead of silently degrading.

Four mechanisms (doc/fault_tolerance.md has the full semantics):

- **liveness** — polled from the hub's sync path (``Hub.receive_bounds``
  calls :meth:`WheelSupervisor.poll`): ``Process.is_alive()`` per spoke,
  plus optional write-id heartbeat progress (bound spokes re-stamp
  their window when idle — cylinders/spoke.py ``_heartbeat`` — so a
  healthy-but-boundless spoke still pulses; a spoke whose write-id
  stops advancing for ``heartbeat_timeout`` seconds is declared
  stalled and terminated).
- **recovery** — a dead spoke is respawned through the launcher's
  ``respawner`` callback on a FRESH window pair (generation-suffixed
  shm names; the dead generation's windows are retired in place and
  unlinked at wheel teardown), with capped exponential backoff
  between attempts. With checkpointing armed (``checkpoint_dir``,
  mpisppy_tpu.ckpt), the respawner's spawn body hands generation N
  the latest warm-state file generation N-1 persisted, so a respawn
  RESUMES the spoke — first published bound no worse than the dead
  generation's best — instead of restarting it cold
  (doc/fault_tolerance.md §checkpoint/resume).
- **quarantine** — after ``max_respawns`` crashes (or
  ``max_rejections`` corrupt payloads flagged by the hub's ingest
  validation) the spoke is retired: removed from the hub's
  classification sets so sends/receives skip it, and the wheel
  continues without it.
- **watchdog** — ``start_watchdog(deadline)`` arms a timer that fires
  :meth:`Hub.fire_watchdog` (terminate + telemetry flush + partial
  bounds) if the wheel outlives its deadline, the wheel-level analog
  of bench.py's SIGTERM flush.

Every transition lands in telemetry: ``hub.spoke_down`` /
``hub.spoke_respawn`` / ``hub.spoke_quarantined`` events + same-named
counters (catalogued in doc/observability.md; ``analyze`` renders them
as the faults section and the degraded-run invariant).

The supervisor runs on the hub's thread (poll is called from
``receive_bounds``), so spoke-list/window swaps never race hub reads;
only the watchdog timer runs on its own daemon thread, and it touches
nothing but the once-guarded ``fire_watchdog``.
"""

from __future__ import annotations

import threading
import time

from .. import global_toc, obs

# states a supervised spoke moves through
RUNNING = "running"
DOWN = "down"              # dead/stalled, respawn scheduled (in backoff)
QUARANTINED = "quarantined"

_DEFAULTS = {
    "poll_interval": 0.25,        # min seconds between full liveness sweeps
    "heartbeat_timeout": None,    # None = write-id progress not enforced
    "max_respawns": 2,            # crashes beyond this quarantine the spoke
    "respawn_backoff": 0.5,       # first-respawn delay (doubles per crash)
    "respawn_backoff_cap": 30.0,
    "max_rejections": 5,          # corrupt payloads before quarantine
}

KNOWN_OPTIONS = (*_DEFAULTS, "crossed_bound_tol")


class WheelDeadline:
    """The watchdog timer half of the supervisor, standalone — for
    wheels with no spoke processes to supervise (the serving layer's
    in-process hub-only wheels, mpisppy_tpu/serve). Arms a daemon
    timer that fires the hub's once-guarded :meth:`Hub.fire_watchdog`
    if the wheel outlives its deadline, even when an iteration wedges
    and the hub never reaches another termination check — exactly
    ``WheelSupervisor.start_watchdog``'s contract, minus the process
    management."""

    def __init__(self, hub, deadline: float):
        self.hub = hub
        self._timer = threading.Timer(float(deadline), self._fire)
        self._timer.daemon = True
        self._cancelled = False

    def start(self):
        self._timer.start()
        return self

    def _fire(self):
        if not self._cancelled and self.hub is not None:
            self.hub.fire_watchdog("deadline_timer")

    def cancel(self):
        self._cancelled = True
        self._timer.cancel()


class _SpokeHealth:
    __slots__ = ("state", "crashes", "rejections", "next_respawn_at",
                 "last_wid", "last_progress", "gen")

    def __init__(self, now):
        self.state = RUNNING
        self.crashes = 0
        self.rejections = 0
        self.next_respawn_at = 0.0
        self.last_wid = 0
        self.last_progress = now
        self.gen = 0


class WheelSupervisor:
    """Supervises one multi-process wheel's spokes.

    ``spokes`` / ``procs`` / ``owned`` are the launcher's LIVE lists
    (utils/multiproc.spin_the_wheel_processes): the supervisor mutates
    them in place on respawn/quarantine so the hub's sends, the final
    join loop, and the window-unlink cleanup always see current state.
    ``respawner(i, gen) -> (proxy, proc)`` spawns generation ``gen`` of
    spoke ``i`` on a fresh window pair.
    """

    def __init__(self, spokes, procs, kinds=None, options=None,
                 respawner=None, owned=None):
        bad = set(options or ()) - set(KNOWN_OPTIONS)
        if bad:
            raise ValueError(f"unknown supervisor options {sorted(bad)}; "
                             f"known: {sorted(KNOWN_OPTIONS)}")
        self.opts = {**_DEFAULTS, **(options or {})}
        self.spokes = spokes
        self.procs = procs
        self.kinds = list(kinds or ["?"] * len(spokes))
        self._respawner = respawner
        self._owned = owned if owned is not None else []
        now = time.monotonic()
        self.health = [_SpokeHealth(now) for _ in spokes]
        self.hub = None
        self._last_poll = 0.0
        self._closed = False
        self._watchdog = None

    # ---- wiring ----
    def attach(self, hub):
        hub.supervisor = self
        self.hub = hub
        # the hub COPIES the spoke list at construction (Hub.__init__);
        # supervise the hub's own list so a respawn swap is what the
        # hub's sends/receives actually see
        if getattr(hub, "spokes", None) is not None:
            self.spokes = hub.spokes
        return self

    def start_watchdog(self, deadline: float):
        """Arm the wheel deadline: after ``deadline`` seconds the hub's
        watchdog fires even if the hub never reaches another
        termination check (terminate signal to every spoke + telemetry
        flush + partial bounds event)."""
        self._watchdog = threading.Timer(float(deadline),
                                         self._watchdog_fire)
        self._watchdog.daemon = True
        self._watchdog.start()

    def _watchdog_fire(self):
        if self._closed or self.hub is None:
            return
        self.hub.fire_watchdog("supervisor")

    def shutdown(self):
        """Stop supervising (called before the hub's own terminate):
        no further respawns, watchdog cancelled. Idempotent."""
        self._closed = True
        if self._watchdog is not None:
            self._watchdog.cancel()
            self._watchdog = None

    # ---- state queries ----
    def state(self, i) -> str:
        return self.health[i].state

    def quarantined(self):
        return [i for i, h in enumerate(self.health)
                if h.state == QUARANTINED]

    # ---- the sync-path poll ----
    def poll(self):
        """One rate-limited liveness sweep; runs on the hub thread."""
        if self._closed:
            return
        now = time.monotonic()
        if now - self._last_poll < self.opts["poll_interval"]:
            return
        self._last_poll = now
        for i, h in enumerate(self.health):
            if h.state == QUARANTINED:
                continue
            if h.state == DOWN:
                if now >= h.next_respawn_at:
                    self._respawn(i, h)
                continue
            p = self.procs[i]
            if not p.is_alive():
                self._mark_down(i, h, "died",
                                exitcode=getattr(p, "exitcode", None))
                continue
            hb = self.opts["heartbeat_timeout"]
            if hb is not None:
                wid = self.spokes[i].my_window.read_id()
                if wid != h.last_wid:
                    h.last_wid = wid
                    h.last_progress = now
                elif now - h.last_progress > float(hb):
                    # alive but not pulsing: treat as hung — terminate
                    # so the respawn path takes over
                    p.terminate()
                    self._mark_down(i, h, "stalled")

    # ---- transitions ----
    def _mark_down(self, i, h, reason, exitcode=None):
        h.crashes += 1
        obs.counter_add("hub.spoke_down")
        obs.event("hub.spoke_down",
                  {"spoke": i, "kind": self.kinds[i], "reason": reason,
                   "exitcode": exitcode, "crashes": h.crashes})
        global_toc(f"supervisor: spoke {i} ({self.kinds[i]}) {reason} "
                   f"(crash {h.crashes}, exitcode {exitcode})")
        if h.crashes > int(self.opts["max_respawns"]) \
                or self._respawner is None:
            self._quarantine(i, h, "crashes")
            return
        backoff = min(self.opts["respawn_backoff"] * 2 ** (h.crashes - 1),
                      self.opts["respawn_backoff_cap"])
        h.state = DOWN
        h.next_respawn_at = time.monotonic() + backoff

    def _respawn(self, i, h):
        h.gen += 1
        try:
            proxy, proc = self._respawner(i, h.gen)
        except Exception as e:
            # a failed spawn counts as another crash (backoff doubles,
            # quarantine eventually) — never raises into the hub loop
            global_toc(f"supervisor: respawn of spoke {i} failed ({e!r})")
            self._mark_down(i, h, "respawn_failed")
            return
        # adopt the fresh pair; the dead generation's windows STAY in
        # the launcher's owned list and are unlinked at wheel teardown,
        # not here — closing them now could race the watchdog timer
        # thread's send_terminate sweep over a stale spoke reference
        # (a kill() on a freed shm handle). They are tiny (a few
        # doubles each) and bounded by the crash budget.
        self._owned += [proxy.hub_window, proxy.my_window]
        self.spokes[i] = proxy
        self.procs[i] = proc
        now = time.monotonic()
        h.state = RUNNING
        h.last_wid = 0
        h.last_progress = now
        if self.hub is not None:
            # fresh window pair starts at write-id 0 — reset freshness
            # so the respawned spoke's hello/bounds are consumed; the
            # bound-flow tracker likewise restarts its lineage seq
            self.hub._spoke_last_ids[i] = 0
            self.hub.note_spoke_respawn(i, h.gen)
        obs.counter_add("hub.spoke_respawn")
        obs.event("hub.spoke_respawn",
                  {"spoke": i, "kind": self.kinds[i], "gen": h.gen,
                   "crashes": h.crashes})
        global_toc(f"supervisor: spoke {i} ({self.kinds[i]}) respawned "
                   f"(gen {h.gen})")

    def _quarantine(self, i, h, cause):
        h.state = QUARANTINED
        obs.counter_add("hub.spoke_quarantined")
        obs.event("hub.spoke_quarantined",
                  {"spoke": i, "kind": self.kinds[i], "cause": cause,
                   "crashes": h.crashes, "rejections": h.rejections})
        global_toc(f"WARNING: supervisor quarantined spoke {i} "
                   f"({self.kinds[i]}) after {cause}; wheel continues "
                   "without it")
        hub = self.hub
        if hub is not None:
            for attr in ("outer_bound_spoke_indices",
                         "inner_bound_spoke_indices",
                         "w_spoke_indices", "nonant_spoke_indices",
                         "cut_spoke_indices"):
                getattr(hub, attr, set()).discard(i)
        # a live-but-poisonous spoke (rejection quarantine) is released
        # via its own kill signal so it exits before the final join
        p = self.procs[i]
        if p is not None and p.is_alive():
            self.spokes[i].hub_window.kill()

    def note_rejection(self, i):
        """The hub's ingest validation flags one rejected payload from
        spoke ``i`` (see Hub._reject_bound); enough of them retire the
        spoke — a corrupt publisher is as dead as a crashed one."""
        if self._closed or i >= len(self.health):
            return
        h = self.health[i]
        h.rejections += 1
        if h.state == RUNNING \
                and h.rejections >= int(self.opts["max_rejections"]):
            self._quarantine(i, h, "rejections")
