"""Lagrangian outer-bound spokes.

``LagrangianOuterBound`` (ref. mpisppy/cylinders/lagrangian_bounder.py:5-87):
takes the hub's W, solves all subproblems with W on / prox off, and
publishes the expected *certified dual* bound (our Ebound is built from the
ADMM dual vectors, so an inexactly solved subproblem cannot overstate it).

``LagrangerOuterBound`` (ref. mpisppy/cylinders/lagranger_bounder.py:9-95):
takes the hub's *nonants* instead and computes its own x̄ and W locally
(optionally with a rescaled rho) before bounding.
"""

from __future__ import annotations

import jax.numpy as jnp

from .spoke import OuterBoundWSpoke, OuterBoundNonantSpoke


class LagrangianOuterBound(OuterBoundWSpoke):
    """Two bound engines, selected by the ``lagrangian_exact_oracle``
    option:

    - default: the batched on-device solve + certified dual bound
      (valid at ANY solve accuracy, tight once duals converge);
    - exact oracle: per-scenario host HiGHS LPs (utils/host_oracle) —
      exact L(W), the analog of the reference's spoke renting a CPU
      simplex per scenario (ref. lagrangian_bounder.py:5-87). Linear
      objectives only; the spoke is asynchronous so host latency never
      blocks the hub."""
    converger_spoke_char = "L"

    @property
    def _exact(self):
        # the host oracle evaluates sum_s p_s (min f_s + W_s x), which is
        # a valid outer bound only on the sum_s p_s W_s = 0 manifold and
        # only for LINEAR objectives — under VARIABLE probabilities the
        # engine's W lives on the vprob-weighted manifold, and quadratic
        # models have no host LP form, so both fall back silently to the
        # (vprob-aware, quadratic-capable) certified device bound
        import numpy as np
        return bool(self.options.get("lagrangian_exact_oracle", False)) \
            and getattr(self.opt, "vprob", None) is None \
            and float(np.abs(np.asarray(self.opt.batch.P_diag)).max()) == 0.0

    def lagrangian_prep(self):
        """Trivial bound before any W arrives (ref. lagrangian_bounder.py:20-52)."""
        if self._exact:
            from ..utils.host_oracle import exact_lagrangian_bound
            b = exact_lagrangian_bound(self.opt.batch, self.opt.batch.prob)
            if b is not None:
                self.update_bound(b)
                return
            # oracle failure: fall through to the always-valid device bound
        self.opt.solve_loop(w_on=False, prox_on=False, update=False)
        self.update_bound(self.opt.Ebound())

    def _bound_from_Ws(self, W_flat):
        # Project the received W onto the dual-feasible manifold
        # sum_s p_s W_s = 0 per (node, slot) by removing its p-weighted
        # node mean. PH-generated W satisfies this in exact arithmetic,
        # but the hub may run a lower precision (an f32 hot loop leaves
        # O(1e-4·scale) mass), and the Lagrangian bound is only a valid
        # outer bound on that manifold — the projection makes the
        # certificate exact at THIS engine's precision.
        W = jnp.asarray(W_flat, self.opt.dtype)
        W = W - self.opt.compute_xbar(W)
        if self._exact:
            from ..utils.host_oracle import exact_lagrangian_bound
            import numpy as np
            b = exact_lagrangian_bound(self.opt.batch,
                                       self.opt.batch.prob,
                                       np.asarray(W))
            if b is not None:
                return b
            # oracle failure: fall through to the device bound
        self.opt.W = W
        self.opt.solve_loop(w_on=True, prox_on=False, update=False)
        return self.opt.Ebound()

    def main(self):
        self.lagrangian_prep()
        while not self.got_kill_signal():
            fresh, values = self.spoke_from_hub()
            if not fresh or values is None:
                continue
            W, _ = self.unpack_hub(values)
            bound = self._bound_from_Ws(W)
            if bound is not None:       # None: an oracle solve failed
                self.update_bound(bound)


class LagrangerOuterBound(OuterBoundNonantSpoke):
    converger_spoke_char = "A"

    def __init__(self, spbase_object, options=None):
        super().__init__(spbase_object, options)
        # per-iteration rho rescale factors {iter: factor}
        # (ref. lagranger_bounder.py:20-27 json rescale option)
        self.rho_rescale = dict(self.options.get("lagranger_rho_rescale", {}))
        self._niter = 0

    def _update_weights_and_solve(self, X):
        opt = self.opt
        factor = self.rho_rescale.get(self._niter)
        if factor is not None:
            opt.rho = opt.rho * float(factor)
            opt.invalidate_factors()
        xn = jnp.asarray(X, opt.dtype)
        opt.xbar = opt.compute_xbar(xn)
        opt.W = opt.W + opt.rho * (xn - opt.xbar)
        opt.solve_loop(w_on=True, prox_on=False, update=False)
        return opt.Ebound()

    def main(self):
        while not self.got_kill_signal():
            fresh, values = self.spoke_from_hub()
            if not fresh or values is None:
                continue
            _, X = self.unpack_hub(values)
            self.update_bound(self._update_weights_and_solve(X))
            self._niter += 1
