"""Lagrangian outer-bound spokes.

``LagrangianOuterBound`` (ref. mpisppy/cylinders/lagrangian_bounder.py:5-87):
takes the hub's W, solves all subproblems with W on / prox off, and
publishes the expected *certified dual* bound (our Ebound is built from the
ADMM dual vectors, so an inexactly solved subproblem cannot overstate it).

``LagrangerOuterBound`` (ref. mpisppy/cylinders/lagranger_bounder.py:9-95):
takes the hub's *nonants* instead and computes its own x̄ and W locally
(optionally with a rescaled rho) before bounding.
"""

from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np

from .spoke import OuterBoundWSpoke, OuterBoundNonantSpoke


class LagrangianOuterBound(OuterBoundWSpoke):
    """Three bound engines, composable by options:

    - default: the batched on-device solve + certified dual bound
      (valid at ANY solve accuracy, tight once duals converge);
    - ``lagrangian_exact_oracle``: per-scenario host HiGHS LPs
      (utils/host_oracle) — exact L(W) of the LP relaxation, the analog
      of the reference's spoke renting a CPU simplex per scenario (ref.
      lagrangian_bounder.py:5-87). Fast (~10 ms/scenario) but floored
      at the instance's LP integrality gap.
    - ``lagrangian_mip_oracle``: per-scenario host HiGHS **MILPs** with
      W on — the true Lagrangian dual function, matching the
      reference's MIP subproblem solves (ref.
      lagrangian_bounder.py:54-56 → phbase.py:947-949) that carry its
      UC gaps to 0.026-0.073% where LP bounds stall near ~1%. Each
      scenario value is the B&B dual bound (valid at any time_limit /
      mip_rel_gap stop). Refreshes run at ``lagrangian_mip_cadence``
      seconds (default 0: back-to-back) on the newest projected W,
      through a subprocess pool that overlaps the hub's device work and
      aborts on the hub's kill signal mid-refresh.

    Linear objectives only for both oracles; quadratic models and
    variable-probability runs fall back to the certified device bound.
    The spoke is asynchronous, so host latency never blocks the hub.
    """
    converger_spoke_char = "L"

    def __init__(self, spbase_object, options=None, trace_prefix=None):
        super().__init__(spbase_object, options, trace_prefix)
        # the oracle-eligibility test re-materialized P_diag to host on
        # every sync when it was a property (ADVICE r2) — it is static,
        # so decide once
        self._linear = getattr(self.opt, "vprob", None) is None and \
            float(np.abs(np.asarray(self.opt.batch.P_diag)).max()) == 0.0
        self._exact = bool(self.options.get("lagrangian_exact_oracle",
                                            False)) and self._linear
        self._mip = bool(self.options.get("lagrangian_mip_oracle",
                                          False)) and self._linear
        self._mip_tl = float(self.options.get("lagrangian_mip_time_limit",
                                              10.0))
        self._mip_gap = float(self.options.get("lagrangian_mip_gap", 1e-4))
        self._mip_cadence = float(self.options.get("lagrangian_mip_cadence",
                                                   0.0))
        # one degenerate scenario LP must not stall the refresh forever
        # (ADVICE r2): timeouts surface as ok=False → device fallback
        self._lp_tl = self.options.get("lagrangian_lp_time_limit", 60.0)
        # LP-EF dual warm start (utils/host_oracle.solve_lp_ef): one
        # host LP solve puts the spoke AT the LP-relaxation Lagrangian
        # maximum before the hub's first W arrives — W convergence
        # stops being the bound bottleneck. Inline (not abortable), so
        # very large batches can disable it.
        self._warm = bool(self.options.get("lagrangian_lp_ef_warmstart",
                                           True)) \
            and (self._exact or self._mip)
        self._pool = None
        self._projector = None
        self._last_mip_at = -float("inf")
        self._last_mip_ok = True

    def _oracle(self):
        if self._pool is None:
            from ..utils.host_oracle import OraclePool
            self._pool = OraclePool(
                self.opt.batch,
                n_workers=self.options.get("lagrangian_oracle_workers"))
        return self._pool

    def _oracle_bound(self, W=None, **kw):
        """Oracle call with the spoke's failure contract: ANY oracle
        problem (worker subprocess death included) degrades to None so
        the caller falls back to the device bound — a bound spoke must
        never crash the wheel over a host solver hiccup."""
        try:
            return self._oracle().lagrangian_bound(
                self.opt.batch.prob, W, kill_check=self.killed, **kw)
        except Exception:
            return None

    def _project_W(self, W_flat):
        # Project the received W onto the dual-feasible manifold
        # sum_s p_s W_s = 0 per (node, slot) by removing its p-weighted
        # node mean. PH-generated W satisfies this in exact arithmetic,
        # but the hub may run a lower precision (an f32 hot loop leaves
        # O(1e-4·scale) mass), and the Lagrangian bound is only a valid
        # outer bound on that manifold. The projection runs in HOST
        # float64 regardless of engine dtype (host_oracle's shared,
        # membership-precomputing projector): the bound certificate's
        # precision is set by the projector, and an f32 projection
        # would leave an O(eps_f32·|W|) off-manifold residual that the
        # f64/MIP oracle bounds (1e-4-level tightness) cannot absorb.
        if getattr(self.opt, "vprob", None) is not None:
            # variable probabilities: the manifold is vprob-weighted;
            # oracles are disabled here, so the engine projection (same
            # precision as the device bound it feeds) is the right one
            W = jnp.asarray(W_flat, self.opt.dtype)
            return W - self.opt.compute_xbar(W)
        if self._projector is None:
            from ..utils.host_oracle import make_w_projector
            self._projector = make_w_projector(self.opt.batch)
        return self._projector(W_flat)

    def lagrangian_prep(self):
        """Bound before any W arrives (ref. lagrangian_bounder.py:20-52
        computes the trivial W=0 bound here). With the LP-EF warm start
        the prep bound is the LP-relaxation OPTIMUM (its dual W* is the
        LP-Lagrangian maximizer), and the MIP oracle refreshed at W*
        immediately lands near the full Lagrangian dual — the W=0
        trivial bound is strictly dominated and skipped."""
        if self._warm:
            try:
                from ..utils.host_oracle import solve_lp_ef
                lp_obj, W_star = solve_lp_ef(self.opt.batch)
            except Exception:
                lp_obj, W_star = None, None
            if W_star is not None:
                self.update_bound(lp_obj)
                if self._mip:
                    b = self._mip_refresh(W_star)
                    if b is not None:
                        self.update_bound(b)
                return
            # LP-EF failure: fall through to the W=0 prep bound
        if self._exact or self._mip:
            b = self._oracle_bound(time_limit=self._lp_tl)
            if b is not None:
                self.update_bound(b)
                return
            # oracle failure: fall through to the always-valid device bound
        self.opt.solve_loop(w_on=False, prox_on=False, update=False)
        self.update_bound(self.opt.Ebound())

    def _fast_bound(self, W):
        """LP-relaxation bound at W: exact host LP oracle when enabled,
        else the certified device bound."""
        if self._exact:
            b = self._oracle_bound(np.asarray(W), time_limit=self._lp_tl)
            if b is not None:
                return b
            if self.killed():
                return None
            # oracle failure: fall through to the device bound
        self.opt.W = jnp.asarray(W, self.opt.dtype)
        self.opt.solve_loop(w_on=True, prox_on=False, update=False)
        return self.opt.Ebound()

    def _mip_refresh(self, W):
        """MIP-tight L(W): expensive (B&B per scenario), so it runs on
        the newest W at the configured cadence and aborts on kill."""
        self._last_mip_at = time.monotonic()
        b = self._oracle_bound(np.asarray(W), milp=True,
                               time_limit=self._mip_tl,
                               mip_gap=self._mip_gap)
        self._last_mip_ok = b is not None
        return b

    def main(self):
        self.lagrangian_prep()
        while not self.got_kill_signal():
            fresh, values = self.spoke_from_hub()
            if not fresh or values is None:
                continue
            W, _ = self.unpack_hub(values)
            W = self._project_W(W)
            if not (self._mip and self._mip_cadence == 0.0
                    and self._last_mip_ok):
                # with back-to-back SUCCEEDING MIP refreshes the LP
                # crawl adds nothing (every published bound is
                # superseded immediately); but if the last refresh
                # failed, the cheap bound must keep flowing or the
                # published bound freezes at its pre-failure value
                bound = self._fast_bound(W)
                if bound is not None:
                    self.update_bound(bound)
            if self._mip and (time.monotonic() - self._last_mip_at
                              >= self._mip_cadence):
                bound = self._mip_refresh(W)
                if bound is not None:   # None: kill/solve failure
                    self.update_bound(bound)

    def finalize(self):
        if self._pool is not None:
            self._pool.close()
        return super().finalize()


class LagrangerOuterBound(OuterBoundNonantSpoke):
    converger_spoke_char = "A"

    def __init__(self, spbase_object, options=None):
        super().__init__(spbase_object, options)
        # per-iteration rho rescale factors {iter: factor}
        # (ref. lagranger_bounder.py:20-27 json rescale option)
        self.rho_rescale = dict(self.options.get("lagranger_rho_rescale", {}))
        self._niter = 0

    def _update_weights_and_solve(self, X):
        opt = self.opt
        factor = self.rho_rescale.get(self._niter)
        if factor is not None:
            opt.rho = opt.rho * float(factor)
            opt.invalidate_factors()
        xn = jnp.asarray(X, opt.dtype)
        opt.xbar = opt.compute_xbar(xn)
        opt.W = opt.W + opt.rho * (xn - opt.xbar)
        opt.solve_loop(w_on=True, prox_on=False, update=False)
        return opt.Ebound()

    def main(self):
        while not self.got_kill_signal():
            fresh, values = self.spoke_from_hub()
            if not fresh or values is None:
                continue
            _, X = self.unpack_hub(values)
            self.update_bound(self._update_weights_and_solve(X))
            self._niter += 1
