"""Lagrangian outer-bound spokes.

``LagrangianOuterBound`` (ref. mpisppy/cylinders/lagrangian_bounder.py:5-87):
takes the hub's W, solves all subproblems with W on / prox off, and
publishes the expected *certified dual* bound (our Ebound is built from the
ADMM dual vectors, so an inexactly solved subproblem cannot overstate it).

``LagrangerOuterBound`` (ref. mpisppy/cylinders/lagranger_bounder.py:9-95):
takes the hub's *nonants* instead and computes its own x̄ and W locally
(optionally with a rescaled rho) before bounding.
"""

from __future__ import annotations

import threading
import time

import jax.numpy as jnp
import numpy as np

from .spoke import OuterBoundWSpoke, OuterBoundNonantSpoke

_UNSET = object()


class _AsyncRefresh:
    """One in-flight background bound refresh at a time, newest-wins
    queueing: ``launch(arg)`` starts ``fn(arg)`` on a daemon thread when
    idle (or parks ``arg`` as the pending argument when busy — only the
    newest pending argument survives), ``poll()`` harvests a finished
    result (or None) and auto-relaunches on the pending argument.

    This is what DEMOTES the exact host-LP oracle from the bound loop's
    bottleneck to an asynchronous tightener: the spoke keeps publishing
    cheap device-certified bounds every sync while a ~minutes-long exact
    refresh runs here, and harvests the exact value whenever it lands.
    ``fn`` must be kill-aware (the oracle pool's kill_check) — the wheel
    terminating mid-refresh abandons the thread harmlessly (daemon)."""

    def __init__(self, fn):
        self._fn = fn
        self._lock = threading.Lock()
        self._thread = None
        self._result = _UNSET
        self._pending = _UNSET

    @property
    def busy(self):
        t = self._thread
        return t is not None and t.is_alive()

    def _start(self, arg):
        def run():
            out = self._fn(arg)
            with self._lock:
                self._result = out

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def launch(self, arg):
        with self._lock:
            if self.busy:
                self._pending = arg
            else:
                self._start(arg)

    def poll(self):
        """Finished result (may be None for a failed refresh) or None."""
        with self._lock:
            out = self._result
            self._result = _UNSET
            if not self.busy and self._pending is not _UNSET:
                arg, self._pending = self._pending, _UNSET
                self._start(arg)
        return None if out is _UNSET else out


class LagrangianOuterBound(OuterBoundWSpoke):
    """Four bound engines, composable by options:

    - default: the batched on-device solve + certified dual bound
      (valid at ANY solve accuracy, tight once duals converge);
    - ``lagrangian_device_duals``: the DEVICE-DUAL mode — the primary
      bound source becomes the engine's own dual iterates from the
      (chunked, packed-df32) prox-off solve, pulled f32 (quantized
      duals are still exact duals), repaired onto the dual-feasible
      cone and certified on host in f64 with directed-rounding margins
      (utils/certify.DualBoundCertifier; the repair is the host twin
      of ops/qp_solver.qp_repair_duals), so every published value is
      provably <= the true optimum WITHOUT an LP oracle call. Bounds
      publish early-and-often: one at prep (W=0, seconds after the
      first solve pass) and one per hub sync. When
      ``lagrangian_exact_oracle`` is also on (and the MIP oracle off —
      a MIP bound dominates the LP bound at equal W), the exact
      host-LP pass is DEMOTED to an asynchronous tightener/cross-check
      (_AsyncRefresh): it runs on the newest projected W in the
      background and its exact value is harvested whenever it lands —
      minutes-long host passes stop gating the wheel's first certified
      bound.
    - ``lagrangian_exact_oracle`` (without device duals): per-scenario
      host HiGHS LPs (utils/host_oracle), blocking — exact L(W) of the
      LP relaxation, the analog of the reference's spoke renting a CPU
      simplex per scenario (ref. lagrangian_bounder.py:5-87). Floored
      at the instance's LP integrality gap.
    - ``lagrangian_mip_oracle``: per-scenario host HiGHS **MILPs** with
      W on — the true Lagrangian dual function, matching the
      reference's MIP subproblem solves (ref.
      lagrangian_bounder.py:54-56 → phbase.py:947-949) that carry its
      UC gaps to 0.026-0.073% where LP bounds stall near ~1%. Each
      scenario value is the B&B dual bound (valid at any time_limit /
      mip_rel_gap stop). Refreshes run at ``lagrangian_mip_cadence``
      seconds (default 0: back-to-back) on the newest projected W,
      through a subprocess pool that overlaps the hub's device work and
      aborts on the hub's kill signal mid-refresh.

    Linear objectives only for the oracles and the host certification;
    quadratic models and variable-probability runs fall back to the
    certified device bound. The spoke is asynchronous, so host latency
    never blocks the hub.
    """
    converger_spoke_char = "L"

    def __init__(self, spbase_object, options=None, trace_prefix=None):
        super().__init__(spbase_object, options, trace_prefix)
        # the oracle-eligibility test re-materialized P_diag to host on
        # every sync when it was a property (ADVICE r2) — it is static,
        # so decide once
        self._linear = getattr(self.opt, "vprob", None) is None and \
            float(np.abs(np.asarray(self.opt.batch.P_diag)).max()) == 0.0
        self._exact = bool(self.options.get("lagrangian_exact_oracle",
                                            False)) and self._linear
        self._mip = bool(self.options.get("lagrangian_mip_oracle",
                                          False)) and self._linear
        # device-dual mode (see class docstring): engine duals as the
        # primary bound source, host-certified; exact oracle demoted to
        # an asynchronous tightener
        self._device_duals = bool(self.options.get(
            "lagrangian_device_duals", False))
        self._certify = bool(self.options.get("lagrangian_certify_host",
                                              True)) and self._linear
        self._certifier = None          # lazy DualBoundCertifier | False
        self._tightener = None          # lazy _AsyncRefresh
        self._mip_tl = float(self.options.get("lagrangian_mip_time_limit",
                                              10.0))
        self._mip_gap = float(self.options.get("lagrangian_mip_gap", 1e-4))
        self._mip_cadence = float(self.options.get("lagrangian_mip_cadence",
                                                   0.0))
        # one degenerate scenario LP must not stall the refresh forever
        # (ADVICE r2): timeouts surface as ok=False → device fallback
        self._lp_tl = self.options.get("lagrangian_lp_time_limit", 60.0)
        # LP-EF dual warm start (utils/host_oracle.solve_lp_ef): one
        # host LP solve puts the spoke AT the LP-relaxation Lagrangian
        # maximum before the hub's first W arrives — W convergence
        # stops being the bound bottleneck. Inline (not abortable), so
        # very large batches can disable it.
        self._warm = bool(self.options.get("lagrangian_lp_ef_warmstart",
                                           True)) \
            and (self._exact or self._mip) and not self._device_duals
        self._pool = None
        self._pool_lock = threading.Lock()
        self._oracle_use_lock = threading.Lock()
        self._projector = None
        self._last_mip_at = -float("inf")
        self._last_mip_ok = True
        # warm resume (mpisppy_tpu.ckpt): the checkpointed dual block
        # parked by install_spoke_state; lagrangian_prep bounds at it
        # instead of the W=0 cold prep
        self._resume_W = None

    # ---- durable warm state (mpisppy_tpu.ckpt) ----
    def spoke_state(self):
        """+ the spoke's Lagrangian dual block (its engine's W, REAL
        scenarios only — the wxbar portability contract, in case the
        spoke engine is ever mesh-padded): a resumed/respawned
        incarnation prep-bounds at the checkpointed duals instead of
        the trivial W=0 point, so its first COMPUTED bound starts
        where the dead generation's left off (the re-published best
        rides resume_publish either way)."""
        state = super().spoke_state()
        S = getattr(self.opt, "_S_orig", self.opt.batch.S)
        state["W"] = np.asarray(self.opt.W, np.float64)[:S]
        return state

    def install_spoke_state(self, state):
        super().install_spoke_state(state)
        W = state.get("W")
        if W is None:
            return
        W = np.asarray(W, np.float64)
        S_real = getattr(self.opt, "_S_orig", self.opt.batch.S)
        if W.shape != (S_real, self.opt.batch.K):
            return          # foreign shape: keep the cold W=0 prep
        if self.opt.batch.S != S_real:
            # mesh pads carry zero objective weight; zero duals there
            # keep the padded block on the dual-feasible manifold
            W = np.concatenate(
                [W, np.zeros((self.opt.batch.S - S_real, W.shape[1]))])
        self._resume_W = W

    def _oracle(self):
        # construction is locked: the async tightener thread and the
        # spoke's own MIP refresh may race on first use
        with self._pool_lock:
            if self._pool is None:
                from ..utils.host_oracle import OraclePool
                self._pool = OraclePool(
                    self.opt.batch,
                    n_workers=self.options.get("lagrangian_oracle_workers"))
            return self._pool

    def _oracle_bound(self, W=None, **kw):
        """Oracle call with the spoke's failure contract: ANY oracle
        problem (worker subprocess death included) degrades to None so
        the caller falls back to the device bound — a bound spoke must
        never crash the wheel over a host solver hiccup.

        Pool USE is serialized under _oracle_use_lock: the async
        exact-LP tightener thread and the spoke thread's own MIP
        refresh share one worker pool, and OraclePool._run is a
        single-caller protocol (two concurrent callers would interleave
        task/result frames on the same worker pipes and cross-deliver
        values computed at different W). The tightener blocking here is
        harmless — it is the background thread."""
        try:
            with self._oracle_use_lock:
                return self._oracle().lagrangian_bound(
                    self.opt.batch.prob, W, kill_check=self.killed, **kw)
        except Exception:
            return None

    def _project_W(self, W_flat):
        # Project the received W onto the dual-feasible manifold
        # sum_s p_s W_s = 0 per (node, slot) by removing its p-weighted
        # node mean. PH-generated W satisfies this in exact arithmetic,
        # but the hub may run a lower precision (an f32 hot loop leaves
        # O(1e-4·scale) mass), and the Lagrangian bound is only a valid
        # outer bound on that manifold. The projection runs in HOST
        # float64 regardless of engine dtype (host_oracle's shared,
        # membership-precomputing projector): the bound certificate's
        # precision is set by the projector, and an f32 projection
        # would leave an O(eps_f32·|W|) off-manifold residual that the
        # f64/MIP oracle bounds (1e-4-level tightness) cannot absorb.
        if getattr(self.opt, "vprob", None) is not None:
            # variable probabilities: the manifold is vprob-weighted;
            # oracles are disabled here, so the engine projection (same
            # precision as the device bound it feeds) is the right one
            W = jnp.asarray(W_flat, self.opt.dtype)
            return W - self.opt.compute_xbar(W)
        if self._projector is None:
            from ..utils.host_oracle import make_w_projector
            self._projector = make_w_projector(self.opt.batch)
        return self._projector(W_flat)

    # -- device-dual mode (the certified-without-an-oracle path) --
    def _host_certified(self, W):
        """Host f64 safe-rounding certification of the engine's current
        row duals (utils/certify). Returns the certified bound, or None
        when certification is unavailable/uncertifiable — callers fall
        back to the device Ebound value."""
        if not self._certify or self._certifier is False:
            return None
        if self._certifier is None:
            try:
                from ..utils.certify import DualBoundCertifier
                self._certifier = DualBoundCertifier.from_batch(
                    self.opt.batch)
            except Exception as e:
                # construction failure (ineligible layout, host OOM on
                # the sparse build) is permanent for this batch: latch
                # off, but SAY SO — the published bounds silently
                # degrading from host-certified to device-certified
                # must be visible in the trace
                from .. import global_toc
                global_toc(f"{type(self).__name__}: host certification "
                           f"unavailable ({e!r}); publishing the device "
                           "dual certificate instead")
                self._certifier = False
                return None
        try:
            # f32 transfer: quantized duals are still exact duals —
            # validity is free, the tightness cost is ~1e-7 relative,
            # and the (S, m) device→host pull halves (tens of MB at
            # uc1024 scale on tunneled links). The cone repair happens
            # host-side inside the certifier (its _sanitize is the
            # same projection ops/qp_solver.qp_repair_duals runs on
            # device — one repair suffices).
            yA = np.asarray(jnp.asarray(self.opt.yA, jnp.float32),
                            np.float64)
            b, _ = self._certifier.bound(
                yA, None if W is None else np.asarray(W, np.float64))
            return b if np.isfinite(b) else None
        except Exception as e:
            # evaluation failure may be TRANSIENT (host memory spike at
            # uc1024 scale): log, fall back to the device certificate
            # for THIS refresh, and retry on the next one — do not
            # latch certification off over one hiccup
            if not getattr(self, "_warned_cert_fail", False):
                self._warned_cert_fail = True
                from .. import global_toc
                global_toc(f"{type(self).__name__}: host certification "
                           f"failed this refresh ({e!r}); falling back "
                           "to the device dual certificate (will keep "
                           "retrying)")
            return None

    def _device_bound(self, W):
        """Certified outer bound from the engine's OWN duals at W (None
        = W off): one batched prox-off solve, dual extraction from the
        chunked/packed solve path, host f64 certification when
        eligible, device dual-objective certificate otherwise."""
        opt = self.opt
        if W is None:
            opt.solve_loop(w_on=False, prox_on=False, update=False)
        else:
            opt.W = jnp.asarray(W, opt.dtype)
            opt.solve_loop(w_on=True, prox_on=False, update=False)
        dev = opt.Ebound()
        cert = self._host_certified(W)
        return dev if cert is None else cert

    def _ensure_tightener(self):
        if self._tightener is None:
            def refresh(W):
                return self._oracle_bound(W, time_limit=self._lp_tl)

            self._tightener = _AsyncRefresh(refresh)
        return self._tightener

    def lagrangian_prep(self):
        """Bound before any W arrives (ref. lagrangian_bounder.py:20-52
        computes the trivial W=0 bound here). With the LP-EF warm start
        the prep bound is the LP-relaxation OPTIMUM (its dual W* is the
        LP-Lagrangian maximizer), and the MIP oracle refreshed at W*
        immediately lands near the full Lagrangian dual — the W=0
        trivial bound is strictly dominated and skipped.

        In device-dual mode the prep bound comes from the engine's own
        first prox-off pass instead (seconds, not the minutes a
        reference-scale exact-LP pass costs on a 1-core host), and the
        exact oracle — when configured — starts as an asynchronous
        tightener at W=0 immediately, so its exact value lands during
        the first hub iterations rather than gating them.

        A RESUMED incarnation (checkpointed dual block installed by
        install_spoke_state) skips the cold W=0 prep entirely and
        bounds at its checkpointed duals — generation N picks up the
        Lagrangian ascent where generation N-1 died."""
        W = self._resume_W
        if W is not None:
            self._resume_W = None
            W = self._project_W(np.asarray(W))
            if self._device_duals:
                self.update_bound(self._device_bound(W))
                if self._exact and not self._mip:
                    self._ensure_tightener().launch(np.asarray(W))
            else:
                b = self._fast_bound(W)
                if b is not None:
                    self.update_bound(b)
            return
        if self._device_duals:
            self.update_bound(self._device_bound(None))
            if self._exact and not self._mip:
                # the exact-LP tightener only exists when the MIP
                # oracle is off: at equal W the MIP bound dominates the
                # LP bound, and one shared worker pool cannot serve a
                # minutes-long background LP pass AND the cadence-fired
                # MIP refresh without starving one of them
                self._ensure_tightener().launch(None)
            return
        if self._warm:
            try:
                from ..utils.host_oracle import solve_lp_ef
                lp_obj, W_star = solve_lp_ef(self.opt.batch)
            except Exception:
                lp_obj, W_star = None, None
            if W_star is not None:
                self.update_bound(lp_obj)
                if self._mip:
                    b = self._mip_refresh(W_star)
                    if b is not None:
                        self.update_bound(b)
                return
            # LP-EF failure: fall through to the W=0 prep bound
        if self._exact or self._mip:
            b = self._oracle_bound(time_limit=self._lp_tl)
            if b is not None:
                self.update_bound(b)
                return
            # oracle failure: fall through to the always-valid device bound
        self.opt.solve_loop(w_on=False, prox_on=False, update=False)
        self.update_bound(self.opt.Ebound())

    def _fast_bound(self, W):
        """LP-relaxation bound at W: exact host LP oracle when enabled,
        else the certified device bound."""
        if self._exact:
            b = self._oracle_bound(np.asarray(W), time_limit=self._lp_tl)
            if b is not None:
                return b
            if self.killed():
                return None
            # oracle failure: fall through to the device bound
        self.opt.W = jnp.asarray(W, self.opt.dtype)
        self.opt.solve_loop(w_on=True, prox_on=False, update=False)
        return self.opt.Ebound()

    def _mip_refresh(self, W):
        """MIP-tight L(W): expensive (B&B per scenario), so it runs on
        the newest W at the configured cadence and aborts on kill."""
        self._last_mip_at = time.monotonic()
        b = self._oracle_bound(np.asarray(W), milp=True,
                               time_limit=self._mip_tl,
                               mip_gap=self._mip_gap)
        self._last_mip_ok = b is not None
        return b

    def main(self):
        self.lagrangian_prep()
        while not self.got_kill_signal():
            if self._tightener is not None:
                # harvest a finished async exact-LP refresh (device-dual
                # mode); a failed refresh returns None and publishes
                # nothing — the device bounds keep flowing regardless
                tightened = self._tightener.poll()
                if tightened is not None:
                    self.update_bound(tightened)
            fresh, values = self.spoke_from_hub()
            if not fresh or values is None:
                continue
            W, _ = self.unpack_hub(values)
            W = self._project_W(W)
            if self._device_duals:
                # primary: the engine's own certified duals at the
                # newest W — published every sync, seconds each
                self.update_bound(self._device_bound(W))
                if self._exact and not self._mip:
                    # newest-wins: the async exact pass always runs on
                    # the freshest projected W (LP tightener only when
                    # the MIP oracle is off — see lagrangian_prep)
                    self._ensure_tightener().launch(np.asarray(W))
                if self._mip and (time.monotonic() - self._last_mip_at
                                  >= self._mip_cadence):
                    # cadence-fired MIP refresh, blocking like the
                    # legacy path (users enabling the MIP oracle accept
                    # its wall); device bounds keep flowing between
                    # refreshes
                    bound = self._mip_refresh(W)
                    if bound is not None:
                        self.update_bound(bound)
                continue
            if not (self._mip and self._mip_cadence == 0.0
                    and self._last_mip_ok):
                # with back-to-back SUCCEEDING MIP refreshes the LP
                # crawl adds nothing (every published bound is
                # superseded immediately); but if the last refresh
                # failed, the cheap bound must keep flowing or the
                # published bound freezes at its pre-failure value
                bound = self._fast_bound(W)
                if bound is not None:
                    self.update_bound(bound)
            if self._mip and (time.monotonic() - self._last_mip_at
                              >= self._mip_cadence):
                bound = self._mip_refresh(W)
                if bound is not None:   # None: kill/solve failure
                    self.update_bound(bound)

    def finalize(self):
        # closing the pool EOFs any in-flight async tightener's worker
        # reads; its daemon thread then exits through the oracle's
        # failure contract (None result, never raised into the wheel)
        if self._pool is not None:
            self._pool.close()
        return super().finalize()


class LagrangerOuterBound(OuterBoundNonantSpoke):
    converger_spoke_char = "A"

    def __init__(self, spbase_object, options=None):
        super().__init__(spbase_object, options)
        # per-iteration rho rescale factors {iter: factor}
        # (ref. lagranger_bounder.py:20-27 json rescale option)
        self.rho_rescale = dict(self.options.get("lagranger_rho_rescale", {}))
        self._niter = 0

    def _update_weights_and_solve(self, X):
        opt = self.opt
        factor = self.rho_rescale.get(self._niter)
        if factor is not None:
            opt.rho = opt.rho * float(factor)
            opt.invalidate_factors()
        xn = jnp.asarray(X, opt.dtype)
        opt.xbar = opt.compute_xbar(xn)
        opt.W = opt.W + opt.rho * (xn - opt.xbar)
        opt.solve_loop(w_on=True, prox_on=False, update=False)
        return opt.Ebound()

    def main(self):
        while not self.got_kill_signal():
            fresh, values = self.spoke_from_hub()
            if not fresh or values is None:
                continue
            _, X = self.unpack_hub(values)
            self.update_bound(self._update_weights_and_solve(X))
            self._niter += 1
