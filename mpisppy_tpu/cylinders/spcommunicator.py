"""Communication windows + SPCommunicator base.

Mirrors the reference's RMA-window discipline (ref. mpisppy/cylinders/
spcommunicator.py:23-124): every buffer is ``length + 1`` doubles whose
last slot is a monotonically increasing write-id; readers detect fresh
data by comparing ids; ``-1`` is the reserved kill value
(ref. mpisppy/cylinders/hub.py:356-368). Ownership discipline matches
Lock/Put/Unlock: only the owner writes, remotes read under the lock.

The default backend is an in-process ``threading.Lock`` + numpy buffer;
``Window.shared(...)`` swaps in the native C++ shared-memory backend for
multi-process cylinder layouts (same protocol, see ops/native/spwindow).
"""

from __future__ import annotations

import threading
import weakref

import numpy as np

# ---- bound-flow lineage (doc/observability.md "live plane") ----
# Every spoke→hub window carries a 3-double lineage SUFFIX behind the
# semantic payload: [publish seq, compute stamp, publish stamp].
#  - publish seq: per-spoke monotonically increasing PUBLISH counter.
#    Distinct from the window write-id, which also advances on idle
#    heartbeat re-stamps (cylinders/spoke._heartbeat) — the seq is how
#    the hub tells a fresh bound from a pulse, and how it counts
#    publishes it never saw (the window overwrites in place, so a slow
#    reader observes the seq jump).
#  - compute/publish stamps: ``time.time()`` wall clock — the one clock
#    hub and spoke PROCESSES share (perf_counter is per-process
#    monotonic and cannot cross a process boundary). Staleness
#    (hub read − spoke publish) therefore carries NTP-slew noise, which
#    is harmless at the >=0.1 s granularity bound flow cares about.
# NaN lineage (the all-NaN startup hello, hand-built test payloads)
# means "no lineage": the hub ingests the payload but books nothing.
LINEAGE_SLOTS = 3


def wire_payload(values, seq, t_compute=None, t_publish=None):
    """Semantic payload + lineage suffix -> the on-wire array."""
    import time

    values = np.asarray(values, dtype=np.float64).reshape(-1)
    now = time.time()
    out = np.empty(values.shape[0] + LINEAGE_SLOTS)
    out[:-LINEAGE_SLOTS] = values
    out[-3] = float(seq)
    out[-2] = now if t_compute is None else float(t_compute)
    out[-1] = now if t_publish is None else float(t_publish)
    return out


def split_wire(values):
    """On-wire array -> (payload view, seq, t_compute, t_publish)."""
    return (values[:-LINEAGE_SLOTS], float(values[-3]),
            float(values[-2]), float(values[-1]))


class Window:
    """A one-writer many-reader buffer with the write-id protocol."""

    KILL = -1

    def __init__(self, length: int):
        self.length = int(length)
        self.buf = np.zeros(self.length + 1)
        self.lock = threading.Lock()

    # -- owner side (ref. hub.py:310-331 hub_to_spoke / spoke.py:59-80) --
    def put(self, values) -> int:
        values = np.asarray(values, dtype=np.float64).reshape(-1)
        assert values.shape[0] == self.length, \
            f"window length {self.length} != payload {values.shape[0]}"
        with self.lock:
            self.buf[:-1] = values
            if self.buf[-1] >= 0:
                self.buf[-1] += 1
            return int(self.buf[-1])

    def kill(self):
        """Write the terminate signal (write-id -1, ref. hub.py:356)."""
        with self.lock:
            self.buf[-1] = Window.KILL

    # -- reader side (ref. hub.py:333-354 hub_from_spoke / spoke.py:82-99) --
    def read(self):
        """Return (values copy, write_id)."""
        with self.lock:
            return self.buf[:-1].copy(), int(self.buf[-1])

    def read_id(self) -> int:
        with self.lock:
            return int(self.buf[-1])

    @staticmethod
    def shared(name: str, length: int, create: bool):
        """The native C++ shared-memory backend (ops/native/spwindow):
        same write-id protocol over POSIX shm with a seqlock, for
        cylinders running as separate PROCESSES (the reference's
        MPI-RMA star, ref. spcommunicator.py:97-124)."""
        return SharedWindow(name, length, create)


class SharedWindow:
    """One-writer many-reader shared-memory window (see ops/native)."""

    KILL = -1

    def __init__(self, name: str, length: int, create: bool):
        from ..ops import native

        self._lib = native.load()
        self.name = name
        self.length = int(length)
        fn = self._lib.spw_create if create else self._lib.spw_open
        self._h = fn(name.encode(), self.length)
        if not self._h:
            raise OSError(f"could not {'create' if create else 'open'} "
                          f"shared window {name!r}")
        self._owner = bool(create)

    def put(self, values) -> int:
        values = np.ascontiguousarray(values, dtype=np.float64).reshape(-1)
        assert values.shape[0] == self.length, \
            f"window length {self.length} != payload {values.shape[0]}"
        import ctypes
        self._lib.spw_put(self._h, values.ctypes.data_as(
            ctypes.POINTER(ctypes.c_double)), self.length)
        return self.read_id()

    def kill(self):
        # tolerate an already-closed handle: the terminate sweep may
        # visit a window another path has since retired
        h = self._h
        if h:
            self._lib.spw_kill(h)

    def read(self):
        import ctypes
        out = np.empty(self.length, dtype=np.float64)
        wid = self._lib.spw_read(self._h, out.ctypes.data_as(
            ctypes.POINTER(ctypes.c_double)), self.length)
        return out, int(wid)

    def read_id(self) -> int:
        return int(self._lib.spw_read_id(self._h))

    def close(self, unlink=None):
        if self._h:
            self._lib.spw_close(self._h, 1 if (self._owner if unlink is None
                                               else unlink) else 0)
            self._h = None


class SPCommunicator:
    """Base of Hub and Spoke: owns an algorithm (`opt`) instance and the
    window pair used to talk across the strata (ref. spcommunicator.py:23).

    Window topology is the reference's star graph: for each spoke there is
    one hub-owned window (hub writes; that spoke reads) and one spoke-owned
    window (spoke writes; hub reads)."""

    def __init__(self, spbase_object, options=None):
        self.opt = spbase_object
        # Communicator options LAYER OVER the engine's: vanilla puts
        # SpokeConfig.options into the ENGINE (opt_kwargs["options"]),
        # and spin_the_wheel builds communicators with no options of
        # their own — without the merge, every spoke-level knob
        # (lagrangian_exact_oracle, xhat_scen_limit, ...) configured
        # through the config tree would be silently dead.
        self.options = dict(getattr(spbase_object, "options", {}) or {})
        self.options.update(options or {})
        # back-pointer used by engines to call sync() mid-iteration
        # (ref. spbase.py:503-514 weakref spcomm setter)
        self.opt.spcomm = weakref.proxy(self)
        self.windows_made = False

    # sizes the subclass must declare before make_windows()
    def local_window_length(self) -> int:
        """Length of the buffer THIS cylinder writes."""
        raise NotImplementedError

    def remote_window_length(self) -> int:
        """Length of the buffer this cylinder reads."""
        raise NotImplementedError

    def finalize(self):
        """Post-kill wrap-up; returns a cylinder-specific result."""
        return None

    def allreduce_or(self, flag: bool) -> bool:
        """Degenerate in-process analog of allreduce(LOR)
        (ref. spcommunicator.py:79): single shared address space."""
        return bool(flag)
