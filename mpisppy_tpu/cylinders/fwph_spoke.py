"""FWPH outer-bound spoke (ref. mpisppy/cylinders/fwph_spoke.py:5-28).

Wraps the FWPH engine; the engine's per-iteration spcomm.sync() publishes
`_local_bound` and its is_converged() doubles as the kill check, exactly
the reference's pattern.
"""

from __future__ import annotations

from .spoke import OuterBoundSpoke


class FrankWolfeOuterBound(OuterBoundSpoke):
    converger_spoke_char = "F"

    def sync(self):
        if self.opt._local_bound is not None:
            self.update_bound(self.opt._local_bound)

    def is_converged(self):
        return self.got_kill_signal()

    def main(self):
        self.opt.fwph_main(finalize=False)
