"""Cylinders: concurrent algorithm instances exchanging bounds/weights.

The reference runs each cylinder as a block of MPI ranks and exchanges
state through one-sided RMA windows with a write-id freshness protocol
(ref. mpisppy/cylinders/spcommunicator.py:3-14, 97-124). The TPU redesign
runs cylinders as host threads (or processes via the native shared-memory
backend, see ops/native) sharing a single accelerator: device work is
serialized by the runtime, host coordination is asynchronous, and the
write-id semantics are preserved exactly so the algorithms' staleness
tolerances carry over.

``SPOKE_SLEEP_TIME`` rate-limits spoke kill-signal polling like the
reference's module knob (ref. mpisppy/cylinders/__init__.py:3).
"""

SPOKE_SLEEP_TIME = 0.01
